"""Fleet orchestrator: `python -m avida_tpu --fleet SPOOL_DIR`.

The supervisor (service/supervisor.py) heals ONE run; real Avida
science is many-seed sweeps and the ROADMAP north star is a service
handling many tenants' runs at once.  This module is the robustness
layer for the *fleet*: a host-only orchestrator (never imports jax,
same rule as the supervisor) that drains a spool directory of JSON job
specs and multiplexes up to `max_jobs` concurrent supervised runs
through one poll loop -- each job a poll()-mode Supervisor in its own
fault domain, so one tenant's crash loop cannot take out another's run
or the orchestrator itself.

Spool layout (everything lives under SPOOL_DIR)::

    <name>.json         queued job spec (fleet_tool.py submit, or any
                        atomic writer)
    <name>/             the job's fault domain, created at admission:
      job.json            the admitted spec (moved from the spool root)
      data/               child data dir (metrics.prom heartbeat,
                          supervised.log, supervisor.jsonl, .dat files)
      ck/                 checkpoint generations (utils/checkpoint.py)
    .bad-<name>.json.*  quarantined malformed specs (never retried)
    <name>.cancelled.json  specs parked by `fleet_tool.py cancel`
    <name>.cancel / <name>.requeue   operator marker files, consumed by
                        the orchestrator on its next poll
    fleet.jsonl[.1]     the crash-safe journal (runlog.append_record,
                        size-capped rotation)
    fleet.prom          aggregate Prometheus metrics
    fleet.lock          single-orchestrator guard (pid)

Job spec schema (README "Fleet runs")::

    {"argv": ["-u", "20000", "-s", "7", "-set", "TPU_CKPT_EVERY", "500"],
     "fault_plan": ["sigkill@update=5"],      # optional, chaos testing
     "env": {"TPU_WATCHDOG_SEC": "60"}}       # optional, per-job knobs

The fleet appends `-d <job>/data -set TPU_CKPT_DIR <job>/ck` AFTER the
spec's argv (last value wins), so a spec cannot escape its fault
domain; the Supervisor then appends `--resume` and forces the metrics
heartbeat as it always does -- one fixed spec both starts and restarts
a job bit-exactly.

Robustness properties, each chaos-tested (tests/test_fleet.py):

  * crash-safe journal + replay: every state transition is an fsync'd
    `{"record": "fleet"}` line.  A killed orchestrator replays the
    journal on restart and resumes every admitted job from its newest
    checkpoint WITHOUT double-spawning: admission is transactional
    (journal the admit first, then atomically move the spec into the
    job dir -- replay completes a half-done move), and children run in
    their own sessions with journaled pids so an orphan left by a
    SIGKILLed orchestrator is reaped (after a /proc identity check)
    before its job is respawned.
  * admission control: jobs past `max_jobs` queue in the spool rather
    than spawn; malformed specs are quarantined to `.bad-*` once, not
    retried forever.
  * crash-storm circuit breaker: `TPU_FLEET_BREAKER_K` same-class
    failures across jobs within `TPU_FLEET_BREAKER_SEC` seconds opens
    the breaker -- admissions pause, the fleet is marked degraded in
    fleet.prom, and a kernel-implicated storm applies the Pallas->XLA
    degradation FLEET-WIDE once instead of per-job.  The breaker closes
    after a full quiet window.
  * graceful drain: SIGTERM forwards to every child (preemption
    checkpoints), completed jobs finish as `done`, incomplete ones are
    journaled `requeued` so the next orchestrator resumes them.
"""

from __future__ import annotations

import json
import os
import re
import signal
import subprocess
import sys
import time

from avida_tpu.observability import alerts as alerts_mod
from avida_tpu.observability import history
from avida_tpu.observability.exporter import (analytics_census_digest,
                                              read_metrics,
                                              render_families,
                                              write_metrics)
from avida_tpu.observability.runlog import append_record, read_records
from avida_tpu.service import FAILURE_CLASSES
from avida_tpu.service.supervisor import Supervisor, SupervisorConfig

JOURNAL_FILE = "fleet.jsonl"
FLEET_METRICS_FILE = "fleet.prom"
LOCK_FILE = "fleet.lock"
JOB_SPEC_FILE = "job.json"

JOB_STATES = ("queued", "running", "batched", "done", "failed",
              "quarantined", "cancelled")

# job names become directory names and metric labels; the whole
# "fleet"/"fleet.*" namespace is the orchestrator's own (fleet.jsonl,
# fleet.prom, fleet.lock) -- a job named after any of those would
# wedge the spool
_NAME_RE = re.compile(r"^[A-Za-z0-9][A-Za-z0-9._-]*$")


def legal_name(name: str) -> bool:
    # also reserved: the operator-marker / parked-spec suffixes -- a job
    # named "foo.cancelled" would write a spec the scanner must skip
    # (and requeue would later resurrect it under the wrong name)
    return bool(_NAME_RE.match(name)) and name != "fleet" \
        and not name.startswith("fleet.") \
        and not name.endswith((".cancel", ".cancelled", ".requeue"))


class FleetLockedError(RuntimeError):
    """Another live orchestrator already owns this spool."""


def validate_spec(spec) -> None:
    """Schema check for one job spec; raises ValueError on anything a
    Supervisor could not safely run.  Malformed specs are quarantined
    at scan time, BEFORE they consume an admission slot."""
    if not isinstance(spec, dict):
        raise ValueError("job spec must be a JSON object")
    argv = spec.get("argv")
    if (not isinstance(argv, list) or not argv
            or not all(isinstance(a, str) for a in argv)):
        raise ValueError("job spec needs a non-empty 'argv' list of "
                         "strings (the child run's command line)")
    plan = spec.get("fault_plan", [])
    if (not isinstance(plan, list)
            or not all(isinstance(s, str) for s in plan)):
        raise ValueError("'fault_plan' must be a list of TPU_FAULT "
                         "spec strings")
    env = spec.get("env", {})
    if (not isinstance(env, dict)
            or not all(isinstance(k, str) and isinstance(v, str)
                       for k, v in env.items())):
        raise ValueError("'env' must be a string-to-string object")
    if not isinstance(spec.get("batch", False), bool):
        raise ValueError("'batch' must be a boolean (device-lane "
                         "packing opt-in)")
    if not isinstance(spec.get("tenant", ""), str):
        raise ValueError("'tenant' must be a string (per-tenant "
                         "admission quota label)")


def spec_seed_and_batch_key(spec) -> tuple:
    """(seed, static-key) for device-lane packing: the seed is lifted
    out of the spec argv (`-s`/`--seed`/`-set RANDOM_SEED`, with the
    solo CLI's precedence), and the key is the CANONICAL static-config
    signature (service/serve.static_signature): the spec's argv is
    resolved the way the child CLI would resolve it -- config files
    loaded, overrides applied -- and hashed with seeds and output/
    checkpoint dirs stripped.  Two specs that differ only in spelling
    (output dirs, `-s` position vs `-set RANDOM_SEED`, override order)
    therefore share one batchability class and one compiled program;
    before PR 12 the key was byte-equal seed-stripped argv, which split
    classes on every cosmetic difference.  seed is None when the spec
    never names one explicitly (unbatchable: the worlds manifest needs
    a concrete per-world seed)."""
    from avida_tpu.service.serve import SpecArgv, static_signature
    seed = SpecArgv(spec.get("argv")).effective_seed
    return seed, static_signature(spec, with_updates=True)


class FleetConfig:
    """Knobs, all overridable via the environment (README "Fleet
    runs")."""

    def __init__(self, max_jobs: int = 2, poll_sec: float = 0.5,
                 breaker_k: int = 3, breaker_sec: float = 300.0,
                 drain_sec: float = 600.0, serve: bool = False,
                 journal_max_bytes: int = 64 << 20,
                 max_batch: int = 16, dynamic: bool = False,
                 tenant_max: int = 0, queue_max: int = 0,
                 serve_min_width: int = 2):
        self.max_jobs = max(int(max_jobs), 1)
        self.poll_sec = float(poll_sec)
        self.breaker_k = int(breaker_k)
        self.breaker_sec = float(breaker_sec)
        self.drain_sec = float(drain_sec)
        self.serve = bool(serve)
        self.journal_max_bytes = int(journal_max_bytes)
        # device-lane packing width cap (TPU_FLEET_MAX_BATCH): one
        # batched child stacks W full PopulationStates on the device,
        # so an unbounded W would let a 100-spec sweep bypass the
        # resource bounding max_jobs exists for -- wider groups split
        # into multiple batches
        self.max_batch = max(int(max_batch), 2)
        # the streaming serve layer (service/serve.py): batchable specs
        # route into warm ghost-padded --serve-worlds children instead
        # of the static coalescer
        self.dynamic = bool(dynamic)
        # per-tenant admission quota (0 = unlimited): max concurrent
        # running/batched jobs per spec "tenant" label
        self.tenant_max = max(int(tenant_max), 0)
        # queue-depth backpressure (0 = unlimited): once this many jobs
        # sit queued, the spool scanner stops ingesting new specs --
        # they wait on disk, unscanned, until the queue drains
        self.queue_max = max(int(queue_max), 0)
        # smallest serve-class width: even a lone arrival gets one
        # ghost slot of instant-admission capacity
        self.serve_min_width = max(int(serve_min_width), 1)

    @classmethod
    def from_env(cls, env) -> "FleetConfig":
        def f(name, default):
            return float(env.get(name, default))
        return cls(
            max_jobs=int(f("TPU_FLEET_MAX_JOBS", 2)),
            poll_sec=f("TPU_FLEET_POLL_SEC", 0.5),
            breaker_k=int(f("TPU_FLEET_BREAKER_K", 3)),
            breaker_sec=f("TPU_FLEET_BREAKER_SEC", 300.0),
            drain_sec=f("TPU_FLEET_DRAIN_SEC", 600.0),
            journal_max_bytes=int(f("TPU_RUNLOG_MAX_BYTES", 64 << 20)),
            max_batch=int(f("TPU_FLEET_MAX_BATCH", 16)),
            dynamic=bool(int(f("TPU_FLEET_DYNAMIC", 0))),
            tenant_max=int(f("TPU_FLEET_TENANT_MAX", 0)),
            queue_max=int(f("TPU_FLEET_QUEUE_MAX", 0)),
            serve_min_width=int(f("TPU_SERVE_MIN_WIDTH", 2)),
        )


class CircuitBreaker:
    """Crash-storm detector: K failures of ONE class (across jobs)
    within a sliding window opens the breaker; it closes again after a
    full quiet window with no same-class failure.  Pure host state
    driven by an injected clock value -- fake-clock unit-testable."""

    def __init__(self, k: int, window_sec: float):
        self.k = max(int(k), 1)
        self.window_sec = float(window_sec)
        self._times: dict = {}          # class -> recent failure times
        self.open_class = None
        self.opened_at = None
        self.last_failure_t = None
        self.trips = 0

    def note_failure(self, cls: str, now: float) -> bool:
        """Record one classified failure at `now`; True exactly when
        this failure trips the breaker open (rising edge)."""
        times = [t for t in self._times.get(cls, ())
                 if now - t < self.window_sec]
        times.append(now)
        self._times[cls] = times
        if self.open_class is not None:
            if cls == self.open_class:
                self.last_failure_t = now    # the storm continues
            return False
        if len(times) >= self.k:
            self.open_class = cls
            self.opened_at = now
            self.last_failure_t = now
            self.trips += 1
            return True
        return False

    def is_open(self, now: float) -> bool:
        return (self.open_class is not None
                and now - self.last_failure_t < self.window_sec)

    def maybe_close(self, now: float):
        """Close after a quiet window; returns the failure class just
        closed (None when nothing changed)."""
        if self.open_class is not None \
                and now - self.last_failure_t >= self.window_sec:
            cls, self.open_class = self.open_class, None
            self._times.pop(cls, None)
            return cls
        return None


class Job:
    """One tenant run: its fault domain paths + orchestration state."""

    def __init__(self, name: str, spool: str):
        self.name = name
        self.spool = spool
        self.dir = os.path.join(spool, name)
        self.state = "queued"
        self.spec = None
        self.sup: Supervisor | None = None
        self.pid = None                 # newest child pid (journaled)
        self.cancel_requested = False
        self._fail_snapshot: dict = {}
        # degrade-hint rules currently firing in this job's embedded
        # supervisor that have already dropped their breadcrumb
        # (fleet._note_alert_hints; re-armed on resolve)
        self._alert_hints: set = set()
        # device-lane packing (spec "batch": true): a LEADER job runs
        # one MultiWorld child serving every member; members park in
        # state "batched" with no supervisor of their own
        self.batch_members: list = []   # member names (leader only)
        self.batch_leader = None        # leader name (members only)
        self._batch_fallback_logged = False
        self._batch_progress = None     # cached resume-progress key
        #                                 (None = rescan; reset whenever
        #                                 the job re-enters the queue)
        self._serve_sig = None          # cached serve-class signature
        self._batch_key = None          # cached (seed, static key)
        self.spool_src = None           # where the queued spec file
        #                                 lives (spool root, or a
        #                                 shard-* subdir)

    @property
    def data_dir(self):
        return os.path.join(self.dir, "data")

    @property
    def ckpt_dir(self):
        return os.path.join(self.dir, "ck")

    @property
    def spec_path(self):
        return os.path.join(self.dir, JOB_SPEC_FILE)

    @property
    def spool_spec_path(self):
        return os.path.join(self.spool, self.name + ".json")


def journal_states(journal_path: str) -> tuple:
    """Replay the fleet journal into (job_state, job_pid, xla_fallback).
    Shared by the orchestrator's restart replay, `fleet_tool.py list`
    and the --status fleet view; reads the rotation pair."""
    state: dict = {}
    pids: dict = {}
    xla = False
    for rec in read_records(journal_path):
        if rec.get("record") != "fleet":
            continue
        ev = rec.get("event")
        name = rec.get("job")
        if ev == "snapshot":
            # compaction record written at rotation: authoritative full
            # state at that instant -- replay survives every older
            # record being gone (the .1 aside is clobbered per rotation)
            state = {n: v.get("state") for n, v in rec["jobs"].items()}
            pids = {n: v.get("pid") for n, v in rec["jobs"].items()
                    if v.get("pid")}
            xla = bool(rec.get("xla_fallback"))
        elif ev == "admit":
            state[name] = "running"
        elif ev == "spawn":
            pids[name] = rec.get("pid")
        elif ev == "coalesced":
            # device-lane packing: the member rides a leader's
            # MultiWorld child; its own checkpoints stay solo-format,
            # so replay can requeue it standalone
            state[name] = "batched"
        elif ev == "cancel_requested":
            # a cancel whose graceful stop was still in flight: must not
            # be resurrected as "running" if the orchestrator dies here
            state[name] = "cancelling"
        elif ev in ("done", "failed", "cancelled", "quarantined",
                    "requeued"):
            state[name] = ev
        elif ev == "xla_fallback":
            xla = True
    return state, pids, xla


def journal_batch_leaders(journal_path: str) -> dict:
    """{member: leader} for every LIVE coalescing in the journal --
    terminal member events (done/failed/cancelled/requeued) dissolve
    the pairing.  Status/list views group member sub-rows under their
    leader with this."""
    leaders: dict = {}
    for rec in read_records(journal_path):
        if rec.get("record") != "fleet":
            continue
        ev = rec.get("event")
        name = rec.get("job")
        if ev == "snapshot":
            leaders = {n: v["leader"] for n, v in rec["jobs"].items()
                       if v.get("leader")}
        elif ev == "coalesced" and rec.get("leader"):
            leaders[name] = rec["leader"]
        elif ev in ("done", "failed", "cancelled", "quarantined",
                    "requeued"):
            leaders.pop(name, None)
    return leaders


def spool_job_states(spool: str) -> dict:
    """{job: state} for one spool: the journal replay merged with a
    scan for not-yet-admitted specs (queued) and parked ones
    (cancelled).  The single source for every read-only job table --
    the --status fleet view and `fleet_tool.py list` both render
    this."""
    state, _, _ = journal_states(os.path.join(spool, JOURNAL_FILE))
    if os.path.isdir(spool):
        for fn in sorted(os.listdir(spool)):
            if fn.startswith("."):
                continue
            if fn.endswith(".cancelled.json"):
                state.setdefault(fn[:-len(".cancelled.json")],
                                 "cancelled")
            elif fn.endswith(".json"):
                state.setdefault(fn[:-len(".json")], "queued")
    return state


class FleetOrchestrator:
    def __init__(self, spool: str, cfg: FleetConfig | None = None,
                 env=None, clock=time.time, sleep=time.sleep,
                 spawn_factory=None):
        # canonical spool path: children's command lines embed it, and
        # the orphan reaper's /proc identity check compares against it
        # -- a restart from a differently-spelled path ("runs" vs
        # "./runs" vs a symlink) must still recognize its own orphans
        self.spool = os.path.realpath(str(spool))
        base_env = dict(os.environ if env is None else env)
        self.cfg = cfg or FleetConfig.from_env(base_env)
        self._base_env = base_env
        self._clock = clock
        self._sleep = sleep
        # tests inject stub children here: factory(job) -> spawn fn with
        # the Supervisor._spawn_default signature (argv, env, logf)
        self._spawn_factory = spawn_factory or self._make_spawn
        self.jobs: dict = {}
        self._stop = False
        self.breaker = CircuitBreaker(self.cfg.breaker_k,
                                      self.cfg.breaker_sec)
        self.xla_fallback = False
        self.admissions_paused = False
        self.failures = {c: 0 for c in FAILURE_CLASSES}
        self.journal_path = os.path.join(self.spool, JOURNAL_FILE)
        self.metrics_path = os.path.join(self.spool, FLEET_METRICS_FILE)
        os.makedirs(self.spool, exist_ok=True)
        # fleet-level alert plane (observability/alerts.py): evaluated
        # over the orchestrator's OWN history ring (fleet.hist.jsonl --
        # queue depth, breaker state) each poll; per-job rules run in
        # each job's embedded Supervisor, whose firing set the poll
        # loop reads in-process (_note_alert_hints).  Rules marked
        # degrade-hint feed a breadcrumb into the failure tally +
        # circuit breaker from EITHER layer (admission pause at worst
        # -- never a kill).
        self._hist = history.HistorySink(self.metrics_path,
                                         env=self._base_env)
        self.alert_eval_sec = float(
            self._base_env.get("TPU_ALERT_EVAL_SEC", 5.0))
        self.alerts = None
        if self.alert_eval_sec > 0:
            try:
                self.alerts = alerts_mod.AlertPlane(
                    alerts_mod.load_rules(self.spool),
                    journal_path=os.path.join(self.spool,
                                              alerts_mod.ALERTS_FILE),
                    max_bytes=self.cfg.journal_max_bytes,
                    on_transition=self._on_alert)
            except (OSError, ValueError) as e:
                print(f"[fleet] alert rules disabled: {e}",
                      file=sys.stderr)
        self._alerts_next = 0.0
        self._pending_recovery: dict = {}
        self._recovered = False
        self._shard_cursor = 0
        self._replay()
        # the streaming serve layer (--dynamic / TPU_FLEET_DYNAMIC):
        # batchable specs route into warm ghost-padded serve children
        self.serve_pool = None
        if self.cfg.dynamic:
            from avida_tpu.service.serve import ServePool
            self.serve_pool = ServePool(self)

    # ---- journal ----

    def journal(self, event: str, **fields):
        rec = {"record": "fleet", "event": event, "time": self._clock(),
               **fields}
        try:
            # rotation is done here rather than via append_record's
            # max_bytes: the fresh file must START with a compaction
            # snapshot, or a second rotation would clobber the .1 aside
            # holding a live job's admit/spawn records and replay would
            # lose the job (and its orphan's pid) entirely
            try:
                size = os.path.getsize(self.journal_path)
            except OSError:
                size = 0
            if size and size + len(json.dumps(rec)) + 1 \
                    > self.cfg.journal_max_bytes:
                os.replace(self.journal_path, self.journal_path + ".1")
                append_record(self.journal_path, {
                    "record": "fleet", "event": "snapshot",
                    "time": self._clock(),
                    "xla_fallback": self.xla_fallback,
                    "jobs": {n: {"state": j.state, "pid": j.pid,
                                 "leader": j.batch_leader}
                             for n, j in self.jobs.items()}})
            append_record(self.journal_path, rec)
        except OSError:
            pass                        # logging must not kill the fleet
        detail = " ".join(f"{k}={v}" for k, v in fields.items())
        print(f"[fleet] {event}" + (f": {detail}" if detail else ""),
              file=sys.stderr)

    def _replay(self):
        """Rebuild job state from the journal -- READ-ONLY: no journal
        writes, no process kills, so constructing an orchestrator (or a
        status/list view over its guts) cannot disturb a live fleet.
        The destructive half (orphan reaping, half-done spec moves,
        replay_resume records) is _recover(), which runs behind the
        fleet.lock at the first poll."""
        state, pids, self.xla_fallback = journal_states(self.journal_path)
        for name, st in state.items():
            job = Job(name, self.spool)
            self.jobs[name] = job
            if st in ("done", "failed", "cancelled", "quarantined"):
                job.state = st
                continue
            if st == "cancelling":
                # the cancel's graceful stop was mid-flight when the
                # last orchestrator died: honor it (never resurrect),
                # but the child may still be alive -- reap at recovery
                job.state = "cancelled"
            else:
                # admitted (or drained-requeued): back to the queue;
                # the Supervisor always appends --resume, so the job
                # continues from its newest checkpoint
                job.state = "queued"
            self._pending_recovery[name] = (pids.get(name), st)

    def _recover(self):
        """The destructive half of replay, run once behind fleet.lock:
        reap orphans left by a killed orchestrator, complete half-done
        admission moves, journal what was resumed."""
        if self._recovered:
            return
        self._recovered = True
        for name, (pid, st) in self._pending_recovery.items():
            job = self.jobs[name]
            self._reap_orphan(name, pid)
            if st == "cancelling":
                self.journal("cancelled", job=name, reason="replayed")
                continue
            src = self._find_spool_spec(name)
            if not os.path.exists(job.spec_path) and src:
                os.makedirs(job.dir, exist_ok=True)
                os.replace(src, job.spec_path)
            if st == "running":
                self.journal("replay_resume", job=name)
        self._pending_recovery = {}

    def _reap_orphan(self, name: str, pid):
        """A SIGKILLed orchestrator leaves children running detached; a
        resumed job must never have TWO children writing one checkpoint
        dir.  Children are spawned in their own session (pgid == pid),
        so kill the group -- but only after /proc confirms the pid
        still belongs to this job (pid reuse must not kill an
        innocent)."""
        if not pid:
            return
        try:
            with open(f"/proc/{pid}/cmdline", "rb") as f:
                cmd = f.read().replace(b"\0", b" ").decode("utf-8",
                                                           "replace")
        except OSError:
            return                      # gone (or no /proc): nothing up
        if os.path.join(self.spool, name) not in cmd:
            return                      # pid reused by someone else
        self.journal("orphan_killed", job=name, pid=pid)
        try:
            os.killpg(pid, signal.SIGKILL)
        except OSError:
            try:
                os.kill(pid, signal.SIGKILL)
            except OSError:
                pass
        deadline = time.time() + 5.0
        while os.path.exists(f"/proc/{pid}") and time.time() < deadline:
            time.sleep(0.05)

    # ---- admission ----

    def _shard_dirs(self) -> list:
        """Spool shards (`shard-*` subdirs, fleet_tool submit --shard):
        a thousands-deep queue splits across shards so one poll tick
        never stats the whole backlog."""
        try:
            return sorted(
                d for d in os.listdir(self.spool)
                if d.startswith("shard-")
                and os.path.isdir(os.path.join(self.spool, d)))
        except OSError:
            return []

    def _find_spool_spec(self, name: str) -> str | None:
        """Where a queued spec file for `name` lives right now: the
        spool root, or one of the shard subdirs."""
        p = os.path.join(self.spool, name + ".json")
        if os.path.exists(p):
            return p
        for d in self._shard_dirs():
            p = os.path.join(self.spool, d, name + ".json")
            if os.path.exists(p):
                return p
        return None

    def _scan_spool(self):
        """Pick up newly submitted specs; quarantine malformed ones NOW
        (a spec that cannot run must not be retried forever, and must
        not wait for an admission slot to be found out).  Scales to
        thousands of queued specs two ways: shard subdirs are visited
        round-robin (one per tick, plus the root), and with
        TPU_FLEET_QUEUE_MAX set the scan stops ingesting once that many
        jobs sit queued -- later specs wait ON DISK, unscanned (the
        backpressure surface), until the queue drains."""
        dirs = [self.spool]
        shards = self._shard_dirs()
        if shards:
            dirs.append(os.path.join(
                self.spool, shards[self._shard_cursor % len(shards)]))
            self._shard_cursor += 1
        queued = sum(1 for j in self.jobs.values()
                     if j.state == "queued")
        for d in dirs:
            try:
                entries = sorted(os.listdir(d))
            except OSError:
                continue
            for fn in entries:
                if not fn.endswith(".json") or fn.startswith(".") \
                        or fn.endswith(".cancelled.json"):
                    continue
                name = fn[:-len(".json")]
                if name in self.jobs:
                    continue            # known: admitted jobs moved
                                        # their spec, so this is a
                                        # resubmit race -- never a
                                        # double spawn
                if self.cfg.queue_max and queued >= self.cfg.queue_max:
                    return              # backpressure: stop ingesting
                path = os.path.join(d, fn)
                job = Job(name, self.spool)
                job.spool_src = path
                try:
                    if not legal_name(name):
                        raise ValueError(f"illegal job name {name!r}")
                    with open(path) as f:
                        spec = json.load(f)
                    validate_spec(spec)
                except (ValueError, OSError) as e:
                    self._quarantine_spec(job, path, str(e))
                    continue
                job.spec = spec
                self.jobs[name] = job
                queued += 1

    def _quarantine_spec(self, job: Job, path: str, error: str):
        dst = os.path.join(
            self.spool,
            f".bad-{os.path.basename(path)}.{int(self._clock())}")
        try:
            os.replace(path, dst)
        except OSError:
            dst = ""
        job.state = "quarantined"
        self.jobs[job.name] = job
        self.journal("quarantined", job=job.name, error=error,
                     moved_to=os.path.basename(dst))

    def _admit(self, now: float):
        """Admission control: batch placement first (serve-pool routing
        under --dynamic, else the static coalescer -- either way a
        batch serves W tenants on one slot), then fill the remaining
        slots from the queue, unless the circuit breaker holds
        admissions.  Per-tenant quotas (TPU_FLEET_TENANT_MAX) hold a
        tenant's overflow in the queue without blocking others."""
        self.admissions_paused = self.breaker.is_open(now)
        if self.admissions_paused:
            return
        running = sum(1 for j in self.jobs.values()
                      if j.state == "running")
        tenants = self._tenant_load() if self.cfg.tenant_max else None
        if self.serve_pool is not None:
            running = self._admit_serve(running, tenants)
        else:
            for members in self._form_batches():
                if running >= self.cfg.max_jobs:
                    break
                if tenants is not None:
                    # the quota covers batched riders too: over-quota
                    # members stay queued; a batch needs >= 2 in-quota
                    # members to still be a batch this tick
                    members = [(j, s) for j, s in members
                               if not self._over_quota(j, tenants)]
                    if len(members) < 2:
                        continue
                if self._start_batch(members):
                    running += 1
                    for j, _ in members:
                        if j.state in ("running", "batched"):
                            self._tenant_note(tenants, j)
        for name in sorted(self.jobs):
            if running >= self.cfg.max_jobs:
                break
            job = self.jobs[name]
            if job.state != "queued":
                continue
            if self.serve_pool is not None and job._serve_sig is not None:
                # a serve-eligible spec the pool could not place THIS
                # tick (class full / no free slot): it waits for a
                # ghost slot or the next class spawn -- starting it
                # solo here would pay the launch+compile the serve
                # layer exists to remove
                continue
            if self._over_quota(job, tenants):
                continue
            if self._start(job):
                running += 1
                self._tenant_note(tenants, job)

    # ---- per-tenant quotas ----

    def _spec_tenant(self, job: Job) -> str:
        spec = self._load_spec(job)
        return str((spec or {}).get("tenant") or "")

    def _tenant_load(self) -> dict:
        load: dict = {}
        for j in self.jobs.values():
            if j.state in ("running", "batched"):
                t = self._spec_tenant(j)
                if t:
                    load[t] = load.get(t, 0) + 1
        return load

    def _over_quota(self, job: Job, tenants) -> bool:
        if tenants is None:
            return False
        t = self._spec_tenant(job)
        return bool(t) and tenants.get(t, 0) >= self.cfg.tenant_max

    def _tenant_note(self, tenants, job: Job):
        if tenants is None:
            return
        t = self._spec_tenant(job)
        if t:
            tenants[t] = tenants.get(t, 0) + 1

    # ---- the streaming serve layer (service/serve.py) ----

    def _admit_serve(self, running: int, tenants) -> int:
        """Serve-pool admission: warm-class placements (cache hits)
        cost NO admission slot -- the class child is already running --
        while each cold class spawn costs one.  Ineligible batch specs
        fall back to the ordinary solo queue with the reason
        journaled."""
        from avida_tpu.service.serve import (SpecArgv,
                                             batch_ineligible_reason)
        pool = self.serve_pool
        groups: dict = {}
        for name in sorted(self.jobs):
            job = self.jobs[name]
            if job.state != "queued":
                continue
            spec = self._load_spec(job)
            if spec is None or not spec.get("batch"):
                continue
            if spec.get("fault_plan"):
                self._batch_fallback(job, "fault_plan is per-process")
                continue
            reason = batch_ineligible_reason(spec)
            if reason is not None:
                self._batch_fallback(job, reason)
                continue
            if SpecArgv(spec.get("argv")).effective_seed is None:
                self._batch_fallback(job, "no explicit seed in argv")
                continue
            if self._over_quota(job, tenants):
                continue
            if pool.offer(job, spec):
                self._tenant_note(tenants, job)
                continue
            if job.state != "queued":
                continue                # quarantined by a failed place
            groups.setdefault(job._serve_sig, []).append((job, spec))
        for sig in sorted(groups):
            if running >= self.cfg.max_jobs:
                break
            if pool.spawn_class(groups[sig]):
                running += 1
                for job, _ in groups[sig]:
                    if job.state == "batched":
                        self._tenant_note(tenants, job)
        return running

    # ---- device-lane packing (spec "batch": true) ----

    def _load_spec(self, job: Job):
        """Best-effort spec read for a queued job (spool root or its
        already-moved job.json); None when unreadable -- the normal
        admission path surfaces the error."""
        if job.spec is not None:
            return job.spec
        src = (job.spool_src if job.spool_src
               and os.path.exists(job.spool_src)
               else self._find_spool_spec(job.name))
        for path in filter(None, (job.spec_path, src)):
            try:
                with open(path) as f:
                    spec = json.load(f)
                validate_spec(spec)
                job.spec = spec
                return spec
            except (OSError, ValueError):
                continue
        return None

    def _batch_fallback(self, job: Job, reason: str):
        """Journal (once) why a '"batch": true' spec runs as an
        ordinary process-per-job instead -- the documented clean
        fallback.  The job stays queued and batchable: a static-equal
        peer arriving before a slot frees can still pick it up."""
        if job._batch_fallback_logged:
            return
        job._batch_fallback_logged = True
        self.journal("batch_fallback", job=job.name, reason=reason)

    def _form_batches(self) -> list:
        """Group queued '"batch": true' specs by their static key
        (seed-stripped argv + env -- identical keys trace one compiled
        update program).  Returns a list of batches, each a [(job,
        seed)] list sorted by name (the first member leads).  Specs
        that cannot batch -- a fault plan (per-process chaos), no
        explicit seed, no static-equal peer -- fall back to
        process-per-job with the reason journaled."""
        groups: dict = {}
        for name in sorted(self.jobs):
            job = self.jobs[name]
            if job.state != "queued":
                continue
            spec = self._load_spec(job)
            if spec is None or not spec.get("batch"):
                continue
            if spec.get("fault_plan"):
                self._batch_fallback(job, "fault_plan is per-process")
                continue
            # the signature now resolves config files and hashes the
            # config dir's contents -- cache it per job like
            # _batch_progress below (a queued spec cannot change, and
            # re-hashing thousands of parked specs' config dirs every
            # poll tick would hammer the disk)
            if job._batch_key is None:
                job._batch_key = spec_seed_and_batch_key(spec)
            seed, key = job._batch_key
            if seed is None:
                self._batch_fallback(job, "no explicit seed in argv")
                continue
            # resume-progress compatibility: the child resumes a batch
            # aligned on ONE update, so a requeued member with
            # checkpoints must not coalesce with a fresh spec (the
            # mixed set would refuse to resume on every boot).  Key on
            # the newest published generation's update (-1 = fresh),
            # cached per job -- it cannot change while the job sits
            # queued, and rescanning 100 parked specs' dirs every
            # poll tick would hammer the disk for nothing
            if job._batch_progress is None:
                from avida_tpu.utils.checkpoint import (
                    generation_update, list_generations)
                gens = list_generations(job.ckpt_dir)
                job._batch_progress = (generation_update(gens[-1])
                                       if gens else -1)
            groups.setdefault((key, job._batch_progress),
                              []).append((job, seed))
        batches = []
        for key in sorted(groups, key=str):
            members = groups[key]
            if len(members) < 2:
                self._batch_fallback(members[0][0],
                                     "no static-equal peer queued")
                continue
            # width cap: split wide groups so one batched child never
            # stacks more than max_batch worlds (TPU_FLEET_MAX_BATCH)
            for i in range(0, len(members), self.cfg.max_batch):
                chunk = members[i:i + self.cfg.max_batch]
                if len(chunk) >= 2:
                    batches.append(chunk)
                else:
                    self._batch_fallback(chunk[0][0],
                                         "width-cap remainder")
        return batches

    def _start_batch(self, members: list) -> bool:
        """Admit one coalesced batch: every member's spec moves into
        its own fault domain (per-world data + checkpoints survive in
        solo-compatible form), a worlds.json manifest lands in the
        leader's domain, and ONE supervised `--worlds` child serves
        them all.  Occupies one admission slot."""
        admitted = [(job, seed) for job, seed in members
                    if self._admit_spec_move(job)]
        if not admitted:
            return False
        if len(admitted) == 1:
            return self._start(admitted[0][0])
        leader, _ = admitted[0]
        manifest = [{"name": j.name, "seed": s,
                     "data_dir": j.data_dir, "ckpt_dir": j.ckpt_dir}
                    for j, s in admitted]
        mpath = os.path.join(leader.dir, "worlds.json")
        tmp = f"{mpath}.tmp.{os.getpid()}"
        try:
            with open(tmp, "w") as f:
                json.dump(manifest, f, indent=1)
                f.write("\n")
            os.replace(tmp, mpath)
        except OSError as e:
            self.journal("batch_fallback", job=leader.name,
                         reason=f"manifest write failed: {e}")
            return self._start(leader)
        # the child argv template: the leader's argv with per-member
        # routing stripped (the worlds manifest carries seeds + dirs);
        # static-equal peers may SPELL their configs differently, but
        # they resolve identically -- that is what the signature proved
        from avida_tpu.service.serve import member_argv
        argv = member_argv(leader.spec) + [
            "--worlds", mpath,
            "-d", leader.data_dir, "-set", "TPU_CKPT_DIR",
            leader.ckpt_dir]
        env = self._child_env(leader.spec)
        try:
            sup = Supervisor(argv, cfg=SupervisorConfig.from_env(env),
                             env=env, spawn=self._spawn_factory(leader),
                             clock=self._clock, sleep=self._sleep)
        except ValueError as e:
            self.journal("batch_fallback", job=leader.name,
                         reason=f"supervisor refused batch argv: {e}")
            return self._start(leader)
        if self.xla_fallback:
            sup._xla_fallback = True
        leader.sup = sup
        leader._fail_snapshot = dict(sup.failures)
        leader.state = "running"
        leader.batch_members = [j.name for j, _ in admitted[1:]]
        self.journal("coalesce", job=leader.name,
                     members=leader.batch_members,
                     worlds=len(admitted))
        for j, _ in admitted[1:]:
            j.state = "batched"
            j.batch_leader = leader.name
            self.journal("coalesced", job=j.name, leader=leader.name)
        sup.publish_metrics()
        return True

    def _finish_batch(self, leader: Job):
        """Propagate the leader's terminal state to its riders: done and
        failed verbatim; a drained/preempted batch requeues every member
        (their solo-format checkpoints make each independently
        resumable -- re-coalescing or running solo both continue
        bit-exactly); a member that asked for cancellation lands
        `cancelled` while its peers requeue."""
        members, leader.batch_members = leader.batch_members, []
        for mname in members:
            m = self.jobs.get(mname)
            if m is None or m.batch_leader != leader.name:
                continue
            m.batch_leader = None
            if leader.state in ("done", "failed"):
                m.state = leader.state
                self.journal(leader.state, job=m.name,
                             batch_leader=leader.name)
            elif m.cancel_requested:
                m.state = "cancelled"
                self.journal("cancelled", job=m.name)
            else:
                m.state = "queued"
                m.sup = None
                m._batch_progress = None   # checkpoints advanced
                m._batch_key = None
                self.journal("requeued", job=m.name,
                             reason="batch_"
                                    + ("cancelled"
                                       if leader.state == "cancelled"
                                       else "drain"))

    def _child_env(self, spec) -> dict:
        """The environment every child (solo, --worlds batch, serve
        class) is spawned with.  Beyond base env + per-spec overrides,
        the fleet points children at ONE spool-level persistent AOT
        program cache (utils/compilecache.py) unless the operator or
        the spec routed it elsewhere -- so a cold-spawned class child
        deserializes a sibling's executables in milliseconds instead
        of re-paying the compile window, and fleet-wide warmup is paid
        once per (signature, width), not once per child.
        TPU_COMPILE_CACHE=0 anywhere in the inherited env still kills
        the cache inside the child (the hard switch)."""
        env = dict(self._base_env)
        env.update(spec.get("env") or {})
        env.setdefault("TPU_COMPILE_CACHE_DIR",
                       os.path.join(self.spool, "compile-cache"))
        return env

    def _admit_spec_move(self, job: Job) -> bool:
        """The transactional half of admission, shared by solo and
        batched starts: journal-first ("admit"), THEN atomically move
        the spec into the job's fault domain -- if we die between the
        two steps, replay finds the admit record and completes the
        move before respawning.  False = quarantined (path blocked)."""
        if os.path.exists(job.spec_path):
            return True
        src = (job.spool_src if job.spool_src
               and os.path.exists(job.spool_src)
               else self._find_spool_spec(job.name)) \
            or job.spool_spec_path
        self.journal("admit", job=job.name)
        try:
            os.makedirs(job.dir, exist_ok=True)
            os.replace(src, job.spec_path)
        except OSError as e:
            # e.g. the job-dir path is blocked by a file: quarantine
            # rather than crash-loop the whole orchestrator
            self._quarantine_spec(job, src, f"spec move failed: {e}")
            return False
        return True

    def _start(self, job: Job) -> bool:
        """Admit one queued job: transactional spec move + Supervisor
        construction + first child launch."""
        if not self._admit_spec_move(job):
            return False
        if job.spec is None:
            try:
                with open(job.spec_path) as f:
                    job.spec = json.load(f)
                validate_spec(job.spec)
            except (ValueError, OSError) as e:
                job.state = "quarantined"
                self.journal("quarantined", job=job.name, error=str(e))
                return False
        argv = list(job.spec["argv"]) + [
            "-d", job.data_dir, "-set", "TPU_CKPT_DIR", job.ckpt_dir]
        env = self._child_env(job.spec)
        try:
            sup = Supervisor(argv,
                             fault_plan=job.spec.get("fault_plan") or (),
                             cfg=SupervisorConfig.from_env(env), env=env,
                             spawn=self._spawn_factory(job),
                             clock=self._clock, sleep=self._sleep)
        except ValueError as e:
            job.state = "quarantined"
            self.journal("quarantined", job=job.name, error=str(e))
            return False
        if self.xla_fallback:
            sup._xla_fallback = True    # fleet-wide degradation applies
        job.sup = sup
        job._fail_snapshot = dict(sup.failures)
        job.state = "running"
        sup.publish_metrics()
        return True

    def _make_spawn(self, job: Job):
        def spawn(argv, env, logf):
            # own session => pgid == pid: the whole child tree is
            # reapable after an orchestrator crash, and a terminal ^C
            # cannot fan out to every tenant
            proc = subprocess.Popen(argv, env=env, stdout=logf,
                                    stderr=logf, start_new_session=True)
            job.pid = proc.pid
            self.journal("spawn", job=job.name, pid=proc.pid,
                         boot=job.sup.boots - 1 if job.sup else 0)
            return proc
        return spawn

    # ---- operator markers (fleet_tool.py cancel/requeue) ----

    def _consume_markers(self):
        # act (journal) FIRST, remove the marker after: a crash in
        # between re-consumes an already-journaled marker on restart (a
        # no-op -- _cancel/_requeue are idempotent), whereas the other
        # order would silently lose the operator's request
        for fn in sorted(os.listdir(self.spool)):
            if fn.endswith(".cancel"):
                self._cancel(fn[:-len(".cancel")])
                os.remove(os.path.join(self.spool, fn))
            elif fn.endswith(".requeue"):
                self._requeue(fn[:-len(".requeue")], reason="operator")
                os.remove(os.path.join(self.spool, fn))

    def _cancel(self, name: str):
        job = self.jobs.get(name)
        if job is None:
            # not ingested yet -- the spec can sit on disk unscanned
            # behind TPU_FLEET_QUEUE_MAX backpressure or a later shard's
            # round-robin turn; park it NOW so a future scan cannot
            # admit a job the operator already cancelled
            src = self._find_spool_spec(name)
            if src:
                try:
                    os.replace(src, os.path.join(
                        self.spool, name + ".cancelled.json"))
                except OSError:
                    return
                self.journal("cancelled", job=name,
                             reason="cancelled before ingestion")
            return
        if job.state in ("done", "failed", "cancelled", "quarantined"):
            return
        if job.state == "queued":
            # park an unadmitted spec so a rescan cannot resurrect it
            src = (job.spool_src if job.spool_src
                   and os.path.exists(job.spool_src)
                   else self._find_spool_spec(name))
            if src:
                os.replace(src, os.path.join(self.spool,
                                             name + ".cancelled.json"))
            job.state = "cancelled"
            self.journal("cancelled", job=name)
            return
        if job.state == "batched":
            if self.serve_pool is not None \
                    and self.serve_pool.cancel(job):
                # serve member: demoted alone -- the class child
                # retires it with a final checkpoint at the next
                # boundary while its classmates keep running
                return
            # a static-batch rider has no child of its own: preempt the
            # whole batch gracefully -- this member lands `cancelled`,
            # its peers requeue from their per-world checkpoints
            # (_finish_batch)
            job.cancel_requested = True
            leader = self.jobs.get(job.batch_leader or "")
            if leader is not None and leader.sup is not None:
                leader.sup.request_stop()
            self.journal("cancel_requested", job=name,
                         batch_leader=job.batch_leader)
            return
        # running: graceful stop; _poll_job records the terminal state
        # once the child has written its preemption checkpoint
        job.cancel_requested = True
        job.sup.request_stop()
        self.journal("cancel_requested", job=name)

    def _requeue(self, name: str, reason: str):
        job = self.jobs.get(name)
        if job is None or job.state not in ("failed", "cancelled"):
            return
        parked = os.path.join(self.spool, name + ".cancelled.json")
        if not os.path.exists(job.spec_path) and os.path.exists(parked):
            os.replace(parked, job.spool_spec_path)
        job.sup = None
        job.spec = None
        job.cancel_requested = False
        job.state = "queued"
        job._batch_progress = None
        job._batch_key = None
        self.journal("requeued", job=name, reason=reason)

    # ---- the poll loop ----

    def _poll_job(self, job: Job, now: float):
        try:
            state = job.sup.poll()
        except Exception as e:
            # one job's supervisor blowing up must not sink the fleet;
            # journaled as "failed" (not a bespoke event) so replay and
            # the job tables agree it is terminal
            job.state = "failed"
            self.journal("failed", job=job.name, error=str(e))
            if job.batch_members:
                self._finish_batch(job)
            return
        self._note_failures(job, now)
        self._note_alert_hints(job)
        if state not in ("done", "failed"):
            return
        if state == "failed":
            job.state = "failed"
            self.journal("failed", job=job.name,
                         failures=dict(job.sup.failures))
        elif job.sup.succeeded:
            job.state = "done"
            self.journal("done", job=job.name)
        elif job.cancel_requested:
            job.state = "cancelled"
            self.journal("cancelled", job=job.name)
        else:
            # supervisor preempted (drain): incomplete but resumable
            job.state = "queued"
            job.sup = None
            job._batch_progress = None   # checkpoints advanced
            job._batch_key = None
            self.journal("requeued", job=job.name, reason="drain")
        if job.batch_members:
            self._finish_batch(job)

    def _note_failures(self, job: Job, now: float):
        """Diff the job supervisor's per-class failure counters into the
        fleet aggregates + the circuit breaker."""
        for cls, n in job.sup.failures.items():
            delta = n - job._fail_snapshot.get(cls, 0)
            if delta <= 0:
                continue
            job._fail_snapshot[cls] = n
            self.failures[cls] = self.failures.get(cls, 0) + delta
            for _ in range(delta):
                if self.breaker.note_failure(cls, now):
                    self._open_breaker(cls, job)

    def note_external_failure(self, cls: str, job: Job):
        """Count one classified failure detected OUTSIDE a job
        supervisor -- a serve child's in-process `sdc` demotion
        (parallel/multiworld.ServeBatch) reports through its status
        file, not an exit code -- into the fleet aggregates and the
        circuit breaker, so an SDC storm (a sick device corrupting one
        tenant after another) pauses admissions like any crash storm."""
        self.failures[cls] = self.failures.get(cls, 0) + 1
        if self.breaker.note_failure(cls, self._clock()):
            self._open_breaker(cls, job)

    def _note_alert_hints(self, job: Job):
        """Degrade-hint breadcrumbs from a job's EMBEDDED supervisor:
        run-level rules (integrity_mismatch and friends, pinned to the
        job's own metrics ring) evaluate inside each job's Supervisor,
        whose AlertPlane the fleet can read in-process -- no file
        round-trip.  One breadcrumb per firing EDGE per job (the set
        diff below re-arms a rule once it resolves), into the same
        failure-tally + circuit-breaker path as _on_alert."""
        plane = getattr(job.sup, "alerts", None)
        if plane is None:
            return
        firing = set(plane.firing)
        for name in sorted(firing - job._alert_hints):
            rule = plane.rules.get(name)
            if rule is None or rule.action != "degrade-hint":
                continue
            self.journal("alert", rule=name, state="firing",
                         severity=rule.severity, job=job.name)
            cls = f"alert:{name}"
            self.failures[cls] = self.failures.get(cls, 0) + 1
            if self.breaker.note_failure(cls, self._clock()):
                self._open_breaker(cls, job)
        job._alert_hints = firing

    def _on_alert(self, rule, state: str, res: dict):
        """AlertPlane edge hook: every transition journals a fleet
        event (the alerts.jsonl {"record": "alert"} line is the
        canonical record; this one correlates it into the fleet
        timeline), and a FIRING degrade-hint rule drops a breadcrumb
        into the failure tally + circuit breaker -- the detection
        plane's only actuator is an admission pause, never a kill."""
        self.journal("alert", rule=rule.name, state=state,
                     severity=rule.severity, value=res.get("value"))
        if state != "firing" or rule.action != "degrade-hint":
            return
        cls = f"alert:{rule.name}"
        self.failures[cls] = self.failures.get(cls, 0) + 1
        if self.breaker.note_failure(cls, self._clock()):
            self._open_breaker(cls, None)

    def _eval_alerts(self, now: float):
        """Evaluate the fleet rule set over fleet.hist.jsonl, at most
        every alert_eval_sec (TPU_ALERT_EVAL_SEC=0 disables)."""
        if self.alerts is None or now < self._alerts_next:
            return
        self._alerts_next = now + self.alert_eval_sec
        samples = {"fleet": history.read_samples(
            history.hist_path(self.metrics_path), tail_bytes=256 << 10)}
        self.alerts.observe(samples, now)

    def _open_breaker(self, cls: str, job: Job | None):
        self.journal("breaker_open", failure_class=cls,
                     k=self.breaker.k,
                     window_sec=self.breaker.window_sec,
                     job=job.name if job is not None else "")
        if job is None or job.sup is None:
            # alert-breadcrumb storms carry no child outcome to
            # implicate the kernel path -- pause admissions only
            return
        out = job.sup.last_outcome
        pallas_storm = (job.sup._xla_fallback
                        or (out is not None and out.pallas))
        if pallas_storm and not self.xla_fallback:
            # a kernel-implicated crash storm: degrade the WHOLE fleet
            # to the XLA path once, instead of letting every job burn a
            # discovery crash on the same broken kernel
            self.xla_fallback = True
            self.journal("xla_fallback",
                         detail="fleet-wide -set TPU_USE_PALLAS 2 "
                                "(kernel-implicated crash storm)")
            for j in self.jobs.values():
                if j.sup is not None:
                    j.sup._xla_fallback = True

    def poll_once(self) -> bool:
        """One orchestration step: scan, consume markers, admit, poll
        every running job.  Returns True while any job is live."""
        self._recover()
        now = self._clock()
        self._scan_spool()
        self._consume_markers()
        closed = self.breaker.maybe_close(now)
        if closed is not None:
            self.journal("breaker_close", failure_class=closed)
        self._eval_alerts(now)
        if self.serve_pool is not None:
            # settle member outcomes BEFORE admission: a member the
            # child finished must journal `done` before the admit pass
            # could mistake its freed slot for capacity twice
            self.serve_pool.poll()
        self._admit(now)
        for job in [j for j in self.jobs.values()
                    if j.state == "running" and j.sup is not None]:
            self._poll_job(job, now)
        self.publish_metrics()
        return any(j.state in ("queued", "running", "batched")
                   for j in self.jobs.values())

    # ---- metrics / status ----

    def publish_metrics(self):
        counts = {s: 0 for s in JOB_STATES}
        for j in self.jobs.values():
            counts[j.state] = counts.get(j.state, 0) + 1
        fams = [
            ("avida_fleet_jobs", "gauge", "jobs by orchestration state",
             {f'state="{s}"': n for s, n in sorted(counts.items())}),
            ("avida_fleet_failures_total", "counter",
             "classified child failures across all jobs",
             {f'class="{c}"': n for c, n in self.failures.items()}),
            ("avida_fleet_breaker_open", "gauge",
             "1 while the crash-storm circuit breaker holds admissions",
             int(self.breaker.open_class is not None)),
            ("avida_fleet_breaker_trips_total", "counter",
             "circuit breaker openings", self.breaker.trips),
            ("avida_fleet_admissions_paused", "gauge",
             "1 while admission control is refusing new jobs",
             int(self.admissions_paused)),
            ("avida_fleet_xla_fallback", "gauge",
             "1 after the fleet-wide Pallas->XLA degradation",
             int(self.xla_fallback)),
            ("avida_fleet_max_jobs", "gauge",
             "admission-control concurrency budget", self.cfg.max_jobs),
            ("avida_fleet_queue_depth", "gauge",
             "jobs ingested and waiting for admission (backpressure "
             "holds later specs on disk past TPU_FLEET_QUEUE_MAX)",
             counts.get("queued", 0)),
            ("avida_fleet_heartbeat_timestamp_seconds", "gauge",
             "unix time of the last orchestrator export",
             round(time.time(), 3)),
        ]
        if self.serve_pool is not None:
            fams += self.serve_pool.gauges()
        if self.alerts is not None:
            fams += self.alerts.families()
        try:
            text = render_families(fams)
            write_metrics(self.metrics_path, text, durable=False)
            self._hist.publish(text)
        except OSError:
            pass

    # ---- lifecycle ----

    def _acquire_lock(self):
        """Two orchestrators draining one spool would double-spawn every
        job -- refuse to start while a live one holds the lock.  The
        acquire is an O_CREAT|O_EXCL create (atomic: two racers cannot
        both win); a lock whose pid is dead, recycled by a non-fleet
        process, or our own is stale and taken over."""
        path = os.path.join(self.spool, LOCK_FILE)
        for _attempt in range(2):
            try:
                fd = os.open(path, os.O_CREAT | os.O_EXCL | os.O_WRONLY)
            except FileExistsError:
                try:
                    with open(path) as f:
                        pid = int(f.read().strip() or 0)
                except (OSError, ValueError):
                    pid = 0
                if pid and pid != os.getpid() \
                        and self._pid_owns_spool(pid):
                    raise FleetLockedError(
                        f"orchestrator pid {pid} already owns "
                        f"{self.spool!r} ({LOCK_FILE})")
                try:
                    os.remove(path)     # stale: take over, then re-race
                except OSError:
                    pass
                continue
            with os.fdopen(fd, "w") as f:
                f.write(f"{os.getpid()}\n")
            return
        raise FleetLockedError(
            f"could not acquire {LOCK_FILE} under {self.spool!r}")

    def _pid_owns_spool(self, pid: int) -> bool:
        """Is `pid` a live fleet orchestrator of THIS spool?  Resolves
        the --fleet argument out of /proc/<pid>/cmdline (relative paths
        against that process's own cwd) so a recycled pid running a
        DIFFERENT spool's fleet does not wedge this one forever.
        Conservative on ambiguity: an unresolvable --fleet argument
        still counts as the owner -- wrongly stealing a live lock
        (double orchestrator) is worse than wrongly refusing to start."""
        try:
            with open(f"/proc/{pid}/cmdline", "rb") as f:
                args = [a.decode("utf-8", "replace")
                        for a in f.read().split(b"\0") if a]
        except OSError:
            return False                # process gone: stale lock
        if "--fleet" not in args:
            return False                # pid recycled by something else
        i = args.index("--fleet")
        if i + 1 >= len(args):
            return True
        raw = args[i + 1]
        try:
            if not os.path.isabs(raw):
                raw = os.path.join(os.readlink(f"/proc/{pid}/cwd"), raw)
            return os.path.realpath(raw) == self.spool
        except OSError:
            return True

    def _release_lock(self):
        try:
            os.remove(os.path.join(self.spool, LOCK_FILE))
        except OSError:
            pass

    def request_stop(self):
        self._stop = True

    def _drain(self) -> int:
        """Graceful shutdown: SIGTERM every child (they write preemption
        checkpoints), wait up to drain_sec, requeue whatever did not
        complete.  Exit 0 -- a drained fleet is a resumable fleet."""
        running = [j for j in self.jobs.values() if j.state == "running"]
        self.journal("drain", jobs_running=len(running),
                     drain_sec=self.cfg.drain_sec)
        for job in running:
            job.sup.request_stop()
        deadline = self._clock() + self.cfg.drain_sec
        while self._clock() < deadline:
            live = [j for j in self.jobs.values()
                    if j.state == "running"]
            if not live:
                break
            for job in live:
                self._poll_job(job, self._clock())
            self.publish_metrics()
            self._sleep(min(self.cfg.poll_sec, 0.5))
        for job in [j for j in self.jobs.values()
                    if j.state == "running"]:
            # drain deadline blown: hard-stop, then one last poll so
            # the kill flows through the supervisor's _finish (child
            # log closed, classified exit record written) and the job
            # lands in the normal requeue path
            proc = job.sup._proc
            if proc is not None:
                job.sup._kill_child(proc)
            self._poll_job(job, self._clock())
            if job.state == "running":          # supervisor stuck: force
                job.state = "queued"
                job.sup = None
                self.journal("requeued", job=job.name,
                             reason="drain_kill")
        self.publish_metrics()
        self.journal("fleet_stop", reason="drain")
        return 0

    def run(self) -> int:
        """Orchestrate until the spool is drained (or forever with
        cfg.serve).  Returns 0 when every known job ended well
        (done/cancelled/requeued), 1 when any failed or was
        quarantined, 2 when another orchestrator holds the lock."""
        try:
            self._acquire_lock()
        except FleetLockedError as e:
            print(f"[fleet] {e}", file=sys.stderr)
            return 2
        saved = {}

        def on_signal(signum, frame):
            self._stop = True

        for s in (signal.SIGTERM, signal.SIGINT):
            try:
                saved[s] = signal.signal(s, on_signal)
            except ValueError:
                pass
        self.journal("fleet_start", max_jobs=self.cfg.max_jobs,
                     jobs_known=len(self.jobs))
        try:
            while True:
                if self._stop:
                    return self._drain()
                active = self.poll_once()
                if not active and not self.cfg.serve:
                    break
                self._sleep(self.cfg.poll_sec)
            bad = [j.name for j in self.jobs.values()
                   if j.state in ("failed", "quarantined")]
            self.journal("fleet_stop", reason="spool drained",
                         failed=sorted(bad))
            return 1 if bad else 0
        finally:
            for s, h in saved.items():
                try:
                    signal.signal(s, h)
                except (ValueError, OSError):
                    pass
            self.publish_metrics()
            self._release_lock()


# ---------------------------------------------------------------------------
# --status fleet view (host-only, no orchestrator required)
# ---------------------------------------------------------------------------

def format_fleet_status(spool: str, now: float | None = None) -> str:
    """Human-readable fleet summary: aggregate gauges from fleet.prom +
    a per-job table reconstructed from the journal and the spool."""
    now = time.time() if now is None else now
    lines = []
    metrics = {}
    mpath = os.path.join(spool, FLEET_METRICS_FILE)
    if os.path.exists(mpath):
        metrics = read_metrics(mpath)
        hb = metrics.get("avida_fleet_heartbeat_timestamp_seconds")
        age = f"{now - hb:.1f}s ago" if hb else "unknown"
        counts = {k.split('state="', 1)[1].rstrip('"}'): int(v)
                  for k, v in metrics.items()
                  if k.startswith("avida_fleet_jobs{")}
        lines.append("fleet       "
                     + ", ".join(f"{s} {n}" for s, n in
                                 sorted(counts.items()) if n))
        if metrics.get("avida_fleet_breaker_open"):
            lines.append("breaker     OPEN (admissions paused)")
        if metrics.get("avida_fleet_xla_fallback"):
            lines.append("degraded    fleet-wide XLA fallback active")
        # fleet-level alert column (observability/alerts.py families
        # exported by the orchestrator's own poll loop)
        from avida_tpu.observability.alerts import format_alert_status
        alert_line = format_alert_status(metrics)
        if alert_line is not None:
            lines.append(alert_line)
        lines.append(f"heartbeat   {age}")
    state = spool_job_states(spool)
    leaders = journal_batch_leaders(os.path.join(spool, JOURNAL_FILE))
    riders: dict = {}
    for member, leader in leaders.items():
        if state.get(member) == "batched":
            riders.setdefault(leader, []).append(member)

    def world_rows(leader: str) -> tuple:
        """({world_name: (update, organisms, straggler_lag)}, batch
        efficiency or None) from the leader batch's per-world metric
        rows (multiworld.prom).  The lag/efficiency gauges come from
        MultiWorldExporter's occupancy families (PR-11)."""
        path = os.path.join(spool, leader, "data", "multiworld.prom")
        if not os.path.exists(path):
            return {}, None
        from avida_tpu.observability.exporter import multiworld_rows
        m = read_metrics(path)
        rows = multiworld_rows(m)
        eff = m.get("avida_multiworld_batch_efficiency")
        return ({n: (int(d.get("avida_update", 0)),
                     int(d.get("avida_organisms", 0)),
                     float(d.get(
                         "avida_multiworld_straggler_lag_updates", 0.0)))
                 for n, d in rows.items()},
                None if eff is None else float(eff))

    for name in sorted(state):
        st = state[name]
        if st == "batched" and leaders.get(name) in riders:
            continue                  # rendered under its leader below
        extra = ""
        sup_prom = os.path.join(spool, name, "data", "supervisor.prom")
        if os.path.exists(sup_prom):
            sup = read_metrics(sup_prom)
            boots = int(sup.get("avida_supervisor_boots_total", 0))
            fails = int(sum(v for k, v in sup.items()
                            if k.startswith(
                                "avida_supervisor_failures_total")))
            extra = f"  (boots {boots}, failures {fails})"
            # per-job alert column: names of rules the job's embedded
            # supervisor currently reports firing
            from avida_tpu.observability.alerts import firing_from_metrics
            firing = firing_from_metrics(sup)["firing"]
            if firing:
                extra += "  ALERTS " + ",".join(sorted(firing))
        run_prom = os.path.join(spool, name, "data", "metrics.prom")
        runm = read_metrics(run_prom) if os.path.exists(run_prom) \
            else None
        if runm is not None and (
                "avida_compile_cache_hits_total" in runm
                or "avida_compile_cache_misses_total" in runm):
            # persistent-compile-cache column (utils/compilecache.py
            # families in the child's own heartbeat): hits/misses and
            # the milliseconds spent deserializing -- a warm fleet
            # shows Nh/0m with single-digit-second load totals where a
            # cold one burned minutes compiling
            extra += (
                "  cache "
                f"{int(runm.get('avida_compile_cache_hits_total', 0))}h/"
                f"{int(runm.get('avida_compile_cache_misses_total', 0))}m"
                f" load "
                f"{runm.get('avida_compile_cache_load_ms_total', 0.0):.0f}"
                f"ms")
        if runm is not None and (
                "avida_integrity_scrubs_total" in runm
                or "avida_state_digest" in runm):
            # integrity-plane column (utils/integrity.py families in
            # the child's heartbeat): scrubs / detected mismatches --
            # a nonzero second number means this job has already been
            # rolled back past silent corruption at least once
            extra += (
                "  integrity "
                f"{int(runm.get('avida_integrity_scrubs_total', 0))}s/"
                f"{int(runm.get('avida_integrity_mismatches_total', 0))}x")
        ana_prom = os.path.join(spool, name, "data", "analytics.prom")
        if os.path.exists(ana_prom):
            # per-tenant census column (analyze/pipeline.py live mode):
            # dominant lineage depth / census age / tasks-held, derived
            # by the same digest helper as the single-run --status line
            d = analytics_census_digest(read_metrics(ana_prom), runm)
            age = "?" if d["age"] is None else str(d["age"])
            extra += (f"  census u{d['update']} age {age}u "
                      f"depth {d['depth']} tasks {d['tasks_held']}")
        serve_json = os.path.join(spool, name, "data", "serve.json")
        if os.path.exists(serve_json):
            # a serve-class child: width/ghost occupancy + compile
            # count from its status file (parallel/multiworld.ServeBatch)
            try:
                with open(serve_json) as f:
                    sj = json.load(f)
                extra += (f"  serve w{sj.get('width')} "
                          f"live {sj.get('live')} "
                          f"ghosts {sj.get('ghosts')} "
                          f"compiles {sj.get('compiles')}")
            except (OSError, ValueError):
                pass
        members = riders.get(name, ())
        if members:
            extra = f"  (batch x{1 + len(members)}){extra}"
        lines.append(f"  {name:<24} {st}{extra}")
        if members:
            # one batched job = one row, its worlds as sub-rows (the
            # leader's own world first, then each rider's), each with
            # its straggler lag; batch efficiency on the leader row
            per, eff = world_rows(name)
            if eff is not None:
                lines[-1] += f"  efficiency {eff:.2f}"
            for wname in [name] + sorted(members):
                u, orgs, lag = per.get(wname, (None, None, 0.0))
                detail = ("(no per-world metrics yet)" if u is None
                          else f"u{u} organisms {orgs} lag {lag:.1f}u")
                role = "lead" if wname == name else "batched"
                lines.append(f"    - {wname:<20} {role}  {detail}")
    return "\n".join(lines) if lines else f"empty spool {spool!r}"


def fleet_status_main(spool: str, max_age: float | None = None) -> int:
    """The --status view for a spool dir: 0 = fleet metrics present
    (and fresh when --max-age is given), 1 = no fleet.prom, 2 = stale
    orchestrator heartbeat."""
    mpath = os.path.join(spool, FLEET_METRICS_FILE)
    if not os.path.exists(mpath):
        # journal-only view (orchestrator never ran / metrics removed):
        # still show the job table, but exit 1 so watchdogs see it
        print(format_fleet_status(spool))
        print(f"no {FLEET_METRICS_FILE} under {spool!r} (orchestrator "
              f"not started?)")
        return 1
    print(format_fleet_status(spool))
    if max_age is not None:
        hb = read_metrics(mpath).get(
            "avida_fleet_heartbeat_timestamp_seconds")
        age = None if hb is None else time.time() - hb
        if age is None or age > max_age:
            shown = "missing" if age is None else f"{age:.1f}s"
            print(f"STALE: orchestrator heartbeat {shown} exceeds "
                  f"--max-age {max_age}s")
            return 2
    return 0


# ---------------------------------------------------------------------------
# CLI entry (dispatched from avida_tpu/__main__.py before any jax import)
# ---------------------------------------------------------------------------

def fleet_main(argv: list) -> int:
    argv = list(argv)
    i = argv.index("--fleet")
    if i + 1 >= len(argv) or argv[i + 1].startswith("--"):
        print("--fleet needs a spool directory argument", file=sys.stderr)
        return 2
    spool = argv[i + 1]
    del argv[i:i + 2]
    cfg = FleetConfig.from_env(os.environ)
    if "--max-jobs" in argv:
        i = argv.index("--max-jobs")
        if i + 1 >= len(argv) or not argv[i + 1].isdigit():
            print("--max-jobs needs an integer argument", file=sys.stderr)
            return 2
        cfg.max_jobs = max(int(argv[i + 1]), 1)
        del argv[i:i + 2]
    if "--serve" in argv:
        cfg.serve = True
        argv.remove("--serve")
    if "--dynamic" in argv:
        cfg.dynamic = True
        argv.remove("--dynamic")
    if argv:
        print(f"unrecognized --fleet arguments: {argv}", file=sys.stderr)
        return 2
    return FleetOrchestrator(spool, cfg=cfg).run()
