"""Streaming serve layer: batchability classes + the warm-program pool.

The fleet orchestrator's device-lane packing (PR 10) coalesces a STATIC
spool: membership freezes at coalesce time, the eligibility key is
byte-equal seed-stripped argv, and every distinct batch shape pays a
fresh ~25s compile.  Production traffic is a STREAM -- arrivals,
cancels, completions -- so this module (host-only, never imports jax;
the same rule as the supervisor and the orchestrator) supplies the
three serving pieces ROADMAP item 2 names:

  1. **Batchability classes** -- `static_signature` resolves a job
     spec's argv the way the child CLI would (config files loaded,
     `-set` overrides applied, config-dir file contents fingerprinted)
     and hashes the RESOLVED static configuration with the
     non-static knobs (seed, output dirs, checkpoint dirs, verbosity,
     checkpoint cadence) stripped.  Two specs that differ only in
     spelling -- output dirs, `-s` position vs `-set RANDOM_SEED`,
     override order, defaults spelled out vs omitted -- land in ONE
     class, the way analyze/testcpu.py bucket-pads heterogeneous
     Test-CPU batches.  `service/fleet.spec_seed_and_batch_key` routes
     through this, so the PR-10 static coalescer inherits the wider
     classes too.
  2. **Width classes** -- batch width is padded to a small power-of-two
     set (`width_class`), so the compiled program's shapes survive
     membership churn; the padding slots ride as inert ghost worlds
     (parallel/multiworld.ServeBatch).
  3. **The warm pool** -- `ServePool` keeps one long-lived
     `--serve-worlds` child per (signature, padded width): an
     in-orchestrator program cache whose entries are warm PROCESSES.
     New arrivals route into a warm child's free ghost slot (cache hit:
     first executed update costs zero fresh compiles) instead of
     spawning a cold one (miss).  Warmth deliberately lives in process
     reuse, NOT in an on-disk XLA cache: JAX_COMPILATION_CACHE_DIR
     corrupts resumed runs on this toolchain (PR-6 finding, heap
     corruption observed; tests/test_chaos.py strips it).

Membership changes flow through each class child's `control.json`
(atomic rewrite; the child reconciles at checkpoint boundaries) and
come back through its `data/serve.json` status file.  Every transition
is journaled in the existing fleet.jsonl grammar -- `admit` for a class
leader, `coalesced` to place a member, `done`/`cancelled`/`requeued`
to settle one -- so journal replay after an orchestrator SIGKILL
resumes every tenant from its own per-world checkpoints with no new
record kinds.
"""

from __future__ import annotations

import hashlib
import json
import os

# config vars that do NOT change the compiled update program or the
# evolved trajectory of a tenant (seeds and output/checkpoint routing,
# cadence knobs the serve child overrides class-wide anyway): stripped
# before hashing so they cannot split a batchability class
NONSTATIC_VARS = frozenset((
    "RANDOM_SEED", "DATA_DIR", "VERBOSITY",
    "TPU_CKPT_DIR", "TPU_CKPT_EVERY", "TPU_CKPT_KEEP", "TPU_CKPT_FINAL",
    "TPU_CKPT_AUDIT", "TPU_METRICS", "TPU_SERVE_IDLE_SEC",
    "TPU_SERVE_POLL_SEC", "TPU_SERVE_WARM",
    # the persistent AOT program cache changes neither the compiled
    # program's semantics nor the trajectory (utils/compilecache.py) --
    # cache knobs must not split a batchability class
    "TPU_COMPILE_CACHE", "TPU_COMPILE_CACHE_DIR",
    # the integrity plane (digests + sampled shadow replay) is host-side
    # batch-level instrumentation: trajectories are bit-identical with
    # it on or off, so its knobs must not split a class either
    "TPU_STATE_DIGEST", "TPU_SCRUB_EVERY",
    # telemetry history rings (observability/history.py) are host-side
    # instrumentation too -- sampling cadence cannot split a class
    "TPU_METRICS_HIST", "TPU_METRICS_HIST_EVERY",
    "TPU_METRICS_HIST_MAX_BYTES",
    # the performance attribution plane (observability/profiler.py)
    # probes device-owned state COPIES only -- trajectories are
    # bit-identical with it on or off, so its knobs cannot split a
    # batchability class either
    "TPU_PROFILE", "TPU_PROFILE_EVERY", "TPU_PROFILE_TRACE",
))
# Reviewed and deliberately NOT listed: TPU_PACKED_CHUNK,
# TPU_PACKED_FUSED, TPU_PACKED_BITS.  They are program-affecting
# STATICS -- each selects a different compiled scan body / resident
# plane layout (WorldParams.packed_chunk/packed_fused/packed_bits are
# static fields; utils/compilecache.cache_key splits on them) -- so a
# batch must not mix values.  They stay in the signature and split
# batchability classes, exactly like TPU_USE_PALLAS.

# spec env vars that are per-job operational knobs, not program inputs
_NONSTATIC_ENV = frozenset((
    "TPU_WATCHDOG_SEC", "TPU_SUPERVISE_POLL_SEC", "TPU_SUPERVISE_GRACE_SEC",
    "TPU_SUPERVISE_MAX_RETRIES", "TPU_SUPERVISE_BACKOFF_BASE",
    "TPU_SUPERVISE_BACKOFF_CAP", "TPU_SUPERVISE_HEALTHY_SEC",
    "TPU_SUPERVISE_SEED", "TPU_PROGRESS_SEC",
    "TPU_COMPILE_CACHE", "TPU_COMPILE_CACHE_DIR",
    "TPU_METRICS_HIST", "TPU_METRICS_HIST_EVERY",
    "TPU_METRICS_HIST_MAX_BYTES", "TPU_ALERT_EVAL_SEC",
    "TPU_PROFILE", "TPU_PROFILE_EVERY", "TPU_PROFILE_TRACE",
))


class SpecArgv:
    """One parsed child argv: the pieces the serving layer routes on.
    THE one spelling of spec-argv analysis -- seed extraction for the
    worlds manifest, dir stripping for fault-domain safety, `-u`
    extraction for per-member budgets -- shared by the static coalescer
    (fleet._form_batches / _start_batch) and the serve pool."""

    def __init__(self, argv):
        self.config_dir = None
        self.sets = []                  # (-set NAME VALUE) pairs, in order
        self.residual = []              # tokens the serving layer keeps
        self.seed = None                # -s / --seed (beats -set RANDOM_SEED)
        self.set_seed = None
        self.updates = None             # -u / --updates
        self.data_dir = None
        argv = list(argv or ())
        i = 0
        while i < len(argv):
            a = argv[i]
            if a in ("-s", "--seed") and i + 1 < len(argv):
                self.seed = argv[i + 1]
                i += 2
            elif a in ("-d", "--data-dir") and i + 1 < len(argv):
                self.data_dir = argv[i + 1]
                i += 2
            elif a in ("-u", "--updates") and i + 1 < len(argv):
                self.updates = argv[i + 1]
                i += 2
            elif a in ("-c", "--config-dir") and i + 1 < len(argv):
                self.config_dir = argv[i + 1]
                i += 2
            elif a == "-set" and i + 2 < len(argv):
                self.sets.append((argv[i + 1], argv[i + 2]))
                i += 3
            else:
                self.residual.append(a)
                i += 1

    @property
    def effective_seed(self):
        """The seed the child would use: `-s` beats `-set RANDOM_SEED`
        regardless of argv position (the solo CLI appends -s AFTER
        every -set override; last one wins in the config)."""
        raw = self.seed
        if raw is None:
            for n, v in self.sets:
                if n == "RANDOM_SEED":
                    raw = v
        try:
            return int(raw) if raw is not None else None
        except (TypeError, ValueError):
            return None

    @property
    def max_updates(self):
        try:
            return int(self.updates) if self.updates is not None else None
        except ValueError:
            return None


def member_argv(spec) -> list:
    """A spec's argv with the per-member routing stripped (seed, data
    dir, checkpoint dir) -- what a `--worlds` / `--serve-worlds` class
    child is launched with (the worlds manifest / control file carries
    the per-member values).  `-u` is KEPT: the static coalescer runs
    one shared budget; the serve pool strips it separately via
    SpecArgv.max_updates into per-member budgets."""
    argv = list(spec.get("argv") or ())
    out = []
    i = 0
    while i < len(argv):
        a = argv[i]
        if a in ("-s", "--seed", "-d", "--data-dir") and i + 1 < len(argv):
            i += 2
            continue
        if a == "-set" and i + 2 < len(argv) \
                and argv[i + 1] in ("RANDOM_SEED", "TPU_CKPT_DIR"):
            i += 3
            continue
        out.append(a)
        i += 1
    return out


def _config_fingerprint(config_dir: str) -> object:
    """Content hash of every regular file in a spec's config dir: two
    specs naming different config dirs with IDENTICAL contents resolve
    to one class; editing any config file splits it.  Config dirs are a
    handful of small text files; unreadable entries hash by name."""
    if not config_dir:
        return None
    try:
        names = sorted(os.listdir(config_dir))
    except OSError:
        return f"unreadable:{os.path.realpath(config_dir)}"
    h = hashlib.sha1()
    for n in names:
        p = os.path.join(config_dir, n)
        if not os.path.isfile(p):
            continue
        h.update(n.encode())
        try:
            with open(p, "rb") as f:
                h.update(hashlib.sha1(f.read()).digest())
        except OSError:
            h.update(b"?")
    return h.hexdigest()


def static_signature(spec, with_updates: bool = True) -> str:
    """The canonical batchability-class key for one job spec: a digest
    of the RESOLVED static configuration.

    Resolution mirrors the child CLI: load `avida.cfg` from the spec's
    config dir (builtin defaults when absent), apply its `-set`
    overrides in order, then drop NONSTATIC_VARS (seed, dirs, cadence
    knobs).  The digest also covers the config-dir file contents (the
    instruction set / environment / events / ancestor files the
    resolved config names all live there), the residual argv tokens the
    parser didn't interpret (unknown flags must not falsely coalesce),
    and the spec's env minus per-job supervisor knobs.  `with_updates`
    keeps `-u` in the key (the static `--worlds` coalescer shares one
    budget); the serve pool passes False and carries per-member budgets
    in the control file.

    Falls back to a literal-argv key when resolution fails (unreadable
    config): degrading to PR-10's byte-equality is always safe."""
    from avida_tpu.config.schema import AvidaConfig, load_avida_cfg
    pa = SpecArgv(spec.get("argv"))
    env = tuple(sorted((k, v) for k, v in (spec.get("env") or {}).items()
                       if k not in _NONSTATIC_ENV))
    try:
        import warnings
        if pa.config_dir:
            cfg_path = os.path.join(pa.config_dir, "avida.cfg")
            with warnings.catch_warnings():
                warnings.simplefilter("ignore")
                if os.path.exists(cfg_path):
                    cfg = load_avida_cfg(cfg_path, pa.sets)
                else:
                    cfg = AvidaConfig()
                    for n, v in pa.sets:
                        cfg.set(n, v)
        else:
            cfg = AvidaConfig()
            for n, v in pa.sets:
                cfg.set(n, v)
        static = {n: getattr(cfg, n) for n in sorted(cfg.field_names())
                  if n not in NONSTATIC_VARS}
        static["extras"] = {k: v for k, v in sorted(cfg.extras.items())
                            if k not in NONSTATIC_VARS}
        body = {
            "static": static,
            "config_files": _config_fingerprint(pa.config_dir),
            "residual": list(pa.residual),
            "env": env,
        }
        if with_updates:
            body["updates"] = pa.updates
        text = json.dumps(body, sort_keys=True, default=str)
        return "sig:" + hashlib.sha1(text.encode()).hexdigest()
    except Exception:
        key = (tuple(member_argv(spec)), env,
               pa.updates if with_updates else None)
        return "raw:" + hashlib.sha1(repr(key).encode()).hexdigest()


def width_class(n: int, min_width: int, max_width: int) -> int:
    """The padded width for n tenants: the smallest power of two >=
    max(n, min_width), capped at the largest power of two <=
    max_width.  A small fixed set of widths = a small fixed set of
    compiled shapes, every one reusable across arbitrary churn."""
    cap = 1
    while cap * 2 <= max(int(max_width), 1):
        cap *= 2
    w = 1
    while w < max(int(n), int(min_width), 1):
        w *= 2
    return min(w, cap)


def batch_ineligible_reason(spec) -> str | None:
    """Host-side screen for workloads the batched drivers refuse
    (telemetry / tracing / analytics / device fault injection are
    per-run host pipelines).  None = may batch."""
    pa = SpecArgv(spec.get("argv"))
    flags = set(pa.residual)
    if "--telemetry" in flags or "--trace" in flags \
            or "--profile-dir" in flags:
        return "telemetry/trace workloads run solo"
    for n, v in pa.sets:
        if n in ("TPU_TELEMETRY", "TPU_TRACE", "TPU_ANALYTICS") \
                and str(v) not in ("0", "-", ""):
            return f"{n} workloads run solo"
        if n == "TPU_FAULT" and str(v) not in ("0", "-", ""):
            return "TPU_FAULT is per-process"
    return None


# ---------------------------------------------------------------------------
# the warm pool
# ---------------------------------------------------------------------------

class ServeClass:
    """One warm program-cache entry: a long-lived `--serve-worlds`
    child serving every tenant of one (signature, width) class."""

    def __init__(self, leader, sig: str, width: int):
        self.leader = leader            # the fleet Job running the child
        self.sig = sig
        self.width = width
        self.members: dict = {}         # name -> control entry
        self.shutdown_sent = False
        self.dirty = False              # members/control.json diverged
        #                                 (a write failed); poll retries

    @property
    def control_path(self) -> str:
        return os.path.join(self.leader.dir, "control.json")

    @property
    def status_path(self) -> str:
        return os.path.join(self.leader.dir, "data", "serve.json")

    def free_slots(self) -> int:
        return self.width - len(self.members)

    def write_control(self):
        # `sig` rides along so the child can stamp its batchability
        # class into the compile-cache entries it publishes (the
        # cache_tool listing's sig column; informational, not keyed)
        doc = {"width": self.width, "sig": self.sig,
               "shutdown": self.shutdown_sent,
               "members": sorted(self.members.values(),
                                 key=lambda e: e["name"])}
        tmp = f"{self.control_path}.tmp.{os.getpid()}"
        os.makedirs(self.leader.dir, exist_ok=True)
        with open(tmp, "w") as f:
            json.dump(doc, f, indent=1)
            f.write("\n")
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, self.control_path)
        self.dirty = False

    def read_status(self):
        try:
            with open(self.status_path) as f:
                return json.load(f)
        except (OSError, ValueError):
            return None


class ServePool:
    """The orchestrator's serving brain (TPU_FLEET_DYNAMIC / --dynamic):
    routes batchable arrivals into warm class children, spawns cold
    ones when no class fits, settles member outcomes from the children's
    status files, and journals everything in the fleet grammar.  Owned
    and driven by FleetOrchestrator; holds no threads and does no
    blocking work of its own."""

    def __init__(self, fleet):
        self.fleet = fleet
        self.classes: dict = {}         # leader name -> ServeClass
        self._seq = 0
        self.cache_hits = 0
        self.cache_misses = 0
        self.promotions = 0
        self.demotions = 0
        self._rebuilt = False

    # ---- restart recovery ----

    def rebuild(self):
        """Reattach classes after a journal replay: every non-terminal
        serve leader (job dir holding a control.json) gets its
        ServeClass back, and members its control still lists -- which
        replay parked back in the queue -- are re-marked batched so
        they are not double-admitted as solo runs."""
        if self._rebuilt:
            return
        self._rebuilt = True
        for name, job in list(self.fleet.jobs.items()):
            ctl_path = os.path.join(job.dir, "control.json")
            if job.state not in ("queued", "running") \
                    or not os.path.exists(ctl_path):
                continue
            try:
                with open(ctl_path) as f:
                    doc = json.load(f)
                width = int(doc.get("width", 0))
                entries = {str(e["name"]): e
                           for e in doc.get("members") or []
                           if isinstance(e, dict) and e.get("name")}
            except (OSError, ValueError):
                continue
            if width < 1:
                continue
            sig = (job.spec or {}).get("serve_sig") or \
                self._sig_from_job(job)
            cls = ServeClass(job, sig, width)
            self.classes[name] = cls
            for mname, entry in entries.items():
                m = self.fleet.jobs.get(mname)
                if m is None or m.state not in ("queued", "batched"):
                    continue
                cls.members[mname] = entry
                m.state = "batched"
                m.batch_leader = name
            self.fleet.journal("serve_reattach", job=name,
                               members=sorted(cls.members))

    def _sig_from_job(self, job) -> str:
        """A reattached leader's class signature.  The stored
        `serve_sig` is authoritative: the leader's own argv carries
        `--serve-worlds CONTROL` and has the member routing stripped,
        so re-hashing it would NEVER equal a member signature and every
        post-restart arrival would cold-spawn a duplicate class."""
        spec = self.fleet._load_spec(job) or {}
        sig = spec.get("serve_sig")
        return sig or static_signature(spec, with_updates=False)

    # ---- admission routing ----

    def offer(self, job, spec) -> bool:
        """Try to place one queued batchable spec into a warm class
        (cache hit).  Returns True when the job was promoted; False
        leaves it queued for _admit to group into a new class (or run
        solo)."""
        if job._serve_sig is None:
            job._serve_sig = static_signature(spec, with_updates=False)
        sig = job._serve_sig
        pa = SpecArgv(spec.get("argv"))
        seed = pa.effective_seed
        if seed is None:
            return False
        for cls in self.classes.values():
            if cls.sig != sig or cls.shutdown_sent:
                continue
            if cls.leader.state != "running" or cls.free_slots() < 1:
                continue
            if self._place(cls, job, seed, pa.max_updates, hit=True):
                self.cache_hits += 1
                return True
            return False                # quarantined: not placeable
        return False

    def spawn_class(self, group) -> bool:
        """Cold path: one admission slot becomes a new class child
        sized for the whole queued group [(job, spec)].  Members beyond
        the width cap stay queued for the next slot (or the next free
        ghost, once this child is warm)."""
        cfg = self.fleet.cfg
        job0, spec0 = group[0]
        sig = job0._serve_sig
        width = width_class(len(group), cfg.serve_min_width,
                            cfg.max_batch)
        self._seq += 1
        name = f"serve-{sig[4:12]}-w{width}-{self._seq}"
        while name in self.fleet.jobs:
            self._seq += 1
            name = f"serve-{sig[4:12]}-w{width}-{self._seq}"
        from avida_tpu.service.fleet import Job
        leader = Job(name, self.fleet.spool)
        cls = ServeClass(leader, sig, width)
        leader.spec = {
            "argv": member_argv(spec0) + ["--serve-worlds",
                                          cls.control_path],
            "env": dict(spec0.get("env") or {}),
            "serve_sig": sig,
        }
        try:
            os.makedirs(leader.dir, exist_ok=True)
            tmp = f"{leader.spec_path}.tmp.{os.getpid()}"
            with open(tmp, "w") as f:
                json.dump(leader.spec, f, indent=1)
                f.write("\n")
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, leader.spec_path)
            cls.write_control()
        except OSError as e:
            self.fleet.journal("batch_fallback", job=job0.name,
                               reason=f"serve class setup failed: {e}")
            return False
        self.fleet.jobs[name] = leader
        self.fleet.journal("admit", job=name)
        self.fleet.journal("serve_class", job=name, sig=sig,
                           width=width, group=len(group))
        if not self.fleet._start(leader):
            return False
        self.classes[name] = cls
        self.cache_misses += 1
        for job, spec in group[:width]:
            pa = SpecArgv(spec.get("argv"))
            self._place(cls, job, pa.effective_seed, pa.max_updates,
                        hit=False)
        return True

    def _place(self, cls: ServeClass, job, seed, max_updates,
               hit: bool) -> bool:
        if not self.fleet._admit_spec_move(job):
            return False                # quarantined by the move
        entry = {"name": job.name, "seed": seed,
                 "data_dir": job.data_dir, "ckpt_dir": job.ckpt_dir,
                 "max_updates": max_updates}
        cls.members[job.name] = entry
        try:
            cls.write_control()
        except OSError:
            cls.dirty = True            # poll() retries the rewrite
        job.state = "batched"
        job.batch_leader = cls.leader.name
        self.promotions += 1
        self.fleet.journal("coalesced", job=job.name,
                           leader=cls.leader.name, serve=True,
                           cache="hit" if hit else "miss")
        return True

    # ---- member lifecycle ----

    def cancel(self, job) -> bool:
        """Demote one serve member: drop it from the control (the child
        retires it with a final checkpoint at the next boundary) while
        its classmates keep running.  The terminal `cancelled` record
        lands at the poll that sees the child's status without it."""
        cls = self.classes.get(job.batch_leader or "")
        if cls is None or job.name not in cls.members:
            return False
        del cls.members[job.name]
        try:
            cls.write_control()
        except OSError:
            cls.dirty = True            # poll() retries the rewrite
        job.cancel_requested = True
        self.demotions += 1
        self.fleet.journal("cancel_requested", job=job.name,
                           batch_leader=cls.leader.name, serve=True)
        return True

    def poll(self):
        """Settle member outcomes from each class child's status file,
        dissolve classes whose leader ended, and ask idle classes to
        shut down when no more traffic can arrive for them."""
        self.rebuild()
        fleet = self.fleet
        for lname, cls in list(self.classes.items()):
            leader = cls.leader
            if leader.state in ("done", "failed", "cancelled",
                                "quarantined"):
                # class gone: iterate every job still POINTING at this
                # leader, not just cls.members -- a cancel-requested
                # member was already dropped from the control and would
                # otherwise be orphaned 'batched' forever (the settle
                # block below never runs for a dead leader).  Members
                # still riding resume elsewhere: their solo-format
                # checkpoints make requeue safe; cancelled members land
                # terminal here.
                for mname, m in sorted(fleet.jobs.items()):
                    if m.batch_leader != lname or m.state != "batched":
                        continue
                    m.batch_leader = None
                    if m.cancel_requested:
                        m.state = "cancelled"
                        fleet.journal("cancelled", job=mname)
                        continue
                    m.state = "queued"
                    m.sup = None
                    m._batch_progress = None
                    m._serve_sig = None
                    m._batch_key = None
                    fleet.journal("requeued", job=mname,
                                  reason="serve_leader_"
                                         + leader.state)
                del self.classes[lname]
                continue
            if cls.dirty and leader.state == "running":
                try:
                    cls.write_control()   # the deferred-rewrite retry
                except OSError:
                    pass
            status = cls.read_status() if leader.state == "running" \
                else None
            if status is not None:
                self._settle_members(cls, status)
            # cancelled members: terminal once the child no longer
            # serves them (status absent counts once the child has
            # reconciled -- or the leader is not even running)
            for mname in [n for n, j in fleet.jobs.items()
                          if j.batch_leader == lname
                          and j.cancel_requested
                          and j.state == "batched"]:
                served = status is not None and (
                    mname in (status.get("members") or {}))
                if not served and mname not in cls.members:
                    m = fleet.jobs[mname]
                    m.state = "cancelled"
                    m.batch_leader = None
                    fleet.journal("cancelled", job=mname)
            # idle eviction: nothing served, nothing queued that fits,
            # and the fleet is draining -> ask the child to exit so
            # run() can finish (a --serve fleet keeps classes warm)
            if not cls.members and not cls.shutdown_sent \
                    and not fleet.cfg.serve:
                # _serve_sig is only computed at admission, which runs
                # AFTER this poll in the tick -- a batch spec ingested
                # this very tick has sig None, and shutting the class
                # down on its account would cold-spawn a duplicate for
                # the exact late arrival the warm pool exists to serve.
                # Defer the eviction until every queued batch spec has
                # been signatured (next tick, after _admit).
                queued_same = any(
                    j.state == "queued"
                    and (j._serve_sig == cls.sig
                         or (j._serve_sig is None
                             and (fleet._load_spec(j) or {}).get("batch")))
                    for j in fleet.jobs.values())
                if not queued_same:
                    cls.shutdown_sent = True
                    try:
                        cls.write_control()
                    except OSError:
                        cls.shutdown_sent = False

    def _settle_members(self, cls: ServeClass, status: dict):
        fleet = self.fleet
        fin = status.get("finished") or {}
        for mname, rec in list(fin.items()):
            job = fleet.jobs.get(mname)
            if job is None or job.state != "batched" \
                    or job.batch_leader != cls.leader.name:
                continue
            st = rec.get("state")
            if st == "done":
                job.state = "done"
                job.batch_leader = None
                cls.members.pop(mname, None)
                fleet.journal("done", job=mname,
                              update=rec.get("update"),
                              serve_leader=cls.leader.name)
                try:
                    cls.write_control()   # the ack: child forgets it
                except OSError:
                    cls.dirty = True
            elif st == "sdc":
                # silent-corruption demotion (the integrity plane): the
                # serve child detected a scrub digest mismatch for this
                # tenant, quarantined its suspect generations and freed
                # the slot -- classmates kept serving.  Requeue the
                # member so the next placement readmits it (warm class
                # first), resuming from the newest digest-verified
                # generation: the tenant rolls back ALONE.  The sig is
                # kept -- same class, same warm child.
                job.state = "queued"
                job.batch_leader = None
                job.sup = None
                job._batch_progress = None   # rolled back: stale
                cls.members.pop(mname, None)
                fleet.journal("sdc", job=mname,
                              update=rec.get("update"),
                              last_verified_update=rec.get(
                                  "last_verified_update"),
                              quarantined=rec.get("quarantined"),
                              serve_leader=cls.leader.name)
                fleet.journal("requeued", job=mname, reason="serve_sdc")
                # the breaker counts sdc like any crash class: a sick
                # device demoting tenant after tenant pauses admissions
                fleet.note_external_failure("sdc", cls.leader)
                try:
                    cls.write_control()   # the ack: child forgets it
                except OSError:
                    cls.dirty = True
            elif st == "rejected":
                # static mismatch the host screen missed: back to the
                # queue as an ordinary solo run, loudly
                job.state = "queued"
                job.batch_leader = None
                job._serve_sig = None
                job._batch_key = None
                job.spec = dict(self.fleet._load_spec(job) or {})
                job.spec.pop("batch", None)
                # persist the strip: the on-disk spec still says
                # batch:true, and a restarted orchestrator re-reading
                # it would replay the whole place/reject/requeue round
                # on every boot for as long as the rejection holds
                try:
                    tmp = f"{job.spec_path}.tmp.{os.getpid()}"
                    with open(tmp, "w") as f:
                        json.dump(job.spec, f, indent=1)
                        f.write("\n")
                        f.flush()
                        os.fsync(f.fileno())
                    os.replace(tmp, job.spec_path)
                except OSError:
                    pass            # worst case: one wasted round
                cls.members.pop(mname, None)
                fleet.journal("batch_fallback", job=mname,
                              reason="serve child rejected: "
                                     + str(rec.get("reason")))
                fleet.journal("requeued", job=mname,
                              reason="serve_rejected")
                try:
                    cls.write_control()
                except OSError:
                    cls.dirty = True

    # ---- observability ----

    def gauges(self) -> list:
        members = sum(len(c.members) for c in self.classes.values())
        ghosts = sum(c.width - len(c.members)
                     for c in self.classes.values()
                     if c.leader.state == "running")
        return [
            ("avida_fleet_serve_classes", "gauge",
             "warm serve classes (one child each)", len(self.classes)),
            ("avida_fleet_serve_members", "gauge",
             "tenants riding serve classes", members),
            ("avida_fleet_serve_ghost_slots", "gauge",
             "free ghost slots across running classes (instant "
             "admission capacity)", ghosts),
            ("avida_fleet_serve_promotions_total", "counter",
             "tenants promoted into serve classes", self.promotions),
            ("avida_fleet_serve_demotions_total", "counter",
             "tenants demoted out of serve classes", self.demotions),
            ("avida_fleet_serve_cache_hits_total", "counter",
             "arrivals placed into an already-warm class",
             self.cache_hits),
            ("avida_fleet_serve_cache_misses_total", "counter",
             "arrivals that had to spawn a cold class child",
             self.cache_misses),
        ]
