"""Self-healing run supervisor: `python -m avida_tpu --supervise ...`.

The PR-4/PR-5 machinery made a single run crash-SAFE (bit-exact
checkpoints with CRC fallback, SIGTERM preemption, `--resume`, the
metrics.prom heartbeat); this module makes it crash-SURVIVING.  The
supervisor launches the world run as a child process and watches it
entirely from OUTSIDE -- it never imports jax, so a wedged device
runtime, an OOM-killed child or a corrupted interpreter state cannot
take the watchdog down with it:

  * liveness: the age of the `avida_heartbeat_timestamp_seconds` sample
    in DATA_DIR/metrics.prom (republished by the child at every chunk
    boundary).  Stale past TPU_WATCHDOG_SEC -> SIGKILL (a hung chunk
    ignores SIGTERM by definition).  A boot grace period
    (TPU_SUPERVISE_GRACE_SEC) covers jit compilation before the first
    heartbeat.
  * restart: exponential backoff with decorrelated jitter and a capped
    retry budget (service/backoff.py); the budget refills after
    TPU_SUPERVISE_HEALTHY_SEC of continuous health.  Every relaunch
    appends `--resume`, so recovery is bit-exact from the newest
    CRC-valid generation (utils/checkpoint.py).
  * failure taxonomy, recorded as {"record": "supervisor"} runlog lines
    in DATA_DIR/supervisor.jsonl and exported as Prometheus counters in
    DATA_DIR/supervisor.prom:

      crash            nonzero exit / signal death (incl. SIGKILL)
      hang             watchdog-killed stale heartbeat
      audit_violation  StateInvariantError (child exit EXIT_AUDIT) or a
                       flight-recorder anomaly onset seen in metrics
      corrupt_ckpt     resume found no valid generation (EXIT_CKPT), or
                       the child logged a checkpoint_corrupt fallback
      preempt          clean SIGTERM preemption (exit 0 + heartbeat
                       preempted=1): relaunched immediately, consuming
                       NO retry budget -- preemption is routine, the
                       Avida way (organism death is not an error)

  * recovery policies that close the loop with PR-4/PR-5:
      - audit_violation -> ROLLBACK: quarantine the newest checkpoint
        generation (renamed to `.bad-*`, invisible to resume) so the
        child restarts from the previous good one instead of replaying
        the corrupt state.
      - a crash whose stderr tail implicates the Pallas/Mosaic kernel
        path -> ONE graceful-degradation relaunch with
        `-set TPU_USE_PALLAS 2` (XLA path) and a loud runlog warning.

Fault injection for the chaos suite rides the same interface: the
supervisor's `fault_plan` hands boot i the i-th TPU_FAULT spec
(utils/faultinject.py) via the child environment and strips it from
every later boot, so an injected failure fires exactly once.

All timing dependencies (clock, sleep, process spawn) are injectable,
so the policy logic is unit-testable with a fake clock and fake
children -- no real sleeps, no real processes (tests/test_supervisor.py).
"""

from __future__ import annotations

import os
import re
import subprocess
import sys
import time

from avida_tpu.observability import alerts as alerts_mod
from avida_tpu.observability import history
from avida_tpu.observability.exporter import (METRICS_FILE,
                                              MULTIWORLD_METRICS_FILE,
                                              read_metrics,
                                              render_families, write_metrics)
from avida_tpu.observability.runlog import append_record
from avida_tpu.service import (EXIT_AUDIT, EXIT_CKPT, EXIT_SDC,
                               FAILURE_CLASSES)
from avida_tpu.service.backoff import RetryPolicy
from avida_tpu.utils.checkpoint import list_generations

RUNLOG_FILE = "supervisor.jsonl"
SUPERVISOR_METRICS_FILE = "supervisor.prom"

_PALLAS_RE = re.compile(r"pallas|mosaic", re.IGNORECASE)
_HEARTBEAT = "avida_heartbeat_timestamp_seconds"
_ANOM_RE = re.compile(r'^avida_trace_code_total\{code="anom_')


def classify(exit_code: int, *, watchdog_killed: bool = False,
             anomaly_killed: bool = False, preempted: bool = False) -> str:
    """Map one child exit to the failure taxonomy ('success' when the
    run completed).  Supervisor-initiated kills take precedence over the
    exit code they caused."""
    if watchdog_killed:
        return "hang"
    if anomaly_killed:
        return "audit_violation"
    if exit_code == 0:
        return "preempt" if preempted else "success"
    if exit_code == EXIT_AUDIT:
        return "audit_violation"
    if exit_code == EXIT_CKPT:
        return "corrupt_ckpt"
    if exit_code == EXIT_SDC:
        return "sdc"
    return "crash"


def pallas_suspect(stderr_tail: str) -> bool:
    """Does a crash's stderr implicate the Pallas/Mosaic kernel path?"""
    return bool(_PALLAS_RE.search(stderr_tail))


def _anomaly_total(metrics: dict) -> float:
    """Sum of the flight recorder's anom_* event counters in a parsed
    metrics.prom dict (0 when tracing is off)."""
    return sum(v for k, v in metrics.items() if _ANOM_RE.match(k))


class SupervisorConfig:
    """Knobs, all overridable via the environment (documented in the
    README's supervised-runs section)."""

    def __init__(self, watchdog_sec: float = 120.0,
                 poll_sec: float | None = None, grace_sec: float = 900.0,
                 max_retries: int = 8, backoff_base: float = 1.0,
                 backoff_cap: float = 60.0, healthy_sec: float = 300.0,
                 seed: int = 0, anomaly_watch: bool = True,
                 progress_sec: float = 0.0):
        self.watchdog_sec = float(watchdog_sec)
        self.poll_sec = (min(max(self.watchdog_sec / 8, 0.2), 5.0)
                         if poll_sec is None else float(poll_sec))
        self.grace_sec = float(grace_sec)
        self.max_retries = int(max_retries)
        self.backoff_base = float(backoff_base)
        self.backoff_cap = float(backoff_cap)
        self.healthy_sec = float(healthy_sec)
        self.seed = int(seed)
        self.anomaly_watch = bool(anomaly_watch)
        # livelock watchdog (default OFF): a child can wedge while still
        # republishing its heartbeat file -- with progress_sec > 0 the
        # watchdog also requires the avida_update counter to ADVANCE
        # within this window once heartbeats have started
        self.progress_sec = float(progress_sec)

    @classmethod
    def from_env(cls, env) -> "SupervisorConfig":
        def f(name, default):
            return float(env.get(name, default))
        return cls(
            watchdog_sec=f("TPU_WATCHDOG_SEC", 120.0),
            poll_sec=(float(env["TPU_SUPERVISE_POLL_SEC"])
                      if "TPU_SUPERVISE_POLL_SEC" in env else None),
            grace_sec=f("TPU_SUPERVISE_GRACE_SEC", 900.0),
            max_retries=int(f("TPU_SUPERVISE_MAX_RETRIES", 8)),
            backoff_base=f("TPU_SUPERVISE_BACKOFF_BASE", 1.0),
            backoff_cap=f("TPU_SUPERVISE_BACKOFF_CAP", 60.0),
            healthy_sec=f("TPU_SUPERVISE_HEALTHY_SEC", 300.0),
            seed=int(f("TPU_SUPERVISE_SEED", 0)),
            anomaly_watch=bool(int(f("TPU_SUPERVISE_ANOM", 1))),
            progress_sec=f("TPU_PROGRESS_SEC", 0.0),
        )


def _child_setting(argv: list, name: str):
    """The LAST `-set NAME VALUE` in a child argv (None when absent)."""
    val = None
    for i in range(len(argv) - 2):
        if argv[i] == "-set" and argv[i + 1] == name:
            val = argv[i + 2]
    return val


def _child_data_dir(argv: list):
    val = None
    for i, a in enumerate(argv):
        if a in ("-d", "--data-dir") and i + 1 < len(argv):
            val = argv[i + 1]
    return val


class Outcome:
    """One boot's result: classification + the evidence behind it."""

    def __init__(self, cls: str, exit_code, *, pallas: bool = False,
                 corrupt_seen: bool = False, update=None,
                 verified_update=None):
        self.cls = cls
        self.exit_code = exit_code
        self.pallas = pallas
        self.corrupt_seen = corrupt_seen
        self.update = update
        # newest scrub-verified update the child reported in its
        # divergence error (None when the tail carried no marker):
        # the sdc rollback's quarantine horizon
        self.verified_update = verified_update


# postmortem context: failure-class exit records carry this much of the
# child's log tail (bytes, utf-8) so the crash evidence survives log
# truncation/rotation alongside the taxonomy class
STDERR_TAIL_RECORD_BYTES = 2048


class _Boot:
    """Per-boot watch state for the non-blocking poll() machine: one
    instance lives from _launch() to _finish()."""

    __slots__ = ("proc", "logf", "log_start", "t0", "hb0",
                 "watchdog_killed", "anomaly_killed", "anom0",
                 "healthy_since", "term_deadline", "hb_max", "hb_fresh_t",
                 "prog_val", "prog_t")

    def __init__(self, proc, logf, log_start, t0, hb0):
        self.proc = proc
        self.logf = logf
        self.log_start = log_start
        self.t0 = t0
        self.hb0 = hb0
        self.watchdog_killed = False
        self.anomaly_killed = False
        self.anom0 = None
        self.healthy_since = None
        self.term_deadline = None       # set after a graceful SIGTERM
        self.hb_max = None              # newest heartbeat timestamp seen
        self.hb_fresh_t = None          # our clock at its last advance
        self.prog_val = None            # last avida_update counter value
        self.prog_t = None              # our clock at its last advance


class Supervisor:
    def __init__(self, child_argv, *, data_dir=None, ckpt_dir=None,
                 fault_plan=(), cfg: SupervisorConfig | None = None,
                 env=None, spawn=None, clock=time.time,
                 sleep=time.sleep):
        self.child_argv = list(child_argv)
        base_env = dict(os.environ if env is None else env)
        self.cfg = cfg or SupervisorConfig.from_env(base_env)
        self.data_dir = data_dir or _child_data_dir(self.child_argv)
        self.ckpt_dir = ckpt_dir or _child_setting(self.child_argv,
                                                   "TPU_CKPT_DIR")
        if not self.data_dir:
            raise ValueError("--supervise needs the child's data dir "
                             "(-d DIR) to read its heartbeat")
        if not self.ckpt_dir:
            raise ValueError("--supervise needs -set TPU_CKPT_DIR DIR in "
                             "the child args (restart recovery resumes "
                             "from native checkpoints)")
        if _child_setting(self.child_argv, "TPU_FAULT") is not None:
            raise ValueError("pass injected faults via --fault-plan, not "
                             "-set TPU_FAULT (a fault baked into the child "
                             "args would re-fire on every restart)")
        # the heartbeat is the watchdog's only liveness signal -- force
        # the exporter on (idempotent when the user already set it) and
        # refuse an explicit opt-out, which would reduce every healthy
        # boot to a grace-period timeout kill
        metrics_set = _child_setting(self.child_argv, "TPU_METRICS")
        if metrics_set is not None and not int(metrics_set):
            raise ValueError("-set TPU_METRICS 0 disables the heartbeat "
                             "the supervisor's watchdog lives on; drop it "
                             "(supervised children always export metrics)")
        if metrics_set is None and "--trace" not in self.child_argv:
            self.child_argv += ["-set", "TPU_METRICS", "1"]
        if "--resume" not in self.child_argv:
            self.child_argv.append("--resume")
        self.fault_plan = list(fault_plan)
        self._base_env = base_env
        self._base_env.pop("TPU_FAULT", None)
        self.policy = RetryPolicy(
            max_retries=self.cfg.max_retries, base=self.cfg.backoff_base,
            cap=self.cfg.backoff_cap, healthy_sec=self.cfg.healthy_sec,
            seed=self.cfg.seed)
        self._spawn = spawn or self._spawn_default
        self._clock = clock
        self._sleep = sleep
        self.boots = 0
        self.restarts = 0
        self.failures = {c: 0 for c in FAILURE_CLASSES}
        self.watchdog_kills = 0
        self.rollbacks = 0
        self.pallas_fallbacks = 0
        self.ckpt_fallbacks = 0
        self.last_exit_code = 0
        self._xla_fallback = False
        self._proc = None
        self._stop = False
        self._corrupt_counted = set()   # generation paths already tallied
        # ---- poll() state machine ----
        # "idle" (next poll launches) -> "running" -> "backoff" -> ... ->
        # "done" (exit_rc 0) | "failed" (exit_rc 1).  The blocking run()
        # is a thin sleep-between-polls wrapper; a fleet orchestrator
        # (service/fleet.py) multiplexes many supervisors by calling
        # poll() round-robin instead.
        self.state = "idle"
        self.exit_rc = None
        self.succeeded = False          # True only after a "done" record
        self.last_outcome = None        # newest Outcome (fleet breaker)
        self._ctx = None                # _Boot while state == "running"
        self._backoff_until = 0.0
        self.runlog_path = os.path.join(self.data_dir, RUNLOG_FILE)
        self.metrics_path = os.path.join(self.data_dir,
                                         SUPERVISOR_METRICS_FILE)
        self.child_log_path = os.path.join(self.data_dir, "supervised.log")
        # size-capped rotation (runlog.append_record): a long heal loop
        # must not grow supervisor.jsonl without bound
        self.runlog_max_bytes = int(
            self._base_env.get("TPU_RUNLOG_MAX_BYTES", 16 << 20))
        # alert plane (observability/alerts.py): the poll loop -- which
        # already reads the child's heartbeat -- additionally evaluates
        # the declarative rule set over the history rings beside it.
        # Firing/resolving edges journal to DATA_DIR/alerts.jsonl and
        # export on supervisor.prom; detection only, the watchdog stays
        # the sole kill authority.  TPU_ALERT_EVAL_SEC=0 disables.
        self.alert_eval_sec = float(
            self._base_env.get("TPU_ALERT_EVAL_SEC", 5.0))
        self.alerts = None
        if self.alert_eval_sec > 0:
            try:
                self.alerts = alerts_mod.AlertPlane(
                    alerts_mod.load_rules(self.data_dir),
                    journal_path=os.path.join(self.data_dir,
                                              alerts_mod.ALERTS_FILE),
                    max_bytes=self.runlog_max_bytes)
            except (OSError, ValueError) as e:
                # a malformed alerts.json must be loud but must not
                # take supervision down with it
                print(f"[supervisor] alert rules disabled: {e}",
                      file=sys.stderr)
        self._alerts_next = 0.0
        self._hist = history.HistorySink(self.metrics_path,
                                         env=self._base_env)

    # ---- plumbing ----

    @staticmethod
    def _spawn_default(argv, env, log_file):
        return subprocess.Popen(argv, env=env, stdout=log_file,
                                stderr=log_file)

    def record(self, event: str, **fields):
        rec = {"record": "supervisor", "event": event,
               "time": self._clock(), "boot": self.boots, **fields}
        try:
            append_record(self.runlog_path, rec,
                          max_bytes=self.runlog_max_bytes)
        except OSError:
            pass                        # logging must not kill recovery
        detail = " ".join(f"{k}={v}" for k, v in fields.items())
        print(f"[supervisor] {event}" + (f": {detail}" if detail else ""),
              file=sys.stderr)
        self.publish_metrics(child_up=self._proc is not None
                             and self._proc.poll() is None)

    def publish_metrics(self, child_up: bool = False):
        fams = [
            ("avida_supervisor_boots_total", "counter",
             "child launches (first boot + every restart)", self.boots),
            ("avida_supervisor_restarts_total", "counter",
             "relaunches after a failure or preemption", self.restarts),
            ("avida_supervisor_failures_total", "counter",
             "classified child failures",
             {f'class="{c}"': n for c, n in self.failures.items()}),
            ("avida_supervisor_watchdog_kills_total", "counter",
             "children SIGKILLed for a stale heartbeat",
             self.watchdog_kills),
            ("avida_supervisor_rollbacks_total", "counter",
             "newest-generation quarantines after audit violations",
             self.rollbacks),
            ("avida_supervisor_pallas_fallbacks_total", "counter",
             "graceful degradations to the XLA path",
             self.pallas_fallbacks),
            ("avida_supervisor_ckpt_fallbacks_total", "counter",
             "corrupt-checkpoint fallbacks observed in child logs",
             self.ckpt_fallbacks),
            ("avida_supervisor_retry_budget", "gauge",
             "failures left before the supervisor gives up",
             self.policy.budget_left()),
            ("avida_supervisor_child_up", "gauge",
             "1 while a child process is running", int(child_up)),
            ("avida_supervisor_last_exit_code", "gauge",
             "the previous child's exit code (negative = signal)",
             self.last_exit_code),
        ]
        if self.alerts is not None:
            fams += self.alerts.families()
        try:
            text = render_families(fams)
            write_metrics(self.metrics_path, text, durable=False)
            self._hist.publish(text)
        except OSError:
            pass

    def _eval_alerts(self):
        """Evaluate the alert rules over the child's history rings, at
        most every alert_eval_sec.  Runs while a child is alive or
        backing off -- a hung or backing-off child keeps its
        staleness/stall alerts honest -- but NOT in the idle state
        (nothing has launched yet; a resume's leftover ring from the
        previous incarnation is evidence of the past, not of a child
        that does not exist), and not against a ring that predates the
        current boot (the compile window of a resumed run would
        otherwise page `stall` on the old incarnation's final samples;
        alert state is FROZEN, not resolved, while evaluation is
        paused, so an alert that fired before a restart stays firing
        until post-launch samples clear it)."""
        if self.alerts is None or self.state == "idle":
            return
        now = self._clock()
        if now < self._alerts_next:
            return
        self._alerts_next = now + self.alert_eval_sec
        # rings are handed to the evaluator SEPARATELY (never merged):
        # on a serve child metrics.prom carries the batch-max counter
        # while multiworld.prom carries per-tenant rows -- one family,
        # two meanings (alerts.samples_for)
        samples = {
            "metrics": history.read_samples(
                history.hist_path(os.path.join(self.data_dir,
                                               METRICS_FILE)),
                tail_bytes=256 << 10),
            "multiworld": history.read_samples(
                history.hist_path(os.path.join(
                    self.data_dir, MULTIWORLD_METRICS_FILE)),
                tail_bytes=256 << 10),
        }
        if self.state == "running" and self._ctx is not None:
            newest = max((s.get("time", 0.0) for rows in samples.values()
                          for s in rows), default=None)
            if newest is not None and newest < self._ctx.t0:
                return          # previous incarnation's ring (see above)
        transitions = self.alerts.observe(samples, now)
        for name, state, res in transitions:
            val = res.get("value")
            print(f"[supervisor] alert {name} {state}"
                  + (f" (value {val})" if val is not None else ""),
                  file=sys.stderr)
        if transitions:
            self.publish_metrics(child_up=self._proc is not None
                                 and self._proc.poll() is None)

    def _read_heartbeat(self):
        path = os.path.join(self.data_dir, METRICS_FILE)
        try:
            return read_metrics(path)
        except OSError:
            return None

    def _effective_child_argv(self) -> list:
        argv = list(self.child_argv)
        if self._xla_fallback:
            argv += ["-set", "TPU_USE_PALLAS", "2"]
        return argv

    def _stderr_tail(self, start: int = 0, nbytes: int = 8192) -> str:
        """The current boot's log HEAD + TAIL: the head (right after
        `start`, the log offset at launch) holds the resume-time markers
        (checkpoint_corrupt fallbacks fire before the first update), the
        tail holds the death traceback.  Never reads before `start`, so
        one boot's failure markers cannot be re-classified against a
        later boot; a long-lived chatty child cannot push the head
        markers out of the classification window."""
        try:
            with open(self.child_log_path, "rb") as f:
                f.seek(0, os.SEEK_END)
                size = f.tell()
                f.seek(start)
                head = f.read(min(2 * nbytes, size - start))
                tail_from = max(size - nbytes, start + len(head))
                tail = b""
                if tail_from < size:
                    f.seek(tail_from)
                    tail = f.read()
                return (head + b"\n...\n" + tail if tail
                        else head).decode("utf-8", "replace")
        except OSError:
            return ""

    def _kill_child(self, proc):
        try:
            proc.kill()
        except OSError:
            pass
        return proc.wait()

    # ---- one boot, decomposed for the poll() machine ----

    def _launch(self):
        boot = self.boots
        self.boots += 1
        fault = self.fault_plan[boot] if boot < len(self.fault_plan) else None
        env = dict(self._base_env)
        if fault:
            env["TPU_FAULT"] = fault
        # the child must import avida_tpu the same way we did
        repo = os.path.dirname(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))))
        env["PYTHONPATH"] = repo + (
            os.pathsep + env["PYTHONPATH"] if env.get("PYTHONPATH") else "")
        argv = [sys.executable, "-m", "avida_tpu"] \
            + self._effective_child_argv()
        self.record("launch", fault=fault or "",
                    xla_fallback=self._xla_fallback)

        os.makedirs(self.data_dir, exist_ok=True)
        # a restarted child inherits the PREVIOUS boot's metrics.prom --
        # its heartbeat is stale by construction until the child's first
        # own export, so liveness only switches from the boot-grace
        # clock to the heartbeat clock once the timestamp ADVANCES
        hb0 = (self._read_heartbeat() or {}).get(_HEARTBEAT)
        logf = open(self.child_log_path, "a")
        try:
            logf.write(f"--- supervisor boot {boot} ---\n")
            logf.flush()
            log_start = logf.tell()
            proc = self._spawn(argv, env, logf)
        except BaseException:
            logf.close()
            raise
        self._proc = proc
        self._ctx = _Boot(proc, logf, log_start, self._clock(), hb0)
        self.state = "running"

    def _watch(self):
        """One non-blocking watch step: poll the child, enforce the
        liveness/anomaly policies.  Returns the exit code once the boot
        is over (child exited or was killed), None while it runs."""
        ctx = self._ctx
        proc = ctx.proc
        rc = proc.poll()
        if rc is not None:
            return rc
        now = self._clock()
        if ctx.term_deadline is not None:
            # graceful anomaly stop in flight: the child got SIGTERM and
            # is writing its final checkpoint -- only the kill deadline
            # still applies
            if now > ctx.term_deadline:
                return self._kill_child(proc)
            return None
        metrics = self._read_heartbeat()
        hb = None if metrics is None else metrics.get(_HEARTBEAT)
        if hb is None or (ctx.hb0 is not None and hb <= ctx.hb0):
            if now - ctx.t0 > self.cfg.grace_sec:
                self.record("watchdog_kill", reason="no heartbeat",
                            grace_sec=self.cfg.grace_sec)
                ctx.watchdog_killed = True
                return self._kill_child(proc)
            return None
        if ctx.hb_max is not None and hb < ctx.hb_max:
            # the heartbeat timestamp moved BACKWARDS (a stepped host
            # clock): that is never evidence of freshness -- measure
            # staleness from OUR clock at the last true advance
            if now - ctx.hb_fresh_t > self.cfg.watchdog_sec:
                self.record("watchdog_kill",
                            reason="heartbeat moved backwards",
                            last_advance_sec=round(now - ctx.hb_fresh_t, 3),
                            watchdog_sec=self.cfg.watchdog_sec)
                ctx.watchdog_killed = True
                return self._kill_child(proc)
            return None
        if ctx.hb_max is None or hb > ctx.hb_max:
            ctx.hb_max = hb
            ctx.hb_fresh_t = now
        age = now - hb
        if age > self.cfg.watchdog_sec:
            self.record("watchdog_kill", reason="stale heartbeat",
                        age_sec=round(age, 3),
                        watchdog_sec=self.cfg.watchdog_sec)
            ctx.watchdog_killed = True
            return self._kill_child(proc)
        if self.cfg.progress_sec > 0:
            # livelock watchdog: fresh heartbeats whose update counter
            # never advances are a wedged scheduler, not a live run
            upd = metrics.get("avida_update")
            if ctx.prog_val is None or (upd is not None
                                        and upd > ctx.prog_val):
                ctx.prog_val = upd
                ctx.prog_t = now
            elif now - ctx.prog_t > self.cfg.progress_sec:
                self.record("watchdog_kill", reason="no progress",
                            update=ctx.prog_val,
                            progress_sec=self.cfg.progress_sec)
                ctx.watchdog_killed = True
                return self._kill_child(proc)
        if self.cfg.anomaly_watch:
            anom = _anomaly_total(metrics)
            if ctx.anom0 is None:
                ctx.anom0 = anom
            elif anom > ctx.anom0:
                # flight-recorder anomaly onset: stop the run
                # GRACEFULLY (SIGTERM -> final checkpoint) and
                # roll back -- by the time a NaN shows up in
                # the trace it is already in the state
                self.record("anomaly_detected", anomalies=anom - ctx.anom0)
                try:
                    proc.terminate()
                except OSError:
                    pass
                ctx.anomaly_killed = True
                ctx.term_deadline = now + max(self.cfg.watchdog_sec, 30)
                return proc.poll()
        if ctx.healthy_since is None:
            ctx.healthy_since = now
        elif self.policy.note_healthy(now - ctx.healthy_since):
            self.record("budget_reset",
                        healthy_sec=round(now - ctx.healthy_since, 1))
            ctx.healthy_since = now
        return None

    def _finish(self, rc) -> Outcome:
        ctx, self._ctx = self._ctx, None
        try:
            ctx.logf.close()
        except OSError:
            pass
        self._proc = None
        self.last_exit_code = rc

        tail = self._stderr_tail(start=ctx.log_start)
        metrics = self._read_heartbeat() or {}
        preempted = bool(metrics.get("avida_preempted", 0)) \
            or "] preempted at update" in tail
        cls = classify(rc, watchdog_killed=ctx.watchdog_killed,
                       anomaly_killed=ctx.anomaly_killed,
                       preempted=preempted)
        if ctx.watchdog_killed:
            self.watchdog_kills += 1
        # CRC/manifest fallbacks the child logged at resume time: count
        # each corrupt GENERATION once, not once per boot -- the corrupt
        # generation stays on disk after fallback, so every later resume
        # re-logs the same path and would otherwise inflate the counter
        corrupt_paths = set(
            re.findall(r"checkpoint_corrupt: path=(\S+)", tail))
        new_corrupt = corrupt_paths - self._corrupt_counted
        self._corrupt_counted |= new_corrupt
        verified = None
        if cls == "sdc":
            # the divergence error names the newest scrub-verified
            # update -- everything saved past it is suspect
            m = re.search(r"last_verified_update=(\d+)", tail)
            verified = int(m.group(1)) if m else None
        out = Outcome(cls, rc,
                      # an sdc whose divergence error names a Pallas
                      # engine is kernel-implicated like a Pallas crash:
                      # it earns the same one-shot XLA degradation
                      pallas=(cls in ("crash", "sdc")
                              and pallas_suspect(tail)),
                      corrupt_seen=bool(new_corrupt),
                      update=metrics.get("avida_update"),
                      verified_update=verified)
        if new_corrupt:
            # the child survived via CRC fallback -- record the class
            # even though this boot may otherwise have succeeded
            self.ckpt_fallbacks += len(new_corrupt)
            self.failures["corrupt_ckpt"] += len(new_corrupt)
            self.record("checkpoint_fallback_observed",
                        paths=sorted(new_corrupt))
        if cls in self.failures and not (cls == "corrupt_ckpt"
                                         and out.corrupt_seen):
            self.failures[cls] += 1
        exit_fields = {"class": cls, "code": rc, "update": out.update,
                       "pallas_suspect": out.pallas}
        if cls in FAILURE_CLASSES and cls != "preempt":
            # postmortem context rides the taxonomy record: the tail end
            # of this boot's log (bounded, so a heal loop cannot bloat
            # the runlog), where the death traceback lands
            exit_fields["stderr_tail"] = tail.encode(
                "utf-8", "replace")[-STDERR_TAIL_RECORD_BYTES:].decode(
                "utf-8", "replace")
        self.record("exit", **exit_fields)
        self.last_outcome = out
        return out

    def _dispatch(self, out: Outcome):
        """Recovery policy: decide the next state from one boot's
        outcome.  Exactly the decision ladder the blocking loop ran --
        relaunch-now paths (preempt, the one free Pallas->XLA
        degradation) launch inline so run() behavior is unchanged."""
        if out.cls == "success":
            self.record("done", update=out.update)
            self.succeeded = True
            self._terminal("done", 0)
            return
        if self._stop:
            # our own SIGTERM, forwarded: the child saved its
            # preemption checkpoint; leave cleanly so the next
            # supervisor invocation resumes bit-exactly
            self.record("supervisor_preempted", update=out.update)
            self._terminal("done", 0)
            return
        if out.cls == "preempt":
            self.restarts += 1
            self.record("restart", reason="preempt")
            self._launch()
            return
        if out.cls == "audit_violation":
            self._rollback()
        if out.cls == "sdc":
            self._sdc_rollback(out.verified_update)
        if out.pallas and not self._xla_fallback:
            # graceful degradation: one free retry on the XLA
            # path with a LOUD warning -- slower, but alive
            self._xla_fallback = True
            self.pallas_fallbacks += 1
            self.restarts += 1
            self.record(
                "pallas_fallback",
                detail="kernel-path failure: retrying on the XLA "
                       "path (-set TPU_USE_PALLAS 2); expect "
                       "reduced throughput")
            self._launch()
            return
        if not self.policy.can_retry():
            self.record("giving_up", failures=dict(self.failures),
                        max_retries=self.cfg.max_retries)
            self._terminal("failed", 1)
            return
        delay = self.policy.next_delay()
        self.restarts += 1
        self.record("backoff", delay_sec=round(delay, 3),
                    budget_left=self.policy.budget_left())
        self._backoff_until = self._clock() + delay
        self.state = "backoff"

    def _terminal(self, state: str, rc: int):
        self.state = state
        self.exit_rc = rc
        # final alert sweep, throttle bypassed: the child's last
        # durable export is on disk BEFORE its exit is observable, so
        # evaluating here deterministically resolves a stall/staleness
        # alert the recovery cleared -- without it, a child that exits
        # within one alert_eval_sec of resolving leaves the journal
        # (and avida_alerts_firing) claiming a live alert forever.
        # Only once a boot actually ran: a supervisor preempted before
        # its first launch has no child evidence to sweep
        if self.alerts is not None and self.boots > 0:
            self._alerts_next = 0.0
            self._eval_alerts()

    # ---- the non-blocking interface (one supervisor among many) ----

    def poll(self) -> str:
        """Advance the supervision state machine one non-blocking step
        and return the current state ("idle"/"running"/"backoff" are
        live, "done"/"failed" terminal with the exit code in
        `exit_rc`).  Never sleeps: callers own the pacing -- run()
        sleeps poll_sec between steps, a fleet orchestrator
        (service/fleet.py) round-robins many supervisors through one
        loop."""
        if self.state in ("done", "failed"):
            return self.state
        self._eval_alerts()
        if self.state == "idle":
            if self._stop:
                # preempted before the first boot: exit NOW -- launching
                # a boot would outlive the cluster's grace window
                self.record("supervisor_preempted")
                self._terminal("done", 0)
            else:
                self._launch()
            return self.state
        if self.state == "backoff":
            if self._stop:
                # preempted while no child was alive (mid-backoff)
                self.record("supervisor_preempted")
                self._terminal("done", 0)
            elif self._clock() >= self._backoff_until:
                self._launch()
            return self.state
        rc = self._watch()
        if rc is None:
            return self.state
        self._dispatch(self._finish(rc))
        return self.state

    def request_stop(self):
        """Graceful drain (the fleet's SIGTERM forwarding): exactly what
        the supervisor's own signal handler does -- flag the stop and
        SIGTERM the live child so it writes a preemption checkpoint."""
        import signal as _signal
        self._stop = True
        proc = self._proc
        if proc is not None:
            try:
                proc.send_signal(_signal.SIGTERM)
            except OSError:
                pass

    # ---- recovery policies ----

    def _rollback(self):
        """Audit violation: quarantine the newest generation so --resume
        restores the previous good one.  The rename prefix `.bad-` is
        invisible to list_generations/restore_candidates; `ckpt_tool.py
        --prune` sweeps quarantined generations later.  With fewer than
        two generations there is nothing to fall back to -- leave the
        only (audited-at-save, so good) checkpoint in place."""
        gens = list_generations(self.ckpt_dir)
        if len(gens) < 2:
            self.record("rollback_skipped",
                        reason=f"{len(gens)} generation(s) on disk")
            return
        newest = gens[-1]
        dst = os.path.join(
            os.path.dirname(newest),
            f".bad-{os.path.basename(newest)}.{int(self._clock())}")
        try:
            os.rename(newest, dst)
        except OSError as e:
            self.record("rollback_failed", error=str(e))
            return
        self.rollbacks += 1
        self.record("rollback", quarantined=newest,
                    resumed_from=os.path.basename(gens[-2]))

    def _ckpt_dirs(self) -> list:
        """The checkpoint dirs this child writes: the configured dir
        itself when it holds generations, else any immediate per-world
        subdirs that do (a --worlds batched child keeps one dir per
        member under the root TPU_CKPT_DIR)."""
        if list_generations(self.ckpt_dir):
            return [self.ckpt_dir]
        try:
            subs = sorted(os.path.join(self.ckpt_dir, d)
                          for d in os.listdir(self.ckpt_dir)
                          if os.path.isdir(os.path.join(self.ckpt_dir, d)))
        except OSError:
            return [self.ckpt_dir]
        return [d for d in subs if list_generations(d)] or [self.ckpt_dir]

    def _sdc_rollback(self, verified_update):
        """Silent-data-corruption recovery (child exit EXIT_SDC): the
        scrub caught a divergence, so state saved since the last
        verified update may embed the corruption -- WITH a
        self-consistent manifest digest (the digest was computed from
        the already-corrupt state), which is why recency alone cannot
        be trusted.  Two passes per checkpoint dir:

          1. quarantine every generation saved PAST the child's
             reported verified horizon (suspect by timing);
          2. digest-verify what remains newest-first (recompute from
             the .npy leaves vs the manifest's state_digest --
             utils/integrity.py, numpy only, no jax) and quarantine
             mismatches until a verified generation is newest.

        With no horizon marker in the child's tail, fall back to the
        audit-violation policy: quarantine the newest generation."""
        from avida_tpu.utils import integrity
        if verified_update is None:
            self._rollback()
            return
        quarantined = []
        for base in self._ckpt_dirs():
            from avida_tpu.utils.checkpoint import quarantine_after
            quarantined += quarantine_after(base, verified_update)
            for gen in reversed(list_generations(base)):
                if len(list_generations(base)) < 2:
                    break       # never strand the run without a resume
                try:
                    stored, recomputed = integrity.generation_digest(gen)
                except (OSError, ValueError, KeyError):
                    continue    # torn/verifying is the CRC path's job
                if stored is None or stored == recomputed:
                    break       # newest surviving generation verifies
                dst = os.path.join(
                    base, f".bad-{os.path.basename(gen)}."
                          f"{int(self._clock())}")
                try:
                    os.rename(gen, dst)
                    quarantined.append(gen)
                    self.record("sdc_digest_quarantine", path=gen,
                                stored=f"{stored:#010x}",
                                recomputed=f"{recomputed:#010x}")
                except OSError:
                    break
        if quarantined:
            self.rollbacks += 1
            self.record("sdc_rollback",
                        verified_update=verified_update,
                        quarantined=[os.path.basename(p)
                                     for p in quarantined],
                        resumable={base: [os.path.basename(g) for g in
                                          list_generations(base)[-1:]]
                                   for base in self._ckpt_dirs()})
        else:
            self.record("sdc_rollback_noop",
                        verified_update=verified_update,
                        detail="no generation postdates the verified "
                               "horizon; resume replays from the "
                               "newest retained generation")

    # ---- the supervision loop ----

    def _install_signal_forwarding(self):
        import signal as _signal
        saved = {}

        def forward(signum, frame):
            self.request_stop()

        for s in (_signal.SIGTERM, _signal.SIGINT):
            try:
                saved[s] = _signal.signal(s, forward)
            except ValueError:
                pass
        return saved

    def run(self) -> int:
        """Supervise to completion (the blocking `--supervise` entry, a
        thin sleep-between-polls wrapper over the poll() machine).
        Returns 0 on run success (or when the supervisor itself was
        preempted after a clean child checkpoint), 1 when the retry
        budget is exhausted."""
        import signal as _signal
        saved = self._install_signal_forwarding()
        self.publish_metrics()
        try:
            while True:
                state = self.poll()
                if state in ("done", "failed"):
                    return self.exit_rc
                if state == "running":
                    self._sleep(self.cfg.poll_sec)
                elif state == "backoff":
                    # chunked so a SIGTERM mid-backoff is honored within
                    # a second, not after the full (up to backoff_cap)
                    # sleep
                    remaining = self._backoff_until - self._clock()
                    if remaining > 0 and not self._stop:
                        self._sleep(min(remaining, 0.5))
        finally:
            for s, h in saved.items():
                try:
                    _signal.signal(s, h)
                except (ValueError, OSError):
                    pass
            self.publish_metrics()


def supervise_main(argv: list) -> int:
    """CLI entry (dispatched from avida_tpu/__main__.py before any jax
    import): strip the supervisor's own flags, everything else is the
    child command line."""
    argv = list(argv)
    argv.remove("--supervise")
    fault_plan = ()
    if "--fault-plan" in argv:
        i = argv.index("--fault-plan")
        if i + 1 >= len(argv):
            print("--fault-plan needs an argument "
                  "(per-boot TPU_FAULT specs separated by '/')",
                  file=sys.stderr)
            return 2
        fault_plan = tuple(argv[i + 1].split("/"))
        del argv[i:i + 2]
    try:
        sup = Supervisor(argv, fault_plan=fault_plan)
    except ValueError as e:
        print(f"[supervisor] {e}", file=sys.stderr)
        return 2
    return sup.run()
