from avida_tpu.systematics.genotypes import GenotypeArbiter, Genotype  # noqa: F401
