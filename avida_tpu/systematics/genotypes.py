"""Live phylogeny: genotype dedup, parent links, depth, extinction.

Host-side re-expression of the reference's systematics layer
(Systematics::GenotypeArbiter, avida-core/source/systematics/
GenotypeArbiter.cc:79 ClassifyNewUnit; active-genotype hash :89-96;
threshold/coalescence bookkeeping; LegacySave :123).  The device never
blocks on this: each update the world hands over only the *newborn* rows
(a small gather keyed on birth_update == current update) and the host does
all bookkeeping -- the provenance layer rides the update stream instead of
sitting inside the hot loop.

Deviation from the reference (documented): classification happens at
update granularity, not at the instant of birth.  Within one lockstep
update every newborn sees its parent's genotype as of the update start,
which is exactly the information order the flush-births scatter defines.
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np


@dataclass
class Genotype:
    """One distinct genome (ref Systematics::Genotype, systematics/Genotype.h)."""
    gid: int
    sequence: np.ndarray          # int8[len]
    parent_gid: int               # -1 for injected ancestors
    depth: int                    # phylogenetic depth (parent.depth + 1)
    update_born: int
    num_units: int = 0            # live organisms with this genome
    total_units: int = 0          # ever born
    last_birth_update: int = -1
    update_deactivated: int = -1  # update the last live unit died (-1 = active)
    threshold: bool = False       # passed abundance threshold (ref :183)
    merit_sum: float = 0.0        # running stats for dominant reporting
    fitness_sum: float = 0.0
    gestation_sum: float = 0.0
    stat_n: int = 0

    @property
    def length(self) -> int:
        return int(len(self.sequence))


class GenotypeArbiter:
    """Classify organisms into genotypes and maintain the live phylogeny.

    Usage: call `process(update, alive, newborn_cells, newborn_genomes,
    newborn_lens, parent_cells)` once per update; query `dominant()`,
    `num_genotypes`, `coalescent_depth()` for stats output.
    """

    def __init__(self, world_cells: int, threshold: int = 3):
        self.threshold = threshold
        self._by_seq: dict[bytes, Genotype] = {}
        self.genotypes: dict[int, Genotype] = {}
        self.cell_gid = np.full(world_cells, -1, np.int64)  # cell -> genotype id
        self._next_id = 1
        self.num_births_total = 0

    # -- classification ---------------------------------------------------

    def classify_seed(self, cell: int, genome: np.ndarray, update: int = -1):
        """Register an injected organism (ref InjectClone / ActivateOrganism)."""
        self._activate(cell, np.asarray(genome, np.int8), parent_gid=-1,
                       update=update)

    def classify_seed_all(self, genome: np.ndarray, update: int = -1):
        """Bulk InjectAll registration (cActionInjectAll): every cell
        becomes one unit of a single genotype in O(previously occupied)
        host work instead of num_cells _activate calls (round-4 review
        weak #7)."""
        seq = np.asarray(genome, np.int8)
        for cell in np.nonzero(self.cell_gid >= 0)[0]:
            self._remove_unit(int(self.cell_gid[cell]), update)
        key = seq.tobytes()
        g = self._by_seq.get(key)
        if g is None:
            g = Genotype(gid=self._next_id, sequence=seq.copy(),
                         parent_gid=-1, depth=0, update_born=update)
            self._next_id += 1
            self._by_seq[key] = g
            self.genotypes[g.gid] = g
        n = self.cell_gid.shape[0]
        # same per-unit bookkeeping as _activate, batched
        g.num_units += n
        g.total_units += n
        g.last_birth_update = update
        g.update_deactivated = -1
        if g.total_units >= self.threshold:
            g.threshold = True
        self.num_births_total += n
        self.cell_gid[:] = g.gid

    def _activate(self, cell: int, seq: np.ndarray, parent_gid: int, update: int):
        key = seq.tobytes()
        g = self._by_seq.get(key)
        if g is None:
            depth = 0
            if parent_gid >= 0 and parent_gid in self.genotypes:
                depth = self.genotypes[parent_gid].depth + 1
            g = Genotype(gid=self._next_id, sequence=seq.copy(),
                         parent_gid=parent_gid, depth=depth, update_born=update)
            self._next_id += 1
            self._by_seq[key] = g
            self.genotypes[g.gid] = g
        old = self.cell_gid[cell]
        if old >= 0:
            self._remove_unit(int(old), update)
        g.num_units += 1
        g.total_units += 1
        g.last_birth_update = update
        g.update_deactivated = -1
        if g.total_units >= self.threshold:
            g.threshold = True
        self.cell_gid[cell] = g.gid
        self.num_births_total += 1

    def _remove_unit(self, gid: int, update: int):
        g = self.genotypes.get(gid)
        if g is None:
            return
        g.num_units -= 1
        if g.num_units <= 0:
            g.num_units = 0
            g.update_deactivated = update

    # -- per-update ingestion ---------------------------------------------

    def process(self, update: int, alive: np.ndarray,
                newborn_cells: np.ndarray, newborn_genomes: np.ndarray,
                newborn_lens: np.ndarray, parent_cells: np.ndarray):
        """Fold one update's births and deaths into the phylogeny.

        newborn_* are the gathered rows for cells whose birth_update equals
        `update`; parent_cells[i] is the parent's cell index (so the parent
        genotype is looked up from the *pre-birth* cell map).
        """
        # parent genotypes resolved against the pre-update cell map
        parent_gids = np.where(parent_cells >= 0,
                               self.cell_gid[np.clip(parent_cells, 0, None)],
                               -1)
        for i, cell in enumerate(newborn_cells):
            L = int(newborn_lens[i])
            self._activate(int(cell), newborn_genomes[i, :L],
                           int(parent_gids[i]), update)
        # deaths: cells we believed occupied that are no longer alive
        dead = (self.cell_gid >= 0) & ~alive
        for cell in np.nonzero(dead)[0]:
            self._remove_unit(int(self.cell_gid[cell]), update)
            self.cell_gid[cell] = -1

    def record_stats(self, cells: np.ndarray, merit, fitness, gestation):
        """Accumulate per-genotype stat sums for reporting (cheap, optional)."""
        for c in cells:
            g = self.genotypes.get(int(self.cell_gid[c]))
            if g is not None:
                g.merit_sum += float(merit[c])
                g.fitness_sum += float(fitness[c])
                g.gestation_sum += float(gestation[c])
                g.stat_n += 1

    # -- queries ----------------------------------------------------------

    @property
    def num_genotypes(self) -> int:
        return sum(1 for g in self.genotypes.values() if g.num_units > 0)

    @property
    def num_threshold(self) -> int:
        return sum(1 for g in self.genotypes.values()
                   if g.num_units > 0 and g.threshold)

    def live_genotypes(self):
        """Iterator over genotypes with living members (stats entropy)."""
        return (g for g in self.genotypes.values() if g.num_units > 0)

    def dominant(self) -> Genotype | None:
        """Most-abundant live genotype (ref dominant genotype reporting)."""
        best = None
        for g in self.genotypes.values():
            if g.num_units > 0 and (best is None or g.num_units > best.num_units
                                    or (g.num_units == best.num_units
                                        and g.gid < best.gid)):
                best = g
        return best

    def average_depth(self) -> float:
        tot = n = 0
        for g in self.genotypes.values():
            if g.num_units > 0:
                tot += g.depth * g.num_units
                n += g.num_units
        return tot / n if n else 0.0

    def prune_extinct(self, keep_ancestry: bool = True):
        """Drop extinct genotypes not on any live lineage (memory control;
        ref keeps historic genotypes only when requested)."""
        live_anc = set()
        for g in self.genotypes.values():
            if g.num_units > 0:
                gid = g.gid
                while gid >= 0 and gid not in live_anc:
                    live_anc.add(gid)
                    gg = self.genotypes.get(gid)
                    gid = gg.parent_gid if gg else -1
        doomed = [gid for gid, g in self.genotypes.items()
                  if g.num_units == 0 and (not keep_ancestry or gid not in live_anc)]
        for gid in doomed:
            g = self.genotypes.pop(gid)
            self._by_seq.pop(g.sequence.tobytes(), None)

    # -- checkpoint serialization (utils/checkpoint.py) -------------------

    _SNAP_FIELDS = ("gid", "parent_gid", "depth", "update_born", "num_units",
                    "total_units", "last_birth_update", "update_deactivated",
                    "threshold", "merit_sum", "fitness_sum", "gestation_sum",
                    "stat_n")

    def to_snapshot(self) -> dict:
        """JSON-able snapshot of the full phylogeny (native checkpoints).
        Genome sequences ride as base64 int8 bytes; everything else is a
        plain scalar, so the round-trip is exact."""
        import base64
        return {
            "threshold": self.threshold,
            "next_id": self._next_id,
            "num_births_total": self.num_births_total,
            "cell_gid": self.cell_gid.tolist(),
            "genotypes": [
                dict({f: getattr(g, f) for f in self._SNAP_FIELDS},
                     seq=base64.b64encode(
                         np.ascontiguousarray(g.sequence, np.int8)
                         .tobytes()).decode("ascii"))
                for g in self.genotypes.values()],
        }

    @classmethod
    def from_snapshot(cls, snap: dict) -> "GenotypeArbiter":
        """Rebuild an arbiter from to_snapshot output (exact inverse)."""
        import base64
        arb = cls(world_cells=len(snap["cell_gid"]),
                  threshold=int(snap["threshold"]))
        arb._next_id = int(snap["next_id"])
        arb.num_births_total = int(snap["num_births_total"])
        arb.cell_gid = np.asarray(snap["cell_gid"], np.int64)
        for rec in snap["genotypes"]:
            seq = np.frombuffer(base64.b64decode(rec["seq"]), np.int8).copy()
            kw = {f: rec[f] for f in cls._SNAP_FIELDS}
            g = Genotype(sequence=seq, **kw)
            arb.genotypes[g.gid] = g
            arb._by_seq[seq.tobytes()] = g
        return arb
