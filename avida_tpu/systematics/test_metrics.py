"""Cached per-genotype Test-CPU metrics.

TPU-native equivalent of Systematics::GenomeTestMetrics
(avida-core/source/systematics/GenomeTestMetrics.cc): sandbox fitness for
a genotype is computed once and memoized by genome content, so reversion
tests (cHardwareBase::Divide_TestFitnessMeasures cc:866) and analyze-mode
recalculation don't re-run gestations for genotypes already scored.
Uncached genotypes are evaluated in ONE batched Test-CPU run
(analyze/testcpu.evaluate_genomes).
"""

from __future__ import annotations

import numpy as np


class GenomeTestMetrics:
    """Host-side genome-bytes -> (viable, fitness, gestation) cache."""

    def __init__(self, params):
        self.params = params
        self._cache: dict[bytes, tuple[bool, float, int]] = {}

    def __len__(self):
        return len(self._cache)

    def get_fitness(self, genomes: np.ndarray, lens: np.ndarray,
                    seed: int = 0) -> np.ndarray:
        """f64[G] sandbox fitness for each genome row (0 = inviable)."""
        from avida_tpu.analyze.testcpu import evaluate_genomes

        keys = [genomes[i, : int(lens[i])].tobytes()
                for i in range(genomes.shape[0])]
        miss = [i for i, k in enumerate(keys) if k not in self._cache]
        if miss:
            # pad the batch to a power of two so the jitted gestation run
            # compiles O(log N) shapes, not one per distinct miss count
            G = 1 << max(len(miss) - 1, 0).bit_length()
            sub = np.zeros((G, self.params.max_memory), np.int8)
            sub_lens = np.zeros(G, np.int32)
            for j, i in enumerate(miss):
                sub[j, : int(lens[i])] = genomes[i, : int(lens[i])]
                sub_lens[j] = lens[i]
            res = evaluate_genomes(self.params, sub, sub_lens, seed=seed)
            for j, i in enumerate(miss):
                fit = float(res.fitness[j]) if bool(res.viable[j]) else 0.0
                self._cache[keys[i]] = (bool(res.viable[j]), fit,
                                        int(res.gestation_time[j]))
        return np.asarray([self._cache[k][1] for k in keys], np.float64)
