"""Cached per-genotype Test-CPU metrics.

TPU-native equivalent of Systematics::GenomeTestMetrics
(avida-core/source/systematics/GenomeTestMetrics.cc): sandbox metrics for
a genotype are computed once and memoized by genome content, so reversion
tests (cHardwareBase::Divide_TestFitnessMeasures cc:866), analyze-mode
recalculation and the checkpoint-native census (analyze/pipeline.py)
don't re-run gestations for genotypes already scored.  Uncached genotypes
are evaluated in ONE batched Test-CPU run
(analyze/testcpu.evaluate_genomes, which bucket-pads the batch so repeat
sweeps reuse O(log G) compiled gestation programs).
"""

from __future__ import annotations

import numpy as np


class GenomeTestMetrics:
    """Host-side (genome bytes, seed) -> sandbox-record cache.

    A record is {"viable": bool, "fitness": float (0 when inviable),
    "gestation": int, "merit": float, "tasks": int64[R] task counts at
    divide} -- everything the census/knockout/lineage passes and the
    reversion test read."""

    def __init__(self, params):
        self.params = params
        self._cache: dict[bytes, dict] = {}
        self.evaluations = 0    # genotypes actually run in the sandbox

    def __len__(self):
        return len(self._cache)

    def get_records(self, genomes: np.ndarray, lens: np.ndarray,
                    seed: int = 0) -> list:
        """One cached record per genome row, content-keyed.  All uncached
        DISTINCT genotypes are evaluated in a single batched Test-CPU
        run; repeat genotypes (the common case in census sweeps) cost
        nothing."""
        from avida_tpu.analyze.testcpu import evaluate_genomes

        # cache key includes the seed: sandbox inputs are seed-derived,
        # so records computed under one seed must never answer a query
        # for another (every in-tree caller holds one seed per instance,
        # but the API advertises the parameter)
        keys = [(genomes[i, : int(lens[i])].tobytes(), int(seed))
                for i in range(genomes.shape[0])]
        # every uncached row gets its own sandbox lane, DUPLICATES
        # INCLUDED (last write wins): sandbox inputs are LANE-indexed
        # (testcpu._sandbox_inputs -- batch-size-invariant but still a
        # function of the lane number), so preserving the historical
        # row-assignment discipline keeps a given call sequence scoring
        # deterministically across this cache-layer refactor.  Note the
        # PR-9 one-time re-base: the sandbox input construction itself
        # changed (per-lane fold_in replaced the flat batch draw, see
        # _sandbox_inputs), so sandbox scores -- and reversion-enabled
        # trajectories -- are NOT comparable with pre-PR-9 builds at
        # the same seed; within this build they are fully
        # deterministic.  Census callers pass unique genotypes, so no
        # lane is wasted where it matters.
        miss = [i for i, k in enumerate(keys) if k not in self._cache]
        if miss:
            G = len(miss)
            sub = np.zeros((G, self.params.max_memory), np.int8)
            sub_lens = np.zeros(G, np.int32)
            for j, i in enumerate(miss):
                n = int(lens[i])
                sub[j, :n] = genomes[i, :n]
                sub_lens[j] = n
            res = evaluate_genomes(self.params, sub, sub_lens,
                                   seed=int(seed))
            self.evaluations += G
            for j, i in enumerate(miss):
                viable = bool(res.viable[j])
                self._cache[keys[i]] = {
                    "viable": viable,
                    "fitness": float(res.fitness[j]) if viable else 0.0,
                    "gestation": int(res.gestation_time[j]),
                    "merit": float(res.merit[j]),
                    "tasks": np.asarray(res.task_counts[j], np.int64),
                }
        return [self._cache[k] for k in keys]

    def get_fitness(self, genomes: np.ndarray, lens: np.ndarray,
                    seed: int = 0) -> np.ndarray:
        """f64[G] sandbox fitness for each genome row (0 = inviable)."""
        return np.asarray(
            [r["fitness"] for r in self.get_records(genomes, lens, seed)],
            np.float64)
