"""State invariant auditor: fast corruption detection for run state.

`audit_state(params, st)` is a single jitted device program that checks
~15 structural invariants of a PopulationState and returns a per-invariant
violation count (int32 each).  It runs on every native checkpoint save
and restore (utils/checkpoint.py via World.save_checkpoint/resume) and
optionally every `TPU_AUDIT_EVERY` updates inside World.run -- a cheap
tripwire that names WHICH property broke (NaN merit, out-of-bounds head,
clobbered lane permutation, negative resource) instead of letting silent
corruption propagate for another 1e6 updates.

It is deliberately a SEPARATE jit from ops/update.update_step: with
auditing disabled nothing here is traced and the production update
program is byte-identical (scripts/check_jaxpr.py digest unchanged).

Invariant catalogue (each maps to a structural guarantee of the engine;
the comment names the code that establishes it):

  merit_finite        alive merit is finite and non-negative (phenotype
                      merit math, ops/interpreter.py DivideReset)
  fitness_finite      alive fitness is finite and non-negative
  bonus_finite        alive cur_bonus is finite
  ip_in_bounds        alive IP in [0, mem_len) after _adjust semantics
  heads_in_bounds     alive READ/WRITE/FLOW heads in [0, mem_len)
  genome_len_range    alive genome_len in [min_genome_len, max_memory]
  mem_len_range       alive mem_len in [1, max_memory]
  genome_ops_valid    alive genome opcodes in [0, num_insts)
  input_ptr_nonneg    alive input_ptr >= 0 (a monotone IO counter, read
                      modulo 3 -- ops/interpreter.py:481)
  stack_ptr_range     alive stack pointers in [0, 10)
  generation_nonneg   alive generation >= 0
  time_nonneg         alive time_used / cpu_cycles >= 0
  budget_carry_range  budget_carry in [0, 100 * AVE_TIME_SLICE]
                      (ops/update.bank_phase clips exactly this window)
  dead_lane_granted   the scheduler grants zero cycles to dead lanes
                      (ops/scheduler.compute_budgets masks by alive;
                      probed with a fixed out-of-stream key)
  lane_perm_bijective lane_perm is a permutation of [0, N)
  lane_inv_inverse    lane_inv composes with lane_perm to the identity
  resources_nonneg    global/spatial/deme resource pools >= -1e-3
                      (float tolerance for diffusion round-off)
  resources_finite    every resource pool entry is finite
  off_window_valid    pending offspring windows lie inside the tape
  nb_count_nonneg     newborn ring-buffer cursor >= 0
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp


from avida_tpu.observability.tracer import DEVICE_MAX_CODE as _TRACE_MAX_CODE


class StateInvariantError(AssertionError):
    """Raised by check_invariants with a per-invariant violation report."""

    def __init__(self, message: str, violations: dict):
        super().__init__(message)
        self.violations = violations


@partial(jax.jit, static_argnums=0)
def audit_state(params, st):
    """Returns {invariant_name: int32 violation count} for the whole
    population state.  All-zero means the state passes."""
    from avida_tpu.ops.update import scheduler_probe

    n, L = st.tape.shape
    alive = st.alive
    mlen = jnp.maximum(st.mem_len, 1)

    def rows(mask):
        return mask.sum().astype(jnp.int32)

    checks = {}
    checks["merit_finite"] = rows(
        alive & (~jnp.isfinite(st.merit) | (st.merit < 0)))
    checks["fitness_finite"] = rows(
        alive & (~jnp.isfinite(st.fitness) | (st.fitness < 0)))
    checks["bonus_finite"] = rows(alive & ~jnp.isfinite(st.cur_bonus))

    ip = st.heads[:, 0]
    checks["ip_in_bounds"] = rows(alive & ((ip < 0) | (ip >= mlen)))
    other = st.heads[:, 1:]
    checks["heads_in_bounds"] = rows(
        alive & ((other < 0) | (other >= mlen[:, None])).any(axis=1))

    checks["genome_len_range"] = rows(
        alive & ((st.genome_len < params.min_genome_len)
                 | (st.genome_len > L)))
    checks["mem_len_range"] = rows(
        alive & ((st.mem_len < 1) | (st.mem_len > L)))

    in_genome = jnp.arange(L)[None, :] < st.genome_len[:, None]
    bad_op = (st.genome < 0) | (st.genome >= params.num_insts)
    checks["genome_ops_valid"] = rows(
        alive & (in_genome & bad_op).any(axis=1))

    checks["input_ptr_nonneg"] = rows(alive & (st.input_ptr < 0))
    checks["stack_ptr_range"] = rows(
        alive & ((st.sp < 0) | (st.sp >= 10)).any(axis=1))
    checks["generation_nonneg"] = rows(alive & (st.generation < 0))
    checks["time_nonneg"] = rows(
        alive & ((st.time_used < 0) | (st.cpu_cycles < 0)))

    carry_cap = 100 * params.ave_time_slice
    checks["budget_carry_range"] = rows(
        (st.budget_carry < 0) | (st.budget_carry > carry_cap))

    _, granted, _ = scheduler_probe(params, st)
    checks["dead_lane_granted"] = rows(~alive & (granted != 0))

    counts = jnp.zeros(n, jnp.int32).at[jnp.clip(st.lane_perm, 0, n - 1)].add(1)
    in_range = (st.lane_perm >= 0) & (st.lane_perm < n)
    checks["lane_perm_bijective"] = rows(~in_range) + rows(counts != 1)
    safe_perm = jnp.clip(st.lane_perm, 0, n - 1)
    checks["lane_inv_inverse"] = rows(
        st.lane_inv[safe_perm] != jnp.arange(n, dtype=st.lane_inv.dtype))

    res_neg = jnp.int32(0)
    res_nan = jnp.int32(0)
    for pool in (st.resources, st.res_grid, st.deme_resources):
        res_neg = res_neg + rows(pool < -1e-3)
        res_nan = res_nan + rows(~jnp.isfinite(pool))
    checks["resources_nonneg"] = res_neg
    checks["resources_finite"] = res_nan

    checks["off_window_valid"] = rows(
        st.divide_pending & ((st.off_len < 0) | (st.off_len > L)
                             | (st.off_start < 0) | (st.off_start >= L)))
    checks["nb_count_nonneg"] = jnp.where(st.nb_count < 0, 1, 0
                                          ).astype(jnp.int32)

    if st.tr_count is not None:
        # flight-recorder ring (observability/tracer.py): the cursor is
        # monotone-nonnegative, and every LIVE slot (index < min(count,
        # cap) -- rows past the cursor are drain scratch) holds a known
        # event code and an in-range cell (-1 = world-level event)
        cap = st.tr_code.shape[0]
        live = jnp.arange(cap) < jnp.clip(st.tr_count, 0, cap)
        checks["trace_cursor_nonneg"] = jnp.where(
            st.tr_count < 0, 1, 0).astype(jnp.int32)
        checks["trace_ring_valid"] = rows(
            live & ((st.tr_code < 1) | (st.tr_code > _TRACE_MAX_CODE)
                    | (st.tr_cell < -1) | (st.tr_cell >= n)))
    return checks


def check_invariants(params, st, where: str = "") -> dict:
    """Host-side wrapper: run the auditor, raise StateInvariantError with
    a per-invariant report when anything is violated, else return the
    (all-zero) count dict."""
    counts = {k: int(v) for k, v in audit_state(params, st).items()}
    bad = {k: v for k, v in counts.items() if v}
    if bad:
        ctx = f" at {where}" if where else ""
        report = ", ".join(f"{k}={v} cell(s)" for k, v in sorted(bad.items()))
        raise StateInvariantError(
            f"state invariant violation{ctx}: {report}", bad)
    return counts


def audit_ok(params, st) -> bool:
    """Boolean convenience for callers that log instead of raising."""
    return not any(int(v) for v in audit_state(params, st).values())
