"""Native bit-exact run checkpoints (crash-safe save / verified resume).

The reference-parity `.spop` format (utils/spop.py) is lossy by design:
genotype-grouped, per-genotype *averaged* merit, no CPU registers or
threads, no PRNG key, no resource or systematics state.  A run killed by
TPU preemption cannot be resumed bit-exactly from it.  This module is the
robustness staple the long-run regime needs (cf. Orbax-style async
checkpointing, PAPERS.md; the reference's SavePopulation/LoadPopulation
pair is the ecosystem-facing sibling, not a replacement):

  * a checkpoint DIRECTORY per generation (`ckpt-<update>`), one `.npy`
    per PopulationState leaf plus the typed PRNG keys, a systematics
    snapshot and a host-counter block;
  * `manifest.json` as the integrity root: per-array CRC32 + shape +
    dtype.  A byte flip or truncation anywhere fails verification;
  * ATOMIC writes: everything lands in a `.tmp-*` sibling, every file is
    fsync'd, then one rename publishes the generation (a crash mid-save
    never clobbers the previous good checkpoint);
  * rolling retention (`TPU_CKPT_KEEP`, default 2) so a corrupt newest
    generation falls back to the previous one.

Resume is BIT-EXACT because the run PRNG stream is a pure function of
(`_run_key`, update number) -- ops/update.update_scan's fold_in design --
so restoring the state pytree, the keys and the update counter replays
the identical trajectory regardless of how the driver re-chunks updates.

`update_scan` donation caveat: the scan DONATES its input state buffers,
so checkpointing always reads the state object World holds AFTER a chunk
returns (never a reference captured before the call).  `save_checkpoint`
materializes host copies via np.asarray before anything else runs.
"""

from __future__ import annotations

import json
import os
import shutil
import time
import zlib

import numpy as np

MANIFEST = "manifest.json"
FORMAT_VERSION = 1
PREFIX = "ckpt-"


class CheckpointError(RuntimeError):
    """A checkpoint generation failed verification or could not be read."""


class CheckpointMismatchError(CheckpointError):
    """Checkpoint is intact but incompatible with this world's config
    (different grid / memory / instruction-set shape) -- falling back to
    an older generation cannot help, so this is never swallowed."""


class CheckpointManifestError(CheckpointError):
    """manifest.json is torn or unreadable (truncated mid-write by a
    crash, or a JSON decode failure) -- distinguished from a payload CRC
    mismatch so tooling (scripts/ckpt_tool.py --verify) can tell "the
    save died" from "the data rotted".  Recovery is identical: skip the
    generation and fall back."""


# ---------------------------------------------------------------------------
# low-level generation store (pure host / numpy -- unit-testable without jax)
# ---------------------------------------------------------------------------

def generation_name(update: int) -> str:
    return f"{PREFIX}{int(update):012d}"


def generation_update(path: str) -> int:
    """Update number encoded in a generation directory name (-1 when the
    name does not carry one).  Works for published `ckpt-*` dirs and the
    crash-window `.old-ckpt-*` asides restore_candidates also scans."""
    name = os.path.basename(path)
    i = name.find(PREFIX)
    if i < 0:
        return -1
    digits = name[i + len(PREFIX):].split(".", 1)[0]
    try:
        return int(digits)
    except ValueError:
        return -1


def _fsync_dir(path: str):
    fd = os.open(path, os.O_RDONLY)
    try:
        os.fsync(fd)
    finally:
        os.close(fd)


def _crc32_file(path: str) -> int:
    crc = 0
    with open(path, "rb") as f:
        while True:
            chunk = f.read(1 << 20)
            if not chunk:
                break
            crc = zlib.crc32(chunk, crc)
    return crc & 0xFFFFFFFF


def list_generations(base_dir: str) -> list:
    """Paths of all published generations, oldest -> newest."""
    if not os.path.isdir(base_dir):
        return []
    out = [os.path.join(base_dir, d) for d in os.listdir(base_dir)
           if d.startswith(PREFIX)]
    return sorted(out)


def write_generation(base_dir: str, update: int, arrays: dict,
                     host: dict, files: dict | None = None,
                     keep: int = 2, extra: dict | None = None) -> str:
    """Write one checkpoint generation atomically; returns its path.

    arrays: name -> np.ndarray (saved as <name>.npy, CRC'd)
    host:   JSON-able scalar block (stored inside the manifest)
    files:  name -> bytes sidecar blobs (CRC'd like arrays)
    extra:  additional top-level manifest keys (the integrity plane's
            `state_digest`; utils/integrity.py)

    The generation directory only appears (rename) after every byte is
    written and fsync'd; a crash at any earlier point leaves a `.tmp-*`
    sibling that the next save sweeps away.  After publishing, retention
    drops the oldest generations beyond `keep`.
    """
    os.makedirs(base_dir, exist_ok=True)
    final = os.path.join(base_dir, generation_name(update))
    tmp = os.path.join(base_dir,
                       f".tmp-{generation_name(update)}.{os.getpid()}")
    if os.path.exists(tmp):
        shutil.rmtree(tmp)
    os.makedirs(tmp)

    manifest = {
        "format": FORMAT_VERSION,
        "update": int(update),
        "saved_at": time.time(),
        "arrays": {},
        "files": {},
        "host": host,
        **(extra or {}),
    }
    for name, arr in arrays.items():
        arr = np.asarray(arr)
        fname = f"{name}.npy"
        fpath = os.path.join(tmp, fname)
        with open(fpath, "wb") as f:
            np.save(f, arr)
            f.flush()
            os.fsync(f.fileno())
        manifest["arrays"][name] = {
            "file": fname,
            "shape": list(arr.shape),
            "dtype": str(arr.dtype),
            "crc32": _crc32_file(fpath),
        }
    for name, blob in (files or {}).items():
        fpath = os.path.join(tmp, name)
        with open(fpath, "wb") as f:
            f.write(blob)
            f.flush()
            os.fsync(f.fileno())
        manifest["files"][name] = {
            "size": len(blob),
            "crc32": zlib.crc32(blob) & 0xFFFFFFFF,
        }
    mpath = os.path.join(tmp, MANIFEST)
    with open(mpath, "w") as f:
        json.dump(manifest, f, indent=1)
        f.write("\n")
        f.flush()
        os.fsync(f.fileno())
    _fsync_dir(tmp)

    # publish: a same-update re-save replaces the old generation, but the
    # old one is moved ASIDE first and removed only after the rename --
    # a crash at any point leaves either the old or the new generation
    # published (never zero; the aside/tmp siblings are swept next save)
    aside = None
    if os.path.exists(final):
        aside = os.path.join(base_dir,
                             f".old-{generation_name(update)}.{os.getpid()}")
        if os.path.exists(aside):
            shutil.rmtree(aside)
        os.rename(final, aside)
    os.rename(tmp, final)
    _fsync_dir(base_dir)
    if aside is not None:
        shutil.rmtree(aside, ignore_errors=True)

    # retention + stale tmp/aside sweep
    gens = list_generations(base_dir)
    for old in gens[:-max(int(keep), 1)] if keep else []:
        shutil.rmtree(old, ignore_errors=True)
    for d in os.listdir(base_dir):
        p = os.path.join(base_dir, d)
        if (d.startswith(".tmp-") or d.startswith(".old-")) and p != tmp:
            shutil.rmtree(p, ignore_errors=True)
    return final


def verify_generation(path: str) -> dict:
    """Validate a generation's manifest + every CRC; returns the manifest.
    Raises CheckpointError on any missing/corrupt/truncated piece."""
    mpath = os.path.join(path, MANIFEST)
    if not os.path.exists(mpath):
        raise CheckpointError(f"{path}: no {MANIFEST}")
    try:
        with open(mpath) as f:
            manifest = json.load(f)
    except (json.JSONDecodeError, OSError) as e:
        raise CheckpointManifestError(
            f"{path}: torn or unreadable manifest ({e})")
    if manifest.get("format") != FORMAT_VERSION:
        raise CheckpointError(
            f"{path}: unsupported checkpoint format "
            f"{manifest.get('format')!r} (want {FORMAT_VERSION})")
    for name, spec in manifest.get("arrays", {}).items():
        fpath = os.path.join(path, spec["file"])
        if not os.path.exists(fpath):
            raise CheckpointError(f"{path}: missing array file {spec['file']}")
        crc = _crc32_file(fpath)
        if crc != spec["crc32"]:
            raise CheckpointError(
                f"{path}: CRC mismatch on {name} "
                f"({crc:#010x} != {spec['crc32']:#010x})")
    for name, spec in manifest.get("files", {}).items():
        fpath = os.path.join(path, name)
        if not os.path.exists(fpath):
            raise CheckpointError(f"{path}: missing sidecar {name}")
        if os.path.getsize(fpath) != spec["size"] \
                or _crc32_file(fpath) != spec["crc32"]:
            raise CheckpointError(f"{path}: corrupt sidecar {name}")
    return manifest


def read_generation(path: str) -> tuple:
    """(manifest, arrays, files) with every CRC verified.  Array dtypes
    and shapes are additionally checked against the manifest (a np.save
    header flip that keeps the CRC is impossible, but the belt matches
    the braces)."""
    manifest = verify_generation(path)
    arrays = {}
    for name, spec in manifest["arrays"].items():
        arr = np.load(os.path.join(path, spec["file"]))
        if list(arr.shape) != spec["shape"] or str(arr.dtype) != spec["dtype"]:
            raise CheckpointError(
                f"{path}: array {name} shape/dtype drifted from manifest")
        arrays[name] = arr
    files = {}
    for name in manifest["files"]:
        with open(os.path.join(path, name), "rb") as f:
            files[name] = f.read()
    return manifest, arrays, files


def restore_candidates(base_dir: str) -> list:
    """Generation paths to try on restore, best-first: published
    generations newest-to-oldest, then any `.old-*` aside left by a
    crash inside write_generation's publish window (old generation moved
    aside but the new one not yet renamed in) -- so even that two-rename
    window cannot strand a run without a resumable checkpoint."""
    gens = list(reversed(list_generations(base_dir)))
    if os.path.isdir(base_dir):
        gens += sorted((os.path.join(base_dir, d)
                        for d in os.listdir(base_dir)
                        if d.startswith(".old-")), reverse=True)
    return gens


def quarantine_after(base_dir: str, update: int) -> list:
    """Silent-corruption recovery helper: move every generation saved
    PAST `update` aside to `.bad-*` (invisible to restore_candidates,
    swept later by `ckpt_tool --prune`), so the next resume rolls back
    to the newest generation at or before the last verified update.
    Always leaves at least one generation published -- when every
    generation postdates the verified horizon the OLDEST survives
    (deterministic replay from it is at least self-consistent, and a
    run with zero resumable generations would wedge in exit 66).
    Returns the quarantined paths, newest first."""
    gens = list_generations(base_dir)
    out = []
    for g in reversed(gens):
        if generation_update(g) <= int(update):
            break
        if len(gens) - len(out) <= 1:
            break
        dst = os.path.join(
            base_dir, f".bad-{os.path.basename(g)}.{int(time.time())}")
        try:
            os.rename(g, dst)
            out.append(g)
        except OSError:
            break
    return out


def latest_valid(base_dir: str, on_skip=None) -> tuple:
    """Newest generation that verifies, as (path, manifest).  Corrupt
    generations are skipped newest-to-oldest (on_skip(path, error) is
    called for each).  Raises CheckpointError when none survives."""
    gens = restore_candidates(base_dir)
    if not gens:
        raise CheckpointError(f"no checkpoints under {base_dir!r}")
    last_err = None
    for path in gens:
        try:
            return path, verify_generation(path)
        except CheckpointError as e:
            last_err = e
            if on_skip is not None:
                on_skip(path, e)
    raise CheckpointError(
        f"no valid checkpoint under {base_dir!r} "
        f"({len(gens)} generation(s) all failed; last: {last_err})")


# ---------------------------------------------------------------------------
# World-level save / restore
# ---------------------------------------------------------------------------

_STATE_PREFIX = "state."


def _host_snapshot(world) -> dict:
    """Everything trajectory- or output-relevant that lives on the host:
    update counter, event cursors, device-scalar accumulators, .dat diff
    baselines, the reversion RNG and the telemetry cursor."""
    world._flush_exec()
    host = {
        "update": int(world.update),
        "seed": int(world.cfg.RANDOM_SEED),
        "avida_time": float(np.asarray(world._avida_time)),
        "last_ave_gen": float(np.asarray(world._last_ave_gen)),
        "deaths_this": int(np.asarray(world._deaths_this)),
        "prev_alive": (None if world._prev_alive is None
                       else int(np.asarray(world._prev_alive))),
        "total_births": int(np.asarray(world._total_births)),
        "cum_insts": int(world._cum_insts),
        "insts_prev_total": int(world._insts_prev_total),
        "time_prev": int(getattr(world, "_time_prev", 0)),
        "last_drain_update": int(world._last_drain_update),
        "events_done_for": world._events_done_for,
        # generation/births event cursors, aligned with world.events order
        # (the live dict is keyed by id(ev), which does not survive a
        # process restart)
        "gen_next": [world._gen_next.get(id(ev)) for ev in world.events],
        "task_exe_prev": (
            np.asarray(world._task_exe_prev, np.int64).tolist()
            if getattr(world, "_task_exe_prev", None) is not None else None),
    }
    if getattr(world, "_revert_on", False):
        host["revert_rng"] = world._revert_rng.bit_generator.state
    tel = getattr(world, "telemetry", None)
    if tel is not None and tel._task_prev is not None:
        host["telemetry"] = {
            "task_prev": np.asarray(tel._task_prev, np.int64).tolist(),
            "updates_run": int(tel._updates_run),
        }
    trc = getattr(world, "tracer", None)
    if trc is not None:
        host["tracer"] = trc.to_snapshot()
    return host


def _host_restore(world, host: dict):
    import jax.numpy as jnp
    world.update = int(host["update"])
    world._avida_time = jnp.float32(host["avida_time"])
    world._last_ave_gen = jnp.float32(host["last_ave_gen"])
    world._deaths_this = jnp.int32(host["deaths_this"])
    world._prev_alive = (None if host["prev_alive"] is None
                         else jnp.int32(host["prev_alive"]))
    world._total_births = jnp.int32(host["total_births"])
    world._cum_insts = int(host["cum_insts"])
    world._insts_prev_total = int(host["insts_prev_total"])
    world._pending_exec = []
    world._time_prev = int(host["time_prev"])
    world._last_drain_update = int(host["last_drain_update"])
    world._events_done_for = host["events_done_for"]
    world._gen_next = {id(ev): v
                       for ev, v in zip(world.events, host.get("gen_next", []))
                       if v is not None}
    world._nb_pending = None
    world._summary_cache_update = None
    if host.get("task_exe_prev") is not None:
        world._task_exe_prev = np.asarray(host["task_exe_prev"], np.int64)
    if "revert_rng" in host and getattr(world, "_revert_on", False):
        world._revert_rng.bit_generator.state = host["revert_rng"]
    tel = getattr(world, "telemetry", None)
    if tel is not None:
        if host.get("telemetry"):
            tel.seed_task_totals(np.asarray(host["telemetry"]["task_prev"],
                                            np.int64))
            tel._updates_run = int(host["telemetry"]["updates_run"])
        # resume continuity: a preempted run's telemetry.jsonl in the same
        # data_dir is APPENDED to (the recorder's reopen-append flag),
        # mirroring the .dat append mode -- not truncated by mode "w"
        if os.path.exists(os.path.join(world.data_dir, "telemetry.jsonl")):
            tel._log_opened = True
    trc = getattr(world, "tracer", None)
    if trc is not None:
        # restore drain counters + arm runlog append (resume continuity)
        trc.from_snapshot(host.get("tracer") or {})
        world._trace_pending = None


def save_checkpoint(base_dir: str, world) -> str:
    """Serialize the ENTIRE run state of `world` into a new generation
    under base_dir.  The caller (World.save_checkpoint) is responsible
    for draining the deferred newborn snapshot first so the systematics
    snapshot is current."""
    import jax

    from avida_tpu.core.state import state_field_names

    st = world.state
    if st is None:
        raise CheckpointError("no population state to checkpoint")
    # None-valued fields (the flight-recorder ring with the recorder
    # off) are empty pytrees with no on-disk representation; with the
    # recorder ON the ring IS serialized -- drained (cursor 0) because
    # World.save_checkpoint flushes the trace first, so a restored ring
    # never replays stale events
    arrays = {_STATE_PREFIX + name: np.asarray(getattr(st, name))
              for name in state_field_names()
              if getattr(st, name) is not None}
    arrays["prng.key"] = np.asarray(jax.random.key_data(world.key))
    arrays["prng.run_key"] = np.asarray(jax.random.key_data(world._run_key))
    host = _host_snapshot(world)
    files = {}
    if world.systematics is not None:
        files["systematics.json"] = json.dumps(
            world.systematics.to_snapshot()).encode()
    keep = int(world.cfg.get("TPU_CKPT_KEEP", 2))
    extra = None
    if getattr(world, "_digest_on", False) \
            or getattr(world, "_scrub_every", 0):
        # integrity plane armed: the manifest carries the order-stable
        # state digest (utils/integrity.py), recomputed here from the
        # very host arrays being written -- by construction equal to
        # the device digest of the live state (ops/digest.py), which
        # is what lets --resume, ckpt_tool --verify and the
        # supervisor's sdc rollback re-verify generations without jax
        from avida_tpu.utils import integrity
        extra = {"state_digest": integrity.digest_arrays(
            integrity.state_arrays_of(arrays))}
    return write_generation(base_dir, world.update, arrays, host,
                            files=files, keep=keep, extra=extra)


def _build_state(world, arrays: dict):
    """Reassemble a PopulationState from a generation's array dict,
    checking field-set and world-shape compatibility.  The flight-
    recorder ring fields are config-dependent (None when the recorder is
    off) and reconciled to THIS world's TPU_TRACE config rather than
    failing the field-set check: every checkpoint's ring is drained
    (cursor 0), so seeding a fresh empty ring on a cap change loses
    nothing."""
    import jax.numpy as jnp
    from avida_tpu.core.state import (TRACE_RING_FIELDS, PopulationState,
                                      state_field_names)

    fields = list(state_field_names())
    have = {k[len(_STATE_PREFIX):] for k in arrays if k.startswith(_STATE_PREFIX)}
    missing = [f for f in fields if f not in have
               and f not in TRACE_RING_FIELDS]
    extra = sorted(have - set(fields))
    if missing or extra:
        raise CheckpointMismatchError(
            f"checkpoint state fields do not match this build "
            f"(missing {missing[:4]}, unknown {extra[:4]})")
    # DEVICE-OWNED copies, not views: jnp.asarray on a freshly-loaded
    # numpy array may zero-copy alias the numpy-owned memory on the CPU
    # backend, and these leaves are DONATED into the update scan.  The
    # jit dispatch path quietly refuses to donate such buffers, but an
    # ahead-of-time Compiled program (utils/compilecache.py) donates
    # unconditionally -- the runtime then frees memory numpy owns:
    # "free(): invalid pointer" heap aborts at process teardown, the
    # same failure mode that condemned JAX_COMPILATION_CACHE_DIR in
    # PR 6 (resumed runs loading cached executables).  One copy per
    # resume is noise; tests/test_compile_cache.py's SIGKILL+resume
    # drill is the regression net.
    vals = {name: (jnp.copy(jnp.asarray(arrays[_STATE_PREFIX + name]))
                   if _STATE_PREFIX + name in arrays else None)
            for name in fields}
    cap = int(world.params.trace_cap)
    if cap == 0:
        for name in TRACE_RING_FIELDS:
            vals[name] = None
    elif vals["tr_code"] is None or vals["tr_code"].shape[0] != cap:
        vals.update(tr_update=jnp.zeros(cap, jnp.int32),
                    tr_cell=jnp.zeros(cap, jnp.int32),
                    tr_code=jnp.zeros(cap, jnp.int32),
                    tr_payload=jnp.zeros(cap, jnp.int32),
                    tr_count=jnp.zeros((), jnp.int32))
    st = PopulationState(**vals)
    p = world.params
    if st.alive.shape != (p.num_cells,) \
            or st.tape.shape != (p.num_cells, p.max_memory):
        raise CheckpointMismatchError(
            f"checkpoint world shape {tuple(st.tape.shape)} does not match "
            f"config ({p.num_cells} cells x {p.max_memory} memory) -- "
            f"resume with the run's original config")
    return st


def _apply(world, manifest: dict, arrays: dict, files: dict):
    import jax
    import jax.numpy as jnp

    st = _build_state(world, arrays)
    world.state = st
    world.key = jax.random.wrap_key_data(jnp.asarray(arrays["prng.key"]))
    world._run_key = jax.random.wrap_key_data(
        jnp.asarray(arrays["prng.run_key"]))
    _host_restore(world, manifest["host"])
    if world.systematics is not None:
        from avida_tpu.systematics import GenotypeArbiter
        if "systematics.json" in files:
            world.systematics = GenotypeArbiter.from_snapshot(
                json.loads(files["systematics.json"].decode()))
        else:
            # checkpoint was written with systematics off: rebuild an
            # ancestry-free phylogeny from the live population (documented
            # approximation -- depth/lineage restart at zero)
            from avida_tpu.observability.runlog import emit_event
            emit_event(world, "checkpoint_no_systematics",
                       detail="rebuilding genotype table from live state; "
                              "phylogenetic depth restarts at 0")
            arb = GenotypeArbiter(world.params.num_cells)
            alive = np.asarray(st.alive)
            genomes = np.asarray(st.genome)
            lens = np.asarray(st.genome_len)
            for c in np.nonzero(alive)[0]:
                arb.classify_seed(int(c), genomes[c, :lens[c]],
                                  update=world.update)
            world.systematics = arb


def restore_checkpoint(base_dir: str, world, at_update: int | None = None
                       ) -> int:
    """Restore `world` from the newest VALID generation under base_dir.

    Corrupt or truncated generations (manifest/CRC failures) are skipped
    with a runlog warning, falling back to the previous retained one;
    config-incompatible checkpoints raise immediately.  Returns the
    restored update number.

    at_update pins the restore to the generation saved at that SPECIFIC
    update (still CRC-verified; asides included).  The multi-world
    batched driver uses this to re-align a fleet of per-world checkpoint
    dirs on one common update when a member's newest generation fell
    back further than its peers' (parallel/multiworld.py)."""
    from avida_tpu.observability.runlog import emit_event

    def on_skip(path, err):
        emit_event(world, "checkpoint_corrupt", path=path, error=str(err),
                   detail="falling back to previous retained generation")

    candidates = restore_candidates(base_dir)
    if at_update is not None:
        candidates = [p for p in candidates
                      if generation_update(p) == int(at_update)]
        if not candidates:
            raise CheckpointError(
                f"no generation at update {at_update} under {base_dir!r}")
    last_err = None
    for path in candidates:
        try:
            manifest, arrays, files = read_generation(path)
        except CheckpointMismatchError:
            raise
        except CheckpointError as e:
            last_err = e
            on_skip(path, e)
            continue
        stored = manifest.get("state_digest")
        if stored is not None:
            # integrity plane: re-verify the restored state's digest
            # against the manifest BEFORE running.  CRC catches bytes
            # that rotted; this catches the loader-corruption class
            # (the PR-13 donation-aliasing landmine's family) where the
            # bytes verify but the decoded state would not -- treated
            # exactly like a CRC failure: skip the generation, fall
            # back, journal with its own reason
            from avida_tpu.utils import integrity
            got = integrity.digest_arrays(integrity.state_arrays_of(arrays))
            if got != int(stored):
                last_err = CheckpointError(
                    f"{path}: state digest mismatch (recomputed "
                    f"{got:#010x} != manifest {int(stored):#010x})")
                emit_event(world, "checkpoint_digest_mismatch", path=path,
                           recomputed=f"{got:#010x}",
                           manifest=f"{int(stored):#010x}",
                           detail="falling back past the generation")
                continue
        try:
            _apply(world, manifest, arrays, files)
        except CheckpointMismatchError:
            raise
        emit_event(world, "checkpoint_restored", path=path,
                   update=int(manifest["update"]))
        return int(manifest["update"])
    raise CheckpointError(
        f"no valid checkpoint under {base_dir!r} (last error: {last_err})")
