"""Deterministic churn traces: streaming fleet traffic you can replay.

The serving layer's whole claim is behavior under CHURN -- arrivals,
cancels, completions interleaving against live batches -- and the only
way to trust it (or to bench it honestly) is to drive it with the same
traffic twice.  This module extends the fault-injection discipline
(utils/faultinject.py: seeded, text-spec'd, reproducible byte-for-byte)
from single-process faults to fleet-level traffic.

Trace grammar (one event per line; the TPU_FAULT `kind:args@trigger`
shape with a time trigger):

    event  := kind [":" args] "@" "t=" FLOAT
    kind   := "submit" | "cancel"
    args   := KEY "=" VALUE ("," KEY "=" VALUE)*

`submit` takes `job=NAME` plus the per-tenant knobs the replayer turns
into a spec: `seed=N`, `u=MAX_UPDATES`, optional `class=K` (an index
into the replayer's static-config variants -- distinct batchability
classes), optional `tenant=T` (the quota label).  `cancel` takes
`job=NAME`.  `complete` events are deliberately NOT in the grammar:
completion is emergent (a tenant finishes when its own `u` budget is
reached), so a trace stays valid across engine speedups.

`generate` draws a whole trace from one integer seed (`fleet_tool.py
gen-trace`); `parse_trace`/`replay` drive a live spool from one --
the acceptance bench (bench.py BENCH_SERVE=1) and the chaos suite both
replay the same committed trace file.
"""

from __future__ import annotations

import os
import random
import time

KINDS = ("submit", "cancel")


class ChurnEvent:
    """One parsed trace line."""

    def __init__(self, t: float, kind: str, args: dict, text: str):
        self.t = float(t)
        self.kind = kind
        self.args = args
        self.text = text

    @property
    def job(self) -> str:
        return self.args.get("job", "")

    def __repr__(self):
        return f"ChurnEvent({self.text!r})"


def parse_event(text: str) -> ChurnEvent:
    part = text.strip()
    if "@" not in part:
        raise ValueError(f"churn event {text!r}: missing @t=SECONDS "
                         f"trigger")
    part, trig = part.rsplit("@", 1)
    name, eq, val = trig.partition("=")
    if not eq or name.strip() != "t":
        raise ValueError(f"churn event {text!r}: trigger must be t=SECONDS")
    t = float(val)
    kind, _, argstr = part.partition(":")
    kind = kind.strip()
    if kind not in KINDS:
        raise ValueError(f"unknown churn kind {kind!r} in {text!r} "
                         f"(known: {', '.join(KINDS)})")
    args = {}
    for tok in argstr.split(","):
        tok = tok.strip()
        if not tok:
            continue
        k, eq, v = tok.partition("=")
        if not eq:
            raise ValueError(f"churn event {text!r}: bare argument "
                             f"{tok!r} (every arg is KEY=VALUE)")
        args[k.strip()] = v.strip()
    if not args.get("job"):
        raise ValueError(f"churn event {text!r}: needs job=NAME")
    if kind == "submit":
        for req in ("seed", "u"):
            int(args.get(req, ""))      # required, integer -- raises
    return ChurnEvent(t, kind, args, text.strip())


def parse_trace(path_or_lines) -> list:
    """Parse a trace file (or an iterable of lines) into time-sorted
    ChurnEvents.  `#` comments and blank lines are skipped."""
    if isinstance(path_or_lines, str):
        with open(path_or_lines) as f:
            lines = f.readlines()
    else:
        lines = list(path_or_lines)
    events = []
    for raw in lines:
        line = raw.split("#", 1)[0].strip()
        if line:
            events.append(parse_event(line))
    if not events:
        raise ValueError("empty churn trace")
    events.sort(key=lambda e: (e.t, e.kind != "submit", e.job))
    return events


def generate(seed: int, jobs: int = 12, classes: int = 1,
             cancel_frac: float = 0.2, span: float = 30.0,
             updates: int = 40, tenants: int = 1) -> list:
    """Draw a deterministic arrival/cancel trace: `jobs` submissions
    uniform over [0, span), round-robin across `classes` static
    variants and `tenants` quota labels, with `cancel_frac` of the
    tenants cancelled somewhere after their arrival.  Same seed, same
    trace -- byte for byte (the faultinject seeding discipline)."""
    rng = random.Random(int(seed))
    lines = []
    arrivals = sorted(round(rng.uniform(0.0, float(span)), 2)
                      for _ in range(int(jobs)))
    cancels = rng.sample(range(int(jobs)),
                         int(round(int(jobs) * float(cancel_frac))))
    for i, t in enumerate(arrivals):
        args = [f"job=t{i:03d}", f"seed={rng.randrange(1, 10_000)}",
                f"u={int(updates)}"]
        if classes > 1:
            args.append(f"class={i % int(classes)}")
        if tenants > 1:
            args.append(f"tenant=org{i % int(tenants)}")
        lines.append(ChurnEvent(t, "submit",
                                dict(a.split("=", 1) for a in args),
                                f"submit:{','.join(args)}@t={t}"))
        if i in cancels:
            ct = round(t + rng.uniform(1.0, float(span)), 2)
            lines.append(ChurnEvent(ct, "cancel", {"job": f"t{i:03d}"},
                                    f"cancel:job=t{i:03d}@t={ct}"))
    lines.sort(key=lambda e: (e.t, e.kind != "submit", e.job))
    return lines


def format_trace(events, seed=None, note: str = "") -> str:
    head = ["# churn trace (utils/churntrace.py grammar: "
            "kind:args@t=SECONDS)"]
    if seed is not None:
        head.append(f"# generated with --seed {seed}")
    if note:
        head.append(f"# {note}")
    return "\n".join(head + [e.text for e in events]) + "\n"


def replay(spool: str, events, argv_for, batch: bool = True,
           speed: float = 1.0, clock=time.time, sleep=time.sleep,
           on_event=None) -> dict:
    """Drive a live spool with a parsed trace: submits via
    fleet_tool.submit, cancels via the operator marker files the
    orchestrator consumes on its next poll.  `argv_for(event)` maps a
    submit event to the child argv (the caller owns the static-config
    variants `class=K` indexes).  Times are scaled by `speed`
    (0 = as fast as possible).  Returns {job: wall-clock submit time}
    -- the queue-wait measurement baseline."""
    import sys
    scripts = os.path.join(os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__)))), "scripts")
    if scripts not in sys.path:
        sys.path.insert(0, scripts)
    import fleet_tool
    t0 = clock()
    submitted = {}
    for ev in events:
        due = t0 + ev.t * speed
        while clock() < due:
            sleep(min(due - clock(), 0.2))
        if ev.kind == "submit":
            spec_kw = {}
            if ev.args.get("tenant"):
                spec_kw["tenant"] = ev.args["tenant"]
            fleet_tool.submit(spool, ev.job, argv_for(ev), batch=batch,
                              **spec_kw)
            submitted[ev.job] = clock()
        elif ev.kind == "cancel":
            try:
                with open(os.path.join(spool, ev.job + ".cancel"),
                          "w"):
                    pass
            except OSError:
                pass
        if on_event is not None:
            on_event(ev)
    return submitted
