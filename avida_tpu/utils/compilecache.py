"""Persistent AOT program cache: serialized executables, not warm processes.

PR 12's "program cache" was process reuse: a warm `--serve-worlds` child
holds its compiled programs in memory, so warmth dies with the process
and every COLD child re-pays the full ~25-40s trace+compile window --
BENCH_r10_local.json shows that window is the entire reason static
coalescing still beat dynamic serving on raw wall.  This module is the
production-inference lever on top: the engine's compiled scan programs
are ahead-of-time lowered (`jit_fn.lower(...).compile()`), their PJRT
executables serialized (`jax.experimental.serialize_executable`), and
stored on disk with the checkpoint subsystem's atomic-publish +
CRC-manifest discipline -- so a cold-spawned class child deserializes a
sibling's executable in milliseconds instead of re-tracing.

This is explicitly NOT `JAX_COMPILATION_CACHE_DIR`.  That knob is the
PR-6 landmine: on this toolchain a resumed run loading XLA's own cached
executables produced glibc heap corruption and garbage state
(README "Known landmines"; tests/test_chaos.py strips the variable).
This cache is our own store with our own integrity root:

  * every entry is published atomically (`.tmp-*` sibling, fsync,
    rename) and carries a manifest with per-file CRC32s -- a byte flip,
    a truncation or a torn publish fails verification and falls back to
    a fresh trace with a journaled `compile_cache` event;
  * entries that verify but were built by a DIFFERENT toolchain or code
    version (jax/jaxlib version, backend platform, the in-repo source
    digest -- scripts/check_jaxpr.py's update_step jaxpr snapshot folded
    in) are refused loudly and overwritten by the fresh compile;
  * `TPU_COMPILE_CACHE=0` (env var or config var -- either kills) is a
    hard kill switch restoring the plain jit path, and the chaos drill
    in tests/test_compile_cache.py proves SIGKILL+resume with the cache
    ON stays bit-exact vs cache OFF -- the exact failure mode that
    condemned the on-disk XLA cache.

Cache key (the entry directory name): sha256 over the program tag
(`update_scan` / `multiworld_scan`), a digest of the static WorldParams
(every trace-relevant config fact, serve.static_signature's device-side
shadow), the static chunk length, the shape/dtype of every dynamic
input leaf (which pins the padded serve width W), the backend
platform/device-kind/device-count, the x64 flag and the
program-affecting env (TPU_KERNEL_ROWSKIP / TPU_TASKS_UNCOND /
TPU_KERNEL_ABLATE read at trace time, plus XLA_FLAGS -- different
compiler flags build genuinely different executables).
Toolchain + code versions deliberately live in the MANIFEST rather than
the key: a drifted entry is *found* and refused with a per-cause
journaled reason (then overwritten), instead of silently orphaned.

The module imports jax lazily: `scripts/cache_tool.py` (list / verify /
prune) runs the pure-host entry plumbing without initializing a device.
"""

from __future__ import annotations

import hashlib
import json
import os
import pickle
import shutil
import time
import zlib

from avida_tpu.utils.checkpoint import _crc32_file, _fsync_dir

MANIFEST = "manifest.json"
EXEC_FILE = "exec.bin"
TREES_FILE = "trees.pkl"
FORMAT = "avida-compile-cache-v1"

# env knobs that change the COMPILED PROGRAM without touching
# WorldParams, so they must split the cache key: the trace-time kernel
# knobs (ops/pallas_cycles.py module level) plus XLA_FLAGS -- two
# processes under different XLA flags compile genuinely different
# executables (fast-math, host device count, ...) and must never share
# an entry
_TRACE_ENV_KNOBS = ("TPU_KERNEL_ROWSKIP", "TPU_TASKS_UNCOND",
                    "TPU_KERNEL_ABLATE", "XLA_FLAGS")


class CompileCacheError(RuntimeError):
    """An entry failed verification (truncated/corrupt/unreadable)."""


class CompileCacheMiss(CompileCacheError):
    """No entry at this key -- the ordinary cold path, distinguished
    structurally from corruption so call() never has to grep an error
    message to decide whether to journal a loud fallback."""


class CompileCacheStale(CompileCacheError):
    """An entry is intact but was built by a different toolchain or
    code version -- refused loudly, then overwritten by the fresh
    compile (the self-healing flavor of invalidation)."""


# ---------------------------------------------------------------------------
# process-level state: the loaded-program memo and the observability counters
# ---------------------------------------------------------------------------

_memo: dict = {}                # key -> jax.stages.Compiled
_key_failed_tags: set = set()   # tags whose key computation failed (once)
_counters = {
    "hits": 0,                  # programs deserialized from disk
    "misses": 0,                # programs compiled fresh (entry absent)
    "errors": 0,                # corrupt/stale/store-failure fallbacks
    "load_ms": 0.0,
    "compile_ms": 0.0,
    "store_ms": 0.0,
}


def cache_load_count() -> int:
    """How many programs this process deserialized from the persistent
    cache -- the scan_trace_count()-style probe: a warm serve child
    should run every chunk shape with cache_load_count() == len(shapes)
    and scan_trace_count() == 0 (zero-trace warmup)."""
    return _counters["hits"]


def cache_miss_count() -> int:
    return _counters["misses"]


def cache_error_count() -> int:
    return _counters["errors"]


def counters() -> dict:
    return dict(_counters)


def reset_for_tests():
    """Clear the memos + counters (tests simulate a fresh process)."""
    _memo.clear()
    _key_memo.clear()
    _params_digests.clear()
    _key_failed_tags.clear()
    for k in _counters:
        _counters[k] = 0 if isinstance(_counters[k], int) else 0.0


def prom_families() -> list:
    """The avida_compile_cache_* exposition families, render_families
    shaped.  Empty when the process never touched the cache, so
    cache-off runs publish byte-identical metrics files."""
    c = _counters
    if not (c["hits"] or c["misses"] or c["errors"]):
        return []
    return [
        ("avida_compile_cache_hits_total", "counter",
         "programs deserialized from the persistent compile cache",
         c["hits"]),
        ("avida_compile_cache_misses_total", "counter",
         "programs compiled fresh (cache entry absent)", c["misses"]),
        ("avida_compile_cache_errors_total", "counter",
         "corrupt/stale/store-failure fallbacks (each journaled as a "
         "compile_cache event)", c["errors"]),
        ("avida_compile_cache_load_ms_total", "counter",
         "milliseconds spent deserializing cached executables",
         round(c["load_ms"], 1)),
        ("avida_compile_cache_compile_ms_total", "counter",
         "milliseconds spent in fresh trace+compile on cache misses",
         round(c["compile_ms"], 1)),
    ]


# ---------------------------------------------------------------------------
# configuration: kill switch + cache root resolution (host-only)
# ---------------------------------------------------------------------------

def enabled(cfg=None) -> bool:
    """TPU_COMPILE_CACHE=0 anywhere -- environment OR config -- is a
    hard kill switch; the cache is on only when neither side disables
    it (config default 1)."""
    if os.environ.get("TPU_COMPILE_CACHE", "1") == "0":
        return False
    if cfg is not None and not int(cfg.get("TPU_COMPILE_CACHE", 1)):
        return False
    return True


def cache_dir(cfg=None) -> str:
    """Config TPU_COMPILE_CACHE_DIR beats env beats the per-user
    default.  The fleet orchestrator points children at
    SPOOL/compile-cache so sibling class children share one store."""
    if cfg is not None:
        d = str(cfg.get("TPU_COMPILE_CACHE_DIR", "-") or "-")
        if d not in ("-", ""):
            return d
    d = os.environ.get("TPU_COMPILE_CACHE_DIR", "")
    if d:
        return d
    base = os.environ.get("XDG_CACHE_HOME",
                          os.path.join(os.path.expanduser("~"), ".cache"))
    return os.path.join(base, "avida_tpu", "compile")


# ---------------------------------------------------------------------------
# key + code digest
# ---------------------------------------------------------------------------

_CODE_DIGEST = None


def code_digest() -> str:
    """Digest of the in-repo engine source: sha256 over every
    avida_tpu/**/*.py file's contents plus the recorded update_step
    jaxpr snapshot (scripts/jaxpr_digest.json -- check_jaxpr.py's
    digest, the code-version component ROADMAP asked to reuse).  ANY
    source edit therefore invalidates every cached executable loudly
    (manifest check at load) -- conservative by design: a stale
    executable that runs is worse than a spurious recompile."""
    global _CODE_DIGEST
    if _CODE_DIGEST is not None:
        return _CODE_DIGEST
    repo = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    pkg = os.path.join(repo, "avida_tpu")
    h = hashlib.sha256()
    # sorted() materializes the whole walk before iteration, so the
    # root-path sort alone fixes the traversal order deterministically
    for root, _dirs, files in sorted(os.walk(pkg)):
        for name in sorted(files):
            if not name.endswith(".py"):
                continue
            path = os.path.join(root, name)
            h.update(os.path.relpath(path, pkg).encode())
            try:
                with open(path, "rb") as f:
                    h.update(hashlib.sha256(f.read()).digest())
            except OSError:
                h.update(b"?")
    snap = os.path.join(repo, "scripts", "jaxpr_digest.json")
    try:
        with open(snap, "rb") as f:
            h.update(f.read())
    except OSError:
        pass
    _CODE_DIGEST = h.hexdigest()
    return _CODE_DIGEST


def _aval_specs(dyn_args) -> list:
    """(shape, dtype) of every dynamic-argument leaf, in tree order --
    pins the world geometry, memory cap, serve width W and the PRNG key
    dtype into the key."""
    import jax

    leaves = jax.tree_util.tree_leaves(dyn_args)
    return [[list(getattr(x, "shape", ())), str(getattr(x, "dtype", type(x)))]
            for x in leaves]


def _toolchain() -> dict:
    import jax
    import jaxlib

    dev = jax.devices()[0]
    return {
        "jax": jax.__version__,
        "jaxlib": jaxlib.__version__,
        "platform": dev.platform,
        "device_kind": dev.device_kind,
        "device_count": jax.device_count(),
        "x64": bool(jax.config.jax_enable_x64),
        "code": code_digest(),
    }


_params_digests: dict = {}
_key_memo: dict = {}


def _params_digest(params) -> str:
    """sha256 of the WorldParams repr, memoized on the (hashable,
    all-static) params object -- the repr walks every instruction-set
    tuple, far too heavy to redo once per chunk in the update loop."""
    d = _params_digests.get(params)
    if d is None:
        d = hashlib.sha256(repr(params).encode()).hexdigest()
        _params_digests[params] = d
    return d


def cache_key(tag: str, params, chunk, dyn_args) -> str:
    """The entry name.  Everything that selects a DIFFERENT compiled
    program must be here; toolchain/code versions are manifest-checked
    instead (module header).  Memoized per (tag, params, chunk, aval
    set): the scan drivers call this once per CHUNK, and everything in
    the key is frozen per process (the env knobs are read at
    pallas_cycles import; devices cannot change under a live backend).
    """
    import jax

    avals = tuple((tuple(getattr(x, "shape", ())),
                   str(getattr(x, "dtype", type(x))))
                  for x in jax.tree_util.tree_leaves(dyn_args))
    memo_key = (tag, params, int(chunk), avals)
    key = _key_memo.get(memo_key)
    if key is not None:
        return key
    dev = jax.devices()[0]
    body = {
        "tag": tag,
        "params": _params_digest(params),
        "chunk": int(chunk),
        "avals": [[list(s), d] for s, d in avals],
        "platform": dev.platform,
        "device_kind": dev.device_kind,
        "device_count": jax.device_count(),
        "x64": bool(jax.config.jax_enable_x64),
        "env": {k: os.environ.get(k, "") for k in _TRACE_ENV_KNOBS},
    }
    text = json.dumps(body, sort_keys=True)
    key = hashlib.sha256(text.encode()).hexdigest()[:40]
    _key_memo[memo_key] = key
    return key


# ---------------------------------------------------------------------------
# the on-disk entry store (pure host; checkpoint atomic-publish pattern)
# ---------------------------------------------------------------------------

def entry_path(root: str, key: str) -> str:
    return os.path.join(root, key)


def list_entries(root: str) -> list:
    """Paths of all published entries under one cache root (dirs whose
    manifest declares our format), sorted oldest-first by mtime."""
    if not os.path.isdir(root):
        return []
    out = []
    for name in os.listdir(root):
        p = os.path.join(root, name)
        if name.startswith((".tmp-", ".old-")) or not os.path.isdir(p):
            continue
        if os.path.exists(os.path.join(p, MANIFEST)):
            out.append(p)
    return sorted(out, key=lambda p: (os.path.getmtime(p), p))


# the manifest fields that decide whether an existing same-key entry is
# EQUIVALENT to what we are about to publish (write_entry's skip test)
# -- the same set _verify_toolchain enforces at load time
_TOOLCHAIN_FIELDS = ("jax", "jaxlib", "platform", "device_kind",
                     "device_count", "x64", "code")


def write_entry(root: str, key: str, payload: bytes, trees: bytes,
                meta: dict) -> str:
    """Atomically publish one cache entry (the checkpoint
    write_generation discipline: tmp sibling, fsync everything, one
    rename).  A same-key entry that already verifies AND matches this
    publish's toolchain/code fields is left untouched: two sibling
    class children compiling the same program concurrently is the
    normal fleet warmup pattern, and yanking the winner's entry out
    from under a third child mid-load would journal a false corruption
    and re-open its compile window.  Corrupt or toolchain-stale
    entries are still replaced (the self-healing path)."""
    os.makedirs(root, exist_ok=True)
    final = entry_path(root, key)
    if os.path.isdir(final):
        try:
            existing = verify_entry(final)
            if all(existing.get(f) == meta.get(f)
                   for f in _TOOLCHAIN_FIELDS):
                return final            # a sibling already published it
        except CompileCacheError:
            pass                        # corrupt/foreign: replace below
    tmp = os.path.join(root, f".tmp-{key}.{os.getpid()}")
    if os.path.exists(tmp):
        shutil.rmtree(tmp)
    os.makedirs(tmp)
    manifest = {
        "format": FORMAT,
        "key": key,
        "created_at": time.time(),
        "files": {},
        **meta,
    }
    for name, blob in ((EXEC_FILE, payload), (TREES_FILE, trees)):
        fpath = os.path.join(tmp, name)
        with open(fpath, "wb") as f:
            f.write(blob)
            f.flush()
            os.fsync(f.fileno())
        manifest["files"][name] = {
            "size": len(blob),
            "crc32": zlib.crc32(blob) & 0xFFFFFFFF,
        }
    mpath = os.path.join(tmp, MANIFEST)
    with open(mpath, "w") as f:
        json.dump(manifest, f, indent=1)
        f.write("\n")
        f.flush()
        os.fsync(f.fileno())
    _fsync_dir(tmp)
    aside = None
    if os.path.exists(final):
        aside = os.path.join(root, f".old-{key}.{os.getpid()}")
        if os.path.exists(aside):
            shutil.rmtree(aside)
        os.rename(final, aside)
    os.rename(tmp, final)
    _fsync_dir(root)
    if aside is not None:
        shutil.rmtree(aside, ignore_errors=True)
    _sweep_debris(root)
    return final


# another process's in-flight .tmp- entry must survive our janitor: the
# fleet points EVERY child at one SPOOL/compile-cache, and two cold
# class children publishing concurrently is the normal warmup pattern,
# not an edge case.  Own-pid debris is always stale (we only sweep
# after our own publish); foreign debris is only swept once it is old
# enough that its writer is surely dead or wedged.
_DEBRIS_MAX_AGE_SEC = 3600.0


def _sweep_debris(root: str) -> list:
    removed = []
    mine = f".{os.getpid()}"
    now = time.time()
    for d in os.listdir(root):
        if not d.startswith((".tmp-", ".old-")):
            continue
        p = os.path.join(root, d)
        if not d.endswith(mine):
            try:
                if now - os.path.getmtime(p) < _DEBRIS_MAX_AGE_SEC:
                    continue            # possibly another writer, live
            except OSError:
                continue
        shutil.rmtree(p, ignore_errors=True)
        removed.append(p)
    return removed


def verify_entry(path: str) -> dict:
    """Manifest + CRC sweep of one entry; returns the manifest.
    Raises CompileCacheError on any missing/truncated/corrupt piece."""
    mpath = os.path.join(path, MANIFEST)
    if not os.path.exists(mpath):
        raise CompileCacheError(f"{path}: no {MANIFEST}")
    try:
        with open(mpath) as f:
            manifest = json.load(f)
    except (json.JSONDecodeError, OSError) as e:
        raise CompileCacheError(f"{path}: torn or unreadable manifest ({e})")
    if manifest.get("format") != FORMAT:
        raise CompileCacheStale(
            f"{path}: entry format {manifest.get('format')!r} "
            f"(want {FORMAT})")
    for name, spec in manifest.get("files", {}).items():
        fpath = os.path.join(path, name)
        if not os.path.exists(fpath):
            raise CompileCacheError(f"{path}: missing {name}")
        if os.path.getsize(fpath) != spec["size"]:
            raise CompileCacheError(f"{path}: truncated {name}")
        crc = _crc32_file(fpath)
        if crc != spec["crc32"]:
            raise CompileCacheError(
                f"{path}: CRC mismatch on {name} "
                f"({crc:#010x} != {spec['crc32']:#010x})")
    return manifest


def _verify_toolchain(path: str, manifest: dict):
    """The loud invalidation gate: refuse an intact entry built by a
    different jax/jaxlib, backend or code version.  Runs BEFORE any
    byte of the pickled payload is touched -- unpickling another
    toolchain's treedefs is exactly the kind of undefined behavior this
    cache exists to never exercise."""
    cur = _toolchain()
    for field, label in (("jax", "jax version"),
                         ("jaxlib", "jaxlib version"),
                         ("platform", "backend platform"),
                         ("device_kind", "device kind"),
                         ("device_count", "device count"),
                         ("x64", "x64 flag"),
                         ("code", "code digest")):
        want, have = manifest.get(field), cur[field]
        if want != have:
            raise CompileCacheStale(
                f"{path}: stale {label} ({want!r} != {have!r})")


def load_entry(root: str, key: str):
    """(compiled, manifest) for one verified, toolchain-current entry.
    Any failure raises CompileCacheError/CompileCacheStale -- callers
    fall back to a fresh trace and journal the reason."""
    from jax.experimental import serialize_executable as _se

    path = entry_path(root, key)
    if not os.path.isdir(path):
        raise CompileCacheMiss(f"{path}: no entry")
    manifest = verify_entry(path)
    _verify_toolchain(path, manifest)
    with open(os.path.join(path, TREES_FILE), "rb") as f:
        in_tree, out_tree = pickle.loads(f.read())
    with open(os.path.join(path, EXEC_FILE), "rb") as f:
        payload = f.read()
    compiled = _se.deserialize_and_load(payload, in_tree, out_tree)
    return compiled, manifest


def prune(root: str, keep: int = 0) -> list:
    """Drop cache entries beyond the newest `keep` (0 = drop all), plus
    stale .tmp-/.old- publish debris.  "Newest" is by directory mtime,
    which load_entry refreshes on every successful load -- retention
    keeps the most recently USED programs, not the most recently
    published ones.  Returns removed paths.  Debris
    goes through the same live-writer age guard as write_entry's
    janitor (_sweep_debris): pruning a LIVE fleet's shared store must
    not destroy a sibling child's in-flight publish."""
    removed = []
    if not os.path.isdir(root):
        return removed
    entries = list_entries(root)
    drop = entries if keep <= 0 else entries[:-keep]
    for p in drop:
        shutil.rmtree(p, ignore_errors=True)
        removed.append(p)
    removed += _sweep_debris(root)
    return removed


def looks_like_cache_dir(path: str) -> bool:
    """Does `path` hold at least one of our entries?  (cache_tool
    --all's tree-walk screen, the ckpt_tool.prune_all pattern.)"""
    if not os.path.isdir(path):
        return False
    for name in os.listdir(path):
        mpath = os.path.join(path, name, MANIFEST)
        try:
            if os.path.exists(mpath):
                with open(mpath) as f:
                    if json.load(f).get("format") == FORMAT:
                        return True
        except (OSError, ValueError):
            continue
    return False


# ---------------------------------------------------------------------------
# the cached call (the only jax-touching entry point)
# ---------------------------------------------------------------------------

def call(jit_fn, tag: str, args: tuple, *, static_argnums=(0, 2),
         cfg=None, log=None, sig: str | None = None):
    """Run `jit_fn(*args)` through the persistent program cache.

    args is the FULL positional tuple (statics included, jit call
    order); static_argnums mirrors the jit wrapper's.  Disabled (kill
    switch) -> the plain jit call, byte-for-byte the pre-cache path.
    Process memo hit -> call the loaded executable (zero host work).
    Disk hit -> verify CRCs + toolchain, deserialize, call.  Miss or
    any verification failure -> fresh `lower().compile()` (identical
    programs to what jit itself builds -- bit-exactness is by
    construction and proven in tests/test_compile_cache.py), then
    serialize + atomically publish so the next process loads it.

    `log(**fields)` (World/ServeBatch pass a runlog emit_event shim)
    journals every load / store / fallback as a `compile_cache` event.
    Never lets a cache failure take down the run: the jit path is the
    universal fallback.

    Performance attribution (observability/profiler.py): when the
    TPU_PROFILE plane is armed, every program construction -- fresh
    compile, disk load, or the cache-disabled AOT flavor below --
    reports its XLA cost/memory analysis to profiler.note_program,
    keyed by this cache's signature.  Stores embed the report in the
    entry manifest (`perf`), so a cached load reports numbers EQUAL to
    the fresh compile that produced it."""
    from avida_tpu.observability import profiler as _profiler

    if not enabled(cfg):
        if not _profiler.enabled(cfg):
            return jit_fn(*args)
        # cache disabled but profiling armed: take the AOT flavor of
        # the plain jit path (lower().compile() builds the identical
        # program jit itself would -- bit-exactness by construction,
        # tests/test_compile_cache.py), memoized in _memo, so the
        # jax.stages.Compiled handle is available for cost/memory
        # capture without a double compile.  Key failures fall back to
        # plain jit: attribution must never block the run.
        statics = sorted(static_argnums)
        dyn_args = tuple(a for i, a in enumerate(args)
                         if i not in statics)
        try:
            key = cache_key(tag, args[statics[0]],
                            args[statics[1]] if len(statics) > 1 else 0,
                            dyn_args)
        except Exception:
            return jit_fn(*args)
        compiled = _memo.get(key)
        if compiled is None:
            compiled = jit_fn.lower(*args).compile()
            _memo[key] = compiled
        # note on memo hits too (dedup inside): a program memoized
        # BEFORE the plane's report was (re)armed must still appear
        _profiler.note_program(
            key, tag, args[statics[1]] if len(statics) > 1 else 0,
            compiled, source="aot", cfg=cfg)
        return compiled(*dyn_args)

    statics = sorted(static_argnums)
    params = args[statics[0]]
    chunk = args[statics[1]] if len(statics) > 1 else 0
    dyn_args = tuple(a for i, a in enumerate(args) if i not in statics)
    try:
        key = cache_key(tag, params, chunk, dyn_args)
    except Exception as e:                      # never block the run
        # counted + journaled ONCE per tag: a persistent key failure
        # would otherwise spam one journal line per chunk while the
        # errors counter showed a healthy cache-off process
        if tag not in _key_failed_tags:
            _key_failed_tags.add(tag)
            _counters["errors"] += 1
            _note(log, action="key_failed", tag=tag, error=str(e))
        return jit_fn(*args)

    compiled = _memo.get(key)
    if compiled is not None:
        _profiler.note_program(key, tag, chunk, compiled,
                               source="memo", cfg=cfg)
        return compiled(*dyn_args)

    root = cache_dir(cfg)
    t0 = time.monotonic()
    loaded = None
    try:
        loaded, _manifest = load_entry(root, key)
    except CompileCacheMiss:
        pass                                    # the ordinary cold path
    except CompileCacheStale as e:
        _counters["errors"] += 1
        _note(log, action="stale", tag=tag, key=key, error=str(e))
    except CompileCacheError as e:
        _counters["errors"] += 1
        _note(log, action="corrupt", tag=tag, key=key, error=str(e))
    except Exception as e:
        # deserialization itself failed (runtime refused the payload):
        # same recovery as corruption -- fresh trace, overwrite
        _counters["errors"] += 1
        _note(log, action="deserialize_failed", tag=tag, key=key,
              error=str(e))
    if loaded is not None:
        ms = (time.monotonic() - t0) * 1000.0
        _counters["hits"] += 1
        _counters["load_ms"] += ms
        _memo[key] = loaded
        try:
            # touch: list_entries/prune order by mtime, and "recently
            # LOADED" must count as recently used -- otherwise
            # `--prune --keep N` evicts the fleet's hottest programs
            # just because they were published first
            os.utime(entry_path(root, key))
        except OSError:
            pass
        _note(log, action="load", tag=tag, key=key, chunk=int(chunk),
              ms=round(ms, 1))
        # attribution capture: the manifest's stored `perf` block (when
        # the storing process was profiling) keeps cached == fresh
        _profiler.note_program(key, tag, chunk, loaded,
                               source="cache_load", cfg=cfg,
                               manifest=_manifest)
        # EXECUTION stays outside the try: a runtime error from the
        # program itself must propagate exactly like the jit path's
        # (the donated inputs are consumed -- retrying against them
        # would mask the real error with "Array has been deleted")
        return loaded(*dyn_args)

    t0 = time.monotonic()
    lowered = jit_fn.lower(*args)
    compiled = lowered.compile()
    compile_ms = (time.monotonic() - t0) * 1000.0
    _counters["misses"] += 1
    _counters["compile_ms"] += compile_ms
    _memo[key] = compiled
    _note(log, action="compile", tag=tag, key=key, chunk=int(chunk),
          ms=round(compile_ms, 1))
    _profiler.note_program(key, tag, chunk, compiled, source="compile",
                           cfg=cfg)

    t0 = time.monotonic()
    try:
        from jax.experimental import serialize_executable as _se
        payload, in_tree, out_tree = _se.serialize(compiled)
        trees = pickle.dumps((in_tree, out_tree))
        meta = {
            "tag": tag,
            "chunk": int(chunk),
            "avals": _aval_specs(dyn_args),
            "params_digest": _params_digest(params),
            "compile_ms": round(compile_ms, 1),
            **_toolchain(),
        }
        if sig:
            meta["sig"] = sig
        if _profiler.enabled(cfg):
            # carry the cost/memory report in the manifest so a LOADED
            # entry attributes identically to the fresh compile (the
            # profiler's cached-vs-fresh equality contract)
            meta["perf"] = _profiler.program_perf(compiled)
        write_entry(root, key, payload, trees, meta)
        _counters["store_ms"] += (time.monotonic() - t0) * 1000.0
        _note(log, action="store", tag=tag, key=key,
              bytes=len(payload))
    except Exception as e:
        # unserializable executable (PJRT serialization support varies
        # by backend: ValueError / NotImplementedError / XlaRuntimeError
        # have all been seen in the wild), unpicklable treedef, or an
        # unwritable cache root: the run proceeds on the in-memory
        # program -- a store failure must never take down the run
        _counters["errors"] += 1
        _note(log, action="store_failed", tag=tag, key=key, error=str(e))
    return compiled(*dyn_args)


def _note(log, **fields):
    if log is None:
        return
    try:
        log(**fields)
    except Exception:
        pass
