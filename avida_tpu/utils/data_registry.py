"""Data provider/recorder registry (tpu-native equivalent of the
reference's Avida::Data layer).

The reference decouples stat production from consumption: providers
announce typed values under dotted IDs, a Manager resolves IDs on demand,
and recorders (file writers, viewers) subscribe to ID sets
(include/public/avida/data/Manager.h:40-85, Provider.h:39-48,
Recorder.h:39-46).  Here the same protocol sits over the device-side
`summarize()` reductions: a provider is a host callable pulling from the
cached per-update summary (one device round-trip per update, shared by
every consumer), and a recorder is fed resolved rows at its print
cadence.  New .dat writers register providers/recorders instead of
editing World (the round-4 review's directive #9).

The generic `PrintData <file> <id,id,...>` action (cActionPrintData,
actions/PrintActions.cc:389-408) is the proof: any registered set of IDs
becomes a .dat file with no new World code.
"""

from __future__ import annotations

import numpy as np

from avida_tpu.utils import output as output_mod


class DataManager:
    """ID -> provider registry + recorder attachment (Data::Manager)."""

    def __init__(self, world):
        self.world = world
        self._providers = {}        # id -> (description, fn(world) -> value)
        self._recorders = []

    # -- provider side (Data::Provider / ArgumentedProvider) --
    def register(self, data_id: str, description: str, fn):
        self._providers[data_id] = (description, fn)

    def available(self):
        return sorted(self._providers)

    def describe(self, data_id: str) -> str:
        return self._providers[data_id][0]

    def resolve(self, data_id: str):
        if data_id not in self._providers:
            raise KeyError(
                f"no data provider registered for {data_id!r} "
                f"(available: {', '.join(self.available())})")
        return self._providers[data_id][1](self.world)

    # -- recorder side (Data::Recorder) --
    def attach(self, recorder):
        self._recorders.append(recorder)

    def process(self, update: int):
        """Feed every attached recorder (called at its own cadence by the
        event loop; the reference calls recorders once per update)."""
        for r in self._recorders:
            r.record(update, self)


class DatRecorder:
    """A .dat-file recorder over a list of (data_id, column description).

    Golden-format output via utils.output.DatFile; one row per record()
    call (the caller controls cadence through the event system)."""

    def __init__(self, data_dir: str, filename: str, title: str, specs,
                 preamble=None):
        self.specs = list(specs)
        self._file = output_mod.DatFile(
            f"{data_dir}/{filename}", title,
            [d for _, d in self.specs], preamble=preamble)

    def record(self, update: int, manager: DataManager):
        self._file.write_row(
            [manager.resolve(i) if i != "core.update" else update
             for i, _ in self.specs])

    def close(self):
        self._file.close()


def register_standard_providers(mgr: DataManager):
    """The core provider set, sourced from World._summary() (device
    reductions), the systematics manager, and host accumulators.  IDs
    follow the reference's dotted style (data/Manager.cc core.* space)."""
    S = lambda key: (lambda w: float(w._summary()[key]))          # noqa: E731
    Si = lambda key: (lambda w: int(w._summary()[key]))           # noqa: E731

    mgr.register("core.update", "Update", lambda w: w.update)
    mgr.register("core.world.organisms", "Count of organisms in the world",
                 Si("num_organisms"))
    mgr.register("core.world.ave_fitness", "Average Fitness",
                 S("ave_fitness"))
    mgr.register("core.world.ave_merit", "Average Merit", S("ave_merit"))
    mgr.register("core.world.ave_gestation_time", "Average Gestation Time",
                 S("ave_gestation"))
    mgr.register("core.world.ave_generation", "Average Generation",
                 S("ave_generation"))
    mgr.register("core.world.ave_age", "Average Organism Age", S("ave_age"))
    mgr.register("core.world.max_fitness", "Maximum Fitness",
                 S("max_fitness"))
    mgr.register("core.world.births", "Births this update",
                 Si("births_this_update"))
    mgr.register("core.world.genotypes",
                 "Count of genotypes in the world",
                 lambda w: w.systematics.num_genotypes if w.systematics
                 else 0)


def instruction_abundance(world):
    """Per-opcode instruction counts across all live genomes
    (cActionPrintInstructionAbundanceHistogram,
    actions/PrintActions.cc: sums cStats inst counts): one masked
    bincount over the opcode plane."""
    st = world.state
    genome = np.asarray(st.genome) & 63
    glen = np.asarray(st.genome_len)
    alive = np.asarray(st.alive)
    in_genome = (np.arange(genome.shape[1])[None, :] < glen[:, None]) \
        & alive[:, None]
    return np.bincount(genome[in_genome].ravel(),
                       minlength=world.params.num_insts)


def depth_histogram(world):
    """genotype depth -> count of genotypes (cActionPrintDepthHistogram)."""
    out = {}
    if world.systematics:
        for g in world.systematics.live_genotypes():
            out[g.depth] = out.get(g.depth, 0) + 1
    return dict(sorted(out.items()))


def abundance_histogram(world):
    """genotype abundance -> count of genotypes with that abundance
    (cActionPrintGenotypeAbundanceHistogram)."""
    out = {}
    if world.systematics:
        for g in world.systematics.live_genotypes():
            out[g.num_units] = out.get(g.num_units, 0) + 1
    return dict(sorted(out.items()))
