"""Deterministic, seeded fault injection: the `TPU_FAULT` spec.

The only way to trust the self-healing machinery (service/supervisor.py,
checkpoint CRC fallback, the state auditor) is to inject the failures it
claims to survive -- deterministically, so a chaos test that passes
today reproduces bit-exactly tomorrow.  Every fault is host-side except
the `nan` kind, which corrupts device state inside the jitted update
behind a static WorldParams flag (same discipline as the flight
recorder: with TPU_FAULT unset the `update_step` jaxpr digest is
unchanged, scripts/check_jaxpr.py).

Spec grammar (config var or environment variable `TPU_FAULT`):

    spec    := fault (";" fault)*
    fault   := kind [":" args] ["@" trigger "=" INT]
    args    := arg ("," arg)*
    arg     := KEY "=" VALUE | VALUE          (bare VALUE -> the kind's
                                               default key, see below)
    trigger := "update" | "chunk"

Kinds (default arg key in brackets):

    crash            raise FaultInjected at a run-loop chunk boundary
                     (an unhandled exception: nonzero exit, no final
                     checkpoint beyond the last auto-save)
    sigkill          SIGKILL our own process at a boundary -- the
                     abrupt host death: no drain, no flush, no atexit
    hang [sec]       stop making progress at a boundary (the heartbeat
                     goes stale; the supervisor's watchdog must kill
                     us).  `hang:sec=5` stalls transiently instead
    corrupt-ckpt [leaf]   after a checkpoint save, flip one seeded
                     payload byte of `state.<leaf>.npy` (default leaf
                     `merit`) in the just-published generation --
                     CRC-detectable corruption at rest
    torn-manifest    after a checkpoint save, truncate the generation's
                     manifest.json at a seeded fraction (a manifest
                     torn mid-write)
    corrupt-digest   after a checkpoint save, flip the manifest's
                     stored `state_digest` (payload CRCs untouched):
                     the loader-corruption class -- bytes verify, the
                     decoded state would not.  Caught by the resume
                     digest verification and `ckpt_tool --verify`
                     (DIGEST MISMATCH), never by CRC
    nan [leaf]       device-side: set `st.<leaf>[cell]` (default leaf
                     `merit`, default cell the injection cell) to NaN
                     at `@update=N` inside the jitted update.  Requires
                     an `@update` trigger; caught by the state auditor
                     and the flight recorder's anomaly events
    bitflip [leaf]   device-side: XOR one bit (default bit 0, the low
                     mantissa bit -- finite, in-bounds, invisible to
                     every auditor invariant) of `st.<leaf>[cell]` at
                     `@update=N` inside the jitted update, modeling a
                     real SDC event.  `bitflip:merit,cell=5,bit=3
                     @update=40`.  Requires `@update`; caught ONLY by
                     the integrity plane's sampled shadow re-execution
                     (TPU_SCRUB_EVERY), because the shadow replay runs
                     the PRISTINE program -- an injected device fault
                     models a transient hardware event, which by
                     definition fires in the live execution only

Triggers: `@update=N` fires at the first chunk boundary whose update
counter is >= N (save kinds: the first save at update >= N); `@chunk=K`
at the K-th boundary of THIS process (1-based).  Boundary kinds default
to the first boundary, save kinds to the first save.  Each fault fires
at most once per process.

Seeding: every fault gets its own `random.Random` stream derived from
(TPU_FAULT_SEED, fault index, fault text), so byte positions and
truncation points are reproducible run-to-run and independent of the
run's own PRNG streams.
"""

from __future__ import annotations

import os
import random
import signal
import time
import zlib

KINDS = ("crash", "sigkill", "hang", "corrupt-ckpt", "torn-manifest",
         "corrupt-digest", "nan", "bitflip")
_DEFAULT_KEY = {"corrupt-ckpt": "leaf", "nan": "leaf", "bitflip": "leaf",
                "hang": "sec"}
_BOUNDARY_KINDS = ("crash", "sigkill", "hang")
_SAVE_KINDS = ("corrupt-ckpt", "torn-manifest", "corrupt-digest")
NAN_LEAVES = ("merit", "fitness")
# the in-bounds SDC kind targets float32 leaves so a low-mantissa flip
# stays finite/non-negative and sails past every audit_state invariant
BITFLIP_LEAVES = ("merit", "fitness")


class FaultInjected(RuntimeError):
    """The `crash` fault kind: a simulated unexpected failure."""


class Fault:
    """One parsed fault: kind, args, optional trigger, its own RNG."""

    def __init__(self, kind: str, args: dict, trigger, text: str):
        self.kind = kind
        self.args = args
        self.trigger = trigger          # None | ("update"|"chunk", int)
        self.text = text
        self.rng: random.Random | None = None
        self.fired = False

    def due(self, update: int, chunk: int) -> bool:
        if self.trigger is None:
            return True
        name, val = self.trigger
        return (update >= val) if name == "update" else (chunk >= val)

    def __repr__(self):
        return f"Fault({self.text!r})"


def _parse_one(text: str) -> Fault:
    part = text
    trigger = None
    if "@" in part:
        part, trig = part.split("@", 1)
        name, eq, val = trig.partition("=")
        if not eq or name not in ("update", "chunk"):
            raise ValueError(
                f"fault {text!r}: trigger must be @update=N or @chunk=K")
        trigger = (name, int(val))
    kind, _, argstr = part.partition(":")
    kind = kind.strip()
    if kind not in KINDS:
        raise ValueError(f"unknown fault kind {kind!r} in {text!r} "
                         f"(known: {', '.join(KINDS)})")
    args = {}
    if argstr:
        for tok in argstr.split(","):
            k, eq, v = tok.partition("=")
            if eq:
                args[k.strip()] = v.strip()
            elif kind in _DEFAULT_KEY:
                args[_DEFAULT_KEY[kind]] = k.strip()
            else:
                raise ValueError(
                    f"fault {text!r}: kind {kind!r} takes no bare argument")
    if kind in _SAVE_KINDS and trigger is not None \
            and trigger[0] != "update":
        raise ValueError(
            f"fault {text!r}: save-time kinds ({', '.join(_SAVE_KINDS)}) "
            f"fire on checkpoint publishes, which have no chunk index -- "
            f"use @update=N or no trigger (first save)")
    if kind in ("nan", "bitflip"):
        if trigger is None or trigger[0] != "update":
            raise ValueError(f"fault {text!r}: {kind} requires @update=N "
                             f"(it is injected inside the jitted update)")
        leaves = NAN_LEAVES if kind == "nan" else BITFLIP_LEAVES
        leaf = args.get("leaf", "merit")
        if leaf not in leaves:
            raise ValueError(f"fault {text!r}: {kind} leaf must be one of "
                             f"{leaves} (got {leaf!r})")
    if kind == "bitflip":
        bit = int(args.get("bit", 0))
        if not 0 <= bit < 32:
            raise ValueError(f"fault {text!r}: bit must be in [0, 32)")
    if kind == "hang" and "sec" in args:
        float(args["sec"])              # validate now, not at fire time
    return Fault(kind, args, trigger, text)


def parse_spec(spec: str, seed: int = 0) -> list:
    """Parse a full TPU_FAULT spec into seeded Fault objects."""
    faults = []
    parts = [p.strip() for p in spec.split(";")]
    for i, part in enumerate(p for p in parts if p):
        f = _parse_one(part)
        f.rng = random.Random(zlib.crc32(f"{seed}|{i}|{part}".encode()))
        faults.append(f)
    if not faults:
        raise ValueError(f"empty TPU_FAULT spec {spec!r}")
    return faults


def active_spec(cfg) -> str | None:
    """The effective fault spec: the TPU_FAULT config var (settable via
    `-set TPU_FAULT ...`) or, when ABSENT there, the TPU_FAULT
    environment variable (how the supervisor injects per-boot faults
    into its children).  An explicit config value of '-', '' or '0'
    means OFF and wins over the environment -- `-set TPU_FAULT 0` must
    be able to disable a fault exported in the shell."""
    val = cfg.get("TPU_FAULT", None)
    if val is None:
        val = os.environ.get("TPU_FAULT", "")
    val = str(val)
    return val if val not in ("-", "", "0") else None


def nan_param(cfg) -> tuple:
    """The static WorldParams.fault_nan tuple (leaf, cell, update) for a
    `nan:` fault in the active spec, or () -- in which case update_step
    traces the identical program (scripts/check_jaxpr.py digest)."""
    spec = active_spec(cfg)
    if not spec:
        return ()
    for f in parse_spec(spec):
        if f.kind != "nan":
            continue
        leaf = f.args.get("leaf", "merit")
        num_cells = int(cfg.WORLD_X) * int(cfg.WORLD_Y)
        cell = int(f.args.get("cell", num_cells // 2))
        if not 0 <= cell < num_cells:
            raise ValueError(f"nan fault cell {cell} outside [0, {num_cells})")
        return (leaf, cell, int(f.trigger[1]))
    return ()


def nan_phase(params, st, update_no):
    """Device-side NaN injection (called from ops/update.update_step and
    observability/staged.StagedUpdate ONLY when params.fault_nan is
    set): poison one float leaf entry at the trigger update.  Pure
    jax -- traced into the update program behind the static gate."""
    import jax.numpy as jnp
    leaf, cell, at_update = params.fault_nan
    arr = getattr(st, leaf)
    poisoned = arr.at[cell].set(jnp.asarray(float("nan"), arr.dtype))
    return st.replace(**{leaf: jnp.where(jnp.equal(update_no, at_update),
                                         poisoned, arr)})


def bitflip_param(cfg) -> tuple:
    """The static WorldParams.fault_bitflip tuple (leaf, cell, bit,
    update) for a `bitflip:` fault in the active spec, or () -- in which
    case update_step traces the identical program (the fault_nan
    discipline; scripts/check_jaxpr.py digest)."""
    spec = active_spec(cfg)
    if not spec:
        return ()
    for f in parse_spec(spec):
        if f.kind != "bitflip":
            continue
        leaf = f.args.get("leaf", "merit")
        num_cells = int(cfg.WORLD_X) * int(cfg.WORLD_Y)
        cell = int(f.args.get("cell", num_cells // 2))
        if not 0 <= cell < num_cells:
            raise ValueError(
                f"bitflip fault cell {cell} outside [0, {num_cells})")
        return (leaf, cell, int(f.args.get("bit", 0)), int(f.trigger[1]))
    return ()


def bitflip_phase(params, st, update_no):
    """Device-side single-bit flip (the modeled SDC event): XOR one bit
    of one float leaf entry at the trigger update, inside the jitted
    update behind the static params.fault_bitflip gate.  The default
    bit 0 (low mantissa) keeps the value finite and in-bounds -- the
    corruption class NO audit_state invariant can see, which is exactly
    what the integrity plane's scrub exists to catch.  The shadow
    re-execution strips this gate (World._shadow_params): a transient
    hardware fault fires in the live execution only."""
    import jax
    import jax.numpy as jnp
    leaf, cell, bit, at_update = params.fault_bitflip
    arr = getattr(st, leaf)
    word = jax.lax.bitcast_convert_type(arr[cell], jnp.uint32) \
        ^ jnp.uint32(1 << bit)
    flipped = arr.at[cell].set(
        jax.lax.bitcast_convert_type(word, arr.dtype))
    return st.replace(**{leaf: jnp.where(jnp.equal(update_no, at_update),
                                         flipped, arr)})


# ---------------------------------------------------------------------------
# host-side corruption helpers (also used directly by tests)
# ---------------------------------------------------------------------------

def corrupt_leaf(gen_path: str, leaf: str = "merit",
                 rng: random.Random | None = None) -> int:
    """Flip one seeded payload byte of state.<leaf>.npy in a published
    checkpoint generation (CRC-detectable at verify/restore time).
    Returns the flipped offset."""
    rng = rng or random.Random(0)
    fpath = os.path.join(gen_path, f"state.{leaf}.npy")
    if not os.path.exists(fpath):
        raise ValueError(f"no state.{leaf}.npy under {gen_path!r}")
    size = os.path.getsize(fpath)
    # aim past the ~128-byte .npy header so the flip lands in the payload
    lo = min(128, max(size - 1, 0))
    pos = rng.randrange(lo, size)
    with open(fpath, "r+b") as f:
        f.seek(pos)
        b = f.read(1)
        f.seek(pos)
        f.write(bytes([b[0] ^ 0x40]))
    return pos


def tear_manifest(gen_path: str, rng: random.Random | None = None) -> int:
    """Truncate a generation's manifest.json at a seeded interior
    fraction -- exactly what a crash mid-manifest-write leaves behind.
    Returns the surviving byte count."""
    rng = rng or random.Random(0)
    mpath = os.path.join(gen_path, "manifest.json")
    size = os.path.getsize(mpath)
    keep = int(size * rng.uniform(0.15, 0.85))
    os.truncate(mpath, keep)
    return keep


def corrupt_digest(gen_path: str, rng: random.Random | None = None) -> int:
    """Flip one seeded bit of the manifest's stored `state_digest`
    (written when the integrity plane is armed; a digest-off manifest
    gets a seeded bogus value) while every payload CRC stays intact --
    the at-rest model of the LOADER-corruption class: the bytes verify,
    the state they decode to would not.  Returns the new stored value.
    Caught by the resume digest verification (restore falls back past
    the generation with a `checkpoint_digest_mismatch` journal line)
    and by `ckpt_tool --verify` (DIGEST MISMATCH), never by CRC."""
    import json
    rng = rng or random.Random(0)
    mpath = os.path.join(gen_path, "manifest.json")
    with open(mpath) as f:
        manifest = json.load(f)
    old = manifest.get("state_digest")
    if old is None:
        new = rng.randrange(1, 1 << 32)
    else:
        new = int(old) ^ (1 << rng.randrange(32))
        if new == int(old):             # unreachable, but stay corrupt
            new = int(old) ^ 1
    manifest["state_digest"] = new
    with open(mpath, "w") as f:
        json.dump(manifest, f, indent=1)
        f.write("\n")
    return new


# ---------------------------------------------------------------------------
# the run-time plan (World hooks)
# ---------------------------------------------------------------------------

class FaultPlan:
    """Parsed faults + fire-once bookkeeping for one process.

    World calls `at_boundary` once per run-loop iteration (after the
    auto-save/audit hooks, so `sigkill@update=N` dies AFTER any save due
    at that boundary) and `at_save` with each just-published generation
    path."""

    def __init__(self, spec: str, seed: int = 0):
        self.spec = spec
        self.faults = parse_spec(spec, seed)
        self._chunk = 0

    def at_boundary(self, world):
        self._chunk += 1
        for f in self.faults:
            if f.fired or f.kind not in _BOUNDARY_KINDS \
                    or not f.due(world.update, self._chunk):
                continue
            f.fired = True
            self._execute(f, world)

    def at_save(self, world, gen_path: str):
        for f in self.faults:
            if f.fired or f.kind not in _SAVE_KINDS:
                continue
            if f.trigger is not None and f.trigger[0] == "update" \
                    and world.update < f.trigger[1]:
                continue
            f.fired = True
            from avida_tpu.observability.runlog import emit_event
            if f.kind == "corrupt-ckpt":
                leaf = f.args.get("leaf", "merit")
                pos = corrupt_leaf(gen_path, leaf, f.rng)
                emit_event(world, "fault_injected", kind="corrupt-ckpt",
                           spec=f.text, path=gen_path, leaf=leaf, offset=pos)
            elif f.kind == "corrupt-digest":
                val = corrupt_digest(gen_path, f.rng)
                emit_event(world, "fault_injected", kind="corrupt-digest",
                           spec=f.text, path=gen_path,
                           stored_digest=f"{val:#010x}")
            else:
                keep = tear_manifest(gen_path, f.rng)
                emit_event(world, "fault_injected", kind="torn-manifest",
                           spec=f.text, path=gen_path, kept_bytes=keep)

    def _execute(self, f: Fault, world):
        if f.kind == "sigkill":
            # the abrupt death: no runlog line, no flush -- exactly what
            # a host OOM-kill or machine loss looks like from outside
            os.kill(os.getpid(), signal.SIGKILL)
            time.sleep(60)              # unreachable: await delivery
            return
        from avida_tpu.observability.runlog import emit_event
        if f.kind == "crash":
            emit_event(world, "fault_injected", kind="crash", spec=f.text,
                       update=world.update)
            raise FaultInjected(
                f"injected crash at update {world.update} ({f.text})")
        # hang: stop making progress.  The heartbeat file goes stale and
        # the supervisor's watchdog SIGKILLs us; a finite `sec` arg
        # models a transient stall that resolves on its own instead.
        emit_event(world, "fault_injected", kind="hang", spec=f.text,
                   update=world.update)
        sec = float(f.args.get("sec", 0) or 0)
        deadline = time.time() + sec if sec > 0 else None
        while deadline is None or time.time() < deadline:
            time.sleep(0.05 if deadline is not None else 1.0)


def plan_from_config(cfg):
    """World's entry point: a FaultPlan when a spec is active, else
    None (the common case -- zero overhead, no hooks fire)."""
    spec = active_spec(cfg)
    if spec is None:
        return None
    return FaultPlan(spec, seed=int(cfg.get("TPU_FAULT_SEED", 0) or 0))
