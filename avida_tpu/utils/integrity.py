"""Silent-corruption integrity plane: host half (digest + taxonomy).

Real accelerator fleets suffer silent data corruption -- a bit flips in
device memory or a lane miscomputes, nothing raises, and the poisoned
state propagates into checkpoints and every downstream resume, analysis
and serve tenant.  The supervisor stack (PRs 6/8/12) heals every failure
that ANNOUNCES itself; this module (plus ops/digest.py, the device half)
closes the silent class, exploiting the engine's strongest property:
bit-exact deterministic replay on every path.  Determinism makes exact
redundant-execution checking essentially free to verify -- re-run a
chunk, compare one digest; any mismatch is corruption, not noise.

Three cooperating pieces:

  * `digest_arrays` -- the ORDER-STABLE u32 mix-and-fold tree digest.
    This host (numpy) implementation and the jitted device one
    (ops/digest.state_digest) agree bit-for-bit by construction: both
    walk leaves in sorted-name order, salt every element with its
    position and every leaf with a crc32 of its name, and fold with the
    same u32 wraparound arithmetic.  The agreement is what lets a
    host-only process (the supervisor, scripts/ckpt_tool.py, `--resume`)
    re-verify a digest the device computed.
  * `generation_digest` -- recompute the digest of a checkpoint
    generation from its `state.*.npy` leaves, for comparison against the
    `state_digest` the manifest stores (utils/checkpoint.py writes it
    when the integrity plane is on).
  * the process-wide integrity counters + their Prometheus families
    (`avida_integrity_*`), empty-when-untouched so integrity-off runs
    publish byte-identical metrics files.

Everything here is numpy/stdlib only -- no jax import, the same rule as
utils/checkpoint.py -- so the supervisor's sdc recovery never has to
load a device runtime to decide which generation to trust.
"""

from __future__ import annotations

import json
import os
import zlib

import numpy as np

# the shared mix-and-fold constants (ops/digest.py uses the same four;
# change one side and the host/device agreement test fails loudly)
C_IDX = 0x9E3779B9          # per-element position salt multiplier
C_MIX = 0x85EBCA6B          # element mixer
C_FOLD = 0xC2B2AE35         # leaf finalizer
FNV_OFFSET = 0x811C9DC5     # cross-leaf combine seed
FNV_PRIME = 0x01000193      # cross-leaf combine multiplier

_U32 = 0xFFFFFFFF

INTEGRITY_LOG = "integrity.jsonl"


def digest_enabled(cfg) -> bool:
    """TPU_STATE_DIGEST, env-OR-config: armed when either the config
    var (avida.cfg / -set) or the environment variable is nonzero --
    the environment half lets an operator (or the fleet) arm digesting
    across every child without touching specs, the TPU_FAULT pattern.
    tests/conftest.py pins the env var to 0 for suite hermeticity;
    explicit test overrides still win through the config half."""
    if int(cfg.get("TPU_STATE_DIGEST", 0) or 0):
        return True
    return bool(int(os.environ.get("TPU_STATE_DIGEST", "0") or 0))


def scrub_every(cfg) -> int:
    """TPU_SCRUB_EVERY (chunks between sampled shadow re-executions),
    env-OR-config with the config value winning when nonzero."""
    v = int(cfg.get("TPU_SCRUB_EVERY", 0) or 0)
    if v:
        return v
    return int(os.environ.get("TPU_SCRUB_EVERY", "0") or 0)


class StateDivergenceError(AssertionError):
    """A scrub (shadow re-execution) produced a different state digest
    than the live execution -- on a deterministic engine that is
    evidence of silent data corruption, never noise.  Mapped to the
    classified child exit EXIT_SDC (67) by __main__ so the supervisor
    can quarantine and roll back instead of blindly retrying."""


# ---------------------------------------------------------------------------
# the digest (host reference implementation)
# ---------------------------------------------------------------------------

def leaf_words(arr: np.ndarray) -> np.ndarray:
    """Canonical u32 word stream of one leaf: bools as 0/1, one-byte
    dtypes zero-extended bit-preserving, four-byte dtypes bit-cast.
    Row-major element order, so the digest is ORDER-STABLE: swapping two
    elements changes it."""
    arr = np.ascontiguousarray(arr)
    if arr.dtype == np.bool_:
        return arr.astype(np.uint32).ravel()
    if arr.dtype.itemsize == 1:
        return arr.view(np.uint8).astype(np.uint32).ravel()
    if arr.dtype.itemsize == 4:
        return arr.ravel().view(np.uint32)
    raise ValueError(
        f"state digest supports 1- and 4-byte leaves only (got "
        f"{arr.dtype}); PopulationState declares every field at one of "
        f"those widths")


def fold_words(words: np.ndarray) -> int:
    """u32[n] -> one u32: position-salted multiply-xor per element, a
    commutative xor reduce (deterministic on every backend), then a
    length-salted finalizer.  The position salt is what makes the xor
    fold order-stable."""
    n = int(words.shape[0])
    if n:
        idx = np.arange(n, dtype=np.uint32)
        h = (words ^ (idx * np.uint32(C_IDX))) * np.uint32(C_MIX)
        h = h ^ (h >> np.uint32(15))
        x = int(np.bitwise_xor.reduce(h))
    else:
        x = 0
    d = ((x ^ ((n * C_IDX) & _U32)) * C_FOLD) & _U32
    return d ^ (d >> 13)


def name_salt(name: str) -> int:
    return zlib.crc32(name.encode()) & _U32


def combine(leaf_digests: list) -> int:
    """[(name, u32)] -> one u32, folded in SORTED name order with a
    per-name salt -- renaming, dropping or swapping a leaf changes the
    digest (the tree-shape half of order stability)."""
    d = FNV_OFFSET
    for name, leaf in sorted(leaf_digests):
        d = ((d ^ (leaf ^ name_salt(name))) * FNV_PRIME) & _U32
        d ^= d >> 17
    return d


def digest_arrays(arrays: dict) -> int:
    """The full tree digest of {leaf_name: np.ndarray} -- the host
    spelling of ops/digest.state_digest (the device computes the same
    value over the live PopulationState)."""
    return combine([(name, fold_words(leaf_words(np.asarray(a))))
                    for name, a in arrays.items()])


# ---------------------------------------------------------------------------
# checkpoint-generation digests (manifest `state_digest` verification)
# ---------------------------------------------------------------------------

_STATE_PREFIX = "state."


def state_arrays_of(arrays: dict) -> dict:
    """The PopulationState subset of a checkpoint's array dict, prefix
    stripped -- the exact leaf set (and names) the digest covers.  The
    PRNG key sidecars are protected by the ordinary CRC manifest; the
    digest covers the evolved state the device actually computes on."""
    return {k[len(_STATE_PREFIX):]: v for k, v in arrays.items()
            if k.startswith(_STATE_PREFIX)}


def generation_digest(gen_path: str) -> tuple:
    """(stored, recomputed) digests for one checkpoint generation --
    stored is None when the manifest predates the integrity plane (or
    it was written with digesting off).  Reads the `state.*.npy` leaves
    directly (numpy only); callers wanting CRC validation first use
    checkpoint.verify_generation."""
    with open(os.path.join(gen_path, "manifest.json")) as f:
        manifest = json.load(f)
    stored = manifest.get("state_digest")
    arrays = {}
    for name, spec in manifest.get("arrays", {}).items():
        if not name.startswith(_STATE_PREFIX):
            continue
        arrays[name[len(_STATE_PREFIX):]] = np.load(
            os.path.join(gen_path, spec["file"]))
    return (None if stored is None else int(stored),
            digest_arrays(arrays))


# ---------------------------------------------------------------------------
# process-wide counters -> avida_integrity_* exposition families
# ---------------------------------------------------------------------------

_counters = {
    "scrubs": 0,            # shadow re-executions completed (or failed)
    "mismatches": 0,        # scrub digest mismatches (detected SDC)
    "digest_ms": 0.0,       # host wall spent dispatching/reading digests
}


def note_scrub():
    _counters["scrubs"] += 1


def note_mismatch():
    _counters["mismatches"] += 1


def note_digest_ms(ms: float):
    _counters["digest_ms"] += float(ms)


def counters() -> dict:
    return dict(_counters)


def reset_for_tests():
    for k in _counters:
        _counters[k] = 0 if isinstance(_counters[k], int) else 0.0


def append_integrity_record(data_dir: str, event: str,
                            max_bytes: int = 16 << 20, **fields):
    """One {"record": "integrity"} line in DATA_DIR/integrity.jsonl
    (size-capped rotation pair; non-durable appends -- the hot-loop
    runlog flavor, a torn tail is tolerated by every reader).  Shared
    by the solo, multi-world and serve drivers so the record shape has
    one spelling."""
    from avida_tpu.observability.runlog import append_record
    rec = {"record": "integrity", "event": event, **fields}
    try:
        append_record(os.path.join(data_dir, INTEGRITY_LOG), rec,
                      max_bytes=max_bytes, durable=False)
    except OSError:
        pass                    # logging must not take down the run


def prom_families() -> list:
    """The avida_integrity_* families, render_families shaped.  Empty
    when the integrity plane never ran, so digest-off processes publish
    byte-identical metrics files (the compilecache.prom_families
    contract)."""
    c = _counters
    if not (c["scrubs"] or c["mismatches"] or c["digest_ms"]):
        return []
    return [
        ("avida_integrity_scrubs_total", "counter",
         "shadow re-executions (sampled chunk replays) completed",
         c["scrubs"]),
        ("avida_integrity_mismatches_total", "counter",
         "scrub digest mismatches -- detected silent data corruption",
         c["mismatches"]),
        ("avida_integrity_digest_ms_total", "counter",
         "milliseconds of host wall spent dispatching and reading "
         "state digests", round(c["digest_ms"], 1)),
    ]
