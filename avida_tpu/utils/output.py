"""Self-documenting .dat output files.

Reproduces the reference's data-file format (Avida::Output::File,
avida-core/source/output/File.cc:102-212: `#` header with numbered column
descriptions, then space-separated rows) for the standard print actions
(PrintAverageData / PrintCountData / PrintTasksData / PrintTimeData, from the
244-action print library, avida-core/source/actions/PrintActions.cc).
"""

from __future__ import annotations

import os
import time


class DatFile:
    def __init__(self, path: str, title: str, col_descrs: list,
                 preamble: list | None = None):
        self.path = path
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        self._f = open(path, "w")
        self._f.write(f"# {title}\n")
        self._f.write(f"# {time.asctime()}\n")
        for line in (preamble or []):
            self._f.write(f"# {line}\n")
        for i, d in enumerate(col_descrs, 1):
            self._f.write(f"# {i:2d}: {d}\n")
        self._f.write("\n")

    def write_row(self, values):
        def fmt(v):
            if isinstance(v, float):
                if v == int(v) and abs(v) < 1e15:
                    return str(int(v))
                return f"{v:g}"
            return str(v)
        self._f.write(" ".join(fmt(v) for v in values) + " \n")
        self._f.flush()

    def close(self):
        self._f.close()


def open_average_dat(data_dir: str) -> DatFile:
    return DatFile(
        os.path.join(data_dir, "average.dat"), "Avida Average Data",
        ["Update", "Merit", "Gestation Time", "Fitness", "Repro Rate?",
         "(deprecated) Size", "Copied Size", "Executed Size",
         "(deprecated) Abundance",
         "Proportion of organisms that gave birth in this update",
         "Proportion of Breed True Organisms", "(deprecated) Genotype Depth",
         "Generation", "Neutral Metric", "Lineage Label",
         "True Replication Rate (based on births/update, time-averaged)"])


def open_count_dat(data_dir: str) -> DatFile:
    return DatFile(
        os.path.join(data_dir, "count.dat"), "Avida count data",
        ["update", "number of insts executed this update",
         "number of organisms", "number of different genotypes",
         "number of different threshold genotypes",
         "(deprecated) number of different species",
         "(deprecated) number of different threshold species",
         "(deprecated) number of different lineages",
         "number of births in this update", "number of deaths in this update",
         "number of breed true", "number of breed true organisms?",
         "number of no-birth organisms", "number of single-threaded organisms",
         "number of multi-threaded organisms", "number of modified organisms"])


def open_tasks_dat(data_dir: str, task_names: list) -> DatFile:
    return DatFile(
        os.path.join(data_dir, "tasks.dat"), "Avida tasks data",
        ["Update"] + [t.capitalize() for t in task_names],
        preamble=["First column gives the current update, next columns give the number",
                  "of organisms that have the particular task as a component of their merit"])


def open_dominant_dat(data_dir: str) -> DatFile:
    return DatFile(
        os.path.join(data_dir, "dominant.dat"), "Avida Dominant Data",
        ["Update", "Average Merit of the Dominant Genotype",
         "Average Gestation Time of the Dominant Genotype",
         "Average Fitness of the Dominant Genotype",
         "Repro Rate?", "Size of Dominant Genotype",
         "Copied Size of Dominant Genotype",
         "Executed Size of Dominant Genotype", "Abundance of Dominant Genotype",
         "Number of Births", "Number of Dominant Breed True?",
         "Dominant Gene Depth", "Dominant Breed In?",
         "Max Fitness?", "Genotype ID of Dominant Genotype",
         "Name of the Dominant Genotype"])


def open_fitness_dat(data_dir: str) -> DatFile:
    return DatFile(
        os.path.join(data_dir, "fitness.dat"), "Avida Fitness Data",
        ["Update", "Avg Generation", "Average Fitness", "Maximum Fitness",
         "Number of organisms"])


def open_stats_dat(data_dir: str) -> DatFile:
    return DatFile(
        os.path.join(data_dir, "stats.dat"), "Generic Statistics Data",
        ["Update", "Average creature age", "Genotype entropy",
         "Average gestation time", "Number of genotypes",
         "Dominant genotype abundance"])


def open_resource_dat(data_dir: str, resource_names: list) -> DatFile:
    return DatFile(
        os.path.join(data_dir, "resource.dat"), "Avida resource data",
        ["Update", "Avida time"] + [f"{n} resource" for n in resource_names],
        preamble=["First columns give the current update and time, next columns give",
                  "the quantity of the particular resource"])


def open_time_dat(data_dir: str) -> DatFile:
    return DatFile(
        os.path.join(data_dir, "time.dat"), "Avida time data",
        ["update", "avida time", "average generation", "num_executed?"])
