"""Self-documenting .dat output files.

Reproduces the reference's data-file format (Avida::Output::File,
avida-core/source/output/File.cc:102-212: `#` header with numbered column
descriptions, then space-separated rows) for the standard print actions
(PrintAverageData / PrintCountData / PrintTasksData / PrintTimeData, from the
244-action print library, avida-core/source/actions/PrintActions.cc).
"""

from __future__ import annotations

import contextlib
import os
import time

# Resume continuity (World.resume -> World._file): inside this context,
# opening a DatFile whose path already holds data APPENDS instead of
# truncating, so a checkpoint-resumed run extends the preempted run's
# .dat rows rather than erasing them.  Depth-counted so nested opens
# behave; fresh files still get their header block.
_APPEND_EXISTING = 0


@contextlib.contextmanager
def append_existing():
    global _APPEND_EXISTING
    _APPEND_EXISTING += 1
    try:
        yield
    finally:
        _APPEND_EXISTING -= 1


def trim_dat_rows(data_dir: str, max_update: int):
    """Resume continuity, half two: drop data rows PAST the restored
    update from every .dat file under data_dir, so appending after a
    checkpoint restore never duplicates updates (a crash that outran the
    last auto-save, or a CRC fallback to an older generation, leaves
    rows newer than the restored state on disk).  The cutoff is STRICT
    (drop rows >= max_update): checkpoints are written before the
    restored update's events fire, so the resumed run re-emits the row
    labeled max_update itself.  Best-effort column convention: the
    standard print actions all emit the update as the first column;
    rows whose first token is non-numeric are kept.  Rewrites are
    atomic (tmp + rename)."""
    if not os.path.isdir(data_dir):
        return
    for fname in os.listdir(data_dir):
        if not fname.endswith(".dat"):
            continue
        path = os.path.join(data_dir, fname)
        with open(path) as f:
            lines = f.readlines()
        kept = []
        dropped = 0
        for line in lines:
            t = line.split()
            if not t or line.startswith("#"):
                kept.append(line)
                continue
            try:
                u = float(t[0])
            except ValueError:
                kept.append(line)
                continue
            if u < max_update:
                kept.append(line)
            else:
                dropped += 1
        if dropped:
            tmp = path + ".tmp"
            with open(tmp, "w") as f:
                f.writelines(kept)
            os.replace(tmp, path)


class DatFile:
    def __init__(self, path: str, title: str, col_descrs: list,
                 preamble: list | None = None):
        self.path = path
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        if _APPEND_EXISTING and os.path.exists(path) \
                and os.path.getsize(path) > 0:
            self._f = open(path, "a")
            return
        self._f = open(path, "w")
        self._f.write(f"# {title}\n")
        self._f.write(f"# {time.asctime()}\n")
        for line in (preamble or []):
            self._f.write(f"# {line}\n")
        for i, d in enumerate(col_descrs, 1):
            self._f.write(f"# {i:2d}: {d}\n")
        self._f.write("\n")

    def write_row(self, values):
        def fmt(v):
            if isinstance(v, float):
                if v == int(v) and abs(v) < 1e15:
                    return str(int(v))
                return f"{v:g}"
            return str(v)
        self._f.write(" ".join(fmt(v) for v in values) + " \n")
        self._f.flush()

    def close(self):
        self._f.close()


def open_average_dat(data_dir: str) -> DatFile:
    return DatFile(
        os.path.join(data_dir, "average.dat"), "Avida Average Data",
        ["Update", "Merit", "Gestation Time", "Fitness", "Repro Rate?",
         "(deprecated) Size", "Copied Size", "Executed Size",
         "(deprecated) Abundance",
         "Proportion of organisms that gave birth in this update",
         "Proportion of Breed True Organisms", "(deprecated) Genotype Depth",
         "Generation", "Neutral Metric", "Lineage Label",
         "True Replication Rate (based on births/update, time-averaged)"])


def open_count_dat(data_dir: str) -> DatFile:
    return DatFile(
        os.path.join(data_dir, "count.dat"), "Avida count data",
        ["update", "number of insts executed this update",
         "number of organisms", "number of different genotypes",
         "number of different threshold genotypes",
         "(deprecated) number of different species",
         "(deprecated) number of different threshold species",
         "(deprecated) number of different lineages",
         "number of births in this update", "number of deaths in this update",
         "number of breed true", "number of breed true organisms?",
         "number of no-birth organisms", "number of single-threaded organisms",
         "number of multi-threaded organisms", "number of modified organisms"])


def open_tasks_dat(data_dir: str, task_names: list) -> DatFile:
    return DatFile(
        os.path.join(data_dir, "tasks.dat"), "Avida tasks data",
        ["Update"] + [t.capitalize() for t in task_names],
        preamble=["First column gives the current update, next columns give the number",
                  "of organisms that have the particular task as a component of their merit"])


def open_dominant_dat(data_dir: str) -> DatFile:
    return DatFile(
        os.path.join(data_dir, "dominant.dat"), "Avida Dominant Data",
        ["Update", "Average Merit of the Dominant Genotype",
         "Average Gestation Time of the Dominant Genotype",
         "Average Fitness of the Dominant Genotype",
         "Repro Rate?", "Size of Dominant Genotype",
         "Copied Size of Dominant Genotype",
         "Executed Size of Dominant Genotype", "Abundance of Dominant Genotype",
         "Number of Births", "Number of Dominant Breed True?",
         "Dominant Gene Depth", "Dominant Breed In?",
         "Max Fitness?", "Genotype ID of Dominant Genotype",
         "Name of the Dominant Genotype"])


def open_fitness_dat(data_dir: str) -> DatFile:
    return DatFile(
        os.path.join(data_dir, "fitness.dat"), "Avida Fitness Data",
        ["Update", "Avg Generation", "Average Fitness", "Maximum Fitness",
         "Number of organisms"])


def open_stats_dat(data_dir: str) -> DatFile:
    return DatFile(
        os.path.join(data_dir, "stats.dat"), "Generic Statistics Data",
        ["Update", "Average creature age", "Genotype entropy",
         "Average gestation time", "Number of genotypes",
         "Dominant genotype abundance"])


def open_resource_dat(data_dir: str, resource_names: list) -> DatFile:
    return DatFile(
        os.path.join(data_dir, "resource.dat"), "Avida resource data",
        ["Update", "Avida time"] + [f"{n} resource" for n in resource_names],
        preamble=["First columns give the current update and time, next columns give",
                  "the quantity of the particular resource"])


def open_time_dat(data_dir: str) -> DatFile:
    return DatFile(
        os.path.join(data_dir, "time.dat"), "Avida time data",
        ["update", "avida time", "average generation", "num_executed?"])
