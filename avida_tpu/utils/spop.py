"""Structured-population (.spop) checkpoint save/load.

Writes the reference's genotype-grouped 20-column format
(cPopulation::SavePopulation, avida-core/source/main/cPopulation.cc:6294;
column list documented in any expected/data/detail-*.spop header) so
ecosystem tooling keeps working, and reloads them
(cPopulation::LoadPopulation cc:6723) by injecting genomes and fast-forwarding
each organism `gest_offset` cycles with masked lockstep micro-steps -- the
TPU-native analogue of the reference's mid-gestation reconstruction.

FIDELITY LIMITS (reference parity, asserted by
tests/test_checkpoint.py::test_spop_fidelity_limits): the format is
genotype-grouped, so a round-trip preserves EXACTLY

  * alive mask, genome sequence and genome_len, per organism;
  * merit / gestation_time / fitness only as the PER-GENOTYPE MEAN
    (every restored member of a genotype gets the group average);
  * generation from the group's first listed cell;

and REBUILDS (does not preserve) CPU state: registers, heads, stacks,
threads and phenotype task counters are re-derived by fast-forwarding
`gest_offset` cycles from a fresh CPU.  PRNG keys, resource pools,
systematics ancestry and per-update accounting are NOT in the format at
all (resources restart at initial levels).  Runs needing bit-exact
persistence use the native checkpoint format (utils/checkpoint.py);
.spop stays for ecosystem tooling parity.
"""

from __future__ import annotations

import time

import jax
import jax.numpy as jnp
import numpy as np


# Reference sequence encoding (cInstruction::GetSymbol, cInstruction.cc:33):
# opcodes 0-25 map to 'a'-'z', 26-51 to 'A'-'Z'.  Larger instruction sets
# have no symbol alphabet in the .spop format -- refuse rather than emit
# unparseable punctuation (the pre-fix code silently wrote chr(ord('a')+op)
# garbage past 'z').
_SEQ_ALPHABET = ("abcdefghijklmnopqrstuvwxyz"
                 "ABCDEFGHIJKLMNOPQRSTUVWXYZ")
_SEQ_DECODE = {c: i for i, c in enumerate(_SEQ_ALPHABET)}


def _seq_to_string(ops: np.ndarray) -> str:
    out = []
    for o in ops:
        o = int(o)
        if not 0 <= o < len(_SEQ_ALPHABET):
            raise ValueError(
                f"opcode {o} has no .spop symbol (the a-zA-Z encoding "
                f"covers 52 instructions); use the native checkpoint "
                f"format (utils/checkpoint.py) for larger instruction sets")
        out.append(_SEQ_ALPHABET[o])
    return "".join(out)


def _string_to_seq(s: str) -> np.ndarray:
    try:
        return np.asarray([_SEQ_DECODE[c] for c in s], np.int8)
    except KeyError as e:
        raise ValueError(
            f"invalid .spop sequence symbol {e.args[0]!r} (expected a-zA-Z)")


def save_population(path: str, params, st, update: int, instset_name: str = "heads_default"):
    alive = np.asarray(st.alive)
    mem_len = np.asarray(st.genome_len)
    genomes = np.asarray(st.genome)
    merit = np.asarray(st.merit)
    gest = np.asarray(st.gestation_time)
    fit = np.asarray(st.fitness)
    gen = np.asarray(st.generation)
    born = np.asarray(st.birth_update)
    offset = np.asarray(st.time_used) - np.asarray(st.gestation_start)

    cells = np.nonzero(alive)[0]
    groups = {}
    for c in cells:
        key = genomes[c, :mem_len[c]].tobytes()
        groups.setdefault(key, []).append(int(c))

    with open(path, "w") as f:
        f.write("#filetype genotype_data\n")
        f.write("#format id src src_args parents num_units total_units length "
                "merit gest_time fitness gen_born update_born "
                "update_deactivated depth hw_type inst_set sequence cells "
                "gest_offset lineage \n")
        f.write("# Structured Population Save\n")
        f.write(f"# {time.asctime()}\n\n")
        for gid, (key, cs) in enumerate(sorted(groups.items(),
                                               key=lambda kv: -len(kv[1])), 1):
            seq = np.frombuffer(key, np.int8)
            c0 = cs[0]
            f.write(" ".join(map(str, [
                gid, "div:int", "(none)", "(none)", len(cs), len(cs),
                len(seq), f"{merit[cs].mean():g}", f"{gest[cs].mean():g}",
                f"{fit[cs].mean():g}", int(gen[c0]), int(born[c0]), -1, 0, 0,
                instset_name, _seq_to_string(seq),
                ",".join(str(c) for c in cs),
                ",".join(str(int(offset[c])) for c in cs),
                0])) + " \n")


def load_population(path: str, params, key):
    """Parse a .spop file; returns a list of dicts (one per organism):
    {cell, genome, merit, gest_offset, generation}."""
    orgs = []
    with open(path) as f:
        for raw in f:
            line = raw.strip()
            if not line or line.startswith("#"):
                continue
            t = line.split()
            if len(t) < 19:
                continue
            length = int(t[6])
            merit = float(t[7])
            gen_born = int(t[10])
            seq = _string_to_seq(t[16])
            assert len(seq) == length, f"sequence length mismatch in {path}"
            cells = [int(c) for c in t[17].split(",")]
            offsets = [int(o) for o in t[18].split(",")]
            parents = t[3]
            for c, off in zip(cells, offsets):
                orgs.append({"cell": c, "genome": seq, "merit": merit,
                             "gest_offset": off, "generation": gen_born,
                             "id": int(t[0]),
                             "depth": int(t[13]),
                             "parent": int(parents.split(",")[0])
                             if parents not in ("(none)", "") else -1})
    return orgs


def restore_population(params, orgs, key, neighbors=None):
    """Build a PopulationState from load_population output and fast-forward
    each organism to its gestation offset with masked micro-steps."""
    from avida_tpu.core.state import zeros_population, make_cell_inputs
    from avida_tpu.ops.interpreter import micro_step

    n, L, R = params.num_cells, params.max_memory, params.num_reactions
    st = zeros_population(n, L, R, params.num_global_res,
                          params.num_spatial_res, params.num_demes,
                          smt=(params.hw_type in (1, 2)),
                          num_registers=params.num_registers,
                          nb_cap=params.nb_cap,
                          n_deme_res=params.num_deme_res,
                          max_threads=params.max_cpu_threads)
    k_in, key = jax.random.split(key)
    st = st.replace(
        inputs=make_cell_inputs(k_in, n),
        deme_resources=jnp.broadcast_to(
            jnp.asarray(params.dres_initial, jnp.float32)[None, :],
            (params.num_demes, params.num_deme_res)),
        resources=jnp.asarray(params.res_initial, jnp.float32),
        res_grid=jnp.broadcast_to(
            jnp.asarray(params.sres_initial, jnp.float32)[:, None],
            (params.num_spatial_res, n)))

    mem = np.zeros((n, L), np.int8)
    mem_len = np.zeros(n, np.int32)
    merit = np.zeros(n, np.float32)
    alive = np.zeros(n, bool)
    gen = np.zeros(n, np.int32)
    offs = np.zeros(n, np.int32)
    for o in orgs:
        c = o["cell"]
        g = o["genome"]
        mem[c, :len(g)] = g
        mem_len[c] = len(g)
        merit[c] = o["merit"]
        alive[c] = True
        gen[c] = o["generation"]
        offs[c] = o["gest_offset"]

    st = st.replace(
        tape=jnp.asarray(mem).astype(jnp.uint8), mem_len=jnp.asarray(mem_len),
        genome=jnp.asarray(mem), genome_len=jnp.asarray(mem_len),
        merit=jnp.asarray(merit), alive=jnp.asarray(alive),
        generation=jnp.asarray(gen),
        cur_bonus=jnp.where(jnp.asarray(alive), params.default_bonus, 0.0),
        executed_size=jnp.asarray(mem_len), copied_size=jnp.asarray(mem_len),
        max_executed=jnp.asarray(
            np.where(alive,
                     params.age_limit * mem_len if params.death_method == 2
                     else (params.age_limit if params.death_method == 1 else 2**30),
                     0).astype(np.int32)),
    )

    if params.demes_use_germline and len(orgs):
        # .spop carries no germline section (format parity with the
        # reference, which stores germlines only in Avida-ED freezers);
        # re-seed each deme's germline from its lowest-index live organism,
        # falling back to the overall first (documented approximation)
        D = params.num_demes
        germ = np.zeros((D, L), np.int8)
        glen = np.zeros(D, np.int32)
        cpd = n // D
        first = orgs[0]
        for d in range(D):
            in_deme = [o for o in orgs if o["cell"] // cpd == d]
            src = min(in_deme, key=lambda o: o["cell"]) if in_deme else first
            g = src["genome"]
            germ[d, :len(g)] = g
            glen[d] = len(g)
        st = st.replace(germ_mem=jnp.asarray(germ), germ_len=jnp.asarray(glen))

    # fast-forward: organism i executes offs[i] cycles
    offs_j = jnp.asarray(offs)
    max_off = int(offs.max()) if len(orgs) else 0

    def body(s, st):
        mask = st.alive & (s < offs_j)
        return micro_step(params, st, jax.random.fold_in(key, s), mask)

    if max_off > 0:
        st = jax.lax.fori_loop(
            0, max_off, lambda s, stx: body(s, stx), st)
    # device-owned copies: several leaves above are jnp.asarray views of
    # numpy buffers, and the state is DONATED into the update scan --
    # an AOT-cached program would free numpy-owned memory (the exact
    # landmine utils/checkpoint._build_state documents)
    return jax.tree.map(jnp.copy, st)
