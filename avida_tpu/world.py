"""World: the host-side composition root and update driver.

TPU-native equivalent of cWorld (construction order mirrored from
cWorld::setup, avida-core/source/main/cWorld.cc:96-199) plus the master
update loop of Avida2Driver::Run (targets/avida/Avida2Driver.cc:64-165).
The device does all organism work (ops/update.py); this class owns config,
events, stats readback and .dat output.
"""

from __future__ import annotations

import os

import jax
import jax.numpy as jnp
import numpy as np

from avida_tpu.config import (AvidaConfig, load_avida_cfg, load_instset,
                              default_instset, heads_sex_instset,
                              transsmt_instset, experimental_instset,
                              pred_look_instset,
                              load_organism, load_environment, load_events)
from avida_tpu.config.environment import default_logic9_environment
from avida_tpu.config.events import parse_event_line
from avida_tpu.core.state import (init_population, make_world_params,
                                  PopulationState)
from avida_tpu.ops import birth as birth_ops
from avida_tpu.ops.update import update_scan, summarize
from avida_tpu.utils import output as output_mod

# Reference default ancestor (support/config/default-heads.org): h-alloc,
# h-search +CA label, mov-head, 85x nop-C body, copy loop w/ AB end label.
_DEFAULT_ANCESTOR_NAMES = (
    ["h-alloc", "h-search", "nop-C", "nop-A", "mov-head"]
    + ["nop-C"] * 86
    + ["h-search", "h-copy", "if-label", "nop-C", "nop-A", "h-divide",
       "mov-head", "nop-A", "nop-B"]
)

# Reference experimental ancestor (support/config/experimental.org):
# 4-nop hardware, so the copy-loop label is D/A (complement under
# Rotate(1,4): D->A? no -- C,A in 3-nop space becomes D,A here) and the
# end label A,B is addressed through the `label` marker instruction.
_EXPERIMENTAL_ANCESTOR_NAMES = (
    ["h-alloc", "h-search", "nop-D", "nop-A", "mov-head", "nop-C", "add"]
    + ["nop-C"] * 81
    + ["h-search", "h-copy", "if-label", "nop-D", "nop-A", "h-divide",
       "mov-head", "nop-A", "add", "label", "nop-A", "nop-B"]
)

# Reference transsmt ancestor (support/config/default-transsmt.org): search
# end label, SetMemory offspring space, copy loop, Divide at end-position.
_TRANSSMT_ANCESTOR_NAMES = (
    ["Search", "Nop-C", "Nop-D", "Push-Prev", "SetMemory", "Nop-A",
     "Head-Move"]
    + ["Nop-C"] * 83
    + ["Search", "Inst-Read", "Inst-Write", "Head-Push", "Nop-C",
       "If-Equal", "Divide", "Head-Move", "Nop-A", "Nop-B"]
)

# Reference transsmt parasite (support/config/default-transsmt-parasite.org):
# nop body, copy loop into its own write space, Inject at the end.
_TRANSSMT_PARASITE_NAMES = (
    ["Nop-A"] + ["Nop-B"] * 75
    + ["Inst-Read", "Val-Add", "Val-Dec", "SetMemory", "Nop-C", "IO",
       "Nop-C", "Nop-B", "Head-Move", "Nop-C", "Search", "Inst-Write",
       "Inst-Read", "If-Greater", "Head-Move", "Val-Sub", "Val-Dec", "IO",
       "Val-Div", "Val-Dec", "Val-Dec", "Val-Dec", "Val-Div", "Inject"]
)


def default_ancestor(instset) -> np.ndarray:
    name_to_op = {n: i for i, n in enumerate(instset.inst_names)}
    if "Divide" in name_to_op or "Divide-Erase" in name_to_op:
        names = _TRANSSMT_ANCESTOR_NAMES       # transsmt hardware
    elif "nop-D" in name_to_op and "h-divide" in name_to_op:
        names = _EXPERIMENTAL_ANCESTOR_NAMES   # 4+-nop experimental
    elif "h-divide" not in name_to_op and "divide-sex" in name_to_op:
        # sexual ancestor: same replicator with divide-sex
        # (ref support/config/default-heads-sex.org)
        names = ["divide-sex" if n == "h-divide" else n
                 for n in _DEFAULT_ANCESTOR_NAMES]
    else:
        names = _DEFAULT_ANCESTOR_NAMES
    missing = [n for n in names if n not in name_to_op]
    if missing:
        raise ValueError(
            f"instruction set {instset.name!r} has no built-in default "
            f"ancestor (lacks {missing[:4]}{'...' if len(missing) > 4 else ''}"
            f"); inject an explicit genome (START_ORGANISM / World.inject "
            f"with a genome argument)")
    return np.asarray([name_to_op[n] for n in names], np.int8)


def default_parasite(instset) -> np.ndarray:
    name_to_op = {n: i for i, n in enumerate(instset.inst_names)}
    return np.asarray([name_to_op[n] for n in _TRANSSMT_PARASITE_NAMES],
                      np.int8)


class World:
    def __init__(self, cfg: AvidaConfig | None = None, config_dir: str | None = None,
                 overrides=None, data_dir: str | None = None):
        if config_dir is not None:
            cfg = load_avida_cfg(os.path.join(config_dir, "avida.cfg"), overrides)
        elif cfg is None:
            from avida_tpu.config.schema import _parse_scalar
            cfg = AvidaConfig()
            for name, value in (overrides or []):
                # same scalar coercion as the config-dir path
                # (load_avida_cfg): a CLI `-set TPU_SYSTEMATICS 0`
                # must store int 0, not the TRUTHY string "0" --
                # extras-var gates that test truthiness (systematics,
                # nb_cap) silently ignored string-zero overrides on
                # the bare-config path before this
                cfg.set(name, _parse_scalar(str(value)))
        self.cfg = cfg
        self.config_dir = config_dir
        self.data_dir = data_dir or cfg.DATA_DIR

        # instruction set (cHardwareManager::LoadInstSets equivalent)
        if config_dir and cfg.INST_SET not in ("-", ""):
            self.instset = load_instset(os.path.join(config_dir, cfg.INST_SET))
        elif "transsmt" in cfg.INST_SET or "smt" in cfg.INST_SET:
            self.instset = transsmt_instset()
        elif "pred" in cfg.INST_SET:
            self.instset = pred_look_instset()
        elif "experimental" in cfg.INST_SET:
            self.instset = experimental_instset()
        elif "sex" in cfg.INST_SET:
            self.instset = heads_sex_instset()
        else:
            self.instset = default_instset()

        # environment (cEnvironment::Load equivalent)
        env_path = (os.path.join(config_dir, cfg.ENVIRONMENT_FILE)
                    if config_dir else None)
        if env_path and os.path.exists(env_path):
            self.environment = load_environment(env_path)
        else:
            self.environment = default_logic9_environment()

        # events (cEventList::LoadEventFile equivalent)
        ev_path = (os.path.join(config_dir, cfg.EVENT_FILE)
                   if config_dir else None)
        if ev_path and os.path.exists(ev_path):
            self.events = load_events(ev_path)
        else:
            self.events = [
                parse_event_line("u begin Inject default-heads.org"),
                parse_event_line("u 0:100:end PrintAverageData"),
                parse_event_line("u 0:100:end PrintCountData"),
                parse_event_line("u 0:100:end PrintTasksData"),
                parse_event_line("u 0:100:end PrintTimeData"),
            ]

        # DEMES_MIGRATION_METHOD 4: parse the MIGRATION_FILE weight matrix
        # (cMigrationMatrix::Load: one whitespace-separated row per source
        # deme) and attach it for make_world_params' CDF build
        if int(cfg.DEMES_MIGRATION_METHOD) == 4 \
                and cfg.MIGRATION_FILE not in ("-", ""):
            mig_path = (os.path.join(config_dir, cfg.MIGRATION_FILE)
                        if config_dir else cfg.MIGRATION_FILE)
            rows = []
            with open(mig_path) as f:
                for line in f:
                    line = line.split("#", 1)[0].strip()
                    if line:
                        rows.append([float(x) for x in line.split()])
            if len(rows) != cfg.NUM_DEMES or any(
                    len(r) != cfg.NUM_DEMES for r in rows):
                raise ValueError(
                    f"MIGRATION_FILE {cfg.MIGRATION_FILE!r} must be a "
                    f"{cfg.NUM_DEMES}x{cfg.NUM_DEMES} matrix")
            cfg._migration_matrix = rows

        self.params = make_world_params(cfg, self.instset, self.environment)
        self.neighbors = jnp.asarray(birth_ops.neighbor_table(
            cfg.WORLD_X, cfg.WORLD_Y, cfg.WORLD_GEOMETRY,
            seed=max(cfg.RANDOM_SEED, 0),
            scale_free_m=getattr(cfg, "SCALE_FREE_M", 3),
            scale_free_alpha=getattr(cfg, "SCALE_FREE_ALPHA", 1.0),
            scale_free_zero_appeal=getattr(cfg, "SCALE_FREE_ZERO_APPEAL",
                                           0.0)))

        seed = cfg.RANDOM_SEED if cfg.RANDOM_SEED >= 0 else int.from_bytes(os.urandom(4), "little")
        self.key = jax.random.key(seed)
        # the run stream: per-update keys are fold_in(_run_key, update_no),
        # a pure function of the seed -- trajectories don't depend on how
        # the driver chunks updates (ops/update.update_scan)
        self.key, self._run_key = jax.random.split(self.key)
        self.update = 0
        self.state: PopulationState | None = None
        self._exit = False
        self._preempt = False        # SIGTERM/SIGINT tripwire (run loop)
        self.preempted = False       # last run() ended via preemption
        self._files = {}
        self._cum_insts = 0          # host-accumulated, birth-reset-proof
        self._insts_prev_total = 0
        self._pending_exec = []      # unsynced per-update device scalars
        self._avida_time = jnp.float32(0.0)   # device scalar, synced lazily
        self._last_ave_gen = jnp.float32(0.0)
        self._deaths_this = jnp.int32(0)      # device scalar
        self._prev_alive = None               # device scalar
        self._total_births = jnp.int32(0)     # device scalar (BIRTHS trigger)
        self._events_done_for = None
        self._warned_actions = set()
        self._nb_pending = None      # deferred newborn-drain snapshot
        self._last_drain_update = 0
        # per-generation-event next-fire bookkeeping (cEventList generation
        # triggers compare against population average generation)
        self._gen_next = {}

        # live phylogeny (ref Systematics::GenotypeArbiter; SURVEY §2f)
        from avida_tpu.systematics import GenotypeArbiter
        self.systematics = (GenotypeArbiter(self.params.num_cells)
                            if cfg.get("TPU_SYSTEMATICS", 1) else None)

        # data provider/recorder registry (ref avida/data/Manager.h);
        # PrintData and the histogram actions resolve through it
        from avida_tpu.utils.data_registry import (DataManager,
                                                   register_standard_providers)
        self.data = DataManager(self)
        register_standard_providers(self.data)

        # opt-in runtime telemetry (avida_tpu/observability/): phase-fenced
        # staged updates, device counters and a telemetry.jsonl run log.
        # With TPU_TELEMETRY=0 (default) nothing is built, written or
        # traced -- the update program is byte-identical to a build
        # without the subsystem (tests/test_telemetry.py).
        self.telemetry = None
        if int(cfg.get("TPU_TELEMETRY", 0)):
            from avida_tpu.observability import TelemetryRecorder
            pdir = str(cfg.get("TPU_PROFILE_DIR", "-") or "-")
            self.telemetry = TelemetryRecorder(
                self, profile_dir=(pdir if pdir not in ("-", "") else None),
                profile_updates=int(cfg.get("TPU_PROFILE_UPDATES", 3)))

        # device-side flight recorder (observability/tracer.py): with
        # TPU_TRACE=1 the jitted update appends structured events to
        # in-state ring buffers, drained to {"record":"trace"} runlog
        # lines only at update-chunk boundaries.  With it off (default)
        # the ring fields are None (empty pytrees) and update_step traces
        # the byte-identical program (scripts/check_jaxpr.py).
        self.tracer = None
        self._trace_pending = None   # deferred ring snapshot (run pipeline)
        if self.params.trace_cap:
            from avida_tpu.observability.tracer import FlightRecorder
            self.tracer = FlightRecorder(self)

        # metrics.prom heartbeat (observability/exporter.py): rewritten
        # atomically at chunk boundaries; implied by the flight
        # recorder.  Each publish also appends one sample row to the
        # metrics.hist.jsonl ring beside it (observability/history.py,
        # TPU_METRICS_HIST knobs resolved env-over-config by the
        # exporter's sink) -- the alert plane and `--status` history
        # line read that ring, never this process
        self.exporter = None
        if int(cfg.get("TPU_METRICS", 0)) or self.tracer is not None:
            from avida_tpu.observability.exporter import MetricsExporter
            self.exporter = MetricsExporter(self)

        # device performance attribution plane (observability/
        # profiler.py; README "Performance attribution"): per-chunk
        # walls + sampled fenced phase/footprint probes on state
        # COPIES.  Off (default): nothing is built and _scan_updates
        # pays zero -- exporter files and trajectories byte-identical.
        # Only meaningful on the scanned-chunk path; telemetry already
        # fences every phase, so the plane stays unbuilt under it.
        self.profiler = None
        from avida_tpu.observability import profiler as _profiler
        if _profiler.enabled(cfg) and self.telemetry is None:
            self.profiler = _profiler.ChunkProfiler(
                self.data_dir, cfg, kind="solo")

        # in-run analytics (analyze/pipeline.py): with TPU_ANALYTICS=1,
        # World.run refreshes an incremental phenotype census (+ the
        # dominant-lineage replay) at checkpoint boundaries and run
        # exit, publishing analytics.prom / analysis/analytics.jsonl so
        # `--status` answers "what evolved?" no staler than one
        # checkpoint interval.  Pure host read at already-synced
        # boundaries: no PRNG draw, no state write -- trajectories are
        # bit-identical with it on or off.
        self.analytics = None
        if int(cfg.get("TPU_ANALYTICS", 0)):
            from avida_tpu.analyze.pipeline import LiveAnalytics
            self.analytics = LiveAnalytics(self)

        # deterministic fault injection (utils/faultinject.py): None in
        # every production run -- with TPU_FAULT unset no hook fires and
        # the update program is untouched (the `nan:`/`bitflip:` kinds
        # ride params.fault_nan/fault_bitflip behind the same static
        # gate as the tracer)
        from avida_tpu.utils.faultinject import plan_from_config
        self.faults = plan_from_config(cfg)

        # silent-corruption integrity plane (ops/digest.py +
        # utils/integrity.py; README "Integrity plane").  Both knobs
        # default OFF: no digest program is built, no state copy is
        # retained, zero cost -- and either way the update program is
        # untouched (the digest is a SEPARATE jit, the audit_state
        # isolation rule).  TPU_STATE_DIGEST=1 computes an order-stable
        # u32 tree digest of the state at every chunk boundary (into
        # the checkpoint manifest, the heartbeat and integrity.jsonl);
        # TPU_SCRUB_EVERY=K re-executes every K-th chunk from the
        # retained pre-chunk state and compares digests -- determinism
        # makes any mismatch corruption, not noise
        from avida_tpu.utils import integrity
        self._digest_on = integrity.digest_enabled(cfg)
        self._scrub_every = integrity.scrub_every(cfg)
        self._chunk_no = 0              # process-lifetime chunk counter
        self._digest_pending = None     # (update, device u32) deferred
        self.state_digest = None        # (update, value) last resolved
        self._last_verified_update = 0  # newest scrub-verified update
        if (self._digest_on or self._scrub_every) \
                and self.telemetry is not None:
            # telemetry forces per-update phase-fenced dispatch through
            # StagedUpdate -- there is no scanned chunk to digest or
            # shadow-replay, so the plane would be a silent no-op; be
            # loud instead of quietly unprotected
            import sys as _sys
            print("[avida-tpu] warning: TPU_STATE_DIGEST/TPU_SCRUB_EVERY "
                  "are no-ops under TPU_TELEMETRY (the integrity plane "
                  "rides the scanned chunk path); run telemetry OR "
                  "scrubbing, not both", file=_sys.stderr)

        # offspring reversion/sterilization via the batched Test CPU
        # (cHardwareBase::Divide_TestFitnessMeasures cc:866); fitness
        # lookups memoize per genotype (systematics/test_metrics.py)
        self._revert = {
            "fatal": (cfg.REVERT_FATAL, cfg.STERILIZE_FATAL),
            "neg": (cfg.REVERT_DETRIMENTAL, cfg.STERILIZE_DETRIMENTAL),
            "neut": (cfg.REVERT_NEUTRAL, cfg.STERILIZE_NEUTRAL),
            "pos": (cfg.REVERT_BENEFICIAL, cfg.STERILIZE_BENEFICIAL),
        }
        self._revert_on = any(p > 0 for pair in self._revert.values()
                              for p in pair)
        self._neut_min = 1.0 - cfg.get("NEUTRAL_MIN", 0.0)
        self._neut_max = 1.0 + cfg.get("NEUTRAL_MAX", 0.0)
        if self._revert_on:
            from avida_tpu.systematics.test_metrics import GenomeTestMetrics
            self.test_metrics = GenomeTestMetrics(self.params)
            self._revert_rng = np.random.default_rng(seed ^ 0x5EED)

    # ---- event actions (subset of the 418-action library) ----

    def _resolve_org_path(self, name: str) -> np.ndarray:
        if self.config_dir:
            p = os.path.join(self.config_dir, name)
            if os.path.exists(p):
                return load_organism(p, self.instset)
        return default_ancestor(self.instset)

    def inject(self, genome: np.ndarray | None = None, cell: int | None = None):
        """Activate one organism (ref cPopulation::Inject, cPopulation.cc:7377).

        On an empty world this creates the population state; mid-run it
        overwrites the target cell only (the reference's Inject semantics),
        preserving every other living organism.
        """
        self.key, k = jax.random.split(self.key)
        if genome is None:
            genome = default_ancestor(self.instset)
        if cell is None:
            cell = self.params.num_cells // 2
        if self.state is None:
            self.state = init_population(self.params, genome, k,
                                         inject_cell=cell)
        else:
            # one-row write (cPopulation::Inject semantics): O(1) in world
            # size, no full-population rebuild
            from avida_tpu.core.state import seed_organism
            self.state = seed_organism(self.params, self.state, genome, k,
                                       cell)
        if self.systematics is not None:
            self.systematics.classify_seed(cell, genome, update=self.update)

    def _action_Inject(self, args):
        genome = self._resolve_org_path(args[0]) if args else None
        self.inject(genome)

    def _action_InjectAll(self, args):
        """InjectAll [filename]: an organism in every cell
        (ref cActionInjectAll, actions/PopulationActions.cc)."""
        genome = self._resolve_org_path(args[0]) if args else None
        if self.state is None:
            # bootstrap state only; the blanket reseed below covers cell 0,
            # so suppress this inject's systematics record to avoid a
            # double classification
            sysm, self.systematics = self.systematics, None
            self.inject(genome, cell=0)
            self.systematics = sysm
        g = genome if genome is not None else default_ancestor(self.instset)
        n, L = self.params.num_cells, self.params.max_memory
        import numpy as np_
        gm = np_.zeros(L, np_.int8)
        gm[: len(g)] = g
        glen = len(g)
        st = self.state
        full = jnp.ones(n, bool)
        self.key, k = jax.random.split(self.key)
        from avida_tpu.ops.demes import _clone_reset
        genome_t = jnp.broadcast_to(jnp.asarray(gm)[None, :], (n, L))
        updates = _clone_reset(
            self.params, st, full, genome_t,
            jnp.full(n, glen, jnp.int32), full,
            jnp.full(n, float(glen), st.merit.dtype), k)
        self.state = st.replace(**updates)
        if self.systematics is not None:
            self.systematics.classify_seed_all(g, update=self.update)

    def _action_Exit(self, args):
        self._exit = True

    def _file(self, name, opener, *a):
        if name not in self._files:
            with self._dat_open_ctx():
                self._files[name] = opener(self.data_dir, *a)
        return self._files[name]

    def _dat_open_ctx(self):
        """After a checkpoint resume, newly opened .dat files APPEND to
        the preempted run's rows instead of truncating them (resume
        continuity; utils/output.append_existing)."""
        if getattr(self, "_dat_append", False):
            return output_mod.append_existing()
        import contextlib
        return contextlib.nullcontext()

    def _summary(self):
        if getattr(self, "_summary_cache_update", None) != self.update:
            s = summarize(self.params, self.state, jnp.int32(self.update - 1))
            self._summary_stats = {k: np.asarray(v) for k, v in s.items()}
            self._summary_cache_update = self.update
        return self._summary_stats

    def _flush_exec(self) -> int:
        """Drain queued per-update executed counts into the host total.
        Entries are int32[k] device vectors; summing in int64 on the host
        keeps long uncapped runs from overflowing."""
        if self._pending_exec:
            self._cum_insts += int(sum(
                np.asarray(x, dtype=np.int64).sum() for x in self._pending_exec))
            self._pending_exec = []
        return self._cum_insts

    def _action_PrintAverageData(self, args):
        s = self._summary()
        f = self._file("average", output_mod.open_average_dat)
        n = max(int(s["num_organisms"]), 1)
        sysm = self.systematics
        abundance = (n / max(sysm.num_genotypes, 1)) if sysm else 0.0
        depth = sysm.average_depth() if sysm else 0.0
        births = int(s["births_this_update"])
        f.write_row([
            self.update, float(s["ave_merit"]), float(s["ave_gestation"]),
            float(s["ave_fitness"]), float(s["ave_repro_rate"]),
            float(s["ave_genome_len"]), float(s["ave_copied_size"]),
            float(s["ave_executed_size"]), abundance,
            births / n, int(s["num_breed_true"]) / n, depth,
            float(s["ave_generation"]), 0.0, 0,
            births / n])

    def _action_PrintCountData(self, args):
        s = self._summary()
        f = self._file("count", output_mod.open_count_dat)
        total = self._flush_exec()
        insts_this_update = total - self._insts_prev_total
        self._insts_prev_total = total
        n = int(s["num_organisms"])
        sysm = self.systematics
        num_gt = sysm.num_genotypes if sysm else 0
        num_thr = sysm.num_threshold if sysm else 0
        births = int(s["births_this_update"])
        breed_true = int(s["num_breed_true"])
        no_birth = int(s["num_no_birth"])   # never yet divided (cStats)
        f.write_row([self.update, insts_this_update, n, num_gt, num_thr,
                     0, 0, 0, births, int(self._deaths_this), breed_true,
                     breed_true, no_birth, n, 0, 0])

    def _action_PrintDominantData(self, args):
        """dominant.dat with live per-genotype reductions (ref
        PrintDominantData, actions/PrintActions.cc; column semantics from
        the golden header in tests/heads_default_100u/expected/data)."""
        if self.systematics is None:
            return
        g = self.systematics.dominant()
        if g is None:
            return
        f = self._file("dominant", output_mod.open_dominant_dat)
        st = self.state
        member = (self.systematics.cell_gid == g.gid) & np.asarray(st.alive)
        cells = np.nonzero(member)[0]
        if cells.size:
            merit = float(np.asarray(st.merit)[cells].mean())
            gest = float(np.asarray(st.gestation_time)[cells].mean())
            fit = float(np.asarray(st.fitness)[cells].mean())
            copied = float(np.asarray(st.copied_size)[cells].mean())
            execd = float(np.asarray(st.executed_size)[cells].mean())
            max_fit = float(np.asarray(st.fitness)[cells].max())
            births = int((np.asarray(st.birth_update)[cells]
                          == self.update - 1).sum())
            breed_true = int(np.asarray(st.breed_true)[cells].sum())
        else:
            merit = gest = fit = copied = execd = max_fit = 0.0
            births = breed_true = 0
        # reference names are "<size>-<base26>" (e.g. 100-aaaaa)
        name = f"{g.length}-" + "".join(
            chr(ord("a") + (g.gid // 26**k) % 26) for k in range(4, -1, -1))
        f.write_row([
            self.update, merit, gest, fit,
            (1.0 / gest if gest else 0.0), g.length, copied, execd,
            g.num_units, births, breed_true, g.depth, 0, max_fit, g.gid,
            name])

    def _action_PrintFitnessData(self, args):
        """fitness.dat (ref cActionPrintFitnessData,
        actions/PrintActions.cc:1380: update, generation, ave/max fitness,
        organism count; histogram variants not implemented)."""
        s = self._summary()
        f = self._file("fitness", output_mod.open_fitness_dat)
        f.write_row([self.update, float(s["ave_generation"]),
                     float(s["ave_fitness"]), float(s["max_fitness"]),
                     int(s["num_organisms"])])

    def _action_PrintStatsData(self, args):
        """stats.dat (ref cActionPrintStatsData -> cStats entropy/age
        aggregation): population age, genotype Shannon entropy, gestation,
        genotype counts."""
        s = self._summary()
        f = self._file("stats", output_mod.open_stats_dat)
        sysm = self.systematics
        entropy = 0.0
        num_gt = 0
        dom_abund = 0
        if sysm is not None and sysm.num_genotypes:
            import math
            counts = [g.num_units for g in sysm.live_genotypes()]
            total = sum(counts) or 1
            entropy = -sum((c / total) * math.log(c / total)
                           for c in counts if c > 0)
            num_gt = sysm.num_genotypes
            dom = sysm.dominant()
            dom_abund = dom.num_units if dom else 0
        f.write_row([self.update, float(s["ave_age"]), entropy,
                     float(s["ave_gestation"]), num_gt, dom_abund])

    def _action_PrintTasksData(self, args):
        s = self._summary()
        f = self._file("tasks", output_mod.open_tasks_dat,
                       self.environment.task_names())
        f.write_row([self.update] + [int(x) for x in s["task_counts"]])

    def _action_PrintTimeData(self, args):
        s = self._summary()
        f = self._file("time", output_mod.open_time_dat)
        total = self._flush_exec()
        insts = total - getattr(self, "_time_prev", 0)
        self._time_prev = total
        f.write_row([self.update, float(self._avida_time),
                     float(s["ave_generation"]), insts])

    def _action_PrintData(self, args):
        """Generic registry-driven writer (cActionPrintData,
        actions/PrintActions.cc:389: `PrintData <fname> <id,id,...>`):
        any registered data IDs become a .dat file -- no World edits."""
        if len(args) < 2:
            return
        fname, fmt = args[0], args[1]
        key = f"printdata:{fname}"
        if key not in self._files:
            from avida_tpu.utils.data_registry import DatRecorder
            ids = [s.strip() for s in fmt.split(",") if s.strip()]
            specs = [(i, self.data.describe(i) if i != "core.update"
                      else "Update") for i in ids]
            with self._dat_open_ctx():
                self._files[key] = DatRecorder(
                    self.data_dir, fname, "Avida data", specs)
        self._files[key].record(self.update, self.data)

    def _action_PrintInstructionAbundanceHistogram(self, args):
        """instruction_histogram.dat: per-opcode counts across live
        genomes (cActionPrintInstructionAbundanceHistogram)."""
        from avida_tpu.utils.data_registry import instruction_abundance
        f = self._file(
            "inst_hist", lambda d: output_mod.DatFile(
                os.path.join(d, args[0] if args
                             else "instruction_histogram.dat"),
                "Avida instruction abundance histogram",
                ["Update"] + list(self.instset.inst_names)))
        f.write_row([self.update] + [int(x)
                                     for x in instruction_abundance(self)])

    def _action_PrintDepthHistogram(self, args):
        """depth_histogram.dat rows: update, depth, genotype count."""
        from avida_tpu.utils.data_registry import depth_histogram
        f = self._file(
            "depth_hist", lambda d: output_mod.DatFile(
                os.path.join(d, args[0] if args else "depth_histogram.dat"),
                "Avida depth histogram",
                ["Update", "Depth", "Number of genotypes"]))
        for depth, count in depth_histogram(self).items():
            f.write_row([self.update, depth, count])

    def _action_PrintGenotypeAbundanceHistogram(self, args):
        """genotype_abundance_histogram.dat rows: update, abundance,
        genotype count."""
        from avida_tpu.utils.data_registry import abundance_histogram
        f = self._file(
            "abund_hist", lambda d: output_mod.DatFile(
                os.path.join(d, args[0] if args
                             else "genotype_abundance_histogram.dat"),
                "Avida genotype abundance histogram",
                ["Update", "Abundance", "Number of genotypes"]))
        for ab, count in abundance_histogram(self).items():
            f.write_row([self.update, ab, count])

    def _action_PrintTasksExeData(self, args):
        """tasks_exe.dat (cActionPrintTasksExeData): number of times each
        task was executed this update -- host diff of the device-side
        lifetime execution totals."""
        s = self._summary()
        f = self._file(
            "tasks_exe", lambda d: output_mod.DatFile(
                os.path.join(d, "tasks_exe.dat"),
                "Avida tasks execution data",
                ["Update"] + [t.capitalize()
                              for t in self.environment.task_names()],
                preamble=["First column gives the current update, all "
                          "further columns give the number",
                          "of times the particular task has been executed "
                          "this update."]))
        totals = np.asarray(s["task_exe_totals"], np.int64)
        prev = getattr(self, "_task_exe_prev", np.zeros_like(totals))
        self._task_exe_prev = totals
        f.write_row([self.update] + [int(x) for x in (totals - prev)])

    def _action_PrintTasksQualData(self, args):
        """tasks_quality.dat (cActionPrintTasksQualData): average and max
        task quality.  Logic-9 task quality is binary in this build
        (documented simplification: the reference's partial-credit tasks
        are not implemented), so avg == max == 1 when any organism's last
        gestation performed the task."""
        s = self._summary()
        f = self._file(
            "tasks_qual", lambda d: output_mod.DatFile(
                os.path.join(d, "tasks_quality.dat"),
                "Avida tasks quality data",
                ["Update"] + [f"{t.capitalize()} {m}"
                              for t in self.environment.task_names()
                              for m in ("Average", "Max")],
                preamble=["First column gives the current update, rest "
                          "give average and max task quality"]))
        row = [self.update]
        for c in [int(x) for x in s["task_counts"]]:
            # binary quality: every performer scores 1.0, so both the
            # average over performers and the max are 1 when anyone
            # performed (0 otherwise)
            row += [1 if c else 0, 1 if c else 0]
        f.write_row(row)

    def _action_PrintResourceData(self, args):
        names = ([r.name for r in self.environment.global_resources()]
                 + [r.name for r in self.environment.spatial_resources()])
        if not names:
            return
        f = self._file("resource", output_mod.open_resource_dat, names)
        levels = [float(x) for x in np.asarray(self.state.resources)]
        if self.params.num_spatial_res:
            levels += [float(x) for x in
                       np.asarray(self.state.res_grid).sum(axis=1)]
        f.write_row([self.update, float(self._avida_time)] + levels)

    def _action_SetResource(self, args):
        """SetResource <name> <level> (ref EnvironmentActions.cc)."""
        name, level = args[0], float(args[1])
        for i, r in enumerate(self.environment.global_resources()):
            if r.name == name:
                self.state = self.state.replace(
                    resources=self.state.resources.at[i].set(level))
                return
        for i, r in enumerate(self.environment.spatial_resources()):
            if r.name == name:
                n = self.params.num_cells
                self.state = self.state.replace(
                    res_grid=self.state.res_grid.at[i].set(
                        jnp.full(n, level / n, jnp.float32)))
                return

    def _action_InjectParasite(self, args):
        """InjectParasite [filename [label [cell_start [cell_end]]]]
        (ref cActionInjectParasite, actions/PopulationActions.cc): place a
        parasite genome into living organisms' parasite memory space.
        Default genome is the stock transsmt parasite."""
        import numpy as np_
        if args and args[0] not in ("-", ""):
            genome = self._resolve_org_path(args[0])
        else:
            genome = default_parasite(self.instset)
        start = int(args[2]) if len(args) > 2 else 0
        end = int(args[3]) if len(args) > 3 else start + 1
        st = self.state
        n, = st.alive.shape
        L = self.params.max_memory
        cells = jnp.arange(n)
        sel = (cells >= start) & (cells < end) & st.alive \
            & ~st.parasite_active
        g = np_.zeros(L, np_.uint8)
        g[: len(genome)] = genome.astype(np_.uint8)
        self.state = st.replace(
            pmem=jnp.where(sel[:, None], jnp.asarray(g)[None, :], st.pmem),
            pmem_len=jnp.where(sel, len(genome), st.pmem_len),
            parasite_active=st.parasite_active | sel,
            smt_head_pos=st.smt_head_pos.at[:, 1].set(
                jnp.where(sel[:, None], 0, st.smt_head_pos[:, 1])),
            smt_head_space=st.smt_head_space.at[:, 1].set(
                jnp.where(sel[:, None], 2, st.smt_head_space[:, 1])),
        )

    def _action_CompeteDemes(self, args):
        """CompeteDemes [competition_type] (ref cPopulation::CompeteDemes;
        action cActionCompeteDemes).  Fitness-proportional deme selection +
        wholesale replacement."""
        from avida_tpu.ops import demes as deme_ops
        ctype = int(args[0]) if args else self.cfg.DEMES_COMPETITION_STYLE
        self.key, k = jax.random.split(self.key)
        self.state = deme_ops.compete_demes(self.params, self.state, k, ctype)

    _REP_TRIGGERS = {"all": 0, "full_deme": 1, "full": 1, "corners": 2,
                     "deme-age": 3, "age": 3, "births": 4,
                     "sat-deme-predicate": 5}

    def _action_ReplicateDemes(self, args):
        """ReplicateDemes [trigger] (ref cPopulation::ReplicateDemes)."""
        from avida_tpu.ops import demes as deme_ops
        trig = args[0] if args else "full"
        trig = self._REP_TRIGGERS.get(str(trig), None) \
            if not str(trig).isdigit() else int(trig)
        if trig is None:
            raise ValueError(f"unknown ReplicateDemes trigger {args[0]!r}")
        self.key, k = jax.random.split(self.key)
        self.state = deme_ops.replicate_demes(
            self.params, self.state, k, trig,
            predicates=tuple(getattr(self, "_deme_predicates", ())))

    def _action_Pred_DemeResourceThresholdPredicate(self, args):
        """Attach a deme resource-threshold predicate
        (cActionPred_DemeResourceThresholdPredicate,
        PopulationActions.cc:4421): `<resource> <op> <value>`; consumed by
        ReplicateDemes sat-deme-predicate."""
        name, op, value = args[0], args[1], float(args[2])
        dres = [r.name for r in self.environment.deme_resources()]
        if name not in dres:
            raise ValueError(
                f"deme resource {name!r} not defined (have {dres})")
        if not hasattr(self, "_deme_predicates"):
            self._deme_predicates = []
        self._deme_predicates.append((dres.index(name), op, value))

    def _action_KillProb(self, args):
        """KillProb [prob]: each living organism dies with probability p
        (ref cActionKillProb, actions/PopulationActions.cc)."""
        p = float(args[0]) if args else 0.9
        self.key, k = jax.random.split(self.key)
        die = (jax.random.uniform(k, (self.params.num_cells,)) < p)             & self.state.alive
        self.state = self.state.replace(alive=self.state.alive & ~die)

    def _action_SerialTransfer(self, args):
        """SerialTransfer [transfer_size]: keep a uniform random sample of
        transfer_size organisms, kill the rest (ref cActionSerialTransfer)."""
        size = int(args[0]) if args else 1
        st = self.state
        n = self.params.num_cells
        self.key, k = jax.random.split(self.key)
        score = jnp.where(st.alive, jax.random.uniform(k, (n,)), -1.0)
        kth = jnp.sort(score)[-size]
        keep = st.alive & (score >= kth)
        self.state = st.replace(alive=keep)

    def _action_LoadPopulation(self, args):
        """LoadPopulation <file.spop> (ref cActionLoadPopulation,
        actions/SaveLoadActions.cc:289 -> cPopulation::LoadPopulation
        cc:6723): rebuild the population from a structured save."""
        from avida_tpu.utils import spop
        path = args[0]
        if self.config_dir and not os.path.isabs(path)                 and not os.path.exists(path):
            path = os.path.join(self.config_dir, args[0])
        if not os.path.exists(path) and not os.path.isabs(args[0]):
            cand = os.path.join(self.data_dir, args[0])
            if os.path.exists(cand):
                path = cand
        self.key, k = jax.random.split(self.key)
        orgs = spop.load_population(path, self.params, k)
        self.state = spop.restore_population(self.params, orgs, k)
        # per-cell task-execution lifetime totals are not part of the
        # reference .spop format; a sidecar written by SavePopulation
        # restores them so tasks_exe.dat stays continuous across a
        # save/load (absent sidecar -> totals restart at zero)
        side = path + ".tasks.npy"
        if os.path.exists(side):
            totals = np.load(side)
            if totals.shape == tuple(self.state.task_exe_total.shape):
                # device-owned copy, never a numpy view: this leaf is
                # donated into the update scan (the AOT-cache landmine
                # utils/checkpoint._build_state documents)
                self.state = self.state.replace(
                    task_exe_total=jnp.copy(
                        jnp.asarray(totals, jnp.int32)))
        self._reset_task_exe_baseline()
        if self.systematics is not None:
            from avida_tpu.systematics import GenotypeArbiter
            self.systematics = GenotypeArbiter(self.params.num_cells)
            for o in orgs:
                self.systematics.classify_seed(o["cell"], o["genome"],
                                               update=self.update)

    def _reset_task_exe_baseline(self):
        """Seed/reset the tasks_exe.dat diff baseline from the CURRENT
        state.  Must run whenever state is (re)loaded wholesale
        (LoadPopulation): the baseline is a host-side snapshot of the
        device lifetime totals, so after a restore the stale value would
        make the first tasks_exe.dat row report lifetime totals as one
        update's work -- or a negative delta if the restored totals are
        smaller."""
        self._summary_cache_update = None      # cached summary is stale too
        self._task_exe_prev = np.asarray(
            jnp.sum(self.state.task_exe_total, axis=0), np.int64)
        if self.telemetry is not None:
            self.telemetry.seed_task_totals(self._task_exe_prev)

    def _action_SavePopulation(self, args):
        from avida_tpu.utils import spop
        os.makedirs(self.data_dir, exist_ok=True)
        path = os.path.join(self.data_dir, f"detail-{self.update}.spop")
        spop.save_population(path, self.params, self.state, self.update)
        # sidecar: per-cell task-execution lifetime totals (not
        # representable in the reference .spop columns) so a LoadPopulation
        # keeps tasks_exe.dat deltas continuous
        np.save(path + ".tasks.npy", np.asarray(self.state.task_exe_total))

    def _dispatch(self, ev):
        handler = getattr(self, f"_action_{ev.action}", None)
        if handler is None:
            if ev.action not in self._warned_actions:
                self._warned_actions.add(ev.action)
                import sys
                print(f"[avida-tpu] warning: event action '{ev.action}' "
                      f"not implemented; skipping", file=sys.stderr)
            return
        handler(ev.args)

    def process_events(self):
        """Fire due events (ref cEventList::Process, called at the top of
        every update, Avida2Driver.cc:92).  Generation triggers compare the
        population average generation against the event's schedule.
        Idempotent per update (run() pre-fires begin events before the loop;
        the first loop iteration must not fire update-0 events again)."""
        if self._events_done_for == self.update:
            return
        self._events_done_for = self.update
        gen_events = [ev for ev in self.events if ev.trigger == "generation"]
        gen = float(self._last_ave_gen) if gen_events else 0.0
        for ev in self.events:
            if ev.trigger == "update":
                if ev.fires_at(self.update):
                    self._dispatch(ev)
            elif ev.trigger == "immediate":
                if self.update == 0:
                    self._dispatch(ev)
            elif ev.trigger in ("generation", "births"):
                # BIRTHS triggers compare cumulative births; generation
                # triggers the population-average generation
                # (cEventList.h:63 trigger enum)
                cur = (float(self._total_births) if ev.trigger == "births"
                       else gen)
                nxt = self._gen_next.setdefault(id(ev), ev.start)
                while cur >= nxt and nxt <= ev.stop:
                    self._dispatch(ev)
                    if ev.interval <= 0:
                        nxt = float("inf")      # one-shot
                    else:
                        nxt += ev.interval
                self._gen_next[id(ev)] = nxt

    # ---- the master update loop (Avida2Driver::Run equivalent) ----

    def run_update(self):
        """Run ONE update (does not advance self.update; callers do).
        Device-side bookkeeping lives in ops/update.update_scan -- this is
        the chunk-of-1 case plus the per-update reversion test and
        systematics feed.  Under telemetry the update runs phase-fenced
        through the recorder (bit-identical trajectory; observability/)
        and an update record lands in telemetry.jsonl."""
        tel = self.telemetry
        if tel is not None:
            executed = tel.update(self)
            if self._revert_on:
                with tel.timeline.phase("host_revert"):
                    self._apply_reversion()
            if self.systematics is not None:
                with tel.timeline.phase("host_systematics"):
                    self._feed_systematics()
            tel.emit(self)
            return executed
        executed = self._scan_updates(1)
        if self._revert_on:
            self._apply_reversion()
        if self.systematics is not None:
            self._feed_systematics()
        return executed

    def _apply_reversion(self):
        """Offspring fitness test: revert (to the parent genome) or
        sterilize newborns whose sandbox fitness classifies fatal /
        detrimental / neutral / beneficial vs their parent's
        (Divide_TestFitnessMeasures, cHardwareBase.cc:866; thresholds
        neut_min/max from NEUTRAL_MIN/MAX).  Sterilization follows the
        reference: the offspring lives but can never divide (sterile
        flag).  Runs at birth rather than at divide (the lockstep flush
        is the divide boundary).  Documented edges: a newborn whose
        parent cell was overwritten this update cannot be reverted --
        inviable (fatal) ones are refused (killed), others admitted
        as-is; device-side per-update birth counters (BIRTHS triggers,
        deaths) are computed before this host step and may overcount by
        the refused offspring."""
        st = self.state
        alive = np.asarray(st.alive)
        born = (np.asarray(st.birth_update) == self.update) & alive
        cells = np.nonzero(born)[0]
        if not cells.size:
            return
        # device-gather ONLY the newborn + parent rows (update-granularity
        # transfer discipline, SURVEY SS5)
        idx = jnp.asarray(cells)
        parents = np.asarray(st.parent_id[idx])
        pidx = jnp.asarray(np.clip(parents, 0, None))
        child_g = np.asarray(st.genome[idx])
        child_l = np.asarray(st.genome_len[idx])
        par_g = np.asarray(st.genome[pidx])
        par_l = np.asarray(st.genome_len[pidx])
        parent_ok = ((parents >= 0) & alive[np.clip(parents, 0, None)]
                     & (np.asarray(st.birth_update[pidx]) != self.update))
        child_fit = self.test_metrics.get_fitness(child_g, child_l)
        parent_fit = self.test_metrics.get_fitness(par_g, par_l)
        neut_min = parent_fit * self._neut_min
        neut_max = parent_fit * self._neut_max
        cat = np.where(child_fit == 0.0, 0,
                       np.where(child_fit < neut_min, 1,
                                np.where(child_fit <= neut_max, 2, 3)))
        probs = np.asarray([self._revert["fatal"], self._revert["neg"],
                            self._revert["neut"], self._revert["pos"]],
                           np.float64)                      # [4, 2]
        u = self._revert_rng.random((2, cells.size))
        want_revert = u[0] < probs[cat, 0]
        revert = want_revert & parent_ok
        sterilize = u[1] < probs[cat, 1]
        # fatal reversions with no parent genome left are refused outright
        kill_fallback = want_revert & ~parent_ok & (cat == 0)
        if self.tracer is not None:
            # host-side flight-recorder events: reversion/sterilization
            # firings (merged into the next drain's per-update records)
            from avida_tpu.observability.tracer import EV_REVERT, EV_STERILIZE
            for c, pc in zip(cells[revert], parents[revert]):
                self.tracer.record_host_event(self.update, int(c),
                                              EV_REVERT, int(pc))
            for c, cc in zip(cells[sterilize], cat[sterilize]):
                self.tracer.record_host_event(self.update, int(c),
                                              EV_STERILIZE, int(cc))
        if not (revert.any() or sterilize.any() or kill_fallback.any()):
            return
        new_st = st
        if revert.any():
            from avida_tpu.ops.interpreter import pack_tape
            rev_cells = jnp.asarray(cells[revert])
            rev_parents = jnp.asarray(parents[revert])
            pg = new_st.genome[rev_parents]
            pl = new_st.genome_len[rev_parents]
            new_st = new_st.replace(
                genome=new_st.genome.at[rev_cells].set(pg),
                tape=new_st.tape.at[rev_cells].set(pack_tape(pg)),
                genome_len=new_st.genome_len.at[rev_cells].set(pl),
                mem_len=new_st.mem_len.at[rev_cells].set(pl),
                breed_true=new_st.breed_true.at[rev_cells].set(True),
            )
        if sterilize.any():
            # reference semantics: the offspring lives (occupying its
            # cell, competing for space) but can never divide
            mark = jnp.asarray(cells[sterilize])
            new_st = new_st.replace(
                sterile=new_st.sterile.at[mark].set(True))
        if kill_fallback.any():
            kill = jnp.asarray(cells[kill_fallback])
            new_st = new_st.replace(alive=new_st.alive.at[kill].set(False))
        self.state = new_st

    def run_updates(self, k: int):
        """Run k consecutive updates as one device program (ops/update.py
        update_scan) -- no per-update host dispatch.  Only valid when no
        event is due inside the window; with systematics enabled the
        device-side newborn ring buffer records per-update birth
        attribution and World.run caps stretches at 8 updates, draining
        the buffer via _feed_systematics at each chunk boundary.
        Advances self.update by k."""
        executed = self._scan_updates(k)
        self.update += k
        return executed

    def _scan_updates(self, k: int):
        """Common device path: returns the per-update executed-count vector
        (int32[k] device array; host sums in int64 at flush time).

        Packed residency (ops/packed_chunk.py): when the configuration
        qualifies (requires TPU_SYSTEMATICS=0 -- a populated newborn
        ring keeps the per-update path), update_scan keeps the state in
        the kernel's plane layout for the whole k-update stretch and
        unpacks at return.  Every host consumer downstream of this call
        therefore still sees canonical [N, L] state: the newborn drain
        snapshot, the flight-recorder drain, auto-save / preemption
        checkpoints and .dat readbacks all run BETWEEN _scan_updates
        calls, i.e. strictly after the chunk-boundary unpack
        (tests/test_native_checkpoint.py, tests/test_tracer.py)."""
        assert self.state is not None, "no population injected"
        from avida_tpu.utils import compilecache
        if self.profiler is not None:
            self.profiler.chunk_begin(k)
        pre = None
        if self._scrub_every > 0:
            self._chunk_no += 1
            if self._chunk_no % self._scrub_every == 0:
                # retain the pre-chunk state for the shadow replay:
                # device-owned COPIES, because update_scan donates its
                # input buffers (both executions consume their own)
                pre = (jax.tree.map(jnp.copy, self.state), self.update)
        self.state, (executed, births, deaths, dts, ave_gens, n_alive) = \
            compilecache.call(
                update_scan, "update_scan",
                (self.params, self.state, k, self._run_key,
                 self.neighbors, jnp.int32(self.update)),
                cfg=self.cfg, log=self._compile_cache_log)
        # avida time advances by 1/ave_gestation per update (the reference's
        # cStats::ProcessUpdate bookkeeping).  All accumulators stay device-
        # side scalars -- no host sync in the update loop.
        self._avida_time = self._avida_time + dts.sum()
        self._last_ave_gen = ave_gens[-1]
        self._deaths_this = deaths[-1]
        self._prev_alive = n_alive[-1]
        self._total_births = self._total_births + births.sum()
        if self.profiler is not None:
            # probe chunks fence + run the staged phase probe on
            # copies; every other chunk this is two perf_counter calls
            self.profiler.chunk_end_solo(self, k)
        if self._digest_on or pre is not None:
            self._integrity_boundary(k, pre)
        return executed

    # ---- silent-corruption integrity plane (README "Integrity plane") --

    def _shadow_params(self):
        """Params for the shadow replay: the PRISTINE program.  Injected
        device-side faults (nan/bitflip) model a transient hardware
        event, which by definition fires in the live execution only --
        the reference re-execution must not replay it.  In production
        (no faults armed) this IS self.params, so the shadow runs the
        already-compiled live program."""
        p = self.params
        if p.fault_nan or getattr(p, "fault_bitflip", ()):
            return p.replace(fault_nan=(), fault_bitflip=())
        return p

    def _engine_name(self) -> str:
        """Which chunk engine the scan just ran -- named in divergence
        errors so the supervisor's kernel-implication heuristic
        (pallas_suspect) can apply the one-shot XLA degradation."""
        from avida_tpu.ops import packed_chunk
        from avida_tpu.ops.update import use_pallas_path
        if not use_pallas_path(self.params):
            return "xla"
        return ("pallas-packed"
                if packed_chunk.active(self.params, self.state)
                else "pallas")

    def _integrity_record(self, event: str, **fields):
        from avida_tpu.utils import integrity
        integrity.append_integrity_record(
            self.data_dir, event,
            max_bytes=int(self.cfg.get("TPU_RUNLOG_MAX_BYTES", 16 << 20)),
            **fields)

    def _resolve_digest(self, pending):
        """Host-resolve one deferred digest scalar (its chunk finished
        at least one boundary ago, so the readback is free) into the
        heartbeat value + the per-chunk runlog record."""
        import time as _time
        u, dev = pending
        t0 = _time.monotonic()
        val = int(np.asarray(dev))
        from avida_tpu.utils import integrity
        integrity.note_digest_ms((_time.monotonic() - t0) * 1e3)
        self.state_digest = (u, val)
        self._integrity_record("digest", update=u, digest=f"{val:#010x}")

    def _flush_digest(self):
        """Resolve any deferred digest NOW (host sync points: checkpoint
        save, run exit) so the heartbeat/runlog never lose the last
        boundary's value."""
        prev, self._digest_pending = self._digest_pending, None
        if prev is not None:
            self._resolve_digest(prev)

    def _integrity_boundary(self, k: int, pre):
        """Per-chunk integrity work, immediately after the scan returned
        and BEFORE any host-side mutation of the state: compute the live
        digest (deferred readback on the hot path), and when this chunk
        was sampled for scrubbing (`pre` holds the retained pre-chunk
        state) re-execute it and compare digests -- any mismatch on this
        deterministic engine is silent corruption, raised as
        StateDivergenceError (child exit 67, the supervisor's `sdc`
        class)."""
        import time as _time

        from avida_tpu.ops.digest import state_digest
        from avida_tpu.utils import integrity
        u1 = self.update + k
        t0 = _time.monotonic()
        d_live = state_digest(self.state)
        integrity.note_digest_ms((_time.monotonic() - t0) * 1e3)
        self._flush_digest()
        if pre is None:
            # digest-only boundary: queue for the deferred readback
            self._digest_pending = (u1, d_live)
            return
        # scrub: shadow re-execution of the chunk just run (a host sync
        # point -- amortized by the TPU_SCRUB_EVERY cadence)
        from avida_tpu.utils import compilecache
        pre_st, u0 = pre
        integrity.note_scrub()
        shadow_st, _ = compilecache.call(
            update_scan, "update_scan",
            (self._shadow_params(), pre_st, k, self._run_key,
             self.neighbors, jnp.int32(u0)),
            cfg=self.cfg, log=self._compile_cache_log)
        t0 = _time.monotonic()
        d_shadow = state_digest(shadow_st)
        live, shad = int(np.asarray(d_live)), int(np.asarray(d_shadow))
        integrity.note_digest_ms((_time.monotonic() - t0) * 1e3)
        if live != shad:
            integrity.note_mismatch()
            engine = self._engine_name()
            self._integrity_record(
                "scrub", update=u1, chunk_updates=k, ok=False,
                live=f"{live:#010x}", shadow=f"{shad:#010x}",
                engine=engine,
                last_verified_update=self._last_verified_update)
            from avida_tpu.observability.runlog import emit_event
            emit_event(self, "state_divergence", update=u1,
                       live=f"{live:#010x}", shadow=f"{shad:#010x}")
            from avida_tpu.utils.integrity import StateDivergenceError
            raise StateDivergenceError(
                f"silent state divergence in updates [{u0}, {u1}): live "
                f"digest {live:#010x} != shadow replay {shad:#010x} "
                f"(engine {engine}, "
                f"last_verified_update={self._last_verified_update})")
        self._last_verified_update = u1
        if self._digest_on:
            self.state_digest = (u1, live)
            self._integrity_record("digest", update=u1,
                                   digest=f"{live:#010x}")
        self._integrity_record("scrub", update=u1, chunk_updates=k,
                               ok=True, digest=f"{live:#010x}")

    def _chunkable(self) -> bool:
        """May event-free stretches run as one scanned device program?
        Anything needing per-update host work (reversion tests, telemetry
        phase fencing, generation/births event triggers) forces single
        stepping.  Shared with the multi-world batched driver
        (avida_tpu/parallel/multiworld.py), which refuses un-chunkable
        configs outright."""
        return (not self._revert_on and self.telemetry is None and
                not any(ev.trigger in ("generation", "births")
                        for ev in self.events))

    def _plan_stretch(self, max_updates, max_stretch: int) -> int:
        """Length of the next event-free stretch starting at self.update,
        under the event schedule, the systematics drain cap and
        TPU_MAX_STRETCH.  Power-of-two buckets keep the number of
        compiled scan variants at <= 8 instead of one per distinct gap.
        The multi-world batched driver calls this SAME planner, so a
        batched run's chunk grid is identical to each member's solo
        grid -- the alignment byte-identical per-world checkpoints rest
        on."""
        due = self._next_event_due()
        if max_updates is not None:
            due = min(due, max_updates)
        cap_stretch = 128.0 if self.systematics is None else 8.0
        if max_stretch > 0:
            cap_stretch = min(cap_stretch, float(max_stretch))
        gap = int(max(1.0, min(due - self.update, cap_stretch)))
        return 1 << (gap.bit_length() - 1)

    def _next_event_due(self) -> float:
        """Earliest update > self.update at which any update-trigger event
        fires (inf if none).  Generation/immediate triggers are handled by
        the caller (they force per-update stepping)."""
        nxt = float("inf")
        for ev in self.events:
            if ev.trigger != "update":
                continue
            if self.update < ev.start:
                nxt = min(nxt, ev.start)
            elif ev.interval > 0:
                k = (self.update - ev.start) // ev.interval
                cand = ev.start + (k + 1) * ev.interval
                if cand <= ev.stop:
                    nxt = min(nxt, cand)
        return nxt

    _NB_SNAP_FIELDS = ("nb_count", "nb_genome", "nb_len", "nb_cell",
                       "nb_parent", "nb_update", "alive", "birth_update",
                       "genome", "genome_len", "parent_id")

    def _snapshot_newborns(self):
        """Device-side copy of everything the systematics drain reads
        (newborn ring buffer + the occupancy/ancestry arrays the overflow
        fallback scans), for a DEFERRED drain: the copies are async
        device ops (no host sync), the live buffer counter is zeroed, and
        the host ingests the snapshot one chunk later -- after the next
        chunk has been dispatched -- so phylogeny bookkeeping overlaps
        device compute (the zero-sync run-loop pipeline)."""
        st = self.state
        snap = {name: jnp.copy(getattr(st, name))
                for name in self._NB_SNAP_FIELDS}
        snap["update_at"] = self.update
        snap["win_start"] = self._last_drain_update
        self._last_drain_update = self.update
        self.state = st.replace(nb_count=jnp.zeros((), jnp.int32))
        return snap

    def _flush_newborn_drain(self):
        """Ingest any deferred newborn snapshot NOW (a host sync point).
        Called at event/report boundaries, before any non-chunked step,
        and before phylogeny pruning, so systematics observers never see
        a stale tree and drain records stay in update order."""
        snap, self._nb_pending = self._nb_pending, None
        if snap is not None:
            self._feed_systematics(snap)

    def _flush_trace(self):
        """Drain the deferred flight-recorder snapshot AND the live ring
        NOW (a host sync point): run exit, preemption, checkpoint save --
        the runlog must hold every event up to the boundary before the
        state (with its zeroed cursor) is serialized or the process
        exits."""
        if self.tracer is None:
            return
        prev, self._trace_pending = self._trace_pending, None
        if prev is not None:
            self.tracer.drain(prev)
        if self.state is not None:
            self.tracer.drain(self.tracer.snapshot(self))

    def _events_fire_now(self) -> bool:
        """Does any event fire at the CURRENT update?  (Generation/births
        triggers force per-update stepping, so they count as always-due;
        used to decide whether a pending newborn snapshot must be
        ingested before process_events reads systematics.)"""
        for ev in self.events:
            if ev.trigger == "update":
                if ev.fires_at(self.update):
                    return True
            elif ev.trigger == "immediate":
                if self.update == 0:
                    return True
            else:
                return True
        return False

    def _feed_systematics(self, snap=None):
        """Drain the device-side newborn record buffer into the host
        phylogeny (chunked-run capable: records carry their update number,
        so a K-update scan feeds K groups in order -- including newborns
        that were overwritten later in the chunk, which the old
        state-scan feed missed).  Overflow (more births than the 2N-record
        buffer) falls back to a state scan for the window and warns.

        snap: a deferred snapshot from _snapshot_newborns (the pipelined
        run loop); None reads the live state synchronously."""
        if snap is None:
            st = self.state
            snap = {name: getattr(st, name)
                    for name in self._NB_SNAP_FIELDS}
            snap["update_at"] = self.update
            snap["win_start"] = self._last_drain_update
            self._last_drain_update = self.update
            if int(np.asarray(st.nb_count)):
                self.state = st.replace(nb_count=jnp.zeros((), jnp.int32))
        count = int(np.asarray(snap["nb_count"]))
        cap = snap["nb_genome"].shape[0]
        alive = np.asarray(snap["alive"])
        overflow = count > cap
        if overflow:
            import sys
            print(f"[avida-tpu] warning: newborn buffer overflow "
                  f"({count} > {cap}); recovering surviving births from a "
                  f"state scan (overwritten-then-dead newborns are lost "
                  f"this window)", file=sys.stderr)
            count = cap
        if count:
            genomes = np.asarray(snap["nb_genome"][:count])
            lens = np.asarray(snap["nb_len"][:count])
            cells = np.asarray(snap["nb_cell"][:count])
            parents = np.asarray(snap["nb_parent"][:count])
            updates = np.asarray(snap["nb_update"][:count])
            if overflow:
                # state-scan fallback for the dropped tail: any cell whose
                # birth_update falls inside this drain window and is not
                # among the buffered records still exists in state (it is
                # the cell's LAST birth); recover genome/parent from the
                # snapshotted arrays.  Only newborns that were overwritten
                # by a later birth AND died are unrecoverable.
                bu = np.asarray(snap["birth_update"])
                # window = updates since the last drain (inclusive: the
                # previous drain set the window start to one past ITS
                # window); bu >= 0 excludes seed cells (bu == -1)
                win_start = snap["win_start"]
                in_window = alive & (bu >= max(win_start, 0))
                recorded = set(zip(cells.tolist(), updates.tolist()))
                extra = np.asarray([c for c in np.nonzero(in_window)[0]
                                    if (int(c), int(bu[c])) not in recorded],
                                   np.int64)
                if extra.size:
                    pid = np.asarray(snap["parent_id"])
                    genomes = np.concatenate(
                        [genomes, np.asarray(snap["genome"][extra])])
                    lens = np.concatenate(
                        [lens, np.asarray(snap["genome_len"][extra])])
                    cells = np.concatenate([cells, extra])
                    parents = np.concatenate([parents, pid[extra]])
                    updates = np.concatenate([updates, bu[extra]])
                    order = np.argsort(updates, kind="stable")
                    genomes, lens, cells, parents, updates = (
                        genomes[order], lens[order], cells[order],
                        parents[order], updates[order])
                    count += extra.size
            # feed groups in update order (records are already appended in
            # update order; split on the update column)
            start = 0
            for i in range(1, count + 1):
                if i == count or updates[i] != updates[start]:
                    u = int(updates[start])
                    # deaths resolve against the end-of-window occupancy for
                    # every group (intermediate occupancy is not retained)
                    self.systematics.process(
                        u, alive, cells[start:i], genomes[start:i],
                        lens[start:i], parents[start:i])
                    start = i
        else:
            self.systematics.process(
                snap["update_at"], alive, np.zeros(0, np.int64),
                np.zeros((0, self.params.max_memory), np.int8),
                np.zeros(0, np.int32), np.zeros(0, np.int32))

    # ---- crash safety: native checkpoints + preemption (utils/checkpoint) --

    def _ckpt_base(self) -> str | None:
        d = str(self.cfg.get("TPU_CKPT_DIR", "-") or "-")
        return None if d in ("-", "") else d

    def _compile_cache_log(self, **fields):
        """Journal one persistent-program-cache action as a
        {"record": "event", "event": "compile_cache"} runlog line:
        loads/compiles/stores are the warmth evidence; corrupt / stale /
        store-failure fallbacks are the loud invalidation trail the
        cache contract promises (utils/compilecache.py)."""
        from avida_tpu.observability.runlog import emit_event
        emit_event(self, "compile_cache", **fields)

    def _install_preempt_handlers(self):
        """SIGTERM/SIGINT set a flag that World.run checks at update-chunk
        boundaries (clean preemption: drain, final checkpoint, return).
        Returns the displaced handlers for restoration; no-op off the
        main thread (signal.signal raises ValueError there)."""
        import signal
        saved = {}

        def trip(signum, frame):
            if self._preempt and signum == signal.SIGINT:
                # second Ctrl-C: the user wants OUT now, not a graceful
                # boundary stop -- escalate (the run loop's finally still
                # closes the .dat/telemetry writers)
                raise KeyboardInterrupt
            self._preempt = True

        for s in (signal.SIGTERM, signal.SIGINT):
            try:
                saved[s] = signal.signal(s, trip)
            except ValueError:
                pass
        return saved

    def save_checkpoint(self, base_dir: str | None = None,
                        audit: bool | None = None) -> str:
        """Write one native checkpoint generation (bit-exact run state:
        full PopulationState, PRNG keys, host counters, event cursors,
        systematics tables).  Atomic: tmp dir + fsync + rename; rolling
        retention via TPU_CKPT_KEEP.  Returns the generation path.

        audit=None follows TPU_CKPT_AUDIT (default 1): the invariant
        sweep is a separate jitted program, so frequently-checkpointing
        short-lived runs (supervised chaos children, latency-sensitive
        tenants) can opt out of its one-off compile with
        TPU_CKPT_AUDIT=0 -- corruption then surfaces at restore/audit
        boundaries instead of save time."""
        if audit is None:
            audit = bool(int(self.cfg.get("TPU_CKPT_AUDIT", 1)))
        from avida_tpu.utils import checkpoint as ckpt_mod
        base = base_dir or self._ckpt_base()
        if base is None:
            raise ValueError(
                "no checkpoint directory (set TPU_CKPT_DIR or pass one)")
        # the systematics snapshot must be current: ingest any deferred
        # newborn drain (host sync) before serializing; likewise the
        # flight-recorder ring drains to the runlog first, so the saved
        # cursor is 0 and a resume never replays stale events
        self._flush_newborn_drain()
        self._flush_trace()
        self._flush_digest()
        if audit:
            from avida_tpu.utils.audit import check_invariants
            check_invariants(self.params, self.state,
                             where=f"checkpoint save (update {self.update})")
        path = ckpt_mod.save_checkpoint(base, self)
        if self.faults is not None:
            # chaos hooks: corrupt-ckpt / torn-manifest mutate the
            # generation JUST published (deterministic at-rest damage;
            # the CRC/manifest fallback must recover on the next resume)
            self.faults.at_save(self, path)
        return path

    def resume(self, ckpt_dir: str | None = None,
               audit: bool | None = None,
               at_update: int | None = None) -> int:
        """Restore this world from the newest VALID checkpoint generation
        and position the run loop to continue bit-exactly (the run PRNG
        stream is a pure function of the restored key and update number).
        Corrupt generations fall back to the previous retained one with a
        runlog warning.  Returns the restored update number.

        at_update pins the restore to one specific generation (the
        multi-world driver re-aligns its members on a common update;
        parallel/multiworld.py)."""
        from avida_tpu.utils import checkpoint as ckpt_mod
        base = ckpt_dir or self._ckpt_base()
        if base is None:
            raise ValueError(
                "no checkpoint directory (set TPU_CKPT_DIR or pass one)")
        update = ckpt_mod.restore_checkpoint(base, self, at_update=at_update)
        # output continuity: files the resumed run opens extend the
        # preempted run's rows instead of truncating them -- after
        # trimming any rows PAST the restored update (a crash that
        # outran the last auto-save, or a fallback to an older
        # generation, leaves newer rows that would otherwise duplicate)
        self._dat_append = True
        output_mod.trim_dat_rows(self.data_dir, update)
        from avida_tpu.observability.runlog import trim_update_records
        trim_update_records(os.path.join(self.data_dir, "telemetry.jsonl"),
                            update)
        # analytics census continuity: censuses PAST the restored
        # update describe a rolled-back timeline (the resumed run may
        # evolve differently) -- trim them so downstream consumers
        # (compare_equ's census-native side) never count a dead
        # branch's discovery; the census AT the restored update
        # describes exactly the restored state and is kept (strict
        # cutoff for analytics records inside trim_update_records).
        # The rotation aside is trimmed too: a 16MB rotation firing
        # between the restored generation and the crash would
        # otherwise preserve dead-branch censuses that
        # native_from_analytics explicitly reads (journal + '.1').
        ana_log = os.path.join(self.data_dir, "analysis",
                               "analytics.jsonl")
        trim_update_records(ana_log, update)
        trim_update_records(ana_log + ".1", update)
        if audit is None:
            audit = bool(int(self.cfg.get("TPU_CKPT_AUDIT", 1)))
        if audit:
            from avida_tpu.utils.audit import check_invariants
            check_invariants(self.params, self.state,
                             where=f"checkpoint restore (update {update})")
        # the restored generation passed the manifest digest check
        # (restore_checkpoint verifies it whenever the manifest carries
        # one), so scrubbing's verification horizon restarts here
        self._last_verified_update = update
        return update

    def run(self, max_updates: int | None = None):
        if self.state is None:
            # fire begin events (Inject) before the loop
            self.process_events()
            if self.state is None:
                self.inject()
        start_insts = self._cum_insts
        ckpt_base = self._ckpt_base()
        ckpt_every = int(self.cfg.get("TPU_CKPT_EVERY", 0))
        audit_every = int(self.cfg.get("TPU_AUDIT_EVERY", 0))
        self.preempted = False
        self._preempt = False
        handlers = self._install_preempt_handlers() if ckpt_base else {}
        last_ckpt = self.update
        last_audit = self.update
        # event-free stretches run as one device program; anything needing
        # per-update host work (systematics, generation triggers,
        # telemetry phase fencing) forces single stepping
        can_chunk = self._chunkable()
        # TPU_MAX_STRETCH bounds the event-free stretch (0 = engine
        # default).  Supervised runs set it to trade a little dispatch
        # overhead for operational granularity: chunk boundaries gate
        # the heartbeat export, the auto-save cadence and preemption
        # latency, so a tighter stretch bounds all three
        max_stretch = int(self.cfg.get("TPU_MAX_STRETCH", 0))
        try:
            while not self._exit and not self._preempt:
                if max_updates is not None and self.update >= max_updates:
                    break
                if self._nb_pending is not None and self._events_fire_now():
                    # report/event boundary: the phylogeny must be current
                    # before any Print action reads it -- the ONE host sync
                    # point of the pipelined loop
                    self._flush_newborn_drain()
                if self.telemetry is not None:
                    # event dispatch covers the .dat writes and their device
                    # readbacks -- the "host I/O" share of the next record
                    with self.telemetry.timeline.phase("events_io"):
                        self.process_events()
                else:
                    self.process_events()
                if self._exit:
                    break
                stretch = (self._plan_stretch(max_updates, max_stretch)
                           if can_chunk else 1)
                if stretch > 1:
                    self._pending_exec.append(self.run_updates(stretch))
                    if self.systematics is not None:
                        # zero-sync pipeline: snapshot this chunk's newborn
                        # records device-side (async copies), then ingest
                        # the PREVIOUS chunk's snapshot while this chunk is
                        # still running on device -- host phylogeny
                        # bookkeeping overlaps device compute instead of
                        # fencing it
                        prev, self._nb_pending = (self._nb_pending,
                                                  self._snapshot_newborns())
                        if prev is not None:
                            self._feed_systematics(prev)
                else:
                    # queue the device vector; host-sync at report boundaries
                    self._flush_newborn_drain()
                    self._pending_exec.append(self.run_update())
                    self.update += 1
                if self.tracer is not None:
                    # flight-recorder drain, same deferred pipeline as the
                    # newborn snapshot: copy this boundary's ring device-
                    # side (async), host-ingest the PREVIOUS boundary's
                    # snapshot while the next chunk runs
                    prev_t, self._trace_pending = (self._trace_pending,
                                                   self.tracer.snapshot(self))
                    if prev_t is not None:
                        self.tracer.drain(prev_t)
                if self.exporter is not None:
                    # deferred (publishes the PREVIOUS boundary's values):
                    # a synchronous export here would fence the chunk
                    # just dispatched and defeat the zero-sync pipeline
                    self.exporter.export_deferred(self)
                if len(self._pending_exec) >= 256:
                    self._flush_exec()
                if self.systematics is not None and self.update % 100 == 0:
                    self._flush_newborn_drain()
                    self.systematics.prune_extinct(keep_ancestry=True)
                # robustness hooks, both at update-chunk boundaries: the
                # periodic invariant audit and the rolling auto-save
                if audit_every and self.update - last_audit >= audit_every:
                    from avida_tpu.utils.audit import check_invariants
                    check_invariants(self.params, self.state,
                                     where=f"update {self.update}")
                    last_audit = self.update
                if ckpt_base and ckpt_every \
                        and self.update - last_ckpt >= ckpt_every:
                    self.save_checkpoint(ckpt_base)
                    last_ckpt = self.update
                    if self.analytics is not None:
                        # checkpoint boundary = census boundary: the
                        # save just synced the host view, so the
                        # incremental census reads it for free
                        self.analytics.refresh(self)
                if self.faults is not None:
                    # injected failures fire at chunk boundaries, AFTER
                    # any auto-save due at the same boundary (so e.g.
                    # `sigkill@update=N` tests the resume path, not a
                    # save race)
                    self.faults.at_boundary(self)
            # orderly exit (normal or preempted): the phylogeny drain and,
            # on preemption, the final checkpoint both need a consistent
            # host view -- neither runs after an exception (the state may
            # be mid-mutation), but the finally below still closes writers
            self._flush_newborn_drain()
            self._flush_trace()
            self._flush_digest()
            if self._preempt and ckpt_base and self.state is not None:
                self.save_checkpoint(ckpt_base)
            elif ckpt_base and self.state is not None \
                    and int(self.cfg.get("TPU_CKPT_FINAL", 0)) \
                    and self.update != last_ckpt:
                # TPU_CKPT_FINAL=1: a completed run publishes its final
                # state as a generation too, so downstream tooling (the
                # chaos suite's bit-exactness proof, analyze pipelines)
                # reads the end state without re-running the world
                self.save_checkpoint(ckpt_base)
            self.preempted = self._preempt
            if self.analytics is not None and self.state is not None:
                # exit census: the freshness contract holds through the
                # end of the run (durable -- this is the last word)
                self.analytics.refresh(self, durable=True)
            if self.profiler is not None and self.state is not None:
                # closing footprint + perf record BEFORE the final
                # heartbeat so its exposition carries the exit numbers
                self.profiler.final(self.state, self.update,
                                    params=self.params)
            if self.exporter is not None and self.state is not None:
                self.exporter.export(self)    # final heartbeat (preempted=1)
        finally:
            import signal as _signal
            for s, h in handlers.items():
                try:
                    _signal.signal(s, h)
                except (ValueError, OSError):
                    pass
            # .dat handles and the telemetry recorder are flushed/closed on
            # ANY exit path -- exception, KeyboardInterrupt, preemption or
            # normal return -- so a crash never loses the buffered tail of
            # telemetry.jsonl or a half-written .dat row
            for f in self._files.values():
                try:
                    f.close()
                except Exception:
                    pass
            self._files = {}
            if self.telemetry is not None:
                try:
                    self.telemetry.close()
                except Exception:
                    pass
            if self.tracer is not None:
                try:
                    self.tracer.close()
                except Exception:
                    pass
            # a SECOND run() on this world must extend its own .dat files,
            # not truncate them: every file handle was just closed, so any
            # reopen (same action, same path) now arms append mode --
            # single header, continuous rows (the PR-4 known wart)
            self._dat_append = True
        return self._flush_exec() - start_insts

    @property
    def num_organisms(self) -> int:
        return int(np.asarray(self.state.alive).sum())
