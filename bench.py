"""Headline benchmark: organism-instructions/second on the stock logic-9 world.

Protocol (BASELINE.md): heads-default instruction set, logic-9 environment,
merit-proportional scheduling, ~100k organisms (320x320 grid fully seeded
with the default ancestor so the measurement starts at target population).
Baseline = 1e8 org-inst/sec (BASELINE.json north star; the reference itself
publishes no absolute numbers).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline",
"phases"}.  The headline fields are measured exactly as before (fused
device-resident scan, host sync only at the end); "phases" is an
informational per-phase wall-time breakdown (ms/update) from the staged
telemetry harness (avida_tpu/observability/harness.py), measured AFTER
the headline timing on the same world.  BENCH_PHASES=0 skips it.
"""

from __future__ import annotations

import json
from functools import partial
import os
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np

BASELINE_INST_PER_SEC = 1e8


def build(world_x, world_y, max_memory, seed):
    from avida_tpu.config import AvidaConfig
    from avida_tpu.core.state import zeros_population, make_cell_inputs
    from avida_tpu.ops import birth as birth_ops
    from avida_tpu.world import World, default_ancestor

    cfg = AvidaConfig()
    cfg.WORLD_X = world_x
    cfg.WORLD_Y = world_y
    cfg.TPU_MAX_MEMORY = max_memory
    cfg.RANDOM_SEED = seed
    # The bench measures the DEFAULT config: uncapped reference-faithful
    # merit bursts (round-5 change; the round-4 bench defaulted to the
    # cap-30 throughput opt-in and was called out for it).  BENCH_CAP=30
    # opts into capped burst scheduling with banking -- ~1.5x faster,
    # documented scheduling deviation (ops/update.py).
    cfg.TPU_MAX_STEPS_PER_UPDATE = int(os.environ.get("BENCH_CAP", "0"))
    w = World(cfg=cfg)
    anc = default_ancestor(w.instset)

    # Seed EVERY cell with the ancestor (mass InjectAll; reference action
    # "InjectAll", PopulationActions.cc) so throughput is measured at full
    # population from update 0.
    n, L, R = w.params.num_cells, w.params.max_memory, w.params.num_reactions
    st = zeros_population(n, L, R, w.params.num_global_res,
                          w.params.num_spatial_res)
    key = jax.random.key(seed)
    k_in, key = jax.random.split(key)
    g = np.zeros(L, np.int8)
    g[: len(anc)] = anc
    glen = len(anc)
    gm = jnp.asarray(np.broadcast_to(g, (n, L)))
    st = st.replace(
        inputs=make_cell_inputs(k_in, n),
        tape=gm.astype(jnp.uint8), genome=gm,
        mem_len=jnp.full(n, glen, jnp.int32),
        genome_len=jnp.full(n, glen, jnp.int32),
        alive=jnp.ones(n, bool),
        merit=jnp.full(n, float(glen), jnp.float32),
        cur_bonus=jnp.full(n, w.params.default_bonus, jnp.float32),
        executed_size=jnp.full(n, glen, jnp.int32),
        copied_size=jnp.full(n, glen, jnp.int32),
        max_executed=jnp.full(n, w.params.age_limit * glen, jnp.int32),
    )
    neighbors = jnp.asarray(
        birth_ops.neighbor_table(world_x, world_y, cfg.WORLD_GEOMETRY))
    return w.params, st, neighbors, key


def measure(world, warmup, timed, chunk=25, seed=100, sharded=False):
    """org-inst/s at a given world side length (world x world organisms).
    Returns (inst_per_sec, params, final_state).

    sharded=True places the population over ALL visible devices
    (parallel/mesh.py) before timing -- the same protocol, measured
    through the shard_map'd kernel path (BENCH_SHARDED=1).

    When the packed-resident chunk qualifies (ops/packed_chunk.py; the
    default TPU configuration does), each timed chunk packs once, runs
    its updates on the resident [LP, N] planes with the packed-native
    birth flush, and unpacks once -- the round-6 tentpole path.  The
    measured protocol is otherwise unchanged."""
    from avida_tpu.ops import packed_chunk
    from avida_tpu.ops.update import update_step

    params, st, neighbors, key = build(world, world, 256, seed=seed)
    if sharded:
        from avida_tpu.parallel import (make_mesh, shard_neighbors,
                                        shard_population)
        mesh = make_mesh()
        st = shard_population(st, mesh)
        neighbors = shard_neighbors(neighbors, mesh)
    packed = packed_chunk.active(params, st)

    @partial(jax.jit, donate_argnums=(0,))
    def run_chunk(st, key, u0):
        if packed:
            pc = packed_chunk.pack_chunk(params, st)

            def pbody(carry, i):
                pc, key = carry
                key, k = jax.random.split(key)
                pc, executed = packed_chunk.update_step_packed(
                    params, pc, k, neighbors, u0 + i)
                return (pc, key), executed
            (pc, key), ex = jax.lax.scan(pbody, (pc, key),
                                         jnp.arange(chunk))
            return packed_chunk.unpack_chunk(params, pc), key, ex.sum()

        def body(carry, i):
            st, key = carry
            key, k = jax.random.split(key)
            st, executed = update_step(params, st, k, neighbors, u0 + i)
            return (st, key), executed
        (st, key), ex = jax.lax.scan(body, (st, key), jnp.arange(chunk))
        return st, key, ex.sum()

    for c in range(warmup):
        st, key, executed = run_chunk(st, key, jnp.int32(c * chunk))
    jax.block_until_ready(st)

    t0 = time.perf_counter()
    counts = []
    for c in range(warmup, warmup + timed):
        st, key, executed = run_chunk(st, key, jnp.int32(c * chunk))
        counts.append(executed)
    jax.block_until_ready(st)
    dt = time.perf_counter() - t0
    executed_total = int(sum(int(x) for x in counts))
    return executed_total / dt, params, st


def kernel_facts(params, st):
    """Routing + budget-tail facts for the bench JSON line: which
    interpret path the measurement took, over how many devices/shards,
    the measured per-block budget utilization of the final state under
    the CURRENT lane mapping (1.0 = no lockstep tail waste), and
    budget_tail_skip_pct -- the share of lockstep lane-cycles the
    kernel's two-level scheduler skips vs a single global while_loop
    (ops/scheduler.block_skip_fraction, from the same per-block budget
    histogram the kernel's level-1 early exit realizes)."""
    from avida_tpu.ops import packed_chunk
    from avida_tpu.ops import scheduler as sched_ops
    from avida_tpu.ops.pallas_cycles import block_dims, kernel_shards
    from avida_tpu.ops.update import use_pallas_path

    pallas = bool(use_pallas_path(params))
    packed = bool(packed_chunk.active(params, st))
    block = block_dims(params, params.num_cells)[0] if pallas \
        else params.num_cells
    use_perm = params.lane_perm_k > 0 and not packed

    @jax.jit
    def tail_fn(st):
        from avida_tpu.ops.update import scheduler_probe
        _, granted, _ = scheduler_probe(params, st, seed=17)
        gp = granted[st.lane_perm] if use_perm else granted
        return (sched_ops.block_utilization(gp, block),
                sched_ops.block_skip_fraction(gp, block))

    util, skip = tail_fn(st)
    return {
        "device_count": jax.device_count(),
        "pallas_path": pallas,
        "packed_chunk": packed,
        "kernel_shards": kernel_shards(params) if pallas else 1,
        "lane_perm": params.lane_perm_k if use_perm else 0,
        "budget_tail_util": round(float(util), 4),
        "budget_tail_skip_pct": round(float(skip) * 100, 2),
    }


def main():
    from avida_tpu.ops.update import update_step

    # The bench is caching-immune by the round-9 harness rule: the
    # persistent AOT program cache (utils/compilecache.py, default-on
    # in production) is disabled for this process AND every child it
    # spawns, so no measurement is flattered by a prior run's store --
    # and no bench run mutates the user's ~/.cache.  The explicit cache
    # arms (BENCH_COMPILE, the dynamic+cache churn leg) re-enable it
    # against isolated roots; an operator override survives setdefault.
    os.environ.setdefault("TPU_COMPILE_CACHE", "0")

    # 320x320 = 102,400 organisms (BASELINE.json config: 100k target scale).
    # Smaller on CPU so the bench terminates quickly off-TPU.  BENCH_SIDE
    # overrides the side outright (perf_tool campaign's --side knob:
    # quick CPU artifacts for the regression-gate drills).
    on_tpu = jax.devices()[0].platform == "tpu"
    world = int(os.environ.get("BENCH_SIDE", "320" if on_tpu else "60"))
    warmup, timed = (1, 2) if on_tpu else (1, 3)

    # Every artifact is self-describing (README "Bench provenance"):
    # the toolchain/device/code-digest facts perf_tool diff refuses to
    # compare across, plus the knob environment that shaped this run.
    from avida_tpu.observability import profiler
    provenance = profiler.bench_provenance(time.time())

    if "--sweep" in sys.argv:
        # BASELINE.json config 2: population sweep 3.6k -> 100k organisms.
        # One JSON line per size (the driver's headline line is the plain
        # `python bench.py` run).
        for w in ([60, 100, 180, 320] if on_tpu else [20, 40, 60]):
            ips, _, _ = measure(w, warmup, timed)
            print(json.dumps({
                "metric": "org_instructions_per_sec",
                "organisms": w * w,
                "value": round(ips, 1),
                "unit": "inst/s",
                "vs_baseline": round(ips / BASELINE_INST_PER_SEC, 4),
                "provenance": provenance,
            }))
        return

    # BENCH_SHARDED=1: the same protocol with the population sharded over
    # every visible device (shard_map'd kernel path) -- the sharded perf
    # trajectory, tracked alongside the single-chip headline.
    sharded = os.environ.get("BENCH_SHARDED", "0") == "1"

    # Multi-update scan inside measure(): the whole timed segment is
    # device-resident; host sync only at the end -- anything else measures
    # dispatch round-trips, not the engine.
    ips, params, st = measure(world, warmup, timed, sharded=sharded)
    line = {
        "metric": "org_instructions_per_sec",
        "value": round(ips, 1),
        "unit": "inst/s",
        "vs_baseline": round(ips / BASELINE_INST_PER_SEC, 4),
    }
    if sharded:
        line["sharded"] = True
    line.update(kernel_facts(params, st))
    if os.environ.get("BENCH_CKPT", "0") == "1":
        line.update(ckpt_audit_overhead(params, st))
    if os.environ.get("BENCH_TRACE", "0") == "1":
        line.update(trace_overhead_fields(world if on_tpu else 30,
                                          updates=64 if on_tpu else 16))
    if os.environ.get("BENCH_SUPERVISE", "0") == "1":
        line.update(supervisor_restart_fields())
    if os.environ.get("BENCH_SCRUB", "0") == "1":
        line.update(scrub_overhead_fields(world if on_tpu else 60,
                                          updates=64 if on_tpu else 32))
    if os.environ.get("BENCH_ANALYZE", "0") == "1":
        line.update(analytics_fields())
    if os.environ.get("BENCH_OBS", "0") == "1":
        line.update(obs_overhead_fields(world if on_tpu else 40,
                                        updates=64 if on_tpu else 32))
    if os.environ.get("BENCH_PROF", "0") == "1":
        line.update(prof_overhead_fields(world if on_tpu else 40,
                                         updates=64 if on_tpu else 32))
    if os.environ.get("BENCH_WORLDS", "0") not in ("", "0"):
        side = int(os.environ.get("BENCH_WORLDS_SIDE",
                                  "120" if on_tpu else "20"))
        line.update(multiworld_fields(int(os.environ["BENCH_WORLDS"]),
                                      side, timed=4 if on_tpu else 3))
    if os.environ.get("BENCH_PACKED_PHASES", "0") == "1":
        line.update(packed_phase_fields(world if on_tpu else 20))
    if os.environ.get("BENCH_COMPILE", "0") == "1":
        line.update(compile_cache_fields())
    if os.environ.get("BENCH_SERVE", "0") == "1":
        line.update(serve_churn_fields())
    if os.environ.get("BENCH_PHASES", "1") != "0":
        phases = phase_breakdown(world)
        line["phases"] = phases
        # per-phase attribution of the tentpole's target costs: the
        # pack/unpack round-trip and the birth flush of the PER-UPDATE
        # path (what packed residency amortizes away -- compare with the
        # phases["packed_chunk"] ms/update of the resident path)
        line["pack_ms"] = round(phases.get("pack", 0.0)
                                + phases.get("unpack", 0.0), 3)
        line["flush_ms"] = round(phases.get("birth_flush", 0.0), 3)
    line["provenance"] = provenance
    print(json.dumps(line))


def multiworld_fields(W, side, timed=3, chunk=25):
    """BENCH_WORLDS=W: fleet-scale batching throughput -- W worlds of
    side x side organisms advanced by ONE compiled multiworld_scan
    (parallel/multiworld.py) vs the SAME W worlds run as sequential
    solo scans (the process-per-tenant model's best case: zero launch
    or compile overhead, only the smaller per-program device work).
    Small worlds by default (BENCH_WORLDS_SIDE): that is the regime the
    fleet serves, where per-update dispatch dominates and batching
    pays most.  Emits:

      world_count               W
      sequential_inst_per_sec   aggregate org-inst/s of the W back-to-
                                back solo runs
      multiworld_inst_per_sec   aggregate org-inst/s of the batched run
      per_world_inst_per_sec    the batched run's per-world split
      batch_efficiency          batched / (W x solo) -- 1.0 = perfect
                                linear scaling
      multiworld_ms_per_update_world
                                observability/harness.measure_multiworld
                                (caching-immune: every rep advances the
                                evolved batched state)

    plus the world-axis occupancy breakdown (PR-11 satellite):

      per_world_trips           each world's own summed per-update trip
                                counts over the timed chunks
      batch_trip_efficiency     sum(per_world_trips) / (W x batch-max):
                                the STRUCTURAL ceiling -- what fraction
                                of the batch-uniform trip count is any
                                world's own work (the exporter gauge's
                                definition)
      multiworld_phases         fenced pre/cycles/post ms of one batched
                                update on the world-folded XLA path +
                                cycle_loop_share (harness.
                                measure_multiworld_phases)
      kernel_world_skip_pct     fraction of lockstep lane-cycles the
                                stacked kernel's per-block early exit
                                skips ACROSS the W tenants' stacked
                                lanes vs one global trip count
                                (scheduler.block_skip_fraction over the
                                world-stacked granted vector)

    Seeds differ per world (the batch serves distinct tenants); timing
    fences only at segment ends, identically for both protocols."""
    from avida_tpu.observability.harness import (measure_multiworld,
                                                 measure_multiworld_phases)
    from avida_tpu.ops import pallas_cycles
    from avida_tpu.ops import scheduler as sched_ops
    from avida_tpu.ops.update import scheduler_probe, update_scan
    from avida_tpu.parallel.multiworld import multiworld_scan

    u0 = 1 << 20
    seeds = [200 + 7 * k for k in range(W)]

    def fresh(seed):
        params, st, neighbors, _ = build(side, side, 256, seed=seed)
        return params, st, neighbors, jax.random.key(seed ^ 0xBEEF)

    # sequential baseline: W solo runs back to back, one warm chunk
    # each (the shared jit cache means only the first pays compile --
    # generous to the sequential side)
    seq_exec = 0
    seq_dt = 0.0
    for seed in seeds:
        params, st, neighbors, key = fresh(seed)
        st, _ = update_scan(params, st, chunk, key, neighbors,
                            jnp.int32(u0))
        jax.block_until_ready(st)
        outs = []
        t0 = time.perf_counter()
        for c in range(timed):
            st, (ex, *_rest) = update_scan(
                params, st, chunk, key, neighbors,
                jnp.int32(u0 + (c + 1) * chunk))
            outs.append(ex)
        jax.block_until_ready(st)
        seq_dt += time.perf_counter() - t0
        seq_exec += int(sum(np.asarray(x, np.int64).sum() for x in outs))
    seq_ips = seq_exec / seq_dt

    # batched: the same W worlds in one device program
    built = [fresh(seed) for seed in seeds]
    params, _, neighbors, _ = built[0]
    bstate = jax.tree.map(lambda *xs: jnp.stack(xs),
                          *[b[1] for b in built])
    bkeys = jnp.stack([b[3] for b in built])
    bstate, _ = multiworld_scan(params, bstate, chunk, bkeys, neighbors,
                                jnp.int32(u0))
    jax.block_until_ready(bstate)
    outs = []
    trip_rows = []
    t0 = time.perf_counter()
    for c in range(timed):
        bstate, (ex, *_rest) = multiworld_scan(
            params, bstate, chunk, bkeys, neighbors,
            jnp.int32(u0 + (c + 1) * chunk))
        outs.append(ex)
        trip_rows.append(_rest[-1])          # trips[W, chunk]
    jax.block_until_ready(bstate)
    bat_dt = time.perf_counter() - t0
    per_world = np.zeros(W, np.int64)
    for ex in outs:
        per_world += np.asarray(ex, np.int64).sum(axis=1)
    bat_ips = float(per_world.sum()) / bat_dt

    # world-axis occupancy: per-world trip totals vs the batch-max grid
    trips = np.concatenate([np.asarray(tr, np.int64) for tr in trip_rows],
                           axis=1)                       # [W, timed*chunk]
    per_world_trips = trips.sum(axis=1)
    leader_trips = trips.max(axis=0).sum()
    trip_eff = float(per_world_trips.sum()) / max(W * leader_trips, 1)

    # the stacked two-level-scheduler attribution: what the per-block
    # early exit skips across ALL W tenants' stacked lanes relative to
    # one global batch-max loop (the vmapped-engine cost model)
    B, n_pad = pallas_cycles.block_dims(params, params.num_cells)
    gs = []
    for i in range(W):
        st_i = jax.tree.map(lambda x, i=i: x[i], bstate)
        g = scheduler_probe(params, st_i)[1]
        gs.append(jnp.pad(g, (0, n_pad - g.shape[0])))
    g_stacked = jnp.concatenate(gs)
    world_skip = float(sched_ops.block_skip_fraction(g_stacked, B))

    from avida_tpu.ops.update import use_pallas_path
    mw_phases = None
    if not use_pallas_path(params):
        # the fenced pre/cycles/post stages mirror the world-FOLDED XLA
        # engine; on the kernel paths the cycle loop is a stacked launch
        # and the solo `phases` row already attributes it
        mw_phases = measure_multiworld_phases(
            params, [fresh(seed)[1] for seed in seeds], neighbors,
            [jax.random.key(s ^ 0xF00D) for s in seeds])

    mw_ms, _ = measure_multiworld(
        params, [fresh(seed)[1] for seed in seeds], neighbors,
        [jax.random.key(s ^ 0xBEEF) for s in seeds])
    out = {
        "world_count": W,
        "world_side": side,
        "sequential_inst_per_sec": round(seq_ips, 1),
        "multiworld_inst_per_sec": round(bat_ips, 1),
        "per_world_inst_per_sec": [round(float(x) / bat_dt, 1)
                                   for x in per_world],
        "batch_efficiency": round(bat_ips / (W * seq_ips), 4),
        "multiworld_ms_per_update_world": round(mw_ms, 3),
        "per_world_trips": [int(x) for x in per_world_trips],
        "batch_trip_efficiency": round(trip_eff, 4),
        "multiworld_phases": mw_phases,
        "kernel_world_skip_pct": round(world_skip * 100.0, 2),
    }
    if os.environ.get("BENCH_WORLDS_SERVE", "1") != "0":
        out.update(multiworld_serve_fields(W, side))
    return out


def multiworld_serve_fields(W, side, updates=40):
    """The fleet-scale half of BENCH_WORLDS: serve W tenants END TO END
    the two ways the fleet can -- W sequential solo CHILD PROCESSES
    (the process-per-job model: every tenant pays python + jax launch
    AND its own ~20-40s compile) versus ONE `--worlds` child batching
    all W (one launch, one compile, one device program).  This is the
    cost the orchestrator's device-lane packing actually removes; the
    steady-state in-program split is the *_inst_per_sec fields above.

    Aggregate serve throughput = total organism-instructions executed /
    wall seconds, read from each run's final metrics.prom -- the
    batched and solo runs execute bit-identical trajectories, so the
    instruction totals agree by construction and the speedup is pure
    wall time."""
    import shutil
    import subprocess
    import tempfile

    from avida_tpu.observability.exporter import read_metrics

    seeds = [200 + 7 * k for k in range(W)]
    repo = os.path.dirname(os.path.abspath(__file__))
    base = ["-set", "WORLD_X", str(side), "-set", "WORLD_Y", str(side),
            "-set", "TPU_MAX_MEMORY", "256",
            "-set", "TPU_MAX_STEPS_PER_UPDATE",
            os.environ.get("BENCH_CAP", "0"),
            "-set", "TPU_METRICS", "1", "-u", str(updates)]
    env = dict(os.environ)
    env.pop("BENCH_WORLDS", None)
    # every solo child must pay its own full launch+compile (the whole
    # point of the serve comparison): the persistent AOT cache would
    # let child 2..W deserialize child 1's programs in milliseconds
    env["TPU_COMPILE_CACHE"] = "0"

    def child(argv):
        t0 = time.perf_counter()
        subprocess.run([sys.executable, "-m", "avida_tpu"] + argv,
                       cwd=repo, env=env, check=True,
                       stdout=subprocess.DEVNULL,
                       stderr=subprocess.DEVNULL)
        return time.perf_counter() - t0

    td = tempfile.mkdtemp(prefix="bench-mw-serve-")
    try:
        seq_sec = 0.0
        seq_insts = 0
        for s in seeds:
            d = os.path.join(td, f"solo{s}")
            seq_sec += child(base + ["-s", str(s), "-d", d])
            seq_insts += int(read_metrics(
                os.path.join(d, "metrics.prom"))["avida_insts_total"])
        droot = os.path.join(td, "batch")
        mw_sec = child(base + ["--worlds",
                               ",".join(str(s) for s in seeds),
                               "-d", droot])
        mw_insts = int(read_metrics(
            os.path.join(droot, "metrics.prom"))["avida_insts_total"])
    finally:
        shutil.rmtree(td, ignore_errors=True)
    return {
        "serve_updates": updates,
        "sequential_serve_sec": round(seq_sec, 2),
        "multiworld_serve_sec": round(mw_sec, 2),
        "sequential_serve_inst_per_sec": round(seq_insts / seq_sec, 1),
        "multiworld_serve_inst_per_sec": round(mw_insts / mw_sec, 1),
        "serve_speedup_x": round((mw_insts / mw_sec)
                                 / max(seq_insts / seq_sec, 1e-9), 2),
    }


def compile_cache_fields():
    """BENCH_COMPILE=1: the persistent AOT program cache
    (utils/compilecache.py) measured per program, caching-immune via
    FRESH subprocess children (scripts/compile_bench_child.py; the
    round-9 harness rule -- process death is the only reliable jit-cache
    flush).  For each engine scan program -- solo update_scan and the
    W-world multiworld_scan -- a COLD child against an empty store
    measures the fresh trace+compile (trace_ms) and the serialize+store
    cost, then a WARM child against the now-populated store measures
    the deserialize path (cache_load_ms, cache_hit).  speedup_x =
    trace_ms / warm construct wall: the committed acceptance number
    (>= 10x on this host)."""
    import shutil
    import subprocess
    import tempfile

    repo = os.path.dirname(os.path.abspath(__file__))
    child = os.path.join(repo, "scripts", "compile_bench_child.py")
    side = os.environ.get("BENCH_COMPILE_SIDE", "8")
    mem = os.environ.get("BENCH_COMPILE_MEM", "256")
    chunk = os.environ.get("BENCH_COMPILE_CHUNK", "8")
    worlds = os.environ.get("BENCH_COMPILE_WORLDS", "8")
    reps = int(os.environ.get("BENCH_COMPILE_REPS", "3"))
    out = {}
    speedups = []
    for tag in ("update_scan", "multiworld_scan"):
        td = tempfile.mkdtemp(prefix=f"bench-cc-{tag}-")
        rows = {}
        try:
            # one cold child (a full compile is too expensive to
            # repeat), then `reps` warm children taking the MIN -- the
            # deserialize path is seconds-scale on a 1-core host where
            # scheduler noise only ever ADDS time, so the min is the
            # honest construction cost (disclosed via warm_reps)
            def run_child():
                env = dict(os.environ)
                env.pop("BENCH_COMPILE", None)
                env.pop("JAX_COMPILATION_CACHE_DIR", None)  # PR-6 landmine
                env["TPU_COMPILE_CACHE"] = "1"
                env["TPU_COMPILE_CACHE_DIR"] = td
                proc = subprocess.run(
                    [sys.executable, child, "--tag", tag, "--side", side,
                     "--mem", mem, "--chunk", chunk, "--worlds", worlds],
                    env=env, capture_output=True, text=True, timeout=1800)
                if proc.returncode != 0:
                    raise RuntimeError(proc.stderr[-500:])
                return json.loads(proc.stdout.strip().splitlines()[-1])

            try:
                rows["cold"] = run_child()
                warms = [run_child() for _ in range(max(reps, 1))]
                rows["warm"] = min(warms,
                                   key=lambda r: r["construct_ms"])
            except RuntimeError as e:
                out[f"compile_cache_{tag}"] = {"error": str(e)}
                continue
        finally:
            shutil.rmtree(td, ignore_errors=True)
        if rows["cold"]["cache_hit"] or not rows["warm"]["cache_hit"]:
            # the cold child's store silently failed (journaled
            # store_failed: unserializable executable / full disk) or
            # the store was pre-populated: record it per-tag instead of
            # killing every other BENCH_* measurement in this run
            out[f"compile_cache_{tag}"] = {
                "error": "cold/warm hit pattern wrong "
                         f"(cold hit={rows['cold']['cache_hit']}, "
                         f"warm hit={rows['warm']['cache_hit']}) -- "
                         "store likely failed; see the cold child's "
                         "journal", **{f"cold_{k}": v for k, v
                                       in rows["cold"].items()}}
            continue
        speedup = rows["cold"]["compile_ms"] / max(
            rows["warm"]["construct_ms"], 1e-9)
        speedups.append(speedup)
        out[f"compile_cache_{tag}"] = {
            "chunk": rows["cold"]["chunk"],
            "worlds": rows["cold"]["worlds"],
            "trace_ms": rows["cold"]["compile_ms"],
            "store_ms": rows["cold"]["store_ms"],
            "cache_load_ms": rows["warm"]["load_ms"],
            "warm_construct_ms": rows["warm"]["construct_ms"],
            "warm_reps": max(reps, 1),
            "cache_hit": rows["warm"]["cache_hit"],
            "payload_bytes": rows["cold"]["payload_bytes"],
            "speedup_x": round(speedup, 1),
        }
    if speedups:
        out["compile_cache_speedup_min_x"] = round(min(speedups), 1)
    return out


def serve_churn_fields(trace_path=None):
    """BENCH_SERVE=1: the streaming serve layer under CHURN -- replay
    the committed churn trace (CHURN_r10.trace, utils/churntrace.py
    grammar; BENCH_SERVE_TRACE overrides) through a REAL fleet
    orchestrator three ways:

      ppj       process-per-job (no batching): every tenant pays its
                own python + jax launch and its own compile
      static    PR-10 static coalescing (--batch, dynamic off): queued
                static-equal specs coalesce into --worlds children at
                admission time; late arrivals that miss the coalesce
                window spawn their own children
      dynamic   the serve layer (--batch + --dynamic): arrivals route
                into ONE warm ghost-padded --serve-worlds child; late
                arrivals are compile-cache hits promoted at checkpoint
                boundaries

    Per mode: wall seconds from first submission until every tenant is
    terminal, aggregate org-inst/s (sum of the tenants' final
    metrics.prom instruction counters / wall -- trajectories are
    bit-identical across modes, so the aggregate is pure wall time),
    p50/p95 queue wait (submission -> journal admit record), and for
    the dynamic mode the compile-cache hit rate from fleet.prom.  The
    orchestrator runs in-process on a background thread (host-only
    logic); every child is a real subprocess."""
    import shutil
    import statistics
    import tempfile
    import threading

    from avida_tpu.observability.exporter import read_metrics
    from avida_tpu.observability.runlog import read_records
    from avida_tpu.service.fleet import FleetConfig, FleetOrchestrator
    from avida_tpu.utils import churntrace

    repo = os.path.dirname(os.path.abspath(__file__))
    trace_path = trace_path or os.environ.get(
        "BENCH_SERVE_TRACE", os.path.join(repo, "CHURN_r10.trace"))
    events = churntrace.parse_trace(trace_path)
    tenants = sorted({e.job for e in events if e.kind == "submit"})
    terminal = ("done", "failed", "cancelled", "quarantined")
    mut = ["0.0075", "0.0085", "0.0095", "0.0065"]  # class=K variants

    def argv_for(ev):
        args = ["-u", ev.args["u"],
                "-set", "WORLD_X", "8", "-set", "WORLD_Y", "8",
                "-set", "TPU_MAX_MEMORY", "256",
                "-set", "AVE_TIME_SLICE", "100",
                "-set", "TPU_MAX_STEPS_PER_UPDATE",
                os.environ.get("BENCH_CAP", "0"),
                "-set", "TPU_CKPT_EVERY", "8",
                "-set", "TPU_CKPT_AUDIT", "0",
                "-set", "TPU_SERVE_POLL_SEC", "0.3",
                "-set", "TPU_METRICS", "1"]
        k = int(ev.args.get("class", 0))
        if k:
            args += ["-set", "COPY_MUT_PROB", mut[k % len(mut)]]
        return args + ["-s", ev.args["seed"]]

    def leg(mode, deadline_sec=1200.0, cache_env=None):
        from avida_tpu.service.fleet import (JOURNAL_FILE,
                                             journal_states)
        td = tempfile.mkdtemp(prefix=f"bench-serve-{mode}-")
        spool = os.path.join(td, "spool")
        env = dict(os.environ)
        env.pop("BENCH_SERVE", None)
        env.pop("JAX_COMPILATION_CACHE_DIR", None)   # PR-6 landmine
        # the three baseline arms stay caching-immune (and comparable
        # with BENCH_r10): the persistent AOT cache is OFF unless this
        # leg is the dynamic+cache arm, which points at a store that
        # SURVIVES across legs -- that persistence is the feature
        env["TPU_COMPILE_CACHE"] = "0"
        env.update(cache_env or {})
        cfg = FleetConfig(max_jobs=2, poll_sec=0.3, serve=True,
                          dynamic=(mode == "dynamic"),
                          serve_min_width=8)
        fleet = FleetOrchestrator(spool, cfg=cfg, env=env)
        th = threading.Thread(target=fleet.run, daemon=True)
        t0 = time.perf_counter()
        th.start()
        submits = churntrace.replay(
            spool, events, argv_for, batch=(mode != "ppj"),
            clock=time.time, sleep=time.sleep)
        deadline = time.time() + deadline_sec
        while time.time() < deadline:
            st, _, _ = journal_states(os.path.join(spool,
                                                   JOURNAL_FILE))
            if all(st.get(t) in terminal for t in tenants):
                break
            time.sleep(1.0)
        wall = time.perf_counter() - t0
        st, _, _ = journal_states(os.path.join(spool, JOURNAL_FILE))
        fleet.request_stop()
        th.join(120)
        insts = 0
        for t in tenants:
            mp = os.path.join(spool, t, "data", "metrics.prom")
            try:
                insts += int(read_metrics(mp).get(
                    "avida_insts_total", 0))
            except OSError:
                pass
        waits = []
        admits = {}
        for rec in read_records(os.path.join(spool, JOURNAL_FILE)):
            if rec.get("record") == "fleet" \
                    and rec.get("event") == "admit" \
                    and rec.get("job") in submits \
                    and rec["job"] not in admits:
                admits[rec["job"]] = rec["time"]
        for t, ts in submits.items():
            if t in admits:
                waits.append(max(admits[t] - ts, 0.0))
        out = {
            "wall_sec": round(wall, 1),
            "insts": insts,
            "agg_inst_per_sec": round(insts / wall, 1),
            "completed": sum(1 for t in tenants
                             if st.get(t) == "done"),
            "cancelled": sum(1 for t in tenants
                             if st.get(t) == "cancelled"),
            "queue_wait_p50_s": round(statistics.median(waits), 2)
            if waits else None,
            "queue_wait_p95_s": round(
                sorted(waits)[max(int(len(waits) * 0.95) - 1, 0)], 2)
            if waits else None,
        }
        if mode == "dynamic":
            try:
                m = read_metrics(os.path.join(spool, "fleet.prom"))
                hits = m.get("avida_fleet_serve_cache_hits_total", 0)
                miss = m.get("avida_fleet_serve_cache_misses_total", 0)
                out["cache_hit_rate"] = round(
                    hits / max(hits + miss, 1), 3)
                out["cache_hits"] = int(hits)
                out["cache_misses"] = int(miss)
            except OSError:
                pass
            for n in sorted(os.listdir(spool)):
                sj = os.path.join(spool, n, "data", "serve.json")
                if os.path.exists(sj):
                    try:
                        with open(sj) as f:
                            doc = json.load(f)
                        out["serve_compiles"] = doc.get("compiles")
                        out["serve_cache_loads"] = doc.get("cache_loads")
                    except (OSError, ValueError):
                        pass
                    break
        shutil.rmtree(td, ignore_errors=True)
        return out

    legs = {m: leg(m) for m in ("ppj", "static", "dynamic")}
    # the fourth arm (round 11): dynamic serving with the persistent AOT
    # executable store (utils/compilecache.py).  The FIRST replay against
    # an empty store is the producer pass (children compile AND
    # serialize; its wall is reported honestly as the prewarm cost); the
    # SECOND replay against the now-populated store is steady-state
    # serving -- what production traffic sees once executables persist
    # across orchestrator restarts: a cold-spawned class child
    # deserializes its programs in milliseconds, so no arrival ever
    # lands inside a compile window.
    ccdir = tempfile.mkdtemp(prefix="bench-serve-cc-")
    cache_env = {"TPU_COMPILE_CACHE": "1", "TPU_COMPILE_CACHE_DIR": ccdir}
    prewarm = leg("dynamic", cache_env=cache_env)
    legs["dynamic+cache"] = leg("dynamic", cache_env=cache_env)
    shutil.rmtree(ccdir, ignore_errors=True)
    dyn, ppj = legs["dynamic"], legs["ppj"]
    dyc = legs["dynamic+cache"]
    return {
        "serve_churn_trace": os.path.basename(trace_path),
        "serve_churn_tenants": len(tenants),
        "serve_churn": legs,
        "serve_churn_cache_prewarm": prewarm,
        "serve_churn_speedup_dynamic_vs_ppj": round(
            dyn["agg_inst_per_sec"] / max(ppj["agg_inst_per_sec"],
                                          1e-9), 2),
        "serve_churn_speedup_dynamic_vs_static": round(
            dyn["agg_inst_per_sec"]
            / max(legs["static"]["agg_inst_per_sec"], 1e-9), 2),
        "serve_churn_speedup_cache_vs_ppj": round(
            dyc["agg_inst_per_sec"] / max(ppj["agg_inst_per_sec"],
                                          1e-9), 2),
        "serve_churn_speedup_cache_vs_static": round(
            dyc["agg_inst_per_sec"]
            / max(legs["static"]["agg_inst_per_sec"], 1e-9), 2),
        "serve_churn_cache_takes_raw_wall_from_static":
            dyc["wall_sec"] < legs["static"]["wall_sec"],
    }


def supervisor_restart_fields():
    """BENCH_SUPERVISE=1: the supervision tax on a restart -- wall time
    per death->classify->record->backoff->relaunch cycle
    (service/supervisor.py), measured with a stub child that exits
    immediately so no jax boot or compile time pollutes the number.
    This is the floor a restarted tenant pays ON TOP of its own resume
    cost; the fleet scheduler budgets against it."""
    import subprocess
    import tempfile

    from avida_tpu.service.supervisor import Supervisor, SupervisorConfig

    def stub_spawn(argv, env, logf):
        return subprocess.Popen(
            [sys.executable, "-c", "raise SystemExit(1)"],
            env=env, stdout=logf, stderr=logf)

    cycles = 6
    with tempfile.TemporaryDirectory() as td:
        data = os.path.join(td, "data")
        ck = os.path.join(td, "ck")
        os.makedirs(ck)
        cfg = SupervisorConfig(watchdog_sec=60, poll_sec=0.005,
                               grace_sec=60, max_retries=cycles,
                               backoff_base=1e-4, backoff_cap=2e-4,
                               healthy_sec=1e9)
        sup = Supervisor(["-d", data, "-set", "TPU_CKPT_DIR", ck],
                         cfg=cfg, spawn=stub_spawn)
        t0 = time.perf_counter()
        rc = sup.run()
        dt = time.perf_counter() - t0
        assert rc == 1 and sup.boots == cycles + 1
    return {"supervisor_restart_ms": round(dt / sup.boots * 1e3, 2)}


def analytics_fields():
    """BENCH_ANALYZE=1: the run-analytics tax in the perf trajectory --
    census_ms (cold batched phenotype census over a synthetic genotype
    table; live incremental refreshes only pay this for NEW genotypes)
    and knockout_ms (one full per-site knockout sweep of the stock
    ancestor), both through observability/harness.measure_analytics.
    Measured after -- and without perturbing -- the headline numbers;
    the analytics pipeline runs in separate jits, so nothing here
    touches the update program."""
    from avida_tpu.observability.harness import measure_analytics
    return measure_analytics()


def ckpt_audit_overhead(params, st):
    """BENCH_CKPT=1: wall cost of the robustness hooks on the final bench
    state -- one native checkpoint generation write (ckpt_save_ms: host
    gather + CRC + fsync'd atomic publish, utils/checkpoint.py) and one
    full invariant audit (audit_ms: utils/audit.py, compiled cost after a
    warmup pass).  Rides the headline JSON line so checkpoint overhead
    shows up in the perf trajectory without perturbing the headline
    numbers (measured after them)."""
    import shutil
    import tempfile

    from avida_tpu.core.state import state_field_names
    from avida_tpu.utils import checkpoint as ckpt_mod
    from avida_tpu.utils.audit import audit_state

    jax.block_until_ready(audit_state(params, st))        # compile warmup
    t0 = time.perf_counter()
    jax.block_until_ready(audit_state(params, st))
    audit_ms = (time.perf_counter() - t0) * 1e3

    # None-valued fields (the flight-recorder ring with TPU_TRACE off)
    # have no on-disk representation (utils/checkpoint.save_checkpoint)
    arrays = {f"state.{name}": np.asarray(getattr(st, name))
              for name in state_field_names()
              if getattr(st, name) is not None}
    tmp = tempfile.mkdtemp(prefix="bench-ckpt-")
    try:
        t0 = time.perf_counter()
        ckpt_mod.write_generation(tmp, 0, arrays,
                                  host={"bench": True}, keep=1)
        ckpt_ms = (time.perf_counter() - t0) * 1e3
    finally:
        shutil.rmtree(tmp, ignore_errors=True)
    return {"ckpt_save_ms": round(ckpt_ms, 2),
            "audit_ms": round(audit_ms, 2)}


def obs_overhead_fields(world, updates=32, seed=100):
    """BENCH_OBS=1: the telemetry history + alert plane's tax in the
    perf trajectory (README "Telemetry history & alerts").  Two costs
    ride each heartbeat: the run process appends one sample row to the
    metrics.hist.jsonl ring (observability/history.py), and the
    supervising process reads the ring tail and evaluates the default
    rule set (observability/alerts.py).  Both are attributed DIRECTLY
    -- fenced single-operation milliseconds against the plain
    per-chunk wall -- because end-to-end wall deltas on a 1-core host
    are ~30% noise, an order of magnitude above this signal (the
    round-13 bench lesson); the wall delta is still reported for
    honesty.  Caching-immune: every append is fresh file I/O on a
    growing ring seeded from the run's own exposition text, and every
    evaluation re-reads the ring tail from disk exactly like the
    supervisor's poll loop.  Emits:

      obs_hist_append_ms      one sample append (parse exposition +
                              rotation-checked jsonl write, no fsync),
                              mean over 256 appends incl. rotations
      obs_alert_eval_ms       one supervisor-style evaluation: ring
                              tail read from disk + all default rules
                              over a populated ring
      obs_chunk_ms            plain per-chunk wall at this chunk size
                              (min over reps)
      obs_overhead_pct        (append + eval) / chunk_ms -- the
                              <2%-of-chunk-wall acceptance gauge,
                              conservatively charging BOTH processes'
                              costs to every heartbeat (alert eval
                              actually runs at TPU_ALERT_EVAL_SEC
                              cadence, not per boundary)
      obs_hist_wall_delta_pct end-to-end wall delta of history-on vs
                              off (min-of-reps; noise-bound, see
                              above)

    Measured after -- and without perturbing -- the headline numbers."""
    import shutil
    import tempfile

    from avida_tpu.observability import alerts, history
    from avida_tpu.observability.exporter import render_metrics
    from avida_tpu.world import World

    chunk = 8

    def run_one(extra, keep=False):
        ov = [("WORLD_X", world), ("WORLD_Y", world),
              ("RANDOM_SEED", seed), ("TPU_SYSTEMATICS", 0),
              ("TPU_MAX_STRETCH", chunk), ("TPU_METRICS", 1)] + extra
        w = World(overrides=ov,
                  data_dir=tempfile.mkdtemp(prefix="bench-obs-"))
        try:
            t0 = time.perf_counter()
            w.run(max_updates=updates)
            wall = time.perf_counter() - t0
        finally:
            if not keep:
                shutil.rmtree(w.data_dir, ignore_errors=True)
        return wall, w

    configs = ([("TPU_METRICS_HIST", 0)], [("TPU_METRICS_HIST", 1)])
    for extra in configs:
        run_one(extra)                               # compile warmup
    reps = int(os.environ.get("BENCH_OBS_REPS", "2"))
    walls = []
    w_on = None
    for extra in configs:
        best = float("inf")
        for _ in range(reps):
            wall, w = run_one(extra, keep=(extra[0][1] == 1))
            best = min(best, wall)
            if extra[0][1] == 1:
                if w_on is not None:
                    shutil.rmtree(w_on.data_dir, ignore_errors=True)
                w_on = w
        walls.append(best)
    plain, hist_on = walls

    # the append cost, on this run's REAL exposition text (every
    # family the heartbeat renders), against a live growing ring
    text = render_metrics(w_on)
    ring_dir = tempfile.mkdtemp(prefix="bench-obs-ring-")
    ring = os.path.join(ring_dir, "metrics.hist.jsonl")
    n_append = 256
    try:
        t0 = time.perf_counter()
        for _ in range(n_append):
            history.append_sample(ring, history.parse_exposition(text))
        append_ms = (time.perf_counter() - t0) / n_append * 1e3

        # the supervisor-side evaluation cost: tail read + all default
        # rules over a ring shaped like a long run's (samples spanning
        # well past every rule window)
        shutil.rmtree(ring_dir, ignore_errors=True)
        os.makedirs(ring_dir)
        now = time.time()
        vals = history.parse_exposition(text)
        for i in range(120):
            history.append_sample(
                ring, dict(vals, avida_update=float(i * chunk)),
                now=now - 600 + i * 5)
        rules = alerts.load_rules()
        n_eval = 64
        t0 = time.perf_counter()
        for _ in range(n_eval):
            samples = history.read_samples(ring, tail_bytes=256 << 10)
            alerts.evaluate(rules, samples, now)
        eval_ms = (time.perf_counter() - t0) / n_eval * 1e3
    finally:
        shutil.rmtree(ring_dir, ignore_errors=True)
        shutil.rmtree(w_on.data_dir, ignore_errors=True)

    chunks = max(updates // chunk, 1)
    chunk_ms = plain / chunks * 1e3
    return {
        "obs_hist_append_ms": round(append_ms, 4),
        "obs_alert_eval_ms": round(eval_ms, 4),
        "obs_chunk_ms": round(chunk_ms, 2),
        "obs_overhead_pct": round((append_ms + eval_ms)
                                  / chunk_ms * 100, 3),
        "obs_hist_wall_delta_pct": round((hist_on - plain)
                                         / plain * 100, 2),
    }


def prof_overhead_fields(world, updates=32, seed=100):
    """BENCH_PROF=1: the performance attribution plane's own tax
    (README "Performance attribution").  The SAME world runs end-to-end
    plain and with TPU_PROFILE=1 (probe on the first chunk only:
    TPU_PROFILE_EVERY=0 isolates the RECURRING per-chunk hook from the
    amortized probe).  Like BENCH_OBS, the acceptance gauge is
    attributed DIRECTLY -- fenced single-operation costs against the
    plain per-chunk wall -- because end-to-end wall deltas on a 1-core
    host carry ~30% noise; the wall delta is still reported for
    honesty.  Emits:

      prof_hook_ms            one probe-boundary bookkeeping pass:
                              state_footprint on the evolved state
                              (two scalar readbacks) + one perf.jsonl
                              append, mean over 32/256 reps --
                              conservatively charged to EVERY chunk
                              (it actually runs at TPU_PROFILE_EVERY
                              cadence; non-probe chunks pay only two
                              perf_counter calls)
      prof_probe_ms           one fenced staged phase probe on a COPY
                              of the evolved state (the off-trajectory
                              attribution pass, amortized over
                              TPU_PROFILE_EVERY chunks)
      prof_chunk_ms           plain per-chunk wall (min over reps)
      prof_overhead_pct       prof_hook_ms / prof_chunk_ms -- the
                              <2%-of-chunk-wall acceptance gauge
      prof_wall_delta_pct     end-to-end wall delta of profile-on vs
                              off (min-of-reps; noise-bound, see
                              above)

    Measured after -- and without perturbing -- the headline numbers."""
    import shutil
    import tempfile

    from avida_tpu.observability import profiler
    from avida_tpu.world import World

    chunk = 8

    def run_one(extra, keep=False):
        ov = [("WORLD_X", world), ("WORLD_Y", world),
              ("RANDOM_SEED", seed), ("TPU_SYSTEMATICS", 0),
              ("TPU_MAX_STRETCH", chunk), ("TPU_METRICS", 1)] + extra
        w = World(overrides=ov,
                  data_dir=tempfile.mkdtemp(prefix="bench-prof-"))
        try:
            t0 = time.perf_counter()
            w.run(max_updates=updates)
            wall = time.perf_counter() - t0
        finally:
            if not keep:
                shutil.rmtree(w.data_dir, ignore_errors=True)
        return wall, w

    configs = ([], [("TPU_PROFILE", 1), ("TPU_PROFILE_EVERY", 0)])
    for extra in configs:
        run_one(extra)                               # compile warmup
    reps = int(os.environ.get("BENCH_PROF_REPS", "2"))
    walls = []
    w_on = None
    for extra in configs:
        best = float("inf")
        for _ in range(reps):
            wall, w = run_one(extra, keep=bool(extra))
            best = min(best, wall)
            if extra:
                if w_on is not None:
                    shutil.rmtree(w_on.data_dir, ignore_errors=True)
                w_on = w
        walls.append(best)
    plain, prof_on = walls

    try:
        # the recurring bookkeeping, on the REAL evolved state: the
        # footprint walk (padded nbytes + two scalar readbacks) and one
        # rotation-checked perf.jsonl append
        n_fp = 32
        t0 = time.perf_counter()
        for _ in range(n_fp):
            fp = profiler.state_footprint(w_on.state)
        fp_ms = (time.perf_counter() - t0) / n_fp * 1e3
        rec = {"record": "perf", "time": 0.0, "kind": "bench",
               "update": updates, "chunk_updates": chunk,
               "final": False, "chunks": updates // chunk,
               "chunk_wall_ms": 0.0, "chunk_fenced_ms": 0.0,
               "phases": {}, "state_bytes": fp["total_bytes"],
               "state_live_bytes": fp["live_bytes"],
               "alive_frac": fp["alive_frac"],
               "genome_len_frac": fp["genome_len_frac"],
               "leaves": {n: lf["bytes"]
                          for n, lf in fp["leaves"].items()},
               "programs": 0}
        n_rec = 256
        t0 = time.perf_counter()
        for _ in range(n_rec):
            profiler.append_perf_record(w_on.data_dir, rec)
        rec_ms = (time.perf_counter() - t0) / n_rec * 1e3
        hook_ms = fp_ms + rec_ms

        # the fenced probe itself (staged phases on a state COPY) --
        # warm from the profiled run; amortized at TPU_PROFILE_EVERY
        w_on.profiler._probe_solo(w_on)              # staged warmup
        t0 = time.perf_counter()
        w_on.profiler._probe_solo(w_on)
        probe_ms = (time.perf_counter() - t0) * 1e3
    finally:
        shutil.rmtree(w_on.data_dir, ignore_errors=True)

    chunks = max(updates // chunk, 1)
    chunk_ms = plain / chunks * 1e3
    return {
        "prof_hook_ms": round(hook_ms, 4),
        "prof_probe_ms": round(probe_ms, 2),
        "prof_chunk_ms": round(chunk_ms, 2),
        "prof_overhead_pct": round(hook_ms / chunk_ms * 100, 3),
        "prof_wall_delta_pct": round((prof_on - plain)
                                     / plain * 100, 2),
    }


def scrub_overhead_fields(world, updates=32, seed=100):
    """BENCH_SCRUB=1: the integrity plane's tax in the perf trajectory
    (README "Integrity plane").  The SAME world config is run
    end-to-end through World.run three ways -- plain, with per-chunk
    state digests (TPU_STATE_DIGEST=1), and with full lockstep
    scrubbing (TPU_SCRUB_EVERY=1: every chunk shadow-re-executed and
    digest-compared) -- each timed after a warm run of the identical
    config, so compile time stays out of the comparison
    (caching-immune: every timed pass evolves its own fresh world
    through the same updates).  Emits:

      digest_ms               one fenced whole-state digest on the
                              evolved final state (compiled cost)
      chunk_ms                plain per-chunk wall at this chunk size
                              (min over reps: single-core host noise
                              runs to ~30% on whole-run walls, so the
                              per-config minimum is the honest floor)
      digest_overhead_pct     digest_ms as a share of chunk_ms -- the
                              <5%-of-chunk-wall acceptance gauge,
                              attributed DIRECTLY (one fenced digest /
                              one chunk) rather than via end-to-end
                              wall deltas, which on this host are
                              noise-bound an order of magnitude above
                              the signal
      digest_wall_delta_pct   the end-to-end wall delta anyway
                              (digest-on run vs plain, min-of-reps) --
                              reported for honesty, read with the
                              noise caveat above
      scrub_overhead_pct      wall overhead of TPU_SCRUB_EVERY=1 vs
                              plain (~100% by construction -- every
                              chunk runs twice; the amortized cost at
                              cadence K is this / K)

    Measured after -- and without perturbing -- the headline numbers."""
    import shutil
    import tempfile

    from avida_tpu.ops.digest import state_digest
    from avida_tpu.world import World

    chunk = 8

    def run_one(extra):
        ov = [("WORLD_X", world), ("WORLD_Y", world),
              ("RANDOM_SEED", seed), ("TPU_SYSTEMATICS", 0),
              ("TPU_MAX_STRETCH", chunk)] + extra
        w = World(overrides=ov, data_dir=tempfile.mkdtemp(prefix="bench-scrub-"))
        try:
            t0 = time.perf_counter()
            w.run(max_updates=updates)
            wall = time.perf_counter() - t0
        finally:
            shutil.rmtree(w.data_dir, ignore_errors=True)
        return wall, w

    configs = ([], [("TPU_STATE_DIGEST", 1)],
               [("TPU_STATE_DIGEST", 1), ("TPU_SCRUB_EVERY", 1)])
    for extra in configs:
        run_one(extra)                               # compile warmup
    reps = int(os.environ.get("BENCH_SCRUB_REPS", "2"))
    walls = []
    wp = None
    for extra in configs:
        best = float("inf")
        for _ in range(reps):
            wall, w = run_one(extra)
            best = min(best, wall)
            if not extra:
                wp = w
        walls.append(best)
    plain, digest, scrub = walls

    jax.block_until_ready(state_digest(wp.state))    # compiled already
    t0 = time.perf_counter()
    jax.block_until_ready(state_digest(wp.state))
    digest_ms = (time.perf_counter() - t0) * 1e3

    chunks = max(updates // chunk, 1)
    chunk_ms = plain / chunks * 1e3
    return {
        "digest_ms": round(digest_ms, 3),
        "chunk_ms": round(chunk_ms, 2),
        "digest_overhead_pct": round(digest_ms / chunk_ms * 100, 3),
        "digest_wall_delta_pct": round((digest - plain) / plain * 100, 2),
        "scrub_overhead_pct": round((scrub - plain) / plain * 100, 2),
    }


def trace_overhead_fields(world, updates=64, seed=100):
    """BENCH_TRACE=1: the observability tax in the perf trajectory.  The
    SAME world is run end-to-end through World.run three ways -- plain,
    with the flight recorder (TPU_TRACE=1), and with full telemetry
    (TPU_TELEMETRY=1, which forces per-update phase fencing) -- each
    timed over `updates` updates after a short warm run so compile time
    stays out of the comparison.  Emits:

      trace_drain_ms          host cost of draining a FULL 4096-event
                              ring at one chunk boundary
                              (observability/harness.measure_trace_drain)
      trace_overhead_pct      wall overhead of TPU_TRACE=1 vs plain (the
                              in-update ring appends + boundary drains)
      telemetry_overhead_pct  wall overhead of TPU_TELEMETRY=1 vs plain
                              (staged phase fencing; the price of the
                              full per-update runlog)

    Measured after -- and without perturbing -- the headline numbers."""
    import shutil
    import tempfile

    from avida_tpu.observability.harness import measure_trace_drain
    from avida_tpu.world import World

    # warm segment == timed segment length: the chunked plain path
    # compiles one scanned program per power-of-two stretch bucket, and
    # the event cadence is periodic, so an equal-length warm run visits
    # the same buckets the timed segment will -- otherwise their compiles
    # land inside the plain timing and the overhead pcts go negative
    warm = updates

    def timed_run(extra):
        d = tempfile.mkdtemp(prefix="bench-trace-")
        try:
            w = World(overrides=[("WORLD_X", world), ("WORLD_Y", world),
                                 ("RANDOM_SEED", seed)] + extra,
                      data_dir=d)
            w.run(max_updates=warm)               # compile + ramp
            t0 = time.perf_counter()
            w.run(max_updates=warm + updates)
            return time.perf_counter() - t0
        finally:
            shutil.rmtree(d, ignore_errors=True)

    t_plain = timed_run([])
    t_trace = timed_run([("TPU_TRACE", 1)])
    t_tel = timed_run([("TPU_TELEMETRY", 1)])
    pct = lambda t: round((t - t_plain) / t_plain * 100, 2)  # noqa: E731
    return {"trace_drain_ms": round(measure_trace_drain(), 3),
            "trace_overhead_pct": pct(t_trace),
            "telemetry_overhead_pct": pct(t_tel)}


def packed_phase_fields(world, seed=100):
    """BENCH_PACKED_PHASES=1: direct attribution of the round-14
    tentpole -- the fused packed-resident scan and the 5-bit genome
    shadow.  Three variants of the SAME world at fixed N, each measured
    two ways (the round-13 lesson: headline claims come from fenced
    direct attribution, never from host-wall deltas):

      packed_ms_per_update_{fused,legacy,fused_bits5}
          end-to-end ms/update of a resident chunk (pack once + updates
          on the planes + unpack once) per engine variant.  `legacy` is
          TPU_PACKED_FUSED=0 (row-space phases but fresh canonical
          mirrors every update); the fused-vs-legacy delta is the cost
          the fused path removes from every in-scan update.
      packed_phases_{fused,legacy,fused_bits5}
          fenced per-phase ms (observability/harness.
          measure_packed_phases): boundary `pack`/`unpack` vs in-scan
          `scan.*` rows show WHERE that delta lives (legacy pays
          mirror refresh inside scan.flush; bits5 moves cost to the
          pack/unpack boundary).

    Residency (the second tentpole axis, pure shape math -- exact on
    any backend):

      packed_bytes / packed_bytes_bits5
          resident plane bytes at this N (profiler.
          packed_planes_footprint): total, per organism, and bytes
          saved by the 5-bit codec.
      orgs_per_gb / orgs_per_gb_bits5
          derived fit-at-fixed-HBM-budget: organisms per GB of
          resident planes under each codec.

    Max-resident probe (largest N that constructs AND runs a short
    resident chunk, doubling the world side from the bench side):

      max_resident_n / max_resident_n_bits5, with cap_hit=True when
      the ladder stopped at the BENCH_PACKED_MAX_N env cap rather
      than at an allocation failure -- on CPU hosts the cap, not HBM,
      is the binding limit, and the artifact says so honestly."""
    from avida_tpu.observability import profiler
    from avida_tpu.observability.harness import (measure_packed_chunk,
                                                 measure_packed_phases)
    from avida_tpu.ops import packed_chunk

    params, st, neighbors, key = build(world, world, 256, seed=seed)
    out = {"packed_n": int(params.num_cells)}
    if not packed_chunk.active(params, st) and params.use_pallas == 0:
        # Off-TPU the auto route skips the kernel entirely; this arm
        # exists to measure the packed engine, so force interpret mode
        # (the test idiom) and say so in the artifact -- interpret-leg
        # numbers gate RELATIVE regressions only, never the headline.
        params = params.replace(use_pallas=1)
        out["packed_forced_interpret"] = True
    if not packed_chunk.active(params, st):
        return {"packed_phases_skipped":
                packed_chunk.ineligible_reason(params) or "inactive"}
    variants = (("fused", params),
                ("legacy", params.replace(packed_fused=0)),
                ("fused_bits5", params.replace(packed_bits=1)))
    on_tpu = jax.devices()[0].platform == "tpu"
    for name, p in variants:
        # update_scan donates its input state: each measurement gets
        # its own copy so the variants stay independent
        ms, _ = measure_packed_chunk(p, jax.tree.map(jnp.copy, st),
                                     neighbors, jax.random.key(seed + 1),
                                     updates=8 if on_tpu else 4,
                                     reps=3 if on_tpu else 2)
        if ms is not None:
            out["packed_ms_per_update_%s" % name] = round(ms, 3)
        ph = measure_packed_phases(p, jax.tree.map(jnp.copy, st),
                                   neighbors, jax.random.key(seed + 2),
                                   reps=2)
        if ph:
            out["packed_phases_%s" % name] = {
                k: round(v, 3) for k, v in ph.items()}

    for bits, tag in ((0, ""), (1, "_bits5")):
        fp = profiler.packed_planes_footprint(
            params.replace(packed_bits=bits), int(params.num_cells))
        out["packed_bytes" + tag] = {
            "total": fp["total_bytes"],
            "per_org": round(fp["bytes_per_org"], 2),
            "saved_vs_unpacked": fp["saved_bytes"],
        }
        out["orgs_per_gb" + tag] = int((1 << 30) // fp["bytes_per_org"])

    for bits, tag in ((0, ""), (1, "_bits5")):
        n, cap_hit = _packed_max_resident(world, bits, seed)
        out["max_resident_n" + tag] = n
        if cap_hit:
            out["max_resident_cap_hit" + tag] = True
    return out


def _packed_max_resident(world, bits, seed, probe_updates=4):
    """Doubling-side ladder: largest N whose resident planes construct
    and survive a short packed scan.  Stops at allocation failure or at
    the BENCH_PACKED_MAX_N cap (default modest on CPU hosts, where RAM
    -- not HBM -- would otherwise absorb the ladder)."""
    from avida_tpu.ops import packed_chunk
    on_tpu = jax.devices()[0].platform == "tpu"
    cap = int(os.environ.get("BENCH_PACKED_MAX_N",
                             str(1 << 22) if on_tpu else "2048"))
    best, cap_hit, side = 0, False, world
    while True:
        if side * side > cap:
            cap_hit = True
            break
        try:
            params, st, neighbors, key = build(side, side, 256, seed=seed)
            if not packed_chunk.active(params, st) \
                    and params.use_pallas == 0:
                params = params.replace(use_pallas=1)
            params = params.replace(packed_bits=bits)

            @jax.jit
            def run(st, key):
                pc = packed_chunk.pack_chunk(params, st)

                def pbody(carry, i):
                    pc, key = carry
                    key, k = jax.random.split(key)
                    pc, ex = packed_chunk.update_step_packed(
                        params, pc, k, neighbors, 1 + i)
                    return (pc, key), ex
                (pc, _), _ = jax.lax.scan(pbody, (pc, key),
                                          jnp.arange(probe_updates))
                return packed_chunk.unpack_chunk(params, pc)

            jax.block_until_ready(run(st, key))
            best = side * side
        except Exception:
            break
        side *= 2
    return best, cap_hit


def phase_breakdown(world, reps=2, seed=100):
    """Per-phase ms/update via the staged harness (runs after -- and does
    not perturb -- the headline measurement).  Fenced phases serialize
    work the fused scan overlaps, so these attribute the update's time;
    they do not sum to the headline's per-update cost.

    When the packed-resident chunk qualifies, a `packed_chunk` row is
    appended: end-to-end ms/update of the resident-plane scan
    (observability/harness.measure_packed_chunk) -- the direct
    comparator for pack + kernel + unpack + birth of the staged
    per-update rows."""
    from avida_tpu.observability.harness import (measure_packed_chunk,
                                                 profile_phases)
    params, st, neighbors, key = build(world, world, 256, seed=seed)
    phases, st, _ = profile_phases(params, st, neighbors, key,
                                   reps=reps, warmup=1)
    out = {name: round(ms, 3) for name, ms in phases.items()}
    pcms, _ = measure_packed_chunk(params, st, neighbors,
                                   jax.random.key(seed + 1))
    if pcms is not None:
        out["packed_chunk"] = round(pcms, 3)
    return out


if __name__ == "__main__":
    main()
