// apto-shim (see platform.h header note) -- umbrella header
#ifndef AptoCore_h
#define AptoCore_h

#include "platform.h"
#include "core/Definitions.h"
#include "core/Algorithms.h"
#include "core/Array.h"
#include "core/FileSystem.h"
#include "core/Functor.h"
#include "core/List.h"
#include "core/Map.h"
#include "core/Mutex.h"
#include "core/Pair.h"
#include "core/Set.h"
#include "core/SmartPtr.h"
#include "core/String.h"
#include "core/StringBuffer.h"
#include "core/StringUtils.h"
#include "core/Thread.h"
#include "core/TypeList.h"
#include "scheduler.h"

namespace Apto {

// 2-D coordinate (apto/core/Coord.h upstream)
template <class T>
class Coord
{
public:
  T x;
  T y;
  Coord() : x(0), y(0) {}
  Coord(T in_x, T in_y) : x(in_x), y(in_y) {}
  bool operator==(const Coord& rhs) const { return x == rhs.x && y == rhs.y; }
  bool operator!=(const Coord& rhs) const { return !(*this == rhs); }
  Coord operator+(const Coord& rhs) const { return Coord(x + rhs.x, y + rhs.y); }
  Coord operator-(const Coord& rhs) const { return Coord(x - rhs.x, y - rhs.y); }
  Coord operator*(T s) const { return Coord(x * s, y * s); }
  Coord& operator+=(const Coord& rhs) { x += rhs.x; y += rhs.y; return *this; }
  Coord& operator-=(const Coord& rhs) { x -= rhs.x; y -= rhs.y; return *this; }
  void Set(T in_x, T in_y) { x = in_x; y = in_y; }
  T& X() { return x; }
  T& Y() { return y; }
  T X() const { return x; }
  T Y() const { return y; }
};

}  // namespace Apto

#endif
