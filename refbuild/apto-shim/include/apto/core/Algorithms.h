// apto-shim (see platform.h header note)
#ifndef AptoCoreAlgorithms_h
#define AptoCoreAlgorithms_h

#include "Array.h"

#include <algorithm>
#include <vector>

namespace Apto {

template <class T> inline T Abs(const T& v) { return (v < T(0)) ? -v : v; }

template <class T> inline const T& Min(const T& a, const T& b)
{ return (b < a) ? b : a; }
template <class T> inline const T& Max(const T& a, const T& b)
{ return (a < b) ? b : a; }

// QSort over an Apto::Array range [from, to] (inclusive, upstream API).
template <class T, template <class> class P>
inline void QSort(Array<T, P>& array, int from, int to)
{
  if (from < 0 || to >= array.GetSize() || from >= to) return;
  // simple in-place sort via std::sort on a copy window
  std::vector<T> tmp;
  tmp.reserve(to - from + 1);
  for (int i = from; i <= to; i++) tmp.push_back(array[i]);
  std::sort(tmp.begin(), tmp.end());
  for (int i = from; i <= to; i++) array[i] = tmp[i - from];
}

// QSort with an int comparator functor (negative = less-than)
template <class T, template <class> class P, class Cmp>
inline void QSort(Array<T, P>& array, Cmp comparator)
{
  std::vector<T> tmp;
  tmp.reserve(array.GetSize());
  for (int i = 0; i < array.GetSize(); i++) tmp.push_back(array[i]);
  std::stable_sort(tmp.begin(), tmp.end(),
                   [&comparator](const T& a, const T& b)
                   { return comparator(a, b) < 0; });
  for (int i = 0; i < array.GetSize(); i++) array[i] = tmp[i];
}

template <class T, template <class> class P>
inline void QSort(Array<T, P>& array)
{ QSort(array, 0, array.GetSize() - 1); }

}  // namespace Apto

#endif
