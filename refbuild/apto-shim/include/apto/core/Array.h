// apto-shim (see platform.h header note)
#ifndef AptoCoreArray_h
#define AptoCoreArray_h

#include "Definitions.h"

#include <algorithm>

namespace Apto {

// Apto::Array<T, StoragePolicy> -- dynamic array.  The upstream policies
// (Basic/Smart/ManagedPointer) change growth/ownership strategy; the shim
// backs every policy with one plain heap buffer (NOT std::vector: the
// vector<bool> proxy specialization breaks `bool&` references that
// avida-core takes into arrays).
template <class T, template <class> class Policy = Basic>
class Array
{
private:
  T* m_data;
  int m_size;
  int m_cap;

  void grow(int need)
  {
    if (need <= m_cap) return;
    int cap = (m_cap > 0) ? m_cap : 4;
    while (cap < need) cap *= 2;
    T* nd = new T[cap];
    for (int i = 0; i < m_size; i++) nd[i] = m_data[i];
    delete[] m_data;
    m_data = nd;
    m_cap = cap;
  }

public:
  typedef T ValueType;

  Array() : m_data(NULL), m_size(0), m_cap(0) {}
  explicit Array(int size) : m_data(NULL), m_size(0), m_cap(0)
  { Resize(size); }
  Array(int size, const T& init) : m_data(NULL), m_size(0), m_cap(0)
  { Resize(size, init); }
  Array(const Array& rhs) : m_data(NULL), m_size(0), m_cap(0) { *this = rhs; }
  template <template <class> class P2>
  Array(const Array<T, P2>& rhs) : m_data(NULL), m_size(0), m_cap(0)
  { *this = rhs; }
  ~Array() { delete[] m_data; }

  template <template <class> class P2>
  Array& operator=(const Array<T, P2>& rhs)
  {
    ResizeClear(rhs.GetSize());
    for (int i = 0; i < m_size; i++) m_data[i] = rhs[i];
    return *this;
  }
  Array& operator=(const Array& rhs)
  {
    if (this == &rhs) return *this;
    ResizeClear(rhs.GetSize());
    for (int i = 0; i < m_size; i++) m_data[i] = rhs.m_data[i];
    return *this;
  }

  inline int GetSize() const { return m_size; }

  inline void ResizeClear(const int in_size)
  {
    delete[] m_data;
    m_data = NULL;
    m_size = m_cap = 0;
    Resize(in_size);
  }
  inline void Resize(int new_size)
  {
    if (new_size < 0) new_size = 0;
    // new slots keep their new[]-default-constructed state from grow();
    // assigning T() here would run T::operator= against a default-
    // constructed temporary, which classes like cPopulationCell (null
    // m_mut_rates dereferenced in operator=) do not support -- upstream
    // apto also leaves new slots default-constructed
    if (new_size > m_size) grow(new_size);
    m_size = new_size;
  }
  inline void Resize(int new_size, const T& empty_value)
  {
    int old = m_size;
    Resize(new_size);
    for (int i = old; i < m_size; i++) m_data[i] = empty_value;
  }

  T& operator[](const int index)
  {
    assert(index >= 0 && index < m_size);
    return m_data[index];
  }
  const T& operator[](const int index) const
  {
    assert(index >= 0 && index < m_size);
    return m_data[index];
  }

  inline T& Get(const int index) { return (*this)[index]; }
  inline const T& Get(const int index) const { return (*this)[index]; }

  inline void Push(const T& value)
  {
    grow(m_size + 1);
    m_data[m_size++] = value;
  }
  inline T Pop()
  {
    T v = m_data[m_size - 1];
    m_size--;
    return v;
  }

  inline void Swap(int idx1, int idx2)
  { std::swap(m_data[idx1], m_data[idx2]); }
  inline void Swap(Array& rhs)
  {
    std::swap(m_data, rhs.m_data);
    std::swap(m_size, rhs.m_size);
    std::swap(m_cap, rhs.m_cap);
  }

  Array operator+(const Array& rhs) const
  {
    Array out(*this);
    for (int i = 0; i < rhs.GetSize(); i++) out.Push(rhs[i]);
    return out;
  }

  inline void SetAll(const T& value)
  { for (int i = 0; i < m_size; i++) m_data[i] = value; }

  inline void Clear() { m_size = 0; }
  inline void SetReserve(int reserve) { grow(reserve); }

  inline void RemoveAt(int index)
  {
    for (int i = index; i < m_size - 1; i++) m_data[i] = m_data[i + 1];
    m_size--;
  }

  // Range view [from, to] inclusive (upstream Array::Range) -- enough API
  // for the cTopology builders: GetSize + operator[]
  class RangeView
  {
  private:
    Array* m_arr;
    int m_from;
    int m_size;
  public:
    RangeView(Array* arr, int from, int to)
      : m_arr(arr), m_from(from), m_size(to - from + 1) {}
    int GetSize() const { return m_size; }
    T& operator[](int i) { return (*m_arr)[m_from + i]; }
    const T& operator[](int i) const { return (*m_arr)[m_from + i]; }
    RangeView Range(int from, int to)
    { return RangeView(m_arr, m_from + from, m_from + to); }
  };
  RangeView Range(int from, int to) { return RangeView(this, from, to); }

  // iterator API (upstream exposes Iterator/ConstIterator with
  // Next()/Get() protocol)
  class Iterator
  {
  private:
    Array& m_arr;
    int m_index;
  public:
    explicit Iterator(Array& arr) : m_arr(arr), m_index(-1) {}
    T* Get() { return (m_index >= 0 && m_index < m_arr.GetSize()) ? &m_arr[m_index] : NULL; }
    T* Next() { m_index++; return Get(); }
  };
  class ConstIterator
  {
  private:
    const Array& m_arr;
    int m_index;
  public:
    explicit ConstIterator(const Array& arr) : m_arr(arr), m_index(-1) {}
    const T* Get() { return (m_index >= 0 && m_index < m_arr.GetSize()) ? &m_arr[m_index] : NULL; }
    const T* Next() { m_index++; return Get(); }
  };
  Iterator Begin() { return Iterator(*this); }
  ConstIterator Begin() const { return ConstIterator(*this); }
};

// ManagedPointer storage: elements live behind stable heap pointers and
// are never copied/assigned -- required for types with private assignment
// (e.g. hardware Thread classes).  Grow/shrink moves pointers only.
template <class T>
class Array<T, ManagedPointer>
{
private:
  T** m_ptrs;
  int m_size;
  int m_cap;

  void grow(int need)
  {
    if (need <= m_cap) return;
    int cap = (m_cap > 0) ? m_cap : 4;
    while (cap < need) cap *= 2;
    T** np_ = new T*[cap];
    for (int i = 0; i < m_size; i++) np_[i] = m_ptrs[i];
    delete[] m_ptrs;
    m_ptrs = np_;
    m_cap = cap;
  }

public:
  typedef T ValueType;

  Array() : m_ptrs(NULL), m_size(0), m_cap(0) {}
  explicit Array(int size) : m_ptrs(NULL), m_size(0), m_cap(0)
  { Resize(size); }
  ~Array()
  {
    for (int i = 0; i < m_size; i++) delete m_ptrs[i];
    delete[] m_ptrs;
  }

  inline int GetSize() const { return m_size; }

  inline void Resize(int new_size)
  {
    if (new_size < 0) new_size = 0;
    for (int i = new_size; i < m_size; i++) delete m_ptrs[i];
    grow(new_size);
    for (int i = m_size; i < new_size; i++) m_ptrs[i] = new T();
    m_size = new_size;
  }
  inline void ResizeClear(const int in_size)
  {
    for (int i = 0; i < m_size; i++) delete m_ptrs[i];
    m_size = 0;
    Resize(in_size);
  }

  inline void Push(const T& value)
  {
    grow(m_size + 1);
    m_ptrs[m_size] = new T(value);
    m_size++;
  }

  T& operator[](const int index)
  {
    assert(index >= 0 && index < m_size);
    return *m_ptrs[index];
  }
  const T& operator[](const int index) const
  {
    assert(index >= 0 && index < m_size);
    return *m_ptrs[index];
  }

private:
  Array(const Array&);
  Array& operator=(const Array&);
};

}  // namespace Apto

#endif
