// apto-shim (see platform.h header note)
#ifndef AptoCoreConditionVariable_h
#define AptoCoreConditionVariable_h
#include "Mutex.h"
#endif
