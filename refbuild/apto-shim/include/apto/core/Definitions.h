// apto-shim (see platform.h header note)
#ifndef AptoCoreDefinitions_h
#define AptoCoreDefinitions_h

#include "../platform.h"

namespace Apto {

class NullType {};
struct EmptyType {};

// --- container inner-storage policies (tag types; the shim's containers
// all use the same std-backed storage, the tags only select defaults) ---
template <class T> class Basic;
template <class T> class Smart;
template <class T> class ManagedPointer;

// --- map/set storage-policy tags: template <Key, Value> class ---
template <class K, class V> class DefaultHashBTree {};
template <class K, class V> class HashBTree {};
// hash-table storage with static table size + hash functor + allocator
// (inherited from by avida-core property-map storage helpers)
template <class K, class V, int TableSize,
          template <class, int> class HashF, class Alloc>
class HashStaticTableLinkedList {};
// primary hash functor; avida-core specializes this for its own key types
template <class T, int HashFactor> class HashKey
{
public:
  static int Hash(const T&) { return 0; }
};

// --- Map defaults-policy tags ---
class ImplicitDefault {};
class ExplicitDefault {};
class Multi {};

// --- multithreading policy tags for ref counting ---
class ThreadSafe;
class SingleThreaded;

}  // namespace Apto

#endif
