// apto-shim (see platform.h header note)
#ifndef AptoCoreFileSystem_h
#define AptoCoreFileSystem_h

#include "String.h"

#include <sys/stat.h>
#include <sys/types.h>
#include <dirent.h>
#include <unistd.h>
#include <cstdio>

namespace Apto {
namespace FileSystem {

inline String PathAppend(const String& path, const String& path_add)
{
  return path + "/" + path_add;
}

inline String GetCWD()
{
  char buf[4096];
  if (getcwd(buf, sizeof(buf))) return String(buf);
  return String(".");
}

inline String GetAbsolutePath(const String& path, const String& working_dir)
{
  if (path.GetSize() == 0) return working_dir;
  if (path[0] == '/') return path;
  return PathAppend(working_dir, path);
}

inline bool IsFile(const String& path)
{
  struct stat st;
  return stat((const char*)path, &st) == 0 && S_ISREG(st.st_mode);
}

inline bool IsDir(const String& path)
{
  struct stat st;
  return stat((const char*)path, &st) == 0 && S_ISDIR(st.st_mode);
}

inline bool MkDir(const String& path)
{
  if (IsDir(path)) return true;
  return mkdir((const char*)path, 0777) == 0;
}

inline bool RmDir(const String& path, bool recursive = false)
{
  if (!recursive) return rmdir((const char*)path) == 0;
  DIR* d = opendir((const char*)path);
  if (d) {
    struct dirent* e;
    while ((e = readdir(d))) {
      String name(e->d_name);
      if (name == "." || name == "..") continue;
      String sub = PathAppend(path, name);
      if (IsDir(sub)) RmDir(sub, true);
      else unlink((const char*)sub);
    }
    closedir(d);
  }
  return rmdir((const char*)path) == 0;
}

inline bool CpFile(const String& from, const String& to)
{
  FILE* in = fopen((const char*)from, "rb");
  if (!in) return false;
  FILE* out = fopen((const char*)to, "wb");
  if (!out) { fclose(in); return false; }
  char buf[65536];
  size_t n;
  while ((n = fread(buf, 1, sizeof(buf), in)) > 0) fwrite(buf, 1, n, out);
  fclose(in);
  fclose(out);
  return true;
}

template <class ArrayT>
inline bool ReadDir(const String& path, ArrayT& entries)
{
  DIR* d = opendir((const char*)path);
  if (!d) return false;
  struct dirent* e;
  while ((e = readdir(d))) {
    String name(e->d_name);
    if (name == "." || name == "..") continue;
    entries.Push(name);
  }
  closedir(d);
  return true;
}

}  // namespace FileSystem
}  // namespace Apto

#endif
