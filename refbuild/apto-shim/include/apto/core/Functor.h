// apto-shim (see platform.h header note)
#ifndef AptoCoreFunctor_h
#define AptoCoreFunctor_h

#include "Definitions.h"
#include "TypeList.h"

#include <functional>
#include <type_traits>
#include <utility>

namespace Apto {

namespace Internal {
// Map the typelist parameter (TL::Create<...> or NullType) to an argument
// pack via std::function.
template <class R, class TList> struct FunctorType;
template <class R> struct FunctorType<R, NullType>
{ typedef std::function<R()> Type; };
template <class R, class... Ts> struct FunctorType<R, TL::Create<Ts...> >
{ typedef std::function<R(Ts...)> Type; };
}  // namespace Internal

// Apto::Functor<ReturnType, TypeListOfArgs> -- callable wrapper accepting
// free functions, (object ptr, member fn ptr), lambdas and other functors.
template <class R, class TList = NullType, class Alloc = NullType>
class Functor
{
public:
  typedef typename Internal::FunctorType<R, TList>::Type FnType;

private:
  FnType m_fn;

public:
  Functor() {}
  Functor(const FnType& fn) : m_fn(fn) {}
  template <class F> Functor(F fn) : m_fn(fn) {}
  template <class Obj, class R2, class... As>
  Functor(Obj* obj, R2 (Obj::*fn)(As...))
  { m_fn = [obj, fn](As... args) -> R { return (obj->*fn)(args...); }; }
  template <class Obj, class R2, class... As>
  Functor(Obj* obj, R2 (Obj::*fn)(As...) const)
  { m_fn = [obj, fn](As... args) -> R { return (obj->*fn)(args...); }; }
  template <class Obj, class R2, class... As>
  Functor(const Obj* obj, R2 (Obj::*fn)(As...) const)
  { m_fn = [obj, fn](As... args) -> R { return (obj->*fn)(args...); }; }

  template <class... A> R operator()(A&&... args) const
  { return m_fn(std::forward<A>(args)...); }

  operator bool() const { return (bool)m_fn; }
  const FnType& Fn() const { return m_fn; }
};

// BindFirst: curry the first argument of a functor.  The bound value is
// captured by DECAYED copy (upstream binds a copy too), so reference-typed
// first parameters (const int&) accept plain values.
template <class R, class T1, class V>
Functor<R, NullType> BindFirst(const Functor<R, TL::Create<T1> >& f, V v)
{
  typename Functor<R, TL::Create<T1> >::FnType fn = f.Fn();
  typename std::decay<V>::type bound = v;
  return Functor<R, NullType>([fn, bound]() -> R { return fn(bound); });
}
template <class R, class T1, class... Rest, class V>
Functor<R, TL::Create<Rest...> >
BindFirst(const Functor<R, TL::Create<T1, Rest...> >& f, V v)
{
  typename Functor<R, TL::Create<T1, Rest...> >::FnType fn = f.Fn();
  typename std::decay<V>::type bound = v;
  return Functor<R, TL::Create<Rest...> >(
    [fn, bound](Rest... rest) -> R { return fn(bound, rest...); });
}

}  // namespace Apto

#endif
