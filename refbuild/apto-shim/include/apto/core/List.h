// apto-shim (see platform.h header note)
#ifndef AptoCoreList_h
#define AptoCoreList_h

#include "Definitions.h"

#include <list>
#include <algorithm>

namespace Apto {

// storage-policy tags for List
template <class T> class DL;         // doubly-linked (default upstream)
template <class T> class SparseVector;

// Apto::List<T, StoragePolicy> -- std::list-backed for every policy.
template <class T, template <class> class Policy = DL>
class List
{
private:
  std::list<T> m_list;

public:
  typedef T ValueType;

  List() {}

  inline int GetSize() const { return (int)m_list.size(); }
  inline void Clear() { m_list.clear(); }

  inline T& GetFirst() { return m_list.front(); }
  inline const T& GetFirst() const { return m_list.front(); }
  inline T& GetLast() { return m_list.back(); }
  inline const T& GetLast() const { return m_list.back(); }

  // Entry handles: O(1) removal tokens handed out by Push/PushRear
  // (upstream apto/core/List.h SparseVector interface)
  class EntryHandle
  {
    friend class List;
  private:
    List* m_list;
    typename std::list<T>::iterator m_it;
    bool m_valid;
  public:
    EntryHandle() : m_list(NULL), m_valid(false) {}
    bool IsValid() const { return m_valid; }
    void Remove()
    {
      if (m_valid && m_list) m_list->m_list.erase(m_it);
      m_valid = false;
    }
  };

  inline void Push(const T& value) { m_list.push_front(value); }
  inline void PushRear(const T& value) { m_list.push_back(value); }
  inline void Push(const T& value, EntryHandle** handle)
  {
    m_list.push_front(value);
    *handle = new EntryHandle();
    (*handle)->m_list = this;
    (*handle)->m_it = m_list.begin();
    (*handle)->m_valid = true;
  }
  inline void PushRear(const T& value, EntryHandle** handle)
  {
    m_list.push_back(value);
    *handle = new EntryHandle();
    (*handle)->m_list = this;
    (*handle)->m_it = --m_list.end();
    (*handle)->m_valid = true;
  }
  inline T Pop() { T v = m_list.front(); m_list.pop_front(); return v; }
  inline T PopRear() { T v = m_list.back(); m_list.pop_back(); return v; }

  bool Remove(const T& value)
  {
    typename std::list<T>::iterator it =
      std::find(m_list.begin(), m_list.end(), value);
    if (it == m_list.end()) return false;
    m_list.erase(it);
    return true;
  }
  bool Contains(const T& value) const
  {
    return std::find(m_list.begin(), m_list.end(), value) != m_list.end();
  }

  template <template <class> class P2>
  List& operator=(const List<T, P2>& rhs)
  {
    m_list.assign(rhs.Std().begin(), rhs.Std().end());
    return *this;
  }

  const std::list<T>& Std() const { return m_list; }
  std::list<T>& Std() { return m_list; }

  class Iterator
  {
  private:
    std::list<T>* m_list;
    typename std::list<T>::iterator m_it;
    bool m_started;
  public:
    Iterator() : m_list(NULL), m_started(false) {}
    explicit Iterator(List& list)
      : m_list(&list.m_list), m_started(false) {}
    T* Get()
    {
      if (!m_started || !m_list || m_it == m_list->end()) return NULL;
      return &*m_it;
    }
    T* Next()
    {
      if (!m_list) return NULL;
      if (!m_started) { m_it = m_list->begin(); m_started = true; }
      else if (m_it != m_list->end()) ++m_it;
      return Get();
    }
  };
  class ConstIterator
  {
  private:
    const std::list<T>* m_list;
    typename std::list<T>::const_iterator m_it;
    bool m_started;
  public:
    ConstIterator() : m_list(NULL), m_started(false) {}
    explicit ConstIterator(const List& list)
      : m_list(&list.m_list), m_started(false) {}
    const T* Get()
    {
      if (!m_started || !m_list || m_it == m_list->end()) return NULL;
      return &*m_it;
    }
    const T* Next()
    {
      if (!m_list) return NULL;
      if (!m_started) { m_it = m_list->begin(); m_started = true; }
      else if (m_it != m_list->end()) ++m_it;
      return Get();
    }
  };

  Iterator Begin() { return Iterator(*this); }
  ConstIterator Begin() const { return ConstIterator(*this); }
};

}  // namespace Apto

#endif
