// apto-shim (see platform.h header note)
#ifndef AptoCoreMap_h
#define AptoCoreMap_h

#include "Definitions.h"
#include "Pair.h"

#include <map>

namespace Apto {

// Apto::Map<K, V, HashPolicy, EntryPolicy> -- backed by std::map (ordered;
// upstream's HashBTree is also ordered-ish for iteration stability).
template <class K, class V,
          template <class, class> class StoragePolicy = DefaultHashBTree,
          class DefaultsPolicy = ImplicitDefault>
class Map
{
private:
  typedef std::map<K, V> StdMap;
  StdMap m_map;

public:
  typedef K KeyType;
  typedef V ValueType;

  Map() {}

  inline int GetSize() const { return (int)m_map.size(); }

  inline void Clear() { m_map.clear(); }

  // operator[] inserts default (upstream Get(key) semantics)
  V& operator[](const K& key) { return m_map[key]; }
  const V& operator[](const K& key) const { return Get(key); }

  V& Get(const K& key) { return m_map[key]; }
  const V& Get(const K& key) const
  {
    static V s_default = V();
    typename StdMap::const_iterator it = m_map.find(key);
    return (it == m_map.end()) ? s_default : it->second;
  }
  bool Get(const K& key, V& out) const
  {
    typename StdMap::const_iterator it = m_map.find(key);
    if (it == m_map.end()) return false;
    out = it->second;
    return true;
  }
  V GetWithDefault(const K& key, const V& default_value) const
  {
    typename StdMap::const_iterator it = m_map.find(key);
    return (it == m_map.end()) ? default_value : it->second;
  }
  inline void Set(const K& key, const V& value) { m_map[key] = value; }

  bool Has(const K& key) const { return m_map.find(key) != m_map.end(); }
  bool Remove(const K& key) { return m_map.erase(key) > 0; }

  bool operator==(const Map& rhs) const { return m_map == rhs.m_map; }
  bool operator!=(const Map& rhs) const { return !(*this == rhs); }

  class KeyIterator
  {
  private:
    StdMap* m_map;
    typename StdMap::iterator m_it;
    bool m_started;
  public:
    explicit KeyIterator(StdMap& map) : m_map(&map), m_started(false) {}
    const K* Get()
    {
      if (!m_started || m_it == m_map->end()) return NULL;
      return &m_it->first;
    }
    const K* Next()
    {
      if (!m_started) { m_it = m_map->begin(); m_started = true; }
      else if (m_it != m_map->end()) ++m_it;
      return Get();
    }
  };

  class ValueIterator
  {
  private:
    StdMap* m_map;
    typename StdMap::iterator m_it;
    bool m_started;
  public:
    explicit ValueIterator(StdMap& map) : m_map(&map), m_started(false) {}
    V* Get()
    {
      if (!m_started || m_it == m_map->end()) return NULL;
      return &m_it->second;
    }
    V* Next()
    {
      if (!m_started) { m_it = m_map->begin(); m_started = true; }
      else if (m_it != m_map->end()) ++m_it;
      return Get();
    }
  };

  class Iterator
  {
  private:
    StdMap* m_map;
    typename StdMap::iterator m_it;
    bool m_started;
    Pair<K, V*> m_cur;
  public:
    explicit Iterator(StdMap& map) : m_map(&map), m_started(false) {}
    Pair<K, V*>* Get()
    {
      if (!m_started || m_it == m_map->end()) return NULL;
      m_cur = Pair<K, V*>(m_it->first, &m_it->second);
      return &m_cur;
    }
    Pair<K, V*>* Next()
    {
      if (!m_started) { m_it = m_map->begin(); m_started = true; }
      else if (m_it != m_map->end()) ++m_it;
      return Get();
    }
  };
  typedef Iterator ConstIterator;

  KeyIterator Keys() { return KeyIterator(m_map); }
  KeyIterator Keys() const { return KeyIterator(const_cast<StdMap&>(m_map)); }
  ValueIterator Values() { return ValueIterator(m_map); }
  ValueIterator Values() const { return ValueIterator(const_cast<StdMap&>(m_map)); }
  Iterator Begin() { return Iterator(m_map); }
  Iterator Begin() const { return Iterator(const_cast<StdMap&>(m_map)); }
};

}  // namespace Apto

#endif
