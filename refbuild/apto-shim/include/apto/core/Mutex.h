// apto-shim (see platform.h header note)
#ifndef AptoCoreMutex_h
#define AptoCoreMutex_h

#include "Definitions.h"

#include <pthread.h>

namespace Apto {

class Mutex
{
  friend class ConditionVariable;
private:
  pthread_mutex_t m_mutex;
  Mutex(const Mutex&);
  Mutex& operator=(const Mutex&);
public:
  Mutex() { pthread_mutex_init(&m_mutex, NULL); }
  ~Mutex() { pthread_mutex_destroy(&m_mutex); }
  void Lock() { pthread_mutex_lock(&m_mutex); }
  void Unlock() { pthread_mutex_unlock(&m_mutex); }
};

class MutexAutoLock
{
private:
  Mutex& m_mutex;
  MutexAutoLock(const MutexAutoLock&);
public:
  explicit MutexAutoLock(Mutex& mutex) : m_mutex(mutex) { m_mutex.Lock(); }
  ~MutexAutoLock() { m_mutex.Unlock(); }
};

class ConditionVariable
{
private:
  pthread_cond_t m_cond;
public:
  ConditionVariable() { pthread_cond_init(&m_cond, NULL); }
  ~ConditionVariable() { pthread_cond_destroy(&m_cond); }
  void Wait(Mutex& mutex) { pthread_cond_wait(&m_cond, &mutex.m_mutex); }
  void Signal() { pthread_cond_signal(&m_cond); }
  void Broadcast() { pthread_cond_broadcast(&m_cond); }
};

class RWLock
{
private:
  pthread_rwlock_t m_lock;
public:
  RWLock() { pthread_rwlock_init(&m_lock, NULL); }
  ~RWLock() { pthread_rwlock_destroy(&m_lock); }
  void ReadLock() { pthread_rwlock_rdlock(&m_lock); }
  void ReadUnlock() { pthread_rwlock_unlock(&m_lock); }
  void WriteLock() { pthread_rwlock_wrlock(&m_lock); }
  void WriteUnlock() { pthread_rwlock_unlock(&m_lock); }
};

class RWLockAutoRead
{
private:
  RWLock& m_lock;
public:
  explicit RWLockAutoRead(RWLock& lock) : m_lock(lock) { m_lock.ReadLock(); }
  ~RWLockAutoRead() { m_lock.ReadUnlock(); }
};

class RWLockAutoWrite
{
private:
  RWLock& m_lock;
public:
  explicit RWLockAutoWrite(RWLock& lock) : m_lock(lock) { m_lock.WriteLock(); }
  ~RWLockAutoWrite() { m_lock.WriteUnlock(); }
};

}  // namespace Apto

#endif
