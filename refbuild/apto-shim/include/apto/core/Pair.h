// apto-shim (see platform.h header note)
#ifndef AptoCorePair_h
#define AptoCorePair_h

namespace Apto {

template <class V1, class V2 = V1>
class Pair
{
public:
  V1 m_v1;
  V2 m_v2;

  Pair() : m_v1(), m_v2() {}
  Pair(const V1& v1) : m_v1(v1), m_v2() {}
  Pair(const V1& v1, const V2& v2) : m_v1(v1), m_v2(v2) {}

  V1& Value1() { return m_v1; }
  const V1& Value1() const { return m_v1; }
  V2& Value2() { return m_v2; }
  const V2& Value2() const { return m_v2; }

  bool operator==(const Pair& rhs) const
  { return m_v1 == rhs.m_v1 && m_v2 == rhs.m_v2; }
  bool operator<(const Pair& rhs) const
  {
    if (m_v1 < rhs.m_v1) return true;
    if (rhs.m_v1 < m_v1) return false;
    return m_v2 < rhs.m_v2;
  }
};

}  // namespace Apto

#endif
