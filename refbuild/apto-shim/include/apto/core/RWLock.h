// apto-shim (see platform.h header note)
#ifndef AptoCoreRWLock_h
#define AptoCoreRWLock_h
#include "Mutex.h"
#endif
