// apto-shim (see platform.h header note)
#ifndef AptoCoreSet_h
#define AptoCoreSet_h

#include "Definitions.h"

#include <set>

namespace Apto {

template <class T,
          template <class, class> class StoragePolicy = DefaultHashBTree,
          class DefaultsPolicy = ImplicitDefault>
class Set
{
private:
  std::set<T> m_set;

public:
  typedef T ValueType;

  Set() {}
  template <template <class, class> class S2, class D2>
  Set(const Set<T, S2, D2>& rhs) { *this = rhs; }
  template <template <class, class> class S2, class D2>
  Set& operator=(const Set<T, S2, D2>& rhs)
  {
    m_set = rhs.Std();
    return *this;
  }
  const std::set<T>& Std() const { return m_set; }

  inline int GetSize() const { return (int)m_set.size(); }
  inline void Clear() { m_set.clear(); }

  inline void Insert(const T& value) { m_set.insert(value); }
  inline bool Has(const T& value) const { return m_set.count(value) > 0; }
  inline bool Remove(const T& value) { return m_set.erase(value) > 0; }

  bool operator==(const Set& rhs) const { return m_set == rhs.m_set; }
  bool operator!=(const Set& rhs) const { return !(*this == rhs); }

  class Iterator
  {
  private:
    std::set<T>* m_set;
    typename std::set<T>::iterator m_it;
    bool m_started;
  public:
    explicit Iterator(std::set<T>& s) : m_set(&s), m_started(false) {}
    const T* Get()
    {
      if (!m_started || m_it == m_set->end()) return NULL;
      return &*m_it;
    }
    const T* Next()
    {
      if (!m_started) { m_it = m_set->begin(); m_started = true; }
      else if (m_it != m_set->end()) ++m_it;
      return Get();
    }
  };
  typedef Iterator ConstIterator;
  Iterator Begin() { return Iterator(m_set); }
  Iterator Begin() const { return Iterator(const_cast<std::set<T>&>(m_set)); }
};

}  // namespace Apto

#endif
