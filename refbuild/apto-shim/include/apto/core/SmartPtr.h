// apto-shim (see platform.h header note)
#ifndef AptoCoreSmartPtr_h
#define AptoCoreSmartPtr_h

#include "Definitions.h"

#include <memory>

namespace Apto {

// Upstream SmartPtr takes storage/ownership/conversion policy params; all
// shim instantiations share std::shared_ptr semantics (matching the
// default InternalRCObject policy, the only one avida-core uses).
class InternalRCObject {};
class ThreadSafeRefCount {};

template <class T, class OwnershipPolicy = InternalRCObject>
class SmartPtr
{
private:
  std::shared_ptr<T> m_ptr;
  template <class T2, class P2> friend class SmartPtr;

public:
  SmartPtr() {}
  explicit SmartPtr(T* ptr) : m_ptr(ptr) {}
  SmartPtr(const std::shared_ptr<T>& p) : m_ptr(p) {}
  template <class T2, class P2>
  SmartPtr(const SmartPtr<T2, P2>& rhs) : m_ptr(rhs.m_ptr) {}

  template <class T2, class P2>
  SmartPtr& operator=(const SmartPtr<T2, P2>& rhs) { m_ptr = rhs.m_ptr; return *this; }

  T& operator*() const { return *m_ptr; }
  T* operator->() const { return m_ptr.get(); }
  T* GetPointer() const { return m_ptr.get(); }

  operator bool() const { return (bool)m_ptr; }
  bool operator!() const { return !m_ptr; }
  template <class T2, class P2>
  bool operator==(const SmartPtr<T2, P2>& rhs) const { return m_ptr == rhs.m_ptr; }
  template <class T2, class P2>
  bool operator!=(const SmartPtr<T2, P2>& rhs) const { return m_ptr != rhs.m_ptr; }
  bool operator==(const T* rhs) const { return m_ptr.get() == rhs; }
  bool operator!=(const T* rhs) const { return m_ptr.get() != rhs; }

  template <class T2>
  void DynamicCastFrom(const SmartPtr<T2>& rhs)
  { m_ptr = std::dynamic_pointer_cast<T>(rhs.m_ptr); }

  const std::shared_ptr<T>& Std() const { return m_ptr; }
};

template <class T, class P>
inline T* GetInternalPtr(const SmartPtr<T, P>& p) { return p.GetPointer(); }

// RefCountObject: intrusive ref-count base upstream; the shim keeps the
// API (AddReference/RemoveReference) for classes that inherit it, but
// SmartPtr above ignores it (shared_ptr external counting).
template <class ThreadingPolicy = SingleThreaded>
class RefCountObject
{
private:
  int m_count;
public:
  RefCountObject() : m_count(0) {}
  RefCountObject(const RefCountObject&) : m_count(0) {}
  RefCountObject& operator=(const RefCountObject&) { return *this; }
  virtual ~RefCountObject() {}
  void AddReference() { m_count++; }
  void RemoveReference() { if (--m_count == 0) delete this; }
  int RefCount() const { return m_count; }
};

class MTRefCountObject : public RefCountObject<ThreadSafe> {};

// --- singleton holder (apto/core/SingletonHolder.h upstream) ---
class CreateWithNew {};
class DestroyAtExit {};

template <class T, class CreatePolicy = CreateWithNew,
          class LifetimePolicy = DestroyAtExit,
          class ThreadingPolicy = SingleThreaded>
class SingletonHolder
{
public:
  static T& Instance()
  {
    static T s_instance;
    return s_instance;
  }
};

}  // namespace Apto

#endif
