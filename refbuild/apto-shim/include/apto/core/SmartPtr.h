// apto-shim (see platform.h header note)
#ifndef AptoCoreSmartPtr_h
#define AptoCoreSmartPtr_h

#include "Definitions.h"

#include <memory>
#include <type_traits>

namespace Apto {

// Upstream SmartPtr takes storage/ownership policy params.  avida-core
// uses two ownership flavors, selected by the policy tag:
//   * InternalRCObject: intrusive -- the pointee inherits RefCountObject
//     and carries its own count.  Critical property: constructing a
//     SmartPtr from a raw pointer ATTACHES to the existing count, so
//     `FacetPtr(new Facet)->AttachTo(w)` (which stores another SmartPtr
//     built from `this` inside AttachTo) is safe.  A shared_ptr backing
//     is NOT equivalent -- each raw-pointer construction would mint a
//     fresh control block and double-free (the round-4 shim's segfault).
//   * everything else (default, ThreadSafeRefCount): external counting,
//     plain shared_ptr semantics; used only for types that are never
//     re-wrapped from raw pointers.
// Dispatch is on the tag (not member detection: SmartPtr is routinely
// instantiated on incomplete types, where detection silently misfires).
class InternalRCObject {};
class ThreadSafeRefCount {};
class ExternalRC {};  // shim default tag (upstream default = non-intrusive)

// --- storage impls -------------------------------------------------------
template <class T, bool Intrusive>
struct PtrStore;

template <class T>
struct PtrStore<T, true> {  // intrusive: pointee owns the count
  typedef typename std::remove_const<T>::type NC;
  T* p;
  PtrStore() : p(0) {}
  explicit PtrStore(T* ptr) : p(ptr) { retain(); }
  PtrStore(const PtrStore& rhs) : p(rhs.p) { retain(); }
  template <class T2>
  PtrStore(const PtrStore<T2, true>& rhs) : p(rhs.p) { retain(); }
  ~PtrStore() { release(); }
  PtrStore& operator=(const PtrStore& rhs) { reset(rhs.p); return *this; }
  void reset(T* ptr) {
    if (ptr) const_cast<NC*>(ptr)->AddReference();
    release();
    p = ptr;
  }
  void retain() { if (p) const_cast<NC*>(p)->AddReference(); }
  void release() { if (p) const_cast<NC*>(p)->RemoveReference(); }
  T* get() const { return p; }
};

template <class T>
struct PtrStore<T, false> {  // external: shared_ptr semantics
  std::shared_ptr<T> p;
  PtrStore() {}
  explicit PtrStore(T* ptr) : p(ptr) {}
  PtrStore(const std::shared_ptr<T>& sp) : p(sp) {}
  template <class T2>
  PtrStore(const PtrStore<T2, false>& rhs) : p(rhs.p) {}
  void reset(T* ptr) { p.reset(ptr); }
  T* get() const { return p.get(); }
};

template <class T, class OwnershipPolicy = ExternalRC>
class SmartPtr
{
private:
  static const bool INTRUSIVE =
      std::is_same<OwnershipPolicy, InternalRCObject>::value;
  PtrStore<T, INTRUSIVE> m_store;
  template <class T2, class P2> friend class SmartPtr;

public:
  SmartPtr() {}
  explicit SmartPtr(T* ptr) : m_store(ptr) {}
  SmartPtr(const std::shared_ptr<T>& p) : m_store(p) {}
  SmartPtr(const SmartPtr& rhs) : m_store(rhs.m_store) {}
  template <class T2, class P2>
  SmartPtr(const SmartPtr<T2, P2>& rhs) : m_store(rhs.m_store) {}

  SmartPtr& operator=(const SmartPtr& rhs)
  { m_store = rhs.m_store; return *this; }
  template <class T2, class P2>
  SmartPtr& operator=(const SmartPtr<T2, P2>& rhs)
  { m_store = PtrStore<T, INTRUSIVE>(rhs.m_store); return *this; }

  T& operator*() const { return *m_store.get(); }
  T* operator->() const { return m_store.get(); }
  T* GetPointer() const { return m_store.get(); }

  operator bool() const { return m_store.get() != 0; }
  bool operator!() const { return !m_store.get(); }
  template <class T2, class P2>
  bool operator==(const SmartPtr<T2, P2>& rhs) const
  { return m_store.get() == rhs.m_store.get(); }
  template <class T2, class P2>
  bool operator!=(const SmartPtr<T2, P2>& rhs) const
  { return m_store.get() != rhs.m_store.get(); }
  bool operator==(const T* rhs) const { return m_store.get() == rhs; }
  bool operator!=(const T* rhs) const { return m_store.get() != rhs; }

  template <class T2, class P2>
  void DynamicCastFrom(const SmartPtr<T2, P2>& rhs)
  { dynCast(rhs, std::integral_constant<bool, INTRUSIVE>()); }

private:
  template <class T2, class P2>
  void dynCast(const SmartPtr<T2, P2>& rhs, std::true_type)
  { m_store.reset(dynamic_cast<T*>(rhs.GetPointer())); }
  template <class T2, class P2>
  void dynCast(const SmartPtr<T2, P2>& rhs, std::false_type)
  { m_store.p = std::dynamic_pointer_cast<T>(rhs.m_store.p); }
};

template <class T, class P>
inline T* GetInternalPtr(const SmartPtr<T, P>& p) { return p.GetPointer(); }

// RefCountObject: intrusive ref-count base (apto/core/RefCount.h upstream).
// Count starts at 0; every SmartPtr attach increments, detach decrements,
// zero deletes.  The `ManagerPtr(new Manager)->AttachTo(w)` pattern works
// because AttachTo stores a second SmartPtr built from `this` (count 2)
// before the temporary releases (count 1).
template <class ThreadingPolicy = SingleThreaded>
class RefCountObject
{
private:
  int m_count;
public:
  RefCountObject() : m_count(0) {}
  RefCountObject(const RefCountObject&) : m_count(0) {}
  RefCountObject& operator=(const RefCountObject&) { return *this; }
  virtual ~RefCountObject() {}
  void AddReference() { m_count++; }
  void RemoveReference() { if (--m_count == 0) delete this; }
  int RefCount() const { return m_count; }
};

class MTRefCountObject : public RefCountObject<ThreadSafe> {};

// --- singleton holder (apto/core/SingletonHolder.h upstream) ---
class CreateWithNew {};
class DestroyAtExit {};

template <class T, class CreatePolicy = CreateWithNew,
          class LifetimePolicy = DestroyAtExit,
          class ThreadingPolicy = SingleThreaded>
class SingletonHolder
{
public:
  static T& Instance()
  {
    static T s_instance;
    return s_instance;
  }
};

}  // namespace Apto

#endif
