// apto-shim (see platform.h header note)
#ifndef AptoCoreString_h
#define AptoCoreString_h

#include "Definitions.h"

#include <string>
#include <cstring>
#include <cstdio>
#include <cctype>

namespace Apto {

// Apto::BasicString<ThreadingPolicy> -- immutable-ish ref-counted string
// upstream; plain std::string wrapper here.  Apto::String = the default
// instantiation (typedef at the bottom).
template <class ThreadingPolicy = SingleThreaded>
class BasicString
{
private:
  std::string m_str;

public:
  BasicString() {}
  BasicString(const char* str) : m_str(str ? str : "") {}
  BasicString(int size, const char* str) : m_str(str, str + size) {}
  BasicString(const std::string& s) : m_str(s) {}
  template <class P2> BasicString(const BasicString<P2>& rhs)
    : m_str(rhs.GetData(), rhs.GetData() + rhs.GetSize()) {}

  inline int GetSize() const { return (int)m_str.size(); }
  inline const char* GetData() const { return m_str.c_str(); }
  inline const char* GetCString() const { return m_str.c_str(); }
  inline operator const char*() const { return m_str.c_str(); }

  inline const std::string& StdString() const { return m_str; }

  BasicString& operator=(const BasicString& rhs) { m_str = rhs.m_str; return *this; }
  BasicString& operator=(const char* rhs) { m_str = rhs ? rhs : ""; return *this; }

  template <class P2> bool operator==(const BasicString<P2>& rhs) const
  { return m_str == rhs.StdString(); }
  bool operator==(const char* rhs) const { return m_str == (rhs ? rhs : ""); }
  template <class P2> bool operator!=(const BasicString<P2>& rhs) const
  { return !(*this == rhs); }
  bool operator!=(const char* rhs) const { return !(*this == rhs); }
  template <class P2> bool operator<(const BasicString<P2>& rhs) const
  { return m_str < rhs.StdString(); }
  bool operator<(const char* rhs) const { return m_str < std::string(rhs ? rhs : ""); }
  template <class P2> bool operator>(const BasicString<P2>& rhs) const
  { return m_str > rhs.StdString(); }
  template <class P2> bool operator<=(const BasicString<P2>& rhs) const
  { return m_str <= rhs.StdString(); }
  template <class P2> bool operator>=(const BasicString<P2>& rhs) const
  { return m_str >= rhs.StdString(); }

  char operator[](int index) const { return m_str[index]; }

  BasicString operator+(const BasicString& rhs) const { return BasicString(m_str + rhs.m_str); }
  BasicString operator+(const char* rhs) const { return BasicString(m_str + (rhs ? rhs : "")); }
  BasicString operator+(char c) const { std::string s(m_str); s += c; return BasicString(s); }
  BasicString& operator+=(const BasicString& rhs) { m_str += rhs.m_str; return *this; }
  BasicString& operator+=(const char* rhs) { m_str += (rhs ? rhs : ""); return *this; }
  BasicString& operator+=(char c) { m_str += c; return *this; }

  inline BasicString Substring(int idx = 0, int length = -1) const
  {
    if (idx < 0) idx = 0;
    if (idx > GetSize()) idx = GetSize();
    if (length < 0) length = GetSize() - idx;
    return BasicString(m_str.substr(idx, length));
  }
  inline bool IsEmpty() const { return m_str.empty(); }

  int Find(char c, int pos = 0) const
  {
    std::string::size_type r = m_str.find(c, pos);
    return (r == std::string::npos) ? -1 : (int)r;
  }
  int Find(const char* str, int pos = 0) const
  {
    std::string::size_type r = m_str.find(str, pos);
    return (r == std::string::npos) ? -1 : (int)r;
  }

  inline bool BeginsWith(const BasicString& prefix) const
  { return m_str.compare(0, prefix.m_str.size(), prefix.m_str) == 0; }

  BasicString Pop(char delim)
  {
    // returns up to delim, leaves remainder in this (upstream semantics)
    std::string::size_type r = m_str.find(delim);
    if (r == std::string::npos) {
      BasicString head(m_str);
      m_str.clear();
      return head;
    }
    BasicString head(m_str.substr(0, r));
    m_str = m_str.substr(r + 1);
    return head;
  }

  BasicString AsLower() const
  {
    std::string out(m_str);
    for (std::string::size_type i = 0; i < out.size(); i++)
      out[i] = (char)tolower(out[i]);
    return BasicString(out);
  }
  BasicString AsUpper() const
  {
    std::string out(m_str);
    for (std::string::size_type i = 0; i < out.size(); i++)
      out[i] = (char)toupper(out[i]);
    return BasicString(out);
  }

  BasicString ToLower() const { return AsLower(); }
  BasicString ToUpper() const { return AsUpper(); }

  BasicString Clone() const { return BasicString(m_str); }

  bool IsNumber(int pos) const
  {
    if (pos < 0 || pos >= GetSize()) return false;
    return isdigit(m_str[pos]) || m_str[pos] == '-' || m_str[pos] == '+';
  }
  bool IsNumber() const
  {
    if (m_str.empty()) return false;
    char* end = NULL;
    strtod(m_str.c_str(), &end);
    return end && *end == '\0';
  }

  BasicString Trim() const
  {
    std::string::size_type b = m_str.find_first_not_of(" \t\r\n");
    if (b == std::string::npos) return BasicString();
    std::string::size_type e = m_str.find_last_not_of(" \t\r\n");
    return BasicString(m_str.substr(b, e - b + 1));
  }

  class StringTransparentConversion;
};

typedef BasicString<SingleThreaded> String;

}  // namespace Apto

#endif
