// apto-shim (see platform.h header note)
#ifndef AptoCoreStringBuffer_h
#define AptoCoreStringBuffer_h

#include "String.h"

namespace Apto {

// mutable string builder (upstream apto/core/StringBuffer.h)
class StringBuffer
{
private:
  std::string m_str;

public:
  StringBuffer() {}
  StringBuffer(const char* str) : m_str(str ? str : "") {}
  StringBuffer(const String& str) : m_str((const char*)str) {}

  inline int GetSize() const { return (int)m_str.size(); }
  inline operator const char*() const { return m_str.c_str(); }
  inline const char* GetData() const { return m_str.c_str(); }

  char operator[](int i) const { return m_str[i]; }
  char& operator[](int i) { return m_str[i]; }

  StringBuffer& operator+=(char c) { m_str += c; return *this; }
  StringBuffer& operator+=(const char* s) { m_str += (s ? s : ""); return *this; }
  StringBuffer& operator+=(const String& s) { m_str += (const char*)s; return *this; }
  StringBuffer& operator=(const char* s) { m_str = (s ? s : ""); return *this; }
};

}  // namespace Apto

#endif
