// apto-shim (see platform.h header note)
#ifndef AptoCoreStringUtils_h
#define AptoCoreStringUtils_h

#include "String.h"

#include <cstdarg>
#include <cstdio>
#include <cstdlib>
#include <string>

namespace Apto {

// Apto::StrAs -- proxy with implicit conversions string -> number.
class StrAs
{
private:
  std::string m_str;
public:
  StrAs(const String& s) : m_str((const char*)s) {}
  StrAs(const char* s) : m_str(s ? s : "") {}
  template <class P> StrAs(const BasicString<P>& s) : m_str(s.StdString()) {}

  operator int() const { return (int)strtol(m_str.c_str(), NULL, 10); }
  operator long() const { return strtol(m_str.c_str(), NULL, 10); }
  operator unsigned int() const { return (unsigned int)strtoul(m_str.c_str(), NULL, 10); }
  operator double() const { return strtod(m_str.c_str(), NULL); }
  operator float() const { return (float)strtod(m_str.c_str(), NULL); }
  operator String() const { return String(m_str.c_str()); }
  operator bool() const
  {
    if (m_str == "true" || m_str == "TRUE" || m_str == "1") return true;
    return strtol(m_str.c_str(), NULL, 10) != 0;
  }

  bool operator==(const char* rhs) const { return m_str == (rhs ? rhs : ""); }
  bool operator!=(const char* rhs) const { return !(*this == rhs); }
};

inline String AsStr(int v)
{ char b[32]; snprintf(b, sizeof(b), "%d", v); return String(b); }
inline String AsStr(long v)
{ char b[32]; snprintf(b, sizeof(b), "%ld", v); return String(b); }
inline String AsStr(unsigned int v)
{ char b[32]; snprintf(b, sizeof(b), "%u", v); return String(b); }
inline String AsStr(double v)
{ char b[48]; snprintf(b, sizeof(b), "%f", v); return String(b); }
inline String AsStr(const char* v) { return String(v); }
inline String AsStr(const String& v) { return v; }

// fuzzy-match suggestion helper (error messages only); the shim returns
// the empty string ("no suggestion")
template <class Iter>
inline String NearMatch(const String&, Iter) { return String(); }

inline String FormatStr(const char* fmt, ...)
{
  char buf[4096];
  va_list args;
  va_start(args, fmt);
  vsnprintf(buf, sizeof(buf), fmt, args);
  va_end(args);
  return String(buf);
}

}  // namespace Apto

#endif
