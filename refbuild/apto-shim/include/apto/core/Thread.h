// apto-shim (see platform.h header note)
#ifndef AptoCoreThread_h
#define AptoCoreThread_h

#include "Definitions.h"
#include "Mutex.h"

#include <pthread.h>

namespace Apto {

class Thread
{
private:
  pthread_t m_thread;
  bool m_running;

  static void* EntryPoint(void* arg)
  {
    static_cast<Thread*>(arg)->Run();
    return NULL;
  }

protected:
  virtual void Run() = 0;

public:
  Thread() : m_running(false) {}
  virtual ~Thread() { if (m_running) Join(); }

  bool Start()
  {
    if (m_running) return true;
    m_running = (pthread_create(&m_thread, NULL, EntryPoint, this) == 0);
    return m_running;
  }
  void Join()
  {
    if (m_running) {
      pthread_join(m_thread, NULL);
      m_running = false;
    }
  }
};

}  // namespace Apto

#endif
