// apto-shim (see platform.h header note)
#ifndef AptoCoreTypeList_h
#define AptoCoreTypeList_h

#include "Definitions.h"

namespace Apto {
namespace TL {

template <class T, class U> struct TypeList
{
  typedef T Head;
  typedef U Tail;
};

// Upstream TL::Create<T1, ..., Tn> is a macro-generated typelist builder;
// avida-core uses the Create<...> instantiation ITSELF as the type
// parameter (e.g. Apto::Functor<R, Apto::TL::Create<int, double> >), so
// the shim's Functor machinery pattern-matches directly on Create<...>.
template <class... Ts> struct Create
{
  // cons-list view, for completeness
  typedef NullType TList;
};
template <class T, class... Ts> struct Create<T, Ts...>
{
  typedef TypeList<T, typename Create<Ts...>::TList> TList;
};

}  // namespace TL
}  // namespace Apto

#endif
