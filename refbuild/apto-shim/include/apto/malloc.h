// apto-shim (see platform.h header note)
#ifndef AptoMalloc_h
#define AptoMalloc_h

#include <cstdlib>

namespace Apto {

class BasicMalloc {};

namespace Malloc {
template <class SuperMalloc> class TCFreeList {};
template <int Size, class M1, class M2> class FixedSegment {};
}  // namespace Malloc

// Apto::ClassAllocator<Alloc> -- upstream overrides operator new/delete to
// route through the allocator policy; the shim inherits default global new.
template <class Alloc> class ClassAllocator {};

}  // namespace Apto
#endif
