// apto-shim: minimal reimplementation of the apto utility library API used
// by avida-core, written from scratch over the C++ standard library so the
// reference simulator can be BUILT AND MEASURED in this environment (the
// real apto submodule is empty and cannot be fetched).  Semantics-bearing
// pieces (Random, schedulers) are documented in their headers; containers
// are API-compatible wrappers with no attempt at ABI or performance parity.
#ifndef AptoPlatform_h
#define AptoPlatform_h

#include <cstddef>
#include <cassert>

#define APTO_PLATFORM(X) APTO_PLATFORM_IS_##X
#define APTO_PLATFORM_IS_WINDOWS 0
#define APTO_PLATFORM_IS_FREEBSD 0
#define APTO_PLATFORM_IS_UNIX 1
#define APTO_PLATFORM_IS_APPLE 0

#ifndef NULL
#define NULL 0
#endif

#ifndef LIB_EXPORT
#define LIB_EXPORT
#endif
#ifndef LIB_IMPORT
#define LIB_IMPORT
#endif
#ifndef LIB_LOCAL
#define LIB_LOCAL
#endif
#ifndef LIB_HIDDEN
#define LIB_HIDDEN
#endif

namespace Apto {
namespace Platform {
inline void Initialize() {}
inline int AvailableCPUs() { return 1; }
}  // namespace Platform
}  // namespace Apto

#endif
