// apto-shim (see platform.h header note)
//
// Apto::Random / Apto::RNG::AvidaRNG.  SEMANTICS NOTE: the upstream
// AvidaRNG is a specific lagged generator whose exact stream cannot be
// reproduced here (the submodule is unavailable); this shim uses
// std::mt19937 underneath.  Every DISTRIBUTION (uniform, P, binomial,
// normal, poisson) follows the documented upstream contract, so
// population-level statistics are comparable, but per-seed golden files
// will differ -- which is true of any cross-RNG comparison and is exactly
// why the avida-tpu baseline protocol is distributional (BASELINE.md).
#ifndef AptoRNG_h
#define AptoRNG_h

#include "core/Definitions.h"

#include <cmath>
#include <random>

namespace Apto {

class Random
{
protected:
  std::mt19937 m_gen;
  int m_seed;

public:
  explicit Random(int seed = -1) { ResetSeed(seed); }
  virtual ~Random() {}

  int GetSeed() const { return m_seed; }
  int MaxSeed() const { return 0x7FFFFFFF; }

  void ResetSeed(int seed)
  {
    m_seed = seed;
    if (seed <= 0) {
      std::random_device rd;
      m_seed = (int)(rd() & 0x7FFFFFFF);
      if (m_seed <= 0) m_seed = 1;
    }
    m_gen.seed((unsigned int)m_seed);
  }
  void Seed(int seed) { ResetSeed(seed); }
  int Seed() const { return m_seed; }

  // uniform double in [0, 1)
  double GetDouble()
  {
    return (m_gen() >> 5) * (1.0 / 67108864.0) / 2.0 +
           (m_gen() >> 6) * (1.0 / 67108864.0 / 67108864.0);
  }
  double GetDouble(double max) { return GetDouble() * max; }
  double GetDouble(double min, double max)
  { return GetDouble() * (max - min) + min; }

  // uniform unsigned int in [0, max)
  unsigned int GetUInt(unsigned int max)
  {
    if (max == 0) return 0;
    std::uniform_int_distribution<unsigned int> d(0, max - 1);
    return d(m_gen);
  }
  unsigned int GetUInt(unsigned int min, unsigned int max)
  { return GetUInt(max - min) + min; }

  // uniform int
  int GetInt() { return (int)(m_gen() & 0x7FFFFFFF); }
  int GetInt(int max) { return (int)GetUInt((unsigned int)(max > 0 ? max : 0)); }
  int GetInt(int min, int max) { return GetInt(max - min) + min; }

  // biased coin
  bool P(double p) { return GetDouble() < p; }

  // std::random_shuffle generator protocol: g(n) in [0, n)
  long operator()(long n) { return (long)GetUInt((unsigned int)n); }

  // random selection of k distinct ints in [0, num) -- upstream Choose
  template <class ArrayT>
  void Choose(int num, ArrayT& out)
  {
    for (int i = 0; i < out.GetSize(); i++) {
      bool again = true;
      while (again) {
        out[i] = GetInt(num);
        again = false;
        for (int j = 0; j < i; j++) if (out[j] == out[i]) { again = true; break; }
      }
    }
  }

  double GetRandNormal()
  {
    std::normal_distribution<double> d(0.0, 1.0);
    return d(m_gen);
  }
  double GetRandNormal(double mean, double variance)
  { return mean + GetRandNormal() * std::sqrt(variance); }

  unsigned int GetRandPoisson(double mean)
  {
    if (mean <= 0.0) return 0;
    std::poisson_distribution<unsigned int> d(mean);
    return d(m_gen);
  }
  unsigned int GetRandPoisson(double n, double p) { return GetRandPoisson(n * p); }

  unsigned int GetFullRandBinomial(double n, double p)
  {
    std::binomial_distribution<unsigned int> d((unsigned int)n, p);
    return d(m_gen);
  }
  unsigned int GetRandBinomial(double n, double p)
  { return GetFullRandBinomial(n, p); }
};

namespace RNG {
class AvidaRNG : public Random
{
public:
  explicit AvidaRNG(int seed = -1) : Random(seed) {}
};
}  // namespace RNG

}  // namespace Apto

#endif
