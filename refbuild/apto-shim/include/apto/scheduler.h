// apto-shim (see platform.h header note)
//
// Apto::Scheduler::{RoundRobin, Probabilistic, Integrated,
// ProbabilisticIntegrated}.  Semantics contract (cAvidaConfig.h:545):
//   RoundRobin     -- SLICING_METHOD 0: equal cycles to every nonzero-
//                     priority entry, cyclic order.
//   Probabilistic  -- SLICING_METHOD 1: each Next() draws an entry with
//                     probability priority/sum(priorities).  Implemented
//                     as a Fenwick (binary-indexed) tree: O(log n) draw
//                     and priority update -- distributionally identical
//                     to upstream's weighted index tree.
//   Integrated     -- SLICING_METHOD 2: deterministic allocation
//                     proportional to priority.  Implemented as stride
//                     scheduling (min-pass entry runs, pass += 1/priority)
//                     which yields the same deterministic-proportional
//                     contract as upstream's binary merit decomposition.
#ifndef AptoScheduler_h
#define AptoScheduler_h

#include "core/Definitions.h"
#include "core/SmartPtr.h"
#include "rng.h"

#include <set>
#include <utility>
#include <vector>

namespace Apto {

class PriorityScheduler
{
public:
  virtual ~PriorityScheduler() {}
  virtual void AdjustPriority(int entry_id, double priority) = 0;
  virtual int Next() = 0;
};

namespace Scheduler {

class RoundRobin : public PriorityScheduler
{
private:
  std::vector<double> m_priority;
  int m_last;

public:
  explicit RoundRobin(int entry_count)
    : m_priority(entry_count, 0.0), m_last(entry_count - 1) {}

  void AdjustPriority(int entry_id, double priority)
  { m_priority[entry_id] = priority; }

  int Next()
  {
    const int n = (int)m_priority.size();
    for (int i = 1; i <= n; i++) {
      int cand = (m_last + i) % n;
      if (m_priority[cand] > 0.0) { m_last = cand; return cand; }
    }
    return -1;
  }
};

class Probabilistic : public PriorityScheduler
{
private:
  // Fenwick tree over entry weights
  std::vector<double> m_tree;   // 1-based
  std::vector<double> m_weight;
  double m_total;
  SmartPtr<Random> m_rng;

  void add(int idx, double delta)
  {
    for (int i = idx + 1; i <= (int)m_weight.size(); i += i & (-i))
      m_tree[i] += delta;
  }

public:
  Probabilistic(int entry_count, SmartPtr<Random> rng)
    : m_tree(entry_count + 1, 0.0), m_weight(entry_count, 0.0),
      m_total(0.0), m_rng(rng) {}

  void AdjustPriority(int entry_id, double priority)
  {
    double delta = priority - m_weight[entry_id];
    if (delta == 0.0) return;
    m_weight[entry_id] = priority;
    m_total += delta;
    add(entry_id, delta);
  }

  int Next()
  {
    if (m_total <= 0.0) return -1;
    double u = m_rng->GetDouble() * m_total;
    // descend the Fenwick tree
    int pos = 0;
    int mask = 1;
    const int n = (int)m_weight.size();
    while ((mask << 1) <= n) mask <<= 1;
    for (; mask; mask >>= 1) {
      int next = pos + mask;
      if (next <= n && m_tree[next] < u) {
        u -= m_tree[next];
        pos = next;
      }
    }
    if (pos >= n) pos = n - 1;
    // pos is 0-based entry index after descent
    if (m_weight[pos] <= 0.0) {
      // numerical edge: walk to a weighted entry
      for (int i = 0; i < n; i++) if (m_weight[i] > 0.0) return i;
      return -1;
    }
    return pos;
  }
};

class Integrated : public PriorityScheduler
{
private:
  // stride scheduling: entry with the smallest pass runs next
  typedef std::pair<double, int> Key;     // (pass, id)
  std::set<Key> m_queue;
  std::vector<double> m_pass;
  std::vector<double> m_priority;
  double m_clock;

public:
  explicit Integrated(int entry_count)
    : m_pass(entry_count, 0.0), m_priority(entry_count, 0.0), m_clock(0.0) {}

  void AdjustPriority(int entry_id, double priority)
  {
    if (m_priority[entry_id] > 0.0)
      m_queue.erase(Key(m_pass[entry_id], entry_id));
    m_priority[entry_id] = priority;
    if (priority > 0.0) {
      // (re)join at the current virtual clock
      m_pass[entry_id] = (m_pass[entry_id] > m_clock) ? m_pass[entry_id]
                                                      : m_clock;
      m_queue.insert(Key(m_pass[entry_id], entry_id));
    }
  }

  int Next()
  {
    if (m_queue.empty()) return -1;
    Key k = *m_queue.begin();
    m_queue.erase(m_queue.begin());
    int id = k.second;
    m_clock = k.first;
    m_pass[id] = k.first + 1.0 / m_priority[id];
    m_queue.insert(Key(m_pass[id], id));
    return id;
  }
};

class ProbabilisticIntegrated : public Probabilistic
{
public:
  ProbabilisticIntegrated(int entry_count, SmartPtr<Random> rng)
    : Probabilistic(entry_count, rng) {}
};

}  // namespace Scheduler
}  // namespace Apto

#endif
