// apto-shim (see platform.h header note)
#ifndef AptoStatAccumulator_h
#define AptoStatAccumulator_h

#include "../core/Definitions.h"

#include <cmath>

namespace Apto {
namespace Stat {

// Streaming accumulator: count/sum/sum-of-squares statistics
// (upstream apto/stat/Accumulator.h API, reconstructed from call sites).
template <class T>
class Accumulator
{
private:
  T m_sum;
  T m_sum2;   // sum of squares
  int m_n;

public:
  Accumulator() : m_sum(0), m_sum2(0), m_n(0) {}

  void Clear() { m_sum = 0; m_sum2 = 0; m_n = 0; }
  void Add(T value) { m_sum += value; m_sum2 += value * value; m_n++; }

  int Count() const { return m_n; }
  T Sum() const { return m_sum; }
  T SumOfSquares() const { return m_sum2; }

  double Mean() const { return m_n ? (double)m_sum / m_n : 0.0; }
  double Average() const { return Mean(); }

  double Variance() const
  {
    if (m_n < 2) return 0.0;
    double mean = Mean();
    return ((double)m_sum2 - m_n * mean * mean) / (m_n - 1);
  }
  double StdDeviation() const { return std::sqrt(Variance()); }
  double StdError() const
  { return m_n ? std::sqrt(Variance() / m_n) : 0.0; }
};

}  // namespace Stat
}  // namespace Apto

#endif
