// apto-shim: everything is header-only; this TU exists so the build
// produces a real static library for avida-core's FIND_LIBRARY.
#include "apto/core.h"
#include "apto/rng.h"
#include "apto/scheduler.h"
