file(REMOVE_RECURSE
  "lib/libavida-core.a"
)
