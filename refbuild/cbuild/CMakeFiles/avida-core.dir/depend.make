# Empty dependencies file for avida-core.
# This may be replaced when dependencies are built.
