CMakeFiles/avida-core.dir/source/analyze/cGenotypeData.cc.o: \
 /root/reference/avida-core/source/analyze/cGenotypeData.cc \
 /usr/include/stdc-predef.h \
 /root/reference/avida-core/source/analyze/cGenotypeData.h
