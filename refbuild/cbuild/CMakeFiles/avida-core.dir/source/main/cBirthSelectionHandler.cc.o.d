CMakeFiles/avida-core.dir/source/main/cBirthSelectionHandler.cc.o: \
 /root/reference/avida-core/source/main/cBirthSelectionHandler.cc \
 /usr/include/stdc-predef.h \
 /root/reference/avida-core/source/main/cBirthSelectionHandler.h
