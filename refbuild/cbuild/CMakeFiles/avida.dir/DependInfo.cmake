
# Consider dependencies only in project.
set(CMAKE_DEPENDS_IN_PROJECT_ONLY OFF)

# The set of languages for which implicit dependencies are needed:
set(CMAKE_DEPENDS_LANGUAGES
  )

# The set of dependency files which are needed:
set(CMAKE_DEPENDS_DEPENDENCY_FILES
  "/root/reference/avida-core/source/targets/avida/Avida2Driver.cc" "CMakeFiles/avida.dir/source/targets/avida/Avida2Driver.cc.o" "gcc" "CMakeFiles/avida.dir/source/targets/avida/Avida2Driver.cc.o.d"
  "/root/reference/avida-core/source/targets/avida/primitive.cc" "CMakeFiles/avida.dir/source/targets/avida/primitive.cc.o" "gcc" "CMakeFiles/avida.dir/source/targets/avida/primitive.cc.o.d"
  )

# Targets to which this target links.
set(CMAKE_TARGET_LINKED_INFO_FILES
  "/root/repo/refbuild/cbuild/CMakeFiles/avida-core.dir/DependInfo.cmake"
  )

# Fortran module output directory.
set(CMAKE_Fortran_TARGET_MODULE_DIR "")
