file(REMOVE_RECURSE
  "CMakeFiles/avida.dir/source/targets/avida/Avida2Driver.cc.o"
  "CMakeFiles/avida.dir/source/targets/avida/Avida2Driver.cc.o.d"
  "CMakeFiles/avida.dir/source/targets/avida/primitive.cc.o"
  "CMakeFiles/avida.dir/source/targets/avida/primitive.cc.o.d"
  "bin/avida"
  "bin/avida.pdb"
)

# Per-language clean rules from dependency scanning.
foreach(lang CXX)
  include(CMakeFiles/avida.dir/cmake_clean_${lang}.cmake OPTIONAL)
endforeach()
