# Empty dependencies file for avida.
# This may be replaced when dependencies are built.
