
# Consider dependencies only in project.
set(CMAKE_DEPENDS_IN_PROJECT_ONLY OFF)

# The set of languages for which implicit dependencies are needed:
set(CMAKE_DEPENDS_LANGUAGES
  )

# The set of dependency files which are needed:
set(CMAKE_DEPENDS_DEPENDENCY_FILES
  "/root/reference/avida-core/source/viewer/ClassificationInfo.cc" "CMakeFiles/viewer.dir/source/viewer/ClassificationInfo.cc.o" "gcc" "CMakeFiles/viewer.dir/source/viewer/ClassificationInfo.cc.o.d"
  "/root/reference/avida-core/source/viewer/Color.cc" "CMakeFiles/viewer.dir/source/viewer/Color.cc.o" "gcc" "CMakeFiles/viewer.dir/source/viewer/Color.cc.o.d"
  "/root/reference/avida-core/source/viewer/Driver.cc" "CMakeFiles/viewer.dir/source/viewer/Driver.cc.o" "gcc" "CMakeFiles/viewer.dir/source/viewer/Driver.cc.o.d"
  "/root/reference/avida-core/source/viewer/Freezer.cc" "CMakeFiles/viewer.dir/source/viewer/Freezer.cc.o" "gcc" "CMakeFiles/viewer.dir/source/viewer/Freezer.cc.o.d"
  "/root/reference/avida-core/source/viewer/GraphicsContext.cc" "CMakeFiles/viewer.dir/source/viewer/GraphicsContext.cc.o" "gcc" "CMakeFiles/viewer.dir/source/viewer/GraphicsContext.cc.o.d"
  "/root/reference/avida-core/source/viewer/Listener.cc" "CMakeFiles/viewer.dir/source/viewer/Listener.cc.o" "gcc" "CMakeFiles/viewer.dir/source/viewer/Listener.cc.o.d"
  "/root/reference/avida-core/source/viewer/Map.cc" "CMakeFiles/viewer.dir/source/viewer/Map.cc.o" "gcc" "CMakeFiles/viewer.dir/source/viewer/Map.cc.o.d"
  "/root/reference/avida-core/source/viewer/OrganismTrace.cc" "CMakeFiles/viewer.dir/source/viewer/OrganismTrace.cc.o" "gcc" "CMakeFiles/viewer.dir/source/viewer/OrganismTrace.cc.o.d"
  )

# Targets to which this target links.
set(CMAKE_TARGET_LINKED_INFO_FILES
  )

# Fortran module output directory.
set(CMAKE_Fortran_TARGET_MODULE_DIR "")
