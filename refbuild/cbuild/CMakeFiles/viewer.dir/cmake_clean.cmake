file(REMOVE_RECURSE
  "CMakeFiles/viewer.dir/source/viewer/ClassificationInfo.cc.o"
  "CMakeFiles/viewer.dir/source/viewer/ClassificationInfo.cc.o.d"
  "CMakeFiles/viewer.dir/source/viewer/Color.cc.o"
  "CMakeFiles/viewer.dir/source/viewer/Color.cc.o.d"
  "CMakeFiles/viewer.dir/source/viewer/Driver.cc.o"
  "CMakeFiles/viewer.dir/source/viewer/Driver.cc.o.d"
  "CMakeFiles/viewer.dir/source/viewer/Freezer.cc.o"
  "CMakeFiles/viewer.dir/source/viewer/Freezer.cc.o.d"
  "CMakeFiles/viewer.dir/source/viewer/GraphicsContext.cc.o"
  "CMakeFiles/viewer.dir/source/viewer/GraphicsContext.cc.o.d"
  "CMakeFiles/viewer.dir/source/viewer/Listener.cc.o"
  "CMakeFiles/viewer.dir/source/viewer/Listener.cc.o.d"
  "CMakeFiles/viewer.dir/source/viewer/Map.cc.o"
  "CMakeFiles/viewer.dir/source/viewer/Map.cc.o.d"
  "CMakeFiles/viewer.dir/source/viewer/OrganismTrace.cc.o"
  "CMakeFiles/viewer.dir/source/viewer/OrganismTrace.cc.o.d"
  "lib/libviewer.a"
  "lib/libviewer.pdb"
)

# Per-language clean rules from dependency scanning.
foreach(lang CXX)
  include(CMakeFiles/viewer.dir/cmake_clean_${lang}.cmake OPTIONAL)
endforeach()
