file(REMOVE_RECURSE
  "lib/libviewer.a"
)
