# Empty compiler generated dependencies file for viewer.
# This may be replaced when dependencies are built.
