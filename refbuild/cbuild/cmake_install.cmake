# Install script for directory: /root/reference/avida-core

# Set the install prefix
if(NOT DEFINED CMAKE_INSTALL_PREFIX)
  set(CMAKE_INSTALL_PREFIX "/root/repo/refbuild/cbuild")
endif()
string(REGEX REPLACE "/$" "" CMAKE_INSTALL_PREFIX "${CMAKE_INSTALL_PREFIX}")

# Set the install configuration name.
if(NOT DEFINED CMAKE_INSTALL_CONFIG_NAME)
  if(BUILD_TYPE)
    string(REGEX REPLACE "^[^A-Za-z0-9_]+" ""
           CMAKE_INSTALL_CONFIG_NAME "${BUILD_TYPE}")
  else()
    set(CMAKE_INSTALL_CONFIG_NAME "Release")
  endif()
  message(STATUS "Install configuration: \"${CMAKE_INSTALL_CONFIG_NAME}\"")
endif()

# Set the component getting installed.
if(NOT CMAKE_INSTALL_COMPONENT)
  if(COMPONENT)
    message(STATUS "Install component: \"${COMPONENT}\"")
    set(CMAKE_INSTALL_COMPONENT "${COMPONENT}")
  else()
    set(CMAKE_INSTALL_COMPONENT)
  endif()
endif()

# Install shared libraries without execute permission?
if(NOT DEFINED CMAKE_INSTALL_SO_NO_EXE)
  set(CMAKE_INSTALL_SO_NO_EXE "1")
endif()

# Is this installation the result of a crosscompile?
if(NOT DEFINED CMAKE_CROSSCOMPILING)
  set(CMAKE_CROSSCOMPILING "FALSE")
endif()

# Set default install directory permissions.
if(NOT DEFINED CMAKE_OBJDUMP)
  set(CMAKE_OBJDUMP "/usr/bin/objdump")
endif()

if(CMAKE_INSTALL_COMPONENT STREQUAL "Unspecified" OR NOT CMAKE_INSTALL_COMPONENT)
  file(INSTALL DESTINATION "${CMAKE_INSTALL_PREFIX}/work" TYPE FILE FILES
    "/root/reference/avida-core/support/config/analyze.cfg"
    "/root/reference/avida-core/support/config/avida.cfg"
    "/root/reference/avida-core/support/config/environment.cfg"
    "/root/reference/avida-core/support/config/events.cfg"
    "/root/reference/avida-core/support/config/instset-heads.cfg"
    "/root/reference/avida-core/support/config/instset-heads-sex.cfg"
    "/root/reference/avida-core/support/config/instset-transsmt.cfg"
    "/root/reference/avida-core/support/config/default-heads.org"
    "/root/reference/avida-core/support/config/default-heads-sex.org"
    "/root/reference/avida-core/support/config/default-transsmt-host.org"
    "/root/reference/avida-core/support/config/default-transsmt-parasite.org"
    )
endif()

if(CMAKE_INSTALL_COMPONENT STREQUAL "Unspecified" OR NOT CMAKE_INSTALL_COMPONENT)
  if(EXISTS "$ENV{DESTDIR}${CMAKE_INSTALL_PREFIX}/work/avida" AND
     NOT IS_SYMLINK "$ENV{DESTDIR}${CMAKE_INSTALL_PREFIX}/work/avida")
    file(RPATH_CHECK
         FILE "$ENV{DESTDIR}${CMAKE_INSTALL_PREFIX}/work/avida"
         RPATH "")
  endif()
  file(INSTALL DESTINATION "${CMAKE_INSTALL_PREFIX}/work" TYPE EXECUTABLE FILES "/root/repo/refbuild/cbuild/bin/avida")
  if(EXISTS "$ENV{DESTDIR}${CMAKE_INSTALL_PREFIX}/work/avida" AND
     NOT IS_SYMLINK "$ENV{DESTDIR}${CMAKE_INSTALL_PREFIX}/work/avida")
    if(CMAKE_INSTALL_DO_STRIP)
      execute_process(COMMAND "/usr/bin/strip" "$ENV{DESTDIR}${CMAKE_INSTALL_PREFIX}/work/avida")
    endif()
  endif()
endif()

if(CMAKE_INSTALL_COMPONENT)
  set(CMAKE_INSTALL_MANIFEST "install_manifest_${CMAKE_INSTALL_COMPONENT}.txt")
else()
  set(CMAKE_INSTALL_MANIFEST "install_manifest.txt")
endif()

string(REPLACE ";" "\n" CMAKE_INSTALL_MANIFEST_CONTENT
       "${CMAKE_INSTALL_MANIFEST_FILES}")
file(WRITE "/root/repo/refbuild/cbuild/${CMAKE_INSTALL_MANIFEST}"
     "${CMAKE_INSTALL_MANIFEST_CONTENT}")
