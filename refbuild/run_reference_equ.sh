#!/bin/bash
# Run the reference avida (built against the apto shim) on the stock
# logic-9 config for N seeds, recording updates-to-first-EQU from tasks.dat
# (printed every 100 updates by the stock events.cfg).  Results ->
# refbuild/ref_equ_results.txt (one "seed first_equ_update" line each).
set -u
BIN=/root/repo/refbuild/cbuild/bin/avida
CFG=/root/reference/avida-core/support/config
OUT=/root/repo/refbuild/ref_equ
SEEDS=${SEEDS:-20}
MAXU=${MAXU:-20000}
PAR=${PAR:-5}
mkdir -p "$OUT"
run_seed() {
  s=$1
  d="$OUT/seed$s"
  mkdir -p "$d" && cd "$d"
  cp "$CFG"/avida.cfg "$CFG"/environment.cfg "$CFG"/events.cfg \
     "$CFG"/instset-heads.cfg "$CFG"/default-heads.org . 2>/dev/null
  # exit at MAXU instead of 100k updates (the stock line reads "u 100000
  # Exit" -- match case-insensitively so the cap actually applies)
  sed -i "s/^u 100000 [Ee]xit/u $MAXU Exit/" events.cfg
  "$BIN" -s "$s" -set WORLD_X 60 -set WORLD_Y 60 > avida.log 2>&1
  # first tasks.dat row (update, ..., equ is column 10: not nand and orn or
  # andn nor xor equ) with nonzero EQU count
  first=$(awk '!/^#/ && NF>=10 && $10 > 0 {print $1; exit}' data/tasks.dat)
  echo "$s ${first:--1}" >> /root/repo/refbuild/ref_equ_results.txt
}
export -f run_seed
export BIN CFG OUT MAXU
: > /root/repo/refbuild/ref_equ_results.txt
seq 1001 $((1000 + SEEDS)) | xargs -P "$PAR" -I{} bash -c 'run_seed {}'
echo done
