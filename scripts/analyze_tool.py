"""Checkpoint-native run analytics CLI (avida_tpu/analyze/pipeline.py).

Usage:
    python scripts/analyze_tool.py CKPT_DIR [options]

    -c DIR            config directory of the archived run (avida.cfg /
                      environment / instruction set); built-in defaults
                      when absent.  TPU_MAX_MEMORY is defaulted from the
                      checkpoint itself so the Test CPU's genome buffer
                      matches the archived state.
    -d DIR            data dir for the outputs; defaults to the sibling
                      `data/` of CKPT_DIR when it exists (the fleet
                      fault-domain layout SPOOL/<job>/{data,ck}), else
                      the configured DATA_DIR.
    -set NAME VALUE   config override (repeatable)
    --census-only     skip the knockout sweeps (census + lineage only)
    --knockout-top N  genotypes to knockout-sweep (default 4: dominant +
                      most-abundant threshold genotypes)
    --seed N          sandbox PRNG seed (default 0)
    -v                print output paths

The standalone face of `python -m avida_tpu --analyze CKPT_DIR`: loads
the newest CRC-valid generation (falling back past corrupt ones exactly
like --resume), reconstructs the population + systematics tables, and
runs the batched phenotype census, knockout attribution and
dominant-lineage replay offline.  Results: census.dat / knockout.dat /
lineage.dat under DATA_DIR/analysis/, {"record":"analytics"} lines in
DATA_DIR/analysis/analytics.jsonl, and DATA_DIR/analytics.prom for
`--status` / Prometheus.  Exit codes: 0 ok, 66 no valid checkpoint
(matching --resume's classified exit), 2 config mismatch.
"""

from __future__ import annotations

import argparse
import os
import sys


def _repo_path():
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    if repo not in sys.path:
        sys.path.insert(0, repo)


def main(argv=None) -> int:
    _repo_path()
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("ckpt_dir")
    p.add_argument("-c", "--config-dir", default=None)
    p.add_argument("-d", "--data-dir", default=None)
    p.add_argument("-set", dest="overrides", nargs=2, action="append",
                   default=[], metavar=("NAME", "VALUE"))
    p.add_argument("--census-only", action="store_true")
    p.add_argument("--knockout-top", type=int, default=4)
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("-v", "--verbose", action="store_true")
    args = p.parse_args(argv)

    from avida_tpu.analyze.pipeline import cli_main
    return cli_main(args.ckpt_dir, config_dir=args.config_dir,
                    overrides=list(map(tuple, args.overrides)),
                    data_dir=args.data_dir, verbose=args.verbose,
                    knockout_top=args.knockout_top,
                    census_only=args.census_only, seed=args.seed)


if __name__ == "__main__":
    sys.exit(main())
