"""Persistent compile-cache inspector/verifier/janitor
(utils/compilecache.py -- the ckpt_tool.py sibling for program-cache
directories).

Usage:
    python scripts/cache_tool.py <cache_dir>            # list entries
    python scripts/cache_tool.py <cache_dir> --verify   # full CRC sweep
    python scripts/cache_tool.py <cache_dir> --prune [--keep N]
                                                        # retention + debris
    python scripts/cache_tool.py --prune --all SPOOL [--keep N]
                                                        # every cache dir
                                                        # under a tree

List mode shows, per entry: short key, program tag, chunk length, the
leading state shape (which pins world geometry and the padded serve
width W), the jax/jaxlib versions and code-digest prefix it was built
under, total bytes and age.  Everything comes from the manifest -- no
jax import, no device touch (the same ops-shell contract as ckpt_tool).

--verify re-reads every entry's exec.bin/trees.pkl against the
manifest CRC32s -- the integrity half of what the engine checks before
deserializing.  The OTHER half (toolchain/code-version staleness) needs
a live jax process to compare against and is enforced at load time with
a journaled `compile_cache` fallback; list mode surfaces the recorded
versions so an operator can spot a drifted store by eye.  Exit 0 when
every entry verifies, 1 otherwise.

--prune keeps the newest --keep N entries (default 0 = drop all) and
sweeps `.tmp-*`/`.old-*` publish debris; --prune --all walks a tree (a
fleet spool with its SPOOL/compile-cache store, or a whole cache
hierarchy) and prunes every directory that holds cache entries.  The
cache is a pure performance artifact -- pruning can never lose run
state, only re-pay a compile.
"""

from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from avida_tpu.utils import compilecache  # noqa: E402


def _fmt_bytes(n: int) -> str:
    for unit in ("B", "KB", "MB", "GB"):
        if n < 1024 or unit == "GB":
            return f"{n:.0f}{unit}" if unit == "B" else f"{n:.1f}{unit}"
        n /= 1024.0
    return f"{n}B"


def _fmt_age(sec: float) -> str:
    if sec < 120:
        return f"{sec:.0f}s"
    if sec < 7200:
        return f"{sec / 60:.0f}m"
    if sec < 172800:
        return f"{sec / 3600:.1f}h"
    return f"{sec / 86400:.1f}d"


def _entry_row(path: str) -> str:
    name = os.path.basename(path)
    try:
        with open(os.path.join(path, compilecache.MANIFEST)) as f:
            m = json.load(f)
    except (OSError, ValueError) as e:
        return f"{name[:12]}  UNREADABLE MANIFEST ({e})"
    size = sum(spec.get("size", 0) for spec in m.get("files", {}).values())
    age = _fmt_age(max(time.time() - float(m.get("created_at", 0)), 0))
    avals = m.get("avals") or []
    lead = "x".join(str(d) for d in avals[0][0]) if avals else "?"
    sig = f" sig={m['sig'][:12]}" if m.get("sig") else ""
    return (f"{name[:12]}  {m.get('tag', '?'):<16} chunk={m.get('chunk', '?'):<4}"
            f" state[{lead}]  jax={m.get('jax', '?')}/{m.get('jaxlib', '?')}"
            f" code={str(m.get('code', '?'))[:8]}"
            f" {_fmt_bytes(size):>8}  {age:>6} old{sig}")


def list_dir(root: str) -> int:
    entries = compilecache.list_entries(root)
    if not entries:
        print(f"no cache entries under {root!r}")
        return 1
    print(f"{len(entries)} entr{'y' if len(entries) == 1 else 'ies'} "
          f"under {root}:")
    for p in reversed(entries):                  # newest first
        print("  " + _entry_row(p))
    return 0


def verify_dir(root: str) -> int:
    entries = compilecache.list_entries(root)
    if not entries:
        print(f"no cache entries under {root!r}")
        return 1
    bad = 0
    for p in entries:
        try:
            compilecache.verify_entry(p)
            print(f"  OK       {os.path.basename(p)[:16]}")
        except compilecache.CompileCacheError as e:
            bad += 1
            print(f"  CORRUPT  {os.path.basename(p)[:16]}: {e}")
    print(f"{len(entries) - bad}/{len(entries)} entries verify")
    return 0 if bad == 0 else 1


def prune_dir(root: str, keep: int) -> int:
    removed = compilecache.prune(root, keep=keep)
    for p in removed:
        print(f"  removed {p}")
    kept = len(compilecache.list_entries(root))
    print(f"pruned {len(removed)} path(s), kept {kept} under {root}")
    return 0


def prune_all(tree: str, keep: int) -> int:
    """One janitor pass over every cache dir under a tree (the
    ckpt_tool.prune_all pattern: a fleet spool holds one shared
    SPOOL/compile-cache plus whatever per-job roots specs routed)."""
    found = 0
    for dirpath, dirnames, _ in os.walk(tree):
        dirnames[:] = [d for d in dirnames
                       if not d.startswith((".tmp-", ".old-"))]
        if compilecache.looks_like_cache_dir(dirpath):
            found += 1
            prune_dir(dirpath, keep)
            dirnames[:] = []            # entries are leaves; don't recurse
    if not found:
        print(f"no compile-cache dirs under {tree!r}")
    return 0


def main(argv=None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    keep = 0
    verify = prune = all_mode = False
    paths = []
    i = 0
    while i < len(argv):
        a = argv[i]
        if a == "--verify":
            verify = True
        elif a == "--prune":
            prune = True
        elif a == "--all":
            all_mode = True
        elif a == "--keep" and i + 1 < len(argv):
            keep = int(argv[i + 1])
            i += 1
        elif a in ("-h", "--help"):
            print(__doc__)
            return 0
        else:
            paths.append(a)
        i += 1
    if len(paths) != 1:
        print(__doc__)
        return 2
    root = paths[0]
    if prune and all_mode:
        return prune_all(root, keep)
    if prune:
        return prune_dir(root, keep)
    if verify:
        return verify_dir(root)
    return list_dir(root)


if __name__ == "__main__":
    sys.exit(main())
