"""Jaxpr-snapshot regression gate for the production update program.

Records a digest of the disabled-telemetry `update_step` jaxpr on the
canonical small world (6x6, L=64 -- the same setup
tests/test_telemetry.py uses) and fails when a refactor changes the
traced program unintentionally.  tests/test_telemetry.py guards the
telemetry flag specifically; THIS gate catches any other accidental
trace change (pure code motion must keep the jaxpr byte-identical --
the repo workflow for update_step refactors).

Usage:
    python scripts/check_jaxpr.py            # verify against snapshot
    python scripts/check_jaxpr.py --update   # re-record (INTENTIONAL
                                             # trace changes only: say
                                             # why in the commit message)

The check runs single-process on the forced-CPU test platform (the
digest depends on backend and jax version, both recorded in the
snapshot; a jax upgrade re-records rather than failing).  Wired into the
fast test tier via tests/test_jaxpr_snapshot.py, which calls compute()
and check() in-process.
"""

from __future__ import annotations

import hashlib
import json
import os
import sys

SNAPSHOT = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "jaxpr_digest.json")


def _force_cpu():
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    if repo not in sys.path:
        sys.path.insert(0, repo)


_COMPUTED = None


def compute() -> dict:
    """Trace the production update_step and digest the jaxpr string.
    Memoized per process: the digest of a fixed program cannot change
    within one interpreter, and two tier-1 tests consult it
    (tests/test_jaxpr_snapshot.py and the fault-off gate in
    tests/test_chaos.py) -- one trace, not two."""
    global _COMPUTED
    if _COMPUTED is not None:
        return dict(_COMPUTED)
    import jax
    import jax.numpy as jnp

    from avida_tpu.config import AvidaConfig
    from avida_tpu.config.environment import default_logic9_environment
    from avida_tpu.config.instset import default_instset
    from avida_tpu.core.state import make_world_params, zeros_population
    from avida_tpu.ops import birth as birth_ops
    from avida_tpu.ops.update import update_step

    cfg = AvidaConfig()
    cfg.WORLD_X = 6
    cfg.WORLD_Y = 6
    cfg.TPU_MAX_MEMORY = 64
    p = make_world_params(cfg, default_instset(),
                          default_logic9_environment())
    st = zeros_population(p.num_cells, p.max_memory, p.num_reactions)
    nb = jnp.asarray(birth_ops.neighbor_table(6, 6, p.geometry))
    jx = str(jax.make_jaxpr(
        lambda s, k, u: update_step(p, s, k, nb, u))(
            st, jax.random.key(0), jnp.int32(0)))
    _COMPUTED = {
        "update_step_sha256": hashlib.sha256(jx.encode()).hexdigest(),
        "jaxpr_lines": jx.count("\n") + 1,
        "jax_version": jax.__version__,
        "platform": jax.devices()[0].platform,
    }
    return dict(_COMPUTED)


def check(current: dict | None = None) -> tuple[bool, str]:
    """(ok, message).  A jax-version or platform difference re-baselines
    implicitly (the digest is only meaningful within one toolchain)."""
    if not os.path.exists(SNAPSHOT):
        return False, (f"no snapshot at {SNAPSHOT}; run "
                       f"`python scripts/check_jaxpr.py --update`")
    with open(SNAPSHOT) as f:
        want = json.load(f)
    cur = current or compute()
    if (cur["jax_version"] != want.get("jax_version")
            or cur["platform"] != want.get("platform")):
        return True, (f"toolchain changed (jax {want.get('jax_version')} "
                      f"-> {cur['jax_version']}, platform "
                      f"{want.get('platform')} -> {cur['platform']}); "
                      f"digest not comparable -- re-record with --update")
    if cur["update_step_sha256"] != want["update_step_sha256"]:
        return False, (
            "disabled-telemetry update_step traces to a DIFFERENT jaxpr "
            f"({cur['jaxpr_lines']} lines, was {want.get('jaxpr_lines')}).\n"
            "If this refactor was meant to be pure code motion, it is not "
            "-- diff str(jax.make_jaxpr(update_step ...)) before/after.\n"
            "If the trace change is intentional (new feature/perf work), "
            "re-record the snapshot DELIBERATELY:\n"
            "    python scripts/check_jaxpr.py --update\n"
            "then commit scripts/jaxpr_digest.json alongside the change "
            "and name the cause in the commit message (recent precedent: "
            "round 2 added perm_phase; round 6 refactored the birth "
            "flush placement into a shared helper).  Re-verify the "
            "TPU_FAULT-off and "
            "trace-off gates still pass (tests/test_chaos.py, "
            "tests/test_telemetry.py) -- they digest the same program.")
    return True, "update_step jaxpr unchanged"


def main() -> int:
    _force_cpu()
    cur = compute()
    if "--update" in sys.argv:
        with open(SNAPSHOT, "w") as f:
            json.dump(cur, f, indent=1)
            f.write("\n")
        print(f"recorded {cur['update_step_sha256'][:16]}... "
              f"({cur['jaxpr_lines']} jaxpr lines) -> {SNAPSHOT}")
        return 0
    ok, msg = check(cur)
    print(("OK: " if ok else "FAIL: ") + msg)
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
