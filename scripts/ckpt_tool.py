"""Checkpoint directory inspector/verifier/janitor (utils/checkpoint.py).

Usage:
    python scripts/ckpt_tool.py <ckpt_dir>            # list generations
    python scripts/ckpt_tool.py <ckpt_dir> --detail   # + census triage
                                                      # column per
                                                      # generation
    python scripts/ckpt_tool.py <ckpt_dir> --verify   # full CRC sweep
    python scripts/ckpt_tool.py <ckpt_dir> --manifest # dump newest manifest
    python scripts/ckpt_tool.py <ckpt_dir> --prune [--keep N]
                                                      # sweep strays +
                                                      # retention overflow
    python scripts/ckpt_tool.py --prune --all SPOOL [--keep N]
                                                      # one pass over every
                                                      # checkpoint dir under
                                                      # a fleet spool

List mode shows, per generation: update number, save time, array count,
total bytes and a cheap manifest-presence status.  --verify re-reads
every array and sidecar, checking each CRC32 against the manifest -- the
same validation World.resume runs, usable from an ops shell to answer
"can this run be resumed, and from which generation?" without loading
jax or touching the device.  A TORN MANIFEST (truncated mid-write by a
crash: JSON decode failure) is reported distinctly from payload CRC
corruption -- the first means the save died, the second means data
rotted at rest.  Exit status: 0 when at least one generation verifies,
1 otherwise.

--prune removes stranded publish debris (`.tmp-*`, `.bad-*` supervisor
quarantines, and `.old-*` publish asides -- the latter only once a
published generation verifies, because an aside can be the sole
resumable copy after a crash inside the publish window) and any
generation beyond the retention window (--keep N, default TPU_CKPT_KEEP
or 2).  The newest VERIFYING generation is never removed, even when
newer-but-corrupt generations fill the keep window.  Prints every path
it removes; exit 0.

--prune --all walks a whole tree (a fleet spool: SPOOL/<job>/ck per
job, service/fleet.py) and runs the same sweep on every directory that
looks like a checkpoint dir -- one janitor pass for an entire sweep's
debris instead of one invocation per job.

--detail appends a triage column sourced from the analytics pipeline's
cheap reader (analyze/pipeline.checkpoint_detail: manifest + two state
arrays + the systematics sidecar, NO Test-CPU evaluation, no jax):
dominant genotype id/units/depth, live organism count and the
tasks-held bitmask -- so spool triage ("which of these 40 jobs evolved
EQU?") doesn't require a full `--analyze` run per checkpoint.
"""

from __future__ import annotations

import json
import os
import re
import shutil
import sys
import time


def _repo_path():
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    if repo not in sys.path:
        sys.path.insert(0, repo)


def _dir_bytes(path: str) -> int:
    return sum(os.path.getsize(os.path.join(path, f))
               for f in os.listdir(path)
               if os.path.isfile(os.path.join(path, f)))


def verify_status(path: str) -> tuple:
    """(ok, status_line, manifest) for one generation.  Three distinct
    failure vocabularies, because they mean three different things on
    an ops floor: TORN MANIFEST = the save died mid-write, CORRUPT =
    payload bytes rotted at rest (CRC), DIGEST MISMATCH = every byte
    verifies but the state they encode no longer folds to the digest
    the run computed on device -- the loader/at-rest silent-corruption
    class the integrity plane exists for (utils/integrity.py; the
    digest is present when the run had TPU_STATE_DIGEST or
    TPU_SCRUB_EVERY armed)."""
    _repo_path()
    from avida_tpu.utils.checkpoint import (CheckpointError,
                                            CheckpointManifestError,
                                            verify_generation)
    try:
        manifest = verify_generation(path)
    except CheckpointManifestError as e:
        return False, f"TORN MANIFEST -- {e}", None
    except (CheckpointError, OSError) as e:
        return False, f"CORRUPT -- {e}", None
    if manifest.get("state_digest") is not None:
        from avida_tpu.utils.integrity import generation_digest
        try:
            stored, recomputed = generation_digest(path)
        except (OSError, ValueError, KeyError) as e:
            return False, f"DIGEST UNREADABLE -- {e}", None
        if stored != recomputed:
            return False, (f"DIGEST MISMATCH -- recomputed "
                           f"{recomputed:#010x} != manifest "
                           f"{stored:#010x}"), None
        return True, "OK (verified, digest ok)", manifest
    return True, "OK (verified)", manifest


def prune(base: str, keep: int) -> list:
    """Remove stranded `.tmp-*`/`.bad-*` entries, `.old-*` publish
    asides, and published generations beyond the newest `keep`.
    Returns removed paths.

    Safety: an `.old-*` aside can be the ONLY resumable copy -- a crash
    inside write_generation's two-rename publish window leaves the old
    generation moved aside and nothing published, and
    restore_candidates() resumes from exactly that aside.  Asides are
    therefore only swept once at least one PUBLISHED generation
    verifies (the same condition under which the engine's own post-save
    sweep runs)."""
    _repo_path()
    from avida_tpu.utils.checkpoint import (CheckpointError,
                                            list_generations,
                                            verify_generation)
    removed = []
    if not os.path.isdir(base):
        return removed
    newest_valid = None
    for gen in reversed(list_generations(base)):
        try:
            verify_generation(gen)
            newest_valid = gen
            break
        except (CheckpointError, OSError):
            continue
    for d in sorted(os.listdir(base)):
        if d.startswith((".tmp-", ".bad-")) \
                or (d.startswith(".old-") and newest_valid is not None):
            p = os.path.join(base, d)
            shutil.rmtree(p, ignore_errors=True)
            removed.append(p)
    gens = list_generations(base)
    for old in gens[:-max(int(keep), 1)]:
        if old == newest_valid:
            # retention must never delete the only generation a resume
            # can actually use (newer ones may all be corrupt)
            continue
        shutil.rmtree(old, ignore_errors=True)
        removed.append(old)
    return removed


_GEN_ENTRY_RE = re.compile(r"^(\.(tmp|bad|old)-)?ckpt-\d{12}")


def _is_ckpt_entry(name: str) -> bool:
    """A published generation (`ckpt-<12 digits>`) or its publish/
    quarantine debris.  Deliberately strict about the digit format: a
    fleet job DIRECTORY merely named `ckpt-something` must not make its
    parent look like a checkpoint dir (prune would rmtree whole fault
    domains as 'retention overflow')."""
    return _GEN_ENTRY_RE.match(name) is not None


def prune_all(base: str, keep: int) -> dict:
    """Walk `base` and prune every directory that looks like a
    checkpoint dir (published generations or stranded
    `.tmp-*`/`.bad-*`/`.old-*` debris in the engine's naming).  The
    one-pass janitor for a fleet spool, where every job keeps its own
    `<job>/ck`.  Returns {ckpt_dir: removed_paths}."""
    swept = {}
    for root, dirs, _files in os.walk(base):
        if any(_is_ckpt_entry(d) for d in dirs):
            swept[root] = prune(root, keep)
            dirs[:] = []        # generations hold only files: done here
    return swept


def main(argv=None) -> int:
    _repo_path()
    from avida_tpu.utils.checkpoint import MANIFEST, list_generations

    argv = list(sys.argv[1:]) if argv is None else list(argv)
    args = [a for a in argv if not a.startswith("--")]
    if not args:
        print(__doc__)
        return 1
    base = args[0]
    do_verify = "--verify" in argv
    do_manifest = "--manifest" in argv
    do_detail = "--detail" in argv

    if "--all" in argv and "--prune" not in argv:
        print("--all only applies to --prune")
        return 2
    if "--prune" in argv:
        if "--keep" in argv:
            i = argv.index("--keep")
            if i + 1 >= len(argv) or not argv[i + 1].isdigit():
                print("--keep needs an integer argument")
                return 2
            keep = int(argv[i + 1])
            args.remove(argv[i + 1])    # not a directory operand
        else:
            keep = int(os.environ.get("TPU_CKPT_KEEP", 2))
        if not args:
            print(__doc__)
            return 1
        base = args[0]
        if "--all" in argv:
            swept = prune_all(base, keep)
            total = 0
            for ckdir in sorted(swept):
                for p in swept[ckdir]:
                    print(f"pruned {p}")
                total += len(swept[ckdir])
                print(f"{ckdir}: {len(swept[ckdir])} path(s) removed, "
                      f"{len(list_generations(ckdir))} generation(s) "
                      f"kept")
            print(f"{total} path(s) removed across "
                  f"{len(swept)} checkpoint dir(s)")
            return 0
        removed = prune(base, keep)
        for p in removed:
            print(f"pruned {p}")
        print(f"{len(removed)} path(s) removed, "
              f"{len(list_generations(base))} generation(s) kept")
        return 0

    gens = list_generations(base)
    if not gens:
        print(f"no checkpoint generations under {base!r}")
        return 1

    any_ok = False
    for path in gens:
        name = os.path.basename(path)
        if do_verify:
            ok, status, manifest = verify_status(path)
        else:
            try:
                with open(os.path.join(path, MANIFEST)) as f:
                    manifest = json.load(f)
                ok, status = True, "present"
            except (OSError, json.JSONDecodeError) as e:
                ok, status, manifest = False, f"TORN MANIFEST -- {e}", None
        if not ok:
            print(f"{name}: {status}")
            continue
        any_ok = True
        saved = time.strftime("%Y-%m-%d %H:%M:%S",
                              time.localtime(manifest.get("saved_at", 0)))
        detail = ""
        if do_detail and manifest.get("state_digest") is not None:
            detail += f", digest {int(manifest['state_digest']):#010x}"
        if do_detail:
            from avida_tpu.analyze.pipeline import checkpoint_detail
            try:
                d = checkpoint_detail(path)
            except Exception as e:      # triage stays best-effort: a
                d = None                # bad sidecar must not kill list
                detail += f", detail unavailable ({e})"
            if d is not None:
                dom = ("-" if d["dominant_gid"] is None else
                       f"gid {d['dominant_gid']} x{d['dominant_units']} "
                       f"depth {d['dominant_depth']}")
                mask = d["tasks_mask"]
                detail += (f", live {d['live']}, dominant {dom}, tasks "
                           + ("-" if mask is None else
                              f"{mask:#x} ({bin(mask).count('1')})"))
        print(f"{name}: update {manifest.get('update')}, saved {saved}, "
              f"{len(manifest.get('arrays', {}))} arrays, "
              f"{_dir_bytes(path) / 1e6:.2f} MB, {status}{detail}")

    if do_manifest and any_ok:
        for path in reversed(gens):
            if do_verify:
                ok, _, manifest = verify_status(path)
                if not ok:
                    continue
            else:
                try:
                    manifest = json.load(open(os.path.join(path, MANIFEST)))
                except Exception:
                    continue
            print(json.dumps(manifest, indent=1))
            break
    return 0 if any_ok else 1


if __name__ == "__main__":
    sys.exit(main())
