"""Checkpoint directory inspector/verifier (utils/checkpoint.py format).

Usage:
    python scripts/ckpt_tool.py <ckpt_dir>            # list generations
    python scripts/ckpt_tool.py <ckpt_dir> --verify   # full CRC sweep
    python scripts/ckpt_tool.py <ckpt_dir> --manifest # dump newest manifest

List mode shows, per generation: update number, save time, array count,
total bytes and a cheap manifest-presence status.  --verify re-reads
every array and sidecar, checking each CRC32 against the manifest -- the
same validation World.resume runs, usable from an ops shell to answer
"can this run be resumed, and from which generation?" without loading
jax or touching the device.  Exit status: 0 when at least one generation
verifies, 1 otherwise.
"""

from __future__ import annotations

import json
import os
import sys
import time


def _repo_path():
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    if repo not in sys.path:
        sys.path.insert(0, repo)


def _dir_bytes(path: str) -> int:
    return sum(os.path.getsize(os.path.join(path, f))
               for f in os.listdir(path)
               if os.path.isfile(os.path.join(path, f)))


def main() -> int:
    _repo_path()
    from avida_tpu.utils.checkpoint import (CheckpointError, MANIFEST,
                                            list_generations,
                                            verify_generation)

    args = [a for a in sys.argv[1:] if not a.startswith("--")]
    if not args:
        print(__doc__)
        return 1
    base = args[0]
    do_verify = "--verify" in sys.argv
    do_manifest = "--manifest" in sys.argv

    gens = list_generations(base)
    if not gens:
        print(f"no checkpoint generations under {base!r}")
        return 1

    any_ok = False
    for path in gens:
        name = os.path.basename(path)
        mpath = os.path.join(path, MANIFEST)
        try:
            if do_verify:
                manifest = verify_generation(path)
                status = "OK (verified)"
            else:
                with open(mpath) as f:
                    manifest = json.load(f)
                status = "present"
            any_ok = True
            saved = time.strftime("%Y-%m-%d %H:%M:%S",
                                  time.localtime(manifest.get("saved_at", 0)))
            print(f"{name}: update {manifest.get('update')}, saved {saved}, "
                  f"{len(manifest.get('arrays', {}))} arrays, "
                  f"{_dir_bytes(path) / 1e6:.2f} MB, {status}")
        except (CheckpointError, OSError, json.JSONDecodeError) as e:
            print(f"{name}: CORRUPT -- {e}")

    if do_manifest and any_ok:
        for path in reversed(gens):
            try:
                manifest = verify_generation(path) if do_verify else \
                    json.load(open(os.path.join(path, MANIFEST)))
            except Exception:
                continue
            print(json.dumps(manifest, indent=1))
            break
    return 0 if any_ok else 1


if __name__ == "__main__":
    sys.exit(main())
