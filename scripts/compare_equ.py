"""Reference-vs-TPU updates-to-EQU distribution comparison.

Inputs:
  - refbuild/ref_equ_results.txt  (reference CPU build, one "seed update"
    line per seed; -1 = EQU not discovered within the update budget)
  - an EQU_r*.json from scripts/equ_harness.py (TPU build; per-seed
    first_task_update.equ, null = censored)

Both sides are right-censored at their update budget, so the primary test
is a Mann-Whitney U on the censored values with censored runs ranked
last (tied at +budget), plus a Fisher exact test on discovery counts.
SciPy is not in the image; the U statistic, its normal approximation, and
the hypergeometric tail are computed directly (they are exact enough at
n = 20 + 20).

Usage: python scripts/compare_equ.py refbuild/ref_equ_results.txt EQU_r05.json
"""

from __future__ import annotations

import json
import math
import sys


def mann_whitney(a, b):
    """Two-sided Mann-Whitney U via normal approximation with tie
    correction (exact enough for n1, n2 >= 8)."""
    n1, n2 = len(a), len(b)
    allv = sorted((v, 0) for v in a) + sorted((v, 1) for v in b)
    allv.sort(key=lambda t: t[0])
    # midranks
    ranks = {}
    i = 0
    vals = [v for v, _ in allv]
    while i < len(vals):
        j = i
        while j < len(vals) and vals[j] == vals[i]:
            j += 1
        for k in range(i, j):
            ranks[k] = (i + j + 1) / 2.0
        i = j
    r1 = sum(ranks[k] for k, (_, g) in enumerate(allv) if g == 0)
    u1 = r1 - n1 * (n1 + 1) / 2.0
    mu = n1 * n2 / 2.0
    # tie correction
    tie_term = 0.0
    i = 0
    while i < len(vals):
        j = i
        while j < len(vals) and vals[j] == vals[i]:
            j += 1
        t = j - i
        tie_term += t ** 3 - t
        i = j
    n = n1 + n2
    var = n1 * n2 / 12.0 * ((n + 1) - tie_term / (n * (n - 1)))
    if var <= 0:
        return u1, 1.0
    z = (u1 - mu) / math.sqrt(var)
    p = math.erfc(abs(z) / math.sqrt(2))
    return u1, p


def fisher_exact(a_hit, a_n, b_hit, b_n):
    """Two-sided Fisher exact on discovery counts."""
    def comb(n, k):
        return math.comb(n, k)

    total = a_n + b_n
    hits = a_hit + b_hit
    denom = comb(total, hits)

    def prob(k):
        if k < max(0, hits - b_n) or k > min(a_n, hits):
            return 0.0
        return comb(a_n, k) * comb(b_n, hits - k) / denom

    p_obs = prob(a_hit)
    return sum(p for k in range(0, min(a_n, hits) + 1)
               if (p := prob(k)) <= p_obs + 1e-12)


def main():
    ref_path, tpu_path = sys.argv[1], sys.argv[2]
    ref = {}
    ref_last = {}
    for line in open(ref_path):
        parts = line.split()
        if len(parts) >= 2:
            ref[int(parts[0])] = int(parts[1])
            # 3-column harvest format (scripts/harvest_ref_equ.py) carries
            # the last update each run reached -- in-flight runs are
            # censored EARLY and set the common comparison budget
            ref_last[int(parts[0])] = (int(parts[2]) if len(parts) >= 3
                                       else 20000)
    tpu_runs = json.load(open(tpu_path))
    if isinstance(tpu_runs, dict):
        tpu_runs = tpu_runs.get("runs", tpu_runs.get("results", []))

    # censor BOTH sides at the smallest horizon among NON-discovering
    # runs (a run that found EQU then stopped is an observed event, not a
    # censoring bound; equ_harness exits each seed at discovery)
    ref_nd = [ref_last[s] for s, v in ref.items() if v < 0] or [20000]
    tpu_nd = [r.get("updates_run", 20000) for r in tpu_runs
              if r["first_task_update"]["equ"] is None] or [20000]
    budget = min(min(ref_nd), min(tpu_nd), 20000)

    ref_vals = [v if 0 < v <= budget else budget + 1 for v in ref.values()]
    ref_hits = sum(1 for v in ref.values() if 0 < v <= budget)

    tpu_vals, tpu_hits = [], 0
    for r in tpu_runs:
        equ = r["first_task_update"]["equ"]
        if equ is None or equ > budget:
            tpu_vals.append(budget + 1)
        else:
            tpu_vals.append(equ)
            tpu_hits += 1

    u, p_u = mann_whitney(ref_vals, tpu_vals)
    p_f = fisher_exact(ref_hits, len(ref_vals), tpu_hits, len(tpu_vals))

    def med(vs):
        s = sorted(vs)
        return s[len(s) // 2]

    out = {
        "censor_budget_updates": budget,
        "reference": {"n": len(ref_vals), "equ_discovered": ref_hits,
                      "median_censored": med(ref_vals)},
        "tpu": {"n": len(tpu_vals), "equ_discovered": tpu_hits,
                "median_censored": med(tpu_vals)},
        "mann_whitney_u": round(u, 1),
        "mann_whitney_p_two_sided": round(p_u, 4),
        "fisher_exact_p_discovery": round(p_f, 4),
        "conclusion": ("distributions statistically indistinguishable at "
                       "alpha=0.05" if p_u > 0.05 and p_f > 0.05 else
                       "distributions differ at alpha=0.05"),
    }
    print(json.dumps(out, indent=2))


if __name__ == "__main__":
    main()
