"""Reference-vs-TPU updates-to-EQU distribution comparison.

Inputs:
  - refbuild/ref_equ_results.txt  (reference CPU build, one
    "seed first_equ last_update" line per seed from
    scripts/harvest_ref_equ.py; -1 = EQU not discovered; resumable over
    partial seed sweeps via that script's --merge)
  - the native side, either of:
      * an EQU_r*.json from scripts/equ_harness.py (per-seed
        first_task_update.equ, null = censored), or
      * run-analytics output (analyze/pipeline.py): a single
        analytics.jsonl, a run data dir, or a sweep/fleet-spool root --
        every analytics.jsonl found below it is one run, and the first
        {"record":"analytics"} census whose tasks_held_mask carries the
        EQU bit (bit 8 in the stock logic-9 ladder; --equ-bit overrides)
        is that run's discovery update.

        SEMANTICS CAVEAT (labeled in the output as native_semantics):
        the census mask is the SANDBOX Test-CPU capability of live
        genotypes, while the reference side (and equ_harness) records
        live in-world task performance; tasks are input-dependent, so
        the two can disagree for individual genotypes and the census
        update is NOT a guaranteed late bound -- census granularity
        (one checkpoint interval) additionally quantizes it.  Use the
        census path for coarse sweep triage; publishable comparisons
        (EQU_COMPARE_r*.json) should use equ_harness live data, which
        measures the same event as the reference.

Both sides are right-censored at their update budget, so the primary test
is a Mann-Whitney U on the censored values with censored runs ranked
last (tied at +budget), plus a Fisher exact test on discovery counts.
SciPy is not in the image; the U statistic, its normal approximation, and
the hypergeometric tail are computed directly (they are exact enough at
n = 20 + 20).

The output labels its horizon explicitly (censor_budget_updates plus the
per-side non-discovering horizons) so a partially-extended sweep is
never mistaken for a full 20k-update comparison.

Usage:
    python scripts/compare_equ.py refbuild/ref_equ_results.txt EQU_r05.json
    python scripts/compare_equ.py ref_results.txt SWEEP_DIR \
        [--equ-bit 8] [--out EQU_COMPARE_rN.json] [--note "..."]
"""

from __future__ import annotations

import json
import math
import os
import sys


def mann_whitney(a, b):
    """Two-sided Mann-Whitney U via normal approximation with tie
    correction (exact enough for n1, n2 >= 8)."""
    n1, n2 = len(a), len(b)
    allv = sorted((v, 0) for v in a) + sorted((v, 1) for v in b)
    allv.sort(key=lambda t: t[0])
    # midranks
    ranks = {}
    i = 0
    vals = [v for v, _ in allv]
    while i < len(vals):
        j = i
        while j < len(vals) and vals[j] == vals[i]:
            j += 1
        for k in range(i, j):
            ranks[k] = (i + j + 1) / 2.0
        i = j
    r1 = sum(ranks[k] for k, (_, g) in enumerate(allv) if g == 0)
    u1 = r1 - n1 * (n1 + 1) / 2.0
    mu = n1 * n2 / 2.0
    # tie correction
    tie_term = 0.0
    i = 0
    while i < len(vals):
        j = i
        while j < len(vals) and vals[j] == vals[i]:
            j += 1
        t = j - i
        tie_term += t ** 3 - t
        i = j
    n = n1 + n2
    var = n1 * n2 / 12.0 * ((n + 1) - tie_term / (n * (n - 1)))
    if var <= 0:
        return u1, 1.0
    z = (u1 - mu) / math.sqrt(var)
    p = math.erfc(abs(z) / math.sqrt(2))
    return u1, p


def fisher_exact(a_hit, a_n, b_hit, b_n):
    """Two-sided Fisher exact on discovery counts."""
    def comb(n, k):
        return math.comb(n, k)

    total = a_n + b_n
    hits = a_hit + b_hit
    denom = comb(total, hits)

    def prob(k):
        if k < max(0, hits - b_n) or k > min(a_n, hits):
            return 0.0
        return comb(a_n, k) * comb(b_n, hits - k) / denom

    p_obs = prob(a_hit)
    return sum(p for k in range(0, min(a_n, hits) + 1)
               if (p := prob(k)) <= p_obs + 1e-12)


def _analytics_journals(path: str) -> list:
    """Every analytics.jsonl at or below `path` (one per run): a single
    file, a run's data dir, a sweep root or a fleet spool all work."""
    if os.path.isfile(path):
        return [path]
    out = set()
    for root, _dirs, files in os.walk(path):
        for f in files:
            # a run killed inside append_record's rotation window can
            # leave ONLY the .1 aside; it is still that run's journal
            # (native_from_analytics reads the pair), so match both
            if f in ("analytics.jsonl", "analytics.jsonl.1"):
                out.add(os.path.join(root, "analytics.jsonl"))
    return sorted(out)


def native_from_analytics(path: str, equ_bit: int = 8) -> list:
    """Native-side runs from run-analytics output (analyze/pipeline.py),
    shaped like equ_harness results: one dict per run with
    first_task_update.equ (the update of the FIRST census holding the
    EQU bit; None = not seen) and updates_run (the last census's update,
    the run's censoring horizon).  Reads the rotation pair
    (analytics.jsonl.1 then analytics.jsonl, runlog.append_record
    semantics) without importing the engine."""
    runs = []
    for journal in _analytics_journals(path):
        first, last, n_records = None, 0, 0
        for p in (journal + ".1", journal):
            if not os.path.exists(p):
                continue        # rotation pair: either side may be absent
            for line in open(p):
                try:
                    rec = json.loads(line)
                except json.JSONDecodeError:
                    continue            # torn tail from a crash
                if rec.get("record") != "analytics":
                    continue
                n_records += 1
                u = int(rec.get("update", 0))
                last = max(last, u)
                if first is None \
                        and int(rec.get("tasks_held_mask", 0)) \
                        & (1 << equ_bit):
                    first = u
        if n_records == 0:
            # a journal with no census yet (freshly started run, torn
            # tail) is NOT an observation: including it as updates_run=0
            # would collapse the common censor budget to 0 and
            # degenerate the whole comparison
            print(f"[compare_equ] skipping {journal}: no analytics "
                  f"records yet", file=sys.stderr)
            continue
        runs.append({"source": journal,
                     "first_task_update": {"equ": first},
                     "updates_run": last})
    return runs


def main():
    argv = list(sys.argv[1:])
    out_path = None
    note = None
    equ_bit = 8
    for flag in ("--out", "--note", "--equ-bit"):
        if flag in argv:
            i = argv.index(flag)
            if i + 1 >= len(argv):
                print(f"{flag} needs an argument", file=sys.stderr)
                return 2
            val = argv[i + 1]
            del argv[i:i + 2]
            if flag == "--out":
                out_path = val
            elif flag == "--note":
                note = val
            else:
                equ_bit = int(val)
    if len(argv) < 2:
        print("usage: compare_equ.py REF_RESULTS NATIVE_SIDE "
              "[--out FILE] [--note TEXT] [--equ-bit N]",
              file=sys.stderr)
        return 2
    ref_path, tpu_path = argv[0], argv[1]
    ref = {}
    ref_last = {}
    for line in open(ref_path):
        parts = line.split()
        if len(parts) >= 2:
            ref[int(parts[0])] = int(parts[1])
            # 3-column harvest format (scripts/harvest_ref_equ.py) carries
            # the last update each run reached -- in-flight runs are
            # censored EARLY and set the common comparison budget
            ref_last[int(parts[0])] = (int(parts[2]) if len(parts) >= 3
                                       else 20000)
    if os.path.isdir(tpu_path) or tpu_path.endswith(".jsonl"):
        tpu_runs = native_from_analytics(tpu_path, equ_bit=equ_bit)
        native_semantics = ("sandbox census capability "
                            "(analytics tasks_held_mask; NOT the same "
                            "event the reference side measures)")
    else:
        tpu_runs = json.load(open(tpu_path))
        if isinstance(tpu_runs, dict):
            tpu_runs = tpu_runs.get("runs", tpu_runs.get("results", []))
        native_semantics = "live in-world first-task update (equ_harness)"
    if not tpu_runs:
        print(f"[compare_equ] no native-side runs found in {tpu_path!r} "
              f"(no analytics.jsonl with census records / empty results "
              f"file) -- nothing to compare", file=sys.stderr)
        return 2
    if not ref:
        print(f"[compare_equ] no reference results in {ref_path!r}",
              file=sys.stderr)
        return 2

    # censor BOTH sides at the smallest horizon among NON-discovering
    # runs (a run that found EQU then stopped is an observed event, not a
    # censoring bound; equ_harness exits each seed at discovery).  The
    # [20000] fallback exists only to keep min() defined when a side has
    # no non-discovering runs -- the report shows the REAL (possibly
    # empty) horizon lists, never the placeholder
    ref_nd = [ref_last[s] for s, v in ref.items() if v < 0]
    tpu_nd = [r.get("updates_run", 20000) for r in tpu_runs
              if r["first_task_update"]["equ"] is None]
    budget = min(min(ref_nd or [20000]), min(tpu_nd or [20000]), 20000)

    ref_vals = [v if 0 < v <= budget else budget + 1 for v in ref.values()]
    ref_hits = sum(1 for v in ref.values() if 0 < v <= budget)

    tpu_vals, tpu_hits = [], 0
    for r in tpu_runs:
        equ = r["first_task_update"]["equ"]
        if equ is None or equ > budget:
            tpu_vals.append(budget + 1)
        else:
            tpu_vals.append(equ)
            tpu_hits += 1

    u, p_u = mann_whitney(ref_vals, tpu_vals)
    p_f = fisher_exact(ref_hits, len(ref_vals), tpu_hits, len(tpu_vals))

    def med(vs):
        s = sorted(vs)
        return s[len(s) // 2]

    out = {
        "censor_budget_updates": budget,
        "horizon": {
            "target_updates": 20000,
            "reference_nondiscovering_horizons": sorted(ref_nd),
            "tpu_nondiscovering_horizons": sorted(tpu_nd),
            "at_full_horizon": budget >= 20000,
        },
        "reference_source": ref_path,
        "native_source": tpu_path,
        "native_semantics": native_semantics,
        "reference": {"n": len(ref_vals), "equ_discovered": ref_hits,
                      "median_censored": med(ref_vals)},
        "tpu": {"n": len(tpu_vals), "equ_discovered": tpu_hits,
                "median_censored": med(tpu_vals)},
        "mann_whitney_u": round(u, 1),
        "mann_whitney_p_two_sided": round(p_u, 4),
        "fisher_exact_p_discovery": round(p_f, 4),
        "conclusion": ("distributions statistically indistinguishable at "
                       "alpha=0.05" if p_u > 0.05 and p_f > 0.05 else
                       "distributions differ at alpha=0.05"),
    }
    if note:
        out["note"] = note
    text = json.dumps(out, indent=2)
    print(text)
    if out_path:
        with open(out_path, "w") as f:
            f.write(text + "\n")


if __name__ == "__main__":
    sys.exit(main())
