"""One caching-immune measurement child for BENCH_COMPILE=1 (bench.py).

Builds ONE engine scan program -- solo `update_scan` or the W-world
`multiworld_scan` -- through the persistent AOT program cache
(utils/compilecache.py) and prints a single JSON line with what the
construction cost and where the program came from:

    {"tag": ..., "chunk": ..., "worlds": ..., "construct_ms": ...,
     "cache_hit": true|false, "compile_ms": ..., "load_ms": ...,
     "store_ms": ..., "payload_bytes": ...}

bench.py runs this twice per tag in FRESH subprocesses against one
cache dir (the round-9 harness rule: microbenchmarks must be
caching-immune, and process death is the only reliable jit-cache
flush): the first child measures the fresh trace+compile (+ serialize/
store), the second measures the deserialize path -- their ratio is the
committed cache speedup.  TPU_COMPILE_CACHE_DIR points both at the
shared store.

Run standalone for a quick eyeball:
    TPU_COMPILE_CACHE_DIR=/tmp/cc python scripts/compile_bench_child.py \
        --tag update_scan --side 8 --mem 256 --chunk 8
"""

from __future__ import annotations

import json
import os
import sys
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> int:
    args = dict(tag="update_scan", side=8, mem=256, chunk=8, worlds=8)
    argv = sys.argv[1:]
    i = 0
    while i < len(argv):
        a = argv[i].lstrip("-")
        if a in args and i + 1 < len(argv):
            args[a] = type(args[a])(argv[i + 1])
            i += 2
        else:
            print(__doc__)
            return 2

    import jax
    import jax.numpy as jnp

    from avida_tpu.config import AvidaConfig
    from avida_tpu.config.environment import default_logic9_environment
    from avida_tpu.config.instset import default_instset
    from avida_tpu.core.state import make_world_params, zeros_population
    from avida_tpu.ops import birth as birth_ops
    from avida_tpu.utils import compilecache

    cfg = AvidaConfig()
    cfg.WORLD_X = cfg.WORLD_Y = int(args["side"])
    cfg.TPU_MAX_MEMORY = int(args["mem"])
    p = make_world_params(cfg, default_instset(),
                          default_logic9_environment())
    # the state World itself would build (init_population's kwargs):
    # systematics newborn ring included -- the measured program must be
    # the PRODUCTION update program, not a stripped-down cousin
    st = zeros_population(p.num_cells, p.max_memory, p.num_reactions,
                          p.num_global_res, p.num_spatial_res,
                          p.num_demes, smt=(p.hw_type in (1, 2)),
                          num_registers=p.num_registers, nb_cap=p.nb_cap,
                          n_deme_res=p.num_deme_res,
                          max_threads=p.max_cpu_threads,
                          trace_cap=p.trace_cap)
    nb = jnp.asarray(birth_ops.neighbor_table(cfg.WORLD_X, cfg.WORLD_Y,
                                              p.geometry))
    key = jax.random.key(1)
    chunk = int(args["chunk"])
    if args["tag"] == "update_scan":
        from avida_tpu.ops.update import update_scan
        call = (update_scan, "update_scan",
                (p, st, chunk, key, nb, jnp.int32(0)))
        worlds = 1
    elif args["tag"] == "multiworld_scan":
        from avida_tpu.parallel.multiworld import multiworld_scan
        worlds = int(args["worlds"])
        bst = jax.tree.map(
            lambda x: jnp.broadcast_to(x, (worlds,) + x.shape).copy()
            if x is not None else None, st)
        keys = jnp.stack([jax.random.key(7 + w) for w in range(worlds)])
        call = (multiworld_scan, "multiworld_scan",
                (p, bst, chunk, keys, nb, jnp.int32(0)))
    else:
        print(f"unknown --tag {args['tag']!r}")
        return 2

    jax.block_until_ready(jnp.zeros(()))        # backend init off the clock
    t0 = time.monotonic()
    out = compilecache.call(call[0], call[1], call[2])
    jax.block_until_ready(jax.tree_util.tree_leaves(out)[0])
    construct_ms = (time.monotonic() - t0) * 1000.0

    c = compilecache.counters()
    payload = 0
    root = compilecache.cache_dir()
    for path in compilecache.list_entries(root):
        m = json.load(open(os.path.join(path, compilecache.MANIFEST)))
        if m.get("tag") == call[1]:
            payload = m["files"][compilecache.EXEC_FILE]["size"]
    print(json.dumps({
        "tag": call[1],
        "chunk": chunk,
        "worlds": worlds,
        "construct_ms": round(construct_ms, 1),
        "cache_hit": c["hits"] > 0,
        "compile_ms": round(c["compile_ms"], 1),
        "load_ms": round(c["load_ms"], 1),
        "store_ms": round(c["store_ms"], 1),
        "payload_bytes": payload,
    }))
    return 0


if __name__ == "__main__":
    sys.exit(main())
