"""EQU-evolution harness: the north-star correctness measurement.

Runs the stock logic-9 world (default 60x60, the reference's
support/config/avida.cfg shape) from a single default ancestor until EQU
evolves (or a generous update cap), over multiple seeds, and records the
first-discovery update of every task on the NOT..EQU ladder
(BASELINE.json: "matching CPU updates-to-EQU").

The reference's own golden run (avida-core/tests/heads_default_100u/
expected/data/tasks.dat) shows zero tasks through update 100 -- discovery
happens on the thousands-of-updates scale; the published observable is the
*ladder*: NOT/NAND within ~1k updates, intermediate 2-input tasks next,
EQU late or never per seed (Lenski et al. 2003 report ~50% of runs evolve
EQU).  This harness asserts the ladder progresses and quantifies
updates-to-first-task distributions so scheduler deviations (budget
carry-over, ops/update.py) can be measured rather than asserted.

Usage:
  python scripts/equ_harness.py [--world 60] [--seeds 5] [--max-updates 20000]
      [--check-every 25] [--uncapped] [--out EQU.json]

The DEFAULT configuration is uncapped reference-faithful scheduling
(TPU_MAX_STEPS_PER_UPDATE = 0, the round-4 default change).  `--cap N`
opts into the capped burst-scheduling deviation to quantify its effect on
discovery timing; the legacy `--uncapped` flag is accepted and is a no-op
(it WAS the opt-in when capped scheduling was the default).  Each result
records `cap_in_effect`, the actual scheduling mode of the run.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

import jax.numpy as jnp
import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

TASK_NAMES = ["not", "nand", "and", "orn", "or", "andn", "nor", "xor", "equ"]


def run_seed(seed: int, world: int, max_updates: int, check_every: int,
             cap: int = 0, use_pallas: int | None = None,
             copy_mut: float | None = None) -> dict:
    from avida_tpu.config import AvidaConfig
    from avida_tpu.ops.update import summarize
    from avida_tpu.world import World

    cfg = AvidaConfig()
    cfg.WORLD_X = world
    cfg.WORLD_Y = world
    cfg.RANDOM_SEED = seed
    if copy_mut is not None:
        cfg.COPY_MUT_PROB = copy_mut    # CI variant: compressed timescale
    cfg.TPU_MAX_STEPS_PER_UPDATE = cap
    if use_pallas is not None:
        cfg.TPU_USE_PALLAS = use_pallas
    cfg.set("TPU_SYSTEMATICS", 0)      # host phylogeny off the hot path
    w = World(cfg=cfg)
    w.events = []                      # no .dat output: harness reads device
    w.inject()

    first_seen = {t: None for t in TASK_NAMES}
    t0 = time.perf_counter()
    insts = 0
    while w.update < max_updates:
        w._pending_exec.append(w.run_updates(check_every))
        insts = w._flush_exec()
        counts = np.asarray(summarize(w.params, w.state,
                                      jnp.int32(w.update - 1))["task_counts"])
        for i, t in enumerate(TASK_NAMES):
            if first_seen[t] is None and counts[i] > 0:
                first_seen[t] = w.update      # known to +- check_every
        if first_seen["equ"] is not None:
            break
    dt = time.perf_counter() - t0
    n_alive = w.num_organisms
    return {
        "seed": seed,
        "world": world,
        "updates_run": w.update,
        "first_task_update": first_seen,
        "tasks_discovered": sum(v is not None for v in first_seen.values()),
        "final_organisms": n_alive,
        "wall_s": round(dt, 1),
        "inst_per_sec": round(insts / dt, 1),
        # provenance: the ACTUAL scheduling mode (0 = uncapped
        # reference-faithful bursts, the default)
        "cap_in_effect": cap,
        "uncapped": cap == 0,
    }


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--world", type=int, default=60)
    ap.add_argument("--seeds", type=int, default=5)
    ap.add_argument("--seed-base", type=int, default=1000)
    ap.add_argument("--max-updates", type=int, default=20000)
    ap.add_argument("--check-every", type=int, default=25)
    ap.add_argument("--uncapped", action="store_true",
                    help="legacy no-op: uncapped is the default")
    ap.add_argument("--cap", type=int, default=0,
                    help="TPU_MAX_STEPS_PER_UPDATE opt-in (0 = uncapped)")
    ap.add_argument("--use-pallas", type=int, default=None)
    ap.add_argument("--out", default=None)
    args = ap.parse_args()

    results = []
    for s in range(args.seeds):
        r = run_seed(args.seed_base + s, args.world, args.max_updates,
                     args.check_every, args.cap, args.use_pallas)
        print(json.dumps(r))
        results.append(r)

    summary = {
        "config": vars(args),
        "runs": results,
        "equ_evolved": sum(r["first_task_update"]["equ"] is not None
                           for r in results),
        "median_tasks_discovered": float(np.median(
            [r["tasks_discovered"] for r in results])),
    }
    print(json.dumps({"summary": {k: summary[k] for k in
                                  ("equ_evolved", "median_tasks_discovered")}}))
    if args.out:
        with open(args.out, "w") as f:
            json.dump(summary, f, indent=1)


if __name__ == "__main__":
    main()
