"""Fleet spool CLI: submit / list / cancel / requeue jobs (service/fleet.py).

Usage:
    python scripts/fleet_tool.py submit SPOOL NAME [--batch]
            [--tenant T] [--shard N] [--backpressure MAX]
            [--fault-plan S/S...] [--env K=V]... -- CHILD_ARGV...
    python scripts/fleet_tool.py list SPOOL
    python scripts/fleet_tool.py cancel SPOOL NAME
    python scripts/fleet_tool.py requeue SPOOL NAME
    python scripts/fleet_tool.py gen-trace OUT --seed N [--jobs N]
            [--classes N] [--cancel FRAC] [--span SEC] [--updates U]
            [--tenants N]

`submit` writes `SPOOL/NAME.json` atomically (tmp + rename), so a live
orchestrator can never pick up a half-written spec.  Everything after
`--` is the child run's command line exactly as `--supervise` takes it,
MINUS `-d`/`-set TPU_CKPT_DIR` (the fleet assigns the job's fault
domain itself).  `cancel`/`requeue` drop marker files the orchestrator
consumes on its next poll -- they work while it runs; a `requeue` of a
failed job left over from a dead orchestrator is honored by the next
one's startup scan.

`list` needs no orchestrator at all: it reconstructs job states from
the fleet journal plus the spool contents, so it answers "what happened
to my sweep?" after everything has exited.

`--batch` marks the spec for device-lane packing: the orchestrator
coalesces queued --batch specs of one batchability class -- the
CANONICAL resolved-static-config signature (service/serve.py), so
specs may differ in dirs, seed spelling or override order -- into ONE
supervised MultiWorld child (`--worlds`), or, under `--fleet ...
--dynamic`, routes them into a warm ghost-padded serve child
(`--serve-worlds`).  Each world keeps its own job dir, .dat output and
solo-compatible checkpoints; on a static mismatch (or no peer, or a
fault plan) the spec falls back to process-per-job with the reason
journaled.  The argv must name its seed explicitly (`-s N`).

Streaming-admission flags: `--tenant T` labels the spec for the
per-tenant quota (TPU_FLEET_TENANT_MAX); `--shard N` spreads specs
over `shard-<k>/` subdirs the orchestrator scans round-robin (one per
poll tick -- thousands of queued specs never stall a tick); and
`--backpressure MAX` refuses the submit (exit 3) while MAX specs
already sit queued on disk -- the producer-side half of
TPU_FLEET_QUEUE_MAX.

`gen-trace` writes a deterministic arrival/cancel churn trace
(utils/churntrace.py grammar, seeded like TPU_FAULT specs) -- the
input of the serve acceptance bench (bench.py BENCH_SERVE=1) and the
chaos suite's SIGKILL-mid-churn drill.
"""

from __future__ import annotations

import json
import os
import sys


def _repo_path():
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    if repo not in sys.path:
        sys.path.insert(0, repo)


class QueueFullError(RuntimeError):
    """--backpressure MAX refused the submit (the queue is full)."""


def _queued_count(spool: str) -> int:
    """Specs waiting on disk: spool root + every shard-* subdir."""
    n = 0
    try:
        entries = os.listdir(spool)
    except OSError:
        return 0
    for fn in entries:
        p = os.path.join(spool, fn)
        if fn.startswith("shard-") and os.path.isdir(p):
            n += sum(1 for s in os.listdir(p)
                     if s.endswith(".json") and not s.startswith(".")
                     and not s.endswith(".cancelled.json"))
        elif fn.endswith(".json") and not fn.startswith(".") \
                and not fn.endswith(".cancelled.json"):
            n += 1
    return n


def _spec_exists(spool: str, name: str) -> bool:
    """A queued spec anywhere in the spool: the root OR any shard-*
    subdir.  The duplicate check must span all of them -- the same name
    submitted with different --shard values hashes to different dirs,
    and the orchestrator would ingest one and silently strand the
    other (inflating --backpressure counts forever)."""
    if os.path.exists(os.path.join(spool, name + ".json")):
        return True
    try:
        entries = os.listdir(spool)
    except OSError:
        return False
    return any(fn.startswith("shard-")
               and os.path.isfile(os.path.join(spool, fn,
                                               name + ".json"))
               for fn in entries)


def submit(spool: str, name: str, argv: list, fault_plan=(),
           env=None, batch: bool = False, tenant: str = "",
           shard: int | None = None,
           backpressure: int = 0) -> str:
    """Write one job spec atomically; returns its path.  Validates with
    the orchestrator's own schema check so a typo is caught here, not
    quarantined later.  `shard=N` hashes the job into `shard-<k>/`
    (k = hash(name) % N); `backpressure=MAX` raises QueueFullError
    while MAX specs already wait on disk."""
    _repo_path()
    import zlib

    from avida_tpu.service.fleet import (legal_name,
                                         spec_seed_and_batch_key,
                                         validate_spec)
    if not legal_name(name):
        raise ValueError(f"illegal job name {name!r}")
    spec = {"argv": list(argv)}
    if fault_plan:
        spec["fault_plan"] = list(fault_plan)
    if env:
        spec["env"] = dict(env)
    if tenant:
        spec["tenant"] = str(tenant)
    if batch:
        spec["batch"] = True
        if fault_plan:
            raise ValueError("--batch and --fault-plan are exclusive "
                             "(fault injection is per-process)")
        if spec_seed_and_batch_key(spec)[0] is None:
            raise ValueError("--batch needs an explicit seed in the "
                             "child argv (-s N) to key the world")
    validate_spec(spec)
    if backpressure and _queued_count(spool) >= int(backpressure):
        raise QueueFullError(
            f"{spool!r} already holds >= {backpressure} queued specs "
            f"(backpressure); resubmit once the fleet drains")
    dest = spool
    if shard:
        k = zlib.crc32(name.encode()) % int(shard)
        dest = os.path.join(spool, f"shard-{k:02d}")
    os.makedirs(dest, exist_ok=True)
    path = os.path.join(dest, name + ".json")
    if _spec_exists(spool, name) \
            or os.path.isdir(os.path.join(spool, name)):
        raise ValueError(f"job {name!r} already exists in {spool!r}")
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w") as f:
        json.dump(spec, f, indent=1)
        f.write("\n")
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)
    return path


def list_jobs(spool: str) -> list:
    """(name, state) pairs from the journal + spool scan (the same
    merge the --status fleet view renders)."""
    _repo_path()
    from avida_tpu.service.fleet import spool_job_states
    return sorted(spool_job_states(spool).items())


def _marker(spool: str, name: str, kind: str) -> str:
    path = os.path.join(spool, f"{name}.{kind}")
    with open(path, "w"):
        pass
    return path


def main(argv=None) -> int:
    argv = list(sys.argv[1:]) if argv is None else list(argv)
    if len(argv) < 2:
        print(__doc__)
        return 2
    cmd, spool = argv[0], argv[1]
    rest = argv[2:]
    if cmd == "submit":
        if not rest or "--" not in rest or rest[0].startswith("-"):
            print("submit needs: SPOOL NAME [flags] -- CHILD_ARGV...")
            return 2
        name = rest[0]
        sep = rest.index("--")
        flags, child = rest[1:sep], rest[sep + 1:]
        fault_plan, env, batch = (), {}, False
        tenant, shard, backpressure = "", None, 0
        i = 0
        while i < len(flags):
            if flags[i] == "--fault-plan" and i + 1 < len(flags):
                fault_plan = tuple(flags[i + 1].split("/"))
                i += 2
            elif flags[i] == "--env" and i + 1 < len(flags) \
                    and "=" in flags[i + 1]:
                k, _, v = flags[i + 1].partition("=")
                env[k] = v
                i += 2
            elif flags[i] == "--batch":
                batch = True
                i += 1
            elif flags[i] == "--tenant" and i + 1 < len(flags):
                tenant = flags[i + 1]
                i += 2
            elif flags[i] == "--shard" and i + 1 < len(flags) \
                    and flags[i + 1].isdigit():
                shard = int(flags[i + 1])
                i += 2
            elif flags[i] == "--backpressure" and i + 1 < len(flags) \
                    and flags[i + 1].isdigit():
                backpressure = int(flags[i + 1])
                i += 2
            else:
                print(f"unknown submit flag {flags[i]!r}")
                return 2
        try:
            path = submit(spool, name, child, fault_plan=fault_plan,
                          env=env, batch=batch, tenant=tenant,
                          shard=shard, backpressure=backpressure)
        except QueueFullError as e:
            print(f"submit held: {e}")
            return 3
        except ValueError as e:
            print(f"submit rejected: {e}")
            return 2
        print(f"submitted {path}")
        return 0
    if cmd == "gen-trace":
        # `spool` is the OUT path for this subcommand
        _repo_path()
        from avida_tpu.utils import churntrace
        opts = {"seed": None, "jobs": 12, "classes": 1, "cancel": 0.2,
                "span": 30.0, "updates": 40, "tenants": 1}
        i = 0
        while i < len(rest):
            key = rest[i].lstrip("-")
            if rest[i].startswith("--") and key in opts \
                    and i + 1 < len(rest):
                opts[key] = float(rest[i + 1]) if key == "cancel" \
                    else (float(rest[i + 1]) if key == "span"
                          else int(rest[i + 1]))
                i += 2
            else:
                print(f"unknown gen-trace flag {rest[i]!r}")
                return 2
        if opts["seed"] is None:
            print("gen-trace needs --seed N (determinism is the point)")
            return 2
        events = churntrace.generate(
            opts["seed"], jobs=opts["jobs"], classes=opts["classes"],
            cancel_frac=opts["cancel"], span=opts["span"],
            updates=opts["updates"], tenants=opts["tenants"])
        text = churntrace.format_trace(
            events, seed=opts["seed"],
            note=(f"jobs={opts['jobs']} classes={opts['classes']} "
                  f"cancel={opts['cancel']} span={opts['span']} "
                  f"updates={opts['updates']} tenants={opts['tenants']}"))
        with open(spool, "w") as f:
            f.write(text)
        print(f"wrote {len(events)} events to {spool}")
        return 0
    if cmd == "list":
        jobs = list_jobs(spool)
        if not jobs:
            print(f"no jobs in {spool!r}")
            return 0
        for name, state in jobs:
            print(f"{name:<24} {state}")
        return 0
    if cmd in ("cancel", "requeue"):
        if not rest:
            print(f"{cmd} needs: SPOOL NAME")
            return 2
        name = rest[0]
        known = dict(list_jobs(spool))
        if name not in known:
            print(f"no such job {name!r} in {spool!r}")
            return 2
        path = _marker(spool, name, cmd)
        print(f"{cmd} marker written: {path} (consumed by the "
              f"orchestrator's next poll)")
        return 0
    print(__doc__)
    return 2


if __name__ == "__main__":
    sys.exit(main())
