"""Fleet spool CLI: submit / list / cancel / requeue jobs (service/fleet.py).

Usage:
    python scripts/fleet_tool.py submit SPOOL NAME [--batch]
            [--fault-plan S/S...] [--env K=V]... -- CHILD_ARGV...
    python scripts/fleet_tool.py list SPOOL
    python scripts/fleet_tool.py cancel SPOOL NAME
    python scripts/fleet_tool.py requeue SPOOL NAME

`submit` writes `SPOOL/NAME.json` atomically (tmp + rename), so a live
orchestrator can never pick up a half-written spec.  Everything after
`--` is the child run's command line exactly as `--supervise` takes it,
MINUS `-d`/`-set TPU_CKPT_DIR` (the fleet assigns the job's fault
domain itself).  `cancel`/`requeue` drop marker files the orchestrator
consumes on its next poll -- they work while it runs; a `requeue` of a
failed job left over from a dead orchestrator is honored by the next
one's startup scan.

`list` needs no orchestrator at all: it reconstructs job states from
the fleet journal plus the spool contents, so it answers "what happened
to my sweep?" after everything has exited.

`--batch` marks the spec for device-lane packing: the orchestrator
coalesces queued --batch specs whose argv (minus the seed) and env are
identical into ONE supervised MultiWorld child (`--worlds`,
avida_tpu/parallel/multiworld.py), so a W-seed sweep costs one process,
one compile and one device program instead of W.  Each world keeps its
own job dir, .dat output and solo-compatible checkpoints; on a static
mismatch (or no peer, or a fault plan) the spec falls back to
process-per-job with the reason journaled.  The argv must name its seed
explicitly (`-s N`).
"""

from __future__ import annotations

import json
import os
import sys


def _repo_path():
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    if repo not in sys.path:
        sys.path.insert(0, repo)


def submit(spool: str, name: str, argv: list, fault_plan=(),
           env=None, batch: bool = False) -> str:
    """Write one job spec atomically; returns its path.  Validates with
    the orchestrator's own schema check so a typo is caught here, not
    quarantined later."""
    _repo_path()
    from avida_tpu.service.fleet import (legal_name,
                                         spec_seed_and_batch_key,
                                         validate_spec)
    if not legal_name(name):
        raise ValueError(f"illegal job name {name!r}")
    spec = {"argv": list(argv)}
    if fault_plan:
        spec["fault_plan"] = list(fault_plan)
    if env:
        spec["env"] = dict(env)
    if batch:
        spec["batch"] = True
        if fault_plan:
            raise ValueError("--batch and --fault-plan are exclusive "
                             "(fault injection is per-process)")
        if spec_seed_and_batch_key(spec)[0] is None:
            raise ValueError("--batch needs an explicit seed in the "
                             "child argv (-s N) to key the world")
    validate_spec(spec)
    os.makedirs(spool, exist_ok=True)
    path = os.path.join(spool, name + ".json")
    if os.path.exists(path) or os.path.isdir(os.path.join(spool, name)):
        raise ValueError(f"job {name!r} already exists in {spool!r}")
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w") as f:
        json.dump(spec, f, indent=1)
        f.write("\n")
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)
    return path


def list_jobs(spool: str) -> list:
    """(name, state) pairs from the journal + spool scan (the same
    merge the --status fleet view renders)."""
    _repo_path()
    from avida_tpu.service.fleet import spool_job_states
    return sorted(spool_job_states(spool).items())


def _marker(spool: str, name: str, kind: str) -> str:
    path = os.path.join(spool, f"{name}.{kind}")
    with open(path, "w"):
        pass
    return path


def main(argv=None) -> int:
    argv = list(sys.argv[1:]) if argv is None else list(argv)
    if len(argv) < 2:
        print(__doc__)
        return 2
    cmd, spool = argv[0], argv[1]
    rest = argv[2:]
    if cmd == "submit":
        if not rest or "--" not in rest or rest[0].startswith("-"):
            print("submit needs: SPOOL NAME [flags] -- CHILD_ARGV...")
            return 2
        name = rest[0]
        sep = rest.index("--")
        flags, child = rest[1:sep], rest[sep + 1:]
        fault_plan, env, batch = (), {}, False
        i = 0
        while i < len(flags):
            if flags[i] == "--fault-plan" and i + 1 < len(flags):
                fault_plan = tuple(flags[i + 1].split("/"))
                i += 2
            elif flags[i] == "--env" and i + 1 < len(flags) \
                    and "=" in flags[i + 1]:
                k, _, v = flags[i + 1].partition("=")
                env[k] = v
                i += 2
            elif flags[i] == "--batch":
                batch = True
                i += 1
            else:
                print(f"unknown submit flag {flags[i]!r}")
                return 2
        try:
            path = submit(spool, name, child, fault_plan=fault_plan,
                          env=env, batch=batch)
        except ValueError as e:
            print(f"submit rejected: {e}")
            return 2
        print(f"submitted {path}")
        return 0
    if cmd == "list":
        jobs = list_jobs(spool)
        if not jobs:
            print(f"no jobs in {spool!r}")
            return 0
        for name, state in jobs:
            print(f"{name:<24} {state}")
        return 0
    if cmd in ("cancel", "requeue"):
        if not rest:
            print(f"{cmd} needs: SPOOL NAME")
            return 2
        name = rest[0]
        known = dict(list_jobs(spool))
        if name not in known:
            print(f"no such job {name!r} in {spool!r}")
            return 2
        path = _marker(spool, name, cmd)
        print(f"{cmd} marker written: {path} (consumed by the "
              f"orchestrator's next poll)")
        return 0
    print(__doc__)
    return 2


if __name__ == "__main__":
    sys.exit(main())
