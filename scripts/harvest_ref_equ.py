"""Harvest updates-to-first-EQU from reference run directories, including
RUNS STILL IN FLIGHT: reads each refbuild/ref_equ/seed*/data/tasks.dat
(stock events print every 100 updates; EQU is column 10) and emits one
"seed first_equ_update last_update" line per seed, -1 = not yet.

Censoring note for scripts/compare_equ.py: a seed whose last_update is
below the comparison budget and first_equ is -1 is censored EARLY -- the
comparison should either wait or censor BOTH sides at min(last_update).

Usage: python scripts/harvest_ref_equ.py [ref_equ_dir] > results.txt
"""

from __future__ import annotations

import os
import sys


def main():
    base = sys.argv[1] if len(sys.argv) > 1 else "refbuild/ref_equ"
    for name in sorted(os.listdir(base)):
        if not name.startswith("seed"):
            continue
        path = os.path.join(base, name, "data", "tasks.dat")
        if not os.path.exists(path):
            continue
        seed = name[4:]
        first = -1
        last = 0
        for line in open(path):
            if line.startswith("#") or not line.strip():
                continue
            parts = line.split()
            if len(parts) < 10:
                continue
            last = int(parts[0])
            if first < 0 and int(parts[9]) > 0:
                first = last
        print(f"{seed} {first} {last}")


if __name__ == "__main__":
    main()
