"""Harvest updates-to-first-EQU from reference run directories, including
RUNS STILL IN FLIGHT: reads each refbuild/ref_equ/seed*/data/tasks.dat
(stock events print every 100 updates; EQU is column 10) and emits one
"seed first_equ_update last_update" line per seed, -1 = not yet.

Censoring note for scripts/compare_equ.py: a seed whose last_update is
below the comparison budget and first_equ is -1 is censored EARLY -- the
comparison should either wait or censor BOTH sides at min(last_update).

RESUMABLE over partial seed sweeps: `--merge PREV.txt` folds a previous
harvest into this one, so a sweep can be extended seed-batch by
seed-batch (or its run dirs archived away) without losing earlier
results.  Per seed, the side whose run reached the LATER update wins --
re-harvesting an extended run supersedes the old line (its tasks.dat
still contains the discovery, so first_equ survives a re-scan), and a
seed whose run dir is gone keeps its previous line.  A seed that flips
from discovered back to -1 can only mean its run dir was REPLACED by a
different run; the merge takes the longer-horizon side but warns on
stderr so the operator notices the substitution.

Usage:
    python scripts/harvest_ref_equ.py [ref_equ_dir] > results.txt
    python scripts/harvest_ref_equ.py [ref_equ_dir] --merge results.txt \
        > results_new.txt
"""

from __future__ import annotations

import os
import sys


def harvest_dir(base: str) -> dict:
    """{seed: (first_equ_update, last_update)} from a sweep directory."""
    out = {}
    if not os.path.isdir(base):
        return out
    for name in sorted(os.listdir(base)):
        if not name.startswith("seed"):
            continue
        path = os.path.join(base, name, "data", "tasks.dat")
        if not os.path.exists(path):
            continue
        seed = name[4:]
        first = -1
        last = 0
        for line in open(path):
            if line.startswith("#") or not line.strip():
                continue
            parts = line.split()
            if len(parts) < 10:
                continue
            last = int(parts[0])
            if first < 0 and int(parts[9]) > 0:
                first = last
        out[seed] = (first, last)
    return out


def read_results(path: str) -> dict:
    """Parse a previous harvest (2- or 3-column lines).  A legacy
    2-column file carries no horizon; default it to the 20000-update
    budget those sweeps ran at -- the same default compare_equ.py
    applies -- so merging one can never collapse the downstream censor
    budget to 0."""
    out = {}
    for line in open(path):
        parts = line.split()
        if len(parts) >= 2:
            out[parts[0]] = (int(parts[1]),
                             int(parts[2]) if len(parts) >= 3 else 20000)
    return out


def merge(cur: dict, prev: dict) -> dict:
    """Per seed, the longer-horizon side wins; a discovered first_equ is
    never replaced by -1 at the same horizon (partial re-harvest of a
    truncated tasks.dat).  A longer-horizon re-harvest that LOSES a
    previous discovery means the seed dir now holds a different run --
    taken, but loudly."""
    out = dict(prev)
    for seed, (first, last) in cur.items():
        pf, pl = out.get(seed, (-1, -1))
        if last > pl or (last == pl and first >= 0):
            if first < 0 <= pf:
                print(f"[harvest_ref_equ] warning: seed {seed} was "
                      f"discovered at {pf} (horizon {pl}) but the "
                      f"current dir reaches {last} with no discovery -- "
                      f"run dir replaced? taking the current side",
                      file=sys.stderr)
            out[seed] = (first, last)
    return out


def main():
    argv = list(sys.argv[1:])
    prev = {}
    if "--merge" in argv:
        i = argv.index("--merge")
        if i + 1 >= len(argv):
            print("--merge needs a previous results file", file=sys.stderr)
            return 2
        prev = read_results(argv[i + 1])
        del argv[i:i + 2]
    base = argv[0] if argv else "refbuild/ref_equ"
    results = merge(harvest_dir(base), prev)
    for seed in sorted(results, key=lambda s: (len(s), s)):
        first, last = results[seed]
        print(f"{seed} {first} {last}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
