"""Telemetry-history ops tool: query/watch/prune the .hist.jsonl rings.

Usage:
    python scripts/metrics_tool.py query DIR FAMILY [--window SEC]
            [--ring NAME] [--labels SUBSTR] [--csv OUT.csv]
    python scripts/metrics_tool.py watch DIR [--interval SEC] [--once]
            [--rules alerts.json]
    python scripts/metrics_tool.py rules [DIR]
    python scripts/metrics_tool.py prune DIR [--keep-bytes N]

DIR is a run data dir or a fleet spool -- every `*.hist.jsonl` ring
under it (not recursive) is discovered (observability/history.py
appends one beside each .prom snapshot when TPU_METRICS_HIST=1).

  query   windowed digest of one family across the discovered rings
          (count/min/max/p50/p95, first->last, per-second rate);
          --csv exports the raw (time, update, value) rows.
  watch   the spectator's alert view: evaluate the declarative rule
          set (observability/alerts.py -- built-in defaults merged
          with DIR/alerts.json, or --rules) over the rings and print
          the firing table; loops every --interval (default 5s) until
          interrupted, or evaluates once with --once.  Exit status
          with --once: 0 = nothing firing, 3 = at least one rule
          firing (cron-able).  Runs armed with TPU_PROFILE=1 also
          publish the avida_perf_* attribution families
          (observability/profiler.py: chunk walls, fenced probe
          phases, per-program XLA cost, state footprint) -- query
          digests them like any family, and watch appends a perf row
          per ring that carries them.
  rules   print the effective rule set (after overrides) as JSON.
  prune   drop `.1` asides and trim live rings to a --keep-bytes tail
          (default 256 KiB), atomically.

Host-only: imports nothing that imports jax, so it runs anywhere the
data dir is mounted.
"""

from __future__ import annotations

import argparse
import csv
import glob
import json
import os
import sys
import time


def _repo_path():
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    if repo not in sys.path:
        sys.path.insert(0, repo)


_repo_path()

from avida_tpu.observability import alerts, history  # noqa: E402


def find_rings(dirpath: str) -> list:
    """Every live history ring directly under `dirpath` (a data dir or
    a spool root), sorted; `metrics` first so the run heartbeat wins
    ties."""
    rings = sorted(glob.glob(os.path.join(dirpath, "*" +
                                          history.HIST_SUFFIX)))
    rings.sort(key=lambda p: (0 if os.path.basename(p).startswith(
        "metrics.") else 1, p))
    return rings


def ring_name(path: str) -> str:
    return os.path.basename(path)[:-len(history.HIST_SUFFIX)]


def load_rings(rings: list, window_sec=None, now=None) -> dict:
    """{ring basename: sample rows}.  Rings are kept SEPARATE -- one
    family can mean different things in different rings (batch-max vs
    per-tenant avida_update on a serve child), so neither the alert
    evaluator nor query may blend them (alerts.samples_for)."""
    return {ring_name(p): history.read_samples(p, window_sec=window_sec,
                                               now=now)
            for p in rings}


def cmd_query(args) -> int:
    rings = find_rings(args.dir)
    if args.ring:
        rings = [p for p in rings
                 if os.path.basename(p) == args.ring + history.HIST_SUFFIX]
    if not rings:
        print(f"no history rings under {args.dir!r} "
              f"(TPU_METRICS_HIST=0, or nothing published yet)")
        return 1
    # one ring per query: the FIRST ring (metrics-first order) where
    # the family has samples in the window wins, and is named in the
    # output so a serve child's per-tenant flavor is an explicit
    # --ring multiworld away
    by_ring = load_rings(rings, window_sec=args.window)
    samples, used, digest = [], None, None
    for p in rings:
        digest = history.summarize(by_ring[ring_name(p)], args.family,
                                   window_sec=args.window,
                                   labels=args.labels)
        if digest.get("count"):
            samples, used = by_ring[ring_name(p)], ring_name(p)
            break
    if used is None:
        print(f"family {args.family!r} has no samples in the window")
        return 1
    print(f"{'ring':<14} {used}")
    for k in ("family", "count", "min", "p50", "p95", "max", "first",
              "last", "span_sec", "rate_per_sec"):
        print(f"{k:<14} {digest.get(k)}")
    if args.csv:
        pts = history.series(
            [r for r in samples
             if args.window is None
             or r.get("time", 0.0) >= time.time() - args.window],
            args.family, labels=args.labels)
        upd = {r.get("time", 0.0): r.get("update")
               for r in samples if "update" in r}
        with open(args.csv, "w", newline="") as f:
            wr = csv.writer(f)
            wr.writerow(["time", "update", args.family])
            for t, v in pts:
                wr.writerow([t, upd.get(t, ""), v])
        print(f"wrote {len(pts)} rows to {args.csv}")
    return 0


def _load_rules(args):
    return alerts.load_rules(args.dir,
                             rules_path=getattr(args, "rules", None))


def cmd_watch(args) -> int:
    rules = _load_rules(args)
    plane = alerts.AlertPlane(rules)     # no journal: spectators only
    while True:
        now = time.time()
        rings = find_rings(args.dir)
        by_ring = load_rings(rings, now=now)
        plane.observe(by_ring, now)
        n = sum(len(v) for v in by_ring.values())
        lines = [time.strftime("%H:%M:%S", time.localtime(now))
                 + f"  {len(rings)} ring(s), {n} sample(s)"]
        for name in sorted(plane.rules):
            state = "FIRING " if name in plane.firing else "ok     "
            val = plane.last_values.get(name)
            shown = "-" if val is None else (f"{val:.4g}")
            lines.append(f"  {state} {name:<28} value {shown:<12} "
                         f"fired {plane.fired_total[name]}x")
        # attribution-plane rider (TPU_PROFILE=1 runs): the latest
        # sample's perf families, one row per ring that carries them
        for rname in sorted(by_ring):
            rows = by_ring[rname]
            if not rows or "avida_perf_chunks_total" not in rows[-1]:
                continue
            s = rows[-1]
            lines.append(
                f"  perf    {rname:<28} chunk "
                f"{s.get('avida_perf_chunk_wall_ms', 0.0):.1f}ms wall / "
                f"{s.get('avida_perf_chunk_fenced_ms', 0.0):.1f}ms "
                f"fenced, {int(s.get('avida_perf_probes_total', 0))} "
                f"probes, state "
                f"{s.get('avida_perf_state_bytes', 0.0) / 2**20:.1f}MiB")
        print("\n".join(lines))
        if args.once:
            return 3 if plane.firing else 0
        sys.stdout.flush()
        time.sleep(args.interval)


def cmd_rules(args) -> int:
    rules = _load_rules(args)
    print(json.dumps([r.to_dict() for r in rules], indent=2))
    return 0


def cmd_prune(args) -> int:
    rings = find_rings(args.dir)
    # include orphaned .1 asides whose live file is gone
    asides = glob.glob(os.path.join(args.dir,
                                    "*" + history.HIST_SUFFIX + ".1"))
    rings += [p[:-2] for p in asides if p[:-2] not in rings]
    if not rings:
        print(f"no history rings under {args.dir!r}")
        return 0
    total = 0
    for p in sorted(set(rings)):
        res = history.prune(p, keep_bytes=args.keep_bytes)
        total += res["removed_bytes"]
        print(f"{p}: removed {res['removed_bytes']} bytes, "
              f"kept {res['kept_bytes']}")
    print(f"total removed: {total} bytes")
    return 0


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    sub = p.add_subparsers(dest="mode", required=True)

    q = sub.add_parser("query", help="windowed digest of one family")
    q.add_argument("dir")
    q.add_argument("family")
    q.add_argument("--window", type=float, default=None,
                   help="seconds of history to digest (default: all)")
    q.add_argument("--ring", default=None,
                   help="restrict to one ring (metrics/multiworld/"
                        "fleet/supervisor)")
    q.add_argument("--labels", default=None,
                   help="label substring filter for labeled families")
    q.add_argument("--csv", default=None, help="export raw rows here")

    w = sub.add_parser("watch", help="evaluate alert rules, print table")
    w.add_argument("dir")
    w.add_argument("--interval", type=float, default=5.0)
    w.add_argument("--once", action="store_true")
    w.add_argument("--rules", default=None,
                   help="alerts.json path (default: DIR/alerts.json "
                        "merged over built-ins)")

    r = sub.add_parser("rules", help="print the effective rule set")
    r.add_argument("dir", nargs="?", default=None)
    r.add_argument("--rules", default=None)

    pr = sub.add_parser("prune", help="trim rings, drop .1 asides")
    pr.add_argument("dir")
    pr.add_argument("--keep-bytes", type=int, default=256 << 10)

    args = p.parse_args(argv)
    try:
        return {"query": cmd_query, "watch": cmd_watch,
                "rules": cmd_rules, "prune": cmd_prune}[args.mode](args)
    except ValueError as e:
        print(f"[metrics_tool] {e}", file=sys.stderr)
        return 2
    except BrokenPipeError:
        return 0                      # `... | head` closed the pipe


if __name__ == "__main__":
    sys.exit(main())
