"""Performance attribution ops tool: report / diff / campaign.

Usage:
    python scripts/perf_tool.py report DIR
    python scripts/perf_tool.py diff A.json B.json [--gate]
            [--tol 0.10] [--force]
    python scripts/perf_tool.py campaign [--out FILE]
            [--arms headline,worlds,compile,obs,prof,packed] [--side N]

  report    one-page attribution summary of a run data dir: the
            avida_perf_* families from metrics.prom (programs with
            their XLA cost/HBM analysis, chunk walls, last probed
            phases, state footprint) plus the perf.jsonl probe
            timeline (observability/profiler.py; arm the run with
            TPU_PROFILE=1).
  diff      compare two bench.py artifacts field by field.  Refuses
            apples-to-oranges pairs LOUDLY (exit 3) when the strict
            provenance fields -- platform, device_kind, device_count,
            x64, code digest -- disagree (--force compares anyway).
            Direction is keyed by field spelling: `value`,
            *_inst_per_sec and speedup* are higher-better; *_ms,
            *_sec and *_pct are lower-better; everything else is
            informational.  With --gate, any regression beyond --tol
            (default 10%) exits 4 -- the CI hook (run_suite --gate).
  campaign  one-command bench driver: runs `python bench.py` once per
            arm (headline / worlds / compile / obs / prof -- the
            BENCH_* env arms) in a fresh subprocess and merges the
            lines into ONE self-describing artifact suitable for
            `diff`.  --side S forwards BENCH_SIDE=S to every arm
            (small CPU artifacts for gate drills).

report and diff are host-only (observability/profiler.py is
importable without jax); campaign spawns bench.py children, which
need the full stack.

Exit status: 0 ok; 2 usage/unreadable input; 3 provenance mismatch;
4 regression found with --gate.
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import time


def _repo_path():
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    if repo not in sys.path:
        sys.path.insert(0, repo)
    return repo


REPO = _repo_path()

from avida_tpu.observability import profiler  # noqa: E402

# ---------------------------------------------------------------------------
# report
# ---------------------------------------------------------------------------


def _read_prom(path: str) -> dict:
    """{family or family{labels}: float} from one .prom snapshot --
    the history.parse_exposition grammar, inlined so `report` needs
    nothing beyond this module and profiler."""
    out = {}
    try:
        with open(path) as f:
            for line in f:
                line = line.strip()
                if not line or line.startswith("#"):
                    continue
                name, _, val = line.rpartition(" ")
                try:
                    out[name] = float(val)
                except ValueError:
                    continue
    except OSError:
        pass
    return out


def cmd_report(args) -> int:
    prom = {}
    for fname in ("metrics.prom", "multiworld.prom"):
        prom = _read_prom(os.path.join(args.dir, fname))
        if any(k.startswith("avida_perf") for k in prom):
            break
    recs = profiler.read_perf_records(args.dir)
    if not any(k.startswith("avida_perf") for k in prom) and not recs:
        print(f"no attribution data under {args.dir!r} "
              f"(run with TPU_PROFILE=1; see README "
              f"'Performance attribution')")
        return 1

    def g(name, default=0.0):
        return prom.get(name, default)

    print(f"perf report  {args.dir}")
    print(f"  chunks {int(g('avida_perf_chunks_total'))} covering "
          f"{int(g('avida_perf_updates_total'))} updates, "
          f"{int(g('avida_perf_probes_total'))} fenced probes")
    print(f"  chunk wall {g('avida_perf_chunk_wall_ms'):.1f}ms unfenced "
          f"/ {g('avida_perf_chunk_fenced_ms'):.1f}ms fenced; probe "
          f"{g('avida_perf_probe_ms'):.1f}ms")
    phases = {k.split('phase="', 1)[1].rstrip('"}'): v
              for k, v in prom.items()
              if k.startswith('avida_perf_phase_ms{')}
    if phases:
        total = sum(phases.values()) or 1.0
        print("  phases (last probe):")
        for n, v in sorted(phases.items(), key=lambda kv: -kv[1]):
            print(f"    {n:<14} {v:9.2f}ms  {v / total * 100:5.1f}%")
    if "avida_perf_cycle_loop_share" in prom:
        print(f"  cycle loop share "
              f"{g('avida_perf_cycle_loop_share'):.1%}")
    if "avida_perf_state_bytes" in prom:
        tb = g("avida_perf_state_bytes")
        lb = g("avida_perf_state_live_bytes")
        line = (f"  state {tb / 2**20:.2f}MiB padded, "
                f"{lb / 2**20:.2f}MiB live "
                f"({(lb / tb * 100) if tb else 0:.0f}%)")
        if "avida_perf_world_state_bytes" in prom:
            line += (f"; {g('avida_perf_world_state_bytes') / 2**20:.2f}"
                     f"MiB/world")
        if "avida_perf_ghost_state_bytes" in prom:
            line += (f", {g('avida_perf_ghost_state_bytes') / 2**20:.2f}"
                     f"MiB ghost")
        print(line)
        leaves = sorted(((k.split('leaf="', 1)[1].rstrip('"}'), v)
                         for k, v in prom.items()
                         if k.startswith('avida_perf_state_leaf_bytes{')),
                        key=lambda kv: -kv[1])
        if leaves:
            print("  largest leaves: " + ", ".join(
                f"{n} {v / 1024:.0f}KiB" for n, v in leaves[:6]))
    progs = {k.split('program="', 1)[1].rstrip('"}'): v
             for k, v in prom.items()
             if k.startswith('avida_perf_program_flops{')}
    if progs:
        acc = {k.split('program="', 1)[1].rstrip('"}'): v
               for k, v in prom.items()
               if k.startswith('avida_perf_program_bytes_accessed{')}
        hbm = {k.split('program="', 1)[1].rstrip('"}'): v
               for k, v in prom.items()
               if k.startswith('avida_perf_program_hbm_bytes{')}
        print(f"  programs ({int(g('avida_perf_programs_total'))} "
              f"with XLA cost analysis):")
        for n, fl in sorted(progs.items(), key=lambda kv: -kv[1]):
            print(f"    {n:<32} {fl / 1e6:9.2f} Mflop  "
                  f"{acc.get(n, 0) / 2**20:8.2f}MiB accessed  "
                  f"{hbm.get(n, 0) / 2**20:8.2f}MiB hbm")
    if recs:
        print(f"  probe timeline ({len(recs)} perf.jsonl records):")
        for r in recs[-8:]:
            tag = "final" if r.get("final") else "probe"
            ph = r.get("phases") or {}
            top = max(ph, key=ph.get) if ph else "-"
            print(f"    u={r.get('update', 0):<8} {tag:<6} "
                  f"wall {r.get('chunk_wall_ms', 0):8.1f}ms  "
                  f"state {r.get('state_bytes', 0) / 2**20:6.2f}MiB  "
                  f"top phase {top}")
    return 0


# ---------------------------------------------------------------------------
# diff (the regression gate)
# ---------------------------------------------------------------------------


def _flatten(obj, prefix="") -> dict:
    """Dotted numeric scalars of a bench line; provenance and lists
    stay out of the comparison."""
    out = {}
    for k, v in obj.items():
        if k == "provenance":
            continue
        key = f"{prefix}{k}"
        if isinstance(v, dict):
            out.update(_flatten(v, key + "."))
        elif isinstance(v, bool):
            continue
        elif isinstance(v, (int, float)):
            out[key] = float(v)
    return out


def _direction(key: str) -> int:
    """+1 higher-better, -1 lower-better, 0 informational.  Keyed by
    the bench field spellings (throughputs and speedups up; walls,
    latencies and overhead shares down)."""
    leaf = key.rsplit(".", 1)[-1]
    if leaf == "value" or leaf.endswith("_inst_per_sec") \
            or "speedup" in leaf or leaf.endswith("_efficiency"):
        return 1
    if leaf.endswith(("_ms", "_sec", "_pct")):
        return -1
    return 0


def diff_lines(a: dict, b: dict, tol: float) -> tuple:
    """(rows, regressions): every shared numeric field compared, the
    direction-aware failures beyond `tol` collected."""
    fa, fb = _flatten(a), _flatten(b)
    rows, regressions = [], []
    for key in sorted(set(fa) & set(fb)):
        va, vb = fa[key], fb[key]
        delta = (vb - va) / abs(va) if va else (0.0 if vb == va else
                                                float("inf"))
        d = _direction(key)
        verdict = "info"
        if d:
            worse = delta < -tol if d > 0 else delta > tol
            better = delta > tol if d > 0 else delta < -tol
            verdict = ("REGRESSION" if worse
                       else "improved" if better else "ok")
        if verdict == "REGRESSION":
            regressions.append((key, va, vb, delta))
        rows.append((key, va, vb, delta, verdict))
    return rows, regressions


def cmd_diff(args) -> int:
    try:
        a = profiler.load_bench_json(args.a)
        b = profiler.load_bench_json(args.b)
    except (OSError, ValueError) as e:
        print(f"[perf_tool] unreadable artifact: {e}", file=sys.stderr)
        return 2
    # campaign artifacts diff arm-by-arm; plain lines diff directly
    arms_a = a.get("arms") if a.get("artifact") else None
    arms_b = b.get("arms") if b.get("artifact") else None
    prov_a = a.get("provenance") or next(
        (v.get("provenance") for v in (arms_a or {}).values()
         if v.get("provenance")), None)
    prov_b = b.get("provenance") or next(
        (v.get("provenance") for v in (arms_b or {}).values()
         if v.get("provenance")), None)
    mismatches = profiler.provenance_mismatches(prov_a or {}, prov_b or {})
    if mismatches:
        print("[perf_tool] REFUSING apples-to-oranges diff -- strict "
              "provenance fields disagree:", file=sys.stderr)
        for f, va, vb in mismatches:
            print(f"  {f}: {va!r} vs {vb!r}", file=sys.stderr)
        if not args.force:
            print("  (--force compares anyway)", file=sys.stderr)
            return 3
    if arms_a is not None or arms_b is not None:
        pairs = [(f"{name}.", (arms_a or {}).get(name),
                  (arms_b or {}).get(name))
                 for name in sorted(set(arms_a or {}) | set(arms_b or {}))]
    else:
        pairs = [("", a, b)]
    rows, regressions = [], []
    for prefix, la, lb in pairs:
        if not (la and lb):
            print(f"  arm {prefix.rstrip('.')}: only in one artifact, "
                  f"skipped")
            continue
        r, bad = diff_lines(la, lb, args.tol)
        rows += [(prefix + k, va, vb, d, v) for k, va, vb, d, v in r]
        regressions += [(prefix + k, va, vb, d) for k, va, vb, d in bad]
    width = max((len(k) for k, *_ in rows), default=10)
    print(f"{'field':<{width}}  {'A':>14}  {'B':>14}  {'delta':>8}  "
          f"verdict")
    for key, va, vb, delta, verdict in rows:
        if verdict == "info" and not args.verbose:
            continue
        print(f"{key:<{width}}  {va:>14.4g}  {vb:>14.4g}  "
              f"{delta * 100:>+7.1f}%  {verdict}")
    if regressions:
        print(f"{len(regressions)} regression(s) beyond "
              f"{args.tol:.0%} tolerance")
        return 4 if args.gate else 0
    print("no regressions" + ("" if args.gate else
                              " (advisory; --gate makes this binding)"))
    return 0


# ---------------------------------------------------------------------------
# campaign (the one-command BENCH artifact driver)
# ---------------------------------------------------------------------------

CAMPAIGN_SCHEMA = "avida-bench-campaign-v1"
# arm name -> the BENCH_* env that arms it in a bench.py child.
# headline keeps the default phase breakdown; every other arm skips it
# (the headline arm already carries those rows).
ARMS = {
    "headline": {},
    "worlds": {"BENCH_WORLDS": "2", "BENCH_PHASES": "0"},
    "compile": {"BENCH_COMPILE": "1", "BENCH_PHASES": "0"},
    "obs": {"BENCH_OBS": "1", "BENCH_PHASES": "0"},
    "prof": {"BENCH_PROF": "1", "BENCH_PHASES": "0"},
    "packed": {"BENCH_PACKED_PHASES": "1", "BENCH_PHASES": "0"},
}


def cmd_campaign(args) -> int:
    arms = [a.strip() for a in args.arms.split(",") if a.strip()]
    unknown = [a for a in arms if a not in ARMS]
    if unknown:
        print(f"[perf_tool] unknown arm(s) {unknown}; "
              f"choose from {sorted(ARMS)}", file=sys.stderr)
        return 2
    out = {"artifact": CAMPAIGN_SCHEMA,
           "generated_at": round(time.time(), 3), "arms": {}}
    for arm in arms:
        env = dict(os.environ)
        env.update(ARMS[arm])
        if args.side:
            env["BENCH_SIDE"] = str(args.side)
        t0 = time.time()
        proc = subprocess.run([sys.executable, "bench.py"], cwd=REPO,
                              env=env, capture_output=True, text=True,
                              timeout=args.timeout)
        if proc.returncode != 0:
            print(f"[perf_tool] arm {arm!r} failed "
                  f"(exit {proc.returncode}):\n{proc.stderr[-800:]}",
                  file=sys.stderr)
            return 2
        line = json.loads(proc.stdout.strip().splitlines()[-1])
        line["arm_wall_sec"] = round(time.time() - t0, 1)
        out["arms"][arm] = line
        print(f"  arm {arm:<10} done in {line['arm_wall_sec']}s "
              f"({line.get('value', 0):.3g} inst/s)", flush=True)
    # one provenance block for the artifact (the arms agree on the
    # strict fields by construction -- same process tree, same code)
    for line in out["arms"].values():
        if line.get("provenance"):
            out["provenance"] = line["provenance"]
            break
    text = json.dumps(out, indent=2)
    if args.out:
        tmp = args.out + ".tmp"
        with open(tmp, "w") as f:
            f.write(text + "\n")
        os.replace(tmp, args.out)
        print(f"wrote {args.out}")
    else:
        print(text)
    return 0


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    sub = p.add_subparsers(dest="mode", required=True)

    r = sub.add_parser("report", help="one-page attribution summary")
    r.add_argument("dir")

    d = sub.add_parser("diff", help="compare two bench artifacts")
    d.add_argument("a")
    d.add_argument("b")
    d.add_argument("--gate", action="store_true",
                   help="exit 4 on any regression beyond --tol")
    d.add_argument("--tol", type=float, default=0.10,
                   help="relative tolerance (default 0.10)")
    d.add_argument("--force", action="store_true",
                   help="compare despite a provenance mismatch")
    d.add_argument("--verbose", action="store_true",
                   help="also print direction-less info fields")

    c = sub.add_parser("campaign", help="run bench arms, merge artifact")
    c.add_argument("--out", default=None)
    c.add_argument("--arms", default="headline,worlds,compile,obs,prof,packed")
    c.add_argument("--side", type=int, default=None,
                   help="forward BENCH_SIDE to every arm")
    c.add_argument("--timeout", type=float, default=3600.0)

    args = p.parse_args(argv)
    try:
        return {"report": cmd_report, "diff": cmd_diff,
                "campaign": cmd_campaign}[args.mode](args)
    except ValueError as e:
        print(f"[perf_tool] {e}", file=sys.stderr)
        return 2
    except BrokenPipeError:
        return 0


if __name__ == "__main__":
    sys.exit(main())
