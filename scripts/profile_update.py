"""Breakdown of one update's wall time on the current backend.

Times each stage of ops/update.update_step separately at bench scale:
scheduler draw, pack, kernel launch, unpack, birth flush, and the fused
whole update.  Run on TPU: `python scripts/profile_update.py [world]`.

MEASUREMENT CAVEATS (learned the hard way; see BASELINE.md):
 - repeated dispatches with IDENTICAL inputs can be elided/cached by the
   runtime and report absurdly low times -- vary an input per call when
   timing an op in isolation;
 - per-call block_until_ready over a remote-device tunnel measures
   network round-trips (100-300 ms, noisy), not device time -- this
   script pipelines N dispatches and syncs once, which is the only
   reliable method here;
 - treat end-to-end `python bench.py` deltas as ground truth (run-to-run
   noise ~ +/-2M inst/s at 102k organisms).
"""

from __future__ import annotations

import sys
import time

import jax
import jax.numpy as jnp

sys.path.insert(0, ".")
from bench import build  # noqa: E402


def timeit(fn, *args, reps=10, warmup=2):
    for _ in range(warmup):
        out = fn(*args)
    jax.block_until_ready(out)
    t0 = time.perf_counter()
    for _ in range(reps):
        out = fn(*args)
    jax.block_until_ready(out)
    return (time.perf_counter() - t0) / reps


def main():
    from avida_tpu.ops import pallas_cycles, scheduler as sched_ops
    from avida_tpu.ops import birth as birth_ops
    from avida_tpu.ops.update import update_step

    world = int(sys.argv[1]) if len(sys.argv) > 1 else 320
    params, st, neighbors, key = build(world, world, 256, seed=100)
    n = params.num_cells
    cap = params.max_steps_per_update or "uncapped"
    print(f"world {world}x{world} = {n} cells, L={params.max_memory}, "
          f"cap={cap}, platform={jax.devices()[0].platform}")

    # advance a few updates so state is "typical"
    for u in range(3):
        key, k = jax.random.split(key)
        st, _ = update_step(params, st, k, neighbors, jnp.int32(u))
    jax.block_until_ready(st)

    k_fixed = jax.random.key(42)
    icap = params.max_steps_per_update or 2**31 - 1

    sched = jax.jit(lambda s, k: sched_ops.compute_budgets(params, s, k))
    budgets = sched(st, k_fixed)
    t_sched = timeit(sched, st, k_fixed)
    granted = jnp.minimum(budgets, icap)

    pack = jax.jit(lambda s, g: pallas_cycles.pack_state(params, s, g))
    packed = pack(st, granted)
    t_pack = timeit(pack, st, granted)

    runp = jax.jit(lambda p, k: pallas_cycles.run_packed(params, p, k, icap))
    t_kernel = timeit(runp, packed, k_fixed)

    unpack = jax.jit(lambda s, p: pallas_cycles.unpack_state(params, s, p))
    t_unpack = timeit(unpack, st, packed)

    flush = jax.jit(lambda s, k: birth_ops.flush_births(
        params, s, k, neighbors, jnp.int32(3), use_off_tape=True))
    t_flush = timeit(flush, st, k_fixed)

    t_full = timeit(
        lambda s, k: update_step(params, s, k, neighbors, jnp.int32(3)),
        st, k_fixed)

    gsum = float(granted.sum())
    print(f"scheduler: {t_sched*1e3:8.2f} ms")
    print(f"pack:      {t_pack*1e3:8.2f} ms")
    print(f"kernel:    {t_kernel*1e3:8.2f} ms   "
          f"({gsum/t_kernel/1e6:.1f} M inst/s kernel-only)")
    print(f"unpack:    {t_unpack*1e3:8.2f} ms")
    print(f"flush:     {t_flush*1e3:8.2f} ms")
    print(f"sum:       {(t_sched+t_pack+t_kernel+t_unpack+t_flush)*1e3:8.2f} ms")
    print(f"full step: {t_full*1e3:8.2f} ms   "
          f"({gsum/t_full/1e6:.1f} M inst/s end-to-end)")


if __name__ == "__main__":
    main()
