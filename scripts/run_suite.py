"""Per-file test-suite sweep: the 1-core-host way to run the full suite.

A single >100-test pytest process intermittently segfaults in XLA's CPU
`backend_compile_and_load` after ~60+ accumulated jit programs (the
crash is in the compiler, not the tests; every crashing file passes in
isolation -- ROUND5_NOTES.md).  The workaround that produced
SUITE_r05.txt, formalized: run each `tests/test_*.py` in its OWN pytest
process, sequentially (never concurrently -- this host has one core and
concurrent jax work inflates every file past its timeout), and write
the per-file results in the SUITE_rN.txt format.

Usage:
    python scripts/run_suite.py --out SUITE_tier1.txt      # tier-1 (default
                                                           # marker 'not slow')
    python scripts/run_suite.py --all-tests --out SUITE_r07.txt  # FULL suite
    python scripts/run_suite.py --files test_fleet.py test_supervisor.py
    python scripts/run_suite.py --timeout 1200             # per file
    python scripts/run_suite.py --only multiworld --slow   # slow tier of the
                                                           # matching files only
    python scripts/run_suite.py --only 'test_pa*'          # fnmatch patterns ok
    python scripts/run_suite.py --timings --out SUITE_r10.txt  # append each
                                                           # file's WALL clock
                                                           # (subprocess spawn +
                                                           # collection + jit
                                                           # compiles included)
                                                           # so the 870s/1-core
                                                           # budget can be
                                                           # allocated from data
    python scripts/run_suite.py --gate BENCH_r15.json      # after the sweep,
                                                           # run bench.py fresh
                                                           # and perf_tool-diff
                                                           # it against the
                                                           # committed artifact;
                                                           # a >10% regression
                                                           # fails the run

--only PATTERN keeps test files whose name contains PATTERN (or matches
it as an fnmatch glob); --slow selects the slow-marked tests instead of
tier-1 -- together they are how the multi-hour slow legs are swept one
file at a time on the 1-core host without editing this script.

--gate BASELINE.json appends the perf regression gate (README
"Performance attribution"): one fresh `python bench.py` subprocess, its
JSON line diffed against the committed baseline artifact via
`scripts/perf_tool.py diff --gate` (provenance-checked: an artifact
from different hardware/code refuses loudly instead of firing falsely).
The gate's verdict folds into the exit status alongside the test sweep.

Exit status: 0 when every file passed (and the gate, if requested,
found no regression), 1 otherwise.  The output file is written
incrementally (a killed sweep keeps the files already run).
"""

from __future__ import annotations

import os
import re
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_SUMMARY_RE = re.compile(
    r"(\d+ (?:passed|failed|error|skipped|xfailed|deselected)"
    r"(?:, \d+ \w+)*) in ([\d.]+)s")


def _env():
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    # the persistent compilation cache corrupts resumed runs on this
    # toolchain (tests/test_chaos.py::_env) -- never inherit it here
    env.pop("JAX_COMPILATION_CACHE_DIR", None)
    return env


def run_file(fname: str, marker: str | None, timeout: float) -> tuple:
    """Run one test file in its own pytest process.  Returns
    (ok, summary_line, wall_seconds) -- wall is the full subprocess
    lifetime (interpreter boot, collection, jit compiles), which is
    what the 870s tier-1 budget actually spends; pytest's own "in Ns"
    understates it by the boot + collection share."""
    cmd = [sys.executable, "-m", "pytest", os.path.join("tests", fname),
           "-q", "--continue-on-collection-errors", "-p",
           "no:cacheprovider", "-p", "no:xdist", "-p", "no:randomly"]
    if marker:
        cmd += ["-m", marker]
    t0 = time.time()
    try:
        proc = subprocess.run(cmd, cwd=REPO, env=_env(),
                              capture_output=True, text=True,
                              timeout=timeout)
        out = proc.stdout + proc.stderr
        rc = proc.returncode
    except subprocess.TimeoutExpired as e:
        out = ((e.stdout or b"").decode("utf-8", "replace")
               if isinstance(e.stdout, bytes) else (e.stdout or ""))
        rc = 124
    dt = time.time() - t0
    m = None
    for m in _SUMMARY_RE.finditer(out):
        pass                            # keep the LAST summary line
    if m:
        summary = f"{m.group(1)} in {m.group(2)}s"
        # rc 5 = nothing collected/ran (every test deselected by the
        # marker) -- the summary reads "N deselected"; not a failure
        ok = rc in (0, 5)
    elif rc == 124:
        summary = f"TIMEOUT after {dt:.0f}s"
        ok = False
    elif rc == 5:
        summary = "no tests collected (deselected)"
        ok = True
    else:
        # a segfault mid-file leaves no summary: report the exit code
        summary = f"NO SUMMARY (exit {rc}, {dt:.0f}s)"
        ok = False
    return ok, summary, dt


def run_gate(baseline: str, timeout: float = 3600.0) -> int:
    """The perf regression gate: one fresh bench.py child, diffed
    against the committed baseline via perf_tool.  Returns an exit
    status (0 = no regression; perf_tool's 3/4 pass through)."""
    import tempfile

    print(f"perf gate: running bench.py against {baseline} ...",
          flush=True)
    try:
        proc = subprocess.run([sys.executable, "bench.py"], cwd=REPO,
                              env=_env(), capture_output=True,
                              text=True, timeout=timeout)
    except subprocess.TimeoutExpired:
        print(f"perf gate: bench.py timed out after {timeout:.0f}s")
        return 1
    if proc.returncode != 0:
        print(f"perf gate: bench.py failed (exit {proc.returncode}):\n"
              f"{proc.stderr[-800:]}")
        return 1
    fd, tmp = tempfile.mkstemp(suffix=".json", prefix="bench-gate-")
    try:
        with os.fdopen(fd, "w") as f:
            f.write(proc.stdout.strip().splitlines()[-1] + "\n")
        rc = subprocess.call(
            [sys.executable, os.path.join("scripts", "perf_tool.py"),
             "diff", baseline, tmp, "--gate"], cwd=REPO, env=_env())
    finally:
        os.unlink(tmp)
    print(f"perf gate: {'OK' if rc == 0 else f'FAILED (exit {rc})'}")
    return rc


def main(argv=None) -> int:
    argv = list(sys.argv[1:]) if argv is None else list(argv)
    out_path = None
    marker = "not slow"
    timeout = 1200.0
    files = None
    only = None
    timings = False
    gate = None
    i = 0
    while i < len(argv):
        a = argv[i]
        if a == "--out" and i + 1 < len(argv):
            out_path = argv[i + 1]
            i += 2
        elif a == "--gate" and i + 1 < len(argv):
            gate = argv[i + 1]
            i += 2
        elif a == "--timings":
            timings = True
            i += 1
        elif a == "-m" and i + 1 < len(argv):
            marker = argv[i + 1] or None
            i += 2
        elif a == "--all-tests":
            marker = None
            i += 1
        elif a == "--slow":
            marker = "slow"
            i += 1
        elif a == "--only" and i + 1 < len(argv):
            only = argv[i + 1]
            i += 2
        elif a == "--timeout" and i + 1 < len(argv):
            timeout = float(argv[i + 1])
            i += 2
        elif a == "--files":
            files = argv[i + 1:]
            break
        else:
            print(__doc__)
            return 2
        continue

    if files is None:
        files = sorted(f for f in os.listdir(os.path.join(REPO, "tests"))
                       if f.startswith("test_") and f.endswith(".py"))
    if only:
        import fnmatch
        files = [f for f in files
                 if only in f or fnmatch.fnmatch(f, only)
                 or fnmatch.fnmatch(f, f"test_{only}.py")]
        if not files:
            print(f"--only {only!r} matches no test file")
            return 2
    header = (f"# Full test-suite sweep (per-file pytest processes; "
              f"marker={marker!r}, timeout={timeout:.0f}s)\n"
              f"# Split rationale: one big pytest process intermittently "
              f"segfaults in XLA's CPU\n"
              f"# compiler after ~60+ accumulated jit programs "
              f"(ROUND5_NOTES.md); per-file\n"
              f"# processes sidestep it.  Run SOLO on the 1-core host.\n")
    outf = open(out_path, "w") if out_path else None
    if outf:
        outf.write(header)
        outf.flush()
    passed = failed = 0
    wall_total = 0.0
    for fname in files:
        ok, summary, dt = run_file(fname, marker, timeout)
        wall_total += dt
        line = f"{fname}: {summary}"
        if timings:
            line += f"  [wall {dt:.1f}s]"
        print(line, flush=True)
        if outf:
            outf.write(line + "\n")
            outf.flush()
        npass = re.search(r"(\d+) passed", summary)
        passed += int(npass.group(1)) if npass else 0
        failed += 0 if ok else 1
    total = (f"TOTAL: {passed} passed, "
             f"{failed} file(s) with failures/timeouts")
    if timings:
        total += f", {wall_total:.0f}s wall"
    print(total)
    gate_rc = 0
    if gate is not None:
        gate_rc = run_gate(gate)
        line = f"PERF GATE vs {gate}: " \
               + ("ok" if gate_rc == 0 else f"FAILED (exit {gate_rc})")
        print(line)
        if outf:
            outf.write(line + "\n")
    if outf:
        outf.write(total + "\n")
        outf.close()
    return 0 if failed == 0 and gate_rc == 0 else 1


if __name__ == "__main__":
    sys.exit(main())
