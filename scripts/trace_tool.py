"""Runlog <-> Chrome/Perfetto trace converter (flight recorder + Timeline).

Usage:
    python scripts/trace_tool.py to-chrome telemetry.jsonl [-o trace.json]
    python scripts/trace_tool.py from-chrome trace.json    [-o trace.jsonl]
    python scripts/trace_tool.py summary   telemetry.jsonl
    python scripts/trace_tool.py fleet     SPOOL           [-o trace.json]

`fleet` merges a whole spool's journals -- fleet.jsonl, every job's
supervisor.jsonl, the alert journals (alerts.jsonl at both layers,
observability/alerts.py) and each job's metrics history ring
(observability/history.py) -- into ONE wall-clock-correlated Perfetto
trace: one process track per job (plus one for the orchestrator),
spans for admit->terminal and for every supervisor boot, spans for
firing->resolved alerts, a per-job `avida_update` counter track with
chunk-boundary spans from the history ring, and instant events for
injected faults, watchdog kills, rollbacks, SDC exits and breaker
trips -- so a churn drill or an incident reads as a single correlated
timeline instead of five journals diffed by hand.  Jobs armed with
TPU_PROFILE=1 additionally get a `perf` row: each chunk interval is
split proportionally into the avida_perf_phase_ms{phase=...} staged
phases the history ring sampled (observability/profiler.py), so the
attribution plane reads on the same wall-clock timeline.

`to-chrome` renders a run's telemetry.jsonl -- the per-update phase
wall-time records ({"record": "update"}, PR 1's Timeline) and the
flight-recorder event records ({"record": "trace"}, observability/
tracer.py) -- as a Chrome trace-event JSON that chrome://tracing and
ui.perfetto.dev open directly:

  - each update becomes a frame on a synthetic timeline whose clock is
    the SUM of recorded update wall times (host gaps between updates are
    not update work and are excluded, matching the runlog's own wall_ms
    semantics); phase brackets (schedule / pack / kernel / birth_flush /
    events_io ...) are complete events ("ph": "X") on per-phase rows;
  - flight-recorder events (births, deaths, first task triggers,
    scheduler stalls, anomalies) are instant events ("ph": "i") at their
    update's frame start, one Chrome thread row per event code, with
    cell/payload in args.

Runs without phase records (TPU_TRACE=1 but telemetry off) still
convert: updates with only trace events get a nominal frame length so
the event timeline stays ordered and zoomable.

`from-chrome` inverts the instant events back into {"record": "trace"}
JSONL (grouped per update, sorted) -- the same shape the FlightRecorder
drain appends, including the ring-overflow "dropped" counter (carried
through the trace as "trace_dropped" markers) -- so a trace.json edited
or filtered in the Perfetto UI can be re-ingested by runlog tooling.
Phase rows do not round-trip (the runlog's per-update phase dict is the
source of truth for those).

`summary` prints per-code event totals and the per-update event rate,
the quick "what happened in this run" view.  It also understands the
analytics pipeline's `{"record": "analytics"}` lines
(analyze/pipeline.py -- point it at DATA_DIR/analysis/analytics.jsonl):
census cadence, genotypes evaluated, knockout sweeps and the last
census digest ride the same summary.
"""

from __future__ import annotations

import argparse
import json
import os
import sys


def _repo_path():
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    if repo not in sys.path:
        sys.path.insert(0, repo)


_repo_path()

from avida_tpu.observability.tracer import EVENT_CODES  # noqa: E402

_CODE_BY_NAME = {name: code for code, name in EVENT_CODES.items()}

# Chrome trace tid layout: update frames on tid 1, one row per event
# code from 10, one row per phase name from 100
_PHASE_TID = 1
_EVENT_TID_BASE = 10
_PHASE_ROW_BASE = 100

# nominal frame for updates that carry events but no phase record
# (telemetry off): 1 ms keeps the timeline ordered and zoomable
_NOMINAL_MS = 1.0


def read_runlog(path: str, analytics: list | None = None):
    """(updates, traces, meta, drops): per-update phase records,
    per-update flight-recorder event lists, the meta record (or {}),
    and per-update ring-overflow drop counts.  When `analytics` is a
    list, {"record": "analytics"} census records (analyze/pipeline.py)
    are appended to it in file order."""
    updates, traces, meta = {}, {}, {}
    drops = {}
    with open(path) as f:
        for line in f:
            try:
                rec = json.loads(line)
            except json.JSONDecodeError:
                continue                    # torn tail from a crash
            kind = rec.get("record")
            if kind == "update":
                updates[int(rec["update"])] = rec
            elif kind == "trace":
                u = int(rec["update"])
                traces.setdefault(u, []).extend(rec.get("events", []))
                if rec.get("dropped"):
                    drops[u] = drops.get(u, 0) + int(rec["dropped"])
            elif kind == "meta":
                meta = rec
            elif kind == "analytics" and analytics is not None:
                analytics.append(rec)
    return updates, traces, meta, drops


def to_chrome(path: str) -> dict:
    """Chrome trace-event dict for a telemetry.jsonl runlog."""
    updates, traces, meta, drops = read_runlog(path)
    events = []
    pid = 1
    events.append({"name": "process_name", "ph": "M", "pid": pid, "tid": 0,
                   "args": {"name": "avida-tpu run"}})
    events.append({"name": "thread_name", "ph": "M", "pid": pid,
                   "tid": _PHASE_TID, "args": {"name": "updates"}})
    tids = {}
    for code in sorted(EVENT_CODES):
        tid = _EVENT_TID_BASE + code
        tids[code] = tid
        events.append({"name": "thread_name", "ph": "M", "pid": pid,
                       "tid": tid,
                       "args": {"name": f"trace:{EVENT_CODES[code]}"}})
    phase_tids = {}
    for rec in updates.values():
        for phase in (rec.get("phases") or {}):
            if phase not in phase_tids:
                tid = _PHASE_ROW_BASE + len(phase_tids)
                phase_tids[phase] = tid
                events.append({"name": "thread_name", "ph": "M", "pid": pid,
                               "tid": tid,
                               "args": {"name": f"phase:{phase}"}})

    cursor_us = 0.0
    for u in sorted(set(updates) | set(traces)):
        rec = updates.get(u)
        wall_ms = float(rec.get("wall_ms", _NOMINAL_MS)) if rec \
            else _NOMINAL_MS
        start_us = cursor_us
        events.append({
            "name": f"update {u}", "ph": "X", "pid": pid, "tid": _PHASE_TID,
            "ts": start_us, "dur": wall_ms * 1e3,
            "args": (rec or {}).get("counters", {}),
        })
        t = start_us
        for phase, ms in ((rec or {}).get("phases") or {}).items():
            events.append({"name": phase, "ph": "X", "pid": pid,
                           "tid": phase_tids[phase], "ts": t, "dur": ms * 1e3,
                           "args": {"update": u}})
            t += ms * 1e3
        if u in drops:
            events.append({"name": "trace_dropped", "ph": "i", "pid": pid,
                           "tid": _PHASE_TID, "ts": start_us, "s": "t",
                           "args": {"update": u, "dropped": drops[u]}})
        for cell, code, payload in traces.get(u, ()):
            events.append({
                "name": EVENT_CODES.get(code, f"code{code}"), "ph": "i",
                "pid": pid, "tid": tids.get(code, _EVENT_TID_BASE),
                "ts": start_us, "s": "t",
                "args": {"update": u, "cell": cell, "payload": payload},
            })
        cursor_us += wall_ms * 1e3
    out = {"traceEvents": events, "displayTimeUnit": "ms"}
    if meta:
        out["otherData"] = {k: meta[k] for k in
                            ("seed", "world", "platform", "interpret_path")
                            if k in meta}
    return out


def from_chrome(path: str):
    """Invert a to-chrome trace.json's instant events back into
    {"record": "trace"} JSONL records (list of dicts, update-sorted)."""
    with open(path) as f:
        doc = json.load(f)
    per_update = {}
    drops = {}
    for ev in doc.get("traceEvents", []):
        if ev.get("ph") != "i":
            continue
        args = ev.get("args") or {}
        if "update" not in args:
            continue
        u = int(args["update"])
        if ev.get("name") == "trace_dropped":
            drops[u] = drops.get(u, 0) + int(args.get("dropped", 0))
            continue
        code = _CODE_BY_NAME.get(ev.get("name"))
        if code is None:
            continue
        per_update.setdefault(u, []).append(
            [int(args.get("cell", -1)), code, int(args.get("payload", 0))])
    recs = []
    for u in sorted(set(per_update) | set(drops)):
        rec = {"record": "trace", "update": u,
               "events": per_update.get(u, [])}
        if u in drops:
            rec["dropped"] = drops[u]
        recs.append(rec)
    return recs


def summary(path: str) -> str:
    analytics = []
    updates, traces, _, drops = read_runlog(path, analytics=analytics)
    totals = {}
    for evs in traces.values():
        for _, code, _ in evs:
            name = EVENT_CODES.get(code, f"code{code}")
            totals[name] = totals.get(name, 0) + 1
    n_ev = sum(totals.values())
    span = (max(traces) - min(traces) + 1) if traces else 0
    lines = [f"updates with phase records: {len(updates)}",
             f"updates with trace events:  {len(traces)} (span {span})",
             f"events total:               {n_ev}"]
    if drops:
        lines.append(f"events dropped (overflow):  {sum(drops.values())}")
    for name in sorted(totals, key=totals.get, reverse=True):
        lines.append(f"  {name:<12} {totals[name]}")
    if analytics:
        last = analytics[-1]
        dom = last.get("dominant") or {}
        held = int(last.get("tasks_held_mask", 0))
        lines += [
            f"analytics records:          {len(analytics)} "
            f"(censuses @ updates "
            f"{analytics[0].get('update')}..{last.get('update')})",
            f"  genotypes evaluated       "
            f"{int(last.get('evaluated_total', 0))} total, "
            f"{int(last.get('knockout_sweeps_total', 0))} knockout "
            f"sweep(s)",
            f"  last census               "
            f"{int(last.get('genotypes', 0))} genotypes, dominant gid "
            f"{dom.get('gid', -1)} depth {dom.get('depth', 0)}, tasks "
            f"{held:#x} ({bin(held).count('1')} held)",
        ]
    return "\n".join(lines)


# ---------------------------------------------------------------------------
# fleet mode: one correlated timeline for a whole spool
# ---------------------------------------------------------------------------

_FLEET_PID = 1
_JOB_PID_BASE = 10

# instant-worthy supervisor events and the fleet events that mark a
# job's lifecycle edges
_SUP_INSTANTS = ("watchdog_kill", "rollback", "sdc_rollback",
                 "sdc_digest_quarantine", "pallas_fallback",
                 "anomaly_detected", "backoff", "budget_reset",
                 "checkpoint_fallback_observed", "giving_up")
_TERMINAL_EVENTS = ("done", "failed", "cancelled", "requeued",
                    "quarantined")


def _job_names(spool: str, fleet_recs: list) -> list:
    names = {rec["job"] for rec in fleet_recs
             if isinstance(rec.get("job"), str) and rec["job"]}
    for entry in sorted(os.listdir(spool)) if os.path.isdir(spool) else ():
        if os.path.isdir(os.path.join(spool, entry, "data")):
            names.add(entry)
    return sorted(names)


def _span(name, pid, tid, t0, t1, base, **args_):
    return {"name": name, "ph": "X", "pid": pid, "tid": tid,
            "ts": (t0 - base) * 1e6,
            "dur": max((t1 - t0) * 1e6, 1.0), "args": args_}


def _instant(name, pid, tid, t, base, **args_):
    return {"name": name, "ph": "i", "pid": pid, "tid": tid,
            "ts": (t - base) * 1e6, "s": "t", "args": args_}


def _alert_spans(journal_path, pid, tid, base, t_end, events):
    """firing->resolved alert spans (+ instants on the edges) from an
    alerts.jsonl rotation pair; an unresolved alert spans to t_end."""
    from avida_tpu.observability.alerts import read_alert_records
    open_since = {}
    for rec in read_alert_records(journal_path):
        rule, t = rec.get("rule"), float(rec.get("time", 0.0))
        if rec.get("state") == "firing":
            open_since[rule] = (t, rec)
        elif rec.get("state") == "resolved" and rule in open_since:
            t0, fire_rec = open_since.pop(rule)
            events.append(_span(f"alert:{rule}", pid, tid, t0, t, base,
                                severity=fire_rec.get("severity"),
                                value=fire_rec.get("value")))
    for rule, (t0, fire_rec) in open_since.items():
        events.append(_span(f"alert:{rule} (unresolved)", pid, tid, t0,
                            max(t_end, t0), base,
                            severity=fire_rec.get("severity"),
                            value=fire_rec.get("value")))


def fleet_trace(spool: str) -> dict:
    """The merged Chrome/Perfetto trace dict for one spool."""
    from avida_tpu.observability import history
    from avida_tpu.observability.runlog import read_records

    fleet_recs = [r for r in
                  read_records(os.path.join(spool, "fleet.jsonl"))
                  if r.get("record") == "fleet"]
    names = _job_names(spool, fleet_recs)
    # every journal is read up front so base/t_end span ALL layers --
    # open-ended spans ("live" boots, unresolved alerts) must end at
    # the global horizon, not at whichever journal happened to be read
    # before them
    sup_by_job = {name: [r for r in read_records(os.path.join(
        spool, name, "data", "supervisor.jsonl"))
        if r.get("record") == "supervisor"] for name in names}
    ring_by_job = {name: history.read_samples(history.hist_path(
        os.path.join(spool, name, "data", "metrics.prom")))
        for name in names}
    times = [float(r.get("time", 0.0)) for r in fleet_recs
             if r.get("time")]
    for recs in sup_by_job.values():
        times += [float(r.get("time", 0.0)) for r in recs
                  if r.get("time")]
    for samples in ring_by_job.values():
        times += [float(r.get("time", 0.0)) for r in samples]
    from avida_tpu.observability.alerts import read_alert_records
    for p in ([os.path.join(spool, "alerts.jsonl")]
              + [os.path.join(spool, n, "data", "alerts.jsonl")
                 for n in names]):
        times += [float(r.get("time", 0.0))
                  for r in read_alert_records(p) if r.get("time")]
    base = min(times) if times else 0.0
    t_end = max(times) if times else 0.0

    events = [{"name": "process_name", "ph": "M", "pid": _FLEET_PID,
               "tid": 0, "args": {"name": f"fleet {spool}"}},
              {"name": "thread_name", "ph": "M", "pid": _FLEET_PID,
               "tid": 1, "args": {"name": "orchestrator"}},
              {"name": "thread_name", "ph": "M", "pid": _FLEET_PID,
               "tid": 2, "args": {"name": "alerts"}}]
    job_pid = {n: _JOB_PID_BASE + i for i, n in enumerate(names)}

    # ---- fleet orchestrator track ----
    admit_t, terminal_t = {}, {}
    for rec in fleet_recs:
        ev, t = rec.get("event"), float(rec.get("time", 0.0))
        job = rec.get("job")
        if ev == "admit" and job:
            admit_t.setdefault(job, t)
        if ev in _TERMINAL_EVENTS and job:
            terminal_t[job] = (t, ev)
        if ev in ("fleet_start", "fleet_stop", "breaker_open",
                  "breaker_close", "xla_fallback", "alert", "drain",
                  "coalesced", "batch_fallback", "degrade_hint",
                  "serve_class", "serve_reattach"):
            args_ = {k: v for k, v in rec.items()
                     if k not in ("record", "time")}
            events.append(_instant(ev, _FLEET_PID, 1, t, base, **args_))
    _alert_spans(os.path.join(spool, "alerts.jsonl"), _FLEET_PID, 2,
                 base, t_end, events)

    # ---- one process per job ----
    for name in names:
        pid = job_pid[name]
        data = os.path.join(spool, name, "data")
        events += [
            {"name": "process_name", "ph": "M", "pid": pid, "tid": 0,
             "args": {"name": f"job {name}"}},
            {"name": "thread_name", "ph": "M", "pid": pid, "tid": 1,
             "args": {"name": "lifecycle"}},
            {"name": "thread_name", "ph": "M", "pid": pid, "tid": 2,
             "args": {"name": "boots"}},
            {"name": "thread_name", "ph": "M", "pid": pid, "tid": 3,
             "args": {"name": "alerts"}},
            {"name": "thread_name", "ph": "M", "pid": pid, "tid": 4,
             "args": {"name": "chunks"}},
            {"name": "thread_name", "ph": "M", "pid": pid, "tid": 5,
             "args": {"name": "perf"}},
        ]
        # admit -> terminal lifecycle span from the fleet journal
        if name in admit_t:
            t1, how = terminal_t.get(name, (t_end, "live"))
            events.append(_span(f"{name} [{how}]", pid, 1,
                                admit_t[name], max(t1, admit_t[name]),
                                base, outcome=how))
        # supervisor boots + instants
        launch = {}
        for rec in sup_by_job[name]:
            ev = rec.get("event")
            t = float(rec.get("time", 0.0))
            boot = int(rec.get("boot", 0))
            if ev == "launch":
                launch[boot] = (t, rec.get("fault") or "")
                if rec.get("fault"):
                    events.append(_instant(
                        f"fault:{rec['fault']}", pid, 2, t, base,
                        boot=boot))
            elif ev == "exit" and boot in launch:
                t0, fault = launch.pop(boot)
                events.append(_span(
                    f"boot {boot} [{rec.get('class')}]", pid, 2, t0, t,
                    base, exit_class=rec.get("class"),
                    code=rec.get("code"), update=rec.get("update"),
                    fault=fault))
                if rec.get("class") == "sdc":
                    events.append(_instant("sdc", pid, 2, t, base,
                                           code=rec.get("code")))
            elif ev in _SUP_INSTANTS:
                args_ = {k: v for k, v in rec.items()
                         if k not in ("record", "time", "stderr_tail")}
                events.append(_instant(ev, pid, 2, t, base, **args_))
        for boot, (t0, fault) in launch.items():
            events.append(_span(f"boot {boot} [live]", pid, 2, t0,
                                max(t_end, t0), base, fault=fault))
        # per-job alert spans
        _alert_spans(os.path.join(data, "alerts.jsonl"), pid, 3, base,
                     t_end, events)
        # update-counter track + chunk spans from the history ring
        samples = ring_by_job[name]
        prev = None
        for rec in samples:
            t = float(rec.get("time", 0.0))
            u = rec.get("update")
            if u is None:
                continue
            events.append({"name": "avida_update", "ph": "C",
                           "pid": pid, "tid": 4,
                           "ts": (t - base) * 1e6,
                           "args": {"update": u}})
            if prev is not None and t > prev[0] and u > prev[1]:
                events.append(_span(f"chunk ->u{u}", pid, 4, prev[0], t,
                                    base, updates=u - prev[1]))
                # attribution-plane sub-spans (TPU_PROFILE=1 runs): the
                # chunk interval split proportionally by the staged
                # phase breakdown the ring sampled at this boundary
                phases = {k.split('phase="', 1)[1].rstrip('"}'): float(v)
                          for k, v in rec.items()
                          if isinstance(v, (int, float))
                          and str(k).startswith('avida_perf_phase_ms{')}
                total = sum(phases.values())
                if total > 0:
                    pt = prev[0]
                    for ph, ms in sorted(phases.items(),
                                         key=lambda kv: -kv[1]):
                        pt1 = pt + (t - prev[0]) * (ms / total)
                        events.append(_span(f"perf:{ph}", pid, 5, pt,
                                            pt1, base,
                                            probe_ms=round(ms, 3)))
                        pt = pt1
            prev = (t, u)
    return {"traceEvents": events, "displayTimeUnit": "ms",
            "otherData": {"spool": spool, "jobs": names,
                          "base_unix_time": base}}


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("mode", choices=["to-chrome", "from-chrome", "summary",
                                    "fleet"])
    p.add_argument("path")
    p.add_argument("-o", "--out", default=None)
    args = p.parse_args(argv)

    if args.mode == "summary":
        print(summary(args.path))
        return 0
    if args.mode == "fleet":
        doc = fleet_trace(args.path)
        out = args.out or os.path.join(args.path, "fleet.trace.json")
        with open(out, "w") as f:
            json.dump(doc, f)
        print(f"{out}: {len(doc['traceEvents'])} trace events across "
              f"{len(doc['otherData']['jobs'])} job(s) "
              f"(open in chrome://tracing or ui.perfetto.dev)")
        return 0
    if args.mode == "to-chrome":
        doc = to_chrome(args.path)
        out = args.out or os.path.splitext(args.path)[0] + ".trace.json"
        with open(out, "w") as f:
            json.dump(doc, f)
        print(f"{out}: {len(doc['traceEvents'])} trace events "
              f"(open in chrome://tracing or ui.perfetto.dev)")
        return 0
    recs = from_chrome(args.path)
    out = args.out or os.path.splitext(args.path)[0] + ".trace.jsonl"
    with open(out, "w") as f:
        for rec in recs:
            f.write(json.dumps(rec) + "\n")
    print(f"{out}: {len(recs)} trace records")
    return 0


if __name__ == "__main__":
    sys.exit(main())
