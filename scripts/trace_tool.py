"""Runlog <-> Chrome/Perfetto trace converter (flight recorder + Timeline).

Usage:
    python scripts/trace_tool.py to-chrome telemetry.jsonl [-o trace.json]
    python scripts/trace_tool.py from-chrome trace.json    [-o trace.jsonl]
    python scripts/trace_tool.py summary   telemetry.jsonl

`to-chrome` renders a run's telemetry.jsonl -- the per-update phase
wall-time records ({"record": "update"}, PR 1's Timeline) and the
flight-recorder event records ({"record": "trace"}, observability/
tracer.py) -- as a Chrome trace-event JSON that chrome://tracing and
ui.perfetto.dev open directly:

  - each update becomes a frame on a synthetic timeline whose clock is
    the SUM of recorded update wall times (host gaps between updates are
    not update work and are excluded, matching the runlog's own wall_ms
    semantics); phase brackets (schedule / pack / kernel / birth_flush /
    events_io ...) are complete events ("ph": "X") on per-phase rows;
  - flight-recorder events (births, deaths, first task triggers,
    scheduler stalls, anomalies) are instant events ("ph": "i") at their
    update's frame start, one Chrome thread row per event code, with
    cell/payload in args.

Runs without phase records (TPU_TRACE=1 but telemetry off) still
convert: updates with only trace events get a nominal frame length so
the event timeline stays ordered and zoomable.

`from-chrome` inverts the instant events back into {"record": "trace"}
JSONL (grouped per update, sorted) -- the same shape the FlightRecorder
drain appends, including the ring-overflow "dropped" counter (carried
through the trace as "trace_dropped" markers) -- so a trace.json edited
or filtered in the Perfetto UI can be re-ingested by runlog tooling.
Phase rows do not round-trip (the runlog's per-update phase dict is the
source of truth for those).

`summary` prints per-code event totals and the per-update event rate,
the quick "what happened in this run" view.  It also understands the
analytics pipeline's `{"record": "analytics"}` lines
(analyze/pipeline.py -- point it at DATA_DIR/analysis/analytics.jsonl):
census cadence, genotypes evaluated, knockout sweeps and the last
census digest ride the same summary.
"""

from __future__ import annotations

import argparse
import json
import os
import sys


def _repo_path():
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    if repo not in sys.path:
        sys.path.insert(0, repo)


_repo_path()

from avida_tpu.observability.tracer import EVENT_CODES  # noqa: E402

_CODE_BY_NAME = {name: code for code, name in EVENT_CODES.items()}

# Chrome trace tid layout: update frames on tid 1, one row per event
# code from 10, one row per phase name from 100
_PHASE_TID = 1
_EVENT_TID_BASE = 10
_PHASE_ROW_BASE = 100

# nominal frame for updates that carry events but no phase record
# (telemetry off): 1 ms keeps the timeline ordered and zoomable
_NOMINAL_MS = 1.0


def read_runlog(path: str, analytics: list | None = None):
    """(updates, traces, meta, drops): per-update phase records,
    per-update flight-recorder event lists, the meta record (or {}),
    and per-update ring-overflow drop counts.  When `analytics` is a
    list, {"record": "analytics"} census records (analyze/pipeline.py)
    are appended to it in file order."""
    updates, traces, meta = {}, {}, {}
    drops = {}
    with open(path) as f:
        for line in f:
            try:
                rec = json.loads(line)
            except json.JSONDecodeError:
                continue                    # torn tail from a crash
            kind = rec.get("record")
            if kind == "update":
                updates[int(rec["update"])] = rec
            elif kind == "trace":
                u = int(rec["update"])
                traces.setdefault(u, []).extend(rec.get("events", []))
                if rec.get("dropped"):
                    drops[u] = drops.get(u, 0) + int(rec["dropped"])
            elif kind == "meta":
                meta = rec
            elif kind == "analytics" and analytics is not None:
                analytics.append(rec)
    return updates, traces, meta, drops


def to_chrome(path: str) -> dict:
    """Chrome trace-event dict for a telemetry.jsonl runlog."""
    updates, traces, meta, drops = read_runlog(path)
    events = []
    pid = 1
    events.append({"name": "process_name", "ph": "M", "pid": pid, "tid": 0,
                   "args": {"name": "avida-tpu run"}})
    events.append({"name": "thread_name", "ph": "M", "pid": pid,
                   "tid": _PHASE_TID, "args": {"name": "updates"}})
    tids = {}
    for code in sorted(EVENT_CODES):
        tid = _EVENT_TID_BASE + code
        tids[code] = tid
        events.append({"name": "thread_name", "ph": "M", "pid": pid,
                       "tid": tid,
                       "args": {"name": f"trace:{EVENT_CODES[code]}"}})
    phase_tids = {}
    for rec in updates.values():
        for phase in (rec.get("phases") or {}):
            if phase not in phase_tids:
                tid = _PHASE_ROW_BASE + len(phase_tids)
                phase_tids[phase] = tid
                events.append({"name": "thread_name", "ph": "M", "pid": pid,
                               "tid": tid,
                               "args": {"name": f"phase:{phase}"}})

    cursor_us = 0.0
    for u in sorted(set(updates) | set(traces)):
        rec = updates.get(u)
        wall_ms = float(rec.get("wall_ms", _NOMINAL_MS)) if rec \
            else _NOMINAL_MS
        start_us = cursor_us
        events.append({
            "name": f"update {u}", "ph": "X", "pid": pid, "tid": _PHASE_TID,
            "ts": start_us, "dur": wall_ms * 1e3,
            "args": (rec or {}).get("counters", {}),
        })
        t = start_us
        for phase, ms in ((rec or {}).get("phases") or {}).items():
            events.append({"name": phase, "ph": "X", "pid": pid,
                           "tid": phase_tids[phase], "ts": t, "dur": ms * 1e3,
                           "args": {"update": u}})
            t += ms * 1e3
        if u in drops:
            events.append({"name": "trace_dropped", "ph": "i", "pid": pid,
                           "tid": _PHASE_TID, "ts": start_us, "s": "t",
                           "args": {"update": u, "dropped": drops[u]}})
        for cell, code, payload in traces.get(u, ()):
            events.append({
                "name": EVENT_CODES.get(code, f"code{code}"), "ph": "i",
                "pid": pid, "tid": tids.get(code, _EVENT_TID_BASE),
                "ts": start_us, "s": "t",
                "args": {"update": u, "cell": cell, "payload": payload},
            })
        cursor_us += wall_ms * 1e3
    out = {"traceEvents": events, "displayTimeUnit": "ms"}
    if meta:
        out["otherData"] = {k: meta[k] for k in
                            ("seed", "world", "platform", "interpret_path")
                            if k in meta}
    return out


def from_chrome(path: str):
    """Invert a to-chrome trace.json's instant events back into
    {"record": "trace"} JSONL records (list of dicts, update-sorted)."""
    with open(path) as f:
        doc = json.load(f)
    per_update = {}
    drops = {}
    for ev in doc.get("traceEvents", []):
        if ev.get("ph") != "i":
            continue
        args = ev.get("args") or {}
        if "update" not in args:
            continue
        u = int(args["update"])
        if ev.get("name") == "trace_dropped":
            drops[u] = drops.get(u, 0) + int(args.get("dropped", 0))
            continue
        code = _CODE_BY_NAME.get(ev.get("name"))
        if code is None:
            continue
        per_update.setdefault(u, []).append(
            [int(args.get("cell", -1)), code, int(args.get("payload", 0))])
    recs = []
    for u in sorted(set(per_update) | set(drops)):
        rec = {"record": "trace", "update": u,
               "events": per_update.get(u, [])}
        if u in drops:
            rec["dropped"] = drops[u]
        recs.append(rec)
    return recs


def summary(path: str) -> str:
    analytics = []
    updates, traces, _, drops = read_runlog(path, analytics=analytics)
    totals = {}
    for evs in traces.values():
        for _, code, _ in evs:
            name = EVENT_CODES.get(code, f"code{code}")
            totals[name] = totals.get(name, 0) + 1
    n_ev = sum(totals.values())
    span = (max(traces) - min(traces) + 1) if traces else 0
    lines = [f"updates with phase records: {len(updates)}",
             f"updates with trace events:  {len(traces)} (span {span})",
             f"events total:               {n_ev}"]
    if drops:
        lines.append(f"events dropped (overflow):  {sum(drops.values())}")
    for name in sorted(totals, key=totals.get, reverse=True):
        lines.append(f"  {name:<12} {totals[name]}")
    if analytics:
        last = analytics[-1]
        dom = last.get("dominant") or {}
        held = int(last.get("tasks_held_mask", 0))
        lines += [
            f"analytics records:          {len(analytics)} "
            f"(censuses @ updates "
            f"{analytics[0].get('update')}..{last.get('update')})",
            f"  genotypes evaluated       "
            f"{int(last.get('evaluated_total', 0))} total, "
            f"{int(last.get('knockout_sweeps_total', 0))} knockout "
            f"sweep(s)",
            f"  last census               "
            f"{int(last.get('genotypes', 0))} genotypes, dominant gid "
            f"{dom.get('gid', -1)} depth {dom.get('depth', 0)}, tasks "
            f"{held:#x} ({bin(held).count('1')} held)",
        ]
    return "\n".join(lines)


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("mode", choices=["to-chrome", "from-chrome", "summary"])
    p.add_argument("path")
    p.add_argument("-o", "--out", default=None)
    args = p.parse_args(argv)

    if args.mode == "summary":
        print(summary(args.path))
        return 0
    if args.mode == "to-chrome":
        doc = to_chrome(args.path)
        out = args.out or os.path.splitext(args.path)[0] + ".trace.json"
        with open(out, "w") as f:
            json.dump(doc, f)
        print(f"{out}: {len(doc['traceEvents'])} trace events "
              f"(open in chrome://tracing or ui.perfetto.dev)")
        return 0
    recs = from_chrome(args.path)
    out = args.out or os.path.splitext(args.path)[0] + ".trace.jsonl"
    with open(out, "w") as f:
        for rec in recs:
            f.write(json.dumps(rec) + "\n")
    print(f"{out}: {len(recs)} trace records")
    return 0


if __name__ == "__main__":
    sys.exit(main())
