import os

# Tests run on CPU with a virtual 8-device mesh so multi-chip sharding logic
# is exercised without TPU hardware (see SURVEY.md §7 step 8).  The axon
# sitecustomize hook registers the TPU backend whenever PALLAS_AXON_POOL_IPS
# is set, overriding JAX_PLATFORMS -- but pytest's conftest imports before
# jax, so forcing the config here wins as long as jax isn't initialized yet.
os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()

# Hermeticity: the persistent AOT program cache (utils/compilecache.py,
# default-on in production) must not let one test's compiled programs --
# or a stale ~/.cache store from an earlier build -- leak into another
# test's run.  Kill it suite-wide via the env half of the hard kill
# switch; the dedicated cache tests (tests/test_compile_cache.py) opt
# back in with monkeypatch.setenv + a tmp_path cache root.
os.environ["TPU_COMPILE_CACHE"] = os.environ.get(
    "TPU_COMPILE_CACHE_FOR_TESTS", "0")

# Hermeticity, same rule for the integrity plane: a developer shell with
# TPU_STATE_DIGEST/TPU_SCRUB_EVERY exported must not make every World in
# the suite pay digest/shadow-replay work (and shift timings or emit
# integrity.jsonl files into test dirs).  Dedicated tests
# (tests/test_integrity.py) opt back in via explicit overrides, which
# beat these env defaults.
os.environ["TPU_STATE_DIGEST"] = "0"
os.environ["TPU_SCRUB_EVERY"] = "0"

# Hermeticity, same rule for the performance attribution plane
# (observability/profiler.py): a developer shell with TPU_PROFILE
# exported must not make every World in the suite pay fenced probes
# (or drop perf.jsonl files into test dirs).  Dedicated tests
# (tests/test_profiler.py) opt back in via config overrides, which the
# plane's config-OR-env arming honors over these env pins.
os.environ["TPU_PROFILE"] = "0"
os.environ["TPU_PROFILE_TRACE"] = "0"

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402


@pytest.fixture(scope="session")
def small_world_cfg():
    from avida_tpu.config import AvidaConfig
    cfg = AvidaConfig()
    cfg.WORLD_X = 10
    cfg.WORLD_Y = 10
    cfg.TPU_MAX_MEMORY = 320
    cfg.RANDOM_SEED = 7
    return cfg


def pytest_configure(config):
    # fast/slow split (round-4 review weak #9): `pytest -m "not slow"` is
    # the quick pre-commit subset (~3-4 min); the full suite is the
    # end-of-round recorded run
    config.addinivalue_line(
        "markers", "slow: multi-minute test (full gestations, chunked "
        "runs, golden scenario sweeps)")
