"""Telemetry history + alert plane (observability/history.py,
observability/alerts.py) and their wiring through the exporters, the
supervisor/fleet poll loops and the ops tooling.

Fast tier is host-only where possible (fake clocks, synthetic rings, no
subprocesses); the two world-compiling tests (bit-identity with history
on/off, jaxpr gate) share one small compiled program.  The real
end-to-end hang drill -- TPU_FAULT=hang, stall alert fires and journals
BEFORE the watchdog kill, resolves after recovery -- is slow-marked.
"""

from __future__ import annotations

import glob
import json
import os
import re
import subprocess
import sys

import numpy as np
import pytest

from avida_tpu.observability import alerts, history
from avida_tpu.service.supervisor import Supervisor, SupervisorConfig

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", "scripts"))
import metrics_tool  # noqa: E402


# ---------------------------------------------------------------------------
# history rings
# ---------------------------------------------------------------------------

def test_hist_path_mapping():
    assert history.hist_path("/d/metrics.prom") == "/d/metrics.hist.jsonl"
    assert history.hist_path("/d/fleet.prom") == "/d/fleet.hist.jsonl"
    assert history.hist_path("/d/odd.txt") == "/d/odd.txt.hist.jsonl"


def test_parse_exposition_matches_read_metrics_semantics():
    text = ("# HELP avida_update updates\n# TYPE avida_update counter\n"
            "avida_update 12\n"
            'avida_trace_code_total{code="birth"} 3\n'
            "garbage line without number trailing\n")
    v = history.parse_exposition(text)
    assert v["avida_update"] == 12.0
    assert v['avida_trace_code_total{code="birth"}'] == 3.0
    assert len(v) == 2


def test_append_read_roundtrip_and_update_field(tmp_path):
    ring = str(tmp_path / "metrics.hist.jsonl")
    for i in range(5):
        history.append_sample(ring, {"avida_update": i * 4, "x": 1.5},
                              now=100.0 + i)
    samples = history.read_samples(ring)
    assert [s["update"] for s in samples] == [0, 4, 8, 12, 16]
    assert [s["time"] for s in samples] == [100.0, 101.0, 102.0, 103.0,
                                            104.0]
    assert samples[-1]["v"]["x"] == 1.5
    # windowing and tail reads see the newest rows
    assert len(history.read_samples(ring, window_sec=2.5, now=104.0)) == 3
    tail = history.read_samples(ring, tail_bytes=200)
    assert tail and tail[-1]["update"] == 16 and len(tail) < 5


def test_ring_rotation_mid_append_stays_bounded(tmp_path):
    ring = str(tmp_path / "metrics.hist.jsonl")
    cap = 2048
    for i in range(200):
        history.append_sample(ring, {"avida_update": i, "pad": 123456.0},
                              now=1000.0 + i, max_bytes=cap)
    # the pair is bounded: live file under the cap, exactly one aside
    assert os.path.getsize(ring) <= cap
    assert os.path.exists(ring + ".1")
    assert os.path.getsize(ring + ".1") <= cap
    samples = history.read_samples(ring)
    # newest sample survived, ordering holds across the rotation seam
    assert samples[-1]["update"] == 199
    upds = [s["update"] for s in samples]
    assert upds == sorted(upds)
    # a torn tail (crash mid-append) is skipped, not fatal
    with open(ring, "a") as f:
        f.write('{"record": "sample", "time": 99')
    assert history.read_samples(ring)[-1]["update"] == 199


def test_sink_knobs_off_and_every(tmp_path):
    prom = str(tmp_path / "metrics.prom")
    text = "avida_update 7\n"
    off = history.HistorySink(prom, env={"TPU_METRICS_HIST": "0"})
    off.publish(text)
    assert not os.path.exists(history.hist_path(prom))       # true no-op
    every = history.HistorySink(prom, env={"TPU_METRICS_HIST_EVERY": "3"})
    for _ in range(7):
        every.publish(text)
    assert len(history.read_samples(history.hist_path(prom))) == 3


def test_sink_env_wins_over_cfg(tmp_path):
    from avida_tpu.config import AvidaConfig
    cfg = AvidaConfig()
    cfg.TPU_METRICS_HIST = 0
    prom = str(tmp_path / "metrics.prom")
    assert not history.HistorySink(prom, env={}, cfg=cfg).knobs.enabled
    assert history.HistorySink(prom, env={"TPU_METRICS_HIST": "1"},
                               cfg=cfg).knobs.enabled


def _mk_samples(values_by_time):
    return [{"record": "sample", "time": t, "v": v}
            for t, v in sorted(values_by_time.items())]


def test_series_labeled_max_and_filter():
    samples = _mk_samples({
        1.0: {'f{world="a"}': 2.0, 'f{world="b"}': 5.0, "g": 1.0}})
    assert history.series(samples, "f") == [(1.0, 5.0)]
    assert history.series(samples, "f", labels='world="a"') == [(1.0, 2.0)]
    assert history.series(samples, "g") == [(1.0, 1.0)]


def test_summarize_quantiles_and_rate():
    samples = _mk_samples({float(t): {"c": float(t * 2)}
                           for t in range(10, 21)})
    d = history.summarize(samples, "c", now=20.0)
    assert d["count"] == 11 and d["min"] == 20.0 and d["max"] == 40.0
    assert d["p50"] == 30.0
    assert d["rate_per_sec"] == 2.0
    assert history.summarize(samples, "absent")["count"] == 0


def test_prune_trims_live_and_drops_aside(tmp_path):
    ring = str(tmp_path / "metrics.hist.jsonl")
    for i in range(300):
        history.append_sample(ring, {"avida_update": i}, now=float(i),
                              max_bytes=4096)
    res = history.prune(ring, keep_bytes=512)
    assert res["removed_bytes"] > 0
    assert not os.path.exists(ring + ".1")
    assert os.path.getsize(ring) <= 512
    # the survivors are the NEWEST rows, whole lines only
    samples = history.read_samples(ring)
    assert samples and samples[-1]["update"] == 299


# ---------------------------------------------------------------------------
# alert rules: threshold / rate / staleness / for-duration / resolve
# ---------------------------------------------------------------------------

def test_threshold_rule_fires_and_resolves():
    r = alerts.Rule("hot", "q", "threshold", 3.0, op=">")
    low = _mk_samples({100.0: {"q": 1.0}})
    high = _mk_samples({100.0: {"q": 1.0}, 101.0: {"q": 9.0}})
    assert not alerts.evaluate_rule(r, low, 101.0)["firing"]
    res = alerts.evaluate_rule(r, high, 102.0)
    assert res["firing"] and res["value"] == 9.0
    # resolve: newest value back under the line
    back = high + _mk_samples({103.0: {"q": 2.0}})
    assert not alerts.evaluate_rule(r, back, 104.0)["firing"]
    # no data at all: never fires
    assert not alerts.evaluate_rule(r, [], 104.0)["firing"]


def test_threshold_for_duration_delays_firing():
    r = alerts.Rule("hot", "q", "threshold", 3.0, op=">", for_sec=10.0)
    samples = _mk_samples({100.0: {"q": 1.0}, 105.0: {"q": 9.0}})
    # condition just started: held only 5s of the required 10
    assert not alerts.evaluate_rule(r, samples, 110.0)["firing"]
    # still high at every as-of point across the window -> fires
    samples += _mk_samples({112.0: {"q": 8.0}})
    res = alerts.evaluate_rule(r, samples, 116.0)
    assert res["firing"] and res["since"] == 106.0
    # a dip inside the window resets the clock
    dipped = samples + _mk_samples({117.0: {"q": 1.0},
                                    118.0: {"q": 9.0}})
    assert not alerts.evaluate_rule(r, dipped, 120.0)["firing"]


def test_rate_stall_semantics():
    r = alerts.Rule("stall", "avida_update", "rate", 0.0, op="<=",
                    window_sec=60.0)
    # young ring (does not span the window yet): not evaluable, no fire
    young = _mk_samples({100.0: {"avida_update": 5.0},
                         110.0: {"avida_update": 5.0}})
    assert not alerts.evaluate_rule(r, young, 120.0)["firing"]
    # flat counter across the window while publishes continue: fires
    flat = _mk_samples({float(t): {"avida_update": 42.0}
                        for t in range(100, 200, 10)})
    assert alerts.evaluate_rule(r, flat, 190.0)["firing"]
    # publisher STOPPED (hung chunk): newest sample predates the whole
    # window -- the counter definitionally went flat, still fires
    assert alerts.evaluate_rule(r, flat, 400.0)["firing"]
    # advancing counter: resolves
    moving = flat + _mk_samples({float(t): {"avida_update": 42.0 + t}
                                 for t in range(200, 280, 10)})
    assert not alerts.evaluate_rule(r, moving, 270.0)["firing"]


def test_staleness_rule_and_empty_ring_honesty():
    r = alerts.Rule("stale", "avida_heartbeat_timestamp_seconds",
                    "staleness", 30.0)
    samples = _mk_samples(
        {100.0: {"avida_heartbeat_timestamp_seconds": 100.0}})
    assert not alerts.evaluate_rule(r, samples, 120.0)["firing"]
    res = alerts.evaluate_rule(r, samples, 140.0)
    assert res["firing"] and res["value"] == 40.0
    # an empty ring is no evidence of staleness
    assert not alerts.evaluate_rule(r, [], 1e9)["firing"]


def test_threshold_below_rules_see_the_worst_labeled_series():
    # one healthy world must not mask seven collapsed ones: below-
    # threshold rules aggregate labeled rows with min, not max
    r = alerts.Rule("collapse", "eff", "threshold", 0.2, op="<")
    samples = _mk_samples({100.0: {'eff{world="a"}': 0.05,
                                   'eff{world="b"}': 0.9}})
    res = alerts.evaluate_rule(r, samples, 101.0)
    assert res["firing"] and res["value"] == 0.05
    # direction-matched: an above-threshold rule still sees the max
    r_hi = alerts.Rule("hot", "eff", "threshold", 0.8, op=">")
    assert alerts.evaluate_rule(r_hi, samples, 101.0)["value"] == 0.9


def test_ring_pinned_rules_never_merge_rings():
    # the serve-batch trap: metrics ring carries the batch-max counter
    # (advancing), the multiworld ring per-tenant rows where a freshly
    # admitted tenant rides at update 0 -- merged, the stall rule's
    # min-collapsed series would sawtooth into a false page
    metrics = _mk_samples({float(t): {"avida_update": 5000.0 + t}
                           for t in range(100, 200, 5)})
    mworld = _mk_samples({float(t): {'avida_update{world="lead"}':
                                     5000.0 + t,
                                     'avida_update{world="fresh"}':
                                     float(t - 150) if t >= 150 else 0.0}
                          for t in range(100, 200, 5)})
    stall = next(r for r in alerts.default_rules() if r.name == "stall")
    assert stall.ring == "metrics"
    by_ring = {"metrics": metrics, "multiworld": mworld}
    res = alerts.evaluate([stall], by_ring, 195.0)
    assert not res["stall"]["firing"]
    # and a rule pinned to a ring the evaluator does not own is inert
    qg = next(r for r in alerts.default_rules()
              if r.name == "queue_growth")
    assert qg.ring == "fleet"
    assert not alerts.evaluate([qg], by_ring, 195.0)["queue_growth"][
        "firing"]
    # an unpinned custom rule still sees the concatenation
    anyr = alerts.Rule("any", "avida_update", "threshold", 1.0, op=">")
    assert alerts.evaluate([anyr], by_ring, 195.0)["any"]["firing"]


def test_staleness_for_sec_folds_into_threshold():
    r = alerts.Rule("stale", "hb", "staleness", 30.0, for_sec=20.0)
    samples = _mk_samples({100.0: {"hb": 100.0}})
    # age 40 > 30 but the 20s hold has not elapsed yet
    assert not alerts.evaluate_rule(r, samples, 140.0)["firing"]
    res = alerts.evaluate_rule(r, samples, 151.0)     # age 51 > 30+20
    assert res["firing"] and res["since"] == 150.0


def test_rule_validation_rejects_garbage():
    with pytest.raises(ValueError, match="unknown kind"):
        alerts.Rule("x", "f", "derivative", 1.0)
    with pytest.raises(ValueError, match="unknown op"):
        alerts.Rule("x", "f", "threshold", 1.0, op="~")
    with pytest.raises(ValueError, match="unknown field"):
        alerts.Rule.from_dict({"name": "x", "family": "f",
                               "kind": "threshold", "value": 1,
                               "threshold": 2})
    with pytest.raises(ValueError, match="needs 'value'"):
        alerts.Rule.from_dict({"name": "x", "family": "f",
                               "kind": "threshold"})
    # null/garbage numerics and non-object entries must surface as
    # ValueError -- the one class the supervisor/fleet alert-disable
    # guards catch (a TypeError here would crash supervision at boot)
    with pytest.raises(ValueError, match="non-numeric"):
        alerts.Rule.from_dict({"name": "x", "family": "f",
                               "kind": "threshold", "value": None})
    with pytest.raises(ValueError, match="JSON object"):
        alerts.Rule.from_dict(["not", "a", "rule"])


def test_load_rules_defaults_and_overrides(tmp_path):
    names = {r.name for r in alerts.load_rules()}
    assert {"heartbeat_stale", "stall", "batch_efficiency_collapse",
            "queue_growth", "integrity_mismatch",
            "compile_cache_errors"} <= names
    with open(tmp_path / "alerts.json", "w") as f:
        json.dump([
            {"name": "stall", "family": "avida_update", "kind": "rate",
             "op": "<=", "value": 0.0, "window_sec": 7.0},
            {"name": "queue_growth", "family": "avida_fleet_queue_depth",
             "kind": "rate", "value": 0, "enabled": False},
            {"name": "custom", "family": "avida_organisms",
             "kind": "threshold", "op": "<", "value": 2.0},
        ], f)
    loaded = {r.name: r for r in alerts.load_rules(str(tmp_path))}
    assert loaded["stall"].window_sec == 7.0          # replaced by name
    assert "queue_growth" not in loaded               # disabled
    assert loaded["custom"].op == "<"                 # extended
    assert "heartbeat_stale" in loaded                # defaults survive
    with open(tmp_path / "alerts.json", "w") as f:
        f.write("{}")
    with pytest.raises(ValueError, match="JSON list"):
        alerts.load_rules(str(tmp_path))


def test_alert_plane_edges_journal_and_families(tmp_path):
    journal = str(tmp_path / "alerts.jsonl")
    rule = alerts.Rule("hot", "q", "threshold", 3.0, op=">",
                       severity="page")
    plane = alerts.AlertPlane([rule], journal_path=journal)
    high = _mk_samples({100.0: {"q": 9.0}})
    assert plane.observe(high, 101.0) == [
        ("hot", "firing", {"firing": True, "value": 9.0, "since": 101.0})]
    # steady state: no new edge, no new journal line
    assert plane.observe(high, 102.0) == []
    low = high + _mk_samples({103.0: {"q": 1.0}})
    trans = plane.observe(low, 104.0)
    assert [(t[0], t[1]) for t in trans] == [("hot", "resolved")]
    recs = [json.loads(line) for line in open(journal)]
    assert [(r["record"], r["state"]) for r in recs] == [
        ("alert", "firing"), ("alert", "resolved")]
    assert recs[0]["severity"] == "page" and recs[0]["rule"] == "hot"
    fams = {name: (kind, value) for name, kind, _, value
            in plane.families()}
    assert fams["avida_alerts_firing"][1] == {'rule="hot"': 0}
    assert fams["avida_alerts_fired_total"][1] == {'rule="hot"': 1}
    assert alerts.read_alert_records(journal) == recs


def test_firing_from_metrics_and_status_line():
    m = {'avida_alerts_firing{rule="stall"}': 1.0,
         'avida_alerts_firing{rule="hot"}': 0.0,
         'avida_alerts_fired_total{rule="stall"}': 3.0,
         'avida_alerts_fired_total{rule="hot"}': 0.0}
    d = alerts.firing_from_metrics(m)
    assert d["firing"] == {"stall": 1} and d["rules"] == ["hot", "stall"]
    line = alerts.format_alert_status(m)
    assert "stall FIRING (3x)" in line
    m['avida_alerts_firing{rule="stall"}'] = 0.0
    assert "none firing (2 rules, 3 fired so far)" \
        in alerts.format_alert_status(m)
    assert alerts.format_alert_status({"avida_update": 1.0}) is None


# ---------------------------------------------------------------------------
# supervisor / fleet integration (fake clock, no subprocesses)
# ---------------------------------------------------------------------------

class FakeClock:
    def __init__(self, t=1000.0):
        self.t = t

    def __call__(self):
        return self.t

    def sleep(self, s):
        self.t += s


class ForeverProc:
    """A child that never exits (the alert tests only need poll())."""
    returncode = None
    pid = 777

    def poll(self):
        return None

    def kill(self):
        self.returncode = -9

    def wait(self, timeout=None):
        return -9

    def terminate(self):
        self.returncode = 0

    def send_signal(self, sig):
        pass


def _write_ring(data_dir, rows):
    ring = history.hist_path(os.path.join(data_dir, "metrics.prom"))
    for t, v in sorted(rows.items()):
        history.append_sample(ring, v, now=t)


def test_supervisor_poll_loop_evaluates_alerts(tmp_path):
    clk = FakeClock(1000.0)
    data, ck = str(tmp_path / "data"), str(tmp_path / "ck")
    os.makedirs(data), os.makedirs(ck)
    # a ring whose update counter has been flat for 100 fake seconds
    _write_ring(data, {float(t): {"avida_update": 42.0,
                                  "avida_heartbeat_timestamp_seconds":
                                  float(t)}
                       for t in range(900, 1001, 5)})
    sup = Supervisor(
        ["-d", data, "-set", "TPU_CKPT_DIR", ck, "-u", "100"],
        cfg=SupervisorConfig(watchdog_sec=1e6, poll_sec=0.5,
                             grace_sec=1e6, max_retries=2,
                             backoff_base=0.1, backoff_cap=1.0,
                             healthy_sec=1e9, seed=2),
        env={}, spawn=lambda argv, env, logf: ForeverProc(),
        clock=clk, sleep=clk.sleep)
    assert sup.alerts is not None
    sup.poll()                    # idle -> launch (no eval pre-launch)
    assert not sup.alerts.firing
    sup.poll()                    # running -> evaluate the fresh ring
    recs = alerts.read_alert_records(os.path.join(data, "alerts.jsonl"))
    assert ("stall", "firing") in [(r["rule"], r["state"]) for r in recs]
    from avida_tpu.observability.exporter import read_metrics
    m = read_metrics(os.path.join(data, "supervisor.prom"))
    assert m['avida_alerts_firing{rule="stall"}'] == 1
    assert m['avida_alerts_fired_total{rule="stall"}'] == 1
    # the counter advances again -> the next evaluation resolves it
    _write_ring(data, {float(t): {"avida_update": 42.0 + t - 1000.0}
                       for t in range(1001, 1011)})
    clk.t = 1010.0
    sup.poll()
    recs = alerts.read_alert_records(os.path.join(data, "alerts.jsonl"))
    assert ("stall", "resolved") in [(r["rule"], r["state"])
                                     for r in recs]
    m = read_metrics(os.path.join(data, "supervisor.prom"))
    assert m['avida_alerts_firing{rule="stall"}'] == 0
    assert m['avida_alerts_fired_total{rule="stall"}'] == 1


def test_supervisor_terminal_sweep_resolves_before_exit(tmp_path):
    """A child that exits within one alert_eval_sec of recovering must
    not leave the journal claiming a live alert: _terminal runs one
    final throttle-bypassed evaluation (the child's last export is on
    disk before its exit is observable)."""
    clk = FakeClock(1000.0)
    data, ck = str(tmp_path / "data"), str(tmp_path / "ck")
    os.makedirs(data), os.makedirs(ck)
    _write_ring(data, {float(t): {"avida_update": 42.0}
                       for t in range(900, 1001, 5)})

    class ExitingProc(ForeverProc):
        def __init__(self):
            self.returncode = None
            self.exit_now = False

        def poll(self):
            if self.exit_now:
                self.returncode = 0
            return self.returncode

    procs = []

    def spawn(argv, env, logf):
        procs.append(ExitingProc())
        return procs[-1]

    sup = Supervisor(
        ["-d", data, "-set", "TPU_CKPT_DIR", ck, "-u", "100"],
        cfg=SupervisorConfig(watchdog_sec=1e6, poll_sec=0.5,
                             grace_sec=1e6, max_retries=2,
                             backoff_base=0.1, backoff_cap=1.0,
                             healthy_sec=1e9, seed=2),
        env={}, spawn=spawn, clock=clk, sleep=clk.sleep)
    sup.poll()                        # launch (no pre-launch eval)
    sup.poll()                        # running: stall fires on the ring
    assert "stall" in sup.alerts.firing
    # recovery lands its samples, then the child exits INSIDE the
    # throttle window -- the terminal sweep must still resolve
    _write_ring(data, {float(t): {"avida_update": 42.0 + t - 1000.0}
                       for t in range(1001, 1011)})
    clk.t = 1010.0
    sup._alerts_next = clk.t + 100.0  # force the throttle CLOSED
    procs[0].exit_now = True
    assert sup.poll() == "done"       # child exited -> terminal sweep
    assert "stall" not in sup.alerts.firing
    recs = alerts.read_alert_records(os.path.join(data, "alerts.jsonl"))
    assert [(r["rule"], r["state"]) for r in recs] == [
        ("stall", "firing"), ("stall", "resolved")]


def test_supervisor_ignores_previous_incarnations_ring(tmp_path):
    """A resume over a data dir whose ring ends long before this boot
    must not page: pre-launch there is nothing to evaluate, and during
    the new boot's compile window the old incarnation's samples are
    evidence of the past -- alert state freezes until a post-launch
    sample lands."""
    clk = FakeClock(1000.0)
    data, ck = str(tmp_path / "data"), str(tmp_path / "ck")
    os.makedirs(data), os.makedirs(ck)
    _write_ring(data, {float(t): {"avida_update": 42.0,
                                  "avida_heartbeat_timestamp_seconds":
                                  float(t)}
                       for t in range(300, 401, 5)})       # 10 min old
    sup = Supervisor(
        ["-d", data, "-set", "TPU_CKPT_DIR", ck, "-u", "100"],
        cfg=SupervisorConfig(watchdog_sec=1e6, poll_sec=0.5,
                             grace_sec=1e6, max_retries=2,
                             backoff_base=0.1, backoff_cap=1.0,
                             healthy_sec=1e9, seed=2),
        env={}, spawn=lambda argv, env, logf: ForeverProc(),
        clock=clk, sleep=clk.sleep)
    sup.poll()                                  # launch
    clk.t = 1006.0
    sup.poll()                                  # compile window
    assert not sup.alerts.firing
    assert not os.path.exists(os.path.join(data, "alerts.jsonl"))
    # the new child publishes advancing samples -> evaluation resumes
    _write_ring(data, {float(t): {"avida_update": 50.0 + t,
                                  "avida_heartbeat_timestamp_seconds":
                                  float(t)}
                       for t in range(1007, 1013)})
    clk.t = 1012.0
    sup.poll()
    assert not sup.alerts.firing                # advancing: no stall


def test_fleet_reads_degrade_hints_from_job_supervisors(tmp_path):
    """Run-level degrade-hint rules (integrity_mismatch, pinned to the
    job's metrics ring) evaluate inside each job's embedded
    Supervisor; the fleet poll loop reads that plane in-process and
    drops the breadcrumb -- without this the advertised alert->breaker
    path would be unreachable."""
    from types import SimpleNamespace

    from avida_tpu.service.fleet import (FleetConfig, FleetOrchestrator,
                                         Job)
    spool = str(tmp_path / "spool")
    clk = FakeClock(3000.0)
    fl = FleetOrchestrator(spool, cfg=FleetConfig(breaker_k=1,
                                                  breaker_sec=60.0),
                           env={}, clock=clk, sleep=clk.sleep)
    rule = next(r for r in alerts.default_rules()
                if r.name == "integrity_mismatch")
    assert rule.action == "degrade-hint"
    plane = alerts.AlertPlane([rule])
    plane.firing["integrity_mismatch"] = 2990.0
    job = Job("sick", spool)
    job.sup = SimpleNamespace(alerts=plane, last_outcome=None,
                              _xla_fallback=False)
    fl._note_alert_hints(job)
    assert fl.failures["alert:integrity_mismatch"] == 1
    assert fl.breaker.open_class == "alert:integrity_mismatch"
    # steady firing: no second breadcrumb until the rule resolves
    fl._note_alert_hints(job)
    assert fl.failures["alert:integrity_mismatch"] == 1
    plane.firing.clear()
    fl._note_alert_hints(job)                   # resolve re-arms
    plane.firing["integrity_mismatch"] = 2995.0
    fl._note_alert_hints(job)
    assert fl.failures["alert:integrity_mismatch"] == 2
    from avida_tpu.observability.runlog import read_records
    events = [(r.get("event"), r.get("rule"), r.get("job"))
              for r in read_records(fl.journal_path)]
    assert ("alert", "integrity_mismatch", "sick") in events


def test_supervisor_alert_eval_disabled_and_bad_rules(tmp_path, capsys):
    data, ck = str(tmp_path / "data"), str(tmp_path / "ck")
    os.makedirs(data), os.makedirs(ck)
    argv = ["-d", data, "-set", "TPU_CKPT_DIR", ck]
    sup = Supervisor(argv, env={"TPU_ALERT_EVAL_SEC": "0"},
                     spawn=lambda *a: ForeverProc())
    assert sup.alerts is None
    # a malformed alerts.json is loud but does not kill supervision
    with open(os.path.join(data, "alerts.json"), "w") as f:
        f.write("{}")
    sup = Supervisor(argv, env={}, spawn=lambda *a: ForeverProc())
    assert sup.alerts is None
    assert "alert rules disabled" in capsys.readouterr().err
    # same survival for a structurally-valid list with a null numeric
    with open(os.path.join(data, "alerts.json"), "w") as f:
        json.dump([{"name": "x", "family": "f", "kind": "threshold",
                    "value": None}], f)
    sup = Supervisor(argv, env={}, spawn=lambda *a: ForeverProc())
    assert sup.alerts is None
    assert "alert rules disabled" in capsys.readouterr().err


def test_fleet_degrade_hint_breadcrumb_and_breaker(tmp_path):
    from avida_tpu.service.fleet import FleetConfig, FleetOrchestrator
    spool = str(tmp_path / "spool")
    os.makedirs(spool)
    with open(os.path.join(spool, "alerts.json"), "w") as f:
        json.dump([{"name": "queue_hot",
                    "family": "avida_fleet_queue_depth",
                    "kind": "threshold", "op": ">", "value": 3.0,
                    "severity": "warn", "action": "degrade-hint"}], f)
    clk = FakeClock(2000.0)
    fl = FleetOrchestrator(spool,
                           cfg=FleetConfig(breaker_k=1,
                                           breaker_sec=60.0),
                           env={}, clock=clk, sleep=clk.sleep)
    ring = history.hist_path(fl.metrics_path)
    for t in range(1900, 2001, 10):
        history.append_sample(ring, {"avida_fleet_queue_depth": 9.0},
                              now=float(t))
    fl._eval_alerts(clk())
    # breadcrumb: failure tally + journal + breaker (admission pause --
    # detection plane, never a kill)
    assert fl.failures["alert:queue_hot"] == 1
    assert fl.breaker.open_class == "alert:queue_hot"
    from avida_tpu.observability.runlog import read_records
    events = [(r.get("event"), r.get("rule"), r.get("job"))
              for r in read_records(fl.journal_path)]
    assert ("alert", "queue_hot", None) in events
    assert ("breaker_open", None, "") in events
    recs = alerts.read_alert_records(os.path.join(spool, "alerts.jsonl"))
    assert [(r["rule"], r["state"]) for r in recs] \
        == [("queue_hot", "firing")]
    fl.publish_metrics()                       # families render cleanly
    from avida_tpu.observability.exporter import read_metrics
    m = read_metrics(fl.metrics_path)
    assert m['avida_alerts_firing{rule="queue_hot"}'] == 1
    assert m['avida_fleet_failures_total{class="alert:queue_hot"}'] == 1
    # and the fleet.prom publish itself rode into the fleet ring
    assert any("avida_fleet_breaker_open" in s["v"]
               for s in history.read_samples(ring))
    # steady firing: no second breadcrumb on the next evaluation
    clk.t += 10
    fl._eval_alerts(clk())
    assert fl.failures["alert:queue_hot"] == 1


def test_format_status_history_line(tmp_path):
    from avida_tpu.observability.exporter import format_status
    ring = str(tmp_path / "metrics.hist.jsonl")
    metrics = {"avida_update": 40, "avida_organisms": 3,
               "avida_heartbeat_timestamp_seconds": 1000.0}
    out = format_status(metrics, now=1000.0, hist_path=ring)
    assert "history     no history" in out
    for t in range(900, 1001, 10):
        history.append_sample(ring, {"avida_update": float(t - 900)},
                              now=float(t))
    out = format_status(metrics, now=1000.0, hist_path=ring)
    assert re.search(r"history     upd/s last \d+ beats: "
                     r"[\d.]+ -> [\d.]+", out)
    # without a hist_path the line is absent (old callers unchanged)
    assert "history" not in format_status(metrics, now=1000.0)


# ---------------------------------------------------------------------------
# exporter consistency lint: the .prom plane has grown across 7 PRs
# ---------------------------------------------------------------------------

# counters that predate the _total convention (PR 5); grandfathered,
# never to grow
_COUNTER_NO_TOTAL = {"avida_update", "avida_time"}

_FAMILY_TUPLE_RE = re.compile(
    r'\(\s*"(avida_[a-z0-9_]+)",\s*"(counter|gauge)"', re.S)
_FAMILY_HELP_RE = re.compile(
    r'"(avida_[a-z0-9_]+)":\s*\(\s*"(counter|gauge)"')
_NAME_RE = re.compile(r"^avida_[a-z0-9]+(_[a-z0-9]+)*$")


def _declared_families():
    repo = os.path.join(os.path.dirname(__file__), "..")
    files = (glob.glob(os.path.join(repo, "avida_tpu", "**", "*.py"),
                       recursive=True)
             + glob.glob(os.path.join(repo, "scripts", "*.py"))
             + [os.path.join(repo, "bench.py")])
    kinds: dict = {}
    for path in files:
        with open(path) as f:
            text = f.read()
        for rx in (_FAMILY_TUPLE_RE, _FAMILY_HELP_RE):
            for m in rx.finditer(text):
                kinds.setdefault(m.group(1), {})[m.group(2)] = \
                    os.path.basename(path)
    return kinds


def test_prom_family_conventions():
    """Walk every render_families family declaration in the tree and
    enforce the exposition conventions: avida_ prefix and lowercase
    snake naming, counters end in _total (the two pre-convention
    counters are a frozen grandfather set), gauges never claim _total,
    and no family is declared with two different types by two
    flavors."""
    kinds = _declared_families()
    # the scan itself must keep working as the plane grows: today it
    # sees ~88 families (incl. the avida_perf_* attribution plane); a
    # collapse here means the regexes rotted
    assert len(kinds) >= 70, sorted(kinds)
    for name, by_kind in sorted(kinds.items()):
        assert _NAME_RE.match(name), f"non-conforming family name {name}"
        assert len(by_kind) == 1, (
            f"family {name} declared with conflicting types {by_kind}")
        kind = next(iter(by_kind))
        if kind == "counter" and name not in _COUNTER_NO_TOTAL:
            assert name.endswith("_total"), (
                f"counter {name} ({by_kind[kind]}) must end in _total")
        if kind == "gauge":
            assert not name.endswith("_total"), (
                f"gauge {name} ({by_kind[kind]}) must not claim _total")
    for name in _COUNTER_NO_TOTAL:
        assert name in kinds, f"grandfathered {name} vanished; prune set"


# ---------------------------------------------------------------------------
# ops tooling: metrics_tool + trace_tool fleet
# ---------------------------------------------------------------------------

def test_metrics_tool_query_watch_prune(tmp_path, capsys):
    d = str(tmp_path)
    ring = os.path.join(d, "metrics.hist.jsonl")
    import time as _time
    now = _time.time()
    for i in range(20):
        history.append_sample(
            ring, {"avida_update": float(i * 4),
                   "avida_heartbeat_timestamp_seconds": now - 20 + i},
            now=now - 20 + i)
    assert metrics_tool.main(["query", d, "avida_update"]) == 0
    out = capsys.readouterr().out
    assert "count          20" in out and "rate_per_sec" in out
    csv_path = os.path.join(d, "upd.csv")
    assert metrics_tool.main(["query", d, "avida_update",
                              "--csv", csv_path]) == 0
    capsys.readouterr()
    assert len(open(csv_path).read().splitlines()) == 21   # header + rows
    # watch --once: the update counter is advancing, heartbeat fresh ->
    # nothing fires, exit 0
    assert metrics_tool.main(["watch", d, "--once"]) == 0
    assert "stall" in capsys.readouterr().out
    # a stalled ring (flat counter spanning the 60s window) flips the
    # exit status to 3 (cron-able)
    d2 = str(tmp_path / "stalled")
    os.makedirs(d2)
    ring2 = os.path.join(d2, "metrics.hist.jsonl")
    for i in range(15):
        history.append_sample(ring2, {"avida_update": 80.0},
                              now=now - 70 + i * 5)
    assert metrics_tool.main(["watch", d2, "--once"]) == 3
    capsys.readouterr()
    assert metrics_tool.main(["rules", d]) == 0
    doc = json.loads(capsys.readouterr().out)
    assert {"name", "family", "kind"} <= set(doc[0])
    assert metrics_tool.main(["prune", d, "--keep-bytes", "512"]) == 0
    assert os.path.getsize(ring) <= 512
    assert metrics_tool.main(["query", d, "no_such_family"]) == 1
    capsys.readouterr()


def test_trace_tool_fleet_merges_layers(tmp_path):
    import trace_tool
    spool = str(tmp_path / "spool")
    data = os.path.join(spool, "job-a", "data")
    os.makedirs(data)
    t0 = 5000.0

    def w(path, recs):
        with open(path, "w") as f:
            for r in recs:
                f.write(json.dumps(r) + "\n")

    w(os.path.join(spool, "fleet.jsonl"), [
        {"record": "fleet", "event": "fleet_start", "time": t0},
        {"record": "fleet", "event": "admit", "time": t0 + 1,
         "job": "job-a"},
        {"record": "fleet", "event": "breaker_open", "time": t0 + 5,
         "failure_class": "crash", "job": "job-a", "k": 3,
         "window_sec": 300},
        {"record": "fleet", "event": "done", "time": t0 + 20,
         "job": "job-a"},
    ])
    w(os.path.join(data, "supervisor.jsonl"), [
        {"record": "supervisor", "event": "launch", "time": t0 + 2,
         "boot": 0, "fault": "hang:sec=5@chunk=2"},
        {"record": "supervisor", "event": "watchdog_kill",
         "time": t0 + 8, "boot": 0, "reason": "stale heartbeat"},
        {"record": "supervisor", "event": "exit", "time": t0 + 8.2,
         "boot": 0, "class": "hang", "code": -9, "update": 4},
        {"record": "supervisor", "event": "launch", "time": t0 + 9,
         "boot": 1, "fault": ""},
        {"record": "supervisor", "event": "exit", "time": t0 + 19,
         "boot": 1, "class": "success", "code": 0, "update": 20},
    ])
    w(os.path.join(data, "alerts.jsonl"), [
        {"record": "alert", "rule": "stall", "state": "firing",
         "time": t0 + 6, "severity": "page", "value": 0.0},
        {"record": "alert", "rule": "stall", "state": "resolved",
         "time": t0 + 12},
    ])
    ring = history.hist_path(os.path.join(data, "metrics.prom"))
    for i, u in enumerate((2, 4, 12, 20)):
        history.append_sample(ring, {"avida_update": float(u)},
                              now=t0 + 3 + i * 4)
    # a second, still-live job whose only record postdates every fleet
    # record: its open-ended boot span must reach the GLOBAL horizon
    # (job-a's newest ring sample at t0+30), not the fleet journal's
    # last timestamp
    data_b = os.path.join(spool, "job-b", "data")
    os.makedirs(data_b)
    w(os.path.join(data_b, "supervisor.jsonl"), [
        {"record": "supervisor", "event": "launch", "time": t0 + 25,
         "boot": 0, "fault": ""},
    ])
    history.append_sample(ring, {"avida_update": 22.0}, now=t0 + 30)
    doc = trace_tool.fleet_trace(spool)
    evs = doc["traceEvents"]
    names = [e["name"] for e in evs]
    # one process per layer, correlated on one clock
    procs = {e["args"]["name"] for e in evs
             if e["name"] == "process_name"}
    assert procs == {f"fleet {spool}", "job job-a", "job job-b"}
    assert "job-a [done]" in names                        # lifecycle span
    assert "boot 0 [hang]" in names and "boot 1 [success]" in names
    assert "alert:stall" in names                         # firing span
    assert "fault:hang:sec=5@chunk=2" in names            # instant
    assert "breaker_open" in names
    spans = {e["name"]: e for e in evs if e["ph"] == "X"}
    # the alert fired DURING boot 0 and resolved inside boot 1
    assert spans["boot 0 [hang]"]["ts"] <= spans["alert:stall"]["ts"]
    # job-b's live boot extends to the global horizon (t0+30), which
    # only the ring knows about -- not to the fleet journal's end
    live = spans["boot 0 [live]"]
    assert live["ts"] + live["dur"] == pytest.approx(30e6)
    counters = [e for e in evs if e["ph"] == "C"]
    assert len(counters) == 5                             # the ring rows
    assert any(e["name"].startswith("chunk ->u") for e in evs)
    # and the CLI writes a loadable json
    out = os.path.join(spool, "fleet.trace.json")
    assert trace_tool.main(["fleet", spool, "-o", out]) == 0
    assert json.load(open(out))["otherData"]["jobs"] == ["job-a",
                                                         "job-b"]


# ---------------------------------------------------------------------------
# the engine is untouched: bit-identity + jaxpr gate (compiles one
# small world program, shared by both runs)
# ---------------------------------------------------------------------------

_WORLD_OVERRIDES = [
    ("WORLD_X", 6), ("WORLD_Y", 6), ("TPU_MAX_MEMORY", 128),
    ("RANDOM_SEED", 19), ("AVE_TIME_SLICE", 30),
    ("TPU_MAX_STEPS_PER_UPDATE", 30), ("TPU_SYSTEMATICS", 0),
    ("TPU_MAX_STRETCH", 4), ("TPU_METRICS", 1),
]


def _run_world(data_dir, updates=12):
    from avida_tpu.world import World
    w = World(overrides=list(_WORLD_OVERRIDES), data_dir=str(data_dir))
    w.run(max_updates=updates)
    return w


def test_trajectory_bit_identical_history_on_vs_off(tmp_path, monkeypatch):
    from avida_tpu.core.state import state_field_names
    monkeypatch.setenv("TPU_METRICS_HIST", "1")
    w_on = _run_world(tmp_path / "on")
    on_ring = history.hist_path(str(tmp_path / "on" / "metrics.prom"))
    assert history.read_samples(on_ring), "ring missing with hist on"
    state_on = {n: np.asarray(getattr(w_on.state, n))
                for n in state_field_names()
                if getattr(w_on.state, n) is not None}
    monkeypatch.setenv("TPU_METRICS_HIST", "0")
    w_off = _run_world(tmp_path / "off")
    assert not os.path.exists(
        history.hist_path(str(tmp_path / "off" / "metrics.prom")))
    assert w_on.update == w_off.update
    for n in sorted(state_on):
        np.testing.assert_array_equal(
            state_on[n], np.asarray(getattr(w_off.state, n)),
            err_msg=f"state leaf {n} differs with history on vs off")
    # the snapshots themselves stayed byte-compatible (minus the
    # wall-clock heartbeat line, which differs by construction)
    def strip_hb(p):
        return [line for line in open(p)
                if "heartbeat_timestamp" not in line]
    assert strip_hb(tmp_path / "on" / "metrics.prom") \
        == strip_hb(tmp_path / "off" / "metrics.prom")


def test_jaxpr_digest_unchanged_with_history_on(monkeypatch):
    """The plane is host-side only: with the knobs armed, the solo
    update_step still traces to the recorded program."""
    monkeypatch.setenv("TPU_METRICS_HIST", "1")
    monkeypatch.setenv("TPU_METRICS", "1")
    monkeypatch.setenv("TPU_ALERT_EVAL_SEC", "1")
    import check_jaxpr
    ok, msg = check_jaxpr.check()
    assert ok, msg


# ---------------------------------------------------------------------------
# the acceptance drill: injected hang -> stall alert fires and journals
# BEFORE the watchdog kill, resolves after recovery (real subprocesses)
# ---------------------------------------------------------------------------

def _drill_env():
    env = dict(os.environ)
    env.pop("TPU_FAULT", None)
    env.pop("JAX_COMPILATION_CACHE_DIR", None)   # PR-6 landmine
    env["JAX_PLATFORMS"] = "cpu"
    env["TPU_ALERT_EVAL_SEC"] = "0.5"
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env["PYTHONPATH"] = repo + (
        os.pathsep + env["PYTHONPATH"] if env.get("PYTHONPATH") else "")
    return env


@pytest.mark.slow
def test_supervised_hang_drill_stall_alert_fires_before_watchdog(tmp_path):
    data, ck = str(tmp_path / "data"), str(tmp_path / "ck")
    os.makedirs(data)
    # tighten the stall window so the drill fits CI time: the injected
    # hang is 45s, the watchdog 14s, the stall window 6s -- the alert
    # must fire in the gap between hang onset and the SIGKILL
    with open(os.path.join(data, "alerts.json"), "w") as f:
        json.dump([{"name": "stall", "family": "avida_update",
                    "kind": "rate", "op": "<=", "value": 0.0,
                    "window_sec": 6.0, "severity": "page"},
                   {"name": "heartbeat_stale",
                    "family": "avida_heartbeat_timestamp_seconds",
                    "kind": "staleness", "value": 6.0,
                    "severity": "page"}], f)
    argv = ["-s", "11", "-u", "20", "-d", data,
            "-set", "TPU_CKPT_DIR", ck]
    for name, value in [("WORLD_X", "8"), ("WORLD_Y", "8"),
                        ("TPU_MAX_MEMORY", "256"),
                        ("AVE_TIME_SLICE", "100"),
                        ("TPU_MAX_STEPS_PER_UPDATE", "100"),
                        ("TPU_SYSTEMATICS", "0"),
                        ("TPU_MAX_STRETCH", "2"),
                        ("TPU_CKPT_EVERY", "4"),
                        ("TPU_CKPT_FINAL", "1")]:
        argv += ["-set", name, value]
    sup = Supervisor(
        argv, fault_plan=["hang:sec=45@chunk=2"],
        cfg=SupervisorConfig(watchdog_sec=14.0, poll_sec=0.25,
                             grace_sec=600.0, max_retries=6,
                             backoff_base=0.05, backoff_cap=0.2,
                             healthy_sec=1e9, seed=3),
        env=_drill_env())
    rc = sup.run()
    assert rc == 0
    assert sup.failures["hang"] == 1 and sup.watchdog_kills == 1

    recs = alerts.read_alert_records(os.path.join(data, "alerts.jsonl"))
    stall = [(r["state"], r["time"]) for r in recs
             if r["rule"] == "stall"]
    assert ("firing" in [s for s, _ in stall]), recs
    fire_t = min(t for s, t in stall if s == "firing")
    sup_recs = [json.loads(line) for line in
                open(os.path.join(data, "supervisor.jsonl"))]
    kills = [r["time"] for r in sup_recs
             if r["event"] == "watchdog_kill"]
    assert kills, sup_recs
    # the alert plane saw the stall BEFORE the watchdog acted
    assert fire_t < kills[0], (fire_t, kills)
    # and recovery resolved it
    assert ("resolved" in [s for s, _ in stall]), recs
    resolve_t = max(t for s, t in stall if s == "resolved")
    assert resolve_t > kills[0]
    # the firing left durable evidence on the .prom spine + --status
    from avida_tpu.observability.exporter import read_metrics
    m = read_metrics(os.path.join(data, "supervisor.prom"))
    assert m['avida_alerts_fired_total{rule="stall"}'] >= 1
    assert m['avida_alerts_firing{rule="stall"}'] == 0      # resolved
    assert "alerts" in alerts.format_alert_status(m)
    # the run itself completed to its budget
    final = read_metrics(os.path.join(data, "metrics.prom"))
    assert final["avida_update"] == 20
