"""Analyze-mode tests: the batch VM over saved populations.

Models the reference's analyze consistency scenarios (tests/analyze_*,
_analyze_detail_all): LOAD a .spop, RECALCULATE, DETAIL, TRACE, knockouts.
"""

import os

import pytest

from avida_tpu.analyze.analyzer import Analyzer, AnalyzeGenotype
from avida_tpu.config import AvidaConfig, default_instset
from avida_tpu.config.environment import default_logic9_environment
from avida_tpu.core.state import make_world_params
from avida_tpu.utils.spop import _seq_to_string
from avida_tpu.world import default_ancestor


@pytest.fixture(scope="module")
def setup():
    cfg = AvidaConfig()
    cfg.WORLD_X = 1
    cfg.WORLD_Y = 1
    cfg.TPU_MAX_MEMORY = 320
    iset = default_instset()
    params = make_world_params(cfg, iset, default_logic9_environment())
    return params, iset, default_ancestor(iset)


def test_load_sequence_recalculate_detail(setup, tmp_path):
    params, iset, anc = setup
    az = Analyzer(params, iset, data_dir=str(tmp_path))
    az.run_command(f"LOAD_SEQUENCE {_seq_to_string(anc)}")
    az.run_command("RECALCULATE")
    g = az.batch[0]
    assert g.viable and g.gestation_time == 389
    assert g.fitness == pytest.approx(97.0 / 389.0)
    az.run_command("DETAIL ancestor.dat id fitness gestation_time length sequence")
    text = (tmp_path / "ancestor.dat").read_text()
    rows = [l for l in text.splitlines() if l and not l.startswith("#")]
    assert len(rows) == 1
    assert "389" in rows[0]


def test_load_spop_roundtrip(setup, tmp_path):
    params, iset, anc = setup
    # build a little world, save .spop, then LOAD it in analyze mode
    from avida_tpu.world import World
    cfg = AvidaConfig()
    cfg.WORLD_X = 8
    cfg.WORLD_Y = 8
    cfg.RANDOM_SEED = 3
    cfg.TPU_MAX_MEMORY = 320
    w = World(cfg=cfg, data_dir=str(tmp_path))
    w.inject()
    for _ in range(30):
        w.run_update()
        w.update += 1
    w._action_SavePopulation([])
    spop = tmp_path / f"detail-{w.update}.spop"
    assert spop.exists()

    az = Analyzer(w.params, iset, data_dir=str(tmp_path))
    az.run_command(f"LOAD {spop}")
    assert len(az.batch) >= 1
    az.run_command("RECALCULATE")
    az.run_command("FILTER fitness > 0")
    assert all(g.fitness > 0 for g in az.batch)
    az.run_command("FIND_GENOTYPE num_cpus")
    assert len(az.batch) == 1


def test_trace(setup, tmp_path):
    params, iset, anc = setup
    az = Analyzer(params, iset, data_dir=str(tmp_path))
    az.run_command(f"LOAD_SEQUENCE {_seq_to_string(anc)}")
    az.run_command("TRACE")
    files = os.listdir(tmp_path / "trace")
    assert len(files) == 1
    text = (tmp_path / "trace" / files[0]).read_text()
    assert "DIVIDE" in text
    # 389 executed cycles to first divide
    assert "U:389" in text


def test_knockouts(setup, tmp_path):
    params, iset, anc = setup
    az = Analyzer(params, iset, data_dir=str(tmp_path))
    # a short region: knock out only sites 90..99 to keep runtime modest ->
    # use a truncated batch trick: full genome knockout is covered by the
    # command; here we just assert the output exists and counts sum to L
    az.batch.append(AnalyzeGenotype(anc, 1))
    az.run_command("ANALYZE_KNOCKOUTS ko.dat")
    rows = [l for l in (tmp_path / "ko.dat").read_text().splitlines()
            if l and not l.startswith("#")]
    vals = rows[0].split()
    length, counts = int(vals[1]), [int(v) for v in vals[2:6]]
    assert length == len(anc)
    assert sum(counts) == length
    assert counts[0] > 0          # some sites are lethal (the divide, copy loop)
    assert counts[2] > 40         # the nop-C spacer region is neutral


def test_align_map_lineage_recombine(setup, tmp_path):
    """Round-4 analyze breadth (VERDICT r3 directive #10): ALIGN,
    MAP_MUTATIONS, FIND_LINEAGE, RECOMBINE."""
    params, iset, anc = setup
    az = Analyzer(params, iset, data_dir=str(tmp_path))
    seq = _seq_to_string(anc)
    az.run_command(f"LOAD_SEQUENCE {seq}")
    az.run_command(f"LOAD_SEQUENCE {seq}")
    # second genotype: a 2-site variant plus lineage link to the first
    az.batch[1].sequence = az.batch[1].sequence.copy()
    az.batch[1].sequence[10] = (az.batch[1].sequence[10] + 1) % params.num_insts
    az.batch[0].src_id = 1
    az.batch[0].parent_src = -1
    az.batch[1].src_id = 2
    az.batch[1].parent_src = 1
    az.batch[1].num_cpus = 5

    az.run_command("ALIGN")
    assert hasattr(az.batch[0], "alignment")
    # gaps only ever pad; stripping them recovers the raw letter sequence
    assert az.batch[1].alignment.replace("_", "") == \
        _seq_to_string(az.batch[1].sequence)
    assert az.batch[0].alignment.replace("_", "") == \
        _seq_to_string(az.batch[0].sequence)

    az.run_command("FIND_LINEAGE num_cpus")
    assert [g.src_id for g in az.batch] == [1, 2]   # root first

    before = len(az.batch)
    az.run_command("RECOMBINE")
    assert len(az.batch) > before                   # recombinant appended

    # MAP_MUTATIONS on a short synthetic genome (keep the mutant batch small)
    az2 = Analyzer(params, iset, data_dir=str(tmp_path))
    az2.run_command(f"LOAD_SEQUENCE {_seq_to_string(anc[:20])}")
    az2.run_command("MAP_MUTATIONS mm")
    files = os.listdir(tmp_path / "mm")
    assert len(files) == 1
    lines = (tmp_path / "mm" / files[0]).read_text().strip().splitlines()
    assert len(lines) == 1 + 20                     # header + one row/site


def test_analyze_modularity(tmp_path):
    """ANALYZE_MODULARITY (cModularityAnalysis::CalcFunctionalModularity):
    knockout-based task-site attribution on a task-performing genotype."""
    from avida_tpu.analyze.analyzer import Analyzer, AnalyzeGenotype
    from avida_tpu.config.instset import default_instset
    from avida_tpu.config.environment import default_logic9_environment
    from avida_tpu.core.state import make_world_params
    from avida_tpu.config import AvidaConfig
    from avida_tpu.world import default_ancestor

    cfg = AvidaConfig()
    cfg.WORLD_X = 2
    cfg.WORLD_Y = 2
    cfg.TPU_MAX_MEMORY = 320
    s = default_instset()
    p = make_world_params(cfg, s, default_logic9_environment())
    a = Analyzer(p, s, data_dir=str(tmp_path))
    # hand-build a replicator that performs NOT: nand;nand;IO on BX
    anc = default_ancestor(s).copy()
    nand, io = s.opcode("nand"), s.opcode("IO")
    anc[10:13] = [io, nand, io]   # IO(read) -> nand -> IO(output ~A)
    a.batch.append(AnalyzeGenotype(anc, 1))
    a.run_command("ANALYZE_MODULARITY mod.dat")
    rows = [ln.split() for ln in open(tmp_path / "mod.dat").read().splitlines()
            if ln and not ln.startswith("#")]
    assert len(rows) == 1
    # columns: id, tasks done, insts in tasks, proportion, ...
    assert rows[0][0] == "1"
    # the file is well-formed regardless of whether this crafted genome
    # earns a task; if it does, sites must be attributed
    if int(rows[0][1]) > 0:
        assert int(rows[0][2]) > 0
