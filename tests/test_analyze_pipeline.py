"""Checkpoint-native analytics pipeline tests (analyze/pipeline.py).

Covers: census/knockout/lineage over a real archived checkpoint,
corrupt-generation fallback matching resume behavior, live-mode census
freshness (within one checkpoint interval) with bit-identical
trajectories analytics-on vs -off, the jaxpr-digest gate proving
`--analyze` never perturbs update_step, the Test-CPU bucket-padding
compile-count probe, and the ckpt_tool --detail triage column.

The packed-chunk-era equivalence drill (TPU_PACKED_CHUNK=1 checkpoints
analyze identically to per-update-era ones) runs chunked worlds on the
interpret-mode Pallas path and is slow-marked.
"""

from __future__ import annotations

import json
import os
import shutil
import sys

import numpy as np
import pytest

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..",
                                "scripts"))

from avida_tpu.analyze import pipeline as pl  # noqa: E402
from avida_tpu.config import AvidaConfig  # noqa: E402
from avida_tpu.world import World  # noqa: E402


def _mk_world(tmp, seeds=(10, 11, 20, 21, 27), overrides=(), world=6,
              max_memory=200, seed=3):
    cfg = AvidaConfig()
    cfg.WORLD_X = world
    cfg.WORLD_Y = world
    cfg.TPU_MAX_MEMORY = max_memory
    cfg.RANDOM_SEED = seed
    cfg.AVE_TIME_SLICE = 120
    for k, v in overrides:
        cfg.set(k, v)
    w = World(cfg=cfg, data_dir=os.path.join(tmp, "data"))
    for c in seeds:
        w.inject(cell=c)
    return w


@pytest.fixture(scope="module")
def archived_run(tmp_path_factory):
    """A real archived run: 6x6 world, systematics on, two checkpoint
    generations (updates 10 and 20) under <tmp>/ck."""
    tmp = str(tmp_path_factory.mktemp("pipeline-run"))
    ck = os.path.join(tmp, "ck")
    # TPU_CKPT_AUDIT=0: skip the save-time invariant sweep's one-off
    # compile (tier-1 budget; the PR-6 chaos-test precedent)
    w = _mk_world(tmp, overrides=(("TPU_CKPT_DIR", ck),
                                  ("TPU_CKPT_KEEP", 4),
                                  ("TPU_CKPT_AUDIT", 0)))
    for _ in range(10):
        w.run_update()
        w.update += 1
    w.save_checkpoint(ck)
    for _ in range(10):
        w.run_update()
        w.update += 1
    w.save_checkpoint(ck)
    return {"world": w, "ck": ck, "tmp": tmp, "update": w.update}


def test_census_knockout_lineage_offline(archived_run, tmp_path):
    w = archived_run["world"]
    tables = pl.load_run_tables(archived_run["ck"])
    assert tables.update == archived_run["update"]
    assert not tables.rebuilt                      # sidecar present
    assert tables.arbiter.num_genotypes == w.systematics.num_genotypes

    pipe = pl.AnalyticsPipeline(w.params, w.environment.task_names(),
                                str(tmp_path), knockout_top=1)
    summary = pipe.run(tables)

    # census: one row per live genotype, dominant first
    census = pipe.census(tables)
    assert len(census) == tables.arbiter.num_genotypes
    dom = tables.arbiter.dominant()
    assert census[0]["gid"] == dom.gid
    assert summary["dominant"]["gid"] == dom.gid
    assert summary["genotypes"] == len(census)
    # the seed ancestor genotype (depth 0) must be viable at the known
    # reference life history
    root_rows = [r for r in census if r["depth"] == 0]
    assert root_rows and any(
        r["viable"] and r["gestation"] == 389 for r in root_rows)

    # knockout: counts partition the genome
    ko = pipe.knockouts(tables)
    assert len(ko) == 1 and ko[0]["gid"] == dom.gid
    assert (ko[0]["lethal"] + ko[0]["detrimental"] + ko[0]["neutral"]
            + ko[0]["beneficial"]) == ko[0]["length"]
    assert ko[0]["lethal"] > 0                     # copy loop / divide

    # lineage: root-first walk ending at the dominant genotype
    lin = pipe.lineage(tables)
    assert lin[0]["parent_gid"] == -1 or lin[0]["depth"] == 0
    assert lin[-1]["gid"] == dom.gid
    assert [r["depth"] for r in lin] == list(range(len(lin)))

    # the observability spine: tables + runlog + prom
    for name in ("census.dat", "knockout.dat", "lineage.dat"):
        assert os.path.exists(os.path.join(str(tmp_path), "analysis",
                                           name))
    recs = [json.loads(line) for line in
            open(os.path.join(str(tmp_path), "analysis",
                              "analytics.jsonl"))]
    assert recs and recs[0]["record"] == "analytics"
    assert recs[0]["update"] == tables.update
    prom = open(os.path.join(str(tmp_path), "analytics.prom")).read()
    assert f"avida_analytics_census_update {tables.update}" in prom
    assert "avida_analytics_dominant_genotype_id" in prom

    # repeat genotypes are content-keyed: a second census evaluates none
    before = pipe.metrics.evaluations
    pipe.census(tables)
    assert pipe.metrics.evaluations == before

    # trace_tool's summary understands the analytics records
    import trace_tool
    text = trace_tool.summary(os.path.join(str(tmp_path), "analysis",
                                           "analytics.jsonl"))
    assert "analytics records" in text and "dominant gid" in text


def test_corrupt_generation_falls_back_like_resume(archived_run,
                                                   tmp_path):
    from avida_tpu.utils import checkpoint as ckpt_mod
    ck = os.path.join(str(tmp_path), "ck")
    shutil.copytree(archived_run["ck"], ck)
    gens = ckpt_mod.list_generations(ck)
    newest = gens[-1]
    gpath = os.path.join(newest, "state.genome.npy")
    blob = bytearray(open(gpath, "rb").read())
    blob[len(blob) // 2] ^= 0xFF
    open(gpath, "wb").write(bytes(blob))

    skipped = []
    tables = pl.load_run_tables(
        ck, on_skip=lambda path, err: skipped.append(path))
    # the pipeline lands on exactly the generation a resume would
    resume_path, manifest = ckpt_mod.latest_valid(ck, on_skip=lambda *a: None)
    assert tables.path == resume_path
    assert tables.update == int(manifest["update"]) < archived_run["update"]
    assert skipped == [newest]


def test_analyze_cli_and_jaxpr_gate(archived_run, tmp_path, capsys):
    """`--analyze CKPT_DIR` runs offline (no World.run) and the
    update_step digest recorded AFTER the pipeline ran in this process
    still matches the snapshot -- analytics never perturbs the
    production update program."""
    from avida_tpu.__main__ import main
    # config matches the archived run's so the Test-CPU programs
    # compiled by the earlier tests are reused (tier-1 budget)
    rc = main(["--analyze", archived_run["ck"], "-d", str(tmp_path),
               "-set", "WORLD_X", "6", "-set", "WORLD_Y", "6",
               "-set", "AVE_TIME_SLICE", "120"])
    assert rc == 0
    out = capsys.readouterr().out
    assert "census" in out and "dominant" in out
    assert os.path.exists(os.path.join(str(tmp_path), "analytics.prom"))

    import check_jaxpr
    ok, msg = check_jaxpr.check()
    assert ok, f"--analyze perturbed update_step: {msg}"


def test_ckpt_tool_detail_column(archived_run, capsys):
    import ckpt_tool
    rc = ckpt_tool.main([archived_run["ck"], "--detail"])
    assert rc == 0
    out = capsys.readouterr().out
    assert "dominant gid" in out and "live" in out and "tasks" in out


def test_bucket_padding_compile_count():
    """Distinct batch sizes inside one power-of-two bucket share a
    single compiled gestation program (the trace-count probe)."""
    from avida_tpu.analyze.testcpu import (evaluate_genomes,
                                           gestation_trace_count)
    from avida_tpu.config import default_instset
    from avida_tpu.config.environment import default_logic9_environment
    from avida_tpu.core.state import make_world_params

    cfg = AvidaConfig()
    cfg.WORLD_X = 1
    cfg.WORLD_Y = 1
    cfg.TPU_MAX_MEMORY = 64
    params = make_world_params(cfg, default_instset(),
                               default_logic9_environment())

    def batch(g):
        genomes = np.zeros((g, 64), np.int8)
        genomes[:, :4] = 2              # inert nop ball: cheap gestation
        return genomes, np.full(g, 4, np.int32)

    evaluate_genomes(params, *batch(8))            # warm bucket 8
    c0 = gestation_trace_count()
    for g in (5, 6, 7, 8):
        r = evaluate_genomes(params, *batch(g))
        assert r.viable.shape == (g,)              # sliced back to G
        assert not r.viable.any()
    assert gestation_trace_count() == c0           # no new compiles
    evaluate_genomes(params, *batch(3))            # bucket 4: one more
    assert gestation_trace_count() == c0 + 1


def test_live_census_freshness_and_bit_identical(tmp_path):
    """TPU_ANALYTICS=1: `--status` census is no staler than one
    checkpoint interval on a finished run, and the evolved trajectory is
    bit-identical with analytics on or off."""
    def run(tag, analytics):
        tmp = os.path.join(str(tmp_path), tag)
        ck = os.path.join(tmp, "ck")
        # TPU_MAX_STRETCH=1 keeps the run on the chunk-of-1 program the
        # module fixture already compiled (host-side knob: same params,
        # same jit cache entry) -- checkpoint boundaries land every
        # update, the auto-save cadence stays TPU_CKPT_EVERY
        ov = [("TPU_CKPT_DIR", ck), ("TPU_CKPT_EVERY", 8),
              ("TPU_METRICS", 1), ("TPU_MAX_STRETCH", 1),
              ("TPU_CKPT_AUDIT", 0)]
        if analytics:
            ov.append(("TPU_ANALYTICS", 1))
        w = _mk_world(tmp, overrides=tuple(ov))
        w.run(max_updates=20)
        return w

    wa = run("on", True)
    wb = run("off", False)

    # freshness: the census update is within one TPU_CKPT_EVERY of the
    # run's final update (the exit refresh actually makes it equal)
    from avida_tpu.observability.exporter import read_metrics
    ana = read_metrics(os.path.join(wa.data_dir, "analytics.prom"))
    assert ana["avida_analytics_census_update"] >= wa.update - 8
    assert not os.path.exists(os.path.join(wb.data_dir, "analytics.prom"))

    # --status shows the analytics line
    from avida_tpu.observability.exporter import status_main
    assert status_main(wa.data_dir) == 0

    # bit-identical trajectories (nb_* rows past nb_count are drain
    # scratch; compare the canonical fields)
    import jax
    for name in ("alive", "genome", "genome_len", "tape", "merit",
                 "fitness", "gestation_time", "birth_update"):
        np.testing.assert_array_equal(
            np.asarray(getattr(wa.state, name)),
            np.asarray(getattr(wb.state, name)), err_msg=name)
    np.testing.assert_array_equal(
        np.asarray(jax.random.key_data(wa._run_key)),
        np.asarray(jax.random.key_data(wb._run_key)))


@pytest.mark.slow
def test_packed_chunk_era_checkpoints_analyze_identically(tmp_path):
    """A TPU_PACKED_CHUNK=1 run's checkpoints (packed-resident engine,
    systematics off) analyze identically to the per-update engine's:
    same census, same dominant, same tasks -- the pipeline is
    engine-agnostic because the chunk-boundary unpack restores canonical
    state before every save."""
    def run(tag, packed):
        tmp = os.path.join(str(tmp_path), tag)
        ck = os.path.join(tmp, "ck")
        w = _mk_world(tmp, overrides=(
            ("TPU_USE_PALLAS", 1),          # interpret mode on CPU
            ("TPU_SYSTEMATICS", 0),         # packed eligibility
            ("TPU_LANE_PERM", 0),           # identity lanes on BOTH
            # engines (packed residency forces identity; the per-update
            # comparator must share the per-lane PRNG streams)
            ("TPU_PACKED_CHUNK", packed),
            ("TPU_CKPT_DIR", ck), ("TPU_CKPT_EVERY", 8),
            ("TPU_CKPT_FINAL", 1), ("TPU_CKPT_AUDIT", 0)))
        w.run(max_updates=16)
        return w, ck

    wp, ck_packed = run("packed", 1)
    wu, ck_plain = run("plain", 0)

    tp = pl.load_run_tables(ck_packed)
    tu = pl.load_run_tables(ck_plain)
    assert tp.update == tu.update
    assert tp.rebuilt and tu.rebuilt       # no sidecar: rebuilt tables
    np.testing.assert_array_equal(tp.alive, tu.alive)
    np.testing.assert_array_equal(tp.genome, tu.genome)

    pa = pl.AnalyticsPipeline(wp.params, wp.environment.task_names(),
                              os.path.join(str(tmp_path), "a"),
                              knockout_top=0)
    pb = pl.AnalyticsPipeline(wu.params, wu.environment.task_names(),
                              os.path.join(str(tmp_path), "b"),
                              knockout_top=0)
    ca = pa.run(tp, knockouts=False)
    cb = pb.run(tu, knockouts=False)
    for key in ("genotypes", "organisms", "tasks_held_mask",
                "lineage_depth"):
        assert ca[key] == cb[key], key
    assert (ca["dominant"] or {}).get("fitness") == \
        (cb["dominant"] or {}).get("fitness")
