"""BIRTH_METHOD 0-8 placement + POPULATION_CAP carrying capacity.

Reference: cPopulation::PositionOffspring (cPopulation.cc:5185, the 12
ePOSITION_OFFSPRING methods from core/Definitions.h:67-82) and the
pop-cap kill paths (cc:5192-5238).
"""

from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from avida_tpu.config import AvidaConfig
from avida_tpu.config.environment import default_logic9_environment
from avida_tpu.config.instset import default_instset
from avida_tpu.core.state import make_world_params, zeros_population
from avida_tpu.ops import birth as birth_ops


def _params(**kw):
    cfg = AvidaConfig()
    cfg.WORLD_X = 6
    cfg.WORLD_Y = 6
    cfg.TPU_MAX_MEMORY = 64
    for k, v in kw.items():
        cfg.set(k, v)
    return make_world_params(cfg, default_instset(),
                             default_logic9_environment())


def _pending_world(params, parents=(14,), fill=()):
    n, L, R = params.num_cells, params.max_memory, params.num_reactions
    st = zeros_population(n, L, R)
    tape = np.zeros((n, L), np.uint8)
    alive = np.zeros(n, bool)
    pend = np.zeros(n, bool)
    age = np.zeros(n, np.int32)
    merit = np.zeros(n, np.float32)
    for c in parents:
        tape[c, :20] = 2
        alive[c] = pend[c] = True
        merit[c] = 10.0
    for i, c in enumerate(fill):
        alive[c] = True
        age[c] = 10 + i * 10          # increasing ages
        merit[c] = 1.0 + i            # increasing merits
    return st.replace(
        tape=jnp.asarray(tape), genome=jnp.asarray(tape.astype(np.int8)),
        alive=jnp.asarray(alive), merit=jnp.asarray(merit),
        time_used=jnp.asarray(age),
        divide_pending=jnp.asarray(pend),
        off_len=jnp.where(jnp.asarray(pend), 20, 0),
        mem_len=jnp.where(jnp.asarray(alive), 20, 0),
        genome_len=jnp.where(jnp.asarray(alive), 20, 0),
    )


def _flush(params, st, seed=0):
    neighbors = jnp.asarray(birth_ops.neighbor_table(
        params.world_x, params.world_y, params.geometry))
    return birth_ops.flush_births(params, st, jax.random.key(seed),
                                  neighbors, jnp.int32(0))


def _newborn_cells(st0, st1):
    return np.nonzero(np.asarray(st1.alive) & ~np.asarray(st0.alive))[0]


def test_birth_method_1_replaces_oldest_neighbor():
    params = _params(BIRTH_METHOD=1, ALLOW_PARENT=0)
    # parent at 14; neighbors 13 and 15 occupied, 15 older; rest empty ->
    # empties win first
    st = _pending_world(params, parents=(14,), fill=(13, 15))
    st1 = _flush(params, st)
    born = _newborn_cells(st, st1)
    assert len(born) == 1 and born[0] not in (13, 15)   # empty preferred
    # now fill the entire neighborhood: oldest (highest fill index) dies
    neigh = birth_ops.neighbor_table(params.world_x, params.world_y, 2)[14]
    st2 = _pending_world(params, parents=(14,), fill=tuple(neigh))
    st3 = _flush(params, st2)
    # the newborn landed on the OLDEST neighbor
    ages = {c: 10 + i * 10 for i, c in enumerate(neigh)}
    oldest = max(neigh, key=lambda c: ages[c])
    assert bool(np.asarray(st3.birth_update)[oldest] == 0)


def test_birth_method_2_replaces_lowest_merit_neighbor():
    params = _params(BIRTH_METHOD=2, ALLOW_PARENT=0)
    neigh = birth_ops.neighbor_table(params.world_x, params.world_y, 2)[14]
    st = _pending_world(params, parents=(14,), fill=tuple(neigh))
    st1 = _flush(params, st)
    lowest = min(neigh, key=lambda c: 1.0 + list(neigh).index(c))
    assert bool(np.asarray(st1.birth_update)[lowest] == 0)


def test_birth_method_3_requires_empty_cell():
    params = _params(BIRTH_METHOD=3, ALLOW_PARENT=0)
    neigh = birth_ops.neighbor_table(params.world_x, params.world_y, 2)[14]
    st = _pending_world(params, parents=(14,), fill=tuple(neigh))
    st1 = _flush(params, st)
    # neighborhood full: no birth, parent still pending
    assert len(_newborn_cells(st, st1)) == 0
    assert bool(st1.divide_pending[14])


def test_birth_method_4_full_soup_random():
    params = _params(BIRTH_METHOD=4)
    st = _pending_world(params, parents=(14,))
    # across seeds, births land beyond the 8-neighborhood
    neigh = set(birth_ops.neighbor_table(params.world_x, params.world_y,
                                         2)[14].tolist()) | {14}
    landed = set()
    for s in range(8):
        st1 = _flush(params, st, seed=s)
        landed.update(_newborn_cells(st, st1).tolist())
    assert landed - neigh, landed


def test_birth_method_5_replaces_global_eldest():
    params = _params(BIRTH_METHOD=5)
    # full world (empty cells count as trivially oldest, so fill them all):
    # the oldest organism dies for the newborn
    fill = tuple(c for c in range(36) if c != 14)
    st = _pending_world(params, parents=(14,), fill=fill)
    st1 = _flush(params, st)
    oldest = fill[-1]                 # highest age in _pending_world
    assert bool(np.asarray(st1.birth_update)[oldest] == 0)


def test_birth_method_8_next_cell():
    params = _params(BIRTH_METHOD=8)
    st = _pending_world(params, parents=(14,))
    st1 = _flush(params, st)
    assert _newborn_cells(st, st1).tolist() == [15]


def test_population_cap_kills_excess():
    params = _params(POPULATION_CAP=5)
    st = _pending_world(params, parents=(14,),
                        fill=tuple(range(8)))    # 9 alive, cap 5
    st1 = _flush(params, st)
    assert int(np.asarray(st1.alive).sum()) == 5


def test_pop_cap_eldest_kills_oldest():
    params = _params(POP_CAP_ELDEST=6)
    st = _pending_world(params, parents=(14,), fill=tuple(range(8)))
    st1 = _flush(params, st)
    alive = np.asarray(st1.alive)
    assert alive.sum() == 6
    # the oldest fills (highest ages: cells 6,7 at ages 70,80) died first
    assert not alive[7] and not alive[6]


def test_birth_method_7_uses_real_facing_on_experimental_hw():
    """BIRTH_METHOD 7 (PARENT_FACING, cPopulation.cc:5259): on hw 3 the
    offspring lands one step in the parent's facing direction."""
    from avida_tpu.config.instset import experimental_instset

    cfg = AvidaConfig()
    cfg.WORLD_X = 5
    cfg.WORLD_Y = 5
    cfg.BIRTH_METHOD = 7
    p = make_world_params(cfg, experimental_instset(),
                          default_logic9_environment())
    n, L = p.num_cells, p.max_memory
    st = zeros_population(n, L, p.num_reactions,
                          num_registers=p.num_registers)
    st = st.replace(
        alive=st.alive.at[12].set(True),
        merit=jnp.ones(n, jnp.float32),
        divide_pending=st.divide_pending.at[12].set(True),
        off_len=jnp.zeros(n, jnp.int32).at[12].set(12),
        off_tape=jnp.zeros((n, L), jnp.uint8).at[12, :12].set(3),
        facing=st.facing.at[12].set(2))   # ring dir 2 = east -> cell 13
    neighbors = jnp.asarray(birth_ops.neighbor_table(5, 5, 2))
    st2 = birth_ops.flush_births(p, st, jax.random.key(1), neighbors,
                                 jnp.int32(1), use_off_tape=True)
    born = np.nonzero(np.asarray(st2.alive) & ~np.asarray(st.alive))[0]
    assert list(born) == [13], born


def test_birth_method_7_invalid_facing_drops_offspring():
    """BIRTH_METHOD 7 on experimental hardware with BOUNDED geometry: an
    edge parent facing off-grid can never place its offspring (the
    reference cannot reach this state -- its facing indexes the in-grid
    connection list).  The offspring must be dropped and divide_pending
    cleared so the parent resumes executing; the pre-fix retry path left
    divide_pending set forever, excluding the parent from exec_mask --
    a permanent livelock (round-5 advisor finding)."""
    from avida_tpu.config.instset import experimental_instset

    cfg = AvidaConfig()
    cfg.WORLD_X = 5
    cfg.WORLD_Y = 5
    cfg.BIRTH_METHOD = 7
    cfg.WORLD_GEOMETRY = 1         # bounded grid: edges exist
    p = make_world_params(cfg, experimental_instset(),
                          default_logic9_environment())
    assert p.geometry == 1
    n, L = p.num_cells, p.max_memory
    st = zeros_population(n, L, p.num_reactions,
                          num_registers=p.num_registers)
    st = st.replace(
        alive=st.alive.at[0].set(True),        # NW corner
        merit=jnp.ones(n, jnp.float32),
        divide_pending=st.divide_pending.at[0].set(True),
        off_len=jnp.zeros(n, jnp.int32).at[0].set(12),
        off_tape=jnp.zeros((n, L), jnp.uint8).at[0, :12].set(3),
        mem_len=st.mem_len.at[0].set(12),
        genome_len=st.genome_len.at[0].set(12),
        facing=st.facing.at[0].set(0))         # facing 0 = north: off-grid
    neighbors = jnp.asarray(birth_ops.neighbor_table(5, 5, 1))
    st2 = birth_ops.flush_births(p, st, jax.random.key(1), neighbors,
                                 jnp.int32(1), use_off_tape=True)
    # no birth anywhere, offspring dropped, parent resumed
    assert np.asarray(st2.alive).sum() == 1
    assert bool(st2.alive[0])
    assert not bool(st2.divide_pending[0]), \
        "invalid-facing parent stayed divide-pending (livelock)"
    # an in-grid facing on the same bounded world still births normally
    st3 = st.replace(facing=st.facing.at[0].set(2))    # east -> cell 1
    st4 = birth_ops.flush_births(p, st3, jax.random.key(1), neighbors,
                                 jnp.int32(1), use_off_tape=True)
    born = np.nonzero(np.asarray(st4.alive) & ~np.asarray(st3.alive))[0]
    assert list(born) == [1], born
    assert not bool(st4.divide_pending[0])
