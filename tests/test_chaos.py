"""Chaos suite: the self-healing proof, with REAL child processes.

A supervised run is SIGKILLed / hung / corrupted at seeded, injected
fault points (utils/faultinject.py), the supervisor
(service/supervisor.py) recovers it without human input, and the final
PopulationState is BIT-EXACT versus an uninterrupted run -- read from
the TPU_CKPT_FINAL generation, so the pytest process never compiles the
world itself.

Tier split (1-core host: children run sequentially, never concurrent
with other jax work): one single-SIGKILL recovery proof stays in
tier-1; the multi-kill, Pallas-path, hang-watchdog and
corrupt-checkpoint proofs are `slow`.  Every child boot pays its own
jit compile -- see _env() for why the persistent compilation cache is
deliberately NOT used.

Also here (fast, in-process): the guarantee that the fault-injection
OFF path leaves the production update program untouched -- with
TPU_FAULT unset, `update_step` traces to the recorded jaxpr digest
(scripts/jaxpr_digest.json), and only an active `nan:` fault changes
the traced program.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys

import numpy as np
import pytest

from avida_tpu.service.supervisor import Supervisor, SupervisorConfig
from avida_tpu.utils import checkpoint as ckpt_mod

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", "scripts"))
import check_jaxpr  # noqa: E402

SEED = 11
UPDATES = 20

# world config shared by every child AND the uninterrupted reference:
# small world, capped slices, systematics off (PR-4 proved chunked
# bit-exactness without it), TPU_MAX_STRETCH=2 so chunk boundaries --
# the fault/save/heartbeat points -- come every 2 updates
_SETS = [
    ("WORLD_X", "8"), ("WORLD_Y", "8"), ("TPU_MAX_MEMORY", "256"),
    ("AVE_TIME_SLICE", "100"), ("TPU_MAX_STEPS_PER_UPDATE", "100"),
    ("TPU_SYSTEMATICS", "0"), ("TPU_MAX_STRETCH", "2"),
    ("TPU_CKPT_EVERY", "4"), ("TPU_CKPT_FINAL", "1"),
]


def _argv(data_dir, ckpt_dir, extra=(), updates=UPDATES):
    argv = ["-s", str(SEED), "-u", str(updates), "-d", str(data_dir),
            "-set", "TPU_CKPT_DIR", str(ckpt_dir)]
    for name, value in _SETS:
        argv += ["-set", name, value]
    for name, value in extra:
        argv += ["-set", name, value]
    return argv


def _env():
    env = dict(os.environ)
    env.pop("TPU_FAULT", None)
    env["JAX_PLATFORMS"] = "cpu"
    # NOTE: deliberately NO persistent jax compilation cache here --
    # JAX_COMPILATION_CACHE_DIR on this CPU toolchain corrupts resumed
    # runs (heap corruption + garbage state observed under jax 0.4.37
    # with donated buffers), so every child boot pays its own compile
    env.pop("JAX_COMPILATION_CACHE_DIR", None)
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env["PYTHONPATH"] = repo + (
        os.pathsep + env["PYTHONPATH"] if env.get("PYTHONPATH") else "")
    return env


def _sup_cfg(**overrides):
    kw = dict(watchdog_sec=120.0, poll_sec=0.25, grace_sec=600.0,
              max_retries=6, backoff_base=0.05, backoff_cap=0.2,
              healthy_sec=1e9, seed=3)
    kw.update(overrides)
    return SupervisorConfig(**kw)


def _final_gen(ckpt_dir):
    gens = ckpt_mod.list_generations(str(ckpt_dir))
    assert gens, f"no generations under {ckpt_dir}"
    manifest, arrays, files = ckpt_mod.read_generation(gens[-1])
    return manifest, arrays


def _assert_bit_exact(ckpt_dir, ref):
    manifest, arrays = _final_gen(ckpt_dir)
    assert manifest["update"] == ref["manifest"]["update"] == UPDATES
    assert set(arrays) == set(ref["arrays"])
    for name in sorted(arrays):
        np.testing.assert_array_equal(arrays[name], ref["arrays"][name],
                                      err_msg=f"array {name}")


@pytest.fixture(scope="module")
def ref_run(tmp_path_factory):
    """The uninterrupted reference: one plain (unsupervised) child run
    to completion, final state published via TPU_CKPT_FINAL."""
    base = tmp_path_factory.mktemp("chaos_ref")
    data, ck = str(base / "data"), str(base / "ck")
    proc = subprocess.run(
        [sys.executable, "-m", "avida_tpu"] + _argv(data, ck),
        env=_env(), capture_output=True, text=True, timeout=600)
    assert proc.returncode == 0, proc.stderr[-2000:]
    manifest, arrays = _final_gen(ck)
    return {"manifest": manifest, "arrays": arrays}


def _supervise(tmp_path, ref, fault_plan, extra=(), cfg=None,
               updates=UPDATES):
    data, ck = str(tmp_path / "data"), str(tmp_path / "ck")
    sup = Supervisor(_argv(data, ck, extra=extra, updates=updates),
                     fault_plan=fault_plan, cfg=cfg or _sup_cfg(),
                     env=_env())
    rc = sup.run()
    return sup, rc, data, ck


# ---------------------------------------------------------------------------
# fast, in-process: TPU_FAULT off => production jaxpr untouched
# ---------------------------------------------------------------------------

def _digest(fault_spec):
    import hashlib

    import jax
    import jax.numpy as jnp

    from avida_tpu.config import AvidaConfig
    from avida_tpu.config.environment import default_logic9_environment
    from avida_tpu.config.instset import default_instset
    from avida_tpu.core.state import make_world_params, zeros_population
    from avida_tpu.ops import birth as birth_ops
    from avida_tpu.ops.update import update_step

    cfg = AvidaConfig()
    cfg.WORLD_X = 6
    cfg.WORLD_Y = 6
    cfg.TPU_MAX_MEMORY = 64
    if fault_spec:
        cfg.set("TPU_FAULT", fault_spec)
    p = make_world_params(cfg, default_instset(),
                          default_logic9_environment())
    st = zeros_population(p.num_cells, p.max_memory, p.num_reactions)
    nb = jnp.asarray(birth_ops.neighbor_table(6, 6, p.geometry))
    jx = str(jax.make_jaxpr(
        lambda s, k, u: update_step(p, s, k, nb, u))(
            st, jax.random.key(0), jnp.int32(0)))
    return p, hashlib.sha256(jx.encode()).hexdigest()


def test_fault_off_leaves_update_step_jaxpr_unchanged():
    """The satellite CI gate: TPU_FAULT unset => update_step traces to
    the recorded snapshot digest.  The trace itself is shared with the
    existing gate -- check_jaxpr.compute() runs in an environment with
    no fault spec, so it IS the fault-off path; this re-asserts it
    post-wiring and pins the param plumbing (every host-side kind stays
    out of WorldParams; tier-1 cost: one cached check, no extra
    trace)."""
    ok, msg = check_jaxpr.check()
    assert ok, ("fault-injection off path changed the production update "
                "program (re-record only for INTENTIONAL trace changes): "
                + msg)
    # nan wiring reaches params (and only nan does) -- pure host asserts
    from avida_tpu.config import AvidaConfig
    from avida_tpu.core.state import _fault_nan_param
    cfg = AvidaConfig()
    cfg.WORLD_X = 6
    cfg.WORLD_Y = 6
    assert _fault_nan_param(cfg) == ()
    cfg.set("TPU_FAULT", "nan:merit@update=3")
    assert _fault_nan_param(cfg) == ("merit", 18, 3)


@pytest.mark.slow
def test_fault_on_changes_the_traced_program():
    """The off-path gate above is not vacuous: an active nan fault
    traces a DIFFERENT update program (one extra trace -- slow tier, the
    off path is the one tier-1 must guard)."""
    p_off, off = _digest(None)
    assert p_off.fault_nan == ()
    p_on, on = _digest("nan:merit@update=3")
    assert p_on.fault_nan == ("merit", 18, 3)
    assert on != off


def test_host_fault_kinds_leave_params_untouched():
    """Host-side kinds (crash/sigkill/hang/ckpt corruption) never reach
    WorldParams -- only `nan:` is traced."""
    from avida_tpu.config import AvidaConfig
    from avida_tpu.core.state import _fault_nan_param
    for spec in ("crash@update=120", "sigkill@chunk=3", "hang@chunk=2",
                 "corrupt-ckpt:leaf=merit;torn-manifest"):
        cfg = AvidaConfig()
        cfg.set("TPU_FAULT", spec)
        assert _fault_nan_param(cfg) == ()


# ---------------------------------------------------------------------------
# tier-1: one seeded SIGKILL at a non-save boundary, supervised recovery
# ---------------------------------------------------------------------------

def test_supervised_sigkill_recovery(tmp_path):
    """The tier-1 recovery proof, sized for the suite budget (two child
    processes, light slices, no separate reference run): the child is
    SIGKILLed at the update-6 chunk boundary -- PAST the last auto-save
    at update 4, so the crash outran the checkpoint -- and the
    supervisor restarts it with --resume to a clean finish, recording
    the crash class in runlog + metrics.  The bit-exact-vs-uninterrupted
    versions of this drill (single reference, >=3 kills, XLA and Pallas)
    are the slow tests below."""
    extra = (("AVE_TIME_SLICE", "30"), ("TPU_MAX_STEPS_PER_UPDATE", "30"),
             ("TPU_CKPT_AUDIT", "0"))
    # minimal event list (Inject only): skips the update-0 Print actions
    # and their one-off summarize compile in BOTH child boots
    cfgdir = tmp_path / "cfg"
    os.makedirs(cfgdir)
    (cfgdir / "avida.cfg").write_text("")
    (cfgdir / "events.cfg").write_text("u begin Inject default-heads.org\n")
    data, ck = str(tmp_path / "data"), str(tmp_path / "ck")
    argv = ["-c", str(cfgdir), "-set", "INST_SET", "-"] \
        + _argv(data, ck, extra=extra, updates=10)
    sup = Supervisor(argv, fault_plan=["sigkill@update=5"],
                     cfg=_sup_cfg(), env=_env())
    rc = sup.run()
    assert rc == 0
    assert sup.boots == 2
    assert sup.failures["crash"] == 1 and sup.restarts == 1
    # the second boot really resumed from the update-4 generation and
    # REPLAYED 4..10 (stderr echoes the runlog event)
    log = open(os.path.join(data, "supervised.log")).read()
    assert "ckpt-000000000004 update=4" in log
    manifest, arrays = _final_gen(ck)
    assert manifest["update"] == 10
    assert "state.alive" in arrays
    # supervisor breadcrumbs: runlog + prometheus counters
    recs = [json.loads(line)
            for line in open(os.path.join(data, "supervisor.jsonl"))]
    assert [r["event"] for r in recs].count("launch") == 2
    from avida_tpu.observability.exporter import read_metrics
    m = read_metrics(os.path.join(data, "supervisor.prom"))
    assert m['avida_supervisor_failures_total{class="crash"}'] == 1
    assert m["avida_supervisor_boots_total"] == 2


@pytest.mark.slow
def test_supervised_single_sigkill_bit_exact(tmp_path, ref_run):
    """The strict version of the tier-1 drill: same single kill at a
    non-save boundary, final state bit-exact vs the uninterrupted
    reference."""
    sup, rc, data, ck = _supervise(tmp_path, ref_run,
                                   fault_plan=["sigkill@update=5"])
    assert rc == 0 and sup.boots == 2
    _assert_bit_exact(ck, ref_run)


# ---------------------------------------------------------------------------
# slow: the full chaos drill
# ---------------------------------------------------------------------------

@pytest.mark.slow
def test_supervised_multi_sigkill_bit_exact_xla(tmp_path, ref_run):
    """Three SIGKILLs at seeded random chunk boundaries, one per boot;
    re-supervised to completion; bit-exact final state (acceptance:
    >= 3 random seeded kills, XLA path)."""
    rng = np.random.default_rng(0xC4A05)
    kills = sorted(int(u) for u in
                   rng.choice(np.arange(3, UPDATES - 2), size=3,
                              replace=False))
    plan = [f"sigkill@update={u}" for u in kills]
    sup, rc, data, ck = _supervise(tmp_path, ref_run, fault_plan=plan)
    assert rc == 0
    assert sup.boots == 4 and sup.failures["crash"] == 3
    _assert_bit_exact(ck, ref_run)


@pytest.mark.slow
def test_supervised_multi_sigkill_bit_exact_pallas(tmp_path,
                                                   tmp_path_factory):
    """The same multi-kill drill through the lane-packed Pallas kernel
    path (interpret mode on CPU), with its own uninterrupted
    reference.  Config mirrors the known-good kernel-path resume test
    (tests/test_native_checkpoint.py): deterministic slicing, no
    mutations, lane_perm refreshed every update."""
    extra = (("TPU_USE_PALLAS", "1"), ("SLICING_METHOD", "0"),
             ("COPY_MUT_PROB", "0.0"), ("DIVIDE_INS_PROB", "0.0"),
             ("DIVIDE_DEL_PROB", "0.0"),
             # pin the budget-sort lane-packed path: packed residency
             # (round 6) would supersede the permutation this drill
             # asserts non-identity on
             ("TPU_PACKED_CHUNK", "0"))
    data0, ck0 = str(tmp_path / "refdata"), str(tmp_path / "refck")
    proc = subprocess.run(
        [sys.executable, "-m", "avida_tpu"] + _argv(data0, ck0, extra=extra),
        env=_env(), capture_output=True, text=True, timeout=600)
    assert proc.returncode == 0, proc.stderr[-2000:]
    manifest, arrays = _final_gen(ck0)
    ref = {"manifest": manifest, "arrays": arrays}
    # the packed path must actually be active with lane packing on
    assert "state.lane_perm" in arrays
    assert not np.array_equal(arrays["state.lane_perm"],
                              np.arange(arrays["state.lane_perm"].size))

    rng = np.random.default_rng(0xC4A06)
    kills = sorted(int(u) for u in
                   rng.choice(np.arange(3, UPDATES - 2), size=3,
                              replace=False))
    sup, rc, data, ck = _supervise(
        tmp_path, ref, fault_plan=[f"sigkill@update={u}" for u in kills],
        extra=extra)
    assert rc == 0
    assert sup.failures["crash"] == 3
    _assert_bit_exact(ck, ref)


@pytest.mark.slow
def test_hang_watchdog_kill_and_resume(tmp_path, ref_run):
    """An injected hang at the third chunk boundary goes heartbeat-stale;
    the watchdog SIGKILLs it and the restart completes bit-exactly --
    no human input (acceptance: hang proof)."""
    sup, rc, data, ck = _supervise(
        tmp_path, ref_run, fault_plan=["hang@chunk=3"],
        cfg=_sup_cfg(watchdog_sec=4.0, poll_sec=0.25))
    assert rc == 0
    assert sup.failures["hang"] == 1 and sup.watchdog_kills == 1
    _assert_bit_exact(ck, ref_run)
    recs = [json.loads(line)
            for line in open(os.path.join(data, "supervisor.jsonl"))]
    kills = [r for r in recs if r["event"] == "watchdog_kill"]
    assert kills and kills[0]["reason"] == "stale heartbeat"
    from avida_tpu.observability.exporter import read_metrics
    m = read_metrics(os.path.join(data, "supervisor.prom"))
    assert m['avida_supervisor_failures_total{class="hang"}'] == 1


@pytest.mark.slow
def test_corrupt_ckpt_generation_skipped_and_classified(tmp_path, ref_run):
    """A checkpoint generation is byte-flipped at rest, then the run is
    killed: resume skips the corrupt generation via CRC fallback (one
    older generation back) and the supervisor records the corrupt_ckpt
    class in its runlog and metrics (acceptance: corrupt-ckpt proof)."""
    sup, rc, data, ck = _supervise(
        tmp_path, ref_run,
        fault_plan=["corrupt-ckpt:leaf=merit@update=8;sigkill@update=9"])
    assert rc == 0
    assert sup.failures["crash"] == 1            # the sigkill
    assert sup.failures["corrupt_ckpt"] == 1     # the CRC fallback, seen
    assert sup.ckpt_fallbacks == 1
    _assert_bit_exact(ck, ref_run)
    log = open(os.path.join(data, "supervised.log")).read()
    assert "checkpoint_corrupt" in log and "checkpoint_restored" in log
    from avida_tpu.observability.exporter import read_metrics
    m = read_metrics(os.path.join(data, "supervisor.prom"))
    assert m['avida_supervisor_failures_total{class="corrupt_ckpt"}'] == 1


@pytest.mark.slow
def test_torn_manifest_generation_skipped_on_resume(tmp_path, ref_run):
    """Same drill with a manifest torn mid-write instead of payload rot:
    the resume falls back past the unreadable generation (the
    deterministic world-level version of the torn-manifest satellite)."""
    sup, rc, data, ck = _supervise(
        tmp_path, ref_run,
        fault_plan=["torn-manifest@update=8;sigkill@update=9"])
    assert rc == 0
    _assert_bit_exact(ck, ref_run)
    log = open(os.path.join(data, "supervised.log")).read()
    assert "checkpoint_corrupt" in log
    assert "torn or unreadable manifest" in log


@pytest.mark.slow
def test_nan_injection_audit_rollback_recovery(tmp_path, ref_run):
    """Device-side NaN lands in merit at update 6; the periodic auditor
    trips (StateInvariantError -> classified exit), the supervisor
    ROLLS BACK (quarantines the newest generation) and the restarted
    child -- fault no longer injected -- replays to a bit-exact
    finish."""
    sup, rc, data, ck = _supervise(
        tmp_path, ref_run, fault_plan=["nan:merit@update=6"],
        extra=(("TPU_AUDIT_EVERY", "2"), ("TPU_CKPT_EVERY", "2")))
    assert rc == 0
    assert sup.failures["audit_violation"] == 1
    assert sup.rollbacks == 1
    assert [d for d in os.listdir(ck) if d.startswith(".bad-")]
    _assert_bit_exact(ck, ref_run)
    log = open(os.path.join(data, "supervised.log")).read()
    assert "merit_finite" in log                 # the auditor named it
    recs = [json.loads(line)
            for line in open(os.path.join(data, "supervisor.jsonl"))]
    assert "rollback" in [r["event"] for r in recs]
