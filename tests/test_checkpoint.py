"""Checkpoint round-trip + population-control actions + BIRTHS trigger.

Reference: SavePopulation/LoadPopulation (cPopulation.cc:6294/6723, gated
by the heads_midrun_30u golden test), cActionKillProb / cActionSerialTransfer
(actions/PopulationActions.cc), BIRTHS event trigger (cEventList.h:63).
"""

from __future__ import annotations

import os

import numpy as np
import jax.numpy as jnp

from avida_tpu.config import AvidaConfig
from avida_tpu.config.events import parse_event_line
from avida_tpu.world import World

import pytest  # noqa: E402

pytestmark = pytest.mark.slow


def _world(tmpdir, seed=11, **kw):
    cfg = AvidaConfig()
    cfg.WORLD_X = 10
    cfg.WORLD_Y = 10
    cfg.TPU_MAX_MEMORY = 320
    cfg.RANDOM_SEED = seed
    cfg.AVE_TIME_SLICE = 100
    cfg.TPU_MAX_STEPS_PER_UPDATE = 100
    for k, v in kw.items():
        cfg.set(k, v)
    return World(cfg=cfg, data_dir=str(tmpdir))


def test_midrun_save_load_continue(tmp_path):
    """The reference's heads_midrun_30u shape: run 15 updates, save, load
    into a fresh world, continue -- the restored population must match the
    save exactly and keep evolving."""
    w = _world(tmp_path)
    w.events = [parse_event_line("u begin Inject"),
                parse_event_line("u 15 SavePopulation")]
    w.run(max_updates=15)
    n_before = w.num_organisms
    assert n_before > 1
    spop_path = os.path.join(str(tmp_path), "detail-15.spop")
    w.process_events()           # fire the u-15 SavePopulation
    assert os.path.exists(spop_path)

    w2 = _world(tmp_path, seed=12)
    w2.events = []
    w2.update = 15
    w2._action_LoadPopulation([spop_path])
    # restored population matches the saved one organism-for-organism
    assert w2.num_organisms == n_before
    a1 = np.asarray(w.state.alive)
    a2 = np.asarray(w2.state.alive)
    np.testing.assert_array_equal(a1, a2)
    np.testing.assert_array_equal(
        np.asarray(w.state.genome_len)[a1], np.asarray(w2.state.genome_len)[a2])
    g1 = np.asarray(w.state.genome)[a1]
    g2 = np.asarray(w2.state.genome)[a2]
    np.testing.assert_array_equal(g1, g2)
    # ...and CONTINUES: more births happen after the reload
    w2.run(max_updates=35)
    assert w2.num_organisms > n_before, "restored world stopped evolving"


def test_kill_prob_and_serial_transfer(tmp_path):
    w = _world(tmp_path, seed=5)
    w.events = []
    w.inject()
    w.run(max_updates=25)
    n0 = w.num_organisms
    assert n0 > 10
    w._action_KillProb(["0.5"])
    n1 = w.num_organisms
    assert n1 < n0
    w._action_SerialTransfer(["3"])
    assert w.num_organisms == 3


def test_births_trigger_fires(tmp_path):
    w = _world(tmp_path, seed=7, TPU_SYSTEMATICS=0)
    fired = []
    w._action_MarkBirths = lambda args: fired.append(int(w._total_births))
    w.events = [parse_event_line("u begin Inject"),
                parse_event_line("b 5:5:end MarkBirths")]
    w.run(max_updates=30)
    assert fired, "BIRTHS trigger never fired"
    assert fired[0] >= 5


def test_tasks_exe_baseline_reset_on_load(tmp_path):
    """tasks_exe.dat after a LoadPopulation must report a per-update
    DELTA, not lifetime totals or a negative diff: the host-side
    _task_exe_prev baseline is reseeded from the restored state, and the
    per-cell lifetime totals travel in a .spop sidecar (round-5 advisor
    finding)."""
    w = _world(tmp_path, seed=21)
    w.events = []
    w.inject()
    w.run(max_updates=5)
    # give the population distinctive lifetime task-execution totals.
    # Materialize the host copy NOW: update_scan donates the state
    # buffers, so the device array backing `fake` is dead after the next
    # w.run() (the documented donation caveat, ops/update.py).
    fake = jnp.ones_like(w.state.task_exe_total) * 7
    fake_np = np.asarray(fake)
    w.state = w.state.replace(task_exe_total=fake)
    w._summary_cache_update = None
    w.update = 5
    w._action_SavePopulation([])
    spop_path = os.path.join(str(tmp_path), "detail-5.spop")
    assert os.path.exists(spop_path + ".tasks.npy")

    # same-process reload after further evolution: the baseline must not
    # go stale (pre-fix: first row after reload = restored - stale
    # baseline, possibly negative)
    w.run(max_updates=9)
    w._action_PrintTasksExeData([])            # refreshes _task_exe_prev
    w._action_LoadPopulation([spop_path])
    totals = np.asarray(w.state.task_exe_total)
    np.testing.assert_array_equal(totals, fake_np)   # sidecar round-trip
    w._summary_cache_update = None
    w._action_PrintTasksExeData([])
    rows = [l.split() for l in
            open(os.path.join(str(tmp_path), "tasks_exe.dat"))
            if l.strip() and not l.startswith("#")]
    last = [int(x) for x in rows[-1][1:]]
    assert all(v == 0 for v in last), \
        f"first tasks_exe row after restore must be a zero delta, got {last}"

    # fresh-process shape: a brand-new World loading the checkpoint also
    # reports deltas, not the 7-per-cell lifetime totals
    w2 = _world(tmp_path / "w2", seed=22)
    w2.events = []
    w2.update = 5
    w2._action_LoadPopulation([spop_path])
    np.testing.assert_array_equal(np.asarray(w2.state.task_exe_total),
                                  fake_np)
    w2._action_PrintTasksExeData([])
    rows2 = [l.split() for l in
             open(os.path.join(str(tmp_path / "w2"), "tasks_exe.dat"))
             if l.strip() and not l.startswith("#")]
    last2 = [int(x) for x in rows2[-1][1:]]
    assert all(v == 0 for v in last2), last2


def test_empty_population_spop_roundtrip(tmp_path):
    """SavePopulation with ZERO live organisms writes a header-only file;
    loading it must yield a clean empty world that keeps running (no
    parse error, no stale population) -- regression for the empty-file
    edge of the .spop round trip."""
    from avida_tpu.utils import spop

    w = _world(tmp_path, seed=31)
    w.events = []
    w.inject()
    w.run(max_updates=3)
    w._action_KillProb(["1.0"])          # extinction event
    assert w.num_organisms == 0
    path = os.path.join(str(tmp_path), "empty.spop")
    spop.save_population(path, w.params, w.state, w.update)
    assert os.path.exists(path)

    import jax
    orgs = spop.load_population(path, w.params, jax.random.key(0))
    assert orgs == []
    w2 = _world(tmp_path / "w2", seed=32)
    w2.events = []
    w2.update = 3
    w2._action_LoadPopulation([path])
    assert w2.num_organisms == 0
    # the empty world still runs (no stale state, no crash)
    w2.run(max_updates=5)
    assert w2.num_organisms == 0


def test_spop_fidelity_limits(tmp_path):
    """Executable documentation of exactly which fields survive a .spop
    round trip (see utils/spop.py header): genome/alive/genome_len are
    exact; merit comes back as the PER-GENOTYPE MEAN; resources restart
    at initial levels; CPU state is rebuilt by gest_offset fast-forward
    rather than preserved.  Future native-checkpoint changes must not
    silently alter this reference-parity contract."""
    import jax
    import jax.numpy as jnp

    from avida_tpu.utils import spop

    w = _world(tmp_path, seed=41)
    w.events = []
    w.inject()
    w.run(max_updates=18)
    st = w.state
    alive = np.asarray(st.alive)
    assert alive.sum() > 2

    # craft distinct per-organism merits so genotype averaging is visible
    n = w.params.num_cells
    crafted = jnp.where(st.alive,
                        jnp.arange(n, dtype=jnp.float32) + 1.0, st.merit)
    w.state = st = st.replace(merit=crafted)

    path = os.path.join(str(tmp_path), "fidelity.spop")
    spop.save_population(path, w.params, st, w.update)
    orgs = spop.load_population(path, w.params, jax.random.key(0))
    st2 = spop.restore_population(w.params, orgs, jax.random.key(1))

    # exact: occupancy, genome identity, genome length
    np.testing.assert_array_equal(np.asarray(st2.alive), alive)
    np.testing.assert_array_equal(np.asarray(st2.genome)[alive],
                                  np.asarray(st.genome)[alive])
    np.testing.assert_array_equal(np.asarray(st2.genome_len)[alive],
                                  np.asarray(st.genome_len)[alive])

    # lossy by design: merit is genotype-averaged on restore
    genomes = np.asarray(st.genome)
    lens = np.asarray(st.genome_len)
    groups = {}
    for c in np.nonzero(alive)[0]:
        groups.setdefault(genomes[c, :lens[c]].tobytes(), []).append(c)
    crafted_np = np.asarray(crafted)
    restored = np.asarray(st2.merit)
    saw_averaging = False
    for cells in groups.values():
        mean = np.float32(crafted_np[cells].mean())
        for c in cells:
            np.testing.assert_allclose(restored[c], mean, rtol=1e-5)
        if len(cells) > 1:
            saw_averaging = True
            assert not np.allclose(crafted_np[cells], crafted_np[cells][0])
    assert saw_averaging, "need a multi-member genotype to show averaging"

    # not in the format: resource pools restart at initial levels
    np.testing.assert_array_equal(
        np.asarray(st2.resources),
        np.asarray(w.params.res_initial, np.float32))
    # CPU state is rebuilt (fresh CPU + fast-forward), not copied: the
    # restored lifetime cycle counter only covers the current gestation
    offsets = np.asarray(st.time_used) - np.asarray(st.gestation_start)
    assert (np.asarray(st2.time_used)[alive]
            <= np.maximum(offsets[alive], 0) + 1).all()
