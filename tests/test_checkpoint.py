"""Checkpoint round-trip + population-control actions + BIRTHS trigger.

Reference: SavePopulation/LoadPopulation (cPopulation.cc:6294/6723, gated
by the heads_midrun_30u golden test), cActionKillProb / cActionSerialTransfer
(actions/PopulationActions.cc), BIRTHS event trigger (cEventList.h:63).
"""

from __future__ import annotations

import os

import numpy as np
import jax.numpy as jnp

from avida_tpu.config import AvidaConfig
from avida_tpu.config.events import parse_event_line
from avida_tpu.world import World

import pytest  # noqa: E402

pytestmark = pytest.mark.slow


def _world(tmpdir, seed=11, **kw):
    cfg = AvidaConfig()
    cfg.WORLD_X = 10
    cfg.WORLD_Y = 10
    cfg.TPU_MAX_MEMORY = 320
    cfg.RANDOM_SEED = seed
    cfg.AVE_TIME_SLICE = 100
    cfg.TPU_MAX_STEPS_PER_UPDATE = 100
    for k, v in kw.items():
        cfg.set(k, v)
    return World(cfg=cfg, data_dir=str(tmpdir))


def test_midrun_save_load_continue(tmp_path):
    """The reference's heads_midrun_30u shape: run 15 updates, save, load
    into a fresh world, continue -- the restored population must match the
    save exactly and keep evolving."""
    w = _world(tmp_path)
    w.events = [parse_event_line("u begin Inject"),
                parse_event_line("u 15 SavePopulation")]
    w.run(max_updates=15)
    n_before = w.num_organisms
    assert n_before > 1
    spop_path = os.path.join(str(tmp_path), "detail-15.spop")
    w.process_events()           # fire the u-15 SavePopulation
    assert os.path.exists(spop_path)

    w2 = _world(tmp_path, seed=12)
    w2.events = []
    w2.update = 15
    w2._action_LoadPopulation([spop_path])
    # restored population matches the saved one organism-for-organism
    assert w2.num_organisms == n_before
    a1 = np.asarray(w.state.alive)
    a2 = np.asarray(w2.state.alive)
    np.testing.assert_array_equal(a1, a2)
    np.testing.assert_array_equal(
        np.asarray(w.state.genome_len)[a1], np.asarray(w2.state.genome_len)[a2])
    g1 = np.asarray(w.state.genome)[a1]
    g2 = np.asarray(w2.state.genome)[a2]
    np.testing.assert_array_equal(g1, g2)
    # ...and CONTINUES: more births happen after the reload
    w2.run(max_updates=35)
    assert w2.num_organisms > n_before, "restored world stopped evolving"


def test_kill_prob_and_serial_transfer(tmp_path):
    w = _world(tmp_path, seed=5)
    w.events = []
    w.inject()
    w.run(max_updates=25)
    n0 = w.num_organisms
    assert n0 > 10
    w._action_KillProb(["0.5"])
    n1 = w.num_organisms
    assert n1 < n0
    w._action_SerialTransfer(["3"])
    assert w.num_organisms == 3


def test_births_trigger_fires(tmp_path):
    w = _world(tmp_path, seed=7, TPU_SYSTEMATICS=0)
    fired = []
    w._action_MarkBirths = lambda args: fired.append(int(w._total_births))
    w.events = [parse_event_line("u begin Inject"),
                parse_event_line("b 5:5:end MarkBirths")]
    w.run(max_updates=30)
    assert fired, "BIRTHS trigger never fired"
    assert fired[0] >= 5


def test_tasks_exe_baseline_reset_on_load(tmp_path):
    """tasks_exe.dat after a LoadPopulation must report a per-update
    DELTA, not lifetime totals or a negative diff: the host-side
    _task_exe_prev baseline is reseeded from the restored state, and the
    per-cell lifetime totals travel in a .spop sidecar (round-5 advisor
    finding)."""
    w = _world(tmp_path, seed=21)
    w.events = []
    w.inject()
    w.run(max_updates=5)
    # give the population distinctive lifetime task-execution totals
    fake = jnp.ones_like(w.state.task_exe_total) * 7
    w.state = w.state.replace(task_exe_total=fake)
    w._summary_cache_update = None
    w.update = 5
    w._action_SavePopulation([])
    spop_path = os.path.join(str(tmp_path), "detail-5.spop")
    assert os.path.exists(spop_path + ".tasks.npy")

    # same-process reload after further evolution: the baseline must not
    # go stale (pre-fix: first row after reload = restored - stale
    # baseline, possibly negative)
    w.run(max_updates=9)
    w._action_PrintTasksExeData([])            # refreshes _task_exe_prev
    w._action_LoadPopulation([spop_path])
    totals = np.asarray(w.state.task_exe_total)
    np.testing.assert_array_equal(totals, np.asarray(fake))   # sidecar round-trip
    w._summary_cache_update = None
    w._action_PrintTasksExeData([])
    rows = [l.split() for l in
            open(os.path.join(str(tmp_path), "tasks_exe.dat"))
            if l.strip() and not l.startswith("#")]
    last = [int(x) for x in rows[-1][1:]]
    assert all(v == 0 for v in last), \
        f"first tasks_exe row after restore must be a zero delta, got {last}"

    # fresh-process shape: a brand-new World loading the checkpoint also
    # reports deltas, not the 7-per-cell lifetime totals
    w2 = _world(tmp_path / "w2", seed=22)
    w2.events = []
    w2.update = 5
    w2._action_LoadPopulation([spop_path])
    np.testing.assert_array_equal(np.asarray(w2.state.task_exe_total),
                                  np.asarray(fake))
    w2._action_PrintTasksExeData([])
    rows2 = [l.split() for l in
             open(os.path.join(str(tmp_path / "w2"), "tasks_exe.dat"))
             if l.strip() and not l.startswith("#")]
    last2 = [int(x) for x in rows2[-1][1:]]
    assert all(v == 0 for v in last2), last2
