"""Persistent AOT program cache (utils/compilecache.py).

Contract under test, layer by layer:

  * entry store: atomic publish, CRC-manifest verification, prune and
    the cache_tool CLI -- pure host, no compile;
  * cache semantics on a cheap jitted scan: miss -> compile+store,
    fresh-process load -> bit-identical outputs, and the THREE loud
    fallbacks the issue names -- truncated entry, CRC-mismatched entry,
    stale code-digest (and stale-jax-version) entry -- each recovering
    with a fresh trace, the journaled `compile_cache` reason and an
    overwritten (healed) entry;
  * engine integration: a World trajectory is bit-exact across
    {cache miss, cache load, cache off} on the XLA path (fast) and the
    packed/Pallas(interpret) path (slow), with cache_load_count() as
    the warm-process probe;
  * the serve-child warm start: a second all-ghost ServeBatch of the
    same class constructs every chunk program with ZERO new traces
    (scan_trace_count flat, cache_load_count == program count) -- the
    fleet-wide warmup paid once per (signature, width) (slow);
  * the chaos drill that condemned JAX_COMPILATION_CACHE_DIR (PR-6
    heap corruption): SIGKILL mid-run, supervised resume with the cache
    ON -- the resumed boot loads serialized executables into donated
    buffers -- stays bit-exact vs an uninterrupted cache-OFF reference
    (slow).

Cache tests opt back IN to the cache (tests/conftest.py kills it
suite-wide for hermeticity) via monkeypatch + a tmp_path root.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time
from functools import partial

import numpy as np
import pytest

from avida_tpu.utils import compilecache as cc

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", "scripts"))
import cache_tool  # noqa: E402


@pytest.fixture()
def cache_root(tmp_path, monkeypatch):
    """A fresh enabled cache rooted under tmp_path (env half of the
    kill switch re-armed; conftest disables it suite-wide)."""
    root = tmp_path / "cc"
    monkeypatch.setenv("TPU_COMPILE_CACHE", "1")
    monkeypatch.setenv("TPU_COMPILE_CACHE_DIR", str(root))
    cc.reset_for_tests()
    yield str(root)
    cc.reset_for_tests()


# ---------------------------------------------------------------------------
# host-only: kill switch, dir resolution, entry store, cache_tool
# ---------------------------------------------------------------------------

class _Cfg(dict):
    def get(self, name, default=None):
        return super().get(name, default)


def test_kill_switch_and_dir_resolution(monkeypatch, tmp_path):
    monkeypatch.delenv("TPU_COMPILE_CACHE", raising=False)
    monkeypatch.delenv("TPU_COMPILE_CACHE_DIR", raising=False)
    assert cc.enabled() and cc.enabled(_Cfg())
    # env kill beats an enabling config; config kill beats a silent env
    monkeypatch.setenv("TPU_COMPILE_CACHE", "0")
    assert not cc.enabled(_Cfg(TPU_COMPILE_CACHE=1))
    monkeypatch.setenv("TPU_COMPILE_CACHE", "1")
    assert not cc.enabled(_Cfg(TPU_COMPILE_CACHE=0))
    assert cc.enabled(_Cfg(TPU_COMPILE_CACHE=1))
    # dir: config beats env beats the per-user default
    monkeypatch.setenv("TPU_COMPILE_CACHE_DIR", str(tmp_path / "env"))
    assert cc.cache_dir(_Cfg(TPU_COMPILE_CACHE_DIR=str(tmp_path / "cfg"))) \
        == str(tmp_path / "cfg")
    assert cc.cache_dir(_Cfg(TPU_COMPILE_CACHE_DIR="-")) \
        == str(tmp_path / "env")
    monkeypatch.delenv("TPU_COMPILE_CACHE_DIR")
    assert cc.cache_dir(None).endswith(os.path.join("avida_tpu", "compile"))


def _fake_entry(root, key=None, payload=b"x" * 4096, meta=None):
    return cc.write_entry(str(root), key or "k" * 40, payload,
                          b"trees", dict({"tag": "update_scan",
                                          "chunk": 2, "jax": "0",
                                          "jaxlib": "0", "code": "c",
                                          "avals": [[[36, 128], "int32"]]},
                                         **(meta or {})))


def test_entry_store_roundtrip_and_prune(tmp_path):
    p1 = _fake_entry(tmp_path, key="a" * 40)
    p2 = _fake_entry(tmp_path, key="b" * 40)
    assert sorted(cc.list_entries(str(tmp_path))) == sorted([p1, p2])
    m = cc.verify_entry(p1)
    assert m["files"][cc.EXEC_FILE]["size"] == 4096
    # same-key republish under an EQUIVALENT toolchain is a no-op (a
    # sibling already published this program -- never yank a live entry
    # out from under a concurrent reader) ...
    _fake_entry(tmp_path, key="a" * 40, payload=b"y" * 8)
    assert cc.verify_entry(p1)["files"][cc.EXEC_FILE]["size"] == 4096
    # ... while a toolchain/code drift still replaces it atomically
    # (the self-healing path), leaving no .tmp/.old debris
    _fake_entry(tmp_path, key="a" * 40, payload=b"y" * 8,
                meta={"code": "c2"})
    assert cc.verify_entry(p1)["files"][cc.EXEC_FILE]["size"] == 8
    assert not [d for d in os.listdir(tmp_path)
                if d.startswith((".tmp-", ".old-"))]
    assert cc.looks_like_cache_dir(str(tmp_path))
    assert not cc.looks_like_cache_dir(str(tmp_path / ("a" * 40)))
    # prune: keep newest 1, then drop all
    removed = cc.prune(str(tmp_path), keep=1)
    assert len(cc.list_entries(str(tmp_path))) == 1
    assert removed
    cc.prune(str(tmp_path), keep=0)
    assert cc.list_entries(str(tmp_path)) == []


def test_publish_janitor_spares_live_foreign_tmp(tmp_path):
    """Sibling class children share one SPOOL/compile-cache: publishing
    our entry must not rmtree another process's FRESH in-flight .tmp-
    dir (it would turn that sibling's store into a journaled
    store_failed and re-open its compile window); stale foreign debris
    and our own pid's debris are swept."""
    fresh = tmp_path / f".tmp-{'c' * 40}.99999"
    os.makedirs(fresh)
    (fresh / cc.EXEC_FILE).write_bytes(b"half-written")
    stale = tmp_path / f".tmp-{'d' * 40}.99998"
    os.makedirs(stale)
    old = time.time() - 2 * cc._DEBRIS_MAX_AGE_SEC
    os.utime(stale, (old, old))
    mine = tmp_path / f".old-{'e' * 40}.{os.getpid()}"
    os.makedirs(mine)
    _fake_entry(tmp_path, key="a" * 40)
    assert fresh.is_dir(), "live sibling tmp was destroyed"
    assert not stale.exists() and not mine.exists()


def test_entry_corruption_detected(tmp_path):
    path = _fake_entry(tmp_path)
    # truncation
    with open(os.path.join(path, cc.EXEC_FILE), "r+b") as f:
        f.truncate(10)
    with pytest.raises(cc.CompileCacheError, match="truncated"):
        cc.verify_entry(path)
    # CRC flip at unchanged size
    path = _fake_entry(tmp_path)
    with open(os.path.join(path, cc.EXEC_FILE), "r+b") as f:
        f.seek(100)
        f.write(b"\xff")
    with pytest.raises(cc.CompileCacheError, match="CRC mismatch"):
        cc.verify_entry(path)
    # torn manifest
    path = _fake_entry(tmp_path)
    with open(os.path.join(path, cc.MANIFEST), "w") as f:
        f.write('{"format": "avi')
    with pytest.raises(cc.CompileCacheError, match="torn"):
        cc.verify_entry(path)
    # foreign format
    path = _fake_entry(tmp_path)
    mp = os.path.join(path, cc.MANIFEST)
    m = json.load(open(mp))
    m["format"] = "something-else"
    json.dump(m, open(mp, "w"))
    with pytest.raises(cc.CompileCacheStale):
        cc.verify_entry(path)


def test_cache_tool_cli(tmp_path, capsys):
    spool = tmp_path / "spool"
    root = spool / "compile-cache"
    _fake_entry(root, key="a" * 40)
    _fake_entry(root, key="b" * 40)
    assert cache_tool.main([str(root)]) == 0
    out = capsys.readouterr().out
    assert "2 entries" in out and "update_scan" in out and "chunk=2" in out
    assert cache_tool.main([str(root), "--verify"]) == 0
    assert "2/2 entries verify" in capsys.readouterr().out
    # corrupt one -> verify fails loudly
    with open(root / ("a" * 40) / cc.EXEC_FILE, "r+b") as f:
        f.truncate(1)
    assert cache_tool.main([str(root), "--verify"]) == 1
    assert "CORRUPT" in capsys.readouterr().out
    # spool-wide prune sweeps the cache dir inside the tree
    assert cache_tool.main(["--prune", "--all", str(spool)]) == 0
    assert cc.list_entries(str(root)) == []
    # empty dir lists as such
    assert cache_tool.main([str(tmp_path / "nope")]) == 1


# ---------------------------------------------------------------------------
# cache semantics on a cheap jitted scan (sub-second compiles)
# ---------------------------------------------------------------------------

def _toy():
    """A miniature of the engine scans: static scale + chunk, donated
    carry, scan body -- cheap enough to compile in well under a
    second, so every fallback path is exercised without paying
    update_scan's compile each time."""
    import jax

    @partial(jax.jit, static_argnums=(0, 2), donate_argnums=(1,))
    def toy(scale, x, steps, y):
        def body(c, _):
            c = c * scale + y
            return c, c.sum()
        return jax.lax.scan(body, x, None, length=steps)
    return toy


def _toy_args():
    import jax.numpy as jnp
    x = jnp.arange(8, dtype=jnp.float32)
    y = jnp.full((8,), 0.5, jnp.float32)
    return (3, x, 4, y)


def _call_toy(toy, events):
    out, sums = cc.call(toy, "toy", _toy_args(), cfg=None,
                        log=lambda **kw: events.append(kw))
    return np.asarray(out), np.asarray(sums)


def test_miss_store_load_bit_exact_and_counters(cache_root):
    events = []
    toy = _toy()
    ref_out, ref_sums = _call_toy(toy, events)
    assert cc.cache_miss_count() == 1 and cc.cache_load_count() == 0
    assert [e["action"] for e in events] == ["compile", "store"]
    assert cc.list_entries(cache_root)
    # memo hit: no new counters, same bits
    out, sums = _call_toy(toy, events)
    assert cc.counters()["misses"] == 1 and cc.cache_load_count() == 0
    np.testing.assert_array_equal(out, ref_out)
    # simulated fresh process: the disk load path
    cc.reset_for_tests()
    events.clear()
    out, sums = _call_toy(_toy(), events)
    assert cc.cache_load_count() == 1 and cc.cache_miss_count() == 0
    assert cc.counters()["compile_ms"] == 0.0
    assert [e["action"] for e in events] == ["load"]
    np.testing.assert_array_equal(out, ref_out)
    np.testing.assert_array_equal(sums, ref_sums)
    # prom families carry the activity
    fams = dict((f[0], f[3]) for f in cc.prom_families())
    assert fams["avida_compile_cache_hits_total"] == 1
    assert fams["avida_compile_cache_misses_total"] == 0


def _entry_of(cache_root):
    entries = cc.list_entries(cache_root)
    assert len(entries) == 1
    return entries[0]


def _corruption_case(cache_root, mutate, expect_action, expect_err):
    """Populate -> mutate the entry -> fresh process -> the call falls
    back to a fresh trace BIT-EXACTLY, journals the reason, and heals
    the entry (the overwrite makes the next load clean)."""
    events = []
    ref_out, ref_sums = _call_toy(_toy(), events)
    mutate(_entry_of(cache_root))
    cc.reset_for_tests()
    events.clear()
    out, sums = _call_toy(_toy(), events)
    np.testing.assert_array_equal(out, ref_out)
    np.testing.assert_array_equal(sums, ref_sums)
    assert cc.cache_error_count() == 1 and cc.cache_miss_count() == 1
    acts = [e["action"] for e in events]
    assert acts == [expect_action, "compile", "store"], acts
    assert expect_err in events[0]["error"]
    # healed: the very next fresh process loads cleanly
    cc.reset_for_tests()
    events.clear()
    out, _ = _call_toy(_toy(), events)
    assert [e["action"] for e in events] == ["load"]
    np.testing.assert_array_equal(out, ref_out)


def test_truncated_entry_falls_back(cache_root):
    def mutate(path):
        with open(os.path.join(path, cc.EXEC_FILE), "r+b") as f:
            f.truncate(16)
    _corruption_case(cache_root, mutate, "corrupt", "truncated")


def test_crc_mismatch_falls_back(cache_root):
    def mutate(path):
        with open(os.path.join(path, cc.EXEC_FILE), "r+b") as f:
            f.seek(32)
            f.write(b"\x5a")
    _corruption_case(cache_root, mutate, "corrupt", "CRC mismatch")


def _edit_manifest(path, **fields):
    mp = os.path.join(path, cc.MANIFEST)
    with open(mp) as f:
        m = json.load(f)
    m.update(fields)
    with open(mp, "w") as f:
        json.dump(m, f)


def test_stale_code_digest_falls_back(cache_root):
    _corruption_case(cache_root,
                     lambda p: _edit_manifest(p, code="deadbeef"),
                     "stale", "code digest")


def test_stale_jax_version_falls_back(cache_root):
    _corruption_case(cache_root,
                     lambda p: _edit_manifest(p, jax="9.9.9"),
                     "stale", "jax version")


def test_unwritable_root_still_runs(tmp_path, monkeypatch):
    """A cache root blocked by a FILE: the store fails with a journaled
    store_failed, the run proceeds on the freshly compiled program."""
    blocked = tmp_path / "blocked"
    blocked.write_text("not a dir")
    monkeypatch.setenv("TPU_COMPILE_CACHE", "1")
    monkeypatch.setenv("TPU_COMPILE_CACHE_DIR", str(blocked))
    cc.reset_for_tests()
    events = []
    out, _ = _call_toy(_toy(), events)
    acts = [e["action"] for e in events]
    assert acts == ["compile", "store_failed"]
    assert out.shape == (8,)
    cc.reset_for_tests()


def test_disabled_is_plain_jit_path(monkeypatch):
    monkeypatch.setenv("TPU_COMPILE_CACHE", "0")
    cc.reset_for_tests()
    events = []
    out, sums = _call_toy(_toy(), events)
    assert events == [] and cc.counters()["misses"] == 0
    assert out.shape == (8,)


# ---------------------------------------------------------------------------
# engine integration: World trajectories across miss / load / off
# ---------------------------------------------------------------------------

_WORLD_SETS = [("WORLD_X", 6), ("WORLD_Y", 6), ("TPU_MAX_MEMORY", 128),
               ("RANDOM_SEED", 11), ("TPU_MAX_STRETCH", 2),
               ("TPU_SYSTEMATICS", 0), ("TPU_CKPT_AUDIT", 0),
               ("AVE_TIME_SLICE", 30), ("TPU_MAX_STEPS_PER_UPDATE", 30)]


def _run_world(tmp_path, name, extra=()):
    from avida_tpu.world import World
    w = World(overrides=_WORLD_SETS + list(extra),
              data_dir=str(tmp_path / name))
    w.run(max_updates=4)
    return {f: np.asarray(getattr(w.state, f)).copy()
            for f in ("alive", "tape", "genome", "merit", "insts_executed")}


def _assert_states(a, b):
    for f in a:
        np.testing.assert_array_equal(a[f], b[f], err_msg=f"field {f}")


def test_world_bit_exact_miss_load_off_xla(cache_root, tmp_path,
                                           monkeypatch):
    """The engine-level contract on the XLA path: populate (miss),
    reload in a simulated fresh process (cache_load_count probe: loaded
    programs, zero fresh compiles), and the kill-switch path -- all
    three trajectories bit-identical."""
    miss = _run_world(tmp_path, "miss")
    assert cc.cache_miss_count() >= 1 and cc.cache_load_count() == 0
    cc.reset_for_tests()
    load = _run_world(tmp_path, "load")
    assert cc.cache_load_count() >= 1
    assert cc.counters()["compile_ms"] == 0.0, \
        "warm process paid a fresh compile"
    _assert_states(miss, load)
    monkeypatch.setenv("TPU_COMPILE_CACHE", "0")
    cc.reset_for_tests()
    off = _run_world(tmp_path, "off")
    assert cc.counters() == {"hits": 0, "misses": 0, "errors": 0,
                             "load_ms": 0.0, "compile_ms": 0.0,
                             "store_ms": 0.0}
    _assert_states(miss, off)


@pytest.mark.slow
def test_world_bit_exact_miss_load_packed_interpret(cache_root, tmp_path):
    """The packed/Pallas(interpret) leg of the acceptance bar: the
    deserialized executable of the packed-resident chunk program
    computes the identical trajectory."""
    from avida_tpu.ops import packed_chunk
    from avida_tpu.world import World
    extra = [("TPU_USE_PALLAS", 1)]
    wprobe = World(overrides=_WORLD_SETS + extra,
                   data_dir=str(tmp_path / "probe"))
    wprobe.process_events()
    assert packed_chunk.active(wprobe.params, wprobe.state), \
        "config must take the packed-resident path for this leg"
    miss = _run_world(tmp_path, "pmiss", extra=extra)
    assert cc.cache_miss_count() >= 1
    cc.reset_for_tests()
    load = _run_world(tmp_path, "pload", extra=extra)
    assert cc.cache_load_count() >= 1
    assert cc.counters()["compile_ms"] == 0.0
    _assert_states(miss, load)


@pytest.mark.slow
def test_serve_warmup_loads_zero_trace_programs(cache_root, tmp_path):
    """The fleet-wide warmup satellite: child A of a (signature, W)
    class compiles+stores its chunk programs; child B (fresh process,
    same class -- simulated by resetting the process memo) constructs
    every program with ZERO new multiworld_scan traces --
    scan_trace_count() flat, cache_load_count() == program count."""
    from avida_tpu.parallel.multiworld import ServeBatch, scan_trace_count
    from avida_tpu.world import World

    def factory_for(base):
        def factory(entry):
            ov = [(k, v) for k, v in _WORLD_SETS if k != "RANDOM_SEED"]
            ov += [("RANDOM_SEED", int(entry["seed"]))]
            return World(overrides=ov, data_dir=entry["data_dir"])
        return factory

    def warm(base) -> int:
        ctl = tmp_path / base / "control.json"
        os.makedirs(ctl.parent, exist_ok=True)
        with open(ctl, "w") as f:
            json.dump({"width": 2, "members": []}, f)
        sb = ServeBatch(2, str(ctl), str(tmp_path / base / "root"),
                        world_factory=factory_for(base))
        sb._stack()
        for k in (1, 2):
            sb._scan(k)
        sb._sync_worlds()
        return 2

    t0 = scan_trace_count()
    n = warm("childA")
    assert scan_trace_count() == t0 + n          # cold: every shape traced
    assert cc.cache_miss_count() == n
    cc.reset_for_tests()                         # "fresh process" B
    t1 = scan_trace_count()
    warm("childB")
    assert scan_trace_count() == t1, "warm child traced a program"
    assert cc.cache_load_count() == n
    assert cc.cache_miss_count() == 0


# ---------------------------------------------------------------------------
# slow: the chaos drill -- SIGKILL + resume with the cache ON
# ---------------------------------------------------------------------------

@pytest.mark.slow
def test_sigkill_resume_with_cache_bit_exact(tmp_path):
    """THE landmine drill: a supervised child is SIGKILLed past its last
    auto-save and restarted with --resume; the restarted boot
    deserializes the first boot's executables into donated buffers --
    the exact access pattern that produced glibc heap corruption under
    JAX_COMPILATION_CACHE_DIR (PR 6) -- and the final state is
    byte-identical to an uninterrupted cache-OFF reference."""
    from avida_tpu.service.supervisor import Supervisor, SupervisorConfig
    from avida_tpu.utils import checkpoint as ckpt_mod

    sets = [(k, str(v)) for k, v in _WORLD_SETS if k != "RANDOM_SEED"]
    sets += [("TPU_CKPT_EVERY", "4"), ("TPU_CKPT_FINAL", "1")]

    def argv(data, ck):
        out = ["-s", "11", "-u", "10", "-d", data,
               "-set", "TPU_CKPT_DIR", ck]
        for n, v in sets:
            out += ["-set", n, v]
        return out

    def env(cache_on):
        e = dict(os.environ)
        e["JAX_PLATFORMS"] = "cpu"
        e.pop("JAX_COMPILATION_CACHE_DIR", None)
        e["TPU_COMPILE_CACHE"] = "1" if cache_on else "0"
        e["TPU_COMPILE_CACHE_DIR"] = str(tmp_path / "cc")
        repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        e["PYTHONPATH"] = repo + (
            os.pathsep + e["PYTHONPATH"] if e.get("PYTHONPATH") else "")
        return e

    def final_gen(ck):
        gens = ckpt_mod.list_generations(ck)
        assert gens, f"no generations under {ck}"
        manifest, arrays, _ = ckpt_mod.read_generation(gens[-1])
        return manifest, arrays

    # uninterrupted reference, cache OFF (the pre-cache engine verbatim)
    rdata, rck = str(tmp_path / "ref_d"), str(tmp_path / "ref_ck")
    proc = subprocess.run(
        [sys.executable, "-m", "avida_tpu"] + argv(rdata, rck),
        env=env(False), capture_output=True, text=True, timeout=900)
    assert proc.returncode == 0, proc.stderr[-2000:]
    rman, rarr = final_gen(rck)

    # the drill: cache ON, SIGKILL at update 5 (past the update-4 save)
    data, ck = str(tmp_path / "d"), str(tmp_path / "ck")
    sup = Supervisor(argv(data, ck), fault_plan=["sigkill@update=5"],
                     cfg=SupervisorConfig(watchdog_sec=300.0, poll_sec=0.25,
                                          grace_sec=900.0, max_retries=6,
                                          backoff_base=0.05,
                                          backoff_cap=0.2,
                                          healthy_sec=1e9, seed=3),
                     env=env(True))
    rc = sup.run()
    assert rc == 0 and sup.boots == 2
    log = open(os.path.join(data, "supervised.log")).read()
    # boot 1 compiled + stored its chunk programs; boot 2 (the resumed
    # boot -- the one feeding deserialized executables donated buffers)
    # LOADED every program and traced none: after the resume marker
    # there are loads and no compiles
    assert log.count("action=store") >= 1
    boot2 = log[log.rindex("checkpoint_restored"):]
    assert "action=load" in boot2, "resumed boot did not hit the cache"
    assert "action=compile" not in boot2, \
        "resumed boot paid a fresh compile despite a warm cache"
    man, arr = final_gen(ck)
    assert man["update"] == rman["update"] == 10
    assert set(arr) == set(rarr)
    for name in sorted(arr):
        np.testing.assert_array_equal(arr[name], rarr[name],
                                      err_msg=f"array {name}")


# ---------------------------------------------------------------------------
# fleet wiring: the spool-level shared cache env
# ---------------------------------------------------------------------------

def test_fleet_child_env_injects_spool_cache(tmp_path):
    """Every fleet child inherits TPU_COMPILE_CACHE_DIR=SPOOL/compile-cache
    unless the operator or the spec routed it -- sibling class children
    share one store (the cold-spawn satellite)."""
    from avida_tpu.service.fleet import FleetOrchestrator
    spool = str(tmp_path / "spool")
    fo = FleetOrchestrator(spool, env={})
    env = fo._child_env({})
    assert env["TPU_COMPILE_CACHE_DIR"] \
        == os.path.join(os.path.realpath(spool), "compile-cache")
    # spec env wins
    env = fo._child_env({"env": {"TPU_COMPILE_CACHE_DIR": "/elsewhere"}})
    assert env["TPU_COMPILE_CACHE_DIR"] == "/elsewhere"
    # operator base env wins too
    fo2 = FleetOrchestrator(spool, env={"TPU_COMPILE_CACHE_DIR": "/op"})
    assert fo2._child_env({})["TPU_COMPILE_CACHE_DIR"] == "/op"
