"""Config subsystem tests (parsers for avida.cfg / instset / .org /
environment.cfg / events.cfg -- SURVEY.md §5 config DSLs)."""

import os
import textwrap

import numpy as np
import pytest

from avida_tpu.config import (AvidaConfig, load_avida_cfg, load_instset,
                              default_instset, load_organism,
                              load_environment, load_events)
from avida_tpu.config.environment import default_logic9_environment
from avida_tpu.config.events import parse_event_line

REF = "/root/reference/avida-core/support/config"


def test_defaults_match_reference():
    cfg = AvidaConfig()
    assert cfg.AVE_TIME_SLICE == 30
    assert cfg.SLICING_METHOD == 1
    assert cfg.COPY_MUT_PROB == 0.0075
    assert cfg.DIVIDE_INS_PROB == 0.05
    assert cfg.BASE_MERIT_METHOD == 4
    assert cfg.WORLD_X == 60 and cfg.WORLD_GEOMETRY == 2


def test_load_avida_cfg(tmp_path):
    p = tmp_path / "avida.cfg"
    p.write_text(textwrap.dedent("""
        WORLD_X 30   # width
        WORLD_Y 20
        COPY_MUT_PROB 0.01
        RANDOM_SEED 42
        SOME_FUTURE_VAR xyz
    """))
    with pytest.warns(UserWarning):
        cfg = load_avida_cfg(str(p), overrides=[("WORLD_Y", "25")])
    assert cfg.WORLD_X == 30
    assert cfg.WORLD_Y == 25          # -set override wins
    assert cfg.COPY_MUT_PROB == 0.01
    assert cfg.extras["SOME_FUTURE_VAR"] == "xyz"


@pytest.mark.skipif(not os.path.isdir(REF), reason="reference not mounted")
def test_load_reference_instset():
    iset = load_instset(os.path.join(REF, "instset-heads.cfg"))
    assert iset.name == "heads_default"
    assert iset.hw_type == 0
    assert iset.num_insts == 26
    assert iset.inst_names[:3] == ["nop-A", "nop-B", "nop-C"]
    assert iset.inst_names == default_instset().inst_names


@pytest.mark.skipif(not os.path.isdir(REF), reason="reference not mounted")
def test_load_reference_organism():
    iset = default_instset()
    ops = load_organism(os.path.join(REF, "default-heads.org"), iset)
    assert len(ops) == 100
    assert iset.inst_names[ops[0]] == "h-alloc"
    assert iset.inst_names[ops[-1]] == "nop-B"
    # matches the built-in ancestor
    from avida_tpu.world import default_ancestor
    np.testing.assert_array_equal(ops, default_ancestor(iset))


@pytest.mark.skipif(not os.path.isdir(REF), reason="reference not mounted")
def test_load_reference_environment():
    env = load_environment(os.path.join(REF, "environment.cfg"))
    assert env.reaction_names() == ["NOT", "NAND", "AND", "ORN", "OR",
                                    "ANDN", "NOR", "XOR", "EQU"]
    t = env.device_tables()
    assert t["task_logic_mask"][0, 15]          # NOT includes logic id 15
    assert t["task_logic_mask"][8, 153]         # EQU includes 153
    assert list(t["max_task_count"]) == [1] * 9
    np.testing.assert_allclose(t["proc_value"],
                               [1, 1, 2, 2, 3, 3, 4, 4, 5])
    builtin = default_logic9_environment().device_tables()
    np.testing.assert_array_equal(t["task_logic_mask"], builtin["task_logic_mask"])


@pytest.mark.skipif(not os.path.isdir(REF), reason="reference not mounted")
def test_load_reference_events():
    evs = load_events(os.path.join(REF, "events.cfg"))
    actions = [e.action for e in evs]
    assert "Inject" in actions and "Exit" in actions
    inj = evs[actions.index("Inject")]
    assert inj.args == ["default-heads.org"]
    exit_ev = evs[actions.index("Exit")]
    assert exit_ev.start == 100000


def test_event_timing():
    ev = parse_event_line("u 0:100:end PrintAverageData")
    assert ev.fires_at(0) and ev.fires_at(100) and ev.fires_at(5000)
    assert not ev.fires_at(50)
    once = parse_event_line("u 100000 Exit")
    assert once.fires_at(100000) and not once.fires_at(100001)
    begin = parse_event_line("u begin Inject foo.org")
    assert begin.fires_at(0) and not begin.fires_at(1)
