"""Instruction cost engine, redundancy-weighted mutations, mutation
completeness (copy-ins/del, slip).

Reference: cHardwareBase::SingleProcess_PayPreCosts (cc:1241), redundancy-
weighted cInstSet::GetRandomInst (cpu/cInstSet.h:52), Divide_DoMutations
copy-lifetime insert/delete + doSlipMutation (cHardwareBase.cc:296,621).
"""

from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from avida_tpu.config import AvidaConfig
from avida_tpu.config.instset import default_instset
from avida_tpu.config.environment import default_logic9_environment
from avida_tpu.core.state import make_world_params, zeros_population
from avida_tpu.ops.interpreter import micro_step, random_inst, extract_offspring


def _params(instset=None, **cfg_kw):
    cfg = AvidaConfig()
    cfg.WORLD_X = 4
    cfg.WORLD_Y = 4
    cfg.TPU_MAX_MEMORY = 64
    for k, v in cfg_kw.items():
        cfg.set(k, v)
    return make_world_params(cfg, instset or default_instset(),
                             default_logic9_environment())


def _one_org(params, program):
    n, L, R = params.num_cells, params.max_memory, params.num_reactions
    st = zeros_population(n, L, R)
    tape = np.zeros((n, L), np.uint8)
    tape[0, : len(program)] = program
    return st.replace(
        tape=jnp.asarray(tape),
        mem_len=st.mem_len.at[0].set(len(program)),
        genome_len=st.genome_len.at[0].set(len(program)),
        alive=st.alive.at[0].set(True))


def test_redundancy_biases_mutation_draws():
    """A 10x-redundant opcode must be drawn ~10x as often (GetRandomInst)."""
    s = default_instset()
    s.redundancy[:] = 1.0
    s.redundancy[5] = 10.0        # if-label 10x
    params = _params(instset=s)
    draws = np.asarray(random_inst(params, jax.random.key(0), (20000,)))
    counts = np.bincount(draws, minlength=params.num_insts)
    frac5 = counts[5] / draws.size
    expect = 10.0 / (params.num_insts - 1 + 10.0)
    assert abs(frac5 - expect) < 0.02, (frac5, expect)
    # uniform opcodes stay uniform relative to each other
    others = counts[np.arange(params.num_insts) != 5]
    assert others.std() / others.mean() < 0.2


def test_instruction_cost_slows_the_right_instruction():
    """cost=3 on `inc` makes each inc take 3 cycles; nop-heavy code is
    unaffected (SingleProcess_PayPreCosts)."""
    s = default_instset()
    inc_op = s.opcode("inc")
    s.cost[inc_op] = 3
    params = _params(instset=s)
    prog_inc = [inc_op] * 8                      # pure inc program
    nopA = s.opcode("nop-A")
    st = _one_org(params, prog_inc)
    exec_mask = st.alive
    for c in range(6):
        st = micro_step(params, st, jax.random.key(c), exec_mask)
    # 6 cycles at cost 3 => exactly 2 incs executed: BX == 2
    assert int(st.regs[0, 1]) == 2, np.asarray(st.regs[0])
    assert int(st.time_used[0]) == 6             # cycles still consumed

    # same program with zero-cost set: 6 incs in 6 cycles
    params0 = _params()
    st0 = _one_org(params0, prog_inc)
    for c in range(6):
        st0 = micro_step(params0, st0, jax.random.key(c), st0.alive)
    assert int(st0.regs[0, 1]) == 6


def test_first_time_cost_charged_once():
    """ft_cost=4 on inc: the first inc costs 1+4, later incs cost 1."""
    s = default_instset()
    inc_op = s.opcode("inc")
    s.ft_cost[inc_op] = 4
    params = _params(instset=s)
    st = _one_org(params, [inc_op] * 12)
    for c in range(9):
        st = micro_step(params, st, jax.random.key(c), st.alive)
    # first inc: 5 cycles; remaining 4 cycles: 4 incs => BX == 5
    assert int(st.regs[0, 1]) == 5, np.asarray(st.regs[0])


def _offspring_lengths(params, n_samples=512, seed=0):
    """Sample offspring lengths from extract_offspring on synthetic
    pending divides of length 40."""
    n, L, R = params.num_cells, params.max_memory, params.num_reactions
    lens = []
    st = zeros_population(n, L, R)
    tape = np.zeros((n, L), np.uint8)
    tape[:, :40] = 3
    st = st.replace(
        tape=jnp.asarray(tape),
        genome_len=jnp.full(n, 40, jnp.int32),
        mem_len=jnp.full(n, 40, jnp.int32),
        alive=jnp.ones(n, bool),
        divide_pending=jnp.ones(n, bool),
        off_len=jnp.full(n, 40, jnp.int32),
    )
    for s in range(n_samples // n):
        _, off_len = extract_offspring(params, st, jax.random.key(seed + s))
        lens.extend(np.asarray(off_len).tolist())
    return np.asarray(lens)


def test_copy_ins_del_shift_length_distribution():
    base = _params(DIVIDE_INS_PROB=0.0, DIVIDE_DEL_PROB=0.0)
    l0 = _offspring_lengths(base)
    assert (l0 == 40).all()

    ins = _params(DIVIDE_INS_PROB=0.0, DIVIDE_DEL_PROB=0.0,
                  COPY_INS_PROB=0.02)
    li = _offspring_lengths(ins)
    # E[insertions] = 40 * 0.02 = 0.8 per offspring
    assert li.mean() > 40.3, li.mean()
    assert (li >= 40).all()

    dele = _params(DIVIDE_INS_PROB=0.0, DIVIDE_DEL_PROB=0.0,
                   COPY_DEL_PROB=0.02)
    ld = _offspring_lengths(dele)
    assert ld.mean() < 39.7, ld.mean()
    assert (ld <= 40).all()


def test_slip_mutation_duplicates_and_deletes_regions():
    slip = _params(DIVIDE_INS_PROB=0.0, DIVIDE_DEL_PROB=0.0,
                   DIVIDE_SLIP_PROB=1.0)
    ls = _offspring_lengths(slip, n_samples=256)
    # every divide slips: lengths spread both ways around 40
    assert (ls > 40).any() and (ls < 40).any(), ls[:20]
    assert ls.min() >= slip.min_genome_len
    assert ls.max() <= 64


def test_instruction_costs_stay_on_the_pallas_kernel():
    """Round 5 widened kernel eligibility: costs and redundancy weights
    are handled in-kernel now (tests/test_pallas.py has the equivalence
    proof); only the energy model and resource-coupled reactions rout
    off."""
    from avida_tpu.ops.pallas_cycles import eligible
    s = default_instset()
    s.cost[s.opcode("inc")] = 3
    assert eligible(_params(instset=s))
    s2 = default_instset()
    s2.redundancy[0] = 5.0
    assert eligible(_params(instset=s2))
    assert eligible(_params())
    assert not eligible(_params(ENERGY_ENABLED=1))


def test_prob_fail_suppresses_effect_but_charges_time():
    """prob_fail=1: the instruction is flagged executed, IP advances, and
    time_used accrues, but the effect never happens (cHardwareCPU.cc:988)."""
    s = default_instset()
    s.prob_fail[s.opcode("inc")] = 1.0
    params = _params(instset=s)
    inc = s.opcode("inc")
    st = _one_org(params, [inc] * 8)
    mask = jnp.zeros(params.num_cells, bool).at[0].set(True)
    key = jax.random.key(3)
    for _ in range(4):
        key, k = jax.random.split(key)
        st = micro_step(params, st, k, mask)
    assert int(st.regs[0].sum()) == 0          # no increments landed
    assert int(st.time_used[0]) == 4           # cycles still paid
    assert int(st.heads[0, 0]) == 4            # IP advanced 1/cycle
    # executed flags set on every visited site (division viability intact)
    assert int(((np.asarray(st.tape[0, :4]) >> 6) & 1).sum()) == 4

    # prob_fail=0 control: the same program increments
    s0 = default_instset()
    params0 = _params(instset=s0)
    st0 = _one_org(params0, [inc] * 8)
    for _ in range(4):
        key, k = jax.random.split(key)
        st0 = micro_step(params0, st0, k, mask)
    assert int(st0.regs[0].sum()) == 4


def test_addl_time_cost_inflates_time_used_only():
    """addl_time_cost adds to time_used (gestation) without consuming extra
    scheduler cycles (cHardwareCPU.cc:985,1015)."""
    s = default_instset()
    s.addl_time_cost[s.opcode("inc")] = 2
    params = _params(instset=s)
    inc = s.opcode("inc")
    st = _one_org(params, [inc] * 8)
    mask = jnp.zeros(params.num_cells, bool).at[0].set(True)
    key = jax.random.key(4)
    for _ in range(3):
        key, k = jax.random.split(key)
        st = micro_step(params, st, k, mask)
    assert int(st.regs[0].sum()) == 3          # all executed normally
    assert int(st.time_used[0]) == 3 * (1 + 2)
    assert int(st.cpu_cycles[0]) == 3


def test_res_cost_refuses_at_load():
    s = default_instset()
    s.res_cost[s.opcode("inc")] = 1.0
    with pytest.raises(NotImplementedError):
        _params(instset=s)


def test_prob_fail_stays_on_the_pallas_kernel():
    from avida_tpu.ops.pallas_cycles import eligible
    s = default_instset()
    s.prob_fail[s.opcode("inc")] = 0.5
    assert eligible(_params(instset=s))
    s2 = default_instset()
    s2.addl_time_cost[s2.opcode("inc")] = 1
    assert eligible(_params(instset=s2))
