"""Data provider/recorder registry (ref include/public/avida/data/
Manager.h): providers resolve by dotted ID, recorders subscribe, and the
generic PrintData action turns ID lists into .dat files without World
edits.  Golden-format checks: tasks_exe.dat and tasks_quality.dat rows
match the reference's expected output for the pre-evolution window
(tests/heads_default_100u/expected/data -- all-zero task columns at
10-update cadence)."""

from __future__ import annotations

import os

import numpy as np
import pytest

from avida_tpu.config import AvidaConfig
from avida_tpu.world import World, parse_event_line


def _world(tmp_path, extra_events=()):
    cfg = AvidaConfig()
    cfg.WORLD_X = 10
    cfg.WORLD_Y = 10
    cfg.RANDOM_SEED = 7
    w = World(cfg=cfg, data_dir=str(tmp_path))
    for line in extra_events:
        w.events.append(parse_event_line(line))
    return w


def test_provider_registry_resolves_and_lists():
    cfg = AvidaConfig()
    cfg.WORLD_X = 5
    cfg.WORLD_Y = 5
    w = World(cfg=cfg)
    w.inject()
    assert "core.world.ave_fitness" in w.data.available()
    assert w.data.resolve("core.world.organisms") == 1
    with pytest.raises(KeyError):
        w.data.resolve("no.such.id")


def test_custom_provider_and_recorder_no_world_edit(tmp_path):
    """A new stat + writer registered entirely from outside World."""
    from avida_tpu.utils.data_registry import DatRecorder
    w = _world(tmp_path)
    w.inject()
    w.data.register("user.longest_genome", "Longest live genome",
                    lambda world: int(np.asarray(world.state.genome_len)[
                        np.asarray(world.state.alive)].max()))
    rec = DatRecorder(str(tmp_path), "custom.dat", "Custom data",
                      [("core.update", "Update"),
                       ("user.longest_genome", "Longest live genome")])
    w.data.attach(rec)
    w.data.process(w.update)
    body = [ln for ln in open(tmp_path / "custom.dat").read().splitlines()
            if ln and not ln.startswith("#")]
    assert body[0].split() == ["0", "100"]


def test_print_data_action(tmp_path):
    w = _world(tmp_path, extra_events=[
        "u 0:5:end PrintData mystats.dat core.update,core.world.organisms,"
        "core.world.ave_merit"])
    w.run(max_updates=11)
    lines = [ln for ln in open(tmp_path / "mystats.dat").read().splitlines()
             if ln and not ln.startswith("#")]
    assert len(lines) >= 2
    first = lines[0].split()
    assert first[0] == "0" and int(first[1]) >= 1


def test_tasks_exe_and_quality_match_golden_window(tmp_path):
    """Rows at the golden cadence: update column + all-zero task columns
    before any task evolves (the reference's heads_default_100u expected
    tasks_exe.dat / tasks_quality.dat)."""
    w = _world(tmp_path, extra_events=[
        "u 0:10:end PrintTasksExeData",
        "u 0:10:end PrintTasksQualData",
        "u 0:10:end PrintInstructionAbundanceHistogram",
    ])
    w.run(max_updates=41)

    ref_dir = ("/root/reference/avida-core/tests/heads_default_100u/"
               "expected/data")
    for fname, ncols in (("tasks_exe.dat", 10), ("tasks_quality.dat", 19)):
        got = [ln.split() for ln in
               open(os.path.join(tmp_path, fname)).read().splitlines()
               if ln and not ln.startswith("#")]
        assert len(got) >= 4, fname
        ref_rows = []
        if os.path.isdir(ref_dir):
            ref_rows = [ln.split() for ln in
                        open(os.path.join(ref_dir, fname)).read().splitlines()
                        if ln and not ln.startswith("#")]
        for i, row in enumerate(got[:4]):
            assert len(row) == ncols, (fname, row)
            assert row[0] == str(i * 10)
            # golden window: no tasks before update 40 at 10x10 from one
            # ancestor -> every task column is 0, matching the reference
            assert all(v in ("0",) for v in row[1:]), (fname, row)
            if ref_rows:
                assert row == ref_rows[i][:ncols], (fname, i)

    # instruction histogram: counts sum to total live genome length
    hist = [ln.split() for ln in
            open(tmp_path / "instruction_histogram.dat").read().splitlines()
            if ln and not ln.startswith("#")]
    st = w.state
    alive = np.asarray(st.alive)
    last = hist[-1]
    assert sum(int(x) for x in last[1:]) == int(
        np.asarray(st.genome_len)[alive].sum())
