"""Deme predicates + non-uniform migration (round-5, VERDICT r4
directive #10): Pred_DemeResourceThresholdPredicate gating ReplicateDemes
(PopulationActions.cc:4421, cPopulation.cc:3008 DEME_TRIGGER_PREDICATE)
and DEMES_MIGRATION_METHOD 1/2/4 (cPopulation.cc:5508-5600,
cMigrationMatrix::GetProbabilisticDemeID)."""

from __future__ import annotations

import os
import tempfile

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from avida_tpu.config import AvidaConfig
from avida_tpu.config.environment import load_environment
from avida_tpu.config.instset import default_instset
from avida_tpu.core.state import make_world_params


def _deme_env():
    d = tempfile.mkdtemp()
    path = os.path.join(d, "environment.cfg")
    with open(path, "w") as f:
        f.write("RESOURCE food:initial=100:inflow=0:outflow=0"
                ":demeresource=1\n"
                "REACTION NOT not process:value=1.0:type=pow:resource=food"
                ":frac=0.1:max=5\n")
    return load_environment(path)


def test_predicate_gated_replication():
    """Only demes whose pool satisfies the predicate replicate."""
    from avida_tpu.ops import demes as deme_ops
    from avida_tpu.core.state import zeros_population, make_cell_inputs

    cfg = AvidaConfig()
    cfg.WORLD_X = 4
    cfg.WORLD_Y = 4
    cfg.NUM_DEMES = 4
    params = make_world_params(cfg, default_instset(), _deme_env())
    n, L, R = params.num_cells, params.max_memory, params.num_reactions
    st = zeros_population(n, L, R, n_deme_res=1, n_demes=4)
    st = st.replace(
        inputs=make_cell_inputs(jax.random.key(0), n),
        alive=jnp.ones(n, bool),
        mem_len=jnp.full(n, 10, jnp.int32),
        genome_len=jnp.full(n, 10, jnp.int32),
        merit=jnp.ones(n, jnp.float32),
        # demes 0,2 below the threshold; 1,3 above
        deme_resources=jnp.asarray([[10.0], [90.0], [20.0], [95.0]]))

    st2 = deme_ops.replicate_demes(
        params, st, jax.random.key(1), deme_ops.TRIGGER_PREDICATE,
        predicates=((0, ">=", 50.0),))
    # satisfied demes (1, 3) replicated into victims; their deme ages reset
    assert int(st2.deme_age[1]) == 0 and int(st2.deme_age[3]) == 0

    with pytest.raises(ValueError):
        deme_ops.replicate_demes(params, st, jax.random.key(1),
                                 deme_ops.TRIGGER_PREDICATE, predicates=())


def test_predicate_action_via_world(tmp_path):
    """End-to-end: the predicate action + sat-deme-predicate event."""
    from avida_tpu.world import World
    d = tmp_path / "cfg"
    d.mkdir()
    (d / "avida.cfg").write_text(
        "WORLD_X 4\nWORLD_Y 4\nNUM_DEMES 4\nRANDOM_SEED 7\n"
        "ENVIRONMENT_FILE environment.cfg\nEVENT_FILE events.cfg\n")
    (d / "environment.cfg").write_text(
        "RESOURCE food:initial=100:inflow=0:outflow=0:demeresource=1\n"
        "REACTION NOT not process:value=1.0:type=pow:resource=food"
        ":frac=0.1:max=5\n")
    (d / "events.cfg").write_text(
        "u begin Inject default-heads.org\n"
        "u begin Pred_DemeResourceThresholdPredicate food >= 50\n"
        "u 2 ReplicateDemes sat-deme-predicate\n"
        "u 4 Exit\n")
    w = World(config_dir=str(d), data_dir=str(tmp_path / "data"))
    w.run(max_updates=5)
    assert getattr(w, "_deme_predicates", None) == [(0, ">=", 50.0)]


def _mig_params(method, num_demes=4, demes_num_x=0, matrix=None):
    cfg = AvidaConfig()
    cfg.WORLD_X = 4
    cfg.WORLD_Y = num_demes
    cfg.NUM_DEMES = num_demes
    cfg.DEMES_MIGRATION_RATE = 1.0
    cfg.DEMES_MIGRATION_METHOD = method
    cfg.DEMES_NUM_X = demes_num_x
    if matrix is not None:
        cfg._migration_matrix = matrix
    from avida_tpu.config.environment import default_logic9_environment
    return make_world_params(cfg, default_instset(),
                             default_logic9_environment())


def _migration_targets(params, seed=0):
    """Place one pending parent in deme 0 and read where its offspring
    lands, across seeds."""
    from avida_tpu.core.state import zeros_population, make_cell_inputs
    from avida_tpu.ops import birth as birth_ops
    n, L, R = params.num_cells, params.max_memory, params.num_reactions
    cpd = n // params.num_demes
    st = zeros_population(n, L, R, n_demes=params.num_demes)
    g = jnp.zeros((n, L), jnp.uint8)
    st = st.replace(
        inputs=make_cell_inputs(jax.random.key(9), n),
        alive=jnp.zeros(n, bool).at[0].set(True),
        mem_len=jnp.full(n, 12, jnp.int32),
        genome_len=jnp.full(n, 12, jnp.int32),
        merit=jnp.ones(n, jnp.float32),
        divide_pending=jnp.zeros(n, bool).at[0].set(True),
        off_len=jnp.zeros(n, jnp.int32).at[0].set(12),
        off_tape=g)
    neighbors = jnp.asarray(birth_ops.neighbor_table(
        params.world_x, params.world_y, 2))
    st2 = birth_ops.flush_births(params, st, jax.random.key(seed),
                                 neighbors, jnp.int32(1),
                                 use_off_tape=True)
    born = np.asarray(st2.alive) & ~np.asarray(st.alive)
    cells = np.nonzero(born)[0]
    return (cells // cpd).tolist()


def test_migration_method_2_adjacent():
    """Method 2: offspring lands in deme +-1 (ring)."""
    p = _mig_params(2, num_demes=4)
    demes = set()
    for s in range(12):
        demes.update(_migration_targets(p, seed=s))
    assert demes <= {1, 3}, demes
    assert len(demes) == 2


def test_migration_method_1_deme_grid():
    """Method 1: 8-neighbor on the DEMES_NUM_X deme grid (2x2 grid: every
    neighbor of deme 0 is one of demes 1,2,3)."""
    p = _mig_params(1, num_demes=4, demes_num_x=2)
    demes = set()
    for s in range(16):
        demes.update(_migration_targets(p, seed=s))
    assert demes <= {0, 1, 2, 3}
    assert len(demes) >= 2


def test_migration_method_4_matrix():
    """Method 4: MIGRATION_FILE weights; deme 0 sends ONLY to deme 2."""
    p = _mig_params(4, num_demes=4, matrix=[
        [0, 0, 1, 0], [1, 0, 0, 0], [0, 1, 0, 0], [0, 0, 1, 0]])
    demes = set()
    for s in range(8):
        demes.update(_migration_targets(p, seed=s))
    assert demes == {2}, demes


def test_migration_method_3_refuses():
    with pytest.raises(NotImplementedError):
        _mig_params(3)


def test_migration_file_parsed_by_world(tmp_path):
    """End-to-end method 4: MIGRATION_FILE is read from the config dir
    (cMigrationMatrix::Load)."""
    from avida_tpu.world import World
    d = tmp_path / "cfg"
    d.mkdir()
    (d / "avida.cfg").write_text(
        "WORLD_X 4\nWORLD_Y 4\nNUM_DEMES 4\nRANDOM_SEED 3\n"
        "DEMES_MIGRATION_RATE 0.5\nDEMES_MIGRATION_METHOD 4\n"
        "MIGRATION_FILE migration.mat\nEVENT_FILE events.cfg\n")
    (d / "migration.mat").write_text(
        "0 0 1 0\n1 0 0 0\n0 1 0 0\n0 0 1 0\n")
    (d / "events.cfg").write_text("u begin Inject default-heads.org\n")
    w = World(config_dir=str(d))
    assert len(w.params.migration_cdf) == 4
    assert w.params.migration_cdf[0][2] == 1.0   # deme 0 -> only deme 2
