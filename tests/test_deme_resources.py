"""Reaction by-products + per-deme resource pools (round-4, VERDICT r3
directive #9).

 - A reaction consuming resource A with product:B converts consumed units
   into B at `conversion` (cEnvironment::DoProcesses cc:1824-1830).
 - RESOURCE ...:demeresource=1 pools are per-deme slices (cDeme resource
   slice; cResource::SetDemeResource): demes draw down independently.
"""

from __future__ import annotations

import os
import tempfile

import numpy as np
import pytest

from avida_tpu.config import AvidaConfig
from avida_tpu.config.environment import load_environment
from avida_tpu.world import World


def _env_file(text):
    d = tempfile.mkdtemp()
    path = os.path.join(d, "environment.cfg")
    with open(path, "w") as f:
        f.write(text)
    return path


def test_product_conversion_parses_and_produces():
    env = load_environment(_env_file(
        "RESOURCE resA:inflow=100:outflow=0.01:initial=1000\n"
        "RESOURCE resB:inflow=0:outflow=0.0:initial=0\n"
        "REACTION NOT not process:value=1.0:type=pow:resource=resA:frac=0.5"
        ":max=10:product=resB:conversion=2.0\n"))
    t = env.device_tables()
    assert t["proc_product_idx"][0] == 1      # resB
    assert t["proc_conversion"][0] == 2.0

    import jax.numpy as jnp
    from avida_tpu.core.state import make_world_params
    from avida_tpu.config.instset import default_instset
    from avida_tpu.ops import tasks as tasks_ops

    cfg = AvidaConfig()
    cfg.WORLD_X = cfg.WORLD_Y = 4
    params = make_world_params(cfg, default_instset(), env)
    tables = tasks_ops.env_tables_to_device(params)
    n, R = 16, params.num_reactions
    rewarded_now = jnp.zeros((n, R), bool).at[3, 0].set(True)
    # drive apply_reactions directly: logic id for NOT on inputs
    out = tasks_ops.apply_reactions(
        params, tables, jnp.zeros(n, bool).at[3].set(True),
        jnp.full(n, -1, jnp.int32).at[3].set(
            int(np.flatnonzero(np.asarray(params.task_logic_mask[0]))[0])),
        jnp.ones(n, jnp.float32), jnp.zeros((n, R), jnp.int32),
        jnp.zeros((n, R), jnp.int32),
        jnp.asarray(params.res_initial, jnp.float32),
        jnp.zeros((0, n), jnp.float32))
    resources = np.asarray(out[3])
    # resA consumed min(1000*0.5, 10) = 10; resB produced 10 * 2 = 20
    assert resources[0] == pytest.approx(990.0)
    assert resources[1] == pytest.approx(20.0)


def test_deme_resources_draw_down_independently():
    env_path = _env_file(
        "RESOURCE food:inflow=0:outflow=0.0:initial=100:demeresource=1\n"
        "REACTION NOT not process:value=1.0:type=pow:resource=food:frac=1.0"
        ":max=5\n")
    cfg = AvidaConfig()
    cfg.WORLD_X = 8
    cfg.WORLD_Y = 8
    cfg.RANDOM_SEED = 3
    cfg.AVE_TIME_SLICE = 100
    cfg.set("NUM_DEMES", 2)
    cfg.set("TPU_SYSTEMATICS", 0)
    w = World(cfg=cfg)
    w.environment = load_environment(env_path)
    from avida_tpu.core.state import make_world_params
    w.params = make_world_params(cfg, w.instset, w.environment)
    assert w.params.num_deme_res == 1
    # a minimal NOT-performer: BX <- input; CX <- BX; BX <- nand(BX, CX)
    # = ~input; output BX  (no replication needed for this test)
    n2o = {n: i for i, n in enumerate(w.instset.inst_names)}
    prog = [n2o[x] for x in
            ["IO", "nop-B", "push", "nop-B", "pop", "nop-C",
             "nand", "nop-B", "IO", "nop-B"]]
    w.inject(genome=np.asarray(prog, np.int8), cell=5)   # deme 0 only
    st = w.state
    assert st.deme_resources.shape == (2, 1)
    np.testing.assert_allclose(np.asarray(st.deme_resources), 100.0)
    for u in range(6):
        w.run_update()
        w.update += 1
    pools = np.asarray(w.state.deme_resources)
    # deme 0 (the only populated one) drew food down; deme 1 untouched
    assert pools[0, 0] < 100.0
    assert pools[1, 0] == pytest.approx(100.0)
