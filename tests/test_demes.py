"""Demes: group structure, deme-local placement, competition, germlines.

Covers BASELINE.json config 5 (multi-deme group selection).  Reference:
cDeme (main/cDeme.h:52), cPopulation::CompeteDemes / ReplicateDemes /
ReplaceDeme, germlines (main/cGermline.h:31); scenarios modeled on the
reference demes_* golden tests.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from avida_tpu.config import AvidaConfig
from avida_tpu.config.environment import default_logic9_environment
from avida_tpu.config.instset import default_instset
from avida_tpu.core.state import make_world_params, zeros_population
from avida_tpu.ops import demes as deme_ops
from avida_tpu.world import World

import pytest  # noqa: E402

pytestmark = pytest.mark.slow


def _params(num_demes=2, side=8, L=64, **kw):
    cfg = AvidaConfig()
    cfg.WORLD_X = side
    cfg.WORLD_Y = side
    cfg.TPU_MAX_MEMORY = L
    cfg.NUM_DEMES = num_demes
    cfg.RANDOM_SEED = 5
    for k, v in kw.items():
        cfg.set(k, v)
    return make_world_params(cfg, default_instset(),
                             default_logic9_environment())


def test_deme_local_placement():
    """An offspring of a deme-0 parent on the deme boundary never lands in
    deme 1 (without migration)."""
    from avida_tpu.ops import birth as birth_ops
    params = _params(num_demes=2)
    n, L = params.num_cells, params.max_memory
    cpd = n // 2
    st = zeros_population(n, L, params.num_reactions, n_demes=2)
    # parent on the last row of deme 0 (boundary cells)
    parent = cpd - 4
    tape = jnp.zeros((n, L), jnp.uint8).at[parent, :20].set(3)
    st = st.replace(
        tape=tape, genome=tape.astype(jnp.int8),
        alive=st.alive.at[parent].set(True),
        merit=st.merit.at[parent].set(10.0),
        divide_pending=st.divide_pending.at[parent].set(True),
        off_len=st.off_len.at[parent].set(20),
        mem_len=st.mem_len.at[parent].set(20),
        genome_len=st.genome_len.at[parent].set(20),
    )
    neighbors = jnp.asarray(birth_ops.neighbor_table(
        params.world_x, params.world_y, params.geometry))
    for s in range(12):
        st2 = birth_ops.flush_births(params, st, jax.random.key(s),
                                     neighbors, jnp.int32(0))
        born = np.nonzero(np.asarray(st2.alive))[0]
        assert all(b < cpd for b in born), f"birth crossed deme: {born}"
    assert int(st2.deme_birth_count[0]) == 1
    assert int(st2.deme_birth_count[1]) == 0


def test_compete_demes_birth_count_fitness():
    """competition_type 1: the deme with all the births takes over."""
    params = _params(num_demes=4, side=8)
    n, L = params.num_cells, params.max_memory
    st = zeros_population(n, L, params.num_reactions, n_demes=4)
    cpd = n // 4
    # deme 2 is populated with marked genomes and has all the births
    tape = np.zeros((n, L), np.uint8)
    alive = np.zeros(n, bool)
    for c in range(2 * cpd, 3 * cpd):
        tape[c, :10] = 7
        alive[c] = True
    st = st.replace(
        tape=jnp.asarray(tape), genome=jnp.asarray(tape.astype(np.int8)),
        genome_len=jnp.where(jnp.asarray(alive), 10, 0),
        mem_len=jnp.where(jnp.asarray(alive), 10, 0),
        alive=jnp.asarray(alive),
        merit=jnp.where(jnp.asarray(alive), 5.0, 0.0).astype(jnp.float32),
        deme_birth_count=jnp.asarray([0, 0, 50, 0], jnp.int32),
        time_used=jnp.full(n, 99, jnp.int32),   # must reset on clone
    )
    st2 = deme_ops.compete_demes(params, st, jax.random.key(0), 1)
    alive2 = np.asarray(st2.alive).reshape(4, cpd)
    # every deme is now a copy of deme 2's block
    assert alive2.all(axis=1).any() or alive2.any(axis=1).all()
    for d in range(4):
        assert alive2[d].sum() == cpd, f"deme {d} not fully cloned"
    g = np.asarray(st2.genome)
    assert (g[0, :10] == 7).all()              # genome copied
    assert int(st2.time_used[0]) == 0          # hardware state fresh
    assert np.asarray(st2.deme_birth_count).sum() == 0   # counters reset


def test_replicate_demes_germline():
    """Germline replication: target deme cleared, center-seeded with the
    (possibly mutated) source germline; both germlines updated."""
    params = _params(num_demes=2, side=8, DEMES_USE_GERMLINE=1,
                     GERMLINE_COPY_MUT=0.0, DEMES_MAX_BIRTHS=3)
    n, L = params.num_cells, params.max_memory
    cpd = n // 2
    st = zeros_population(n, L, params.num_reactions, n_demes=2)
    germ = np.zeros((2, L), np.int8)
    germ[0, :15] = 4
    st = st.replace(
        alive=(jnp.arange(n) < cpd),          # deme 0 fully occupied
        genome_len=jnp.where(jnp.arange(n) < cpd, 15, 0),
        mem_len=jnp.where(jnp.arange(n) < cpd, 15, 0),
        germ_mem=jnp.asarray(germ), germ_len=jnp.asarray([15, 0], jnp.int32),
        deme_birth_count=jnp.asarray([5, 0], jnp.int32),
    )
    st2 = deme_ops.replicate_demes(params, st, jax.random.key(1),
                                   deme_ops.TRIGGER_BIRTHS)
    alive2 = np.asarray(st2.alive)
    # deme 1 now holds exactly one organism: the germline seed at center
    assert alive2[cpd:].sum() == 1
    seed_cell = cpd + np.nonzero(alive2[cpd:])[0][0]
    assert (np.asarray(st2.genome[seed_cell])[:15] == 4).all()
    assert int(st2.germ_len[1]) == 15
    assert (np.asarray(st2.germ_mem[1])[:15] == 4).all()
    assert int(st2.deme_birth_count[0]) == 0   # source counters reset


def test_multi_deme_world_with_competition():
    """End-to-end: multi-deme world runs with periodic CompeteDemes and
    sustains its population (reference demes scenarios)."""
    from avida_tpu.config.events import parse_event_line
    cfg = AvidaConfig()
    cfg.WORLD_X = 12
    cfg.WORLD_Y = 12
    cfg.TPU_MAX_MEMORY = 320
    cfg.NUM_DEMES = 4
    cfg.RANDOM_SEED = 23
    cfg.AVE_TIME_SLICE = 100
    cfg.TPU_MAX_STEPS_PER_UPDATE = 100
    cfg.set("TPU_SYSTEMATICS", 0)
    w = World(cfg=cfg)
    w.events = [parse_event_line("u 10:10:end CompeteDemes 1")]
    w.inject()                                  # ancestor in deme 2 (center)
    w.run(max_updates=40)
    assert w.num_organisms > 4, w.num_organisms
    # competition replicated the seeded deme's lineage into other demes
    alive = np.asarray(w.state.alive).reshape(4, -1)
    assert (alive.sum(axis=1) > 0).sum() >= 2, alive.sum(axis=1)
