"""DIVIDE_METHOD / GENERATION_INC_METHOD / DIV_MUT_PROB physics.

Round-4 fix for parsed-but-ignored config vars (VERDICT r3 weak #5):
 - DIVIDE_METHOD 1 (default, SPLIT): the dividing parent's clock fully
   resets (cPhenotype::DivideReset cc:1037-1039); method 0 leaves the
   mother's clock running.
 - GENERATION_INC_METHOD 1 (default, BOTH): parent generation increments
   at divide too (cc:1052); method 0 increments only the offspring.
 - DIV_MUT_PROB: per-site substitution applied on divide
   (cHardwareBase::Divide_DoMutations cc:434).
"""

from __future__ import annotations

import numpy as np

from avida_tpu.config import AvidaConfig
from avida_tpu.world import World


def _world(**over):
    cfg = AvidaConfig()
    cfg.WORLD_X = 8
    cfg.WORLD_Y = 8
    cfg.TPU_MAX_MEMORY = 200
    cfg.RANDOM_SEED = 7
    cfg.COPY_MUT_PROB = 0.0
    cfg.DIVIDE_INS_PROB = 0.0
    cfg.DIVIDE_DEL_PROB = 0.0
    cfg.SLICING_METHOD = 0
    cfg.AVE_TIME_SLICE = 100
    cfg.set("TPU_SYSTEMATICS", 0)
    for k, v in over.items():
        cfg.set(k, v)
    w = World(cfg=cfg)
    w.inject()
    return w


def _run(w, updates):
    for u in range(updates):
        w.run_update()
        w.update += 1
    return w.state


def test_divide_method_1_resets_parent_clock():
    st = _run(_world(DIVIDE_METHOD=1), 6)
    divided = np.asarray(st.alive & (st.num_divides > 0))
    assert divided.any(), "no divide happened; lengthen the run"
    t = np.asarray(st.time_used)[divided]
    g = np.asarray(st.gestation_time)[divided]
    # clock restarted at last divide: lifetime-age < one full gestation
    # cannot hold for every parent unless time_used was reset
    assert (t < g + np.asarray(st.cpu_cycles)[divided] + 1).all()
    assert t.min() < g.min(), (
        "no divided parent shows a post-reset clock (time_used should "
        "restart at 0 on divide under DIVIDE_METHOD 1)")


def test_divide_method_0_keeps_parent_clock():
    st = _run(_world(DIVIDE_METHOD=0), 6)
    divided = np.asarray(st.alive & (st.num_divides > 0))
    assert divided.any()
    t = np.asarray(st.time_used)[divided]
    g = np.asarray(st.gestation_time)[divided]
    # mother untouched: age keeps counting from birth, so every divided
    # parent is at least one full gestation old
    assert (t >= g).all()


def test_generation_inc_method():
    st1 = _run(_world(GENERATION_INC_METHOD=1), 6)
    gens1 = np.asarray(st1.generation)[np.asarray(st1.alive)]
    # BOTH: the original parent itself advanced to generation >= 1
    assert gens1.min() >= 1

    st0 = _run(_world(GENERATION_INC_METHOD=0), 6)
    alive0 = np.asarray(st0.alive)
    gens0 = np.asarray(st0.generation)[alive0]
    divided0 = np.asarray(st0.num_divides)[alive0] > 0
    # offspring-only: a divided ancestor stays at its birth generation
    assert gens0[divided0].min() == 0
    assert gens0.max() >= 1        # children did increment


def test_div_mut_prob_substitutes_sites():
    # with ONLY DIV_MUT_PROB active (copy/divide ins/del all zero), any
    # alive organism whose genome differs from the ancestor proves the
    # per-site divide substitutions are applied
    w = _world(DIV_MUT_PROB=0.2)
    seed_cell = int(np.argmax(np.asarray(w.state.alive)))
    anc = np.asarray(w.state.genome[seed_cell])
    st = _run(w, 10)
    alive = np.asarray(st.alive)
    assert alive.sum() > 2, "population never grew"
    genomes = np.asarray(st.genome)[alive]
    mutated = (genomes != anc[None, :]).any(axis=1)
    assert mutated.any(), "DIV_MUT_PROB=0.2 produced zero substitutions"

    # control: without it, every genome stays identical to the ancestor
    w0 = _world()
    st0 = _run(w0, 10)
    g0 = np.asarray(st0.genome)[np.asarray(st0.alive)]
    assert (g0 == anc[None, :]).all()
