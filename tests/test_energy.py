"""Energy model (cAvidaConfig.h:649-667, cPhenotype energy branch).

Round-4 (VERDICT r3 directive #6): energy store, energy->merit conversion
(cPhenotype::ConvertEnergyToMerit cc:2403), parent->child energy split at
birth, and the energy-class placement methods (BIRTH_METHOD 9-11).
"""

from __future__ import annotations

import numpy as np
import pytest

from avida_tpu.config import AvidaConfig
from avida_tpu.world import World


def _world(**over):
    cfg = AvidaConfig()
    cfg.WORLD_X = 8
    cfg.WORLD_Y = 8
    cfg.RANDOM_SEED = 7
    cfg.AVE_TIME_SLICE = 100
    cfg.COPY_MUT_PROB = 0.0
    cfg.DIVIDE_INS_PROB = 0.0
    cfg.DIVIDE_DEL_PROB = 0.0
    cfg.ENERGY_ENABLED = 1
    cfg.ENERGY_GIVEN_ON_INJECT = 1000.0
    cfg.set("TPU_SYSTEMATICS", 0)
    for k, v in over.items():
        cfg.set(k, v)
    w = World(cfg=cfg)
    w.inject()
    return w


def _run(w, updates):
    for u in range(updates):
        w.run_update()
        w.update += 1
    return w.state


def test_energy_conservation_across_divide():
    w = _world(FRAC_PARENT_ENERGY_GIVEN_TO_ORG_AT_BIRTH=0.5,
               FRAC_ENERGY_DECAY_AT_ORG_BIRTH=0.0)
    st0 = w.state
    total0 = float(np.asarray(st0.energy).sum())
    assert total0 == pytest.approx(1000.0)
    st = _run(w, 6)
    alive = np.asarray(st.alive)
    assert alive.sum() >= 2, "no birth happened"
    # no decay, no instruction energy costs in the stock set: total energy
    # is conserved across divides (split 50/50)
    total = float(np.asarray(st.energy)[alive].sum())
    assert total == pytest.approx(total0, rel=1e-5)
    # both parent and child carry energy and an energy-derived merit
    e = np.asarray(st.energy)[alive]
    m = np.asarray(st.merit)[alive]
    assert (e > 0).all()
    np.testing.assert_allclose(m, 100.0 * e / 200, rtol=1e-5)


def test_energy_decay_at_birth():
    w = _world(FRAC_ENERGY_DECAY_AT_ORG_BIRTH=0.2)
    st = _run(w, 6)
    alive = np.asarray(st.alive)
    assert alive.sum() >= 2
    total = float(np.asarray(st.energy)[alive].sum())
    assert total < 1000.0 * 0.81 + 1e-3   # at least one 20% decay applied


def test_energy_birth_methods_place():
    for bm in (9, 10, 11):
        w = _world(BIRTH_METHOD=bm)
        st = _run(w, 6)
        assert int(np.asarray(st.alive).sum()) >= 2, \
            f"BIRTH_METHOD {bm} never placed a child"
