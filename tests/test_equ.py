"""CI-runnable EQU-harness variant: the task ladder must progress.

Small-world, capped-updates version of scripts/equ_harness.py (the
north-star correctness harness, BASELINE.json "matching CPU
updates-to-EQU").  Asserts that evolution actually works end to end: from
a single default ancestor, copy-mutations + merit-proportional scheduling
+ logic-9 rewards must discover multiple logic tasks within a bounded
number of updates.  Full-scale numbers (60x60, 5 seeds, EQU) are recorded
in EQU_r03.json by the script; the reference's own golden window
(heads_default_100u expected/data/tasks.dat) is all zeros through update
100, so ladder progression is the only CI-scale observable.
"""

from __future__ import annotations

import sys
import os

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", "scripts"))

from equ_harness import run_seed  # noqa: E402

import pytest  # noqa: E402

pytestmark = pytest.mark.slow


def test_task_ladder_progresses():
    # copy_mut above stock (0.02 vs 0.0075) compresses the discovery
    # timescale so the ladder moves within a CPU-friendly update budget;
    # stock-rate physics is exercised by the full-scale script on TPU
    r = run_seed(seed=1009, world=24, max_updates=1500, check_every=150,
                 cap=0, copy_mut=0.02)
    first = r["first_task_update"]
    assert first["not"] is not None or first["nand"] is not None, (
        f"no first-tier logic task discovered in 1500 updates: {first}")
    assert r["tasks_discovered"] >= 2, (
        f"task ladder did not progress past one task: {first}")
    assert r["final_organisms"] > 100, "population failed to fill the world"
