"""Experimental hardware (hw_type 3): 8-register CPU + sensing/movement.

Covers the round-4 cHardwareExperimental core (VERDICT r3 directive #3):
 - the stock experimental instset replicates (experimental.org ancestor,
   4-nop labels, 8 registers);
 - the avatars-pred_look sensing set: rotate-x changes facing, look-ahead
   reports the first organism on the facing ray into the 8 sensor
   registers (GoLook cc:3895), move relocates the organism with lockstep
   conflict resolution, set-forage-target stores predator/prey identity.
"""

from __future__ import annotations

import numpy as np

from avida_tpu.config import AvidaConfig
from avida_tpu.world import World


def _world(instset, wx=8, wy=8, seed=5):
    cfg = AvidaConfig()
    cfg.WORLD_X = wx
    cfg.WORLD_Y = wy
    cfg.RANDOM_SEED = seed
    cfg.INST_SET = instset
    cfg.AVE_TIME_SLICE = 100
    cfg.set("TPU_SYSTEMATICS", 0)
    return World(cfg=cfg)


def _prog(w, names, pad_to=24):
    name_to_op = {n: i for i, n in enumerate(w.instset.inst_names)}
    ops = [name_to_op[n] for n in names]
    # pad with nop-A so the IP wraps through no-ops
    ops += [name_to_op["nop-A"]] * (pad_to - len(ops))
    return np.asarray(ops, np.int8)


def test_experimental_replicates():
    w = _world("instset-experimental.cfg")
    assert w.params.hw_type == 3
    assert w.params.num_registers == 8
    w.inject()
    for u in range(8):
        w.run_update()
        w.update += 1
    assert int(np.asarray(w.state.alive).sum()) > 1


def test_rotate_and_move():
    from avida_tpu.ops.interpreter import micro_step
    import jax
    import jax.numpy as jnp

    w = _world("pred_look.cfg")
    walker = _prog(w, ["move", "nop-B"])
    cell = 4 * 8 + 4                      # (y=4, x=4)
    w.inject(genome=walker, cell=cell)
    st = w.state.replace(facing=w.state.facing.at[cell].set(0))
    exec_mask = jnp.zeros(64, bool).at[cell].set(True)
    st = micro_step(w.params, st, jax.random.key(0), exec_mask)
    alive = np.asarray(st.alive)
    assert not alive[cell], "organism should have moved off its start cell"
    occupied = np.flatnonzero(alive)
    assert len(occupied) == 1
    y, x = divmod(int(occupied[0]), 8)
    assert (x, y) == (4, 3), "facing 0 = one step north"


def test_look_ahead_sees_organism():
    from avida_tpu.ops.interpreter import micro_step

    w = _world("pred_look.cfg")
    looker_cell = 4 * 8 + 4
    target_cell = 1 * 8 + 4               # 3 cells north
    looker = _prog(w, ["look-ahead", "nop-B"])
    blocker = _prog(w, ["nop-A"])
    w.inject(genome=looker, cell=looker_cell)
    w.inject(genome=blocker, cell=target_cell)
    st = w.state.replace(
        facing=w.state.facing.at[looker_cell].set(0),
        forage_target=w.state.forage_target.at[target_cell].set(7))
    import jax
    import jax.numpy as jnp
    exec_mask = jnp.zeros(64, bool).at[looker_cell].set(True)
    st = micro_step(w.params, st, jax.random.key(0), exec_mask)
    regs = np.asarray(st.regs)[looker_cell]
    # GoLook output registers from ?BX?=1: habitat, distance, search_type,
    # id_sought, count, value, group, ft
    assert regs[1] == -2                  # habitat: organism search
    assert regs[2] == 3                   # distance to the blocker
    assert regs[4] == target_cell         # id of the organism seen
    assert regs[5] == 1                   # count
    assert regs[0] == 7                   # ft wraps to register 0 (1+7)%8


def test_set_forage_target_and_rotate_x():
    from avida_tpu.ops.interpreter import micro_step
    import jax
    import jax.numpy as jnp

    w = _world("pred_look.cfg")
    cell = 9
    # inc; inc; set-forage-target  -> ft = 2
    # every operand-taking instruction is followed by an explicit nop-B
    # (the padding nop would otherwise be consumed as the modifier)
    prog = _prog(w, ["inc", "inc", "set-forage-target", "inc",
                     "rotate-x", "nop-B"])
    w.inject(genome=prog, cell=cell)
    st = w.state
    exec_mask = jnp.zeros(64, bool).at[cell].set(True)
    for _ in range(5):
        st = micro_step(w.params, st, jax.random.key(1), exec_mask)
    assert int(np.asarray(st.forage_target)[cell]) == 2
    # rotate-x by BX=3: facing moved 3 ring steps
    assert int(np.asarray(st.facing)[cell]) == 3


def test_move_conflict_lowest_index_wins():
    from avida_tpu.ops.interpreter import micro_step
    import jax
    import jax.numpy as jnp

    w = _world("pred_look.cfg")
    # two movers both facing the same empty cell: (3,4) from north and south
    mover = _prog(w, ["move", "nop-B"])
    a, b, tgt = 2 * 8 + 4, 4 * 8 + 4, 3 * 8 + 4
    w.inject(genome=mover, cell=a)
    w.inject(genome=mover, cell=b)
    st = w.state.replace(
        facing=w.state.facing.at[a].set(4).at[b].set(0))  # a south, b north
    exec_mask = jnp.zeros(64, bool).at[a].set(True).at[b].set(True)
    st = micro_step(w.params, st, jax.random.key(2), exec_mask)
    alive = np.asarray(st.alive)
    assert alive[tgt], "the contested cell should now be occupied"
    assert not alive[a], "lower-index mover a should have won the move"
    assert alive[b], "loser b stays put"
    # loser's move register reports failure, winner's reports success
    assert int(np.asarray(st.regs)[tgt, 1]) == 1
    assert int(np.asarray(st.regs)[b, 1]) == 0


def test_pred_look_instset_loads():
    """The avatars-pred_look set (ref tests/avatars-pred_look/config/
    instset.cfg) loads without raises and builds world params."""
    from avida_tpu.config.instset import pred_look_instset
    from avida_tpu.config.environment import default_logic9_environment
    from avida_tpu.core.state import make_world_params
    from avida_tpu.config import AvidaConfig
    cfg = AvidaConfig()
    cfg.WORLD_X = 5
    cfg.WORLD_Y = 5
    s = pred_look_instset()
    p = make_world_params(cfg, s, default_logic9_environment())
    assert p.hw_type == 3 and p.num_insts == len(s.inst_names)


def test_predator_hunts_and_kills_prey():
    """Integration (avatars-pred_look-modeled): a predator program walks
    toward a prey organism and attacks it -- the prey dies, the attacker
    absorbs PRED_EFFICIENCY x its merit, turns predator, and the success
    flag lands in ?BX? (Inst_AttackPrey cc:5407, ExecuteAttack cc:7001)."""
    import numpy as np
    import jax
    import jax.numpy as jnp
    from avida_tpu.config import AvidaConfig
    from avida_tpu.config.instset import pred_look_instset
    from avida_tpu.config.environment import default_logic9_environment
    from avida_tpu.core.state import make_world_params, zeros_population
    from avida_tpu.ops.interpreter import micro_step

    s = pred_look_instset()
    s.inst_names.append("attack-prey")
    s.redundancy = np.append(s.redundancy, 1.0)
    s.cost = np.append(s.cost, 0).astype(np.int32)
    s.ft_cost = np.append(s.ft_cost, 0).astype(np.int32)
    s.energy_cost = np.append(s.energy_cost, 0.0)
    s.prob_fail = np.append(s.prob_fail, 0.0)
    s.addl_time_cost = np.append(s.addl_time_cost, 0).astype(np.int32)
    s.res_cost = np.append(s.res_cost, 0.0)

    cfg = AvidaConfig()
    cfg.WORLD_X = 5
    cfg.WORLD_Y = 5
    cfg.TPU_MAX_MEMORY = 32
    cfg.PRED_PREY_SWITCH = 0
    cfg.PRED_EFFICIENCY = 1.0
    cfg.COPY_MUT_PROB = 0.0
    p = make_world_params(cfg, s, default_logic9_environment())

    n, L = p.num_cells, p.max_memory
    st = zeros_population(n, L, p.num_reactions, num_registers=8)
    # predator at cell 12 (2,2) facing north; prey at cell 2 (0,2), two
    # steps north: program = move, attack-prey
    move, atk = s.opcode("move"), s.opcode("attack-prey")
    nopA = s.opcode("nop-A")
    tape = np.zeros((n, L), np.uint8)
    tape[12, :4] = [move, atk, nopA, nopA]
    st = st.replace(
        tape=jnp.asarray(tape),
        mem_len=st.mem_len.at[12].set(4).at[2].set(4),
        genome_len=st.genome_len.at[12].set(4).at[2].set(4),
        alive=st.alive.at[12].set(True).at[2].set(True),
        merit=jnp.ones(n, jnp.float32).at[2].set(5.0),
        forage_target=st.forage_target.at[2].set(0),       # prey
        )
    mask = jnp.zeros(n, bool).at[12].set(True)
    step = jax.jit(lambda s_, k: micro_step(p, s_, k, mask))
    key = jax.random.key(0)
    # cycle 1: predator moves north (12 -> 7)
    key, k = jax.random.split(key)
    st = step(st, k)
    assert bool(np.asarray(st.alive)[7]) and not bool(np.asarray(st.alive)[12])
    # the predator travels with its program; re-mask its new cell
    mask2 = jnp.zeros(n, bool).at[7].set(True)
    step2 = jax.jit(lambda s_, k: micro_step(p, s_, k, mask2))
    # cycle 2: attack-prey kills the prey at cell 2
    key, k = jax.random.split(key)
    st = step2(st, k)
    assert not bool(np.asarray(st.alive)[2]), "prey survived the attack"
    assert float(np.asarray(st.merit)[7]) == 6.0   # 1 + 1.0 x 5
    assert int(np.asarray(st.forage_target)[7]) == -2  # now a predator
    assert int(np.asarray(st.regs)[7, 1]) == 1     # success flag in BX
