"""Fleet orchestrator tier (service/fleet.py + scripts/fleet_tool.py).

Tier-1 here is host-only: fake clock, fake sleeps, SCRIPTED stub
children injected through the Supervisor's spawn seam -- no jax, no
real subprocesses, so nothing compiles a world in-budget (the 1-core
host rule).  The end-to-end chaos proof with REAL children -- three
concurrent faulted jobs plus a SIGKILL of the orchestrator itself,
each job bit-exact versus its uninterrupted reference -- is the slow
test at the bottom.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

import numpy as np
import pytest

import test_supervisor as ts
from avida_tpu.observability.exporter import read_metrics
from avida_tpu.observability.runlog import append_record, read_records
from avida_tpu.service.fleet import (JOURNAL_FILE, CircuitBreaker,
                                     FleetConfig, FleetOrchestrator,
                                     fleet_status_main, journal_states,
                                     validate_spec)
from avida_tpu.utils import checkpoint as ckpt_mod

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", "scripts"))
import fleet_tool  # noqa: E402

# every job supervisor in the fake-time tests runs with tight knobs so
# crash loops resolve in a handful of fake seconds
SUP_ENV = {"TPU_WATCHDOG_SEC": "10", "TPU_SUPERVISE_POLL_SEC": "0.5",
           "TPU_SUPERVISE_GRACE_SEC": "30",
           "TPU_SUPERVISE_MAX_RETRIES": "3",
           "TPU_SUPERVISE_BACKOFF_BASE": "0.1",
           "TPU_SUPERVISE_BACKOFF_CAP": "0.5",
           "TPU_SUPERVISE_HEALTHY_SEC": "1000000000"}


def _cfg(**kw):
    base = dict(max_jobs=2, poll_sec=0.5, breaker_k=3, breaker_sec=60.0,
                drain_sec=30.0)
    base.update(kw)
    return FleetConfig(**base)


class StubChildren:
    """Per-job scripted children: job name -> list of FakeProc
    factories, popped one per boot.  Tracks spawn order and the
    concurrency high-water mark (the admission-control proof)."""

    def __init__(self, clock, scripts):
        self.clock = clock
        self.scripts = {k: list(v) for k, v in scripts.items()}
        self.spawned = []               # (job_name, proc, argv)
        self.max_concurrent = 0

    def factory(self, job):
        def spawn(argv, env, logf):
            proc = self.scripts[job.name].pop(0)()
            proc._spawned(argv, env, logf)
            if "-d" in argv:
                proc._data = argv[argv.index("-d") + 1]
            live = 1 + sum(1 for _, p, _ in self.spawned
                           if p.returncode is None)
            self.max_concurrent = max(self.max_concurrent, live)
            self.spawned.append((job.name, proc, argv))
            return proc
        return spawn


class PreemptibleProc(ts.FakeProc):
    """A stub child that honors SIGTERM the way a real run does: write
    the preemption heartbeat, then exit 0."""

    def terminate(self):
        if self.returncode is None:
            ts._write_metrics(self._data, hb=self.clock(), preempted=1)
            self.returncode = 0


def _mk_fleet(tmp_path, clock, scripts, **cfg_kw):
    spool = str(tmp_path / "spool")
    stubs = StubChildren(clock, scripts)
    fleet = FleetOrchestrator(spool, cfg=_cfg(**cfg_kw), env=dict(SUP_ENV),
                              clock=clock, sleep=clock.sleep,
                              spawn_factory=stubs.factory)
    return fleet, spool, stubs


def _events(spool):
    recs = [r for r in read_records(os.path.join(spool, JOURNAL_FILE))
            if r.get("record") == "fleet"]
    return [(r["event"], r.get("job")) for r in recs], recs


# ---------------------------------------------------------------------------
# spec validation + quarantine
# ---------------------------------------------------------------------------

def test_validate_spec_rejects_garbage():
    validate_spec({"argv": ["-u", "1"]})
    validate_spec({"argv": ["-u", "1"], "fault_plan": ["crash"],
                   "env": {"A": "1"}})
    for bad in ([], {"argv": []}, {"argv": "nope"}, {"argv": [1, 2]},
                {"x": 1}, {"argv": ["-u"], "fault_plan": "crash"},
                {"argv": ["-u"], "env": {"A": 1}}):
        with pytest.raises(ValueError):
            validate_spec(bad)


def test_fleet_quarantines_malformed_specs_once(tmp_path):
    clk = ts.FakeClock()
    spool = str(tmp_path / "spool")
    os.makedirs(spool)
    with open(os.path.join(spool, "broken.json"), "w") as f:
        f.write("{this is not json")
    with open(os.path.join(spool, "noargv.json"), "w") as f:
        json.dump({"x": 1}, f)
    fleet_tool.submit(spool, "good", ["-u", "5"])
    fleet, spool, stubs = _mk_fleet(
        tmp_path, clk,
        {"good": [lambda: ts.FakeProc(clk, code=0, runtime=1.0)]})
    assert fleet.run() == 1                 # quarantines poison the exit
    states = {n: j.state for n, j in fleet.jobs.items()}
    assert states == {"broken": "quarantined", "noargv": "quarantined",
                      "good": "done"}
    # moved aside, not retried forever: exactly one quarantine each
    bad = [f for f in os.listdir(spool) if f.startswith(".bad-")]
    assert len(bad) == 2
    events, _ = _events(spool)
    assert events.count(("quarantined", "broken")) == 1
    assert events.count(("quarantined", "noargv")) == 1
    m = read_metrics(os.path.join(spool, "fleet.prom"))
    assert m['avida_fleet_jobs{state="quarantined"}'] == 2
    assert m['avida_fleet_jobs{state="done"}'] == 1


def test_fleet_tool_submit_validates(tmp_path):
    spool = str(tmp_path / "spool")
    # the orchestrator's own namespace is reserved: a job named
    # fleet.prom / fleet.jsonl / fleet.lock would wedge the spool
    for bad in ("bad name", "fleet", "fleet.prom", "fleet.jsonl",
                "fleet.lock", ".hidden"):
        with pytest.raises(ValueError, match="illegal job name"):
            fleet_tool.submit(spool, bad, ["-u", "1"])
    fleet_tool.submit(spool, "ok", ["-u", "1"])
    with pytest.raises(ValueError, match="already exists"):
        fleet_tool.submit(spool, "ok", ["-u", "1"])


def test_fleet_reserved_name_spec_is_quarantined_not_fatal(tmp_path):
    """A hand-written fleet.prom.json spec (bypassing fleet_tool) must
    be quarantined at scan, never admitted over the orchestrator's own
    files."""
    clk = ts.FakeClock()
    spool = str(tmp_path / "spool")
    os.makedirs(spool)
    with open(os.path.join(spool, "fleet.prom.json"), "w") as f:
        json.dump({"argv": ["-u", "1"]}, f)
    fleet, spool, stubs = _mk_fleet(tmp_path, clk, {})
    assert fleet.run() == 1
    assert fleet.jobs["fleet.prom"].state == "quarantined"
    assert not stubs.spawned


# ---------------------------------------------------------------------------
# admission control
# ---------------------------------------------------------------------------

def test_fleet_runs_spool_to_completion_within_budget(tmp_path):
    clk = ts.FakeClock()
    names = ("j1", "j2", "j3", "j4")
    spool = str(tmp_path / "spool")
    for n in names:
        fleet_tool.submit(spool, n, ["-u", "10"])
    fleet, spool, stubs = _mk_fleet(
        tmp_path, clk,
        {n: [lambda: ts.FakeProc(clk, code=0, runtime=3.0)]
         for n in names},
        max_jobs=2)
    assert fleet.run() == 0
    assert all(j.state == "done" for j in fleet.jobs.values())
    assert len(stubs.spawned) == 4
    # the admission-control core claim: never more than max_jobs live
    assert stubs.max_concurrent == 2
    state, _, _ = journal_states(os.path.join(spool, JOURNAL_FILE))
    assert state == {n: "done" for n in names}
    m = read_metrics(os.path.join(spool, "fleet.prom"))
    assert m['avida_fleet_jobs{state="done"}'] == 4
    assert m["avida_fleet_max_jobs"] == 2
    # every child got its own fault domain + the supervisor essentials
    for name, _, argv in stubs.spawned:
        i = argv.index("-d")
        assert argv[i + 1] == os.path.join(spool, name, "data")
        assert "TPU_CKPT_DIR" in argv and "--resume" in argv


# ---------------------------------------------------------------------------
# journal replay: a killed orchestrator resumes without double-spawning
# ---------------------------------------------------------------------------

def test_fleet_replay_resumes_jobs_without_double_spawn(tmp_path):
    clk = ts.FakeClock()
    spool = str(tmp_path / "spool")
    for n in ("j1", "j2"):
        fleet_tool.submit(spool, n, ["-u", "10"])
    # orchestrator 1: children run forever; abandon it mid-flight (the
    # in-process equivalent of SIGKILL -- no drain, no cleanup)
    f1, spool, stubs1 = _mk_fleet(
        tmp_path, clk,
        {n: [lambda: ts.FakeProc(clk, runtime=None)]
         for n in ("j1", "j2")})
    for _ in range(3):
        f1.poll_once()
    assert all(j.state == "running" for j in f1.jobs.values())
    assert not os.path.exists(os.path.join(spool, "j1.json"))
    # orchestrator 2 replays the journal: both jobs queued for resume,
    # each spawned exactly ONCE more, no re-admission records
    stubs2 = StubChildren(clk, {n: [lambda: ts.FakeProc(clk, code=0,
                                                        runtime=1.0)]
                                for n in ("j1", "j2")})
    f2 = FleetOrchestrator(spool, cfg=_cfg(), env=dict(SUP_ENV),
                           clock=clk, sleep=clk.sleep,
                           spawn_factory=stubs2.factory)
    assert {n: j.state for n, j in f2.jobs.items()} == \
        {"j1": "queued", "j2": "queued"}
    assert f2.run() == 0
    assert len(stubs2.spawned) == 2
    events, _ = _events(spool)
    assert [e for e, _ in events].count("admit") == 2       # from f1 only
    assert events.count(("replay_resume", "j1")) == 1
    assert {n: j.state for n, j in f2.jobs.items()} == \
        {"j1": "done", "j2": "done"}


def test_fleet_replay_completes_half_done_admission(tmp_path):
    """Crash window between the (fsync'd) admit record and the spec
    move: replay must complete the move, and the job must not be
    spawned twice."""
    clk = ts.FakeClock()
    spool = str(tmp_path / "spool")
    fleet_tool.submit(spool, "j1", ["-u", "10"])
    append_record(os.path.join(spool, JOURNAL_FILE),
                  {"record": "fleet", "event": "admit", "job": "j1",
                   "time": 0.0})
    fleet, spool, stubs = _mk_fleet(
        tmp_path, clk,
        {"j1": [lambda: ts.FakeProc(clk, code=0, runtime=1.0)]})
    assert fleet.jobs["j1"].state == "queued"
    assert fleet.run() == 0
    # recovery (behind the lock) completed the half-done spec move
    assert os.path.exists(os.path.join(spool, "j1", "job.json"))
    assert not os.path.exists(os.path.join(spool, "j1.json"))
    assert len(stubs.spawned) == 1
    events, _ = _events(spool)
    assert [e for e, _ in events].count("admit") == 1       # no re-admit


def test_fleet_replay_honors_in_flight_cancellation(tmp_path):
    """An orchestrator killed between cancel_requested and the child's
    exit must NOT resurrect the job on restart -- the cancel marker was
    already consumed, so losing it here would make the cancellation
    silently un-reissuable."""
    clk = ts.FakeClock()
    spool = str(tmp_path / "spool")
    os.makedirs(spool)
    jp = os.path.join(spool, JOURNAL_FILE)
    for rec in ({"event": "admit", "job": "c1"},
                {"event": "cancel_requested", "job": "c1"}):
        append_record(jp, {"record": "fleet", "time": 0.0, **rec})
    fleet = FleetOrchestrator(spool, cfg=_cfg(), env=dict(SUP_ENV),
                              clock=clk, sleep=clk.sleep,
                              spawn_factory=StubChildren(clk, {}).factory)
    assert fleet.jobs["c1"].state == "cancelled"
    assert fleet.run() == 0                     # cancelled is not a failure
    events, _ = _events(spool)
    assert ("cancelled", "c1") in events
    assert ("replay_resume", "c1") not in events


def test_fleet_journal_rotation_snapshot_keeps_replay_whole(tmp_path):
    """Rotation clobbers the .1 aside, so a long heal loop could lose a
    live job's admit/spawn records entirely -- the compaction snapshot
    written at every rotation must keep replay authoritative."""
    clk = ts.FakeClock()
    spool = str(tmp_path / "spool")
    fleet_tool.submit(spool, "longrun", ["-u", "10"])
    fleet_tool.submit(spool, "noisy", ["-u", "10"])
    # a tiny cap rotates on every record, so noisy's terminal-failure
    # traffic pushes longrun's admit record out of BOTH files of the
    # rotation pair while longrun is still live
    scripts = {"longrun": [lambda: ts.FakeProc(clk, runtime=None)],
               "noisy": [lambda: ts.FakeProc(clk, code=1, runtime=0.5)
                         for _ in range(9)]}
    fleet, spool, stubs = _mk_fleet(tmp_path, clk, scripts,
                                    journal_max_bytes=10)
    for _ in range(60):
        fleet.poll_once()
        clk.sleep(0.5)              # poll_once alone never advances time
    assert os.path.exists(os.path.join(spool, JOURNAL_FILE + ".1"))
    assert fleet.jobs["longrun"].state == "running"
    recs = read_records(os.path.join(spool, JOURNAL_FILE))
    assert not any(r.get("event") == "admit" and r.get("job") == "longrun"
                   for r in recs)                   # raw record rotated away
    assert any(r.get("event") == "snapshot" for r in recs)
    # abandon the orchestrator (SIGKILL equivalent): the journal pair
    # no longer holds longrun's admit record, only snapshots do
    f2 = FleetOrchestrator(spool, cfg=_cfg(), env=dict(SUP_ENV),
                           clock=clk, sleep=clk.sleep,
                           spawn_factory=StubChildren(clk, {}).factory)
    assert "longrun" in f2.jobs and f2.jobs["longrun"].state == "queued"
    assert f2.jobs["noisy"].state in ("queued", "failed")


def test_fleet_supervisor_exception_is_terminal_across_replay(tmp_path):
    """A job whose supervisor machinery itself blows up is journaled
    `failed` (a state replay understands), not resurrected forever."""
    clk = ts.FakeClock()
    spool = str(tmp_path / "spool")
    fleet_tool.submit(spool, "cursed", ["-u", "1"])

    def exploding_factory(job):
        def spawn(argv, env, logf):
            raise RuntimeError("spawn machinery broken")
        return spawn

    fleet = FleetOrchestrator(spool, cfg=_cfg(), env=dict(SUP_ENV),
                              clock=clk, sleep=clk.sleep,
                              spawn_factory=exploding_factory)
    assert fleet.run() == 1
    assert fleet.jobs["cursed"].state == "failed"
    f2 = FleetOrchestrator(spool, cfg=_cfg(), env=dict(SUP_ENV),
                           clock=clk, sleep=clk.sleep,
                           spawn_factory=StubChildren(clk, {}).factory)
    assert f2.jobs["cursed"].state == "failed"      # stays terminal
    assert f2.run() == 1


def test_fleet_terminal_states_survive_replay(tmp_path):
    clk = ts.FakeClock()
    spool = str(tmp_path / "spool")
    fleet_tool.submit(spool, "ok", ["-u", "1"])
    fleet_tool.submit(spool, "boom", ["-u", "1"])
    scripts = {"ok": [lambda: ts.FakeProc(clk, code=0, runtime=1.0)],
               "boom": [lambda: ts.FakeProc(clk, code=1, runtime=0.5)
                        for _ in range(9)]}
    fleet, spool, stubs = _mk_fleet(tmp_path, clk, scripts)
    assert fleet.run() == 1
    assert fleet.jobs["boom"].state == "failed"
    f2 = FleetOrchestrator(spool, cfg=_cfg(), env=dict(SUP_ENV),
                           clock=clk, sleep=clk.sleep,
                           spawn_factory=StubChildren(clk, {}).factory)
    # nothing to do: done stays done, failed stays failed (until an
    # operator requeues it)
    assert {n: j.state for n, j in f2.jobs.items()} == \
        {"ok": "done", "boom": "failed"}
    assert f2.run() == 1


# ---------------------------------------------------------------------------
# crash-storm circuit breaker
# ---------------------------------------------------------------------------

def test_circuit_breaker_trips_on_k_same_class_in_window():
    br = CircuitBreaker(3, 60.0)
    assert not br.note_failure("crash", 0.0)
    assert not br.note_failure("crash", 10.0)
    assert br.note_failure("crash", 20.0)           # rising edge at K
    assert br.is_open(21.0) and br.trips == 1
    # same-class failures while open extend it, without re-tripping
    assert not br.note_failure("crash", 50.0)
    assert br.maybe_close(100.0) is None            # quiet < window
    assert br.maybe_close(110.0) == "crash"
    assert not br.is_open(110.0)


def test_circuit_breaker_needs_same_class_within_window():
    br = CircuitBreaker(2, 60.0)
    assert not br.note_failure("crash", 0.0)
    assert not br.note_failure("hang", 10.0)        # class isolation
    assert not br.note_failure("crash", 70.0)       # first one expired
    assert br.note_failure("crash", 80.0)


def test_fleet_breaker_pauses_admissions_then_recovers(tmp_path):
    clk = ts.FakeClock()
    spool = str(tmp_path / "spool")
    for n in ("a-boom", "b-boom", "c-late"):
        fleet_tool.submit(spool, n, ["-u", "10"])
    scripts = {
        "a-boom": [lambda: ts.FakeProc(clk, code=1, runtime=0.5)
                   for _ in range(9)],
        "b-boom": [lambda: ts.FakeProc(clk, code=1, runtime=0.5)
                   for _ in range(9)],
        "c-late": [lambda: ts.FakeProc(clk, code=0, runtime=1.0)],
    }
    fleet, spool, stubs = _mk_fleet(tmp_path, clk, scripts,
                                    max_jobs=2, breaker_k=2,
                                    breaker_sec=40.0)
    assert fleet.run() == 1                         # the two crash loops
    states = {n: j.state for n, j in fleet.jobs.items()}
    assert states == {"a-boom": "failed", "b-boom": "failed",
                      "c-late": "done"}
    events, recs = _events(spool)
    names = [e for e, _ in events]
    assert "breaker_open" in names and "breaker_close" in names
    # admission control actually held: c-late was only admitted after
    # the breaker closed
    assert names.index("breaker_close") < events.index(("admit", "c-late"))
    # fleet aggregates saw every classified failure (4 boots per loop)
    assert fleet.failures["crash"] == 8
    m = read_metrics(os.path.join(spool, "fleet.prom"))
    assert m['avida_fleet_failures_total{class="crash"}'] == 8
    assert m["avida_fleet_breaker_trips_total"] == 1
    assert m["avida_fleet_breaker_open"] == 0       # closed by the end


def test_fleet_pallas_storm_degrades_fleet_wide_once(tmp_path):
    clk = ts.FakeClock()
    spool = str(tmp_path / "spool")
    for n in ("p1", "p2", "z-late"):
        fleet_tool.submit(spool, n, ["-u", "10"])

    def pallas_boom(proc, argv, env, logf):
        logf.write("jax._src.pallas.mosaic.lowering.LoweringError: bad\n")
        logf.flush()

    def pallas_pair():
        return [lambda: ts.FakeProc(clk, code=1, runtime=0.5,
                                    on_spawn=pallas_boom),
                lambda: ts.FakeProc(clk, code=0, runtime=1.0)]

    scripts = {"p1": pallas_pair(), "p2": pallas_pair(),
               "z-late": [lambda: ts.FakeProc(clk, code=0, runtime=1.0)]}
    fleet, spool, stubs = _mk_fleet(tmp_path, clk, scripts,
                                    max_jobs=2, breaker_k=2,
                                    breaker_sec=20.0)
    assert fleet.run() == 0
    assert fleet.xla_fallback
    events, _ = _events(spool)
    assert [e for e, _ in events].count("xla_fallback") == 1
    # the late admission inherited the fleet-wide degradation: its
    # FIRST boot already carries -set TPU_USE_PALLAS 2
    late_argv = [argv for name, _, argv in stubs.spawned
                 if name == "z-late"][0]
    i = late_argv.index("TPU_USE_PALLAS")
    assert late_argv[i - 1] == "-set" and late_argv[i + 1] == "2"
    m = read_metrics(os.path.join(spool, "fleet.prom"))
    assert m["avida_fleet_xla_fallback"] == 1


# ---------------------------------------------------------------------------
# graceful drain + operator markers
# ---------------------------------------------------------------------------

def test_fleet_drain_requeues_incomplete_jobs(tmp_path):
    clk = ts.FakeClock()
    spool = str(tmp_path / "spool")
    fleet_tool.submit(spool, "run1", ["-u", "1000"])
    fleet_tool.submit(spool, "wait2", ["-u", "1000"])
    fleet, spool, stubs = _mk_fleet(
        tmp_path, clk,
        {"run1": [lambda: PreemptibleProc(clk, runtime=None)]},
        max_jobs=1)
    sleeps = []
    real_sleep = fleet._sleep

    def stopping_sleep(s):
        real_sleep(s)
        sleeps.append(s)
        if len(sleeps) >= 3:
            fleet._stop = True                      # SIGTERM arrives

    fleet._sleep = stopping_sleep
    assert fleet.run() == 0                         # drained, not failed
    assert fleet.jobs["run1"].state == "queued"     # requeued, resumable
    assert fleet.jobs["wait2"].state == "queued"    # never admitted
    proc = stubs.spawned[0][1]
    assert proc.returncode == 0                     # SIGTERM, not SIGKILL
    events, recs = _events(spool)
    assert ("requeued", "run1") in events
    reasons = [r.get("reason") for r in recs if r["event"] == "requeued"]
    assert "drain" in reasons
    # a fresh orchestrator picks both up and finishes them.  run1's
    # resumed child must republish its heartbeat with preempted=0 (as
    # every real run does on exit) -- the stale preemption marker from
    # the drained boot would otherwise classify its clean exit as
    # another preempt
    def finish(proc, argv, env, logf):
        ts._write_metrics(os.path.dirname(logf.name), hb=clk(),
                          preempted=0)

    stubs2 = StubChildren(
        clk, {n: [lambda: ts.FakeProc(clk, code=0, runtime=1.0,
                                      on_spawn=finish)]
              for n in ("run1", "wait2")})
    f2 = FleetOrchestrator(spool, cfg=_cfg(), env=dict(SUP_ENV),
                           clock=clk, sleep=clk.sleep,
                           spawn_factory=stubs2.factory)
    assert f2.run() == 0
    assert all(j.state == "done" for j in f2.jobs.values())


def test_fleet_cancel_and_requeue_markers(tmp_path, capsys):
    clk = ts.FakeClock()
    spool = str(tmp_path / "spool")
    fleet_tool.submit(spool, "c1", ["-u", "1000"])
    fleet_tool.submit(spool, "c2", ["-u", "1000"])
    scripts = {"c1": [lambda: PreemptibleProc(clk, runtime=None)],
               "c2": [lambda: ts.FakeProc(clk, code=0, runtime=1.0)]}
    fleet, spool, stubs = _mk_fleet(tmp_path, clk, scripts, max_jobs=1)
    fleet.poll_once()                               # admit c1
    assert fleet.jobs["c1"].state == "running"
    assert fleet_tool.main(["cancel", spool, "c1"]) == 0
    assert fleet_tool.main(["cancel", spool, "c2"]) == 0
    for _ in range(4):
        fleet.poll_once()
    assert fleet.jobs["c1"].state == "cancelled"
    assert fleet.jobs["c2"].state == "cancelled"
    assert os.path.exists(os.path.join(spool, "c2.cancelled.json"))
    # an operator requeue resurrects the parked spec
    assert fleet_tool.main(["requeue", spool, "c2"]) == 0
    assert fleet.run() == 0
    assert fleet.jobs["c2"].state == "done"
    assert fleet.jobs["c1"].state == "cancelled"    # stays cancelled
    capsys.readouterr()
    assert fleet_tool.main(["list", spool]) == 0
    out = capsys.readouterr().out
    assert "c1" in out and "cancelled" in out and "done" in out
    # marker for an unknown job is refused
    assert fleet_tool.main(["cancel", spool, "ghost"]) == 2


# ---------------------------------------------------------------------------
# status view + CLI plumbing
# ---------------------------------------------------------------------------

def test_fleet_status_view_and_main_dispatch(tmp_path, capsys):
    clk = ts.FakeClock()
    spool = str(tmp_path / "spool")
    fleet_tool.submit(spool, "jv", ["-u", "1"])
    fleet, spool, stubs = _mk_fleet(
        tmp_path, clk,
        {"jv": [lambda: ts.FakeProc(clk, code=0, runtime=1.0)]})
    assert fleet.run() == 0
    capsys.readouterr()
    assert fleet_status_main(spool) == 0
    out = capsys.readouterr().out
    assert "jv" in out and "done" in out and "fleet" in out
    # __main__ --status routes a spool dir to the fleet view
    from avida_tpu.__main__ import main
    assert main(["--status", spool]) == 0
    assert "jv" in capsys.readouterr().out
    assert main(["--status", spool, "--max-age", "3600"]) == 0
    # stale orchestrator heartbeat -> exit 2
    mpath = os.path.join(spool, "fleet.prom")
    text = open(mpath).read()
    with open(mpath, "w") as f:
        f.write("".join(
            "avida_fleet_heartbeat_timestamp_seconds 1.0\n"
            if line.startswith("avida_fleet_heartbeat") else line + "\n"
            for line in text.splitlines()))
    assert fleet_status_main(spool, max_age=60.0) == 2
    assert "STALE" in capsys.readouterr().out


def test_fleet_main_cli_parse(tmp_path):
    from avida_tpu.service.fleet import fleet_main
    spool = str(tmp_path / "spool")
    assert fleet_main(["--fleet"]) == 2
    assert fleet_main(["--fleet", spool, "--max-jobs", "x"]) == 2
    assert fleet_main(["--fleet", spool, "--bogus"]) == 2
    # an empty spool drains immediately (exit 0, lock released)
    assert fleet_main(["--fleet", spool, "--max-jobs", "3"]) == 0
    assert not os.path.exists(os.path.join(spool, "fleet.lock"))


# ---------------------------------------------------------------------------
# device-lane packing: '"batch": true' spec coalescing (host-only, stub
# children through the Supervisor._spawn seam -- no jax compile)
# ---------------------------------------------------------------------------

def test_spec_seed_and_batch_key():
    from avida_tpu.service.fleet import spec_seed_and_batch_key
    s, k = spec_seed_and_batch_key({"argv": ["-u", "10", "-s", "7"]})
    assert s == 7 and k.startswith("sig:")
    s2, k2 = spec_seed_and_batch_key(
        {"argv": ["-u", "10", "-set", "RANDOM_SEED", "9"]})
    assert s2 == 9
    assert k == k2                       # seed spelling doesn't split keys
    s3, k3 = spec_seed_and_batch_key({"argv": ["-u", "10"]})
    assert s3 is None                    # no explicit seed: unbatchable
    # precedence mirrors the solo CLI: -s is appended AFTER -set
    # overrides by __main__, so it wins regardless of argv position
    s5, _ = spec_seed_and_batch_key(
        {"argv": ["-s", "7", "-set", "RANDOM_SEED", "9"]})
    assert s5 == 7
    _, k4 = spec_seed_and_batch_key(
        {"argv": ["-u", "10", "-s", "7"], "env": {"A": "1"}})
    assert k4 != k                       # env differences split batches
    validate_spec({"argv": ["-u", "1"], "batch": True})
    with pytest.raises(ValueError):
        validate_spec({"argv": ["-u", "1"], "batch": "yes"})


def test_batch_key_is_canonical_not_byte_equal():
    """The PR-12 over-strict-coalesce fix: the batchability key is the
    RESOLVED static config, so specs that differ only in output dirs,
    `-s` position, override order, or defaults spelled out vs omitted
    share one class (they fell back to process-per-job before)."""
    from avida_tpu.service.fleet import spec_seed_and_batch_key
    base = {"argv": ["-u", "10", "-s", "7", "-set", "WORLD_X", "60"]}
    _, k = spec_seed_and_batch_key(base)
    # output dirs + checkpoint dirs are per-member routing, not statics
    _, k_dirs = spec_seed_and_batch_key(
        {"argv": ["-d", "out/a", "-set", "TPU_CKPT_DIR", "ck/a",
                  "-u", "10", "-s", "8", "-set", "WORLD_X", "60"]})
    assert k_dirs == k
    # seed spelling/position + override order are cosmetic
    _, k_pos = spec_seed_and_batch_key(
        {"argv": ["-set", "WORLD_X", "60", "-u", "10",
                  "-set", "RANDOM_SEED", "9"]})
    assert k_pos == k
    # a default spelled out explicitly resolves identically
    _, k_spelled = spec_seed_and_batch_key(
        {"argv": ["-u", "10", "-s", "7", "-set", "WORLD_X", "60",
                  "-set", "WORLD_Y", "60"]})
    assert k_spelled == k                # WORLD_Y 60 is the default
    # genuinely different statics still split
    _, k_other = spec_seed_and_batch_key(
        {"argv": ["-u", "10", "-s", "7", "-set", "WORLD_X", "50"]})
    assert k_other != k
    # a different run budget splits the STATIC coalescer's key (one
    # shared -u per --worlds child; the serve pool strips it instead)
    _, k_u = spec_seed_and_batch_key(
        {"argv": ["-u", "20", "-s", "7", "-set", "WORLD_X", "60"]})
    assert k_u != k
    from avida_tpu.service.serve import static_signature
    assert static_signature(base, with_updates=False) == \
        static_signature({"argv": ["-u", "20", "-s", "7",
                                   "-set", "WORLD_X", "60"]},
                         with_updates=False)


def test_fleet_batch_coalesces_static_equal_specs(tmp_path):
    """Three --batch specs differing only in seed coalesce into ONE
    supervised --worlds child on one admission slot; a static-mismatched
    --batch spec falls back to process-per-job with the reason
    journaled; terminal state propagates to every rider."""
    clk = ts.FakeClock()
    spool = str(tmp_path / "spool")
    for n, s in (("b1", 7), ("b2", 8), ("b3", 9)):
        fleet_tool.submit(spool, n, ["-u", "10", "-s", str(s)],
                          batch=True)
    fleet_tool.submit(spool, "solo1",
                      ["-u", "10", "-s", "4", "-set", "WORLD_X", "20"],
                      batch=True)
    fleet, spool, stubs = _mk_fleet(
        tmp_path, clk,
        {"b1": [lambda: ts.FakeProc(clk, code=0, runtime=3.0)],
         "solo1": [lambda: ts.FakeProc(clk, code=0, runtime=3.0)]},
        max_jobs=2)
    assert fleet.run() == 0
    # ONE child served b1+b2+b3; one more for the fallback
    assert sorted(n for n, _, _ in stubs.spawned) == ["b1", "solo1"]
    assert stubs.max_concurrent <= 2
    argv = next(a for n, _, a in stubs.spawned if n == "b1")
    i = argv.index("--worlds")
    with open(argv[i + 1]) as f:
        manifest = json.load(f)
    assert [e["name"] for e in manifest] == ["b1", "b2", "b3"]
    assert [e["seed"] for e in manifest] == [7, 8, 9]
    for e in manifest:
        # every rider keeps its OWN fault domain: per-world data and
        # solo-compatible checkpoints under its own job dir
        assert e["data_dir"] == os.path.join(spool, e["name"], "data")
        assert e["ckpt_dir"] == os.path.join(spool, e["name"], "ck")
    assert "-s" not in argv              # seed lives in the manifest
    assert argv[argv.index("-d") + 1] == os.path.join(spool, "b1",
                                                      "data")
    assert "--resume" in argv            # supervisor restart contract
    assert all(fleet.jobs[n].state == "done"
               for n in ("b1", "b2", "b3", "solo1"))
    events, recs = _events(spool)
    assert ("coalesce", "b1") in events
    assert ("coalesced", "b2") in events and ("coalesced", "b3") in events
    fallback = [r for r in recs if r["event"] == "batch_fallback"]
    assert [r["job"] for r in fallback] == ["solo1"]
    state, _, _ = journal_states(os.path.join(spool, JOURNAL_FILE))
    assert state == {n: "done" for n in ("b1", "b2", "b3", "solo1")}


def test_fleet_batch_member_cancel_preempts_and_requeues(tmp_path):
    """Cancelling a rider preempts the whole batch: the rider lands
    cancelled, the leader requeues (its per-world checkpoint resumes
    it), and -- its peer gone -- the requeued spec falls back to a solo
    process and completes.  Also covers the status view's one-row-plus-
    sub-rows rendering and journal replay of a live batch."""
    from avida_tpu.service.fleet import (format_fleet_status,
                                         journal_batch_leaders)
    clk = ts.FakeClock()
    spool = str(tmp_path / "spool")
    for n, s in (("p1", 3), ("p2", 5)):
        fleet_tool.submit(spool, n, ["-u", "1000", "-s", str(s)],
                          batch=True)
    fleet, spool, stubs = _mk_fleet(
        tmp_path, clk,
        {"p1": [lambda: PreemptibleProc(clk, runtime=None),
                # the fallback boot must republish preempted=0 (real
                # children do on exit); the drained boot's stale marker
                # would otherwise classify its clean exit as a preempt
                lambda: ts.FakeProc(
                    clk, code=0, runtime=1.0,
                    on_spawn=lambda p, a, e, lf: ts._write_metrics(
                        os.path.dirname(lf.name), hb=clk(),
                        preempted=0))]},
        max_jobs=2)
    fleet.poll_once()
    assert fleet.jobs["p1"].state == "running"
    assert fleet.jobs["p2"].state == "batched"
    assert fleet.jobs["p1"].batch_members == ["p2"]

    # status view: one batched row with per-world sub-rows
    os.makedirs(os.path.join(spool, "p1", "data"), exist_ok=True)
    with open(os.path.join(spool, "p1", "data",
                           "multiworld.prom"), "w") as f:
        f.write('avida_update{world="p1"} 12\n'
                'avida_update{world="p2"} 12\n'
                'avida_organisms{world="p1"} 3\n'
                'avida_organisms{world="p2"} 4\n')
    view = format_fleet_status(spool, now=clk())
    assert "(batch x2)" in view
    assert "- p2" in view and "u12 organisms 4" in view
    assert "\n  p2 " not in view         # rider has no top-level row

    # a replay over the journal resumes BOTH as queued (the rider's
    # solo-format checkpoints make it independently resumable --
    # re-coalescing or running solo both continue bit-exactly)
    state, _, _ = journal_states(os.path.join(spool, JOURNAL_FILE))
    assert state == {"p1": "running", "p2": "batched"}
    assert journal_batch_leaders(
        os.path.join(spool, JOURNAL_FILE)) == {"p2": "p1"}
    replay = FleetOrchestrator(spool, cfg=_cfg(), env=dict(SUP_ENV),
                               clock=clk, sleep=clk.sleep,
                               spawn_factory=StubChildren(clk, {}).factory)
    assert replay.jobs["p1"].state == "queued"
    assert replay.jobs["p2"].state == "queued"

    assert fleet_tool.main(["cancel", spool, "p2"]) == 0
    for _ in range(4):
        fleet.poll_once()
    assert fleet.jobs["p2"].state == "cancelled"
    proc = stubs.spawned[0][1]
    assert proc.returncode == 0          # graceful SIGTERM, not kill
    events, recs = _events(spool)
    assert ("cancel_requested", "p2") in events
    # the leader requeued, then -- no peer left -- fell back solo
    assert fleet.run() == 0
    assert fleet.jobs["p1"].state == "done"
    assert fleet.jobs["p2"].state == "cancelled"
    fallback = [r for r in _events(spool)[1]
                if r["event"] == "batch_fallback"]
    assert any(r["job"] == "p1" for r in fallback)


def test_fleet_batch_groups_by_resume_progress(tmp_path):
    """A requeued member with checkpoints must not coalesce with a
    fresh static-equal spec: the child resumes a batch aligned on one
    update, so mixed progress would refuse on every boot.  Grouping
    keys on the newest published generation's update."""
    clk = ts.FakeClock()
    spool = str(tmp_path / "spool")
    for n, s in (("r1", 3), ("r2", 5)):
        fleet_tool.submit(spool, n, ["-u", "1000", "-s", str(s)],
                          batch=True)
    # r1 already has checkpoint progress (a requeued member); r2 is
    # fresh -- a bare generation dir is all the host-side key reads
    os.makedirs(os.path.join(spool, "r1", "ck", "ckpt-000000000008"))
    fleet, spool, stubs = _mk_fleet(
        tmp_path, clk,
        {n: [lambda: ts.FakeProc(clk, code=0, runtime=2.0)]
         for n in ("r1", "r2")},
        max_jobs=2)
    assert fleet.run() == 0
    # no coalesce: two solo children, each journaled as a fallback
    assert sorted(n for n, _, _ in stubs.spawned) == ["r1", "r2"]
    assert all("--worlds" not in a for _, _, a in stubs.spawned)
    reasons = [r.get("reason") for r in _events(spool)[1]
               if r["event"] == "batch_fallback"]
    assert reasons and all("peer" in r for r in reasons)


def test_fleet_batch_width_cap_splits_groups(tmp_path):
    """TPU_FLEET_MAX_BATCH bounds how many worlds one batched child
    stacks: a 5-spec static-equal group at max_batch=2 becomes two
    2-world batches plus a solo fallback -- the admission throttle's
    resource bounding survives device-lane packing."""
    clk = ts.FakeClock()
    spool = str(tmp_path / "spool")
    names = [f"c{i}" for i in range(1, 6)]
    for i, n in enumerate(names):
        fleet_tool.submit(spool, n, ["-u", "10", "-s", str(i + 1)],
                          batch=True)
    fleet, spool, stubs = _mk_fleet(
        tmp_path, clk,
        {n: [lambda: ts.FakeProc(clk, code=0, runtime=2.0)]
         for n in ("c1", "c3", "c5")},
        max_jobs=3, max_batch=2)
    assert fleet.run() == 0
    assert sorted(n for n, _, _ in stubs.spawned) == ["c1", "c3", "c5"]
    for leader, width in (("c1", 2), ("c3", 2)):
        argv = next(a for n, _, a in stubs.spawned if n == leader)
        with open(argv[argv.index("--worlds") + 1]) as f:
            assert len(json.load(f)) == width
    assert all(fleet.jobs[n].state == "done" for n in names)
    reasons = [r.get("reason") for r in _events(spool)[1]
               if r["event"] == "batch_fallback"]
    assert "width-cap remainder" in reasons


# ---------------------------------------------------------------------------
# slow: the end-to-end chaos proof with real children
# ---------------------------------------------------------------------------

# world config shared by every job and its uninterrupted reference --
# mirrors tests/test_chaos.py: small world, chunk boundaries every 2
# updates, auto-save every 4, final generation published
_SETS = [
    ("WORLD_X", "8"), ("WORLD_Y", "8"), ("TPU_MAX_MEMORY", "256"),
    ("AVE_TIME_SLICE", "100"), ("TPU_MAX_STEPS_PER_UPDATE", "100"),
    ("TPU_SYSTEMATICS", "0"), ("TPU_MAX_STRETCH", "2"),
    ("TPU_CKPT_EVERY", "4"), ("TPU_CKPT_FINAL", "1"),
]
_UPDATES = 20


def _child_argv(seed):
    argv = ["-s", str(seed), "-u", str(_UPDATES)]
    for name, value in _SETS:
        argv += ["-set", name, value]
    return argv


def _env():
    env = dict(os.environ)
    env.pop("TPU_FAULT", None)
    env["JAX_PLATFORMS"] = "cpu"
    # NO persistent jax compilation cache: see tests/test_chaos.py::_env
    env.pop("JAX_COMPILATION_CACHE_DIR", None)
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env["PYTHONPATH"] = repo + (
        os.pathsep + env["PYTHONPATH"] if env.get("PYTHONPATH") else "")
    return env


def _final_arrays(ckpt_dir):
    gens = ckpt_mod.list_generations(str(ckpt_dir))
    assert gens, f"no generations under {ckpt_dir}"
    manifest, arrays, _files = ckpt_mod.read_generation(gens[-1])
    return manifest, arrays


@pytest.mark.slow
def test_fleet_chaos_three_faulted_jobs_plus_orchestrator_sigkill(tmp_path):
    """The acceptance drill: >= 3 concurrent jobs, each with its own
    injected fault (crash / hang / corrupt-ckpt+sigkill), plus one
    SIGKILL of the orchestrator itself mid-flight.  Everything
    completes unattended and every job's final state is BIT-EXACT
    versus its uninterrupted reference run."""
    jobs = {
        "j-crash": (13, ["crash@update=7"]),
        "j-hang": (17, ["hang@chunk=3"]),
        "j-corrupt": (19,
                      ["corrupt-ckpt:leaf=merit@update=8;sigkill@update=9"]),
    }
    env = _env()
    # uninterrupted references, sequential (1-core-host rule)
    refs = {}
    for name, (seed, _plan) in jobs.items():
        data = str(tmp_path / f"ref-{name}" / "data")
        ck = str(tmp_path / f"ref-{name}" / "ck")
        proc = subprocess.run(
            [sys.executable, "-m", "avida_tpu"] + _child_argv(seed)
            + ["-d", data, "-set", "TPU_CKPT_DIR", ck],
            env=env, capture_output=True, text=True, timeout=900)
        assert proc.returncode == 0, proc.stderr[-2000:]
        refs[name] = _final_arrays(ck)

    spool = str(tmp_path / "spool")
    knobs = {"TPU_WATCHDOG_SEC": "20", "TPU_SUPERVISE_POLL_SEC": "0.25",
             "TPU_SUPERVISE_GRACE_SEC": "600",
             "TPU_SUPERVISE_BACKOFF_BASE": "0.05",
             "TPU_SUPERVISE_BACKOFF_CAP": "0.2"}
    for name, (seed, plan) in jobs.items():
        fleet_tool.submit(spool, name, _child_argv(seed),
                          fault_plan=plan, env=knobs)
    cmd = [sys.executable, "-m", "avida_tpu", "--fleet", spool,
           "--max-jobs", "3"]
    with open(os.path.join(spool, "orchestrator.log"), "w") as logf:
        orch = subprocess.Popen(cmd, env=env, stdout=logf, stderr=logf)
        # wait for real progress (every job has published a checkpoint
        # generation), then SIGKILL the orchestrator itself
        deadline = time.time() + 900
        while time.time() < deadline:
            if orch.poll() is not None:
                break
            if all(ckpt_mod.list_generations(
                    os.path.join(spool, n, "ck")) for n in jobs):
                break
            time.sleep(1.0)
        killed = False
        if orch.poll() is None:
            orch.kill()
            orch.wait()
            killed = True
    assert killed, "orchestrator finished before the kill window -- " \
                   "the drill proved nothing"

    # restart: journal replay + orphan reaping + resume to completion
    proc2 = subprocess.run(cmd, env=env, capture_output=True, text=True,
                           timeout=1800)
    assert proc2.returncode == 0, \
        proc2.stdout[-1000:] + proc2.stderr[-2000:]
    state, _, _ = journal_states(os.path.join(spool, JOURNAL_FILE))
    assert state == {n: "done" for n in jobs}
    for name in jobs:
        manifest, arrays = _final_arrays(os.path.join(spool, name, "ck"))
        ref_manifest, ref_arrays = refs[name]
        assert manifest["update"] == ref_manifest["update"] == _UPDATES
        assert set(arrays) == set(ref_arrays)
        for key in sorted(arrays):
            np.testing.assert_array_equal(
                arrays[key], ref_arrays[key],
                err_msg=f"job {name} array {key}")
