"""World geometries beyond grid/torus (nGeometry.h:30-37, cTopology.h
builders): clique, hex, lattice, random-connected, scale-free -- all as
static [N, C] neighbor tables with -1 padding for short connection lists.
"""

from __future__ import annotations

import numpy as np
import pytest

from avida_tpu.ops.birth import neighbor_table


def _degrees(t):
    return (t >= 0).sum(axis=1)


def test_hex_six_neighbors():
    t = neighbor_table(5, 5, 4)
    d = _degrees(t)
    # interior cells: 6 connections (grid minus NE/SW diagonals)
    assert d[2 * 5 + 2] == 6
    # NE/SW diagonal neighbors are absent for the center cell
    c = 2 * 5 + 2
    assert (1 * 5 + 3) not in set(t[c][t[c] >= 0])   # NE of (2,2)
    assert (3 * 5 + 1) not in set(t[c][t[c] >= 0])   # SW


def test_grid_edge_lists_short():
    t = neighbor_table(4, 4, 1)
    d = _degrees(t)
    assert d[0] == 3          # corner
    assert d[1] == 5          # edge
    assert d[1 * 4 + 1] == 8  # interior


def test_lattice_z1_equals_grid():
    assert (neighbor_table(4, 4, 6) == neighbor_table(4, 4, 1)).all()


def test_clique_all_pairs():
    t = neighbor_table(3, 3, 3)
    assert t.shape == (9, 8)
    for c in range(9):
        assert set(t[c]) == set(range(9)) - {c}


def test_random_connected_is_connected_and_symmetric():
    t = neighbor_table(6, 6, 7, seed=11)
    n = 36
    adj = {c: set(t[c][t[c] >= 0]) for c in range(n)}
    for c in range(n):
        for d in adj[c]:
            assert c in adj[d], "graph must be bidirectional"
    seen = {0}
    frontier = [0]
    while frontier:
        c = frontier.pop()
        for d in adj[c]:
            if d not in seen:
                seen.add(d)
                frontier.append(d)
    assert len(seen) == n, "graph must be a single component"


def test_scale_free_hubs_and_m():
    t = neighbor_table(8, 8, 8, seed=5, scale_free_m=3)
    d = _degrees(t)
    assert d.min() >= 1
    # preferential attachment: max degree well above the median
    assert d.max() >= 2 * np.median(d)
    adj = {c: set(t[c][t[c] >= 0]) for c in range(64)}
    for c in adj:
        for e in adj[c]:
            assert c in adj[e]


def test_unwired_geometries_raise():
    with pytest.raises(NotImplementedError):
        neighbor_table(4, 4, 0)
    with pytest.raises(NotImplementedError):
        neighbor_table(4, 4, 5)


def test_world_runs_on_hex():
    from avida_tpu.config import AvidaConfig
    from avida_tpu.world import World

    cfg = AvidaConfig()
    cfg.WORLD_X = 8
    cfg.WORLD_Y = 8
    cfg.WORLD_GEOMETRY = 4
    cfg.RANDOM_SEED = 3
    cfg.AVE_TIME_SLICE = 100
    cfg.set("TPU_SYSTEMATICS", 0)
    w = World(cfg=cfg)
    w.inject()
    for u in range(8):
        w.run_update()
        w.update += 1
    assert int(np.asarray(w.state.alive).sum()) > 1
