"""Gradient (moving-peak) resources.

Reference: cGradientCount (main/cGradientCount.cc) via
cEnvironment::LoadGradientResource (cc:831): a cone of resource
height/(dist+1) within `spread` of a peak that takes a random step every
`updatestep` updates; plateau caps the cone top.  Simplifications
documented in ops/resources.step_gradient (no halos/hills/barriers or
plateau depletion).
"""

from __future__ import annotations


import numpy as np

from avida_tpu.world import World


def _world(tmp_path):
    env_cfg = tmp_path / "environment.cfg"
    env_cfg.write_text(
        "GRADIENT_RESOURCE food:height=8:spread=6:plateau=2:updatestep=2"
        ":move_a_scaler=2\n"
        "REACTION NOT not process:value=1.0:type=pow:resource=food\n")
    (tmp_path / "avida.cfg").write_text(
        "WORLD_X 20\nWORLD_Y 20\nRANDOM_SEED 5\n"
        "ENVIRONMENT_FILE environment.cfg\n"
        "AVE_TIME_SLICE 100\nTPU_MAX_STEPS_PER_UPDATE 100\n")
    return World(config_dir=str(tmp_path), data_dir=str(tmp_path))


def test_gradient_resource_cone_and_movement(tmp_path):
    w = _world(tmp_path)
    r = w.environment.spatial_resources()[0]
    assert r.is_gradient and r.height == 8 and r.spread == 6

    w.inject()
    w.run(max_updates=10)
    rg = np.asarray(w.state.res_grid[0]).reshape(20, 20)
    peak = np.asarray(w.state.grad_peak[0]).copy()
    assert (peak >= 0).all()
    # the cone exists, is bounded by the plateau cap, and covers the spread
    assert rg.max() > 0
    assert abs(rg.max() - 2.0) < 1e-5
    assert 20 < (rg > 0).sum() < 160          # pi*6^2 ~ 113 cells
    # the resource value at the peak cell is the plateau
    assert abs(rg[peak[1], peak[0]] - 2.0) < 1e-5

    # the peak wanders over time (move_a_scaler > 1)
    w.run(max_updates=40)
    peak2 = np.asarray(w.state.grad_peak[0])
    assert (peak != peak2).any(), (peak, peak2)
