"""Integrity-plane suite: device digests, sampled shadow re-execution,
and SDC-aware recovery (ops/digest.py, utils/integrity.py, the World /
MultiWorld / ServeBatch scrub hooks, the supervisor `sdc` class).

Layout mirrors the chaos suite: the digest units, the off-path gates
and the in-process detection proofs are tier-1; the real-subprocess
scrub-rollback chaos drills (XLA and Pallas) and the batched/serve legs
are `slow`.  conftest.py pins TPU_STATE_DIGEST/TPU_SCRUB_EVERY env to 0
suite-wide; these tests opt back in via explicit config overrides
(which beat the env half of the knobs).
"""

from __future__ import annotations

import json
import os
import subprocess
import sys

import numpy as np
import pytest

from avida_tpu.utils import integrity
from avida_tpu.utils.integrity import StateDivergenceError

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", "scripts"))
import check_jaxpr  # noqa: E402

SEED = 11
UPDATES = 24

# one shared world config for every in-process test in this module, so
# the update_scan / digest programs compile once per pytest process
_SETS = [
    ("WORLD_X", 6), ("WORLD_Y", 6), ("TPU_MAX_MEMORY", 128),
    ("RANDOM_SEED", SEED), ("TPU_SYSTEMATICS", 0),
    ("COPY_MUT_PROB", 0.0075), ("TPU_USE_PALLAS", 2),
    ("TPU_MAX_STRETCH", 4),
]


def _world(tmp, extra=()):
    from avida_tpu.world import World
    return World(overrides=_SETS + list(extra), data_dir=str(tmp))


def _small_state(trace_cap=0):
    import jax
    from avida_tpu.config import AvidaConfig
    from avida_tpu.config.environment import default_logic9_environment
    from avida_tpu.config.instset import default_instset
    from avida_tpu.core.state import make_world_params, zeros_population
    cfg = AvidaConfig()
    cfg.WORLD_X = 6
    cfg.WORLD_Y = 6
    cfg.TPU_MAX_MEMORY = 64
    if trace_cap:
        cfg.set("TPU_TRACE", 1)
        cfg.set("TPU_TRACE_CAP", trace_cap)
    p = make_world_params(cfg, default_instset(),
                          default_logic9_environment())
    st = zeros_population(p.num_cells, p.max_memory, p.num_reactions,
                          nb_cap=p.nb_cap, trace_cap=p.trace_cap)
    import jax.numpy as jnp
    key = jax.random.key(7)
    st = st.replace(
        merit=jax.random.uniform(key, st.merit.shape) * 100,
        tape=jax.random.randint(jax.random.fold_in(key, 1),
                                st.tape.shape, 0, 255).astype(jnp.uint8),
        alive=jax.random.bernoulli(jax.random.fold_in(key, 2),
                                   0.5, st.alive.shape))
    return p, st


# ---------------------------------------------------------------------------
# digest units: host/device agreement, order stability, batched [W]
# ---------------------------------------------------------------------------

def test_digest_host_device_agreement():
    """The jitted device digest and the numpy host digest fold to the
    SAME u32 -- the property that lets host-only processes (--resume,
    ckpt_tool, the supervisor's sdc rollback) re-verify what the device
    computed.  Repeatable within a process, and None-valued leaves (the
    disabled flight-recorder ring) are skipped on both sides."""
    from avida_tpu.core.state import state_field_names
    from avida_tpu.ops.digest import state_digest
    p, st = _small_state()
    dev = int(state_digest(st))
    arrays = {n: np.asarray(getattr(st, n)) for n in state_field_names()
              if getattr(st, n) is not None}
    assert dev == integrity.digest_arrays(arrays)
    assert int(state_digest(st)) == dev          # deterministic
    # the ring-armed state digests differently (more leaves) but still
    # agrees host/device
    p2, st2 = _small_state(trace_cap=64)
    dev2 = int(state_digest(st2))
    arrays2 = {n: np.asarray(getattr(st2, n)) for n in state_field_names()
               if getattr(st2, n) is not None}
    assert dev2 == integrity.digest_arrays(arrays2)


def test_digest_order_stability():
    """Position-salted fold: swapping two elements, changing one bit,
    or renaming a leaf each change the digest -- a reordered or
    misattributed state can never alias a healthy one."""
    from avida_tpu.ops.digest import state_digest
    p, st = _small_state()
    base = int(state_digest(st))
    swapped = st.replace(
        merit=st.merit.at[0].set(st.merit[1]).at[1].set(st.merit[0]))
    assert int(state_digest(swapped)) != base
    import jax
    import jax.numpy as jnp
    word = jax.lax.bitcast_convert_type(st.merit[3], jnp.uint32) \
        ^ jnp.uint32(1)
    flipped = st.replace(merit=st.merit.at[3].set(
        jax.lax.bitcast_convert_type(word, st.merit.dtype)))
    assert int(state_digest(flipped)) != base
    # host side: the leaf NAME salts the fold
    a = np.arange(8, dtype=np.int32)
    assert integrity.digest_arrays({"x": a}) \
        != integrity.digest_arrays({"y": a})
    # length-sensitivity: a truncated leaf cannot alias
    assert integrity.fold_words(np.arange(8, dtype=np.uint32)) \
        != integrity.fold_words(np.arange(9, dtype=np.uint32))


def test_digest_batched_matches_solo():
    """state_digest_batched([W] stack) == per-world solo digests: the
    cross-driver comparison the serve rollback relies on."""
    import jax
    import jax.numpy as jnp
    from avida_tpu.ops.digest import state_digest, state_digest_batched
    p, st = _small_state()
    st2 = st.replace(merit=st.merit * 2 + 1)
    bst = jax.tree.map(lambda a, b: jnp.stack([a, b]), st, st2)
    batched = [int(x) for x in np.asarray(state_digest_batched(bst))]
    assert batched == [int(state_digest(st)), int(state_digest(st2))]


# ---------------------------------------------------------------------------
# off-path gates: jaxpr untouched, zero-cost defaults, bit-identity
# ---------------------------------------------------------------------------

def test_integrity_knobs_leave_update_step_jaxpr_unchanged():
    """The digest/scrub live OUTSIDE the traced update program (the
    audit_state isolation rule): WorldParams is identical with the
    knobs on or off, so the solo update_step jaxpr digest is unchanged
    in both directions -- and the recorded snapshot still matches."""
    from avida_tpu.config import AvidaConfig
    from avida_tpu.config.environment import default_logic9_environment
    from avida_tpu.config.instset import default_instset
    from avida_tpu.core.state import make_world_params

    def params_with(knobs):
        cfg = AvidaConfig()
        cfg.WORLD_X = 6
        cfg.WORLD_Y = 6
        cfg.TPU_MAX_MEMORY = 64
        for n, v in knobs:
            cfg.set(n, v)
        return make_world_params(cfg, default_instset(),
                                 default_logic9_environment())

    off = params_with([])
    on = params_with([("TPU_STATE_DIGEST", 1), ("TPU_SCRUB_EVERY", 1)])
    assert on == off
    ok, msg = check_jaxpr.check()
    assert ok, msg


def test_bitflip_grammar_and_param_plumbing():
    """`bitflip:` parses (requires @update, leaf whitelist, bit range),
    reaches WorldParams.fault_bitflip, and -- like every host-side
    kind -- `corrupt-digest` never touches params."""
    from avida_tpu.config import AvidaConfig
    from avida_tpu.core.state import _fault_bitflip_param
    from avida_tpu.utils.faultinject import parse_spec

    cfg = AvidaConfig()
    cfg.WORLD_X = 6
    cfg.WORLD_Y = 6
    assert _fault_bitflip_param(cfg) == ()
    cfg.set("TPU_FAULT", "bitflip:merit,cell=5,bit=3@update=40")
    assert _fault_bitflip_param(cfg) == ("merit", 5, 3, 40)
    cfg2 = AvidaConfig()
    cfg2.WORLD_X = 6
    cfg2.WORLD_Y = 6
    cfg2.set("TPU_FAULT", "corrupt-digest@update=8")
    assert _fault_bitflip_param(cfg2) == ()

    with pytest.raises(ValueError, match="requires @update"):
        parse_spec("bitflip:merit")
    with pytest.raises(ValueError, match="leaf must be one of"):
        parse_spec("bitflip:genome@update=3")
    with pytest.raises(ValueError, match="bit must be"):
        parse_spec("bitflip:merit,bit=40@update=3")
    with pytest.raises(ValueError, match="save-time kinds"):
        parse_spec("corrupt-digest@chunk=3")


def test_prom_families_empty_when_untouched():
    """The avida_integrity_* families render only once the plane ran --
    integrity-off processes publish byte-identical metrics files."""
    saved = integrity.counters()
    integrity.reset_for_tests()
    try:
        assert integrity.prom_families() == []
        integrity.note_scrub()
        fams = {f[0]: f[3] for f in integrity.prom_families()}
        assert fams["avida_integrity_scrubs_total"] == 1
        assert fams["avida_integrity_mismatches_total"] == 0
    finally:
        integrity.reset_for_tests()
        for k, v in saved.items():
            integrity._counters[k] = v


def test_digest_on_trajectory_bit_identical(tmp_path):
    """TPU_STATE_DIGEST + TPU_SCRUB_EVERY change nothing about the
    evolved trajectory: same seed, same updates, final state
    bit-identical to a digest-off run -- and the heartbeat-facing
    state_digest value matches an independent device digest of that
    final state."""
    from avida_tpu.core.state import state_field_names
    from avida_tpu.ops.digest import state_digest

    w_off = _world(tmp_path / "off")
    w_off.run(max_updates=UPDATES)
    w_on = _world(tmp_path / "on", extra=[("TPU_STATE_DIGEST", 1),
                                          ("TPU_SCRUB_EVERY", 2)])
    w_on.run(max_updates=UPDATES)
    for name in state_field_names():
        a, b = getattr(w_off.state, name), getattr(w_on.state, name)
        if a is None:
            assert b is None
            continue
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b),
                                      err_msg=f"leaf {name}")
    assert w_on.state_digest is not None
    u, val = w_on.state_digest
    assert u == UPDATES
    assert val == int(state_digest(w_on.state))
    assert w_on._last_verified_update == UPDATES
    # the per-chunk runlog records landed
    recs = [json.loads(line) for line in
            open(tmp_path / "on" / "integrity.jsonl")]
    assert any(r["event"] == "digest" for r in recs)
    assert any(r["event"] == "scrub" and r["ok"] for r in recs)


# ---------------------------------------------------------------------------
# detection: injected bitflip caught by the sampled shadow re-execution
# ---------------------------------------------------------------------------

def _run_bitflip(tmp, extra=(), at=13):
    w = _world(tmp, extra=[("TPU_STATE_DIGEST", 1), ("TPU_SCRUB_EVERY", 1),
                           ("TPU_FAULT", f"bitflip:merit,cell=3@update={at}")
                           ] + list(extra))
    with pytest.raises(StateDivergenceError) as exc:
        w.run(max_updates=UPDATES)
    return w, str(exc.value)


def test_bitflip_detected_xla(tmp_path):
    """A one-bit, in-bounds, finite flip -- invisible to audit_state by
    construction -- is caught by the scrub in the chunk where it fired,
    and the error carries the recovery markers the supervisor parses
    (last_verified_update, the engine name)."""
    saved = integrity.counters()
    integrity.reset_for_tests()
    try:
        w, msg = _run_bitflip(tmp_path)
        assert "last_verified_update=12" in msg
        assert "engine xla" in msg
        assert "[12, 16)" in msg
        assert integrity.counters()["mismatches"] == 1
        # the flip really was audit-invisible: the corrupted state
        # passes every invariant
        from avida_tpu.utils.audit import check_invariants
        check_invariants(w.params, w.state)
        # the shadow replay runs the PRISTINE program
        assert w.params.fault_bitflip == ("merit", 3, 0, 13)
        assert w._shadow_params().fault_bitflip == ()
    finally:
        integrity.reset_for_tests()
        for k, v in saved.items():
            integrity._counters[k] = v


@pytest.mark.slow
def test_bitflip_detected_interpret_pallas(tmp_path):
    """The same detection on the Pallas path (interpret mode on CPU;
    fault injection forces the per-update kernel engine -- packed
    residency is ineligible under an armed device fault, like nan).
    The divergence error names a pallas engine, which is what earns
    the supervisor's one-shot XLA degradation."""
    from avida_tpu.ops import packed_chunk
    w, msg = _run_bitflip(tmp_path, extra=[("TPU_USE_PALLAS", 1)])
    assert "engine pallas" in msg
    assert packed_chunk.ineligible_reason(w.params, False) is not None


# ---------------------------------------------------------------------------
# resume digest verification + ckpt_tool sweep
# ---------------------------------------------------------------------------

@pytest.fixture()
def ck_run(tmp_path):
    """A digest-on checkpointed run: generations at updates 8/16/24,
    each manifest carrying state_digest."""
    w = _world(tmp_path / "data",
               extra=[("TPU_STATE_DIGEST", 1),
                      ("TPU_CKPT_DIR", str(tmp_path / "ck")),
                      ("TPU_CKPT_EVERY", 8), ("TPU_CKPT_KEEP", 8),
                      ("TPU_CKPT_FINAL", 1)])
    w.run(max_updates=UPDATES)
    return tmp_path, w


def test_resume_digest_verify_falls_back(ck_run, tmp_path):
    """--resume recomputes the restored state's digest against the
    manifest BEFORE running: a generation whose bytes verify (CRC ok)
    but whose stored digest does not match falls back past, exactly
    like a CRC failure, journaled with its own reason."""
    base, w = ck_run
    from avida_tpu.utils import checkpoint as ckpt_mod
    from avida_tpu.utils.faultinject import corrupt_digest
    gens = ckpt_mod.list_generations(str(base / "ck"))
    assert len(gens) == 3
    m = json.load(open(os.path.join(gens[-1], "manifest.json")))
    assert "state_digest" in m
    # sanity: every generation verifies before the tamper
    stored, recomputed = integrity.generation_digest(gens[-1])
    assert stored == recomputed
    corrupt_digest(gens[-1])
    # CRC still passes -- only the digest catches this class
    ckpt_mod.verify_generation(gens[-1])
    w2 = _world(tmp_path / "data2",
                extra=[("TPU_STATE_DIGEST", 1),
                       ("TPU_CKPT_DIR", str(base / "ck"))])
    at = w2.resume()
    assert at == 16                     # fell back past update 24
    assert w2._last_verified_update == 16


def test_ckpt_tool_digest_sweep(ck_run):
    """ckpt_tool --verify reports DIGEST MISMATCH distinctly from CRC
    CORRUPT / TORN MANIFEST, and --list --detail prints the stored
    digest."""
    base, w = ck_run
    import ckpt_tool
    from avida_tpu.utils import checkpoint as ckpt_mod
    from avida_tpu.utils.faultinject import (corrupt_digest, corrupt_leaf,
                                             tear_manifest)
    gens = ckpt_mod.list_generations(str(base / "ck"))
    ok, status, _ = ckpt_tool.verify_status(gens[0])
    assert ok and "digest ok" in status
    corrupt_digest(gens[0])
    ok, status, _ = ckpt_tool.verify_status(gens[0])
    assert not ok and status.startswith("DIGEST MISMATCH")
    corrupt_leaf(gens[1])
    ok, status, _ = ckpt_tool.verify_status(gens[1])
    assert not ok and status.startswith("CORRUPT")
    tear_manifest(gens[2])
    ok, status, _ = ckpt_tool.verify_status(gens[2])
    assert not ok and status.startswith("TORN MANIFEST")


# ---------------------------------------------------------------------------
# supervisor: sdc classification + digest-verified rollback (fake clock)
# ---------------------------------------------------------------------------

def test_classify_sdc():
    from avida_tpu.service import EXIT_SDC, FAILURE_CLASSES
    from avida_tpu.service.supervisor import classify
    assert EXIT_SDC == 67
    assert "sdc" in FAILURE_CLASSES
    assert classify(EXIT_SDC) == "sdc"
    assert classify(0) == "success"
    assert classify(EXIT_SDC, watchdog_killed=True) == "hang"


def _fake_sup(tmp_path, clock=lambda: 1000.0):
    from avida_tpu.service.supervisor import Supervisor, SupervisorConfig
    data = tmp_path / "data"
    os.makedirs(data, exist_ok=True)
    return Supervisor(
        ["-d", str(data), "-set", "TPU_CKPT_DIR", str(tmp_path / "ck")],
        cfg=SupervisorConfig(), env={}, clock=clock, sleep=lambda s: None)


def _fake_gen(base, update, value, tamper=False):
    from avida_tpu.utils import checkpoint as ckpt_mod
    arrays = {"state.x": np.full(4, value, np.int32)}
    digest = integrity.digest_arrays(integrity.state_arrays_of(arrays))
    if tamper:
        digest ^= 0x10
    ckpt_mod.write_generation(str(base), update, arrays, host={},
                              keep=99, extra={"state_digest": digest})


def test_sdc_rollback_quarantines_suspects(tmp_path):
    """The sdc recovery ladder, no processes: generations PAST the
    child's verified horizon are quarantined, then the survivors are
    digest-verified newest-first and mismatches quarantined too, so
    --resume lands on a digest-verified generation."""
    from avida_tpu.utils import checkpoint as ckpt_mod
    sup = _fake_sup(tmp_path)
    ck = tmp_path / "ck"
    _fake_gen(ck, 8, 1)
    _fake_gen(ck, 16, 2, tamper=True)   # CRC-valid, digest-corrupt
    _fake_gen(ck, 24, 3)                # saved past the horizon
    sup._sdc_rollback(verified_update=16)
    gens = ckpt_mod.list_generations(str(ck))
    assert [ckpt_mod.generation_update(g) for g in gens] == [8]
    bad = [d for d in os.listdir(ck) if d.startswith(".bad-")]
    assert len(bad) == 2
    assert sup.rollbacks == 1
    # no marker in the tail -> the plain newest-generation rollback
    sup2 = _fake_sup(tmp_path / "two")
    _fake_gen(tmp_path / "two" / "ck", 8, 1)
    _fake_gen(tmp_path / "two" / "ck", 16, 2)
    sup2._sdc_rollback(verified_update=None)
    gens2 = ckpt_mod.list_generations(str(tmp_path / "two" / "ck"))
    assert [ckpt_mod.generation_update(g) for g in gens2] == [8]


def test_sdc_rollback_never_strands_the_run(tmp_path):
    """Every generation postdating the horizon: the oldest survives
    (a wedge into exit 66 would be worse than a self-consistent
    replay)."""
    from avida_tpu.utils import checkpoint as ckpt_mod
    sup = _fake_sup(tmp_path)
    ck = tmp_path / "ck"
    _fake_gen(ck, 16, 2)
    _fake_gen(ck, 24, 3)
    sup._sdc_rollback(verified_update=8)
    gens = ckpt_mod.list_generations(str(ck))
    assert [ckpt_mod.generation_update(g) for g in gens] == [16]


def test_quarantine_after_helper(tmp_path):
    from avida_tpu.utils import checkpoint as ckpt_mod
    for u in (8, 16, 24):
        _fake_gen(tmp_path, u, u)
    removed = ckpt_mod.quarantine_after(str(tmp_path), 8)
    assert len(removed) == 2
    assert [ckpt_mod.generation_update(g)
            for g in ckpt_mod.list_generations(str(tmp_path))] == [8]


def test_fleet_breaker_counts_sdc(tmp_path):
    """An SDC storm trips the fleet circuit breaker like any crash
    class -- both via supervisor failure diffs (FAILURE_CLASSES grew
    sdc, so _note_failures picks it up) and via the serve pool's
    external-failure note."""
    from avida_tpu.service.fleet import CircuitBreaker
    br = CircuitBreaker(3, 300.0)
    assert not br.note_failure("sdc", 0.0)
    assert not br.note_failure("sdc", 1.0)
    assert br.note_failure("sdc", 2.0)
    assert br.is_open(3.0)


# ---------------------------------------------------------------------------
# slow: the end-to-end scrub-rollback chaos drills (real processes)
# ---------------------------------------------------------------------------

_CHILD_SETS = [(n, str(v)) for n, v in _SETS if n != "RANDOM_SEED"] + [
    ("TPU_CKPT_EVERY", "8"), ("TPU_CKPT_FINAL", "1"),
    ("TPU_CKPT_KEEP", "8"), ("TPU_STATE_DIGEST", "1"),
    ("TPU_SCRUB_EVERY", "2"),
]


def _child_argv(data, ck, extra=()):
    argv = ["-s", str(SEED), "-u", str(UPDATES), "-d", str(data),
            "-set", "TPU_CKPT_DIR", str(ck)]
    for name, value in _CHILD_SETS + list(extra):
        argv += ["-set", name, str(value)]
    return argv


def _child_env():
    env = dict(os.environ)
    env.pop("TPU_FAULT", None)
    env.pop("JAX_COMPILATION_CACHE_DIR", None)   # the PR-6 landmine
    env["JAX_PLATFORMS"] = "cpu"
    env["TPU_COMPILE_CACHE"] = "0"
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env["PYTHONPATH"] = repo + (
        os.pathsep + env["PYTHONPATH"] if env.get("PYTHONPATH") else "")
    return env


def _drill(tmp_path, ref_arrays, extra=()):
    """Supervised child with an injected bitflip inside a SCRUBBED
    chunk (scrub_every=2 x 4-update chunks: [4,8), [12,16), ... are
    sampled; update 13 lands in [12,16)): detect -> exit 67 -> sdc
    rollback -> resume clean -> final generation bit-identical to the
    uninterrupted no-fault reference."""
    from avida_tpu.service.supervisor import Supervisor, SupervisorConfig
    from avida_tpu.utils import checkpoint as ckpt_mod
    data, ck = str(tmp_path / "data"), str(tmp_path / "ck")
    sup = Supervisor(
        _child_argv(data, ck, extra=extra),
        fault_plan=("bitflip:merit,cell=3@update=13",),
        cfg=SupervisorConfig(watchdog_sec=120.0, poll_sec=0.25,
                             grace_sec=600.0, max_retries=6,
                             backoff_base=0.05, backoff_cap=0.2,
                             healthy_sec=1e9, seed=3),
        env=_child_env())
    rc = sup.run()
    assert rc == 0
    assert sup.failures["sdc"] == 1
    recs = [json.loads(line) for line in open(os.path.join(
        data, "supervisor.jsonl"))]
    assert any(r.get("event") == "exit" and r.get("class") == "sdc"
               for r in recs)
    assert any(r.get("event", "").startswith("sdc_rollback")
               for r in recs)
    gens = ckpt_mod.list_generations(ck)
    manifest, arrays, _ = ckpt_mod.read_generation(gens[-1])
    assert manifest["update"] == UPDATES
    assert set(arrays) == set(ref_arrays)
    for name in sorted(arrays):
        np.testing.assert_array_equal(arrays[name], ref_arrays[name],
                                      err_msg=f"array {name}")
    return sup, recs


@pytest.fixture(scope="module")
def ref_arrays(tmp_path_factory):
    """Uninterrupted no-fault reference, via the SAME CLI path as the
    drill children (config parity)."""
    base = tmp_path_factory.mktemp("integrity_ref")
    data, ck = str(base / "data"), str(base / "ck")
    proc = subprocess.run(
        [sys.executable, "-m", "avida_tpu"] + _child_argv(data, ck),
        env=_child_env(), capture_output=True, text=True, timeout=600)
    assert proc.returncode == 0, proc.stderr[-2000:]
    from avida_tpu.utils import checkpoint as ckpt_mod
    gens = ckpt_mod.list_generations(ck)
    _, arrays, _ = ckpt_mod.read_generation(gens[-1])
    return arrays


@pytest.mark.slow
def test_scrub_rollback_drill_xla(tmp_path, ref_arrays):
    sup, recs = _drill(tmp_path, ref_arrays)
    assert sup.pallas_fallbacks == 0    # xla engine: no degradation


@pytest.mark.slow
def test_scrub_rollback_drill_pallas(tmp_path, ref_arrays):
    """The kernel-path drill (interpret Pallas on CPU): the divergence
    error names a pallas engine, so the supervisor applies the one-shot
    Pallas->XLA degradation on the recovery boot -- and the final state
    is STILL bit-identical (the engines are bit-exact equals)."""
    sup, recs = _drill(tmp_path, ref_arrays,
                       extra=(("TPU_USE_PALLAS", "1"),))
    assert sup.pallas_fallbacks == 1
    assert any(r.get("event") == "pallas_fallback" for r in recs)


# ---------------------------------------------------------------------------
# slow: batched + serve flavors
# ---------------------------------------------------------------------------

@pytest.mark.slow
def test_multiworld_batched_digests_match_solo(tmp_path):
    """A W=2 batch with the integrity plane on: per-world digests equal
    each member's solo digest (same state bits -> same fold), scrub
    passes, trajectories stay bit-exact vs solo runs."""
    from avida_tpu.ops.digest import state_digest
    from avida_tpu.parallel.multiworld import MultiWorld
    solo = {}
    for seed in (7, 8):
        w = _world(tmp_path / f"solo{seed}",
                   extra=[("RANDOM_SEED", seed)])
        w.run(max_updates=UPDATES)
        solo[seed] = int(state_digest(w.state))
    mw = MultiWorld.from_seeds(
        [7, 8], overrides=_SETS + [("TPU_STATE_DIGEST", 1),
                                   ("TPU_SCRUB_EVERY", 2)],
        data_dir=str(tmp_path / "batch"))
    mw.run(max_updates=UPDATES)
    assert mw.state_digests is not None
    u, vals = mw.state_digests
    assert u == UPDATES
    assert vals == [solo[7], solo[8]]
    assert mw._last_verified_update == UPDATES


@pytest.mark.slow
def test_serve_sdc_demotes_corrupt_tenant_alone(tmp_path):
    """The serving guarantee: an SDC in ONE tenant's live execution
    (emulated by corrupting that tenant's slot in the scan output --
    the shadow replay reproduces the clean result) demotes that tenant
    alone with its suspect generations quarantined and an `sdc`
    outcome for the pool, while its classmate keeps serving and
    finishes bit-exact."""
    import jax
    import jax.numpy as jnp
    from avida_tpu.parallel.multiworld import ServeBatch
    from avida_tpu.utils import checkpoint as ckpt_mod
    from avida_tpu.utils import compilecache

    base = tmp_path
    control = base / "control.json"
    members = [{"name": f"t{i}", "seed": 7 + i,
                "data_dir": str(base / f"t{i}" / "data"),
                "ckpt_dir": str(base / f"t{i}" / "ck"),
                "max_updates": UPDATES} for i in range(2)]
    control.write_text(json.dumps(
        {"width": 2, "members": members}))

    def factory(entry):
        from avida_tpu.world import World
        ov = _SETS + [("TPU_STATE_DIGEST", 1), ("TPU_SCRUB_EVERY", 1),
                      ("RANDOM_SEED", int(entry["seed"]))]
        if entry.get("ckpt_dir"):
            ov.append(("TPU_CKPT_DIR", entry["ckpt_dir"]))
        return World(overrides=[(n, v) for n, v in ov
                                if n != "RANDOM_SEED"]
                     + [("RANDOM_SEED", int(entry["seed"]))],
                     data_dir=entry["data_dir"])

    sb = ServeBatch(2, str(control), str(base / "serve"),
                    world_factory=factory)
    assert sb._scrub_every == 1
    sb._reconcile()
    assert sb.num_live == 2

    # advance two clean boundaries (scrubbed, passing), with per-tenant
    # checkpoints so the corrupt tenant has generations to quarantine
    sb._stack()
    for _ in range(2):
        sb._scan(4)
        sb._sync_worlds()
        for i, w in sb._live():
            w.save_checkpoint()
        sb._stack()
    assert sb._verified == [8, 8]

    # emulate an SDC in tenant t0's NEXT live chunk: corrupt slot 0 of
    # the first scan result only -- the shadow replay (second call)
    # recomputes clean, so the digests diverge exactly like a real
    # transient flip
    real_call = compilecache.call
    armed = {"n": 1}

    def corrupting_call(jit_fn, tag, args, **kw):
        out = real_call(jit_fn, tag, args, **kw)
        if tag == "multiworld_scan" and armed["n"]:
            armed["n"] -= 1
            bst, outs = out
            word = jax.lax.bitcast_convert_type(
                bst.merit[0, 3], jnp.uint32) ^ jnp.uint32(1)
            bst = bst.replace(merit=bst.merit.at[0, 3].set(
                jax.lax.bitcast_convert_type(word, bst.merit.dtype)))
            return bst, outs
        return out

    saved = integrity.counters()
    integrity.reset_for_tests()
    try:
        # the batched drivers resolve `compilecache.call` through the
        # module attribute at call time, so patching the module global
        # intercepts exactly the scan dispatches
        compilecache.call = corrupting_call
        sb._scan(4)
    finally:
        compilecache.call = real_call
    assert integrity.counters()["mismatches"] == 1
    integrity.reset_for_tests()
    for k, v in saved.items():
        integrity._counters[k] = v

    # t0 demoted alone, generations past its verified horizon gone
    assert sb.finished["t0"]["state"] == "sdc"
    assert sb.finished["t0"]["last_verified_update"] == 8
    assert sb.num_live == 1
    assert sb.names.count("t1") == 1
    gens = ckpt_mod.list_generations(members[0]["ckpt_dir"])
    assert [ckpt_mod.generation_update(g) for g in gens] == [4, 8]

    # the classmate keeps serving to completion, bit-exact vs solo
    for _ in range(3):
        sb._scan(4)
    sb._sync_worlds()
    (i1, w1), = sb._live()
    assert w1.update == UPDATES
    solo = _world(tmp_path / "solo8", extra=[("RANDOM_SEED", 8)])
    solo.run(max_updates=UPDATES)
    np.testing.assert_array_equal(np.asarray(w1.state.merit),
                                  np.asarray(solo.state.merit))
    np.testing.assert_array_equal(np.asarray(w1.state.tape),
                                  np.asarray(solo.state.tape))
