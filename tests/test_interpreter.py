"""Interpreter oracle tests.

The strongest single check available: the default ancestor
(support/config/default-heads.org) must self-replicate exactly, and its
life-history numbers must match the reference's golden outputs
(tests/heads_default_100u/expected/data/average.dat row 0: merit 97,
gestation 389, copied size 100, executed size 97 -- the reference computes
these by running the very same program through cHardwareCPU).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from avida_tpu.config import AvidaConfig, default_instset
from avida_tpu.config.environment import default_logic9_environment
from avida_tpu.core.state import init_population, make_world_params
from avida_tpu.ops.interpreter import extract_offspring, micro_step
from avida_tpu.world import default_ancestor

pytestmark = pytest.mark.slow


def make_single_org(cfg_updates=None):
    cfg = AvidaConfig()
    cfg.WORLD_X = 1
    cfg.WORLD_Y = 1
    cfg.TPU_MAX_MEMORY = 320
    # no mutations for exact-replication checks
    cfg.COPY_MUT_PROB = 0.0
    cfg.DIVIDE_INS_PROB = 0.0
    cfg.DIVIDE_DEL_PROB = 0.0
    for k, v in (cfg_updates or {}).items():
        setattr(cfg, k, v)
    iset = default_instset()
    env = default_logic9_environment()
    params = make_world_params(cfg, iset, env)
    genome = default_ancestor(iset)
    st = init_population(params, genome, jax.random.key(0), inject_cell=0)
    return params, st, genome


def run_until_divide(params, st, max_cycles=1000):
    mask = jnp.ones(1, bool)
    step = jax.jit(lambda s, k: micro_step(params, s, k, mask))
    key = jax.random.key(1)
    for cycle in range(max_cycles):
        key, k = jax.random.split(key)
        st = step(st, k)
        if bool(st.divide_pending[0]):
            return st, cycle + 1
    raise AssertionError("ancestor never divided")


def test_ancestor_first_steps():
    params, st, genome = make_single_org()
    mask = jnp.ones(1, bool)
    key = jax.random.key(1)
    step = jax.jit(lambda s, k: micro_step(params, s, k, mask))

    # cycle 1: h-alloc extends memory 100 -> 300, AX = 100
    st = step(st, key)
    assert int(st.mem_len[0]) == 300
    assert int(st.regs[0, 0]) == 100
    assert bool(st.mal_active[0])
    # allocated region filled with default instruction (op 0)
    np.testing.assert_array_equal(np.asarray(st.mem[0, 100:300]), 0)

    # cycle 2: h-search with label CA -> complement AB found at genome end;
    # FLOW lands on first line of allocated space (100), BX=97, CX=2
    st = step(st, key)
    assert int(st.heads[0, 3]) == 100, "FLOW should mark offspring start"
    assert int(st.regs[0, 2]) == 2      # CX = label size
    # BX = last-label-line - IP position (97 - 3... see Inst_HeadSearch)
    assert int(st.regs[0, 1]) == 96

    # cycle 3: mov-head nop-C -> WRITE head to FLOW (=100)
    st = step(st, key)
    assert int(st.heads[0, 2]) == 100


def test_ancestor_replicates_exactly():
    params, st, genome = make_single_org()
    st, gestation = run_until_divide(params, st)

    # golden numbers from the reference run (expected average.dat row 0)
    assert gestation == 389, f"gestation {gestation} != 389"
    assert int(st.off_len[0]) == 100
    off, _ = extract_offspring(params, st, jax.random.key(9))
    offspring = np.asarray(off[0, :100])
    np.testing.assert_array_equal(offspring, genome,
                                  "offspring must be an exact copy")
    assert int(st.executed_size[0]) == 97
    assert int(st.child_copied_size[0]) == 100
    # merit = min(len, copied, executed) * bonus(1) = 97
    assert float(st.merit[0]) == 97.0
    assert float(st.fitness[0]) == pytest.approx(97.0 / 389.0)
    # parent reset: memory cropped to 100, IP at 0, heads cleared
    assert int(st.mem_len[0]) == 100
    assert int(st.heads[0, 0]) == 0
    assert int(st.generation[0]) == 1


def test_second_gestation_same_length():
    # after the divide reset the parent re-runs the same program; the second
    # gestation must also be 389 (steady-state replication)
    params, st, genome = make_single_org()
    st, g1 = run_until_divide(params, st)
    st = st.replace(divide_pending=jnp.zeros(1, bool))  # flush
    st, g2 = run_until_divide(params, st)
    assert g2 == 389


def test_copy_mutations_change_offspring():
    params, st, genome = make_single_org({"COPY_MUT_PROB": 0.05})
    st, gestation = run_until_divide(params, st)
    off, off_len = extract_offspring(params, st, jax.random.key(9))
    offspring = np.asarray(off[0, :int(off_len[0])])
    # with 5% per-copy mutation over ~200 copies, changes are certain
    assert (offspring[:100] != genome).any() or int(off_len[0]) != 100


def test_death_by_age():
    # DEATH_METHOD 2: die at genome_length * AGE_LIMIT cycles
    params, st, genome = make_single_org({"AGE_LIMIT": 1})
    mask = jnp.ones(1, bool)
    step = jax.jit(lambda s, k: micro_step(params, s, k, mask))
    key = jax.random.key(1)
    for _ in range(100):
        st = step(st, key)
    assert not bool(st.alive[0])
    assert int(st.time_used[0]) == 100
