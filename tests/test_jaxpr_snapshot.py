"""Fast-tier wiring for the jaxpr-snapshot regression gate
(scripts/check_jaxpr.py): the disabled-telemetry update_step must trace
to the recorded program.  Runs IN-PROCESS (tier-1 runs solo on a 1-core
host; no subprocess spawn) on the conftest-forced CPU platform --
exactly the toolchain the snapshot was recorded under."""

from __future__ import annotations

import sys
import os

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", "scripts"))

import check_jaxpr  # noqa: E402


def test_update_step_jaxpr_matches_snapshot():
    ok, msg = check_jaxpr.check()
    assert ok, msg


def test_snapshot_digest_is_current_format():
    import json
    with open(check_jaxpr.SNAPSHOT) as f:
        snap = json.load(f)
    assert len(snap["update_step_sha256"]) == 64
    assert snap["platform"] == "cpu"
