"""Tier-1 lint gate: `ruff check` over the repo with the pyproject config.

Keeps the scoped rule set (unused imports, constant f-strings, comparison
pitfalls -- see [tool.ruff.lint] in pyproject.toml) from regressing.  The
container images used for CI bake ruff in; dev hosts without it skip
cleanly rather than fail.
"""

import os
import shutil
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _ruff_argv():
    """Best available ruff entry point, or None."""
    try:                                    # pip-installed wheel
        from ruff.__main__ import find_ruff_bin
        return [find_ruff_bin()]
    except ImportError:
        pass
    exe = shutil.which("ruff")
    if exe:
        return [exe]
    try:
        import ruff  # noqa: F401  -- module present but no bin helper
        return [sys.executable, "-m", "ruff"]
    except ImportError:
        return None


def test_ruff_clean():
    argv = _ruff_argv()
    if argv is None:
        pytest.skip("ruff not installed")
    proc = subprocess.run(
        argv + ["check", "--no-cache", "."],
        cwd=REPO, capture_output=True, text=True, timeout=300)
    assert proc.returncode == 0, (
        "ruff findings:\n" + proc.stdout + proc.stderr)
