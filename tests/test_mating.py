"""Mating types + birth-chamber handlers (round-5, VERDICT r4 directive
#7): set-mating-type-* instructions (cHardwareCPU.cc:10896-10946),
typed assortative pairing (cBirthMatingTypeGlobalHandler::SelectOffspring),
and modular continuous recombination (cBirthChamber.cc:316)."""

from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from avida_tpu.config import AvidaConfig
from avida_tpu.config.instset import heads_sex_instset
from avida_tpu.config.environment import default_logic9_environment
from avida_tpu.core.state import make_world_params, zeros_population


def _mating_instset():
    s = heads_sex_instset()
    for name in ("set-mating-type-male", "set-mating-type-female",
                 "set-mating-type-juvenile", "if-mating-type-male",
                 "if-mating-type-female"):
        s.inst_names.append(name)
        s.redundancy = np.append(s.redundancy, 1.0)
        s.cost = np.append(s.cost, 0).astype(np.int32)
        s.ft_cost = np.append(s.ft_cost, 0).astype(np.int32)
        s.energy_cost = np.append(s.energy_cost, 0.0)
        s.prob_fail = np.append(s.prob_fail, 0.0)
        s.addl_time_cost = np.append(s.addl_time_cost, 0).astype(np.int32)
        s.res_cost = np.append(s.res_cost, 0.0)
    return s


def _params(**kw):
    cfg = AvidaConfig()
    cfg.WORLD_X = 4
    cfg.WORLD_Y = 4
    cfg.TPU_MAX_MEMORY = 64
    cfg.MATING_TYPES = 1
    cfg.COPY_MUT_PROB = 0.0
    for k, v in kw.items():
        cfg.set(k, v)
    return make_world_params(cfg, _mating_instset(),
                             default_logic9_environment())


def test_mating_type_instructions():
    """set-male/-female transitions + the male->female refusal + the
    mating-type conditionals."""
    from avida_tpu.ops.interpreter import micro_step
    p = _params()
    s = _mating_instset()
    male = s.opcode("set-mating-type-male")
    female = s.opcode("set-mating-type-female")
    ifm = s.opcode("if-mating-type-male")
    inc = s.opcode("inc")
    st = zeros_population(p.num_cells, p.max_memory, p.num_reactions)
    prog = [male, female, ifm, inc, ifm, inc]
    tape = np.zeros((p.num_cells, p.max_memory), np.uint8)
    tape[0, :len(prog)] = prog
    st = st.replace(tape=jnp.asarray(tape),
                    mem_len=st.mem_len.at[0].set(len(prog)),
                    genome_len=st.genome_len.at[0].set(len(prog)),
                    alive=st.alive.at[0].set(True))
    mask = jnp.zeros(p.num_cells, bool).at[0].set(True)
    key = jax.random.key(0)
    step = jax.jit(lambda s_, k: micro_step(p, s_, k, mask))
    assert int(st.mating_type[0]) == -1     # juvenile at birth
    key, k = jax.random.split(key)
    st = step(st, k)
    assert int(st.mating_type[0]) == 1      # became male
    key, k = jax.random.split(key)
    st = step(st, k)
    assert int(st.mating_type[0]) == 1      # set-female REFUSED (is male)
    # if-mating-type-male executes the inc; BX becomes 1
    for _ in range(2):
        key, k = jax.random.split(key)
        st = step(st, k)
    assert int(st.regs[0, 1]) == 1


def test_assortative_pairing_and_juvenile_loss():
    """M+F pair (recombine), juvenile offspring lost, extra male stored
    with its type."""
    from avida_tpu.ops.birth import recombine_sexual
    p = _params()
    n, L = p.num_cells, p.max_memory
    st = zeros_population(n, L, p.num_reactions)
    g = np.zeros((n, L), np.int8)
    for c in range(4):
        g[c, :20] = c + 1
    st = st.replace(
        alive=jnp.asarray([True] * 4 + [False] * (n - 4)),
        merit=jnp.ones(n, jnp.float32).at[0].set(8.0).at[2].set(2.0),
        divide_pending=jnp.asarray([True] * 4 + [False] * (n - 4)),
        off_sex=jnp.asarray([True] * 4 + [False] * (n - 4)),
        # parents: male, male, female, juvenile
        mating_type=jnp.asarray([1, 1, 0, -1] + [-1] * (n - 4), jnp.int32))
    off_mem = jnp.asarray(g)
    off_len = jnp.where(st.divide_pending, 20, 0)
    pending = st.divide_pending
    (om, ol, cm, placeable, dual, dm, dl, dmer, store) = recombine_sexual(
        p, st, jax.random.key(2), off_mem, off_len, pending)
    placeable = np.asarray(placeable)
    # exactly ONE male paired female 2 (pairing is a per-flush random
    # matching, so either male may be chosen); the other male waits
    assert placeable[2]
    assert placeable[0] != placeable[1], placeable[:4]
    # the unpaired male went to the store; juvenile 3's offspring dropped
    assert not placeable[3]
    bc_mem, bc_len, bc_merit, bc_valid, bc_type = store
    assert bool(bc_valid) and int(bc_type) == 1
    assert int(bc_len) == 20


def test_stored_male_pairs_next_female():
    """A stored male entry mates the next flush's female offspring."""
    from avida_tpu.ops.birth import recombine_sexual
    p = _params()
    n, L = p.num_cells, p.max_memory
    st = zeros_population(n, L, p.num_reactions)
    st = st.replace(
        alive=st.alive.at[5].set(True),
        merit=jnp.ones(n, jnp.float32),
        divide_pending=st.divide_pending.at[5].set(True),
        off_sex=st.off_sex.at[5].set(True),
        mating_type=jnp.full(n, -1, jnp.int32).at[5].set(0),  # female
        bc_mem=jnp.full(L, 3, jnp.int8),
        bc_len=jnp.asarray(16, jnp.int32),
        bc_merit=jnp.asarray(4.0, jnp.float32),
        bc_valid=jnp.asarray(True),
        bc_type=jnp.asarray(1, jnp.int32))                    # stored male
    off_mem = jnp.zeros((n, L), jnp.int8).at[5, :20].set(7)
    off_len = jnp.zeros(n, jnp.int32).at[5].set(20)
    (om, ol, cm, placeable, dual, dm, dl, dmer, store) = recombine_sexual(
        p, st, jax.random.key(4), off_mem, off_len, st.divide_pending)
    assert bool(np.asarray(placeable)[5])
    assert bool(np.asarray(dual)[5])         # store child rides this row
    assert not bool(store[3])                # store consumed


def test_same_type_offspring_wait_not_pair():
    """Two male-parent offspring do NOT pair with each other."""
    from avida_tpu.ops.birth import recombine_sexual
    p = _params()
    n, L = p.num_cells, p.max_memory
    st = zeros_population(n, L, p.num_reactions)
    st = st.replace(
        alive=st.alive.at[0].set(True).at[1].set(True),
        merit=jnp.ones(n, jnp.float32),
        divide_pending=st.divide_pending.at[0].set(True).at[1].set(True),
        off_sex=st.off_sex.at[0].set(True).at[1].set(True),
        mating_type=jnp.full(n, -1, jnp.int32).at[0].set(1).at[1].set(1))
    off_mem = jnp.zeros((n, L), jnp.int8)
    off_len = jnp.zeros(n, jnp.int32).at[0].set(20).at[1].set(20)
    (om, ol, cm, placeable, dual, dm, dl, dmer, store) = recombine_sexual(
        p, st, jax.random.key(5), off_mem, off_len, st.divide_pending)
    assert not np.asarray(placeable)[:2].any()   # neither placed
    assert bool(store[3]) and int(store[4]) == 1  # one stored (male)


def test_modular_recombination_snaps_to_module_boundaries():
    """MODULE_NUM=4 with equal 40-inst genomes: crossover cuts land on
    multiples of 10, so swapped regions are whole modules and offspring
    length stays 40 (DoModularContRecombination)."""
    from avida_tpu.ops.birth import recombine_sexual
    cfg_extra = dict(MATING_TYPES=0, MODULE_NUM=4)
    p = _params(**cfg_extra)
    n, L = p.num_cells, p.max_memory
    for seed in range(6):
        st = zeros_population(n, L, p.num_reactions)
        st = st.replace(
            alive=st.alive.at[0].set(True).at[1].set(True),
            merit=jnp.ones(n, jnp.float32),
            divide_pending=st.divide_pending.at[0].set(True).at[1].set(
                True),
            off_sex=st.off_sex.at[0].set(True).at[1].set(True))
        g = np.zeros((n, L), np.int8)
        g[0, :40] = 1
        g[1, :40] = 2
        off_mem = jnp.asarray(g)
        off_len = jnp.zeros(n, jnp.int32).at[0].set(40).at[1].set(40)
        (om, ol, cm, placeable, *_rest) = recombine_sexual(
            p, st, jax.random.key(seed), off_mem, off_len,
            st.divide_pending)
        om = np.asarray(om)
        ol = np.asarray(ol)
        assert ol[0] == 40 and ol[1] == 40
        # content switches only at module boundaries (multiples of 10)
        child = om[0, :40]
        switches = np.nonzero(np.diff(child))[0] + 1
        assert all(sw % 10 == 0 for sw in switches), (seed, switches)
