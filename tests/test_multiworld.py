"""Multi-world device batching (parallel/multiworld.py + --worlds CLI).

Tier-1 proves the batching contract on the XLA path: every world in a
W=4 batch -- mutations on, births on, systematics on -- is bit-exact
vs its solo run, per-world checkpoints are byte-identical to solo ones,
and a mixed-seed batch survives SIGTERM preemption + aligned resume.
The Pallas-kernel / packed-resident-chunk interaction is slow-marked
(interpret mode).  Single-world behavior is guarded by the jaxpr digest
gate: batching adds NO state and NO trace change to update_step.
"""

from __future__ import annotations

import json
import os
import signal
import sys

import numpy as np
import pytest

from avida_tpu.config import AvidaConfig
from avida_tpu.parallel.multiworld import MultiWorld, multiworld_scan
from avida_tpu.utils import checkpoint as ckpt_mod
from avida_tpu.world import World

SEEDS = (3, 11, 29, 41)
# 17 updates = mutations + births + multiple genotypes at this world
# config, on a chunk grid of 8+8+1: the trailing SINGLE-update chunk
# pins the solo run_update drain convention (systematics window
# stamped with the pre-advance update) under the checkpoint
# byte-compare.  Only chunk sizes 8 and 1 ever compile; 20 would add
# a chunk-4 program for both the solo and batched sides -- pure
# tier-1 budget, no extra coverage
U = 17


def _cfg(seed, ck=None, **extra):
    cfg = AvidaConfig()
    cfg.WORLD_X = 8
    cfg.WORLD_Y = 8
    cfg.TPU_MAX_MEMORY = 256
    cfg.RANDOM_SEED = seed
    cfg.AVE_TIME_SLICE = 100
    cfg.TPU_MAX_STEPS_PER_UPDATE = 100
    cfg.set("TPU_CKPT_AUDIT", 0)
    if ck:
        cfg.set("TPU_CKPT_DIR", str(ck))
        cfg.set("TPU_CKPT_EVERY", 8)
        cfg.set("TPU_CKPT_FINAL", 1)
    for k, v in extra.items():
        cfg.set(k, v)
    return cfg


def _world(seed, data, ck=None, **extra):
    w = World(cfg=_cfg(seed, ck, **extra), data_dir=str(data))
    w.events = []
    return w


@pytest.fixture(scope="module")
def solo_refs(tmp_path_factory):
    """The four uninterrupted solo reference runs (with per-world
    checkpoint generations) every batch leg compares against."""
    td = tmp_path_factory.mktemp("solo")
    refs = []
    for s in SEEDS:
        w = _world(s, td / f"d{s}", td / f"ck{s}")
        w.run(max_updates=U)
        refs.append((w, str(td / f"ck{s}")))
    return refs


def _assert_world_equal(a, b, nb_scratch_exact=True):
    """Solo world `a` == batch member `b`: full state, host
    accumulators, executed totals and the phylogeny."""
    scratch = ("nb_genome", "nb_len", "nb_cell", "nb_parent", "nb_update")
    for name in a.state.__dataclass_fields__:
        va = getattr(a.state, name)
        if va is None:
            continue
        va = np.asarray(va)
        vb = np.asarray(getattr(b.state, name))
        if name in scratch and not nb_scratch_exact:
            cnt = int(np.asarray(a.state.nb_count))
            va, vb = va[:cnt], vb[:cnt]
        np.testing.assert_array_equal(va, vb, err_msg=f"field {name}")
    for attr in ("_avida_time", "_last_ave_gen", "_deaths_this",
                 "_total_births"):
        assert np.asarray(getattr(a, attr)) == np.asarray(
            getattr(b, attr)), attr
    assert a._flush_exec() == b._flush_exec()
    assert a.systematics.num_genotypes == b.systematics.num_genotypes
    assert sorted(g.sequence.tobytes()
                  for g in a.systematics.live_genotypes()) \
        == sorted(g.sequence.tobytes()
                  for g in b.systematics.live_genotypes())


def test_w4_batch_bit_exact_and_checkpoints_byte_identical(
        solo_refs, tmp_path):
    """The acceptance core: a W=4 batch (distinct seeds, one compiled
    program) reproduces each member's solo trajectory exactly AND
    publishes per-world checkpoint generations byte-identical to the
    solo runs' -- so --resume, ckpt_tool and the analytics pipeline
    work unchanged on batch output."""
    worlds = [_world(s, tmp_path / f"d{s}", tmp_path / f"ck{s}",
                     TPU_METRICS=1) for s in SEEDS]
    mw = MultiWorld(worlds, data_dir=str(tmp_path / "root"))
    mw.run(max_updates=U)
    for i, (solo, solo_ck) in enumerate(solo_refs):
        _assert_world_equal(solo, mw.worlds[i])
        ga = ckpt_mod.list_generations(solo_ck)
        gb = ckpt_mod.list_generations(str(tmp_path / f"ck{SEEDS[i]}"))
        assert [os.path.basename(p) for p in ga] \
            == [os.path.basename(p) for p in gb] and ga
        for pa, pb in zip(ga, gb):
            for fn in sorted(os.listdir(pa)):
                with open(os.path.join(pa, fn), "rb") as f:
                    ba = f.read()
                with open(os.path.join(pb, fn), "rb") as f:
                    bb = f.read()
                if fn == ckpt_mod.MANIFEST:
                    ja, jb = json.loads(ba), json.loads(bb)
                    ja.pop("saved_at"), jb.pop("saved_at")
                    assert ja == jb, f"{os.path.basename(pa)}/{fn}"
                else:
                    assert ba == bb, f"{os.path.basename(pa)}/{fn}"
    # the exporter satellite: aggregate heartbeat at the root plus
    # per-world labeled rows in multiworld.prom
    from avida_tpu.observability.exporter import read_metrics
    agg = read_metrics(str(tmp_path / "root" / "metrics.prom"))
    per = read_metrics(str(tmp_path / "root" / "multiworld.prom"))
    assert agg["avida_update"] == U
    assert per["avida_multiworld_size"] == len(SEEDS)
    orgs = [per[f'avida_organisms{{world="w{k:03d}"}}']
            for k in range(len(SEEDS))]
    assert agg["avida_organisms"] == sum(orgs)
    assert orgs[0] == mw.worlds[0].num_organisms


def test_mixed_seed_batch_sigterm_resume_bit_exact(solo_refs, tmp_path):
    """SIGTERM lands mid-batch: the preemption flag trips at the next
    chunk boundary, every world saves a checkpoint at the SAME update,
    and a fresh batch resumes aligned and finishes bit-exact vs the
    uninterrupted solo runs."""
    worlds = [_world(s, tmp_path / f"d{s}", tmp_path / f"ck{s}")
              for s in SEEDS]
    mw = MultiWorld(worlds, data_dir=str(tmp_path / "root"))

    def hook(m):
        if m.update >= 8:
            os.kill(os.getpid(), signal.SIGTERM)

    mw._boundary_hook = hook
    mw.run(max_updates=U)
    assert mw.preempted and mw.update < U
    saved = [ckpt_mod.latest_valid(str(tmp_path / f"ck{s}"))[1]["update"]
             for s in SEEDS]
    assert len(set(saved)) == 1          # one aligned preempt boundary

    worlds2 = [_world(s, tmp_path / f"d{s}", tmp_path / f"ck{s}")
               for s in SEEDS]
    mw2 = MultiWorld(worlds2, data_dir=str(tmp_path / "root2"))
    assert mw2.resume() == saved[0]
    mw2.run(max_updates=U)
    assert not mw2.preempted
    for i, (solo, _) in enumerate(solo_refs):
        # rows past the newborn-ring cursor are drain scratch whose
        # stale contents legitimately differ across a resume re-chunk
        _assert_world_equal(solo, mw2.worlds[i], nb_scratch_exact=False)


def test_batch_eligibility_validation(tmp_path):
    from avida_tpu.config.events import parse_event_line

    a = _world(1, tmp_path / "a")
    with pytest.raises(ValueError, match="identical static"):
        MultiWorld([a, _world(2, tmp_path / "b", WORLD_X=10)])
    with pytest.raises(ValueError, match="shared event schedule"):
        b = _world(2, tmp_path / "c")
        b.events = [parse_event_line("u 5 Exit")]
        MultiWorld([a, b])
    with pytest.raises(ValueError, match="chunkable"):
        c = _world(1, tmp_path / "e")
        d = _world(2, tmp_path / "f")
        c.events = [parse_event_line("g 0:10 PrintAverageData")]
        d.events = [parse_event_line("g 0:10 PrintAverageData")]
        MultiWorld([c, d])
    with pytest.raises(ValueError, match="at least one"):
        MultiWorld([])
    # distinct cfg objects are required (seeds/dirs must be per-world)
    with pytest.raises(ValueError, match="own config"):
        MultiWorld([a, a])


def test_worlds_cli_rejects_bad_spec(tmp_path):
    from avida_tpu.__main__ import main
    assert main(["--worlds", str(tmp_path / "nope.json"),
                 "-u", "1"]) == 2


def test_multiworld_off_zero_state_and_jaxpr_digest():
    """The trace_cap/lane_perm pattern: with no batch in play the
    engine is untouched -- importing the batcher adds no
    PopulationState field, and the single-world update_step still
    traces to the recorded jaxpr digest."""
    import avida_tpu.parallel.multiworld  # noqa: F401  (the import IS the test)
    from avida_tpu.core.state import PopulationState
    assert not any("world" in f or "batch" in f
                   for f in PopulationState.__dataclass_fields__)
    sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..",
                                    "scripts"))
    import check_jaxpr
    ok, msg = check_jaxpr.check(check_jaxpr.compute())
    assert ok, msg


@pytest.mark.slow
def test_batch_matches_solo_on_pallas_and_packed_paths():
    """The kernel interaction: the batched scan composes with the
    interpret-mode Pallas cycle kernel AND the packed-resident chunk,
    bit-exact per world vs solo scans with the same knobs."""
    import jax
    import jax.numpy as jnp

    from avida_tpu.ops.update import update_scan

    def mk(seed, packed):
        cfg = AvidaConfig()
        cfg.WORLD_X = 8
        cfg.WORLD_Y = 8
        cfg.TPU_MAX_MEMORY = 256
        cfg.RANDOM_SEED = seed
        cfg.TPU_USE_PALLAS = 1
        cfg.set("TPU_KERNEL_SHARDS", 1)
        cfg.set("TPU_LANE_PERM", 0)
        cfg.set("TPU_PACKED_CHUNK", 1 if packed else 0)
        cfg.set("TPU_SYSTEMATICS", 0)
        w = World(cfg=cfg)
        w.events = []
        w.inject()
        return w

    for packed in (False, True):
        seeds = [5, 9]
        solo = []
        for s in seeds:
            w = mk(s, packed)
            st, _ = update_scan(w.params, w.state, 4, w._run_key,
                                w.neighbors, jnp.int32(0))
            solo.append(st)
        worlds = [mk(s, packed) for s in seeds]
        bstate = jax.tree.map(lambda *xs: jnp.stack(xs),
                              *[w.state for w in worlds])
        rkeys = jnp.stack([w._run_key for w in worlds])
        bst, _ = multiworld_scan(worlds[0].params, bstate, 4, rkeys,
                                 worlds[0].neighbors, jnp.int32(0))
        for i in range(len(seeds)):
            for name in bst.__dataclass_fields__:
                v = getattr(bst, name)
                if v is None:
                    continue
                np.testing.assert_array_equal(
                    np.asarray(getattr(solo[i], name)),
                    np.asarray(v)[i],
                    err_msg=f"packed={packed} world={i} field {name}")
