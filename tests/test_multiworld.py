"""Multi-world device batching (parallel/multiworld.py + --worlds CLI).

Tier-1 proves the batching contract on the XLA path: every world in a
W=4 batch -- mutations on, births on, systematics on -- is bit-exact
vs its solo run, per-world checkpoints are byte-identical to solo ones,
and a mixed-seed batch survives SIGTERM preemption + aligned resume.
The Pallas-kernel / packed-resident-chunk interaction is slow-marked
(interpret mode).  Single-world behavior is guarded by the jaxpr digest
gate: batching adds NO state and NO trace change to update_step.
"""

from __future__ import annotations

import json
import os
import signal
import sys

import numpy as np
import pytest

from avida_tpu.config import AvidaConfig
from avida_tpu.parallel.multiworld import MultiWorld, multiworld_scan
from avida_tpu.utils import checkpoint as ckpt_mod
from avida_tpu.world import World

SEEDS = (3, 11, 29, 41)
# 17 updates = mutations + births + multiple genotypes at this world
# config, on a chunk grid of 8+8+1: the trailing SINGLE-update chunk
# pins the solo run_update drain convention (systematics window
# stamped with the pre-advance update) under the checkpoint
# byte-compare.  Only chunk sizes 8 and 1 ever compile; 20 would add
# a chunk-4 program for both the solo and batched sides -- pure
# tier-1 budget, no extra coverage
U = 17


def _cfg(seed, ck=None, **extra):
    cfg = AvidaConfig()
    cfg.WORLD_X = 8
    cfg.WORLD_Y = 8
    cfg.TPU_MAX_MEMORY = 256
    cfg.RANDOM_SEED = seed
    cfg.AVE_TIME_SLICE = 100
    cfg.TPU_MAX_STEPS_PER_UPDATE = 100
    cfg.set("TPU_CKPT_AUDIT", 0)
    if ck:
        cfg.set("TPU_CKPT_DIR", str(ck))
        cfg.set("TPU_CKPT_EVERY", 8)
        cfg.set("TPU_CKPT_FINAL", 1)
    for k, v in extra.items():
        cfg.set(k, v)
    return cfg


def _world(seed, data, ck=None, **extra):
    w = World(cfg=_cfg(seed, ck, **extra), data_dir=str(data))
    w.events = []
    return w


@pytest.fixture(scope="module")
def solo_refs(tmp_path_factory):
    """The four uninterrupted solo reference runs (with per-world
    checkpoint generations) every batch leg compares against."""
    td = tmp_path_factory.mktemp("solo")
    refs = []
    for s in SEEDS:
        w = _world(s, td / f"d{s}", td / f"ck{s}")
        w.run(max_updates=U)
        refs.append((w, str(td / f"ck{s}")))
    return refs


def _assert_world_equal(a, b, nb_scratch_exact=True):
    """Solo world `a` == batch member `b`: full state, host
    accumulators, executed totals and the phylogeny."""
    scratch = ("nb_genome", "nb_len", "nb_cell", "nb_parent", "nb_update")
    for name in a.state.__dataclass_fields__:
        va = getattr(a.state, name)
        if va is None:
            continue
        va = np.asarray(va)
        vb = np.asarray(getattr(b.state, name))
        if name in scratch and not nb_scratch_exact:
            cnt = int(np.asarray(a.state.nb_count))
            va, vb = va[:cnt], vb[:cnt]
        np.testing.assert_array_equal(va, vb, err_msg=f"field {name}")
    for attr in ("_avida_time", "_last_ave_gen", "_deaths_this",
                 "_total_births"):
        assert np.asarray(getattr(a, attr)) == np.asarray(
            getattr(b, attr)), attr
    assert a._flush_exec() == b._flush_exec()
    assert a.systematics.num_genotypes == b.systematics.num_genotypes
    assert sorted(g.sequence.tobytes()
                  for g in a.systematics.live_genotypes()) \
        == sorted(g.sequence.tobytes()
                  for g in b.systematics.live_genotypes())


def test_w4_batch_bit_exact_and_checkpoints_byte_identical(
        solo_refs, tmp_path):
    """The acceptance core: a W=4 batch (distinct seeds, one compiled
    program) reproduces each member's solo trajectory exactly AND
    publishes per-world checkpoint generations byte-identical to the
    solo runs' -- so --resume, ckpt_tool and the analytics pipeline
    work unchanged on batch output."""
    worlds = [_world(s, tmp_path / f"d{s}", tmp_path / f"ck{s}",
                     TPU_METRICS=1) for s in SEEDS]
    mw = MultiWorld(worlds, data_dir=str(tmp_path / "root"))
    mw.run(max_updates=U)
    for i, (solo, solo_ck) in enumerate(solo_refs):
        _assert_world_equal(solo, mw.worlds[i])
        ga = ckpt_mod.list_generations(solo_ck)
        gb = ckpt_mod.list_generations(str(tmp_path / f"ck{SEEDS[i]}"))
        assert [os.path.basename(p) for p in ga] \
            == [os.path.basename(p) for p in gb] and ga
        for pa, pb in zip(ga, gb):
            for fn in sorted(os.listdir(pa)):
                with open(os.path.join(pa, fn), "rb") as f:
                    ba = f.read()
                with open(os.path.join(pb, fn), "rb") as f:
                    bb = f.read()
                if fn == ckpt_mod.MANIFEST:
                    ja, jb = json.loads(ba), json.loads(bb)
                    ja.pop("saved_at"), jb.pop("saved_at")
                    assert ja == jb, f"{os.path.basename(pa)}/{fn}"
                else:
                    assert ba == bb, f"{os.path.basename(pa)}/{fn}"
    # the exporter satellite: aggregate heartbeat at the root plus
    # per-world labeled rows in multiworld.prom
    from avida_tpu.observability.exporter import read_metrics
    agg = read_metrics(str(tmp_path / "root" / "metrics.prom"))
    per = read_metrics(str(tmp_path / "root" / "multiworld.prom"))
    assert agg["avida_update"] == U
    assert per["avida_multiworld_size"] == len(SEEDS)
    orgs = [per[f'avida_organisms{{world="w{k:03d}"}}']
            for k in range(len(SEEDS))]
    assert agg["avida_organisms"] == sum(orgs)
    assert orgs[0] == mw.worlds[0].num_organisms


def test_mixed_seed_batch_sigterm_resume_bit_exact(solo_refs, tmp_path):
    """SIGTERM lands mid-batch: the preemption flag trips at the next
    chunk boundary, every world saves a checkpoint at the SAME update,
    and a fresh batch resumes aligned and finishes bit-exact vs the
    uninterrupted solo runs."""
    worlds = [_world(s, tmp_path / f"d{s}", tmp_path / f"ck{s}")
              for s in SEEDS]
    mw = MultiWorld(worlds, data_dir=str(tmp_path / "root"))

    def hook(m):
        if m.update >= 8:
            os.kill(os.getpid(), signal.SIGTERM)

    mw._boundary_hook = hook
    mw.run(max_updates=U)
    assert mw.preempted and mw.update < U
    saved = [ckpt_mod.latest_valid(str(tmp_path / f"ck{s}"))[1]["update"]
             for s in SEEDS]
    assert len(set(saved)) == 1          # one aligned preempt boundary

    worlds2 = [_world(s, tmp_path / f"d{s}", tmp_path / f"ck{s}")
               for s in SEEDS]
    mw2 = MultiWorld(worlds2, data_dir=str(tmp_path / "root2"))
    assert mw2.resume() == saved[0]
    mw2.run(max_updates=U)
    assert not mw2.preempted
    for i, (solo, _) in enumerate(solo_refs):
        # rows past the newborn-ring cursor are drain scratch whose
        # stale contents legitimately differ across a resume re-chunk
        _assert_world_equal(solo, mw2.worlds[i], nb_scratch_exact=False)


def test_batch_eligibility_validation(tmp_path):
    from avida_tpu.config.events import parse_event_line

    a = _world(1, tmp_path / "a")
    with pytest.raises(ValueError, match="identical static"):
        MultiWorld([a, _world(2, tmp_path / "b", WORLD_X=10)])
    with pytest.raises(ValueError, match="shared event schedule"):
        b = _world(2, tmp_path / "c")
        b.events = [parse_event_line("u 5 Exit")]
        MultiWorld([a, b])
    with pytest.raises(ValueError, match="chunkable"):
        c = _world(1, tmp_path / "e")
        d = _world(2, tmp_path / "f")
        c.events = [parse_event_line("g 0:10 PrintAverageData")]
        d.events = [parse_event_line("g 0:10 PrintAverageData")]
        MultiWorld([c, d])
    with pytest.raises(ValueError, match="at least one"):
        MultiWorld([])
    # distinct cfg objects are required (seeds/dirs must be per-world)
    with pytest.raises(ValueError, match="own config"):
        MultiWorld([a, a])


def test_worlds_cli_rejects_bad_spec(tmp_path):
    from avida_tpu.__main__ import main
    assert main(["--worlds", str(tmp_path / "nope.json"),
                 "-u", "1"]) == 2


def test_multiworld_off_zero_state_and_jaxpr_digest():
    """The trace_cap/lane_perm pattern: with no batch in play the
    engine is untouched -- importing the batcher adds no
    PopulationState field, and the single-world update_step still
    traces to the recorded jaxpr digest."""
    import avida_tpu.parallel.multiworld  # noqa: F401  (the import IS the test)
    from avida_tpu.core.state import PopulationState
    assert not any("world" in f or "batch" in f
                   for f in PopulationState.__dataclass_fields__)
    sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..",
                                    "scripts"))
    import check_jaxpr
    ok, msg = check_jaxpr.check(check_jaxpr.compute())
    assert ok, msg


def _mk_scan_world(seed, **overrides):
    """A raw-scan world (no World.run machinery) for the engine-level
    bit-exactness legs below."""
    from avida_tpu.world import World
    cfg = AvidaConfig()
    cfg.WORLD_X = 6
    cfg.WORLD_Y = 6
    cfg.TPU_MAX_MEMORY = 256
    cfg.RANDOM_SEED = seed
    cfg.AVE_TIME_SLICE = 100
    cfg.set("SLICING_METHOD", 2)      # deterministic merit-proportional
    #                                   stride: merit skew => budget skew
    for k, v in overrides.items():
        cfg.set(k, v)
    w = World(cfg=cfg)
    w.events = []
    w.inject()
    return w


def _skew_merit(st, factor):
    """Heavy-tailed merit on half the alive lanes: per-world max budgets
    (and with them per-update trip counts) diverge hard across a batch."""
    import jax.numpy as jnp
    n = st.merit.shape[0]
    half = st.alive & ((jnp.arange(n) % 2) == 0)
    return st.replace(merit=jnp.where(half, st.merit * factor, st.merit))


WARM_RAGGED = 24


def _warmed_ragged(seed, k, **overrides):
    """World `seed` advanced WARM_RAGGED updates solo, then merit-skewed
    (world index k == 1 gets the x64 heavy tail)."""
    import jax.numpy as jnp

    from avida_tpu.ops.update import update_scan
    w = _mk_scan_world(seed, **overrides)
    st, _ = update_scan(w.params, w.state, WARM_RAGGED, w._run_key,
                        w.neighbors, jnp.int32(0))
    return w, _skew_merit(st, 64.0 if k == 1 else 1.0)


def test_ragged_budget_batch_bit_exact_xla():
    """The tentpole's acceptance core on the XLA path: a batch whose
    worlds want DIFFERENT trip counts (heavy-tailed merit in world 1
    only) stays bit-exact vs solo.  This is exactly the case PR-10's
    vmapped while_loop paid for (batch-max trips + per-cycle selects)
    and the case the world-folded loop must get right: world 0 runs
    fully-masked iterations past its own max_k, which must be an exact
    identity on every state leaf."""
    import jax
    import jax.numpy as jnp

    from avida_tpu.ops.update import update_scan

    solo, keys = [], []
    for k, s in enumerate((5, 9)):
        w, st = _warmed_ragged(s, k)
        keys.append(w._run_key)
        s2, _ = update_scan(w.params, st, WARM_RAGGED, w._run_key,
                            w.neighbors, jnp.int32(WARM_RAGGED))
        solo.append(s2)

    sts = [_warmed_ragged(s, k)[1] for k, s in enumerate((5, 9))]
    w0 = _mk_scan_world(5)
    bstate = jax.tree.map(lambda *xs: jnp.stack(xs), *sts)
    bst, bouts = multiworld_scan(w0.params, bstate, WARM_RAGGED,
                                 jnp.stack(keys), w0.neighbors,
                                 jnp.int32(WARM_RAGGED))
    trips = np.asarray(bouts[-1])
    # the skew must actually make world 1 the leader: every masked
    # iteration of world 0 below is only exercised when trips diverge
    assert trips[1].sum() > trips[0].sum()
    assert (trips[1] > trips[0]).any()
    for i in range(2):
        for name in bst.__dataclass_fields__:
            v = getattr(bst, name)
            if v is None:
                continue
            np.testing.assert_array_equal(
                np.asarray(getattr(solo[i], name)), np.asarray(v)[i],
                err_msg=f"world {i} field {name}")


def test_engine_report_and_fallback_reason(tmp_path, capsys):
    """The packed-engine eligibility satellite: a batch that cannot take
    the stacked packed-resident path reports the exact reason in the
    runlog (stderr echo asserted here), and the reason function is the
    single spelling `packed_chunk.active` routes through."""
    from avida_tpu.ops import packed_chunk

    worlds = [_world(s, tmp_path / f"d{s}") for s in (1, 2)]
    mw = MultiWorld(worlds, data_dir=str(tmp_path / "root"))
    reason = mw._report_engine()
    err = capsys.readouterr().err
    assert mw.engine == "per-update"
    assert reason is not None and "multiworld_engine" in err
    assert "fallback_reason" in err and reason in err
    # the reason tracks the active() predicate exactly
    assert packed_chunk.ineligible_reason(mw.params, False) == reason
    assert not packed_chunk.active(mw.params)

    # a packed-eligible config reports the stacked engine (kernel forced
    # into interpret mode off-TPU; systematics off empties the nb ring)
    cfg = _cfg(1, TPU_USE_PALLAS=1, TPU_SYSTEMATICS=0, TPU_LANE_PERM=0)
    from avida_tpu.world import World as _W
    w = _W(cfg=cfg, data_dir=str(tmp_path / "pk"))
    assert packed_chunk.ineligible_reason(w.params, False) is None
    # on the same otherwise-eligible config, a systematics newborn ring
    # is the one remaining gate -- and it names itself
    assert "newborn ring" in packed_chunk.ineligible_reason(w.params, True)


@pytest.mark.slow
def test_batch_matches_solo_on_pallas_and_packed_paths():
    """The kernel interaction: the batched scan composes with the
    interpret-mode Pallas cycle kernel AND the packed-resident chunk,
    bit-exact per world vs solo scans with the same knobs."""
    import jax
    import jax.numpy as jnp

    from avida_tpu.ops.update import update_scan

    def mk(seed, packed):
        cfg = AvidaConfig()
        cfg.WORLD_X = 8
        cfg.WORLD_Y = 8
        cfg.TPU_MAX_MEMORY = 256
        cfg.RANDOM_SEED = seed
        cfg.TPU_USE_PALLAS = 1
        cfg.set("TPU_KERNEL_SHARDS", 1)
        cfg.set("TPU_LANE_PERM", 0)
        cfg.set("TPU_PACKED_CHUNK", 1 if packed else 0)
        cfg.set("TPU_SYSTEMATICS", 0)
        w = World(cfg=cfg)
        w.events = []
        w.inject()
        return w

    for packed in (False, True):
        seeds = [5, 9]
        solo = []
        for s in seeds:
            w = mk(s, packed)
            st, _ = update_scan(w.params, w.state, 4, w._run_key,
                                w.neighbors, jnp.int32(0))
            solo.append(st)
        worlds = [mk(s, packed) for s in seeds]
        bstate = jax.tree.map(lambda *xs: jnp.stack(xs),
                              *[w.state for w in worlds])
        rkeys = jnp.stack([w._run_key for w in worlds])
        bst, _ = multiworld_scan(worlds[0].params, bstate, 4, rkeys,
                                 worlds[0].neighbors, jnp.int32(0))
        for i in range(len(seeds)):
            for name in bst.__dataclass_fields__:
                v = getattr(bst, name)
                if v is None:
                    continue
                np.testing.assert_array_equal(
                    np.asarray(getattr(solo[i], name)),
                    np.asarray(v)[i],
                    err_msg=f"packed={packed} world={i} field {name}")


def _transplant_last_lane(st, boost=64.0):
    """Clone the most-copied alive organism into the LAST lane of the
    world and boost its merit, so a divide (and a birth whose data
    movement wraps the world edge) reliably originates from the final
    lane of the world's block -- the stacked layout's world-boundary
    cross-talk case."""
    import jax.numpy as jnp
    n = st.alive.shape[0]
    src = jnp.argmax(jnp.where(st.alive, st.copied_size, -1))
    upd = {}
    for name in st.__dataclass_fields__:
        v = getattr(st, name)
        if v is None or not hasattr(v, "shape") or v.ndim == 0:
            continue
        if name in ("lane_perm", "lane_inv") or v.shape[0] != n:
            continue                  # world-level / bijective fields
        upd[name] = v.at[n - 1].set(v[src])
    st = st.replace(**upd)
    return st.replace(merit=st.merit.at[n - 1].set(st.merit[src] * boost))


@pytest.mark.slow
def test_ragged_stacked_packed_bit_exact_with_last_lane_birth():
    """Stage 2 under fire: a W=2 packed-resident stacked batch with
    heavy-tailed budgets (ragged per-block trip counts ACROSS tenants)
    and a parent dividing FROM the last lane of world 0's block, so the
    packed flush's rolls wrap that world's edge right at the world
    boundary of the stacked layout.  Bit-exact per world vs solo packed
    scans, and world 1's state is untouched by world 0's edge birth (the
    cross-talk guard -- the bit-exact compare proves it)."""
    import jax
    import jax.numpy as jnp

    from avida_tpu.ops import packed_chunk
    from avida_tpu.ops.update import update_scan

    K = 12     # the transplanted last-lane parent needs ~10 updates to
    #            finish its gestation and win a placement (verified: its
    #            first birth lands inside this window)
    over = dict(TPU_USE_PALLAS=1, TPU_SYSTEMATICS=0, TPU_LANE_PERM=0,
                TPU_KERNEL_SHARDS=1, TPU_PACKED_CHUNK=1)

    def built(k, s):
        w, st = _warmed_ragged(s, k, **over)
        return w, _transplant_last_lane(st)

    solo, keys = [], []
    for k, s in enumerate((5, 9)):
        w, st = built(k, s)
        assert packed_chunk.active(w.params, st)
        keys.append(w._run_key)
        s2, _ = update_scan(w.params, st, K, w._run_key, w.neighbors,
                            jnp.int32(WARM_RAGGED))
        solo.append(s2)

    sts = [built(k, s)[1] for k, s in enumerate((5, 9))]
    w0 = _mk_scan_world(5, **over)
    n = sts[0].alive.shape[0]
    bstate = jax.tree.map(lambda *xs: jnp.stack(xs), *sts)
    bst, bouts = multiworld_scan(w0.params, bstate, K, jnp.stack(keys),
                                 w0.neighbors, jnp.int32(WARM_RAGGED))
    trips = np.asarray(bouts[-1])
    assert trips[1].sum() > trips[0].sum()        # genuinely ragged
    for i in range(2):
        for name in bst.__dataclass_fields__:
            v = getattr(bst, name)
            if v is None:
                continue
            np.testing.assert_array_equal(
                np.asarray(getattr(solo[i], name)), np.asarray(v)[i],
                err_msg=f"world {i} field {name}")
    # the boundary case actually fired: some cell of world 0 was born
    # from the last-lane parent during the compared window
    pid = np.asarray(bst.parent_id)[0]
    bu = np.asarray(bst.birth_update)[0]
    assert ((pid == n - 1) & (bu >= WARM_RAGGED)).any(), \
        "no birth from the last lane -- retune the transplant"


@pytest.mark.slow
@pytest.mark.skipif(len(__import__("jax").devices()) < 2,
                    reason="needs 2 devices")
def test_stacked_kernel_sharded_bit_exact():
    """TPU_KERNEL_SHARDS=2 with the world axis stacked: the stacked
    launch shard_maps over the combined [LP, W*n_pad] lane axis (each
    shard gets whole world blocks) and the per-world seed bases make
    its streams shard-count-invariant -- so the sharded stacked batch
    matches the UNSHARDED solo scans bit-exactly, mutations on."""
    import jax
    import jax.numpy as jnp

    from avida_tpu.ops import packed_chunk
    from avida_tpu.ops.update import update_scan

    K = 6
    base = dict(TPU_USE_PALLAS=1, TPU_SYSTEMATICS=0, TPU_LANE_PERM=0,
                TPU_PACKED_CHUNK=1)
    solo, keys = [], []
    for s in (5, 9):
        w = _mk_scan_world(s, TPU_KERNEL_SHARDS=1, **base)
        keys.append(w._run_key)
        st, _ = update_scan(w.params, w.state, K, w._run_key,
                            w.neighbors, jnp.int32(0))
        solo.append(st)

    worlds = [_mk_scan_world(s, TPU_KERNEL_SHARDS=2, **base)
              for s in (5, 9)]
    assert packed_chunk.active(worlds[0].params, worlds[0].state)
    bstate = jax.tree.map(lambda *xs: jnp.stack(xs),
                          *[w.state for w in worlds])
    bst, _ = multiworld_scan(worlds[0].params, bstate, K,
                             jnp.stack(keys), worlds[0].neighbors,
                             jnp.int32(0))
    for i in range(2):
        for name in bst.__dataclass_fields__:
            v = getattr(bst, name)
            if v is None:
                continue
            np.testing.assert_array_equal(
                np.asarray(getattr(solo[i], name)), np.asarray(v)[i],
                err_msg=f"world {i} field {name}")
